"""Dispatch governor: occupancy-driven closed-loop control of the tick.

PR 2 froze the dispatch plane's tick at a static ``QuorumTickInterval``.
That interval is a throughput/latency dial with no single right setting:
too wide and a 3PC wave waits most of a tick for its quorum verdicts
(the bounded in-flight batch window stalls the pipeline); too narrow
and an idle or
trickling pool pays a near-empty padded scatter per tick. RBFT's
throughput case (Aublin et al., ICDCS 2013) and the pipelined-BFT designs
(HotStuff, PODC 2019) both point the same way: the win is keeping device
and host phases overlapped WITHOUT paying per-message dispatch — which
makes the tick interval a control variable, not a constant.

:class:`DispatchGovernor` closes the loop over the metrics the dispatch
plane already measures (``device.flush_occupancy``,
``device.dispatches_per_tick``):

- **narrow** while a tick chains more than one grouped step (its votes
  overflowed the top ``FLUSH_LADDER`` rung — splitting the same votes
  across more ticks costs no extra dispatches and cuts quorum latency),
  or while the occupancy EWMA runs above ``GovernorOccupancyHigh``;
- **widen** while the EWMA sits below ``GovernorOccupancyLow`` (sparse
  ticks: a wider tick coalesces the same trickle of votes into fewer,
  fuller scatters);
- **hold** in between.

The equilibrium is the dispatch plane's own contract: one tick ≈ one
grouped device step, as full as the workload allows.

Determinism: ``observe`` is a pure function of the metric sequence (EWMA
state + multiplicative steps clamped to configured bounds — no wall
clock, no randomness), so a seeded run (including chaos-scheduled fault
runs) replays to the *identical* interval trajectory. The trajectory is
itself an artifact: every observation lands in the metrics collector
(``governor.tick_interval`` stat + histogram, ``governor.occupancy_ewma``)
and in :attr:`trajectory` for bench/report digests.
"""
from __future__ import annotations

from collections import deque
from typing import Optional

from ..common.metrics_collector import MetricsCollector, MetricsName
from ..observability.trace import NULL_TRACE

# retained trajectory window: full fidelity for any bench/test-sized run,
# bounded for a deployed node governing ticks for days (at the default
# floor of base/4 this is hours of history; the running min/max and the
# metrics stat/histogram keep whole-run aggregates exact)
TRAJECTORY_WINDOW = 65536

# governor anomaly (flight-recorder trigger): the law has pinned the
# interval at its floor for this many consecutive ticks while the
# saturation signal persists — the controller can no longer relieve the
# load, which is exactly the moment a trace tail is worth keeping
ANOMALY_SATURATED_TICKS = 8


class DispatchGovernor:
    """Deterministic EWMA controller for the quorum tick interval."""

    def __init__(self, interval: float, min_interval: float,
                 max_interval: float, alpha: float = 0.3,
                 occupancy_low: float = 0.02, occupancy_high: float = 0.85,
                 widen: float = 1.5, narrow: float = 0.5,
                 backpressure_queue_frac: float = 0.5,
                 metrics: Optional[MetricsCollector] = None,
                 trace=None):
        if not (0.0 < min_interval <= max_interval):
            raise ValueError(
                f"bad governor bounds [{min_interval}, {max_interval}]")
        if not (0.0 < alpha <= 1.0):
            raise ValueError(f"alpha must be in (0, 1]: {alpha}")
        if widen <= 1.0 or not (0.0 < narrow < 1.0):
            raise ValueError(f"bad step factors widen={widen} narrow={narrow}")
        self.min_interval = float(min_interval)
        self.max_interval = float(max_interval)
        self.interval = min(max(float(interval), self.min_interval),
                            self.max_interval)
        self.alpha = float(alpha)
        self.occupancy_low = float(occupancy_low)
        self.occupancy_high = float(occupancy_high)
        self.widen = float(widen)
        self.narrow = float(narrow)
        self.backpressure_queue_frac = float(backpressure_queue_frac)
        # absorb clamp (ordering fast path, pipelined-by-default): while
        # a dispatched step's verdicts are still in flight, the NEXT
        # tick exists to absorb work already paid for — an absorb tick
        # with no new votes dispatches nothing, so holding it at the
        # governor-widened interval buys no amortization and costs a
        # full wide tick of quorum latency at burst onset. The clamp
        # caps the EFFECTIVE interval at the configured base while
        # ``inflight`` is reported; the law's own interval state is
        # untouched, so the occupancy trajectory is unchanged.
        self.absorb_interval = self.interval
        self.absorb_clamps = 0
        # ingress backpressure (ingress/admission.BackpressureSignal):
        # fed once per tick by the ingress drain, consumed by the NEXT
        # observe call. None = no signal — the law is then bit-identical
        # to the PR 3/PR 4 occupancy-only law.
        self._backpressure = None
        self.backpressure_narrows = 0
        self.backpressure_widens = 0
        self.backpressure_holds = 0
        self.ewma: Optional[float] = None  # occupancy EWMA (None = cold)
        # per-shard EWMAs (mesh-sharded dispatch plane): one series per
        # shard, all fed the same law; ``ewma`` above is always the
        # HOTTEST shard's value (with one shard they coincide, which is
        # exactly the PR 3 behaviour)
        self.shard_ewmas: Optional[list] = None
        self.ticks = 0
        # interval AFTER each observation (bounded recent window); the
        # running extremes below stay exact over the whole run
        self.trajectory: "deque[float]" = deque(maxlen=TRAJECTORY_WINDOW)
        self._interval_low: Optional[float] = None
        self._interval_high: Optional[float] = None
        self.metrics = metrics if metrics is not None else MetricsCollector()
        # flight recorder: saturation anomalies dump the trace tail
        self.trace = trace if trace is not None else NULL_TRACE
        self._saturated_ticks = 0
        self.anomalies = 0

    # ------------------------------------------------------------------

    def observe(self, votes: int, capacity: int, dispatches: int,
                inflight: bool = False) -> float:
        """Feed one tick's measurements; returns the interval for the NEXT
        tick. ``votes``/``capacity`` are the tick's scattered vote count
        and padded scatter capacity (0/0 for an idle tick — occupancy 0,
        which is what lets an idle pool widen); ``dispatches`` is how many
        grouped device steps the tick chained. ``inflight`` reports a
        pipelined plane's unabsorbed step (``plane.lagging``) — see the
        absorb clamp in :meth:`observe_shards`."""
        return self.observe_shards([votes], [capacity], dispatches,
                                   inflight=inflight)

    def observe_shards(self, votes_per_shard, capacity_per_shard,
                       dispatches: int, inflight: bool = False) -> float:
        """Per-shard variant of :meth:`observe` for the mesh-sharded
        dispatch plane: each shard's occupancy feeds its OWN EWMA, and
        the control law acts on the hottest one — a saturated shard
        narrows the tick for the whole pool even while its siblings
        idle, deterministically (a pool-wide average would let n-1 idle
        shards mask one drowning in votes). With a single shard this is
        bit-for-bit the PR 3 law.

        ``inflight`` (ordering fast path): the pipelined plane reports
        that the step it just dispatched carries votes whose verdicts
        ride back NEXT tick. The returned (effective) interval is then
        capped at the configured base interval so the absorb happens
        promptly — measured: without the clamp, a burst landing on a
        governor-widened tick lags its quorum verdicts by a full wide
        interval per 3PC wave (adaptive ordered/sim-sec 2.86 vs static
        3.08 on the budget gate's bursty profile; with it, parity). A
        clamped tick with nothing newly pending absorbs WITHOUT
        dispatching (the pipelined flush skips empty dispatches), so an
        idle pool's amortization is untouched; while a 3PC wave is
        actively chaining, its phases ride the base cadence — the
        deliberate latency-over-coalescing trade (an absorb-only
        variant that deferred new votes to the law tick was measured:
        it kept the dispatch count but put the 7% sim-throughput
        regression right back). The clamp never touches
        ``self.interval`` — the law's trajectory is the pure occupancy
        law either way, and ``inflight=False`` calls are bit-identical
        to the PR 3/4/6 law."""
        occs = [v / c if c > 0 else 0.0
                for v, c in zip(votes_per_shard, capacity_per_shard)]
        if not occs:
            occs = [0.0]
        if self.shard_ewmas is None or len(self.shard_ewmas) != len(occs):
            self.shard_ewmas = list(occs)  # cold (or shard-count change)
        else:
            self.shard_ewmas = [
                self.alpha * occ + (1.0 - self.alpha) * ewma
                for occ, ewma in zip(occs, self.shard_ewmas)]
        self.ewma = max(self.shard_ewmas)
        # the signal is popped BEFORE the base law so retry pressure can
        # gate the occupancy widen (below); None / zero-signal paths
        # leave every branch bit-identical to the PR 3/PR 4 law
        sig, self._backpressure = self._backpressure, None
        retry_hold = sig is not None and sig.retry_pressure > 0
        saturated = dispatches > 1 or self.ewma >= self.occupancy_high
        if saturated:
            self.interval = max(self.interval * self.narrow,
                                self.min_interval)
        elif self.ewma <= self.occupancy_low:
            # retry-pressure HOLD (overload robustness plane): between
            # shed bursts a retry storm looks calm — the queue drained,
            # occupancy dipped — but the re-offers already sit on the
            # timer. Widening here is the metastable oscillation: wide
            # tick -> the whole backoff cohort lands in one drain ->
            # shed -> narrow -> repeat. While retries are outstanding
            # the law holds its narrow instead of widening; the widen
            # resumes the first tick the storm is actually over.
            if retry_hold:
                self.backpressure_holds += 1
            else:
                self.interval = min(self.interval * self.widen,
                                    self.max_interval)
        # ingress backpressure (PR 3's open "widen while leeching" hook):
        # queue growth or shedding narrows ON TOP of the occupancy law —
        # draining the auth queue sooner is the only relief the tick can
        # offer — while a leeching pool widens: a node replaying ledger
        # catchup gains nothing from tight ticks, and wider ticks hand
        # the host loop to the leecher. Queue growth outranks leeching
        # (a full queue hurts now; catchup tolerates latency), and
        # leeching outranks the retry hold (seeder throttling protects
        # ordering; the leecher still gets its wide ticks). Narrowing
        # here counts as saturation for the anomaly trigger: pinned at
        # the floor with the queue still growing is exactly the moment a
        # trace tail is worth keeping.
        if sig is not None:
            growth = sig.shed_delta > 0 or (
                sig.capacity > 0 and sig.queue_depth
                >= sig.capacity * self.backpressure_queue_frac)
            if growth:
                self.interval = max(self.interval * self.narrow,
                                    self.min_interval)
                self.backpressure_narrows += 1
                saturated = True
            elif sig.leeching:
                self.interval = min(self.interval * self.widen,
                                    self.max_interval)
                self.backpressure_widens += 1
        # anomaly: pinned at the floor AND still saturated — narrowing
        # can't relieve the load anymore. Fires ONCE per episode (the
        # counter only rearms after a non-saturated tick), deterministic
        # like the rest of the law.
        if saturated and self.interval <= self.min_interval:
            self._saturated_ticks += 1
            if self._saturated_ticks == ANOMALY_SATURATED_TICKS:
                self.anomalies += 1
                if self.trace.enabled:
                    self.trace.trigger_dump(
                        "governor_saturated",
                        args={"ewma": round(self.ewma, 6),
                              "interval": self.interval,
                              "ticks": self.ticks})
        else:
            self._saturated_ticks = 0
        self.ticks += 1
        # absorb clamp: the EFFECTIVE cadence (what the timer runs at)
        # is capped at the base interval while verdicts are in flight;
        # the law's interval state above stays pure occupancy control
        effective = self.interval
        if inflight and effective > self.absorb_interval:
            effective = max(self.absorb_interval, self.min_interval)
            self.absorb_clamps += 1
        self.trajectory.append(effective)
        if self._interval_low is None or effective < self._interval_low:
            self._interval_low = effective
        if self._interval_high is None or effective > self._interval_high:
            self._interval_high = effective
        self.metrics.add_event(MetricsName.GOVERNOR_TICK_INTERVAL,
                               effective)
        self.metrics.add_to_histogram(MetricsName.GOVERNOR_TICK_INTERVAL,
                                      round(effective, 6))
        self.metrics.add_event(MetricsName.GOVERNOR_OCCUPANCY_EWMA,
                               self.ewma)
        if len(self.shard_ewmas) > 1:
            for si, ewma in enumerate(self.shard_ewmas):
                self.metrics.add_event(
                    f"{MetricsName.GOVERNOR_SHARD_OCCUPANCY_EWMA}.{si}",
                    ewma)
        return effective

    def feed_backpressure(self, signal) -> None:
        """Hand the NEXT :meth:`observe`/:meth:`observe_shards` call one
        tick's :class:`~indy_plenum_tpu.ingress.admission
        .BackpressureSignal`. Feeding ``None`` (or never feeding) leaves
        the law bit-identical to the occupancy-only PR 3/PR 4 law —
        deterministic either way, since the signal itself is a pure
        function of the seeded workload."""
        self._backpressure = signal

    # ------------------------------------------------------------------

    def trajectory_summary(self) -> dict:
        """The bench/report digest: where the interval travelled (exact
        whole-run extremes; median over the retained window) and where
        the occupancy EWMA settled."""
        if not self.trajectory:
            return {"ticks": 0, "interval_min": self.interval,
                    "interval_median": self.interval,
                    "interval_max": self.interval,
                    "occupancy_ewma": self.ewma}
        ordered = sorted(self.trajectory)
        mid = len(ordered) // 2
        median = ordered[mid] if len(ordered) % 2 else (
            ordered[mid - 1] + ordered[mid]) / 2.0
        out = {
            "ticks": self.ticks,
            "interval_min": round(self._interval_low, 6),
            "interval_median": round(median, 6),
            "interval_max": round(self._interval_high, 6),
            "occupancy_ewma": (round(self.ewma, 6)
                               if self.ewma is not None else None),
            "anomalies": self.anomalies,
        }
        if self.backpressure_narrows or self.backpressure_widens \
                or self.backpressure_holds:
            out["backpressure_narrows"] = self.backpressure_narrows
            out["backpressure_widens"] = self.backpressure_widens
            out["backpressure_holds"] = self.backpressure_holds
        if self.shard_ewmas is not None and len(self.shard_ewmas) > 1:
            out["shards"] = len(self.shard_ewmas)
            out["shard_occupancy_ewma"] = [
                round(e, 6) for e in self.shard_ewmas]
        return out

    @classmethod
    def from_config(cls, config, metrics: Optional[MetricsCollector] = None,
                    trace=None) -> Optional["DispatchGovernor"]:
        """The single wiring point for every tick driver (quorum_driver,
        Node._quorum_tick): None unless tick-batched AND adaptive."""
        if config.QuorumTickInterval <= 0 or not config.QuorumTickAdaptive:
            return None
        lo, hi = config.governor_bounds()
        return cls(config.QuorumTickInterval, lo, hi,
                   alpha=config.GovernorEwmaAlpha,
                   occupancy_low=config.GovernorOccupancyLow,
                   occupancy_high=config.GovernorOccupancyHigh,
                   widen=config.GovernorWiden,
                   narrow=config.GovernorNarrow,
                   backpressure_queue_frac=(
                       config.GovernorBackpressureQueueFrac),
                   metrics=metrics, trace=trace)
