"""Device plane: JAX/XLA/Pallas kernels for the crypto + quorum hot paths.

This package is the TPU-native replacement for the reference's native crypto
stack (libsodium via ``stp_core/crypto/nacl_wrappers.py``, indy-crypto BLS via
``crypto/bls/indy_crypto/bls_crypto_indy_crypto.py``) and for the per-message
Python quorum bookkeeping in ``plenum/server/consensus/ordering_service.py``.

Everything here is pure-functional JAX: batched over the in-flight 3PC
request/message batch, shardable over a ``jax.sharding.Mesh`` whose axis
mirrors the validator set. All arithmetic is int32 (native TPU VPU lanes —
no 64-bit emulation anywhere on the hot path).
"""
