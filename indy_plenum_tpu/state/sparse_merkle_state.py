"""Authenticated key/value state as a binary sparse Merkle tree (SMT).

Replaces the reference's Merkle Patricia Trie (state/trie/pruning_trie.py)
with a TPU-friendly fixed-depth structure:

- path = sha256(key): 256 bits, one tree level per bit;
- empty subtrees use precomputed per-level default hashes and are never
  stored, so storage is O(written keys * 256) content-addressed nodes;
- nodes are content-addressed (hash -> (left, right) / leaf payload) in a
  KeyValueStorage, which makes every historical root remain readable —
  committed vs uncommitted heads are just two root pointers, and
  ``revert_to_head`` is a pointer assignment (the reference's
  revertToHead walks and prunes; here old roots are free);
- a state proof for a key is the 256 sibling hashes, compressed with a
  bitmap marking defaults (typically ~10 non-default siblings), and
  verification is a fixed 256-step hash fold — batchable on device.

Leaf hash = H(0x00 || path || value); node hash = H(0x01 || l || r);
default leaf = H(b"") per level 256, defaults[l] = H(0x01||d||d) upward.

Batched state commit (the O(delta) plane): :meth:`SparseMerkleState
.apply_batch` applies a whole write set in ONE bottom-up tree walk —
last-write-wins dedupe per key, entries sorted by path bits, the touched
subtree rebuilt level by level so each distinct internal node on any
updated path is hashed exactly once per batch (a Jellyfish-style batched
version commit; the sequential ``set()`` loop pays ``writes x 256``
hashes instead). Per-level hash waves are flat ``(left, right)`` arrays
dispatched through the batched device SHA-256 kernel
(:func:`indy_plenum_tpu.tpu.sha256.merkle_node_hash`) under the same
MEASURED host-vs-device offload policy as catchup proof verification
(``DEVICE_MIN_BATCH`` / ``_AdaptiveOffload`` in
``server/catchup/catchup_rep_service.py``) — the policy decides the
placement, the resulting root is bit-identical either way.
:meth:`begin_batch` / :meth:`flush_batch` expose the same walk as a
write-buffering overlay for ``WriteRequestManager.apply_batch`` (reads
at ``is_committed=False`` see the pending writes, so dynamic validation
inside a 3PC batch observes earlier requests in the same batch exactly
as it would under sequential application).
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

import msgpack

from ..storage.kv_store import KeyValueStorage, KeyValueStorageInMemory
from .state import State

DEPTH = 256
_LEAF_PREFIX = b"\x00"
_NODE_PREFIX = b"\x01"

# defaults mirrored from the config knobs (StateNodeCacheSize /
# StateCommitBatch*) so a bare SparseMerkleState() behaves like a
# config-built one; LedgersBootstrap threads the live knob values in
DEFAULT_NODE_CACHE_SIZE = 65536
DEFAULT_COMMIT_BATCH_MIN = 4
DEFAULT_COMMIT_MODE = "auto"

# the state plane keeps its OWN adaptive offload policy instance: the
# catchup plane's EMAs are nanoseconds per PROOF (~a 48-level fold per
# sample) while these are nanoseconds per single node hash — sharing one
# EMA pair would compare incommensurable units. The class (and the
# DEVICE_MIN_BATCH floor) is the catchup plane's, so the selection LAW
# is identical; only the measurements are local.
_WAVE_OFFLOAD = None


def _wave_offload_policy():
    global _WAVE_OFFLOAD
    if _WAVE_OFFLOAD is None:
        from ..server.catchup.catchup_rep_service import _AdaptiveOffload

        _WAVE_OFFLOAD = _AdaptiveOffload()
    return _WAVE_OFFLOAD


def _h(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _defaults() -> List[bytes]:
    """defaults[level] = hash of an empty subtree whose root is at level.

    level DEPTH = leaves; level 0 = tree root.
    """
    out = [b""] * (DEPTH + 1)
    out[DEPTH] = _h(b"")
    for level in range(DEPTH - 1, -1, -1):
        out[level] = _h(_NODE_PREFIX + out[level + 1] + out[level + 1])
    return out


DEFAULTS = _defaults()
EMPTY_ROOT = DEFAULTS[0]


def _path_bits(key: bytes) -> List[int]:
    digest = _h(key)
    return [(digest[i // 8] >> (7 - i % 8)) & 1 for i in range(DEPTH)]


def _bit(digest: bytes, level: int) -> int:
    return (digest[level >> 3] >> (7 - (level & 7))) & 1


class _PlanNode:
    """One touched internal node of a batched update, awaiting its wave
    hash. ``left``/``right`` are either concrete 32-byte hashes
    (untouched subtrees, defaults, leaf hashes) or child plan nodes."""

    __slots__ = ("left", "right", "hash")

    def __init__(self, left, right):
        self.left = left
        self.right = right
        self.hash = None


class SparseMerkleState(State):
    def __init__(self, kv: Optional[KeyValueStorage] = None,
                 initial_root: Optional[bytes] = None,
                 node_cache_size: int = DEFAULT_NODE_CACHE_SIZE,
                 commit_batch_enabled: bool = True,
                 commit_batch_min: int = DEFAULT_COMMIT_BATCH_MIN,
                 commit_mode: str = DEFAULT_COMMIT_MODE):
        if commit_mode not in ("host", "device", "auto"):
            raise ValueError(f"unknown commit_mode {commit_mode!r}")
        self._kv = kv if kv is not None else KeyValueStorageInMemory()
        # write-buffer: uncommitted nodes stay in memory; commit() flushes
        # them to the KV backend in one atomic batch (a crash between
        # batches loses only uncommitted state, as with the reference)
        self._dirty: dict[bytes, bytes] = {}
        # bounded LRU fronting the KV store: content-addressed nodes are
        # immutable, so entries never invalidate — hot-key paths stop
        # re-fetching ~256 nodes per touch (StateNodeCacheSize knob;
        # 0 disables)
        self._cache: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._cache_size = int(node_cache_size)
        # batch overlay (begin_batch/flush_batch): key -> value-or-None
        # in insertion order; None = no batch open
        self._pending: Optional[Dict[bytes, Optional[bytes]]] = None
        self._commit_batch_enabled = bool(commit_batch_enabled)
        self._commit_batch_min = int(commit_batch_min)
        self.commit_mode = commit_mode
        # meters (deterministic: wave sizes are a pure function of the
        # write set, independent of host/device placement)
        self.hashes_total = 0       # tree hashes: leaves + internal nodes
        self.batches_applied = 0
        self.batch_writes_total = 0  # writes buffered into batches
        self.batch_keys_total = 0    # distinct keys after dedupe
        self.cache_hits = 0
        self.cache_misses = 0
        # placement meters (NOT deterministic across modes — report-only)
        self.wave_host_hashes = 0
        self.wave_device_hashes = 0
        root = initial_root or self._load_root() or EMPTY_ROOT
        self._committed_root = root
        self._root = root

    # --- persistence of the committed head pointer ---------------------

    _ROOT_KEY = b"\xffROOT"

    def _load_root(self) -> Optional[bytes]:
        try:
            return self._kv.get(self._ROOT_KEY)
        except KeyError:
            return None

    def _store_root(self) -> None:
        self._kv.put(self._ROOT_KEY, self._committed_root)

    # --- node store ----------------------------------------------------

    def _put_node(self, data: bytes) -> bytes:
        h = _h(data)
        self._dirty[b"n" + h] = data
        return h

    def _get_node(self, h: bytes) -> bytes:
        key = b"n" + h
        node = self._dirty.get(key)
        if node is not None:
            return node
        cache = self._cache
        node = cache.get(key)
        if node is not None:
            self.cache_hits += 1
            cache.move_to_end(key)
            return node
        self.cache_misses += 1
        node = self._kv.get(key)
        if self._cache_size > 0:
            cache[key] = node
            if len(cache) > self._cache_size:
                cache.popitem(last=False)
        return node

    @property
    def node_cache_len(self) -> int:
        return len(self._cache)

    def cache_hit_rate(self) -> float:
        total = self.cache_hits + self.cache_misses
        return self.cache_hits / total if total else 0.0

    def sized_resources(self, prefix: str = "state."):
        """Resource-ledger registration (observability.telemetry): the
        LRU node cache (bounded), the dirty overlay and the uncommitted
        write count (both transient — drained at each commit, watched
        by the leak law rather than a declared cap)."""
        from ..observability.telemetry import SizedResource

        return (
            SizedResource(prefix + "node_cache",
                          lambda: len(self._cache),
                          bound=self._cache_size or None,
                          entry_bytes=256),
            SizedResource(prefix + "dirty", lambda: len(self._dirty),
                          bound=None, entry_bytes=256),
            SizedResource(prefix + "pending_writes",
                          lambda: self.pending_writes,
                          bound=None, entry_bytes=128),
        )

    # --- core update ---------------------------------------------------

    def _update(self, root: bytes, key: bytes,
                value: Optional[bytes]) -> bytes:
        bits = _path_bits(key)
        path_digest = _h(key)
        # walk down, recording siblings
        siblings: List[bytes] = []
        node = root
        for level in range(DEPTH):
            if node == DEFAULTS[level]:
                siblings.extend(DEFAULTS[l + 1] for l in range(level, DEPTH))
                node = DEFAULTS[DEPTH]
                break
            raw = self._get_node(node)
            left, right = raw[1:33], raw[33:65]
            if bits[level] == 0:
                siblings.append(right)
                node = left
            else:
                siblings.append(left)
                node = right
        # new leaf
        if value is None:
            new = DEFAULTS[DEPTH]
        else:
            leaf_data = _LEAF_PREFIX + path_digest + value
            new = self._put_node(leaf_data)
            self.hashes_total += 1
        # walk back up
        for level in range(DEPTH - 1, -1, -1):
            sibling = siblings[level]
            if bits[level] == 0:
                data = _NODE_PREFIX + new + sibling
            else:
                data = _NODE_PREFIX + sibling + new
            new = _h(data)
            if new != DEFAULTS[level]:
                self._dirty[b"n" + new] = data
        self.hashes_total += DEPTH
        return new

    def _lookup(self, root: bytes, key: bytes) -> Optional[bytes]:
        bits = _path_bits(key)
        path_digest = _h(key)
        node = root
        for level in range(DEPTH):
            if node == DEFAULTS[level]:
                return None
            raw = self._get_node(node)
            left, right = raw[1:33], raw[33:65]
            node = left if bits[level] == 0 else right
        if node == DEFAULTS[DEPTH]:
            return None
        raw = self._get_node(node)
        assert raw[:1] == _LEAF_PREFIX and raw[1:33] == path_digest
        return raw[33:]

    # --- batched update (one tree walk per write set) -------------------

    def apply_batch(self, items: Iterable[Tuple[bytes, Optional[bytes]]]
                    ) -> bytes:
        """Apply many ``(key, value-or-None)`` writes in ONE bottom-up
        tree walk; returns (and installs) the new working root.

        Last-write-wins dedupe per key first — sequentially applying the
        same sequence ends at the tree holding each key's final value,
        so the batched root is bit-identical to the ``set()``/
        ``remove()`` loop (asserted by the ``state_gate`` and the
        property tests). Entries are then sorted by path digest (= path
        bit order) and the touched subtree is rebuilt bottom-up: each
        distinct internal node on any updated path is hashed exactly
        once, collected into per-level waves and dispatched through
        :meth:`_hash_wave` (host SHA or the batched device kernel under
        the measured offload policy — identical digests either way).
        """
        final: Dict[bytes, Optional[bytes]] = {}
        n_writes = 0
        for key, value in items:
            n_writes += 1
            final[key] = value
        if not final:
            return self._root
        self.batches_applied += 1
        self.batch_writes_total += n_writes
        self.batch_keys_total += len(final)
        if len(final) < self._commit_batch_min:
            # tiny deltas: the plan/wave machinery costs more than it
            # saves (prefix sharing needs siblings to share with)
            for key, value in final.items():
                self._root = self._update(self._root, key, value)
            return self._root
        entries: List[Tuple[bytes, bytes]] = []
        for key, value in final.items():
            digest = _h(key)
            if value is None:
                leaf = DEFAULTS[DEPTH]
            else:
                leaf = self._put_node(_LEAF_PREFIX + digest + value)
                self.hashes_total += 1
            entries.append((digest, leaf))
        entries.sort()
        waves: List[List[_PlanNode]] = [[] for _ in range(DEPTH)]
        root = self._build(self._root, 0, entries, 0, len(entries), waves)
        if isinstance(root, _PlanNode):
            self._resolve_waves(waves)
            root = root.hash
        self._root = root
        return root

    def _build(self, node: bytes, level: int,
               entries: List[Tuple[bytes, bytes]], lo: int, hi: int,
               waves: List[List[_PlanNode]]):
        """Plan the rebuild of the subtree rooted at ``node`` (level
        ``level``) under ``entries[lo:hi]``; returns a concrete hash
        (untouched / unchanged) or a :class:`_PlanNode`."""
        if hi == lo:
            return node
        if level == DEPTH:
            # one leaf slot; dedupe guarantees a single entry
            return entries[hi - 1][1]
        if hi - lo == 1 and node == DEFAULTS[level]:
            # empty subtree, one entry: the whole descending chain has
            # default siblings — build it iteratively (this is ~all of
            # the nodes in a populate-from-empty batch)
            digest, leaf = entries[lo]
            if leaf == DEFAULTS[DEPTH]:
                return node  # removing from an empty subtree: no-op
            cur = leaf
            for lvl in range(DEPTH - 1, level - 1, -1):
                d = DEFAULTS[lvl + 1]
                pn = _PlanNode(cur, d) if _bit(digest, lvl) == 0 \
                    else _PlanNode(d, cur)
                waves[lvl].append(pn)
                cur = pn
            return cur
        if node == DEFAULTS[level]:
            left = right = DEFAULTS[level + 1]
        else:
            raw = self._get_node(node)
            left, right = raw[1:33], raw[33:65]
        # entries are sorted by digest and share the first `level` bits:
        # binary-search the 0/1 boundary at this level's bit
        a, b = lo, hi
        while a < b:
            mid = (a + b) // 2
            if _bit(entries[mid][0], level):
                b = mid
            else:
                a = mid + 1
        new_left = self._build(left, level + 1, entries, lo, a, waves)
        new_right = self._build(right, level + 1, entries, a, hi, waves)
        if new_left is left and new_right is right:
            return node  # rewrites of identical values: subtree unchanged
        if not isinstance(new_left, _PlanNode) \
                and not isinstance(new_right, _PlanNode) \
                and new_left == left and new_right == right:
            return node
        pn = _PlanNode(new_left, new_right)
        waves[level].append(pn)
        return pn

    def _resolve_waves(self, waves: List[List[_PlanNode]]) -> None:
        """Hash the planned nodes bottom-up, one batched wave per level
        (children at level+1 are resolved before level runs)."""
        for level in range(DEPTH - 1, -1, -1):
            wave = waves[level]
            if not wave:
                continue
            pairs: List[Tuple[bytes, bytes]] = []
            for pn in wave:
                left, right = pn.left, pn.right
                if isinstance(left, _PlanNode):
                    left = left.hash
                if isinstance(right, _PlanNode):
                    right = right.hash
                pairs.append((left, right))
            digests = self._hash_wave(pairs)
            default = DEFAULTS[level]
            dirty = self._dirty
            for pn, (left, right), digest in zip(wave, pairs, digests):
                pn.hash = digest
                if digest != default:
                    dirty[b"n" + digest] = _NODE_PREFIX + left + right
            self.hashes_total += len(wave)

    def _hash_wave(self, pairs: List[Tuple[bytes, bytes]]) -> List[bytes]:
        """One per-level hash wave: H(0x01||l||r) for every pair.

        Placement follows the catchup offload law: waves below
        DEVICE_MIN_BATCH (or mode 'host') run the host SHA loop; larger
        waves consult the measured policy in 'auto' mode or force the
        device kernel in 'device' mode. Digests are bit-identical on
        either path — only nanoseconds move.
        """
        mode = self.commit_mode
        if mode != "host":
            from ..server.catchup.catchup_rep_service import (
                DEVICE_MIN_BATCH,
            )

            if len(pairs) >= DEVICE_MIN_BATCH:
                policy = _wave_offload_policy()
                if mode == "device" or policy.use_device():
                    return self._hash_wave_device(pairs, policy, mode)
                return self._hash_wave_host(pairs, policy)
        return self._hash_wave_host(pairs, None)

    def _hash_wave_host(self, pairs: List[Tuple[bytes, bytes]],
                        policy) -> List[bytes]:
        import time as _time

        # da: allow[nondet-source] -- perf_counter here (and below) feeds the offload policy's host EMA only: placement steering, never results/fingerprints
        t0 = _time.perf_counter()
        prefix = _NODE_PREFIX
        sha = hashlib.sha256
        out = [sha(prefix + left + right).digest() for left, right in pairs]
        if policy is not None:
            dt = _time.perf_counter() - t0  # da: allow[nondet-source] -- offload-policy host EMA close (see t0 above)
            policy.note_host(dt * 1e9 / len(pairs))
        self.wave_host_hashes += len(pairs)
        return out

    def _hash_wave_device(self, pairs: List[Tuple[bytes, bytes]],
                          policy, mode: str) -> List[bytes]:
        import time as _time

        import numpy as np

        n = len(pairs)
        if mode == "auto" and policy.host_ns is None:
            # one-time calibration: the policy cannot compare modes until
            # it has a host sample (same idiom as catchup's proof verify;
            # the sampled digests are discarded — the device wave below
            # recomputes them, keeping results placement-independent)
            sample = pairs[:min(256, n)]
            # da: allow[nondet-source] -- one-time host-calibration timing for the offload policy; sampled digests are discarded
            t0 = _time.perf_counter()
            for left, right in sample:
                _h(_NODE_PREFIX + left + right)
            dt = _time.perf_counter() - t0  # da: allow[nondet-source] -- host-calibration EMA close (see t0 above)
            policy.note_host(dt * 1e9 / len(sample))
        # da: allow[nondet-source] -- device-wave blocking time feeds the offload policy's device EMA only
        t0 = _time.perf_counter()
        try:
            from ..tpu.sha256 import merkle_node_hash_bytes

            left = np.frombuffer(
                b"".join(p[0] for p in pairs), np.uint8).reshape(n, 32)
            right = np.frombuffer(
                b"".join(p[1] for p in pairs), np.uint8).reshape(n, 32)
            resolved = merkle_node_hash_bytes(left, right)
        except Exception:  # noqa: BLE001 — no usable device backend
            return self._hash_wave_host(pairs, policy)
        dt = _time.perf_counter() - t0  # da: allow[nondet-source] -- device-wave EMA close (see t0 above)
        policy.note_device(dt * 1e9 / n)
        self.wave_device_hashes += n
        return [resolved[i].tobytes() for i in range(n)]

    # --- batch overlay (WriteRequestManager's per-3PC-batch seam) -------

    def begin_batch(self) -> bool:
        """Start buffering writes for a one-walk commit; returns whether
        batch mode engaged (False = the knob disabled it and writes
        apply sequentially as before). While a batch is open,
        ``get(is_committed=False)`` consults the pending overlay first,
        so dynamic validation sees earlier writes of the same batch."""
        if not self._commit_batch_enabled:
            return False
        if self._pending is None:
            self._pending = {}
        return True

    def flush_batch(self) -> bytes:
        """Apply everything buffered since :meth:`begin_batch` via ONE
        :meth:`apply_batch` walk; returns the new working root."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            if pending:
                self.apply_batch(pending.items())
        return self._root

    def discard_batch(self) -> None:
        self._pending = None

    @property
    def in_batch(self) -> bool:
        return self._pending is not None

    @property
    def pending_writes(self) -> int:
        return len(self._pending) if self._pending is not None else 0

    # --- State API -----------------------------------------------------

    def set(self, key: bytes, value: bytes) -> None:
        if self._pending is not None:
            self._pending[key] = value
            return
        self._root = self._update(self._root, key, value)

    def remove(self, key: bytes) -> None:
        if self._pending is not None:
            self._pending[key] = None
            return
        self._root = self._update(self._root, key, None)

    def get(self, key: bytes, is_committed: bool = False) -> Optional[bytes]:
        if not is_committed and self._pending is not None \
                and key in self._pending:
            return self._pending[key]
        root = self._committed_root if is_committed else self._root
        return self._lookup(root, key)

    def get_for_root_hash(self, root: bytes, key: bytes) -> Optional[bytes]:
        return self._lookup(root, key)

    def commit(self, root_hash: Optional[bytes] = None) -> None:
        """Advance the committed head.

        With ``root_hash`` given, only the committed pointer moves — the
        working head stays at the tip, so later staged (pipelined) batches
        survive committing an earlier one. Without it, everything staged
        becomes committed (head == tip).
        """
        self.flush_batch()
        self._committed_root = root_hash if root_hash is not None \
            else self._root
        if root_hash is None:
            self._root = self._committed_root
        if self._dirty:
            self._kv.do_batch(list(self._dirty.items()))
            self._dirty.clear()
        self._store_root()

    def revert_to_head(self) -> None:
        self._pending = None
        self._root = self._committed_root

    def set_head_hash(self, root: bytes) -> None:
        """Move the working head to a known root (LIFO batch revert: nodes
        are content-addressed, so any recorded root remains reachable).
        An open write buffer is DISCARDED — this is the exception/revert
        path, and the buffered writes belong to the abandoned batch."""
        self._pending = None
        self._root = root

    @property
    def head_hash(self) -> bytes:
        if self._pending:
            self.flush_batch()
        return self._root

    @property
    def committed_head_hash(self) -> bytes:
        return self._committed_root

    # --- proofs --------------------------------------------------------

    def generate_state_proof(self, key: bytes, root: Optional[bytes] = None,
                             serialize: bool = True):
        """Proof of (non-)membership: bitmap + non-default siblings.

        Returns msgpack bytes when ``serialize`` (wire format for
        state-proof replies), else the (bitmap, siblings) tuple.
        """
        if self._pending:
            self.flush_batch()
        root = root if root is not None else self._committed_root
        bits = _path_bits(key)
        siblings: List[bytes] = []
        node = root
        for level in range(DEPTH):
            if node == DEFAULTS[level]:
                siblings.extend(DEFAULTS[l + 1] for l in range(level, DEPTH))
                break
            raw = self._get_node(node)
            left, right = raw[1:33], raw[33:65]
            if bits[level] == 0:
                siblings.append(right)
                node = left
            else:
                siblings.append(left)
                node = right
        bitmap = bytearray(DEPTH // 8)
        packed: List[bytes] = []
        for level, sib in enumerate(siblings):
            if sib != DEFAULTS[level + 1]:
                bitmap[level // 8] |= 1 << (7 - level % 8)
                packed.append(sib)
        proof = (bytes(bitmap), packed)
        if serialize:
            return msgpack.packb([proof[0], proof[1]], use_bin_type=True)
        return proof


def verify_state_proof(root: bytes, key: bytes, value: Optional[bytes],
                       proof) -> bool:
    """Client-side scalar verification (host oracle for the device kernel).

    The proof (and often the root) is UNTRUSTED wire input: any
    malformed shape — undecodable msgpack, a short root, non-bytes path
    elements, wrong-length siblings or bitmap — verifies ``False``
    instead of raising (parity with ``verify_proved_read``; a byzantine
    replier must not crash the client)."""
    try:
        if isinstance(proof, (bytes, bytearray)):
            bitmap, packed = msgpack.unpackb(bytes(proof), raw=False)
        else:
            bitmap, packed = proof
        if not isinstance(root, (bytes, bytearray)) or len(root) != 32:
            return False
        if not isinstance(key, (bytes, bytearray)):
            return False
        if not isinstance(bitmap, (bytes, bytearray)) \
                or len(bitmap) != DEPTH // 8:
            return False
        if not all(isinstance(sib, (bytes, bytearray)) and len(sib) == 32
                   for sib in packed):
            return False
        bits = _path_bits(bytes(key))
        path_digest = _h(bytes(key))
        siblings = []
        it = iter(packed)
        for level in range(DEPTH):
            if bitmap[level // 8] & (1 << (7 - level % 8)):
                try:
                    siblings.append(bytes(next(it)))
                except StopIteration:
                    return False
            else:
                siblings.append(DEFAULTS[level + 1])
        if value is None:
            node = DEFAULTS[DEPTH]
        else:
            node = _h(_LEAF_PREFIX + path_digest + bytes(value))
        for level in range(DEPTH - 1, -1, -1):
            if bits[level] == 0:
                node = _h(_NODE_PREFIX + node + siblings[level])
            else:
                node = _h(_NODE_PREFIX + siblings[level] + node)
        return node == bytes(root)
    except Exception:  # noqa: BLE001 — untrusted wire input: any shape error is a failed proof
        return False


# API-compat alias: the reference calls its concrete state PruningState
PruningState = SparseMerkleState
