"""Authenticated key/value state as a binary sparse Merkle tree (SMT).

Replaces the reference's Merkle Patricia Trie (state/trie/pruning_trie.py)
with a TPU-friendly fixed-depth structure:

- path = sha256(key): 256 bits, one tree level per bit;
- empty subtrees use precomputed per-level default hashes and are never
  stored, so storage is O(written keys * 256) content-addressed nodes;
- nodes are content-addressed (hash -> (left, right) / leaf payload) in a
  KeyValueStorage, which makes every historical root remain readable —
  committed vs uncommitted heads are just two root pointers, and
  ``revert_to_head`` is a pointer assignment (the reference's
  revertToHead walks and prunes; here old roots are free);
- a state proof for a key is the 256 sibling hashes, compressed with a
  bitmap marking defaults (typically ~10 non-default siblings), and
  verification is a fixed 256-step hash fold — batchable on device.

Leaf hash = H(0x00 || path || value); node hash = H(0x01 || l || r);
default leaf = H(b"") per level 256, defaults[l] = H(0x01||d||d) upward.
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Tuple

import msgpack

from ..storage.kv_store import KeyValueStorage, KeyValueStorageInMemory
from .state import State

DEPTH = 256
_LEAF_PREFIX = b"\x00"
_NODE_PREFIX = b"\x01"


def _h(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _defaults() -> List[bytes]:
    """defaults[level] = hash of an empty subtree whose root is at level.

    level DEPTH = leaves; level 0 = tree root.
    """
    out = [b""] * (DEPTH + 1)
    out[DEPTH] = _h(b"")
    for level in range(DEPTH - 1, -1, -1):
        out[level] = _h(_NODE_PREFIX + out[level + 1] + out[level + 1])
    return out


DEFAULTS = _defaults()
EMPTY_ROOT = DEFAULTS[0]


def _path_bits(key: bytes) -> List[int]:
    digest = _h(key)
    return [(digest[i // 8] >> (7 - i % 8)) & 1 for i in range(DEPTH)]


class SparseMerkleState(State):
    def __init__(self, kv: Optional[KeyValueStorage] = None,
                 initial_root: Optional[bytes] = None):
        self._kv = kv if kv is not None else KeyValueStorageInMemory()
        # write-buffer: uncommitted nodes stay in memory; commit() flushes
        # them to the KV backend in one atomic batch (a crash between
        # batches loses only uncommitted state, as with the reference)
        self._dirty: dict[bytes, bytes] = {}
        root = initial_root or self._load_root() or EMPTY_ROOT
        self._committed_root = root
        self._root = root

    # --- persistence of the committed head pointer ---------------------

    _ROOT_KEY = b"\xffROOT"

    def _load_root(self) -> Optional[bytes]:
        try:
            return self._kv.get(self._ROOT_KEY)
        except KeyError:
            return None

    def _store_root(self) -> None:
        self._kv.put(self._ROOT_KEY, self._committed_root)

    # --- node store ----------------------------------------------------

    def _put_node(self, data: bytes) -> bytes:
        h = _h(data)
        self._dirty[b"n" + h] = data
        return h

    def _get_node(self, h: bytes) -> bytes:
        key = b"n" + h
        if key in self._dirty:
            return self._dirty[key]
        return self._kv.get(key)

    # --- core update ---------------------------------------------------

    def _update(self, root: bytes, key: bytes,
                value: Optional[bytes]) -> bytes:
        bits = _path_bits(key)
        path_digest = _h(key)
        # walk down, recording siblings
        siblings: List[bytes] = []
        node = root
        for level in range(DEPTH):
            if node == DEFAULTS[level]:
                siblings.extend(DEFAULTS[l + 1] for l in range(level, DEPTH))
                node = DEFAULTS[DEPTH]
                break
            raw = self._get_node(node)
            left, right = raw[1:33], raw[33:65]
            if bits[level] == 0:
                siblings.append(right)
                node = left
            else:
                siblings.append(left)
                node = right
        # new leaf
        if value is None:
            new = DEFAULTS[DEPTH]
        else:
            leaf_data = _LEAF_PREFIX + path_digest + value
            new = self._put_node(leaf_data)
        # walk back up
        for level in range(DEPTH - 1, -1, -1):
            sibling = siblings[level]
            if bits[level] == 0:
                data = _NODE_PREFIX + new + sibling
            else:
                data = _NODE_PREFIX + sibling + new
            new = _h(data)
            if new != DEFAULTS[level]:
                self._dirty[b"n" + new] = data
        return new

    def _lookup(self, root: bytes, key: bytes) -> Optional[bytes]:
        bits = _path_bits(key)
        path_digest = _h(key)
        node = root
        for level in range(DEPTH):
            if node == DEFAULTS[level]:
                return None
            raw = self._get_node(node)
            left, right = raw[1:33], raw[33:65]
            node = left if bits[level] == 0 else right
        if node == DEFAULTS[DEPTH]:
            return None
        raw = self._get_node(node)
        assert raw[:1] == _LEAF_PREFIX and raw[1:33] == path_digest
        return raw[33:]

    # --- State API -----------------------------------------------------

    def set(self, key: bytes, value: bytes) -> None:
        self._root = self._update(self._root, key, value)

    def remove(self, key: bytes) -> None:
        self._root = self._update(self._root, key, None)

    def get(self, key: bytes, is_committed: bool = False) -> Optional[bytes]:
        root = self._committed_root if is_committed else self._root
        return self._lookup(root, key)

    def get_for_root_hash(self, root: bytes, key: bytes) -> Optional[bytes]:
        return self._lookup(root, key)

    def commit(self, root_hash: Optional[bytes] = None) -> None:
        """Advance the committed head.

        With ``root_hash`` given, only the committed pointer moves — the
        working head stays at the tip, so later staged (pipelined) batches
        survive committing an earlier one. Without it, everything staged
        becomes committed (head == tip).
        """
        self._committed_root = root_hash if root_hash is not None \
            else self._root
        if root_hash is None:
            self._root = self._committed_root
        if self._dirty:
            self._kv.do_batch(list(self._dirty.items()))
            self._dirty.clear()
        self._store_root()

    def revert_to_head(self) -> None:
        self._root = self._committed_root

    def set_head_hash(self, root: bytes) -> None:
        """Move the working head to a known root (LIFO batch revert: nodes
        are content-addressed, so any recorded root remains reachable)."""
        self._root = root

    @property
    def head_hash(self) -> bytes:
        return self._root

    @property
    def committed_head_hash(self) -> bytes:
        return self._committed_root

    # --- proofs --------------------------------------------------------

    def generate_state_proof(self, key: bytes, root: Optional[bytes] = None,
                             serialize: bool = True):
        """Proof of (non-)membership: bitmap + non-default siblings.

        Returns msgpack bytes when ``serialize`` (wire format for
        state-proof replies), else the (bitmap, siblings) tuple.
        """
        root = root if root is not None else self._committed_root
        bits = _path_bits(key)
        siblings: List[bytes] = []
        node = root
        for level in range(DEPTH):
            if node == DEFAULTS[level]:
                siblings.extend(DEFAULTS[l + 1] for l in range(level, DEPTH))
                break
            raw = self._get_node(node)
            left, right = raw[1:33], raw[33:65]
            if bits[level] == 0:
                siblings.append(right)
                node = left
            else:
                siblings.append(left)
                node = right
        bitmap = bytearray(DEPTH // 8)
        packed: List[bytes] = []
        for level, sib in enumerate(siblings):
            if sib != DEFAULTS[level + 1]:
                bitmap[level // 8] |= 1 << (7 - level % 8)
                packed.append(sib)
        proof = (bytes(bitmap), packed)
        if serialize:
            return msgpack.packb([proof[0], proof[1]], use_bin_type=True)
        return proof


def verify_state_proof(root: bytes, key: bytes, value: Optional[bytes],
                       proof) -> bool:
    """Client-side scalar verification (host oracle for the device kernel)."""
    if isinstance(proof, (bytes, bytearray)):
        bitmap, packed = msgpack.unpackb(bytes(proof), raw=False)
    else:
        bitmap, packed = proof
    bits = _path_bits(key)
    path_digest = _h(key)
    siblings = []
    it = iter(packed)
    for level in range(DEPTH):
        if bitmap[level // 8] & (1 << (7 - level % 8)):
            try:
                siblings.append(next(it))
            except StopIteration:
                return False
        else:
            siblings.append(DEFAULTS[level + 1])
    if value is None:
        node = DEFAULTS[DEPTH]
    else:
        node = _h(_LEAF_PREFIX + path_digest + value)
    for level in range(DEPTH - 1, -1, -1):
        if bits[level] == 0:
            node = _h(_NODE_PREFIX + node + siblings[level])
        else:
            node = _h(_NODE_PREFIX + siblings[level] + node)
    return node == root


# API-compat alias: the reference calls its concrete state PruningState
PruningState = SparseMerkleState
