"""Key/value state interface with committed/uncommitted heads and proofs.

Reference: state/state.py (`State`) + state/pruning_state.py
(`PruningState`, an Ethereum-style Merkle Patricia Trie).

DESIGN DEPARTURE (TPU-first): the concrete implementation here is a
**binary sparse Merkle tree** (:mod:`sparse_merkle_state`), not an MPT.
Same capabilities — authenticated key/value store, committed vs
uncommitted heads, revert, externally-verifiable proofs — but with a
fixed 256-level structure whose proof verification is a fixed-depth hash
fold, i.e. exactly the shape the batched device kernel
(:func:`indy_plenum_tpu.tpu.sha256.sha256_fixed`) wants: no variable-arity
nodes, no RLP, no data-dependent control flow.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional, Tuple


class State(ABC):
    @abstractmethod
    def set(self, key: bytes, value: bytes) -> None:
        """Update the uncommitted head."""

    @abstractmethod
    def get(self, key: bytes, is_committed: bool = False) -> Optional[bytes]:
        ...

    @abstractmethod
    def remove(self, key: bytes) -> None:
        ...

    @abstractmethod
    def commit(self, root_hash: Optional[bytes] = None) -> None:
        """Promote the uncommitted head (or an explicit historical root)."""

    @abstractmethod
    def revert_to_head(self) -> None:
        """Discard uncommitted changes (back to the committed head)."""

    @property
    @abstractmethod
    def head_hash(self) -> bytes:
        """Uncommitted root."""

    @property
    @abstractmethod
    def committed_head_hash(self) -> bytes:
        ...

    @abstractmethod
    def generate_state_proof(self, key: bytes, root: Optional[bytes] = None,
                             serialize: bool = True):
        ...
