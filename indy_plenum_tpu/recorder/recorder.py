"""Record one node's inputs; replay them into a fresh node bit-for-bit.

Reference: plenum/recorder/ (`Recorder`, the replayer scripts). Because
every consensus service sees time ONLY through the TimerService and inputs
ONLY through the external bus + client ingress, a node is a deterministic
function of (genesis, config, timed input log). The recorder tees both
input surfaces with virtual-clock timestamps; the replayer schedules the
log against a fresh MockTimer and the replayed node reproduces the
original ordered log, ledgers and state roots — the debugging story for
"what did this node see before it diverged".
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common.messages.message_base import node_message_registry
from ..common.request import Request

NET = "net"
CLIENT = "client"


class Recorder:
    def __init__(self):
        self.entries: List[Tuple[float, str, str, Dict[str, Any]]] = []
        self._now: Optional[Callable[[], float]] = None

    # --- wiring ---------------------------------------------------------

    def attach(self, node) -> None:
        """Tee the node's two input surfaces (idempotent per node: a
        second attach would double-record every input and the replay
        would diverge)."""
        if getattr(node, "_recorder_attached", None) is self:
            return
        node._recorder_attached = self
        self._now = node.timer.get_current_time

        original_incoming = node.external_bus.process_incoming

        def recording_incoming(msg, frm):
            self.record_net(frm, msg)
            return original_incoming(msg, frm)

        node.external_bus.process_incoming = recording_incoming

        original_submit = node.submit_client_request

        def recording_submit(req, client_id=None):
            self.record_client(client_id, req)
            return original_submit(req, client_id=client_id)

        node.submit_client_request = recording_submit

    # --- recording ------------------------------------------------------

    def record_net(self, frm: str, msg) -> None:
        if hasattr(msg, "as_dict"):
            self.entries.append((self._now(), NET, frm, msg.as_dict()))

    def record_client(self, client_id: Optional[str], req: Request) -> None:
        self.entries.append(
            (self._now(), CLIENT, client_id or "", req.as_dict()))

    # --- persistence ----------------------------------------------------

    def dump(self, path: str) -> None:
        with open(path, "w") as fh:
            for ts, kind, frm, payload in self.entries:
                fh.write(json.dumps([ts, kind, frm, payload]) + "\n")

    @classmethod
    def load(cls, path: str) -> "Recorder":
        rec = cls()
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    ts, kind, frm, payload = json.loads(line)
                    rec.entries.append((ts, kind, frm, payload))
        return rec


class Replayer:
    """Schedule a recorded input log against a fresh node's MockTimer."""

    def __init__(self, recorder: Recorder):
        self._entries = list(recorder.entries)

    def replay_into(self, node, timer) -> None:
        """``timer``: the MockTimer the node was built on, positioned at or
        before the first entry. Schedules every input at its recorded
        virtual time; the caller advances the clock."""
        start = timer.get_current_time()
        for ts, kind, frm, payload in self._entries:
            delay = max(0.0, ts - start)
            if kind == NET:
                def deliver(p=dict(payload), f=frm):
                    msg = node_message_registry.obj_from_dict(dict(p))
                    node.external_bus.process_incoming(msg, f)
            else:
                def deliver(p=dict(payload), c=frm):
                    node.submit_client_request(
                        Request.from_dict(dict(p)), client_id=c or None)
            timer.schedule(delay, deliver)

    @property
    def duration(self) -> float:
        if not self._entries:
            return 0.0
        return self._entries[-1][0] - self._entries[0][0]


class ReplayNetwork:
    """The replayed node's sends go nowhere (its outputs are a FUNCTION of
    the recorded inputs; the pool is not there to answer)."""

    def create_peer(self, name: str):
        from ..common.event_bus import ExternalBus

        return ExternalBus(lambda msg, dst=None: None)
