"""Message recording + deterministic replay (reference: plenum/recorder/)."""
from .recorder import Recorder, Replayer

__all__ = ["Recorder", "Replayer"]
