"""BLS wiring helpers (reference: plenum/bls/bls_crypto_factory.py,
bls_bft_factory.py — the plugin seam building signer/verifier/replica)."""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..crypto.bls.bls_crypto import BlsCryptoSigner, BlsKeyPair
from .bls_bft_replica import BlsBftReplica
from .bls_key_register import BlsKeyRegister
from .bls_store import BlsStore


def generate_bls_keys(seed: bytes) -> Tuple[BlsKeyPair, str, str]:
    """seed -> (keypair, pk_b58, proof_of_possession_b58)."""
    kp = BlsKeyPair(seed)
    return kp, kp.pk_b58, kp.pop()


def create_bls_bft_replica(node_name: str,
                           keypair: BlsKeyPair,
                           pool_keys: Dict[str, Tuple[str, str]],
                           store: Optional[BlsStore] = None,
                           pool_state_root_provider=None,
                           suspicion_sink=None) -> BlsBftReplica:
    """pool_keys: node name -> (pk_b58, pop_b58); PoP verified on load."""
    register = BlsKeyRegister()
    for name, (pk, pop) in pool_keys.items():
        register.add_key(name, pk, pop, require_pop=True)
    return BlsBftReplica(
        node_name=node_name,
        signer=BlsCryptoSigner(keypair),
        key_register=register,
        store=store,
        pool_state_root_provider=pool_state_root_provider,
        suspicion_sink=suspicion_sink,
    )
