"""BLS protocol integration: BlsBftReplica, BlsStore, key register, factory.

Reference: plenum/bls/ (bls_bft_replica_plenum.py, bls_crypto_factory.py,
bls_store.py, bls_key_register_pool_manager.py).
"""
from .bls_bft_replica import BlsBftReplica
from .bls_key_register import BlsKeyRegister
from .bls_store import BlsStore
from .factory import create_bls_bft_replica, generate_bls_keys

__all__ = ["BlsBftReplica", "BlsKeyRegister", "BlsStore",
           "create_bls_bft_replica", "generate_bls_keys"]
