"""Validator BLS public keys, sourced from the pool (NODE txns / genesis).

Reference: plenum/bls/bls_key_register_pool_manager.py. Keys rotate via
NODE txns through consensus; the register answers "key of node X as of
now". Proof-of-possession is checked at registration (rogue-key defence).
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

from ..crypto.bls.bls_crypto import BlsCryptoVerifier

logger = logging.getLogger(__name__)


class BlsKeyRegister:
    def __init__(self):
        self._keys: Dict[str, str] = {}  # node name -> pk b58

    def add_key(self, node_name: str, pk_b58: str,
                pop_b58: Optional[str] = None,
                require_pop: bool = False) -> bool:
        if pop_b58 is not None:
            if not BlsCryptoVerifier.verify_pop(pop_b58, pk_b58):
                logger.warning("rejecting BLS key for %s: bad proof of "
                               "possession", node_name)
                return False
        elif require_pop:
            logger.warning("rejecting BLS key for %s: missing proof of "
                           "possession", node_name)
            return False
        self._keys[node_name] = pk_b58
        return True

    def remove_key(self, node_name: str) -> None:
        """Demoted validator: its key must stop counting toward multi-sigs."""
        self._keys.pop(node_name, None)

    def get_key(self, node_name: str) -> Optional[str]:
        return self._keys.get(node_name)

    def get_keys(self, node_names) -> Optional[list]:
        out = []
        for name in node_names:
            pk = self._keys.get(name)
            if pk is None:
                return None
            out.append(pk)
        return out
