"""Multi-signature store keyed by state root.

Reference: plenum/bls/bls_store.py (`BlsStore`). State-proof reads fetch
the multi-sig proving a given committed state root; any KV backend works
(in-memory for sim, sqlite for durable nodes).
"""
from __future__ import annotations

import json
from typing import Optional

from ..crypto.bls.bls_crypto import MultiSignature
from ..storage.kv_store import KeyValueStorage, KeyValueStorageInMemory


class BlsStore:
    def __init__(self, kv: Optional[KeyValueStorage] = None):
        self._kv = kv if kv is not None else KeyValueStorageInMemory()

    def put(self, multi_sig: MultiSignature) -> None:
        key = multi_sig.value.state_root_hash.encode()
        self._kv.put(key, json.dumps(multi_sig.as_dict(),
                                     sort_keys=True).encode())

    def get(self, state_root_b58: str) -> Optional[MultiSignature]:
        try:
            raw = self._kv.get(state_root_b58.encode())
        except KeyError:
            return None
        if raw is None:
            return None
        return MultiSignature.from_dict(json.loads(raw.decode()))
