"""The protocol side of BLS: sign state roots in COMMIT, aggregate at order.

Reference: plenum/bls/bls_bft_replica_plenum.py (`BlsBftReplicaPlenum`),
implementing the seam declared by
:class:`indy_plenum_tpu.server.consensus.ordering_service.NoOpBlsBftReplica`:

- ``update_pre_prepare``: attach the latest known multi-sig to outgoing
  PRE-PREPAREs (propagates proofs of *previous* roots through the pool);
- ``validate_pre_prepare``: verify an attached multi-sig (suspicion
  PPR_BLS_MULTISIG_WRONG on failure);
- ``update_commit``: BLS-sign the batch's MultiSignatureValue;
- ``validate_commit``: OPTIMISTIC — individual COMMIT signatures are
  recorded without a pairing check; the aggregate is verified once at
  ordering time and only on failure are individual signatures re-checked
  to identify the culprit (aggregate-first is the batch-friendly, TPU-first
  discipline: one pairing check per ordered batch instead of n);
- ``process_order``: aggregate n-f valid signatures into a MultiSignature,
  persist it to the BlsStore keyed by state root (state-proof reads), and
  remember it for the next PRE-PREPARE.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

from ..common.exceptions import SuspiciousNode
from ..crypto.bls import bn254 as bn
from ..crypto.bls.bls_crypto import (
    BlsCryptoSigner,
    BlsCryptoVerifier,
    MultiSignature,
    MultiSignatureValue,
    g1_from_bytes,
    g1_to_bytes,
)
from ..server.suspicion_codes import Suspicions
from ..utils.base58 import b58decode, b58encode
from .bls_key_register import BlsKeyRegister
from .bls_store import BlsStore

logger = logging.getLogger(__name__)


class BlsBftReplica:
    def __init__(self,
                 node_name: str,
                 signer: BlsCryptoSigner,
                 key_register: BlsKeyRegister,
                 store: Optional[BlsStore] = None,
                 pool_state_root_provider=None,
                 suspicion_sink=None):
        self._name = node_name
        self._signer = signer
        self._verifier = BlsCryptoVerifier()
        self._register = key_register
        self._store = store if store is not None else BlsStore()
        self.key_register = key_register  # pool manager updates membership
        self._pool_root = pool_state_root_provider or (lambda: "")
        # called with a SuspiciousNode when the culprit re-check identifies
        # a bad signer (process_order cannot raise: ordering must proceed)
        self._suspicion_sink = suspicion_sink or (lambda ex: None)
        # (view_no, pp_seq_no) -> sender -> sig b58
        self._sigs: Dict[Tuple[int, int], Dict[str, str]] = {}
        self._latest_multi_sig: Optional[MultiSignature] = None
        # deferred mode (set by tick-driven compositions): process_order
        # queues its aggregate checks and flush() verifies ALL batches
        # ordered this tick in one random-linear-combination multi-
        # pairing (BlsCryptoVerifier.verify_multi_sig_batch) — one shared
        # final exponentiation per tick instead of one pairing per batch
        self.defer_verification = False
        self._pending_orders: list = []

    # --- value under signature -----------------------------------------

    def _value_for(self, pp) -> Optional[MultiSignatureValue]:
        if pp is None or pp.stateRootHash is None:
            return None
        return MultiSignatureValue(
            ledger_id=pp.ledgerId,
            state_root_hash=pp.stateRootHash,
            pool_state_root_hash=pp.poolStateRootHash or self._pool_root(),
            txn_root_hash=pp.txnRootHash or "",
            timestamp=pp.ppTime,
        )

    # --- PRE-PREPARE ----------------------------------------------------

    def update_pre_prepare(self, params: dict, ledger_id) -> dict:
        if self._latest_multi_sig is not None:
            params["blsMultiSig"] = self._latest_multi_sig.as_dict()
        return params

    def validate_pre_prepare(self, pp, sender) -> None:
        raw = getattr(pp, "blsMultiSig", None)
        if raw is None:
            return
        try:
            ms = MultiSignature.from_dict(dict(raw))
        except (KeyError, TypeError, ValueError):
            raise SuspiciousNode(
                sender, Suspicions.PPR_BLS_MULTISIG_WRONG) from None
        # steady-state memo: the attached multi-sig is almost always one
        # WE assembled (or already verified) for that state root — an
        # identical store entry needs no second pairing check
        known = self._store.get(ms.value.state_root_hash)
        if known is not None and known == ms:
            return
        pks = self._register.get_keys(ms.participants)
        if pks is None or not self._verifier.verify_multi_sig(
                ms.signature, ms.value.serialize(), pks):
            raise SuspiciousNode(sender, Suspicions.PPR_BLS_MULTISIG_WRONG)

    def process_pre_prepare(self, pp, sender) -> None:
        raw = getattr(pp, "blsMultiSig", None)
        if raw is None:
            return
        ms = MultiSignature.from_dict(dict(raw))  # validated above
        self._store.put(ms)
        self._latest_multi_sig = ms

    # --- PREPARE (nothing to do) ----------------------------------------

    def process_prepare(self, prepare, sender) -> None:
        pass

    # --- COMMIT ---------------------------------------------------------

    def update_commit(self, params: dict, pp) -> dict:
        value = self._value_for(pp)
        if value is not None:
            params["blsSig"] = self._signer.sign(value.serialize())
        return params

    def validate_commit(self, commit, sender, pp) -> None:
        # optimistic: defer PAIRING checks to aggregation (see module doc),
        # but a signature must at least decode to a canonical on-curve G1
        # point — otherwise one byzantine COMMIT would make aggregate_sigs
        # raise at ordering time on every honest node. A missing signature
        # is fine (not every node must have BLS keys).
        sig = getattr(commit, "blsSig", None)
        if sig is None:
            return
        if not isinstance(sig, str):
            raise SuspiciousNode(sender, Suspicions.CM_BLS_WRONG)
        try:
            pt = g1_from_bytes(b58decode(sig))
        except (ValueError, KeyError):
            raise SuspiciousNode(sender, Suspicions.CM_BLS_WRONG) from None
        if pt is None:
            # the identity encoding: contributes nothing to the aggregate
            # but would fail the aggregate check every batch, forcing the
            # per-signer culprit scan on the ordering hot path
            raise SuspiciousNode(sender, Suspicions.CM_BLS_WRONG)

    def process_commit(self, commit, sender) -> None:
        sig = getattr(commit, "blsSig", None)
        if sig is None:
            return
        key = (commit.viewNo, commit.ppSeqNo)
        self._sigs.setdefault(key, {})[sender] = sig

    # --- ordering -------------------------------------------------------

    def process_order(self, key, quorums, pp) -> None:
        value = self._value_for(pp)
        if value is None:
            return
        sigs = dict(self._sigs.get(key, {}))
        # include our own signature (we signed in update_commit only if we
        # sent a COMMIT; recompute — signing is cheap, one G1 mul)
        sigs[self._name] = self._signer.sign(value.serialize())
        # decode each signature exactly ONCE and aggregate the points
        # directly. validate_commit guarantees stored sigs decode to
        # non-identity points, but a raise here would desync execution on
        # every honest node, so drop failures instead of propagating.
        points: Dict[str, object] = {}
        for p, s in sigs.items():
            try:
                pt = g1_from_bytes(b58decode(s))
            except (ValueError, KeyError):
                pt = None
            if pt is None:
                logger.warning("%s: dropping bad BLS sig from %s at %s",
                               self._name, p, key)
                continue
            points[p] = pt
        if not quorums.bls_signatures.is_reached(len(points)):
            logger.debug("%s: no BLS quorum for %s (%d sigs)", self._name,
                         key, len(points))
            return
        participants = sorted(points)
        message = value.serialize()

        def _aggregate(names):
            acc = None
            for nm in names:
                acc = bn.g1_add(acc, points[nm])
            return b58encode(g1_to_bytes(acc))

        agg = _aggregate(participants)
        pks = self._register.get_keys(participants)
        if pks is None:
            return
        if self.defer_verification:
            # verified in ONE multi-pairing with everything else ordered
            # this tick (flush()); ordering itself never waited on the
            # multi-sig — it only feeds proved reads + the next PP
            self._pending_orders.append(
                (key, quorums, value, participants, agg, sigs, message,
                 pks, _aggregate))
            return
        if not self._verifier.verify_multi_sig(agg, message, pks):
            retry = self._retry_without_culprits(
                key, quorums, sigs, message, participants, _aggregate)
            if retry is None:
                return
            participants, agg = retry
        ms = MultiSignature(signature=agg, participants=participants,
                            value=value)
        self._store.put(ms)
        self._latest_multi_sig = ms

    def _retry_without_culprits(self, key, quorums, sigs, message,
                                participants, aggregate_fn):
        """Aggregate check failed: identify bad signers individually,
        raise suspicions, and retry with the good subset. Returns
        (good_participants, good_aggregate) or None if no quorum of good
        signatures remains."""
        good = []
        for p in participants:
            pk = self._register.get_key(p)
            if pk and self._verifier.verify_sig(sigs[p], message, pk):
                good.append(p)
            elif p == self._name:
                logger.error("%s: OWN BLS sig failed verification at %s",
                             self._name, key)
            else:
                logger.warning("%s: invalid BLS sig from %s at %s",
                               self._name, p, key)
                self._suspicion_sink(
                    SuspiciousNode(p, Suspicions.CM_BLS_WRONG))
        if not quorums.bls_signatures.is_reached(len(good)):
            return None
        return good, aggregate_fn(good)

    def flush(self) -> None:
        """Verify every batch ordered since the last tick in one
        random-linear-combination multi-pairing; store the proven
        multi-sigs (deferred mode's tick hook — a no-op otherwise)."""
        if not self._pending_orders:
            return
        batch, self._pending_orders = self._pending_orders, []
        # through the instance seam (compositions may substitute or
        # instrument the verifier), same as every other verification path
        verdicts = self._verifier.verify_multi_sig_batch(
            [(agg, message, pks)
             for (_k, _q, _v, _p, agg, _s, message, pks, _a) in batch])
        for ok, (key, quorums, value, participants, agg, sigs, message,
                 pks, aggregate_fn) in zip(verdicts, batch):
            if not ok:
                retry = self._retry_without_culprits(
                    key, quorums, sigs, message, participants,
                    aggregate_fn)
                if retry is None:
                    continue
                participants, agg = retry
            ms = MultiSignature(signature=agg, participants=participants,
                                value=value)
            self._store.put(ms)
            self._latest_multi_sig = ms

    # --- GC -------------------------------------------------------------

    def gc(self, key_3pc) -> None:
        stable_seq = key_3pc[1]
        self._sigs = {k: v for k, v in self._sigs.items()
                      if k[1] > stable_seq}

    # --- reads (state proofs) -------------------------------------------

    @property
    def store(self) -> BlsStore:
        return self._store

    @property
    def latest_multi_sig(self) -> Optional[MultiSignature]:
        return self._latest_multi_sig
