"""Client-side request signers: simple and DID flavours.

Reference: plenum/common/signer_simple.py (`SimpleSigner`),
plenum/common/signer_did.py (`DidSigner`), plenum/common/verifier.py
(`DidVerifier`). A signer owns an Ed25519 seed and signs the canonical
signing serialization of a request; the two flavours differ only in how the
identifier/verkey pair is derived:

- SimpleSigner: identifier = base58(verkey) — the full verkey IS the id;
- DidSigner: identifier (the DID) = base58(verkey[:16]); the wire verkey is
  abbreviated as "~" + base58(verkey[16:]) (the DID supplies the prefix).
"""
from __future__ import annotations

import os
from typing import Dict, Optional

from ..utils.base58 import b58decode, b58encode
from . import ed25519 as ed


class Signer:
    def __init__(self, seed: Optional[bytes] = None):
        if seed is None:
            seed = os.urandom(32)
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self.seed = seed
        self.verkey_raw: bytes = ed.fast_public_key(seed)

    @property
    def identifier(self) -> str:
        raise NotImplementedError

    @property
    def verkey(self) -> str:
        """Wire form of the verkey (full or abbreviated)."""
        raise NotImplementedError

    def sign_bytes(self, data: bytes) -> bytes:
        return ed.fast_sign(self.seed, data)

    def sign_request(self, request) -> None:
        """Attach signature (single-sig) to a Request in place."""
        request.identifier = self.identifier
        request.signature = b58encode(self.sign_bytes(request.signing_bytes()))

    def endorse_request(self, request) -> None:
        """Add a multi-sig endorsement under this signer's identifier."""
        sig = b58encode(self.sign_bytes(request.signing_bytes()))
        if request.signatures is None:
            request.signatures = {}
        request.signatures[self.identifier] = sig


class SimpleSigner(Signer):
    @property
    def identifier(self) -> str:
        return b58encode(self.verkey_raw)

    @property
    def verkey(self) -> str:
        return b58encode(self.verkey_raw)


class DidSigner(Signer):
    @property
    def identifier(self) -> str:
        return b58encode(self.verkey_raw[:16])

    @property
    def verkey(self) -> str:
        return "~" + b58encode(self.verkey_raw[16:])

    @property
    def full_verkey(self) -> str:
        return b58encode(self.verkey_raw)


def resolve_verkey_bytes(identifier: str, verkey: Optional[str]) -> bytes:
    """Wire (identifier, verkey) -> raw 32-byte Ed25519 key.

    Mirrors the reference's DidVerifier: an abbreviated verkey ("~xyz") is
    completed with the DID bytes as prefix; a missing verkey means the
    identifier itself encodes the full key (SimpleSigner / cryptonym).
    """
    if verkey is None or verkey == "":
        raw = b58decode(identifier)
    elif verkey.startswith("~"):
        raw = b58decode(identifier) + b58decode(verkey[1:])
    else:
        raw = b58decode(verkey)
    if len(raw) != 32:
        raise ValueError(
            f"verkey for {identifier} is {len(raw)} bytes, expected 32")
    return raw
