"""Host Ed25519: keygen / sign / verify on Python ints, RFC 8032 semantics.

Replaces the reference's libsodium binding (``stp_core/crypto/nacl_wrappers.py``:
``Signer``, ``Verifier``, ``SigningKey``, ``VerifyKey``). Signing happens on
the host (it is per-client, low volume); *verification* is the node hot path
(``plenum/server/client_authn.py`` ``CoreAuthNr.authenticate``) and is done in
bulk on the TPU by :mod:`indy_plenum_tpu.tpu.ed25519`, which imports the curve
constants and reference point arithmetic from here.

When the ``cryptography`` package (OpenSSL) is available we use it for fast
host-side sign/verify; the pure-Python path is always available and is the
oracle for kernel tests.
"""
from __future__ import annotations

import hashlib
from typing import Optional, Tuple

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# Base point B: y = 4/5 mod p, x = recovered even... sign bit 0 per RFC 8032.
_BY = (4 * pow(5, P - 2, P)) % P

Point = Tuple[int, int, int, int]  # extended homogeneous (X, Y, Z, T), T=XY/Z

IDENTITY: Point = (0, 1, 1, 0)


def _sqrt_ratio(u: int, v: int) -> Optional[int]:
    """x with v*x^2 == u (mod p), or None if no square root exists."""
    # cand = u*v^3 * (u*v^7)^((p-5)/8) -- standard RFC 8032 trick
    cand = (u * pow(v, 3, P) * pow((u * pow(v, 7, P)) % P, (P - 5) // 8, P)) % P
    if (v * cand * cand) % P == u % P:
        return cand
    if (v * cand * cand) % P == (-u) % P:
        return (cand * SQRT_M1) % P
    return None


def decompress(data: bytes) -> Optional[Point]:
    """32-byte compressed point -> extended point, rejecting non-canonical y."""
    if len(data) != 32:
        return None
    y = int.from_bytes(data, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= P:
        return None
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    x = _sqrt_ratio(u, v)
    if x is None:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return (x, y, 1, (x * y) % P)


def compress(pt: Point) -> bytes:
    X, Y, Z, _ = pt
    zi = pow(Z, P - 2, P)
    x = (X * zi) % P
    y = (Y * zi) % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def point_add(p: Point, q: Point) -> Point:
    """Unified addition, add-2008-hwcd-3 for a=-1 twisted Edwards."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = ((Y1 - X1) * (Y2 - X2)) % P
    B = ((Y1 + X1) * (Y2 + X2)) % P
    C = (T1 * 2 * D % P * T2) % P
    Dd = (Z1 * 2 * Z2) % P
    E = (B - A) % P
    F = (Dd - C) % P
    G = (Dd + C) % P
    H = (B + A) % P
    return ((E * F) % P, (G * H) % P, (F * G) % P, (E * H) % P)


def point_double(p: Point) -> Point:
    """dbl-2008-hwcd for a=-1."""
    X1, Y1, Z1, _ = p
    A = (X1 * X1) % P
    B = (Y1 * Y1) % P
    C = (2 * Z1 * Z1) % P
    Dd = (-A) % P
    E = ((X1 + Y1) * (X1 + Y1) - A - B) % P
    G = (Dd + B) % P
    F = (G - C) % P
    H = (Dd - B) % P
    return ((E * F) % P, (G * H) % P, (F * G) % P, (E * H) % P)


def point_neg(p: Point) -> Point:
    X, Y, Z, T = p
    return ((-X) % P, Y, Z, (-T) % P)


def point_eq(p: Point, q: Point) -> bool:
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def scalar_mult(k: int, p: Point) -> Point:
    acc = IDENTITY
    while k > 0:
        if k & 1:
            acc = point_add(acc, p)
        p = point_double(p)
        k >>= 1
    return acc


def _base_point() -> Point:
    pt = decompress(_BY.to_bytes(32, "little"))
    assert pt is not None
    return pt


BASE: Point = _base_point()


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def secret_scalar(seed: bytes) -> Tuple[int, bytes]:
    """seed (32 bytes) -> (clamped scalar a, hash prefix for nonce derivation)."""
    h = hashlib.sha512(seed).digest()
    return _clamp(h), h[32:]


def public_key(seed: bytes) -> bytes:
    a, _ = secret_scalar(seed)
    return compress(scalar_mult(a, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    a, prefix = secret_scalar(seed)
    A = public_key(seed)
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    Rb = compress(scalar_mult(r, BASE))
    k = int.from_bytes(hashlib.sha512(Rb + A + msg).digest(), "little") % L
    S = (r + k * a) % L
    return Rb + S.to_bytes(32, "little")


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """Scalar host verification (the oracle; the TPU path is the hot one)."""
    if len(sig) != 64 or len(pk) != 32:
        return False
    Rb, Sb = sig[:32], sig[32:]
    S = int.from_bytes(Sb, "little")
    if S >= L:
        return False
    A = decompress(pk)
    R = decompress(Rb)
    if A is None or R is None:
        return False
    k = int.from_bytes(hashlib.sha512(Rb + pk + msg).digest(), "little") % L
    # S*B == R + k*A  <=>  S*B + k*(-A) == R
    lhs = point_add(scalar_mult(S, BASE), scalar_mult(k, point_neg(A)))
    return compress(lhs) == Rb


# ---------------------------------------------------------------------------
# Fast host path via OpenSSL when present (sign/keygen convenience).
# ---------------------------------------------------------------------------
try:  # pragma: no cover - environment probe
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.exceptions import InvalidSignature

    HAVE_OPENSSL = True

    def fast_sign(seed: bytes, msg: bytes) -> bytes:
        return Ed25519PrivateKey.from_private_bytes(seed).sign(msg)

    def fast_public_key(seed: bytes) -> bytes:
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        return (
            Ed25519PrivateKey.from_private_bytes(seed)
            .public_key()
            .public_bytes(Encoding.Raw, PublicFormat.Raw)
        )

    def fast_verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
        try:
            Ed25519PublicKey.from_public_bytes(pk).verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            return False

except ImportError:  # pragma: no cover
    HAVE_OPENSSL = False
    fast_sign = sign
    fast_public_key = public_key
    fast_verify = verify
