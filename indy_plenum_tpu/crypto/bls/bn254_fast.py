"""Fast BN254 path: projective coordinates, inversion-free Miller loop.

:mod:`indy_plenum_tpu.crypto.bls.bn254` is the correctness oracle — affine
arithmetic, untwist-to-Fp12 Miller loop, one Fp inversion per point op.
This module is the production path the oracle pins in tests/test_bls.py:

- G1/G2 scalar multiplication in Jacobian coordinates (ONE inversion per
  scalar mul instead of one per bit: 24 ms -> ~0.5 ms for a G1 sign);
- the optimal-ate Miller loop on the twist in homogeneous fractional
  coordinates (x = X/Z, y = Y/Z over Fp2) with denominator-free line
  evaluation — line values are scaled by Fp2 subfield factors, which the
  final exponentiation kills (c^((p^12-1)/r) = 1 for c in Fp2 because
  (p^2-1) | (p^6-1) divides the easy part);
- sparse 0-1-3 line accumulation: a line evaluates to
  c0 + c1*w + c3*w^3, so the Fp12 product touches ~15 Fp2 muls instead of
  a dense mul.

Formulas are derived directly from the affine chord/tangent equations
(docstrings show the derivation), NOT transcribed from any library; the
oracle equivalence tests are the safety net for the embedding layout.
"""
from __future__ import annotations

from typing import Tuple

from . import bn254 as bn
from .bn254 import (
    FP12_ONE,
    FP2_ZERO,
    P,
    f2_add,
    f2_inv,
    f2_mul,
    f2_muls,
    f2_neg,
    f2_sqr,
    f2_sub,
    f6_add,
    f6_mul_v,
    f6_sub,
    f12_mul,
    f12_sqr,
)

Fp2 = Tuple[int, int]

# twist curve constant b' = 3/xi (E': y^2 = x^3 + b') — the oracle's B2
_B_TWIST = bn.B2


# ---------------------------------------------------------------------------
# G1 Jacobian (x = X/Z^2, y = Y/Z^3), curve y^2 = x^3 + 3
# ---------------------------------------------------------------------------


def _g1j_double(X: int, Y: int, Z: int):
    if Y == 0:
        return 0, 1, 0  # infinity
    S = 4 * X * Y * Y % P
    M = 3 * X * X % P
    X3 = (M * M - 2 * S) % P
    Y8 = 8 * pow(Y, 4, P) % P
    Y3 = (M * (S - X3) - Y8) % P
    Z3 = 2 * Y * Z % P
    return X3, Y3, Z3


def _g1j_add_affine(X: int, Y: int, Z: int, x2: int, y2: int):
    if Z == 0:
        return x2, y2, 1
    Z2 = Z * Z % P
    U2 = x2 * Z2 % P
    S2 = y2 * Z2 * Z % P
    H = (U2 - X) % P
    r = (S2 - Y) % P
    if H == 0:
        if r == 0:
            return _g1j_double(X, Y, Z)
        return 0, 1, 0
    H2 = H * H % P
    H3 = H * H2 % P
    XH2 = X * H2 % P
    X3 = (r * r - H3 - 2 * XH2) % P
    Y3 = (r * (XH2 - X3) - Y * H3) % P
    Z3 = Z * H % P
    return X3, Y3, Z3


def g1_mul(pt: bn.G1Point, k: int) -> bn.G1Point:
    """Jacobian double-and-add; one field inversion total."""
    k %= bn.R
    if pt is None or k == 0:
        return None
    x2, y2 = pt
    X, Y, Z = 0, 1, 0
    for bit in bin(k)[2:]:
        X, Y, Z = _g1j_double(X, Y, Z)
        if bit == "1":
            X, Y, Z = _g1j_add_affine(X, Y, Z, x2, y2)
    if Z == 0:
        return None
    zi = pow(Z, P - 2, P)
    zi2 = zi * zi % P
    return (X * zi2 % P, Y * zi2 * zi % P)


# ---------------------------------------------------------------------------
# G2 Jacobian over Fp2 (same formulas, field ops from the oracle)
# ---------------------------------------------------------------------------

_F2_0: Fp2 = (0, 0)
_F2_1: Fp2 = (1, 0)


def _g2j_double(X: Fp2, Y: Fp2, Z: Fp2):
    if Y == _F2_0:
        return _F2_0, _F2_1, _F2_0
    Y2 = f2_sqr(Y)
    S = f2_muls(f2_mul(X, Y2), 4)
    M = f2_muls(f2_sqr(X), 3)
    X3 = f2_sub(f2_sqr(M), f2_muls(S, 2))
    Y3 = f2_sub(f2_mul(M, f2_sub(S, X3)), f2_muls(f2_sqr(Y2), 8))
    Z3 = f2_muls(f2_mul(Y, Z), 2)
    return X3, Y3, Z3


def _g2j_add_affine(X: Fp2, Y: Fp2, Z: Fp2, x2: Fp2, y2: Fp2):
    if Z == _F2_0:
        return x2, y2, _F2_1
    Z2 = f2_sqr(Z)
    U2 = f2_mul(x2, Z2)
    S2 = f2_mul(f2_mul(y2, Z2), Z)
    H = f2_sub(U2, X)
    r = f2_sub(S2, Y)
    if H == _F2_0:
        if r == _F2_0:
            return _g2j_double(X, Y, Z)
        return _F2_0, _F2_1, _F2_0
    H2 = f2_sqr(H)
    H3 = f2_mul(H, H2)
    XH2 = f2_mul(X, H2)
    X3 = f2_sub(f2_sub(f2_sqr(r), H3), f2_muls(XH2, 2))
    Y3 = f2_sub(f2_mul(r, f2_sub(XH2, X3)), f2_mul(Y, H3))
    Z3 = f2_mul(Z, H)
    return X3, Y3, Z3


def g2_mul(pt: bn.G2Point, k: int) -> bn.G2Point:
    k %= bn.R
    if pt is None or k == 0:
        return None
    x2, y2 = pt
    X, Y, Z = _F2_0, _F2_1, _F2_0
    for bit in bin(k)[2:]:
        X, Y, Z = _g2j_double(X, Y, Z)
        if bit == "1":
            X, Y, Z = _g2j_add_affine(X, Y, Z, x2, y2)
    if Z == _F2_0:
        return None
    zi = f2_inv(Z)
    zi2 = f2_sqr(zi)
    return (f2_mul(X, zi2), f2_mul(Y, f2_mul(zi2, zi)))


def fp_sqrt(x: int):
    """sqrt mod P, or None for a non-residue (same API as the C backend)."""
    x %= bn.P
    y = pow(x, (bn.P + 1) // 4, bn.P)
    return y if y * y % bn.P == x else None


def g2_in_subgroup(pt: bn.G2Point) -> bool:
    """[R]Q == O via an UNREDUCED Jacobian ladder — g2_mul reduces the
    scalar mod R, which would turn this check into a tautology and admit
    out-of-subgroup keys (the twist's order is R*(2P - R))."""
    if pt is None:
        return True
    if not bn.g2_is_on_curve(pt):
        return False
    x2, y2 = pt
    X, Y, Z = _F2_0, _F2_1, _F2_0
    for bit in bin(bn.R)[2:]:
        X, Y, Z = _g2j_double(X, Y, Z)
        if bit == "1":
            X, Y, Z = _g2j_add_affine(X, Y, Z, x2, y2)
    return Z == _F2_0


def g1_sum(points) -> bn.G1Point:
    """Sum many G1 points with ONE inversion (Jacobian accumulation)."""
    X, Y, Z = 0, 1, 0
    for pt in points:
        if pt is None:
            continue
        X, Y, Z = _g1j_add_affine(X, Y, Z, pt[0], pt[1])
    if Z == 0:
        return None
    zi = pow(Z, P - 2, P)
    zi2 = zi * zi % P
    return (X * zi2 % P, Y * zi2 * zi % P)


def g2_sum(points) -> bn.G2Point:
    X, Y, Z = _F2_0, _F2_1, _F2_0
    for pt in points:
        if pt is None:
            continue
        X, Y, Z = _g2j_add_affine(X, Y, Z, pt[0], pt[1])
    if Z == _F2_0:
        return None
    zi = f2_inv(Z)
    zi2 = f2_sqr(zi)
    return (f2_mul(X, zi2), f2_mul(Y, f2_mul(zi2, zi)))


# ---------------------------------------------------------------------------
# Miller loop on the twist: fractional coords x=X/Z, y=Y/Z over Fp2.
#
# A line through the UNTWISTED points evaluated at P=(xp, yp) has the shape
# c0 + c1*w + c3*w^3 with c_i in Fp2 (derivation in each step function);
# w^2 = v places c1 at Fp12 position (1,0) and c3 at (1,1).
# ---------------------------------------------------------------------------


def _sparse_013(f, c0: Fp2, c1: Fp2, c3: Fp2):
    """f * (c0 + c1*w + c3*w^3), exploiting the zero coefficients.

    With f = (a, b), l = (la, lb), la = (c0,0,0), lb = (c1,c3,0):
    f*l = (a*la + v*(b*lb), (a+b)(la+lb) - a*la - b*lb)  [oracle f12_mul].
    a*la is a scalar Fp2 product; b*lb and the cross term hit the sparse
    (e0, e1, 0) pattern: (b0,b1,b2)*(e0,e1,0) =
      (b0e0 + XI*b2e1, b0e1 + b1e0, b1e1 + b2e0).
    """
    a, b = f
    t0 = (f2_mul(a[0], c0), f2_mul(a[1], c0), f2_mul(a[2], c0))

    def sparse6(x, e0, e1):
        x0, x1, x2 = x
        return (f2_add(f2_mul(x0, e0), bn._mul_xi(f2_mul(x2, e1))),
                f2_add(f2_mul(x0, e1), f2_mul(x1, e0)),
                f2_add(f2_mul(x1, e1), f2_mul(x2, e0)))

    t1 = sparse6(b, c1, c3)
    s = f6_add(a, b)
    cross = sparse6(s, f2_add(c0, c1), c3)
    new_a = f6_add(t0, f6_mul_v(t1))
    new_b = f6_sub(f6_sub(cross, t0), t1)
    return (new_a, new_b)


def _dbl_step(X: Fp2, Y: Fp2, Z: Fp2, xp: int, yp: int):
    """Double T and evaluate the tangent line at P.

    Affine tangent at (x, y): lambda = 3x^2/2y; line at P is
    yp - y - lambda(xp - x). Untwisted (x~ = x w^2, y~ = y w^3) and scaled
    by 2y * Z^3 (Fp2 factors — killed by final exp):
      c0 = 2 Y Z^2 * yp,  c1 = -3 X^2 Z * xp,  c3 = X^3 - 2 b' Z^3.
    Point update (scale Z3 = 8 (YZ)^3):
      X3 = 2YZ(9X^4 - 8XY^2Z),  Y3 = 36X^3Y^2Z - 27X^6 - 8Y^4Z^2.
    """
    X2 = f2_sqr(X)
    X4 = f2_sqr(X2)
    Y2 = f2_sqr(Y)
    Z2 = f2_sqr(Z)
    YZ = f2_mul(Y, Z)
    XY2Z = f2_mul(f2_mul(X, Y2), Z)

    c0 = f2_muls(f2_mul(Y, Z2), 2 * yp)
    c1 = f2_muls(f2_mul(X2, Z), (-3 * xp) % P)
    c3 = f2_sub(f2_mul(X, X2),
                f2_muls(f2_mul(_B_TWIST, f2_mul(Z, Z2)), 2))

    X3 = f2_muls(f2_mul(YZ, f2_sub(f2_muls(X4, 9), f2_muls(XY2Z, 8))), 2)
    Y3 = f2_sub(
        f2_sub(f2_muls(f2_mul(f2_mul(X, X2), f2_mul(Y2, Z)), 36),
               f2_muls(f2_mul(X2, X4), 27)),
        f2_muls(f2_mul(f2_sqr(Y2), Z2), 8))
    Z3 = f2_muls(f2_mul(YZ, f2_mul(Y2, Z2)), 8)
    return (X3, Y3, Z3), (c0, c1, c3)


def _add_step(X: Fp2, Y: Fp2, Z: Fp2, x2: Fp2, y2: Fp2, xp: int, yp: int):
    """Add affine Q=(x2,y2) to T and evaluate the chord line at P.

    lambda = (y2 - y)/(x2 - x); with A = y2 Z - Y, B = x2 Z - X:
    line scaled by B:  c0 = B*yp,  c1 = -A*xp,  c3 = A x2 - B y2.
    Point update (Z3 = B^3 Z):
      X3 = B (A^2 Z - (X + x2 Z) B^2),
      Y3 = A ((2 x2 Z + X) B^2 - A^2 Z) - y2 B^3 Z.
    """
    x2Z = f2_mul(x2, Z)
    A = f2_sub(f2_mul(y2, Z), Y)
    B = f2_sub(x2Z, X)
    # the ate loop on a prime-order Q never lands on T = +/-Q mid-loop,
    # but the frobenius correction points could in principle collide; the
    # oracle handles those cases, so delegate rather than mis-evaluate
    if B == _F2_0:
        raise _NeedOracle
    A2 = f2_sqr(A)
    B2 = f2_sqr(B)
    B3 = f2_mul(B, B2)
    A2Z = f2_mul(A2, Z)

    c0 = f2_muls(B, yp)
    c1 = f2_muls(A, (-xp) % P)
    c3 = f2_sub(f2_mul(A, x2), f2_mul(B, y2))

    X3 = f2_mul(B, f2_sub(A2Z, f2_mul(f2_add(X, x2Z), B2)))
    Y3 = f2_sub(
        f2_mul(A, f2_sub(f2_mul(f2_add(f2_muls(x2Z, 2), X), B2), A2Z)),
        f2_mul(y2, f2_mul(B3, Z)))
    Z3 = f2_mul(B3, Z)
    return (X3, Y3, Z3), (c0, c1, c3)


class _NeedOracle(Exception):
    pass


def _frobenius_twist(q: bn.G2Point) -> bn.G2Point:
    """pi(Q) expressed back in twist coordinates.

    Computed via the oracle's untwist/Frobenius (x~ = x w^2 has its Fp2
    coefficient at Fp6 slot v of the first half; y~ = y w^3 at slot v of
    the second half), so the twisting constants cannot drift from the
    oracle's embedding.
    """
    u = bn._untwist(q)
    fx = bn.f12_frobenius(u[0])
    fy = bn.f12_frobenius(u[1])
    # fx must be (0, X', 0 | 0, 0, 0), fy must be (0,0,0 | 0, Y', 0)
    assert fx[0][0] == FP2_ZERO and fx[0][2] == FP2_ZERO \
        and fx[1] == bn.FP6_ZERO, "frobenius x not in w^2 position"
    assert fy[0] == bn.FP6_ZERO and fy[1][0] == FP2_ZERO \
        and fy[1][2] == FP2_ZERO, "frobenius y not in w^3 position"
    return (fx[0][1], fy[1][1])


_ATE_BITS = bin(6 * bn.U + 2)[3:]


def miller_loop(q: bn.G2Point, p_at: bn.G1Point):
    if q is None or p_at is None:
        return FP12_ONE
    xp, yp = p_at
    x2, y2 = q
    T = (x2, y2, _F2_1)
    f = FP12_ONE
    for bit in _ATE_BITS:
        T, line = _dbl_step(*T, xp, yp)
        f = _sparse_013(f12_sqr(f), *line)
        if bit == "1":
            T, line = _add_step(*T, x2, y2, xp, yp)
            f = _sparse_013(f, *line)
    q1 = _frobenius_twist(q)
    q2 = _frobenius_twist(q1)
    nq2 = (q2[0], f2_neg(q2[1]))
    T, line = _add_step(*T, q1[0], q1[1], xp, yp)
    f = _sparse_013(f, *line)
    _, line = _add_step(*T, nq2[0], nq2[1], xp, yp)
    f = _sparse_013(f, *line)
    return f


def multi_pairing(pairs):
    """prod e(Pi, Qi) with a shared final exponentiation."""
    try:
        f = FP12_ONE
        for p_at, q in pairs:
            if p_at is None or q is None:
                continue
            f = f12_mul(f, miller_loop(q, p_at))
        return bn._full(f)
    except _NeedOracle:  # pragma: no cover — degenerate correction points
        return bn.multi_pairing(pairs)


def pairing(q: bn.G2Point, p_at: bn.G1Point):
    assert bn.g1_is_on_curve(p_at), "P not on G1"
    assert bn.g2_is_on_curve(q), "Q not on E'"
    return multi_pairing([(p_at, q)])


def pairing_check(pairs) -> bool:
    return multi_pairing(pairs) == FP12_ONE
