"""BN254 (alt_bn128) pairing arithmetic, pure Python.

Host-side replacement for the reference's indy-crypto/ursa BLS backend
(crypto/bls/indy_crypto/bls_crypto_indy_crypto.py, Rust BN254 via AMCL).
SURVEY.md §7 ranks BN254 pairings the hardest kernel and prescribes a host
implementation first (TPU batch Miller loop only if profiling demands).

Standard construction (the Ethereum alt_bn128 parameterization):
  u = 4965661367192848881
  p = 36u^4 + 36u^3 + 24u^2 + 6u + 1   (field modulus)
  r = 36u^4 + 36u^3 + 18u^2 + 6u + 1   (group order)
  E:  y^2 = x^3 + 3       over Fp   (G1)
  E': y^2 = x^3 + 3/(9+i) over Fp2  (G2, D-type sextic twist)
Pairing: optimal ate, Miller loop over 6u+2, then final exponentiation
(p^12-1)/r with the standard hard-part decomposition.

Tower: Fp2 = Fp[i]/(i^2+1); Fp6 = Fp2[v]/(v^3 - (9+i)); Fp12 = Fp6[w]/(w^2 - v).
Elements are represented as nested tuples of ints; all functions are pure.
"""
from __future__ import annotations

from typing import Optional, Tuple

U = 4965661367192848881
P = 36 * U**4 + 36 * U**3 + 24 * U**2 + 6 * U + 1
R = 36 * U**4 + 36 * U**3 + 18 * U**2 + 6 * U + 1

assert P == 21888242871839275222246405745257275088696311157297823662689037894645226208583
assert R == 21888242871839275222246405745257275088548364400416034343698204186575808495617

# --- Fp2 -------------------------------------------------------------------
# a + b*i with i^2 = -1

Fp2 = Tuple[int, int]
FP2_ONE: Fp2 = (1, 0)
FP2_ZERO: Fp2 = (0, 0)

# the twist constant xi = 9 + i
XI: Fp2 = (9, 1)


def f2_add(a: Fp2, b: Fp2) -> Fp2:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a: Fp2, b: Fp2) -> Fp2:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a: Fp2) -> Fp2:
    return ((-a[0]) % P, (-a[1]) % P)


def f2_mul(a: Fp2, b: Fp2) -> Fp2:
    # (a0 + a1 i)(b0 + b1 i) = (a0b0 - a1b1) + (a0b1 + a1b0) i
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def f2_sqr(a: Fp2) -> Fp2:
    # (a0 + a1 i)^2 = (a0+a1)(a0-a1) + 2 a0 a1 i
    t0 = (a[0] + a[1]) * (a[0] - a[1])
    t1 = 2 * a[0] * a[1]
    return (t0 % P, t1 % P)


def f2_muls(a: Fp2, s: int) -> Fp2:
    return ((a[0] * s) % P, (a[1] * s) % P)


def f2_inv(a: Fp2) -> Fp2:
    # 1/(a0 + a1 i) = (a0 - a1 i)/(a0^2 + a1^2)
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    ninv = pow(norm, P - 2, P)
    return ((a[0] * ninv) % P, (-a[1] * ninv) % P)


def f2_conj(a: Fp2) -> Fp2:
    return (a[0], (-a[1]) % P)


def f2_pow(a: Fp2, e: int) -> Fp2:
    out = FP2_ONE
    base = a
    while e:
        if e & 1:
            out = f2_mul(out, base)
        base = f2_sqr(base)
        e >>= 1
    return out


# --- Fp6 = Fp2[v]/(v^3 - XI) ----------------------------------------------

Fp6 = Tuple[Fp2, Fp2, Fp2]
FP6_ZERO: Fp6 = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE: Fp6 = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def _mul_xi(a: Fp2) -> Fp2:
    return f2_mul(a, XI)


def f6_add(a: Fp6, b: Fp6) -> Fp6:
    return (f2_add(a[0], b[0]), f2_add(a[1], b[1]), f2_add(a[2], b[2]))


def f6_sub(a: Fp6, b: Fp6) -> Fp6:
    return (f2_sub(a[0], b[0]), f2_sub(a[1], b[1]), f2_sub(a[2], b[2]))


def f6_neg(a: Fp6) -> Fp6:
    return (f2_neg(a[0]), f2_neg(a[1]), f2_neg(a[2]))


def f6_mul(a: Fp6, b: Fp6) -> Fp6:
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(t0, _mul_xi(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)),
                                   f2_add(t1, t2))))
    c1 = f2_add(f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)),
                       f2_add(t0, t1)), _mul_xi(t2))
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)),
                       f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def f6_sqr(a: Fp6) -> Fp6:
    return f6_mul(a, a)


def f6_muls2(a: Fp6, s: Fp2) -> Fp6:
    return (f2_mul(a[0], s), f2_mul(a[1], s), f2_mul(a[2], s))


def f6_mul_v(a: Fp6) -> Fp6:
    # v * (a0 + a1 v + a2 v^2) = XI*a2 + a0 v + a1 v^2
    return (_mul_xi(a[2]), a[0], a[1])


def f6_inv(a: Fp6) -> Fp6:
    a0, a1, a2 = a
    c0 = f2_sub(f2_sqr(a0), _mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    t = f2_add(f2_mul(a2, c1), f2_mul(a1, c2))
    t = f2_add(_mul_xi(t), f2_mul(a0, c0))
    ti = f2_inv(t)
    return (f2_mul(c0, ti), f2_mul(c1, ti), f2_mul(c2, ti))


# --- Fp12 = Fp6[w]/(w^2 - v) ----------------------------------------------

Fp12 = Tuple[Fp6, Fp6]
FP12_ONE: Fp12 = (FP6_ONE, FP6_ZERO)


def f12_mul(a: Fp12, b: Fp12) -> Fp12:
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c0 = f6_add(t0, f6_mul_v(t1))
    c1 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), f6_add(t0, t1))
    return (c0, c1)


def f12_sqr(a: Fp12) -> Fp12:
    a0, a1 = a
    t0 = f6_mul(a0, a1)
    c0 = f6_mul(f6_add(a0, a1), f6_add(a0, f6_mul_v(a1)))
    c0 = f6_sub(f6_sub(c0, t0), f6_mul_v(t0))
    c1 = f6_add(t0, t0)
    return (c0, c1)


def f12_conj(a: Fp12) -> Fp12:
    return (a[0], f6_neg(a[1]))


def f12_inv(a: Fp12) -> Fp12:
    a0, a1 = a
    t = f6_sub(f6_mul(a0, a0), f6_mul_v(f6_mul(a1, a1)))
    ti = f6_inv(t)
    return (f6_mul(a0, ti), f6_neg(f6_mul(a1, ti)))


def f12_pow(a: Fp12, e: int) -> Fp12:
    if e < 0:
        return f12_pow(f12_conj(a), -e)  # valid for unitary elements only
    out = FP12_ONE
    base = a
    while e:
        if e & 1:
            out = f12_mul(out, base)
        base = f12_sqr(base)
        e >>= 1
    return out


# Frobenius coefficients: gamma_1[j] = XI^((p-1)*j/6) for j=1..5
_G1C = [f2_pow(XI, (P - 1) * j // 6) for j in range(6)]


def f12_frobenius(a: Fp12) -> Fp12:
    """x -> x^p on Fp12."""
    (a00, a01, a02), (a10, a11, a12) = a
    c00 = f2_conj(a00)
    c01 = f2_mul(f2_conj(a01), _G1C[2])
    c02 = f2_mul(f2_conj(a02), _G1C[4])
    c10 = f2_mul(f2_conj(a10), _G1C[1])
    c11 = f2_mul(f2_conj(a11), _G1C[3])
    c12 = f2_mul(f2_conj(a12), _G1C[5])
    return ((c00, c01, c02), (c10, c11, c12))


def f12_frobenius_n(a: Fp12, n: int) -> Fp12:
    for _ in range(n):
        a = f12_frobenius(a)
    return a


# --- G1 (affine over Fp, b=3) ----------------------------------------------

G1Point = Optional[Tuple[int, int]]  # None = infinity
G1_GEN: G1Point = (1, 2)


def g1_is_on_curve(pt: G1Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - 3) % P == 0


def g1_add(a: G1Point, b: G1Point) -> G1Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_neg(a: G1Point) -> G1Point:
    if a is None:
        return None
    return (a[0], (-a[1]) % P)


def g1_mul(a: G1Point, k: int) -> G1Point:
    k %= R
    out: G1Point = None
    add = a
    while k:
        if k & 1:
            out = g1_add(out, add)
        add = g1_add(add, add)
        k >>= 1
    return out


# --- G2 (affine over Fp2, b = 3/XI) ---------------------------------------

B2: Fp2 = f2_mul((3, 0), f2_inv(XI))

G2Point = Optional[Tuple[Fp2, Fp2]]
G2_GEN: G2Point = (
    (10857046999023057135944570762232829481370756359578518086990519993285655852781,
     11559732032986387107991004021392285783925812861821192530917403151452391805634),
    (8495653923123431417604973247489272438418190587263600148770280649306958101930,
     4082367875863433681332203403145435568316851327593401208105741076214120093531),
)


def g2_is_on_curve(pt: G2Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    return f2_sub(f2_sqr(y), f2_add(f2_mul(f2_sqr(x), x), B2)) == FP2_ZERO


def g2_add(a: G2Point, b: G2Point) -> G2Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if f2_add(y1, y2) == FP2_ZERO:
            return None
        lam = f2_mul(f2_muls(f2_sqr(x1), 3), f2_inv(f2_muls(y1, 2)))
    else:
        lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
    x3 = f2_sub(f2_sub(f2_sqr(lam), x1), x2)
    y3 = f2_sub(f2_mul(lam, f2_sub(x1, x3)), y1)
    return (x3, y3)


def g2_neg(a: G2Point) -> G2Point:
    if a is None:
        return None
    return (a[0], f2_neg(a[1]))


def g2_mul(a: G2Point, k: int) -> G2Point:
    k %= R
    out: G2Point = None
    add = a
    while k:
        if k & 1:
            out = g2_add(out, add)
        add = g2_add(add, add)
        k >>= 1
    return out


def g2_in_subgroup(pt: G2Point) -> bool:
    """Full-order check: r*Q == O (G2's cofactor is > 1).

    The ladder must NOT reduce the scalar mod R the way g2_mul does —
    [R mod R]Q = O for every point, which would make this check vacuous
    and admit out-of-subgroup keys (small-subgroup confinement on the
    twist, whose order is R*(2P - R))."""
    if pt is None:
        return True
    if not g2_is_on_curve(pt):
        return False
    out: G2Point = None
    add = pt
    k = R
    while k:
        if k & 1:
            out = g2_add(out, add)
        add = g2_add(add, add)
        k >>= 1
    return out is None


# --- pairing ---------------------------------------------------------------
# Strategy: untwist G2 into E(Fp12) and run a textbook Miller loop with
# affine Fp12 arithmetic. ~3x slower than sparse-line tricks but immune to
# embedding-layout bugs — this library is the correctness oracle; speed
# lives on-device (SURVEY.md §7).


def _embed_f2(a: Fp2) -> Fp12:
    return ((a, FP2_ZERO, FP2_ZERO), FP6_ZERO)


def _embed_int(x: int) -> Fp12:
    return _embed_f2((x % P, 0))


# w^2 = v, w^6 = XI: the untwist scale factors
_W2: Fp12 = ((FP2_ZERO, FP2_ONE, FP2_ZERO), FP6_ZERO)  # = v = w^2
_W3: Fp12 = (FP6_ZERO, (FP2_ZERO, FP2_ONE, FP2_ZERO))  # = v*w = w^3

F12Point = Optional[Tuple[Fp12, Fp12]]


def _untwist(q: G2Point) -> F12Point:
    """E'(Fp2) -> E(Fp12): (x, y) -> (x*w^2, y*w^3)."""
    if q is None:
        return None
    x, y = q
    return (f12_mul(_embed_f2(x), _W2), f12_mul(_embed_f2(y), _W3))


def _f12pt_add(a: F12Point, b: F12Point) -> F12Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if f12_add(y1, y2) == _F12_ZERO:
            return None
        lam = f12_mul(f12_muls(f12_sqr(x1), 3),
                      f12_inv(f12_muls(y1, 2)))
    else:
        lam = f12_mul(f12_sub(y2, y1), f12_inv(f12_sub(x2, x1)))
    x3 = f12_sub(f12_sub(f12_sqr(lam), x1), x2)
    y3 = f12_sub(f12_mul(lam, f12_sub(x1, x3)), y1)
    return (x3, y3)


def f12_add(a: Fp12, b: Fp12) -> Fp12:
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_sub(a: Fp12, b: Fp12) -> Fp12:
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


def f12_muls(a: Fp12, s: int) -> Fp12:
    return (f6_muls2(a[0], (s % P, 0)), f6_muls2(a[1], (s % P, 0)))


_F12_ZERO: Fp12 = (FP6_ZERO, FP6_ZERO)


def _line_f12(t: F12Point, q: F12Point, xp: Fp12, yp: Fp12) -> Fp12:
    """Line through t and q (tangent if equal) evaluated at (xp, yp)."""
    x1, y1 = t
    x2, y2 = q
    if x1 == x2 and f12_add(y1, y2) == _F12_ZERO:
        return f12_sub(xp, x1)  # vertical
    if x1 == x2 and y1 == y2:
        lam = f12_mul(f12_muls(f12_sqr(x1), 3), f12_inv(f12_muls(y1, 2)))
    else:
        lam = f12_mul(f12_sub(y2, y1), f12_inv(f12_sub(x2, x1)))
    return f12_sub(f12_sub(yp, y1), f12_mul(lam, f12_sub(xp, x1)))


def miller_loop(q: G2Point, p_at: G1Point) -> Fp12:
    if q is None or p_at is None:
        return FP12_ONE
    big_q = _untwist(q)
    xp, yp = _embed_int(p_at[0]), _embed_int(p_at[1])
    t = big_q
    f = FP12_ONE
    for bit in bin(6 * U + 2)[3:]:
        f = f12_mul(f12_sqr(f), _line_f12(t, t, xp, yp))
        t = _f12pt_add(t, t)
        if bit == "1":
            f = f12_mul(f, _line_f12(t, big_q, xp, yp))
            t = _f12pt_add(t, big_q)
    # optimal-ate correction terms: Q1 = pi(Q), Q2 = pi^2(Q)
    q1 = (f12_frobenius(big_q[0]), f12_frobenius(big_q[1]))
    q2 = (f12_frobenius(q1[0]), f12_frobenius(q1[1]))
    nq2 = (q2[0], f12_sub(_F12_ZERO, q2[1]))
    f = f12_mul(f, _line_f12(t, q1, xp, yp))
    t = _f12pt_add(t, q1)
    f = f12_mul(f, _line_f12(t, nq2, xp, yp))
    return f


def final_exponentiation(f: Fp12) -> Fp12:
    return _full(f)


def _easy(f: Fp12) -> Fp12:
    f1 = f12_conj(f)  # f^(p^6) for unitary... general: conj works after inv
    f2i = f12_inv(f)
    f = f12_mul(f1, f2i)  # f^(p^6 - 1)
    return f12_mul(f12_frobenius_n(f, 2), f)  # ^(p^2 + 1)


def _conj(a: Fp12) -> Fp12:
    return f12_conj(a)


def _hard(m: Fp12) -> Fp12:
    """Hard part m^((p^4-p^2+1)/r) for a unitary m, via the
    Devegili-Scott-Dahab vector addition chain (3 u-power chains instead of
    one 2544-bit exponentiation). Pinned against the generic power in
    tests/test_bls.py."""
    fu1 = f12_pow(m, U)
    fu2 = f12_pow(fu1, U)
    fu3 = f12_pow(fu2, U)
    fp1 = f12_frobenius(m)
    fp2 = f12_frobenius(fp1)
    fp3 = f12_frobenius(fp2)
    y0 = f12_mul(f12_mul(fp1, fp2), fp3)
    y1 = _conj(m)
    y2 = f12_frobenius_n(fu2, 2)
    y3 = _conj(f12_frobenius(fu1))
    y4 = _conj(f12_mul(fu1, f12_frobenius(fu2)))
    y5 = _conj(fu2)
    y6 = _conj(f12_mul(fu3, f12_frobenius(fu3)))
    t0 = f12_mul(f12_sqr(y6), f12_mul(y4, y5))
    t1 = f12_mul(f12_mul(y3, y5), t0)
    t0 = f12_mul(t0, y2)
    t1 = f12_mul(f12_sqr(t1), t0)
    t1 = f12_sqr(t1)
    t0 = f12_mul(t1, y1)
    t1 = f12_mul(t1, y0)
    t0 = f12_sqr(t0)
    return f12_mul(t0, t1)


def _full(f: Fp12) -> Fp12:
    return _hard(_easy(f))


def pairing(q: G2Point, p_at: G1Point) -> Fp12:
    """e(P, Q) with P in G1, Q in G2 (argument order: Q, P)."""
    assert g1_is_on_curve(p_at), "P not on G1"
    assert g2_is_on_curve(q), "Q not on E'"
    return _full(miller_loop(q, p_at))


def multi_pairing(pairs) -> Fp12:
    """prod e(Pi, Qi): shared final exponentiation (the batch trick)."""
    f = FP12_ONE
    for p_at, q in pairs:
        if p_at is None or q is None:
            continue
        f = f12_mul(f, miller_loop(q, p_at))
    return _full(f)


def pairing_check(pairs) -> bool:
    """True iff prod e(Pi, Qi) == 1."""
    return multi_pairing(pairs) == FP12_ONE
