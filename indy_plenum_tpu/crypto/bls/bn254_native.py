"""Loader + adapter for the native BN254 backend (native/bn254/bn254c.c).

Reference analog: crypto/bls/indy_crypto/bls_crypto_indy_crypto.py — the
reference's BLS backend is a native Rust library (ursa/AMCL); ours is a C
extension compiled on first use (gcc + CPython headers are part of the
toolchain image). Exposes the same point representation as
:mod:`indy_plenum_tpu.crypto.bls.bn254` (int tuples); conversion crosses
the boundary as fixed-width big-endian bytes, coarse-grained per call.

Importing this module raises if the extension cannot be built/loaded —
callers select the backend via :func:`available`.
"""
from __future__ import annotations

import logging
import os
from typing import Optional, Tuple

from . import bn254 as bn

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "..", "..", "native", "bn254", "bn254c.c")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_native_build")


def _build_and_load():
    from ...utils.native_build import build_native_ext

    return build_native_ext(_SRC, _BUILD_DIR, "bn254c")


_C = _build_and_load()

# ---------------------------------------------------------------------------
# conversions: oracle int tuples <-> fixed-width big-endian bytes
# ---------------------------------------------------------------------------


def _g1_bytes(pt: bn.G1Point) -> Optional[bytes]:
    if pt is None:
        return None
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def _g1_from(b: Optional[bytes]) -> bn.G1Point:
    if b is None:
        return None
    return (int.from_bytes(b[:32], "big"), int.from_bytes(b[32:], "big"))


def _g2_bytes(pt: bn.G2Point) -> Optional[bytes]:
    if pt is None:
        return None
    (x0, x1), (y0, y1) = pt
    return b"".join(v.to_bytes(32, "big") for v in (x0, x1, y0, y1))


def _g2_from(b: Optional[bytes]) -> bn.G2Point:
    if b is None:
        return None
    v = [int.from_bytes(b[i:i + 32], "big") for i in range(0, 128, 32)]
    return ((v[0], v[1]), (v[2], v[3]))


def _scalar(k: int) -> bytes:
    return (k % bn.R).to_bytes(32, "big")


# ---------------------------------------------------------------------------
# public API (mirrors bn254_fast)
# ---------------------------------------------------------------------------


def g1_mul(pt: bn.G1Point, k: int) -> bn.G1Point:
    return _g1_from(_C.g1_mul(_g1_bytes(pt), _scalar(k)))


def fp_sqrt(x: int):
    """sqrt mod P, or None if ``x`` is a non-residue (C fast path)."""
    out = _C.fp_sqrt((x % bn.P).to_bytes(32, "big"))
    return None if out is None else int.from_bytes(out, "big")


def g2_mul(pt: bn.G2Point, k: int) -> bn.G2Point:
    return _g2_from(_C.g2_mul(_g2_bytes(pt), _scalar(k)))


def g1_sum(points) -> bn.G1Point:
    return _g1_from(_C.g1_sum(
        [_g1_bytes(p) for p in points if p is not None]))


def g1_sum_checked_bytes(raws) -> bytes:
    """Sum raw 64-byte G1 encodings with canonical + on-curve validation
    done in C (raises ValueError on any invalid encoding); returns the
    64-byte aggregate (all-zeros for the identity). The aggregation hot
    path — no per-point int conversion crosses the boundary."""
    out = _C.g1_sum_checked(raws)
    return b"\x00" * 64 if out is None else out


def g2_sum(points) -> bn.G2Point:
    return _g2_from(_C.g2_sum(
        [_g2_bytes(p) for p in points if p is not None]))


def g2_in_subgroup(pt: bn.G2Point) -> bool:
    if pt is None:
        return True
    if not bn.g2_is_on_curve(pt):
        return False
    return bool(_C.g2_in_subgroup(_g2_bytes(pt)))


def multi_pairing(pairs) -> "bn.Fp12":
    raw = _C.multi_pairing(
        [(_g1_bytes(p), _g2_bytes(q)) for p, q in pairs])
    coeffs = [int.from_bytes(raw[i:i + 32], "big")
              for i in range(0, 384, 32)]
    return (((coeffs[0], coeffs[1]), (coeffs[2], coeffs[3]),
             (coeffs[4], coeffs[5])),
            ((coeffs[6], coeffs[7]), (coeffs[8], coeffs[9]),
             (coeffs[10], coeffs[11])))


def pairing(q: bn.G2Point, p_at: bn.G1Point):
    assert bn.g1_is_on_curve(p_at), "P not on G1"
    assert bn.g2_is_on_curve(q), "Q not on E'"
    return multi_pairing([(p_at, q)])


def pairing_check(pairs) -> bool:
    return bool(_C.pairing_check(
        [(_g1_bytes(p), _g2_bytes(q)) for p, q in pairs]))
