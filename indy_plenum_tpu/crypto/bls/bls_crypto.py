"""BLS signatures over BN254: sign / verify / aggregate + value objects.

Reference: crypto/bls/bls_crypto.py (`BlsCryptoSigner`, `BlsCryptoVerifier`)
and crypto/bls/bls_multi_signature.py (`MultiSignature`,
`MultiSignatureValue`); concrete backend analog of
crypto/bls/indy_crypto/bls_crypto_indy_crypto.py (ursa/AMCL BN254 in Rust —
Rust is unavailable here, so the host backend is the pure-Python
:mod:`indy_plenum_tpu.crypto.bls.bn254` pairing library).

Scheme: signatures in G1, public keys in G2 (small sigs, one G2 key per
validator), hash-to-G1 by try-and-increment over sha256 (constant-time is
NOT required: inputs are public protocol data). Proof of possession = BLS
signature over the serialized public key (rogue-key defence).
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence

from ...utils.base58 import b58decode, b58encode
from . import bn254 as bn

# backend ladder: native C (the analog of the reference's Rust backend)
# -> projective pure-Python -> both pinned against the affine oracle
try:
    from . import bn254_native as fast

    NATIVE_BACKEND = True
except Exception as _native_err:  # pragma: no cover — no compiler/headers
    import logging as _logging

    _logging.getLogger(__name__).warning(
        "native BN254 backend unavailable (%s); using pure-Python "
        "projective path", _native_err)
    from . import bn254_fast as fast  # type: ignore[no-redef]

    NATIVE_BACKEND = False

# --- point serialization (wire: base58 of fixed-width big-endian) ---------


def g1_to_bytes(pt: bn.G1Point) -> bytes:
    if pt is None:
        return b"\x00" * 64
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def g1_from_bytes(data: bytes) -> bn.G1Point:
    if len(data) != 64:
        raise ValueError("G1 point must be 64 bytes")
    if data == b"\x00" * 64:
        return None
    pt = (int.from_bytes(data[:32], "big"), int.from_bytes(data[32:], "big"))
    # canonical encodings only: a coordinate >= P would alias another point
    # mod P, giving one signature several distinct wire forms (malleability
    # breaking digest-based dedup and the b58-keyed subgroup cache)
    if pt[0] >= bn.P or pt[1] >= bn.P:
        raise ValueError("non-canonical G1 coordinate")
    if not bn.g1_is_on_curve(pt):
        raise ValueError("point not on G1")
    return pt


def g2_to_bytes(pt: bn.G2Point) -> bytes:
    if pt is None:
        return b"\x00" * 128
    (x0, x1), (y0, y1) = pt
    return b"".join(v.to_bytes(32, "big") for v in (x0, x1, y0, y1))


def g2_from_bytes(data: bytes) -> bn.G2Point:
    if len(data) != 128:
        raise ValueError("G2 point must be 128 bytes")
    if data == b"\x00" * 128:
        return None
    vals = [int.from_bytes(data[i:i + 32], "big") for i in range(0, 128, 32)]
    if any(v >= bn.P for v in vals):
        raise ValueError("non-canonical G2 coordinate")
    pt = ((vals[0], vals[1]), (vals[2], vals[3]))
    if not bn.g2_is_on_curve(pt):
        raise ValueError("point not on E'")
    return pt


# --- hash to G1 (try-and-increment) ---------------------------------------


def hash_to_g1(msg: bytes) -> bn.G1Point:
    ctr = 0
    while True:
        h = hashlib.sha256(msg + ctr.to_bytes(4, "big")).digest()
        x = int.from_bytes(h, "big") % bn.P
        rhs = (x * x * x + 3) % bn.P
        y = pow(rhs, (bn.P + 1) // 4, bn.P)
        if y * y % bn.P == rhs:
            # normalize sign deterministically
            if y > bn.P // 2:
                y = bn.P - y
            return (x, y)
        ctr += 1


# --- key generation / sign / verify / aggregate ----------------------------


class BlsKeyPair:
    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self.sk = int.from_bytes(
            hashlib.sha512(b"bls-bn254-sk" + seed).digest(), "big") % bn.R
        self.pk: bn.G2Point = fast.g2_mul(bn.G2_GEN, self.sk)

    @property
    def pk_b58(self) -> str:
        return b58encode(g2_to_bytes(self.pk))

    def pop(self) -> str:
        """Proof of possession: BLS sig over the serialized pubkey."""
        return b58encode(g1_to_bytes(
            fast.g1_mul(hash_to_g1(g2_to_bytes(self.pk)), self.sk)))


class BlsCryptoSigner:
    """Reference: BlsCryptoSigner (indy-crypto backend)."""

    def __init__(self, keypair: BlsKeyPair):
        self._kp = keypair

    @property
    def pk(self) -> str:
        return self._kp.pk_b58

    def sign(self, message: bytes) -> str:
        sig = fast.g1_mul(hash_to_g1(message), self._kp.sk)
        return b58encode(g1_to_bytes(sig))


# validator keys are static between NODE txns: memoize the expensive
# subgroup membership checks (r*Q == O is a full scalar mul)
_SUBGROUP_CACHE: Dict[str, bool] = {}


def _g2_checked(pk_b58: str) -> Optional[bn.G2Point]:
    """Decode a G2 key with a cached subgroup check; None if invalid."""
    ok = _SUBGROUP_CACHE.get(pk_b58)
    try:
        pk = g2_from_bytes(b58decode(pk_b58))
    except ValueError:
        return None
    if pk is None:
        return None
    if ok is None:
        ok = fast.g2_in_subgroup(pk)
        if len(_SUBGROUP_CACHE) > 4096:
            _SUBGROUP_CACHE.clear()
        _SUBGROUP_CACHE[pk_b58] = ok
    return pk if ok else None


class BlsCryptoVerifier:
    """Reference: BlsCryptoVerifier. Stateless pairing checks."""

    @staticmethod
    def verify_sig(signature_b58: str, message: bytes, pk_b58: str) -> bool:
        try:
            sig = g1_from_bytes(b58decode(signature_b58))
        except ValueError:
            return False
        pk = _g2_checked(pk_b58)
        if sig is None or pk is None:
            return False
        # e(H(m), pk) == e(sig, G2) <=> e(H(m), pk) * e(-sig, G2) == 1
        return fast.pairing_check([
            (hash_to_g1(message), pk),
            (bn.g1_neg(sig), bn.G2_GEN),
        ])

    @staticmethod
    def verify_pop(pop_b58: str, pk_b58: str) -> bool:
        try:
            pk_bytes = b58decode(pk_b58)
            g2_from_bytes(pk_bytes)
        except ValueError:
            return False
        return BlsCryptoVerifier.verify_sig(pop_b58, pk_bytes, pk_b58)

    @staticmethod
    def aggregate_sigs(signatures_b58: Sequence[str]) -> str:
        acc = fast.g1_sum(
            g1_from_bytes(b58decode(s)) for s in signatures_b58)
        return b58encode(g1_to_bytes(acc))

    @staticmethod
    def verify_multi_sig(signature_b58: str, message: bytes,
                         pks_b58: Sequence[str]) -> bool:
        try:
            sig = g1_from_bytes(b58decode(signature_b58))
        except ValueError:
            return False
        pts = []
        for pk in pks_b58:
            p = _g2_checked(pk)
            if p is None:
                return False
            pts.append(p)
        acc = fast.g2_sum(pts)
        if sig is None or acc is None:
            return False
        return fast.pairing_check([
            (hash_to_g1(message), acc),
            (bn.g1_neg(sig), bn.G2_GEN),
        ])


# --- multi-signature value objects ----------------------------------------


class MultiSignatureValue:
    """What the pool actually co-signs: the committed roots at a 3PC batch.

    Reference: crypto/bls/bls_multi_signature.py (`MultiSignatureValue`).
    """

    FIELDS = ("ledger_id", "state_root_hash", "pool_state_root_hash",
              "txn_root_hash", "timestamp")

    def __init__(self, ledger_id: int, state_root_hash: str,
                 pool_state_root_hash: str, txn_root_hash: str,
                 timestamp: int):
        self.ledger_id = ledger_id
        self.state_root_hash = state_root_hash
        self.pool_state_root_hash = pool_state_root_hash
        self.txn_root_hash = txn_root_hash
        self.timestamp = timestamp

    def as_dict(self) -> Dict:
        return {k: getattr(self, k) for k in self.FIELDS}

    @classmethod
    def from_dict(cls, data: Dict) -> "MultiSignatureValue":
        return cls(**{k: data[k] for k in cls.FIELDS})

    def serialize(self) -> bytes:
        from ...common.serializers.serialization import serialize_for_signing

        return serialize_for_signing(self.as_dict())

    def __eq__(self, other):
        return isinstance(other, MultiSignatureValue) \
            and self.as_dict() == other.as_dict()


class MultiSignature:
    """signature + participants + signed value (reference: MultiSignature)."""

    def __init__(self, signature: str, participants: List[str],
                 value: MultiSignatureValue):
        self.signature = signature
        self.participants = list(participants)
        self.value = value

    def as_dict(self) -> Dict:
        return {"signature": self.signature,
                "participants": self.participants,
                "value": self.value.as_dict()}

    @classmethod
    def from_dict(cls, data: Dict) -> "MultiSignature":
        return cls(data["signature"], list(data["participants"]),
                   MultiSignatureValue.from_dict(dict(data["value"])))

    def __eq__(self, other):
        return isinstance(other, MultiSignature) \
            and self.as_dict() == other.as_dict()
