"""BLS signatures over BN254: sign / verify / aggregate + value objects.

Reference: crypto/bls/bls_crypto.py (`BlsCryptoSigner`, `BlsCryptoVerifier`)
and crypto/bls/bls_multi_signature.py (`MultiSignature`,
`MultiSignatureValue`); concrete backend analog of
crypto/bls/indy_crypto/bls_crypto_indy_crypto.py (ursa/AMCL BN254 in Rust —
Rust is unavailable here, so the host backend is the pure-Python
:mod:`indy_plenum_tpu.crypto.bls.bn254` pairing library).

Scheme: signatures in G1, public keys in G2 (small sigs, one G2 key per
validator), hash-to-G1 by try-and-increment over sha256 (constant-time is
NOT required: inputs are public protocol data). Proof of possession = BLS
signature over the serialized public key (rogue-key defence).
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence

from ...utils.base58 import b58decode, b58encode
from . import bn254 as bn

# backend ladder: native C (the analog of the reference's Rust backend)
# -> projective pure-Python -> both pinned against the affine oracle
try:
    from . import bn254_native as fast

    NATIVE_BACKEND = True
except Exception as _native_err:  # pragma: no cover — no compiler/headers
    import logging as _logging

    _logging.getLogger(__name__).warning(
        "native BN254 backend unavailable (%s); using pure-Python "
        "projective path", _native_err)
    from . import bn254_fast as fast  # type: ignore[no-redef]

    NATIVE_BACKEND = False

# --- point serialization (wire: base58 of fixed-width big-endian) ---------


def g1_to_bytes(pt: bn.G1Point) -> bytes:
    if pt is None:
        return b"\x00" * 64
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def g1_from_bytes(data: bytes) -> bn.G1Point:
    if len(data) != 64:
        raise ValueError("G1 point must be 64 bytes")
    if data == b"\x00" * 64:
        return None
    pt = (int.from_bytes(data[:32], "big"), int.from_bytes(data[32:], "big"))
    # canonical encodings only: a coordinate >= P would alias another point
    # mod P, giving one signature several distinct wire forms (malleability
    # breaking digest-based dedup and the b58-keyed subgroup cache)
    if pt[0] >= bn.P or pt[1] >= bn.P:
        raise ValueError("non-canonical G1 coordinate")
    if not bn.g1_is_on_curve(pt):
        raise ValueError("point not on G1")
    return pt


def g2_to_bytes(pt: bn.G2Point) -> bytes:
    if pt is None:
        return b"\x00" * 128
    (x0, x1), (y0, y1) = pt
    return b"".join(v.to_bytes(32, "big") for v in (x0, x1, y0, y1))


def g2_from_bytes(data: bytes) -> bn.G2Point:
    if len(data) != 128:
        raise ValueError("G2 point must be 128 bytes")
    if data == b"\x00" * 128:
        return None
    vals = [int.from_bytes(data[i:i + 32], "big") for i in range(0, 128, 32)]
    if any(v >= bn.P for v in vals):
        raise ValueError("non-canonical G2 coordinate")
    pt = ((vals[0], vals[1]), (vals[2], vals[3]))
    if not bn.g2_is_on_curve(pt):
        raise ValueError("point not on E'")
    return pt


# --- hash to G1 (try-and-increment) ---------------------------------------


def hash_to_g1(msg: bytes) -> bn.G1Point:
    ctr = 0
    while True:
        h = hashlib.sha256(msg + ctr.to_bytes(4, "big")).digest()
        x = int.from_bytes(h, "big") % bn.P
        rhs = (x * x * x + 3) % bn.P
        # the modular sqrt is the whole cost of a hash-to-curve attempt;
        # the backend's fp_sqrt (C Montgomery pow) is ~30x the Python pow
        y = fast.fp_sqrt(rhs)
        if y is not None:
            # normalize sign deterministically
            if y > bn.P // 2:
                y = bn.P - y
            return (x, y)
        ctr += 1


# --- key generation / sign / verify / aggregate ----------------------------


class BlsKeyPair:
    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self.sk = int.from_bytes(
            hashlib.sha512(b"bls-bn254-sk" + seed).digest(), "big") % bn.R
        self.pk: bn.G2Point = fast.g2_mul(bn.G2_GEN, self.sk)

    @property
    def pk_b58(self) -> str:
        return b58encode(g2_to_bytes(self.pk))

    def pop(self) -> str:
        """Proof of possession: BLS sig over the serialized pubkey."""
        return b58encode(g1_to_bytes(
            fast.g1_mul(hash_to_g1(g2_to_bytes(self.pk)), self.sk)))


class BlsCryptoSigner:
    """Reference: BlsCryptoSigner (indy-crypto backend)."""

    def __init__(self, keypair: BlsKeyPair):
        self._kp = keypair

    @property
    def pk(self) -> str:
        return self._kp.pk_b58

    def sign(self, message: bytes) -> str:
        sig = fast.g1_mul(hash_to_g1(message), self._kp.sk)
        return b58encode(g1_to_bytes(sig))


class PairingCounter:
    """Process-wide pairing accounting (the state-proof plane's cost
    meter): ``checks`` counts pairing-equation evaluations (one shared
    final exponentiation each), ``pairings`` the Miller loops they
    contained. The proof plane's serve-path contract — a cache hit costs
    ZERO pairings — is asserted against this counter by
    ``scripts/check_dispatch_budget.py``'s proof gate and the ``proofs``
    bench, so every verification path in this module must route through
    :func:`_pairing_check`."""

    __slots__ = ("checks", "pairings")

    def __init__(self):
        self.checks = 0
        self.pairings = 0

    def snapshot(self) -> tuple:
        return (self.checks, self.pairings)


PAIRINGS = PairingCounter()


def _pairing_check(pairs) -> bool:
    PAIRINGS.checks += 1
    PAIRINGS.pairings += len(pairs)
    return fast.pairing_check(pairs)


# validator keys are static between NODE txns: memoize the expensive
# subgroup membership checks (r*Q == O is a full scalar mul)
_SUBGROUP_CACHE: Dict[str, bool] = {}
# ... and the aggregated pool key per participant set (decode + subgroup
# checks + 64 G2 adds otherwise repeat for every single verification)
_APK_CACHE: Dict[tuple, Optional[bn.G2Point]] = {}


def _aggregated_pk(pks_b58: Sequence[str]) -> Optional[bn.G2Point]:
    key = tuple(pks_b58)
    if key in _APK_CACHE:
        return _APK_CACHE[key]
    pts = []
    apk: Optional[bn.G2Point] = None
    for pk in pks_b58:
        p = _g2_checked(pk)
        if p is None:
            break
        pts.append(p)
    else:
        apk = fast.g2_sum(pts)
    if len(_APK_CACHE) > 1024:
        _APK_CACHE.clear()
    _APK_CACHE[key] = apk
    return apk


def _g2_checked(pk_b58: str) -> Optional[bn.G2Point]:
    """Decode a G2 key with a cached subgroup check; None if invalid."""
    ok = _SUBGROUP_CACHE.get(pk_b58)
    try:
        pk = g2_from_bytes(b58decode(pk_b58))
    except ValueError:
        return None
    if pk is None:
        return None
    if ok is None:
        ok = fast.g2_in_subgroup(pk)
        if len(_SUBGROUP_CACHE) > 4096:
            _SUBGROUP_CACHE.clear()
        _SUBGROUP_CACHE[pk_b58] = ok
    return pk if ok else None


class BlsCryptoVerifier:
    """Reference: BlsCryptoVerifier. Stateless pairing checks."""

    @staticmethod
    def verify_sig(signature_b58: str, message: bytes, pk_b58: str) -> bool:
        try:
            sig = g1_from_bytes(b58decode(signature_b58))
        except ValueError:
            return False
        pk = _g2_checked(pk_b58)
        if sig is None or pk is None:
            return False
        # e(H(m), pk) == e(sig, G2) <=> e(H(m), pk) * e(-sig, G2) == 1
        return _pairing_check([
            (hash_to_g1(message), pk),
            (bn.g1_neg(sig), bn.G2_GEN),
        ])

    @staticmethod
    def verify_pop(pop_b58: str, pk_b58: str) -> bool:
        try:
            pk_bytes = b58decode(pk_b58)
            g2_from_bytes(pk_bytes)
        except ValueError:
            return False
        return BlsCryptoVerifier.verify_sig(pop_b58, pk_bytes, pk_b58)

    @staticmethod
    def aggregate_sigs(signatures_b58: Sequence[str]) -> str:
        if NATIVE_BACKEND:
            # raw-bytes fast path: canonical + on-curve checks and the
            # sum all happen in ONE C call (no per-share int conversion)
            return b58encode(fast.g1_sum_checked_bytes(
                [b58decode(s) for s in signatures_b58]))
        acc = fast.g1_sum(
            g1_from_bytes(b58decode(s)) for s in signatures_b58)
        return b58encode(g1_to_bytes(acc))

    @staticmethod
    def verify_multi_sig(signature_b58: str, message: bytes,
                         pks_b58: Sequence[str]) -> bool:
        try:
            sig = g1_from_bytes(b58decode(signature_b58))
        except ValueError:
            return False
        acc = _aggregated_pk(pks_b58)
        if sig is None or acc is None:
            return False
        return _pairing_check([
            (hash_to_g1(message), acc),
            (bn.g1_neg(sig), bn.G2_GEN),
        ])

    @staticmethod
    def verify_multi_sig_batch(
            items: Sequence[tuple],
            scalar_fn=None) -> List[bool]:
        """Verify k multi-signatures in (near) ONE pairing computation.

        ``items``: (signature_b58, message: bytes, pks_b58) per ordered
        batch. Instead of k independent pairing checks (2 Miller loops +
        1 final exponentiation EACH), the k equations are combined with
        fresh 128-bit random scalars r_i:

            prod_g e(sum_{i in g} r_i*H(m_i), apk_g)
                 * e(-sum_i r_i*sig_i, G2) == 1

        where batches are grouped by aggregated public key apk_g (ONE
        group in the common case — the same pool signs every batch), so
        the whole batch costs |groups|+1 Miller loops and ONE shared
        final exponentiation, plus two short-scalar G1 muls per item.
        A forged item makes the combined check fail with probability
        1 - 2^-128; on failure every item is re-verified individually,
        so the returned verdicts are always exact.

        Reference analog: crypto/bls/indy_crypto/bls_crypto_indy_crypto
        .py verifies one multi-sig per call; batching across ordered 3PC
        batches is the TPU-era redesign (SURVEY §2.3 / §7 step 6).

        ``scalar_fn(idx, sig_b58, message) -> int`` overrides the scalar
        source (the state-proof plane's SEEDED replay mode —
        :func:`indy_plenum_tpu.proofs.batch_verify.verify_multi_sigs_batch`
        documents when predictable scalars are safe). Default: fresh
        ``secrets`` randomness, sound against adversarial input.
        """
        import secrets

        k = len(items)
        if k == 0:
            return []
        parsed = []  # indices of combinable items
        verdicts = [False] * k
        # apk carried IN the group entry (the bounded _APK_CACHE may be
        # cleared by a later miss in this very loop — re-reading it after
        # the loop could KeyError)
        by_apk: Dict[tuple, tuple] = {}  # pks_key -> (apk, entries)
        for idx, (sig_b58, message, pks_b58) in enumerate(items):
            try:
                sig = g1_from_bytes(b58decode(sig_b58))
            except ValueError:
                continue
            apk = _aggregated_pk(pks_b58)
            if sig is None or apk is None:
                continue
            r = (int.from_bytes(secrets.token_bytes(16), "big")
                 if scalar_fn is None
                 else scalar_fn(idx, sig_b58, message))
            if r == 0:
                r = 1  # a zero scalar would erase the item from the check
            h = hash_to_g1(message)
            by_apk.setdefault(tuple(pks_b58), (apk, []))[1].append(
                (r, h, sig))
            parsed.append(idx)
        if parsed:
            pairs = []
            sig_terms = []
            for apk, entries in by_apk.values():
                pairs.append((
                    fast.g1_sum(fast.g1_mul(h, r) for r, h, _ in entries),
                    apk))
                sig_terms.extend(
                    fast.g1_mul(sig, r) for r, _, sig in entries)
            agg_sig = fast.g1_sum(sig_terms)
            if agg_sig is not None:
                pairs.append((bn.g1_neg(agg_sig), bn.G2_GEN))
            if _pairing_check(pairs):
                for idx in parsed:
                    verdicts[idx] = True
                return verdicts
        # combined check failed: at least one forgery — find it exactly
        for idx in parsed:
            sig_b58, message, pks_b58 = items[idx]
            verdicts[idx] = BlsCryptoVerifier.verify_multi_sig(
                sig_b58, message, pks_b58)
        return verdicts

    @staticmethod
    def aggregate_and_verify_batch(
            items: Sequence[tuple]) -> List[tuple]:
        """Aggregate each item's signature shares AND batch-verify the
        aggregates: the full per-ordered-batch BLS cycle (BASELINE
        config 3), amortized across k batches.

        ``items``: (sig_shares_b58: Sequence[str], message: bytes,
        pks_b58) per ordered batch. Returns [(agg_sig_b58 | None, ok)].
        """
        aggs: List[Optional[str]] = []
        for shares, _msg, _pks in items:
            try:
                aggs.append(BlsCryptoVerifier.aggregate_sigs(shares))
            except ValueError:
                aggs.append(None)
        verdicts = BlsCryptoVerifier.verify_multi_sig_batch([
            (agg if agg is not None else "", msg, pks)
            for agg, (_s, msg, pks) in zip(aggs, items)])
        return list(zip(aggs, verdicts))


# --- multi-signature value objects ----------------------------------------


class MultiSignatureValue:
    """What the pool actually co-signs: the committed roots at a 3PC batch.

    Reference: crypto/bls/bls_multi_signature.py (`MultiSignatureValue`).
    """

    FIELDS = ("ledger_id", "state_root_hash", "pool_state_root_hash",
              "txn_root_hash", "timestamp")

    def __init__(self, ledger_id: int, state_root_hash: str,
                 pool_state_root_hash: str, txn_root_hash: str,
                 timestamp: int):
        self.ledger_id = ledger_id
        self.state_root_hash = state_root_hash
        self.pool_state_root_hash = pool_state_root_hash
        self.txn_root_hash = txn_root_hash
        self.timestamp = timestamp

    def as_dict(self) -> Dict:
        return {k: getattr(self, k) for k in self.FIELDS}

    @classmethod
    def from_dict(cls, data: Dict) -> "MultiSignatureValue":
        return cls(**{k: data[k] for k in cls.FIELDS})

    def serialize(self) -> bytes:
        from ...common.serializers.serialization import serialize_for_signing

        return serialize_for_signing(self.as_dict())

    def __eq__(self, other):
        return isinstance(other, MultiSignatureValue) \
            and self.as_dict() == other.as_dict()


class MultiSignature:
    """signature + participants + signed value (reference: MultiSignature)."""

    def __init__(self, signature: str, participants: List[str],
                 value: MultiSignatureValue):
        self.signature = signature
        self.participants = list(participants)
        self.value = value

    def as_dict(self) -> Dict:
        return {"signature": self.signature,
                "participants": self.participants,
                "value": self.value.as_dict()}

    @classmethod
    def from_dict(cls, data: Dict) -> "MultiSignature":
        return cls(data["signature"], list(data["participants"]),
                   MultiSignatureValue.from_dict(dict(data["value"])))

    def __eq__(self, other):
        return isinstance(other, MultiSignature) \
            and self.as_dict() == other.as_dict()
