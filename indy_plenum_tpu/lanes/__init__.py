"""Ordering lanes: keyspace-partitioned write path under one barrier.

Public surface:

- :class:`~indy_plenum_tpu.lanes.router.LaneRouter` /
  :func:`~indy_plenum_tpu.lanes.router.route_key` — the deterministic
  key→lane law;
- :class:`~indy_plenum_tpu.lanes.barrier.CrossLaneBarrier` — the
  cross-lane checkpoint barrier (sealed windows + fingerprint chain);
- :class:`~indy_plenum_tpu.lanes.pool.LanedPool` /
  :func:`~indy_plenum_tpu.lanes.pool.lane_meshes` — K full ordering
  lanes on one clock/recorder/barrier, each optionally on its own
  fabric-mesh slice.
"""
from .barrier import CrossLaneBarrier
from .pool import LanedPool, lane_meshes, lane_seed
from .router import LaneRouter, route_key

__all__ = ["CrossLaneBarrier", "LanedPool", "LaneRouter", "lane_meshes",
           "lane_seed", "route_key"]
