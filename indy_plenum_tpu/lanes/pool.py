"""LanedPool: K independent ordering lanes under one barrier.

The multi-lane write path (README "Ordering lanes"): the request
keyspace partitions across K LANES via the deterministic
:class:`~indy_plenum_tpu.lanes.router.LaneRouter`; each lane is a full
:class:`~indy_plenum_tpu.simulation.pool.SimPool` — n validators, its
own :class:`~indy_plenum_tpu.simulation.sim_network.SimNetwork`, its own
master-instance :class:`~indy_plenum_tpu.tpu.vote_plane.VotePlaneGroup`
(optionally on its own fabric-mesh slice, :func:`lane_meshes`) — all
lanes sharing ONE virtual clock, ONE metrics collector, ONE
flight-recorder ring (each lane tagging its events through a
:class:`~indy_plenum_tpu.observability.trace.LaneTraceView`), ONE
dispatch tick (:func:`~indy_plenum_tpu.simulation.quorum_driver
.drive_lane_ticks`), and ONE
:class:`~indy_plenum_tpu.lanes.barrier.CrossLaneBarrier` threaded into
every lane's checkpoint service.

Determinism: the router law, per-lane derived seeds, the shared virtual
clock, and the barrier's fold are all pure functions of (seed, inputs),
so a seeded laned run replays byte-identical per-lane ``ordered_hash``es
AND the byte-identical sealed-window fingerprint chain — the lanes gate
(``scripts/check_dispatch_budget.py``) asserts exactly that.
"""
from __future__ import annotations

import hashlib
from typing import List, Optional

from ..common.constants import DOMAIN_LEDGER_ID
from ..common.metrics_collector import MetricsCollector, MetricsName
from ..common.request import Request
from ..common.timer import RepeatingTimer
from ..config import Config, getConfig
from ..simulation.mock_timer import MockTimer
from ..simulation.pool import SimPool
from ..simulation.quorum_driver import drive_lane_ticks
from .barrier import CrossLaneBarrier
from .router import LaneRouter


def lane_seed(seed: int, lane: int) -> int:
    """Per-lane derived seed (network latency draws, shed tiebreaks):
    distinct per lane, pure function of the pool seed."""
    h = hashlib.sha256(b"lane-pool|%d|%d" % (seed, lane)).digest()
    return int.from_bytes(h[:4], "big")


def lane_meshes(lanes: int, shape) -> list:
    """Slice the host's device grid into ``lanes`` disjoint fabric
    meshes of ``shape`` each — lane l's vote plane compiles
    (``tpu/compile_plan.py``) and runs on devices
    ``[l*prod(shape), (l+1)*prod(shape))`` only: the lanes scale across
    the fabric instead of contending for it."""
    import jax

    from ..tpu import quorum as q

    per = 1
    for dim in shape:
        per *= dim
    devices = jax.devices()
    need = lanes * per
    if len(devices) < need:
        raise ValueError(
            f"lane_meshes needs {need} devices for {lanes} lanes of "
            f"{tuple(shape)}, host has {len(devices)}")
    return [q.make_fabric_mesh(devices[lane * per:(lane + 1) * per],
                               tuple(shape))
            for lane in range(lanes)]


def _lane_busy(lane_pool: SimPool) -> bool:
    """Deterministic busyness probe for the barrier's idle-advance law:
    a lane counts busy while it holds admitted-but-undrained, pending,
    or in-flight (pre-prepared but unordered) work, or is mid view
    change. Pure function of pool state on the virtual clock."""
    if lane_pool.admission is not None and lane_pool.admission.depth:
        return True
    if lane_pool._ingress:
        return True
    for node in lane_pool.nodes:
        if node.data.waiting_for_new_view:
            return True
        if node.requests_view.has_ready(DOMAIN_LEDGER_ID):
            return True
        last = node.data.last_ordered_3pc[1]
        ordering = node.ordering
        if any(seq > last for (_view, seq) in ordering.prePrepares):
            return True
        if any(seq > last for (_view, seq) in ordering.sent_preprepares):
            return True
    return False


class LanedPool:
    def __init__(self, lanes: int = 0, n_nodes: int = 4, seed: int = 0,
                 config: Optional[Config] = None,
                 device_quorum: bool = False,
                 real_execution: bool = False,
                 sign_requests: bool = False,
                 bls: bool = False,
                 num_instances: int = 1,
                 meshes=None,
                 host_eval: bool = False,
                 pipelined_flush: bool = True,
                 trace: bool = False,
                 trace_capacity: Optional[int] = None):
        self.config = config or getConfig(
            {"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 10})
        # the config knob is the deployed-surface default; an explicit
        # constructor arg wins (bench/gate runs sweep lane counts)
        lanes = lanes or self.config.OrderingLanes or 1
        self.n_lanes = lanes
        self.seed = seed
        self.timer = MockTimer(start_time=1_700_000_000.0)
        self.metrics = MetricsCollector()
        from ..observability.trace import (
            NULL_TRACE,
            LaneTraceView,
            TraceRecorder,
        )

        self.trace = (TraceRecorder(
            self.timer.get_current_time,
            capacity=trace_capacity or self.config.TraceRecorderCapacity)
            if trace else NULL_TRACE)
        self.router = LaneRouter(
            lanes, seed=self.config.LaneRouterSeed or seed,
            metrics=self.metrics)
        self.barrier = CrossLaneBarrier(
            lanes, chk_freq=self.config.CHK_FREQ,
            clock=self.timer.get_current_time,
            trace=self.trace, metrics=self.metrics,
            keep=self.config.LaneBarrierKeepWindows)
        if meshes is not None and len(meshes) != lanes:
            raise ValueError(
                f"meshes must carry one mesh per lane: "
                f"{len(meshes)} != {lanes}")
        self.lane_pools: List[SimPool] = [
            SimPool(n_nodes=n_nodes, seed=lane_seed(seed, lane),
                    config=self.config,
                    device_quorum=device_quorum,
                    real_execution=real_execution,
                    sign_requests=sign_requests,
                    bls=bls,
                    shadow_check=False if device_quorum else None,
                    num_instances=num_instances,
                    mesh=meshes[lane] if meshes is not None else None,
                    host_eval=host_eval,
                    pipelined_flush=pipelined_flush,
                    timer=self.timer,
                    metrics=self.metrics,
                    trace_recorder=(LaneTraceView(self.trace, lane)
                                    if trace else None),
                    drive_ticks=False,
                    barrier=self.barrier,
                    lane=lane)
            for lane in range(lanes)]
        for lane, lane_pool in enumerate(self.lane_pools):
            self.barrier.set_idle_probe(
                lane, lambda lp=lane_pool: not _lane_busy(lp))
        self.metrics.add_event(MetricsName.LANE_COUNT, lanes)

        # one tick for every lane (tick-batched mode); in per-message
        # mode the barrier still needs a deterministic re-evaluation
        # pulse for its idle-advance law
        self._tick_timer = drive_lane_ticks(
            self.timer, self.config, self.lane_pools,
            barrier=self.barrier, trace=self.trace, metrics=self.metrics)
        self.governor = getattr(self._tick_timer, "governor", None)
        self._pulse_timer = None
        if self._tick_timer is None:
            self._pulse_timer = RepeatingTimer(
                self.timer, 0.05, self._barrier_pulse, barrier=True)

    def _barrier_pulse(self) -> None:
        self.barrier.service_tick()
        for lane, lane_pool in enumerate(self.lane_pools):
            self.metrics.add_event(
                "%s.%d" % (MetricsName.LANE_ORDERED, lane),
                min(len(nd.ordered_digests) for nd in lane_pool.nodes))

    # --- traffic --------------------------------------------------------

    def submit_request(self, seq: int,
                       client_id: Optional[str] = None) -> Request:
        """Build the request, route it by its key, submit it to the
        owning lane."""
        req = self.lane_pools[0].build_request(seq)
        lane = self.router.route(req)
        self.lane_pools[lane].submit_built(req, client_id)
        return req

    def submit_to_lane(self, seq: int, lane: int) -> Request:
        """Targeted (un-routed) submission — barrier flush padding and
        tests; real client traffic goes through :meth:`submit_request`."""
        req = self.lane_pools[lane].build_request(seq)
        self.lane_pools[lane].submit_built(req)
        return req

    def run_for(self, seconds: float) -> None:
        self.timer.advance(seconds)

    # --- seal flush -----------------------------------------------------

    def seal_flush(self, seq_base: int = 10_000_000,
                   max_sim_s: float = 300.0) -> int:
        """Drive every lane to a sealed boundary: pad each lane to its
        next checkpoint boundary (single-request batches — the
        simulation stand-in for freshness empty batches) and run until
        the barrier has sealed every executed window. Returns the number
        of pad requests submitted. Deterministic: two same-seed runs pad
        identically."""
        chk = self.config.CHK_FREQ
        seq = seq_base
        spent = 0.0
        while spent < max_sim_s:
            self.run_for(0.5)
            spent += 0.5
            all_idle = True
            for lane, lane_pool in enumerate(self.lane_pools):
                if _lane_busy(lane_pool):
                    all_idle = False
                    continue
                last = max(nd.data.last_ordered_3pc[1]
                           for nd in lane_pool.nodes)
                if last % chk != 0:
                    self.submit_to_lane(seq, lane)
                    seq += 1
                    all_idle = False
            if all_idle and self.barrier.sealed_window >= max(
                    self.barrier.window_of(
                        max(nd.data.last_ordered_3pc[1]
                            for nd in lane_pool.nodes))
                    for lane_pool in self.lane_pools):
                return seq - seq_base
        raise AssertionError(
            f"seal_flush did not converge within {max_sim_s} sim-s: "
            f"{self.counters()}")

    # --- fingerprints / agreement --------------------------------------

    def honest_nodes_agree(self) -> bool:
        return all(lp.honest_nodes_agree() for lp in self.lane_pools)

    def ordered_hashes(self) -> List[str]:
        """Per-lane ordering fingerprints, lane order."""
        return [lp.ordered_hash() for lp in self.lane_pools]

    @property
    def sealed_fingerprint(self) -> str:
        """The barrier chain tip — THE cross-lane ordering fingerprint."""
        return self.barrier.seal_fingerprint

    def ordered_total(self) -> int:
        return sum(min(len(nd.ordered_digests) for nd in lp.nodes)
                   for lp in self.lane_pools)

    def ordered_per_lane(self) -> List[int]:
        return [min(len(nd.ordered_digests) for nd in lp.nodes)
                for lp in self.lane_pools]

    def counters(self) -> dict:
        return {
            "lanes": self.n_lanes,
            "ordered_per_lane": self.ordered_per_lane(),
            "router": self.router.counters(),
            "barrier": self.barrier.counters(),
        }
