"""Cross-lane checkpoint barrier: one consistent stabilized window.

Each ordering lane runs its own full 3PC pipeline, so without a join
point the lanes' stabilized checkpoint windows drift apart — state
proofs and catchup would see K mutually-inconsistent "latest" windows.
The barrier is that join point, and it enforces ONE rule:

    **no lane may commit (stabilize) a window the barrier hasn't
    sealed.**

Mechanics: a lane's :class:`~indy_plenum_tpu.server.consensus
.checkpoint_service.CheckpointService` calls :meth:`offer` the moment it
observes a local checkpoint quorum for window ``w`` (``w = seqNoEnd //
CHK_FREQ`` — lane-local window ordinals). The first offer makes the lane
*ready* at ``w``; window ``w`` **seals** once every lane is ready at
``w`` (or provably idle — see below). Until then the stabilization is
HELD: no GC, no watermark advance, no ``CheckpointStabilized`` — so the
lane's ordering stalls at its high watermark after at most
``LOG_SIZE/CHK_FREQ`` unsealed windows. That watermark stall IS the
skew bound: a fast lane can never run away from the pool's sealed
window, which is exactly what keeps the proof plane
(:mod:`~indy_plenum_tpu.proofs`) and catchup on one consistent window —
both ride ``CheckpointStabilized``, which the barrier now gates.

**Idle lanes**: a lane with no admitted, pending, or in-flight work
cannot produce checkpoints, and a strict all-lanes-ready rule would
deadlock the busy lanes against it. An idle lane is therefore vacuously
ready at every window (its per-lane digest folds as ``"idle"``). The
idleness probe is injected per lane (:meth:`set_idle_probe`) and
consulted at deterministic instants only (offers, catchup floors, and
the dispatch tick via :meth:`service_tick`) — the deployed analog is the
freshness empty batch (``StateFreshnessUpdateInterval``), which keeps an
idle lane's checkpoints flowing for real.

**Sealed-window fingerprint**: sealing window ``w`` folds the per-lane
checkpoint digests in lane order into a running chain —

    seal_fp(w) = sha256(seal_fp(w-1) | w | d_0 | d_1 | ... | d_{K-1})

where ``d_l`` is lane ``l``'s checkpoint digest for ``w`` (itself the
sha256 over the lane's ordered batch digests since the previous
boundary), ``"idle"`` for a vacuously-ready lane, and ``"catchup"`` for
a window a lane skipped by leeching. The chain tip
(:attr:`seal_fingerprint`) is THE cross-lane ordering fingerprint:
seeded runs replay it bit-for-bit, and the lanes gate compares runs on
it exactly like ``ordered_hash``.
"""
from __future__ import annotations

import hashlib
from typing import Callable, Dict, List, Optional, Tuple

GENESIS_FINGERPRINT = hashlib.sha256(b"lane-barrier-genesis").hexdigest()
IDLE_DIGEST = "idle"
CATCHUP_DIGEST = "catchup"


class CrossLaneBarrier:
    def __init__(self, lanes: int, chk_freq: int,
                 clock: Optional[Callable[[], float]] = None,
                 trace=None, metrics=None, keep: int = 0):
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1: {lanes}")
        if chk_freq < 1:
            raise ValueError(f"chk_freq must be >= 1: {chk_freq}")
        self.lanes = int(lanes)
        self.chk_freq = int(chk_freq)
        # per-window record retention: 0 = retain everything (bounded
        # sim runs; full-chain recomputation), > 0 = keep the last
        # ``keep`` windows' seal records (the chain tip is O(1) state,
        # so verification re-seeds from the oldest retained window's
        # predecessor) — a long-lived pool must not grow O(windows)
        self.keep = int(keep)
        self._clock = clock if clock is not None else (lambda: 0.0)
        from ..observability.trace import NULL_TRACE

        self._trace = trace if trace is not None else NULL_TRACE
        self._metrics = metrics
        # the barrier state proper
        self.sealed_window = 0
        self.seal_fingerprint = GENESIS_FINGERPRINT
        self.fingerprints: Dict[int, str] = {}  # window -> chain value
        # window -> the per-lane digest list the fold consumed (lane
        # order) — the cross-lane invariant recomputes the chain from it
        self.seal_digests: Dict[int, List[str]] = {}
        self.seals = 0
        self._ready: Dict[int, int] = {}  # lane -> max ready window
        # (lane, window) pairs that emitted a barrier.ready trace mark —
        # the sealed mark names them so the Perfetto export only closes
        # flow arrows that actually have a start
        self._ready_marked: set = set()
        # (lane, window) -> checkpoint digest; first reporter wins (all
        # honest nodes of a lane report the quorum-checked digest)
        self._digests: Dict[Tuple[int, int], str] = {}
        self._ready_at: Dict[int, float] = {}  # window -> first-ready ts
        # held stabilizations: (lane, window, node) -> release callback;
        # keyed so tick-mode stabilization retries can't enqueue twice
        self._held: Dict[Tuple[int, int, str], Callable[[], None]] = {}
        self._held_order: List[Tuple[int, int, str]] = []
        self._idle_probe: Dict[int, Callable[[], bool]] = {}
        self._advancing = False

    # ------------------------------------------------------------------

    def set_idle_probe(self, lane: int, probe: Callable[[], bool]) -> None:
        """``probe()`` must return True iff ``lane`` has no admitted,
        pending, or in-flight work — a deterministic function of pool
        state on the virtual clock."""
        self._idle_probe[lane] = probe

    def window_of(self, seq_no_end: int) -> int:
        return seq_no_end // self.chk_freq

    def ready_window(self, lane: int) -> int:
        return self._ready.get(lane, 0)

    # ------------------------------------------------------------------

    def offer(self, lane: int, node: str, seq_no_end: int, digest: str,
              release: Callable[[], None]) -> bool:
        """A lane node's stabilization attempt for the window ending at
        ``seq_no_end``. Returns True when the window is already sealed
        (the caller stabilizes synchronously); otherwise the release
        callback is held and invoked — in offer order — the moment the
        barrier seals the window."""
        window = self.window_of(seq_no_end)
        self._digests.setdefault((lane, window), digest)
        if self._ready.get(lane, 0) < window:
            self._ready[lane] = window
            self._ready_marked.add((lane, window))
            if self._trace.enabled:
                self._trace.record(
                    "barrier.ready", cat="lanes", key=(window,),
                    args={"lane": lane, "seq": seq_no_end, "node": node})
        self._ready_at.setdefault(window, self._clock())
        self._advance()
        if window <= self.sealed_window:
            # late offer for an already-sealed window (e.g. a node whose
            # quorum observation lagged the seal): nothing to hold, and
            # the fold already consumed (or idled) this lane's slot
            self._digests.pop((lane, window), None)
            return True
        hkey = (lane, window, node)
        if hkey not in self._held:
            self._held[hkey] = release
            self._held_order.append(hkey)
        return False

    def lane_caught_up(self, lane: int, seq_no_end: int) -> None:
        """Catchup moved the lane's stable floor past windows it never
        locally stabilized: the leeched state is pool-verified, so the
        lane is vacuously ready up to that floor."""
        window = self.window_of(seq_no_end)
        if self._ready.get(lane, 0) >= window:
            return
        # every window the jump skips folds as "catchup" (the lane never
        # produced a local digest for it — the leeched state stands in)
        for skipped in range(self._ready.get(lane, 0) + 1, window + 1):
            if skipped > self.sealed_window:
                self._digests.setdefault((lane, skipped), CATCHUP_DIGEST)
        self._ready[lane] = window
        self._ready_marked.add((lane, window))
        self._ready_at.setdefault(window, self._clock())
        if self._trace.enabled:
            # the mark's seq is the WINDOW BOUNDARY, not the raw caught-
            # up pp_seq_no: a mid-window floor (seq 7, CHK_FREQ 2) covers
            # only window 3 (boundary 6), and the causal plane joins a
            # batch's barrier hop on "ready seq >= batch seq" — a raw 7
            # would wrongly claim window 3 covers the seq-7 batch
            self._trace.record(
                "barrier.ready", cat="lanes", key=(window,),
                args={"lane": lane, "seq": window * self.chk_freq,
                      "via": "catchup"})
        self._advance()

    def service_tick(self) -> None:
        """The dispatch tick's barrier pulse: re-evaluate the seal
        condition so a lane that went IDLE since the last offer (its
        probe flips with no new checkpoint to trigger one) unblocks the
        held lanes at a deterministic instant."""
        self._advance()

    # ------------------------------------------------------------------

    def _lane_ready_or_idle(self, lane: int, window: int) -> bool:
        if self._ready.get(lane, 0) >= window:
            return True
        probe = self._idle_probe.get(lane)
        return probe is not None and probe()

    def _advance(self) -> None:
        if self._advancing:
            return  # releases can re-enter through stabilization
        self._advancing = True
        try:
            while self._held or self._seal_next_possible():
                target = self.sealed_window + 1
                if not all(self._lane_ready_or_idle(lane, target)
                           for lane in range(self.lanes)):
                    break
                self._seal(target)
                self._release_upto(self.sealed_window)
        finally:
            self._advancing = False

    def _seal_next_possible(self) -> bool:
        """Only seal past the held queue when some lane actually reached
        the next window — vacuous idle-only seals (every lane idle, no
        work anywhere) would otherwise spin the window ordinal forever."""
        target = self.sealed_window + 1
        return any(self._ready.get(lane, 0) >= target
                   for lane in range(self.lanes))

    def _seal(self, window: int) -> None:
        digests = [self._digests.pop((lane, window), IDLE_DIGEST)
                   for lane in range(self.lanes)]
        fold = hashlib.sha256(
            ("%s|%d|%s" % (self.seal_fingerprint, window,
                           "|".join(digests))).encode()).hexdigest()
        self.sealed_window = window
        self.seal_fingerprint = fold
        self.fingerprints[window] = fold
        self.seal_digests[window] = digests
        self.seals += 1
        if self.keep > 0:
            floor = window - self.keep
            for old in [w for w in self.seal_digests if w <= floor]:
                del self.seal_digests[old]
                self._ready_at.pop(old, None)
            # keep the fingerprint ONE window below the digest floor:
            # it seeds the retained-chain recomputation
            for old in [w for w in self.fingerprints if w < floor]:
                del self.fingerprints[old]
            self._ready_marked = {
                key for key in self._ready_marked if key[1] > floor}
            self._digests = {key: d for key, d in self._digests.items()
                             if key[1] > floor}
        now = self._clock()
        lag = now - self._ready_at.get(window, now)
        if self._metrics is not None:
            from ..common.metrics_collector import MetricsName

            self._metrics.add_event(MetricsName.LANE_SEALED_WINDOW, window)
            self._metrics.add_event(MetricsName.LANE_BARRIER_SEAL_LAG, lag)
        if self._trace.enabled:
            ready_lanes = sorted(
                lane for lane in range(self.lanes)
                if (lane, window) in self._ready_marked)
            self._trace.record(
                "barrier.sealed", cat="lanes", key=(window,),
                args={"fingerprint": fold, "lanes": self.lanes,
                      "ready_lanes": ready_lanes,
                      "lag": round(lag, 9)})

    def _release_upto(self, window: int) -> None:
        due = [k for k in self._held_order if k[1] <= window]
        self._held_order = [k for k in self._held_order if k[1] > window]
        for key in due:
            release = self._held.pop(key)
            release()

    # ------------------------------------------------------------------

    def sized_resources(self, prefix: str = "barrier."):
        """Resource-ledger registration (observability.telemetry): the
        sealed-window records (bounded by ``keep``; keep=0 retains
        everything by design — the leak law still watches it) and the
        held-open in-flight window state."""
        from ..observability.telemetry import SizedResource

        bound = self.keep if self.keep > 0 else None
        return (
            SizedResource(prefix + "seal_digests",
                          lambda: len(self.seal_digests),
                          bound=bound, entry_bytes=256),
            SizedResource(prefix + "fingerprints",
                          lambda: len(self.fingerprints),
                          bound=bound, entry_bytes=128),
            SizedResource(prefix + "held", lambda: len(self._held),
                          bound=None, entry_bytes=128),
        )

    def counters(self) -> dict:
        return {
            "lanes": self.lanes,
            "sealed_window": self.sealed_window,
            "seals": self.seals,
            "seal_fingerprint": self.seal_fingerprint,
            "ready_window_per_lane": [self._ready.get(lane, 0)
                                      for lane in range(self.lanes)],
            "held": len(self._held),
        }
