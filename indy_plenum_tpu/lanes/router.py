"""Deterministic key→lane router for the multi-lane write path.

Mir-BFT (Stathakopoulou et al.) scales BFT ordering by partitioning the
request space across concurrent ordering instances; RBFT (Aublin et al.,
ICDCS 2013 — the source paper) already runs f+1 protocol instances in
parallel but orders every request on all of them. The lane router is the
partition law in between: every request maps to exactly ONE ordering
lane, decided by a pure function of its routing key and a seed —

    lane = sha256(b"lane|<seed>|<routing key>")[:8]  mod  K

so (a) every honest node computes the identical assignment with zero
coordination, (b) a seeded run replays the byte-identical lane split,
and (c) requests touching the same state key always land in the same
lane (no cross-lane write conflicts by construction).

The **routing key** is the request's state key when it has one — the
operation's ``dest`` field (NYM target, the plenum state-trie key) — and
the ``identifier|reqId`` pair otherwise, so keyless requests still
spread uniformly instead of pooling in one lane.
"""
from __future__ import annotations

import hashlib
from typing import Any, List, Optional

from ..common.constants import TARGET_NYM
from ..common.metrics_collector import MetricsName


def route_key(req: Any) -> str:
    """The request's partition key (see module docstring)."""
    operation = getattr(req, "operation", None) or {}
    dest = operation.get(TARGET_NYM) if isinstance(operation, dict) else None
    if dest:
        return str(dest)
    return "%s|%s" % (getattr(req, "identifier", ""),
                      getattr(req, "reqId", ""))


class LaneRouter:
    """Stateless routing law + per-lane assignment accounting.

    ``distribution`` (and the ``lanes.routed.<lane>`` metrics) is the
    observability surface: a skewed split is a capacity problem the
    Monitor's lanes block makes visible.
    """

    def __init__(self, lanes: int, seed: int = 0, metrics=None):
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1: {lanes}")
        self.lanes = int(lanes)
        self.seed = int(seed)
        self._metrics = metrics
        self.routed_total = 0
        self.distribution: List[int] = [0] * self.lanes

    def lane_of(self, key: str) -> int:
        """Pure routing law — no state, usable by clients and tests."""
        h = hashlib.sha256(b"lane|%d|%s" % (self.seed, key.encode()))
        return int.from_bytes(h.digest()[:8], "big") % self.lanes

    def route(self, req: Any) -> int:
        """Assign ``req`` to its lane and account for it."""
        lane = self.lane_of(route_key(req))
        self.routed_total += 1
        self.distribution[lane] += 1
        if self._metrics is not None:
            self._metrics.add_event(
                "%s.%d" % (MetricsName.LANE_ROUTED, lane))
        return lane

    def counters(self) -> dict:
        return {"lanes": self.lanes,
                "routed": self.routed_total,
                "distribution": list(self.distribution)}
