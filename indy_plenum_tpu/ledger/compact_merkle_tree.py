"""Append-only compact Merkle tree: O(log n) state, O(log n) append.

Reference: ledger/compact_merkle_tree.py. Keeps only the *frontier* (root
hashes of the maximal complete subtrees, one per set bit of the size);
full leaf/internal hashes go to a :class:`HashStore` so audit paths and
consistency proofs can be served.

Internal nodes are addressed by (level, offset): the complete subtree of
2^level leaves starting at leaf ``offset`` (offset aligned to 2^level).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from .hash_stores import HashStore, MemoryHashStore
from .tree_hasher import TreeHasher, _largest_power_of_two_smaller_than


class CompactMerkleTree:
    def __init__(self, hasher: Optional[TreeHasher] = None,
                 hash_store: Optional[HashStore] = None):
        self.hasher = hasher or TreeHasher()
        self.hash_store = hash_store or MemoryHashStore()
        self._size = 0
        self._frontier: List[bytes] = []  # index i = subtree of 2^i leaves
        self._load()

    # --- persistence ------------------------------------------------------

    def _load(self) -> None:
        n = self.hash_store.leaf_count
        self._size = n
        # frontier: index = level, value = hash of the complete subtree of
        # 2^level leaves at that position of the size's binary decomposition
        frontier: List[Optional[bytes]] = [None] * n.bit_length()
        for level in range(n.bit_length()):
            if (n >> level) & 1:
                offset = (n >> (level + 1)) << (level + 1)
                frontier[level] = self._stored_hash(level, offset)
        self._frontier = frontier  # type: ignore[assignment]

    def _stored_hash(self, level: int, offset: int) -> bytes:
        if level == 0:
            return self.hash_store.read_leaf(offset)
        return self.hash_store.read_node(level, offset)

    # --- append -----------------------------------------------------------

    def reset(self) -> None:
        """Forget all leaves (caller resets the hash store; catchup resync)."""
        self._size = 0
        self._frontier = []

    @property
    def tree_size(self) -> int:
        return self._size

    def __len__(self) -> int:
        return self._size

    @property
    def root_hash(self) -> bytes:
        # fold the frontier (O(log n), no store reads)
        return self.root_with_extra_leaves(())

    def append(self, leaf_data: bytes) -> bytes:
        """Append one leaf; persists hashes; returns the leaf hash."""
        leaf_hash = self.hasher.hash_leaf(leaf_data)
        index = self._size
        self.hash_store.write_leaf(index, leaf_hash)
        h = leaf_hash
        level = 0
        # merge complete subtrees upward wherever the size bit is set
        while level < len(self._frontier) and self._frontier[level] is not None:
            h = self.hasher.hash_children(self._frontier[level], h)
            self._frontier[level] = None
            level += 1
            offset = ((index + 1) - (1 << level))
            self.hash_store.write_node(level, offset, h)
        if level == len(self._frontier):
            self._frontier.append(None)
        self._frontier[level] = h
        self._size += 1
        self.hash_store.leaf_count = self._size
        return leaf_hash

    def extend(self, leaves: Sequence[bytes]) -> None:
        for leaf in leaves:
            self.append(leaf)

    # --- roots / proofs ---------------------------------------------------

    def merkle_tree_hash(self, lo: int, hi: int) -> bytes:
        """MTH over leaves [lo, hi); O(log n) via stored subtree hashes."""
        if hi <= lo:
            return self.hasher.hash_empty()
        size = hi - lo
        if size == 1:
            return self.hash_store.read_leaf(lo)
        if lo % size == 0 and size & (size - 1) == 0:
            # complete aligned subtree — stored at append time
            level = size.bit_length() - 1
            try:
                return self.hash_store.read_node(level, lo)
            except KeyError:
                pass  # partially-built region; recurse
        k = _largest_power_of_two_smaller_than(size)
        return self.hasher.hash_children(
            self.merkle_tree_hash(lo, lo + k),
            self.merkle_tree_hash(lo + k, hi))

    def root_hash_at(self, tree_size: int) -> bytes:
        """Root as of historical size ``tree_size`` (<= current size)."""
        if tree_size > self._size:
            raise ValueError(f"size {tree_size} > {self._size}")
        if tree_size == 0:
            return self.hasher.hash_empty()
        return self.merkle_tree_hash(0, tree_size)

    def audit_path(self, index: int, tree_size: Optional[int] = None
                   ) -> List[bytes]:
        """RFC 6962 PATH(index, D[tree_size]), leaf-to-root order."""
        n = self._size if tree_size is None else tree_size
        if index >= n:
            raise ValueError(f"index {index} >= size {n}")

        def path(m: int, lo: int, hi: int) -> List[bytes]:
            if hi - lo <= 1:
                return []
            k = _largest_power_of_two_smaller_than(hi - lo)
            if m < lo + k:
                return path(m, lo, lo + k) + [self.merkle_tree_hash(lo + k, hi)]
            return path(m, lo + k, hi) + [self.merkle_tree_hash(lo, lo + k)]

        return path(index, 0, n)

    def consistency_proof(self, old_size: int, new_size: Optional[int] = None
                          ) -> List[bytes]:
        """RFC 6962 PROOF(old_size, D[new_size])."""
        n = self._size if new_size is None else new_size
        if old_size > n:
            raise ValueError(f"{old_size} > {n}")
        if old_size == 0 or old_size == n:
            return []

        def subproof(m: int, lo: int, hi: int, b: bool) -> List[bytes]:
            if m == hi - lo:
                # SUBPROOF(m, D[m], b): empty if D[0:m] is the known old
                # tree itself (b), else the one subtree hash — for ANY
                # width, not just leaves (RFC 6962 §2.1.2)
                return [] if b else [self.merkle_tree_hash(lo, hi)]
            k = _largest_power_of_two_smaller_than(hi - lo)
            if m <= k:
                return (subproof(m, lo, lo + k, b)
                        + [self.merkle_tree_hash(lo + k, hi)])
            return (subproof(m - k, lo + k, hi, False)
                    + [self.merkle_tree_hash(lo, lo + k)])

        return subproof(old_size, 0, n, True)

    # --- bulk/clone helpers (uncommitted-root computation) ----------------

    def frontier_snapshot(self) -> tuple:
        return (self._size, tuple(self._frontier))

    def root_with_extra_leaves(self, extra_leaf_data: Sequence[bytes]) -> bytes:
        """Root hash if ``extra_leaf_data`` were appended — WITHOUT mutating
        the tree or the hash store. O(k log n). This is how the uncommitted
        txn root for a speculatively-applied 3PC batch is computed."""
        frontier: List[Optional[bytes]] = list(self._frontier)
        size = self._size
        for data in extra_leaf_data:
            h = self.hasher.hash_leaf(data)
            level = 0
            while level < len(frontier) and frontier[level] is not None:
                h = self.hasher.hash_children(frontier[level], h)
                frontier[level] = None
                level += 1
            if level == len(frontier):
                frontier.append(None)
            frontier[level] = h
            size += 1
        if size == 0:
            return self.hasher.hash_empty()
        root: Optional[bytes] = None
        for h in frontier:  # little-endian: combine towards the top
            if h is None:
                continue
            root = h if root is None else self.hasher.hash_children(h, root)
        return root  # type: ignore[return-value]
