"""Genesis transaction builders and bootstrap loading.

Reference: ledger/genesis_txn/ (`GenesisTxnInitiatorFromFile`) and the
pool/domain genesis file format. Genesis txns are pre-consensus committed
facts: the initial trustee/steward NYMs (domain) and the validator NODE
txns (pool). They are applied directly to the committed ledger + state at
node init — no 3PC, no audit txn.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..common.constants import (
    ALIAS,
    BLS_KEY,
    BLS_KEY_PROOF,
    CLIENT_IP,
    CLIENT_PORT,
    CURRENT_TXN_VERSION,
    NODE,
    NODE_IP,
    NODE_PORT,
    NYM,
    ROLE,
    SERVICES,
    TARGET_NYM,
    TXN_METADATA,
    TXN_PAYLOAD,
    TXN_PAYLOAD_DATA,
    TXN_PAYLOAD_METADATA,
    TXN_PAYLOAD_METADATA_FROM,
    TXN_SIGNATURE,
    TXN_TYPE,
    TXN_VERSION,
    VALIDATOR,
    VERKEY,
)


def _txn(typ: str, data: Dict[str, Any],
         frm: Optional[str] = None) -> Dict[str, Any]:
    return {
        TXN_VERSION: CURRENT_TXN_VERSION,
        TXN_PAYLOAD: {
            TXN_TYPE: typ,
            TXN_PAYLOAD_DATA: data,
            TXN_PAYLOAD_METADATA: (
                {TXN_PAYLOAD_METADATA_FROM: frm} if frm else {}),
        },
        TXN_METADATA: {},
        TXN_SIGNATURE: {},
    }


def genesis_nym_txn(did: str, verkey: Optional[str] = None,
                    role: Optional[str] = None,
                    frm: Optional[str] = None) -> Dict[str, Any]:
    data: Dict[str, Any] = {TARGET_NYM: did}
    if verkey is not None:
        data[VERKEY] = verkey
    if role is not None:
        data[ROLE] = role
    return _txn(NYM, data, frm)


def genesis_node_txn(node_nym: str, alias: str, steward_did: str,
                     node_ip: str = "127.0.0.1", node_port: int = 9701,
                     client_ip: str = "127.0.0.1", client_port: int = 9702,
                     blskey: Optional[str] = None,
                     blskey_pop: Optional[str] = None,
                     transport_verkey: Optional[str] = None
                     ) -> Dict[str, Any]:
    from ..common.constants import TRANSPORT_VERKEY

    data = {
        TARGET_NYM: node_nym,
        "data": {
            ALIAS: alias,
            NODE_IP: node_ip,
            NODE_PORT: node_port,
            CLIENT_IP: client_ip,
            CLIENT_PORT: client_port,
            SERVICES: [VALIDATOR],
            **({BLS_KEY: blskey} if blskey else {}),
            **({BLS_KEY_PROOF: blskey_pop} if blskey_pop else {}),
            **({TRANSPORT_VERKEY: transport_verkey}
               if transport_verkey else {}),
        },
    }
    return _txn(NODE, data, frm=steward_did)


def load_genesis_file(path: str) -> List[Dict[str, Any]]:
    """One JSON txn per line (the reference's genesis file format)."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def dump_genesis_file(path: str, txns: List[Dict[str, Any]]) -> None:
    with open(path, "w") as fh:
        for txn in txns:
            fh.write(json.dumps(txn, sort_keys=True) + "\n")
