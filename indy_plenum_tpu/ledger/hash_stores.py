"""Hash stores: leaf + internal node hashes addressed by (level, offset).

Reference: ledger/hash_stores/* (HashStore, LevelDbHashStore, FileHashStore).
The reference addresses internal nodes by a sequential creation index with
bit-twiddling recovery; here nodes are addressed directly by their subtree
coordinates — level ``l`` (subtree of 2^l leaves) and leaf offset — which
makes audit-path assembly O(log n) KV gets with no index math.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional

from ..storage.kv_store import KeyValueStorage, KeyValueStorageInMemory


class HashStore(ABC):
    @abstractmethod
    def write_leaf(self, index: int, leaf_hash: bytes) -> None:
        ...

    @abstractmethod
    def read_leaf(self, index: int) -> bytes:
        ...

    @abstractmethod
    def write_node(self, level: int, offset: int, node_hash: bytes) -> None:
        ...

    @abstractmethod
    def read_node(self, level: int, offset: int) -> bytes:
        ...

    @property
    @abstractmethod
    def leaf_count(self) -> int:
        ...

    @leaf_count.setter
    @abstractmethod
    def leaf_count(self, count: int) -> None:
        ...

    def reset(self) -> None:
        ...


class MemoryHashStore(HashStore):
    def __init__(self):
        self._leaves: dict[int, bytes] = {}
        self._nodes: dict[tuple[int, int], bytes] = {}
        self._count = 0

    def write_leaf(self, index, leaf_hash):
        self._leaves[index] = leaf_hash

    def read_leaf(self, index):
        return self._leaves[index]

    def write_node(self, level, offset, node_hash):
        self._nodes[(level, offset)] = node_hash

    def read_node(self, level, offset):
        return self._nodes[(level, offset)]

    @property
    def leaf_count(self):
        return self._count

    @leaf_count.setter
    def leaf_count(self, count):
        self._count = count

    def reset(self):
        self._leaves.clear()
        self._nodes.clear()
        self._count = 0


class KvHashStore(HashStore):
    """Durable hash store over any KeyValueStorage backend."""

    def __init__(self, kv: Optional[KeyValueStorage] = None):
        self._kv = kv if kv is not None else KeyValueStorageInMemory()

    @staticmethod
    def _leaf_key(index: int) -> bytes:
        return b"L" + index.to_bytes(8, "big")

    @staticmethod
    def _node_key(level: int, offset: int) -> bytes:
        return b"N" + level.to_bytes(2, "big") + offset.to_bytes(8, "big")

    def write_leaf(self, index, leaf_hash):
        self._kv.put(self._leaf_key(index), leaf_hash)

    def read_leaf(self, index):
        try:
            return self._kv.get(self._leaf_key(index))
        except KeyError:
            raise KeyError(f"leaf {index}") from None

    def write_node(self, level, offset, node_hash):
        self._kv.put(self._node_key(level, offset), node_hash)

    def read_node(self, level, offset):
        try:
            return self._kv.get(self._node_key(level, offset))
        except KeyError:
            raise KeyError(f"node ({level},{offset})") from None

    @property
    def leaf_count(self):
        try:
            return int(self._kv.get(b"C"))
        except KeyError:
            return 0

    @leaf_count.setter
    def leaf_count(self, count):
        self._kv.put(b"C", str(count))

    def reset(self):
        self._kv.drop()
