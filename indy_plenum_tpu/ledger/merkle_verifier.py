"""Audit-path and consistency-proof verification (host scalar path).

Reference: ledger/merkle_verifier.py (`MerkleVerifier`, `STH` in
ledger/util.py). The bulk path — verifying thousands of catchup txns at
once — is the batched device kernel in
:mod:`indy_plenum_tpu.tpu.merkle` (BASELINE.md config 5); this host
verifier is the scalar oracle and the client-side implementation.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

from .tree_hasher import TreeHasher, _largest_power_of_two_smaller_than


class STH(NamedTuple):
    """Signed tree head (size + root)."""

    tree_size: int
    sha256_root_hash: bytes


class MerkleVerifier:
    def __init__(self, hasher: Optional[TreeHasher] = None):
        self.hasher = hasher or TreeHasher()

    def root_from_audit_path(self, leaf_hash: bytes, index: int,
                             audit_path: Sequence[bytes],
                             tree_size: int) -> bytes:
        """Fold a leaf-to-root audit path into the implied root hash."""
        fn, fsn = index, tree_size - 1
        r = leaf_hash
        for sibling in audit_path:
            if fsn == 0:
                raise ValueError("audit path longer than expected")
            if fn % 2 or fn == fsn:
                r = self.hasher.hash_children(sibling, r)
                while fn % 2 == 0 and fn != 0:
                    fn >>= 1
                    fsn >>= 1
            else:
                r = self.hasher.hash_children(r, sibling)
            fn >>= 1
            fsn >>= 1
        if fsn != 0:
            raise ValueError("audit path shorter than expected")
        return r

    def verify_leaf_inclusion(self, leaf_data: bytes, index: int,
                              audit_path: Sequence[bytes], sth: STH) -> bool:
        try:
            root = self.root_from_audit_path(
                self.hasher.hash_leaf(leaf_data), index, audit_path,
                sth.tree_size)
        except ValueError:
            return False
        return root == sth.sha256_root_hash

    def verify_consistency(self, old_size: int, new_size: int,
                           old_root: bytes, new_root: bytes,
                           proof: Sequence[bytes]) -> bool:
        """RFC 6962 consistency-proof check between two tree heads."""
        if old_size > new_size:
            return False
        if old_size == new_size:
            return old_root == new_root and not proof
        if old_size == 0:
            return not proof
        node, last_node = old_size - 1, new_size - 1
        while node % 2:
            node >>= 1
            last_node >>= 1
        proof = list(proof)
        if node:
            if not proof:
                return False
            new_hash = old_hash = proof.pop(0)
        else:
            new_hash = old_hash = old_root
        while node:
            if node % 2:
                if not proof:
                    return False
                nxt = proof.pop(0)
                old_hash = self.hasher.hash_children(nxt, old_hash)
                new_hash = self.hasher.hash_children(nxt, new_hash)
            elif node < last_node:
                if not proof:
                    return False
                new_hash = self.hasher.hash_children(
                    new_hash, proof.pop(0))
            node >>= 1
            last_node >>= 1
        if old_hash != old_root:
            return False
        while last_node:
            if not proof:
                return False
            new_hash = self.hasher.hash_children(new_hash, proof.pop(0))
            last_node >>= 1
        return new_hash == new_root and not proof
