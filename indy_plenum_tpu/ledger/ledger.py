"""Append-only transaction ledger with committed/uncommitted staging.

Reference: ledger/ledger.py (`Ledger`): seqNo-addressed txn log (1-based),
compact Merkle tree for roots/proofs, and a two-phase append — speculative
``append_txns`` during 3PC dynamic validation, then ``commit_txns`` when the
batch orders or ``discard_txns`` on revert (view change). The committed and
uncommitted root hashes are both observable; PRE-PREPARE carries the
uncommitted root every replica must reproduce.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..common.serializers.serialization import (
    ledger_txn_serializer,
)
from ..common.txn_util import append_txn_metadata, get_seq_no
from ..storage.kv_store import KeyValueStorage, KeyValueStorageInMemory
from .compact_merkle_tree import CompactMerkleTree


class Ledger:
    def __init__(self,
                 tree: Optional[CompactMerkleTree] = None,
                 txn_store: Optional[KeyValueStorage] = None,
                 serializer=ledger_txn_serializer):
        # NOT `tree or ...`: an empty CompactMerkleTree is falsy (__len__)
        self.tree = tree if tree is not None else CompactMerkleTree()
        self.txn_store = txn_store if txn_store is not None \
            else KeyValueStorageInMemory()
        self.serializer = serializer
        self._uncommitted: List[Dict[str, Any]] = []
        self.seq_no = self.tree.tree_size  # committed height (1-based last)

    # --- committed accessors ---------------------------------------------

    @property
    def size(self) -> int:
        return self.seq_no

    @property
    def root_hash(self) -> bytes:
        return self.tree.root_hash

    @property
    def uncommitted_size(self) -> int:
        return self.seq_no + len(self._uncommitted)

    @property
    def uncommitted_root_hash(self) -> bytes:
        return self.tree.root_with_extra_leaves(
            [self.serializer.dumps(t) for t in self._uncommitted])

    @property
    def uncommitted_txns(self) -> List[Dict[str, Any]]:
        return list(self._uncommitted)

    @staticmethod
    def _key(seq_no: int) -> bytes:
        return seq_no.to_bytes(8, "big")

    def get_by_seq_no(self, seq_no: int) -> Dict[str, Any]:
        if not 1 <= seq_no <= self.seq_no:
            raise KeyError(seq_no)
        return self.serializer.loads(self.txn_store.get(self._key(seq_no)))

    def get_serialized(self, seq_no: int) -> bytes:
        """Committed txn's STORED bytes — the exact leaf the Merkle tree
        hashed (audit proofs are over these, not a re-serialization)."""
        if not 1 <= seq_no <= self.seq_no:
            raise KeyError(seq_no)
        return self.txn_store.get(self._key(seq_no))

    def get_by_seq_no_uncommitted(self, seq_no: int) -> Dict[str, Any]:
        if seq_no <= self.seq_no:
            return self.get_by_seq_no(seq_no)
        idx = seq_no - self.seq_no - 1
        if idx >= len(self._uncommitted):
            raise KeyError(seq_no)
        return self._uncommitted[idx]

    def get_all_txn(self, frm: int = 1, to: Optional[int] = None):
        to = self.seq_no if to is None else min(to, self.seq_no)
        for seq in range(max(1, frm), to + 1):
            yield seq, self.get_by_seq_no(seq)

    # --- two-phase append -------------------------------------------------

    def append_txns(self, txns: Iterable[Dict[str, Any]]
                    ) -> Tuple[int, int, List[Dict[str, Any]]]:
        """Stage txns (uncommitted); assigns provisional seqNos; returns
        (start_seq_no, end_seq_no, txns)."""
        txns = list(txns)
        start = self.uncommitted_size + 1
        for i, txn in enumerate(txns):
            append_txn_metadata(txn, seq_no=start + i)
        self._uncommitted.extend(txns)
        return start, self.uncommitted_size, txns

    def commit_txns(self, count: int) -> Tuple[Tuple[int, int],
                                               List[Dict[str, Any]]]:
        """Move the first ``count`` staged txns into the committed log."""
        if count > len(self._uncommitted):
            raise ValueError(
                f"commit {count} > staged {len(self._uncommitted)}")
        committed = self._uncommitted[:count]
        self._uncommitted = self._uncommitted[count:]
        start = self.seq_no + 1
        batch = []
        for txn in committed:
            self.seq_no += 1
            data = self.serializer.dumps(txn)
            batch.append((self._key(self.seq_no), data))
            self.tree.append(data)
        self.txn_store.do_batch(batch)
        return (start, self.seq_no), committed

    def discard_txns(self, count: int) -> None:
        """Drop the LAST ``count`` staged txns (revert on view change)."""
        if count > len(self._uncommitted):
            raise ValueError(
                f"discard {count} > staged {len(self._uncommitted)}")
        if count:
            self._uncommitted = self._uncommitted[:-count]

    def add(self, txn: Dict[str, Any]) -> Dict[str, Any]:
        """Directly append a committed txn (catchup path: already ordered)."""
        assert not self._uncommitted, "add() while 3PC txns are staged"
        if get_seq_no(txn) is None:
            append_txn_metadata(txn, seq_no=self.seq_no + 1)
        data = self.serializer.dumps(txn)
        self.seq_no += 1
        self.txn_store.put(self._key(self.seq_no), data)
        self.tree.append(data)
        return txn

    def recover_tree(self) -> int:
        """Rebuild the Merkle tree from the committed txn log when the
        hash store is missing or behind it (crash recovery: the ledger
        LOG is the truth — a lost/stale hash store must never strand a
        node with an inconsistent root). Returns the number of leaves
        replayed."""
        log_size = self.txn_store.size
        if self.tree.tree_size > log_size:
            # tree AHEAD of the log (crash between the tree persist and
            # the log append): the LOG is still the truth — a root
            # committing to a leaf the log doesn't contain would poison
            # every proof served. Rebuild the tree from scratch — hash
            # store FIRST (CompactMerkleTree.reset leaves persistence to
            # the caller): a surviving leaf_count key would reload the
            # stale oversized tree on every restart, and orphaned
            # leaf/node entries would linger in durable storage.
            if self.tree.hash_store is not None:
                self.tree.hash_store.reset()
            self.tree.reset()
        behind = log_size - self.tree.tree_size
        if behind <= 0:
            self.seq_no = self.tree.tree_size
            return 0
        for seq in range(self.tree.tree_size + 1, log_size + 1):
            self.tree.append(self.txn_store.get(self._key(seq)))
        self.seq_no = self.tree.tree_size
        return behind

    def reset_to(self, size: int) -> None:
        """Truncate the committed log to ``size`` txns (diverged-node
        resync: everything past — or, for ``size=0``, the whole log — is
        re-fetched through catchup). The compact tree has no un-append, so
        the frontier is rebuilt by replaying the surviving txns; stored
        txns past ``size`` are deleted."""
        assert not self._uncommitted, "reset_to() while 3PC txns are staged"
        if size >= self.seq_no:
            return
        keep = [self.get_by_seq_no(s) for s in range(1, size + 1)]
        # descending: append-only stores (ChunkedFileStore) only support
        # tail removal, and KV stores don't care about the order
        for s in range(self.seq_no, size, -1):
            self.txn_store.remove(self._key(s))
        if self.tree.hash_store is not None:
            self.tree.hash_store.reset()
        self.tree.reset()
        self.seq_no = 0
        for txn in keep:
            self.seq_no += 1
            self.tree.append(self.serializer.dumps(txn))

    # --- proofs (serving catchup / state proofs) -------------------------

    def audit_path(self, seq_no: int, tree_size: Optional[int] = None):
        return self.tree.audit_path(seq_no - 1, tree_size)

    def consistency_proof(self, old_size: int,
                          new_size: Optional[int] = None):
        return self.tree.consistency_proof(old_size, new_size)

    def root_hash_at(self, tree_size: int) -> bytes:
        return self.tree.root_hash_at(tree_size)
