"""RFC 6962 tree hashing (domain-separated SHA-256).

Reference: ledger/tree_hasher.py. leaf = H(0x00 || data),
node = H(0x01 || left || right); the empty tree hashes to H(b"").
"""
from __future__ import annotations

import hashlib

LEAF_PREFIX = b"\x00"
NODE_PREFIX = b"\x01"


class TreeHasher:
    def __init__(self, hashfunc=hashlib.sha256):
        self._hashfunc = hashfunc

    def hash_empty(self) -> bytes:
        return self._hashfunc(b"").digest()

    def hash_leaf(self, data: bytes) -> bytes:
        return self._hashfunc(LEAF_PREFIX + data).digest()

    def hash_children(self, left: bytes, right: bytes) -> bytes:
        return self._hashfunc(NODE_PREFIX + left + right).digest()

    def hash_full_tree(self, leaves) -> bytes:
        """MTH over a list of raw leaf payloads (test oracle; O(n))."""
        n = len(leaves)
        if n == 0:
            return self.hash_empty()
        if n == 1:
            return self.hash_leaf(leaves[0])
        k = _largest_power_of_two_smaller_than(n)
        return self.hash_children(
            self.hash_full_tree(leaves[:k]), self.hash_full_tree(leaves[k:]))


def _largest_power_of_two_smaller_than(n: int) -> int:
    k = 1
    while k * 2 < n:
        k *= 2
    return k
