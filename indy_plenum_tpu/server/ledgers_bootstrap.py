"""Node-init wiring: ledgers, states, handlers, genesis, state rebuild.

Reference: plenum/server/ledgers_bootstrap.py (`LedgersBootstrapper`).
Builds the DatabaseManager with the four standard ledgers (POOL, DOMAIN,
CONFIG, AUDIT), sparse-Merkle states for the stateful ones, registers the
request/batch handlers with a WriteRequestManager, applies genesis txns to
fresh ledgers, and rebuilds any state that is missing or behind its ledger
(crash recovery: the ledger is the truth, state is derived).
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from ..common.constants import (
    AUDIT_LEDGER_ID,
    CONFIG_LEDGER_ID,
    DOMAIN_LEDGER_ID,
    POOL_LEDGER_ID,
)
from ..common.txn_util import get_type
from ..ledger.compact_merkle_tree import CompactMerkleTree
from ..ledger.hash_stores import MemoryHashStore
from ..ledger.ledger import Ledger
from ..state.sparse_merkle_state import SparseMerkleState
from ..storage.kv_store import KeyValueStorage, KeyValueStorageInMemory
from .batch_handlers.batch_handlers import (
    AuditBatchHandler,
    LedgerBatchHandler,
)
from .database_manager import DatabaseManager
from .request_handlers.node_handler import NodeHandler
from .request_handlers.nym_handler import NymHandler
from .request_managers.write_request_manager import WriteRequestManager

logger = logging.getLogger(__name__)

STATEFUL_LEDGERS = (POOL_LEDGER_ID, DOMAIN_LEDGER_ID, CONFIG_LEDGER_ID)


class NodeStorage:
    """The durable stores of one node, keyed so a 'restart' can reopen
    them (in tests the same objects are handed to a fresh bootstrap —
    equivalent to reopening on-disk stores)."""

    def __init__(self, factory=KeyValueStorageInMemory):
        self.txn_stores: Dict[int, KeyValueStorage] = {}
        self.hash_stores: Dict[int, Any] = {}
        self.state_stores: Dict[int, KeyValueStorage] = {}
        for lid in (POOL_LEDGER_ID, DOMAIN_LEDGER_ID, CONFIG_LEDGER_ID,
                    AUDIT_LEDGER_ID):
            self.txn_stores[lid] = factory()
            self.hash_stores[lid] = MemoryHashStore()
            if lid in STATEFUL_LEDGERS:
                self.state_stores[lid] = factory()


class LedgersBootstrap:
    def __init__(self, storage: Optional[NodeStorage] = None,
                 pool_genesis: Optional[List[Dict]] = None,
                 domain_genesis: Optional[List[Dict]] = None,
                 config=None):
        self.storage = storage or NodeStorage()
        self.pool_genesis = pool_genesis or []
        self.domain_genesis = domain_genesis or []
        self.config = config
        self.db = DatabaseManager()
        self.write_manager = WriteRequestManager(self.db)
        self.nym_handler: Optional[NymHandler] = None
        self.node_handler: Optional[NodeHandler] = None
        self.audit_handler: Optional[AuditBatchHandler] = None

    # ------------------------------------------------------------------

    def build(self) -> "LedgersBootstrap":
        for lid in (POOL_LEDGER_ID, DOMAIN_LEDGER_ID, CONFIG_LEDGER_ID,
                    AUDIT_LEDGER_ID):
            ledger = Ledger(
                tree=CompactMerkleTree(hash_store=self.storage.hash_stores[lid]),
                txn_store=self.storage.txn_stores[lid])
            # crash recovery: a lost/stale hash store rebuilds from the
            # durable txn log (the log is the truth; the tree is derived)
            ledger.recover_tree()
            state = None
            if lid in STATEFUL_LEDGERS:
                config = self.config
                if config is not None:
                    state = SparseMerkleState(
                        kv=self.storage.state_stores[lid],
                        node_cache_size=config.StateNodeCacheSize,
                        commit_batch_enabled=config.StateCommitBatchEnabled,
                        commit_batch_min=config.StateCommitBatchMin,
                        commit_mode=config.StateCommitBatchMode)
                else:
                    state = SparseMerkleState(
                        kv=self.storage.state_stores[lid])
            self.db.register_new_database(lid, ledger, state)

        self.nym_handler = NymHandler(self.db)
        self.node_handler = NodeHandler(
            self.db, get_nym_data=self.nym_handler.get_nym_data)
        from .request_handlers.pool_config_handler import PoolConfigHandler

        self.pool_config_handler = PoolConfigHandler(
            self.db, get_nym_data=self.nym_handler.get_nym_data)
        self.write_manager.register_req_handler(self.nym_handler)
        self.write_manager.register_req_handler(self.node_handler)
        self.write_manager.register_req_handler(self.pool_config_handler)
        for lid in STATEFUL_LEDGERS:
            self.write_manager.register_batch_handler(
                LedgerBatchHandler(self.db, lid))
        self.audit_handler = AuditBatchHandler(self.db)
        self.write_manager.register_audit_handler(self.audit_handler)

        self._apply_genesis(POOL_LEDGER_ID, self.pool_genesis)
        self._apply_genesis(DOMAIN_LEDGER_ID, self.domain_genesis)
        self._rebuild_states_if_behind()
        return self

    # ------------------------------------------------------------------

    def _apply_genesis(self, lid: int, txns: List[Dict]) -> None:
        ledger = self.db.get_ledger(lid)
        if ledger.size > 0 or not txns:
            return  # already initialized (restart) or nothing to do
        state = self.db.get_state(lid)
        for txn in txns:
            ledger.add(dict(txn))
            self._update_state_for(txn)
        if state is not None:
            state.commit()
        logger.info("ledger %d: %d genesis txns", lid, len(txns))

    def _update_state_for(self, txn: Dict) -> None:
        handler = self.write_manager.handlers.get(get_type(txn))
        if handler is not None:
            handler.update_state(txn, None, is_committed=True)

    def _rebuild_states_if_behind(self) -> None:
        """States are derived data: replay committed ledger txns through the
        handlers when a state is missing or stale (reference: state rebuild
        at node init). Coverage is located via the audit ledger — the
        recovery spine records each batch's state root per ledger — by
        finding the newest audit txn whose recorded root matches the
        persisted committed state root; the ledger sizes it pins tell us
        where replay must resume. A state matching no audit txn (corrupt or
        fresh) is rebuilt from scratch (the SMT 'reset' is a pointer move)."""
        from ..common.constants import (
            AUDIT_TXN_LEDGERS_SIZE,
            AUDIT_TXN_STATE_ROOT,
        )
        from ..common.txn_util import get_payload_data
        from ..state.sparse_merkle_state import EMPTY_ROOT
        from ..utils.base58 import b58encode

        audit_ledger = self.db.get_ledger(AUDIT_LEDGER_ID)
        for lid in STATEFUL_LEDGERS:
            ledger = self.db.get_ledger(lid)
            state = self.db.get_state(lid)
            if ledger.size == 0:
                continue
            current = b58encode(state.committed_head_hash)
            from_size = None
            if state.committed_head_hash == EMPTY_ROOT:
                from_size = 0
            elif audit_ledger.size == 0:
                # no batch ever committed (audit txns are 1:1 with batches):
                # the ledger holds only genesis, which the persisted state
                # already covers
                from_size = ledger.size
            else:
                for seq in range(audit_ledger.size, 0, -1):
                    data = get_payload_data(audit_ledger.get_by_seq_no(seq))
                    if data.get(AUDIT_TXN_STATE_ROOT, {}).get(str(lid)) \
                            == current:
                        from_size = data[AUDIT_TXN_LEDGERS_SIZE][str(lid)]
                        break
            if from_size is None:
                logger.warning(
                    "ledger %d: state root unknown to audit ledger; "
                    "rebuilding from genesis", lid)
                state.set_head_hash(EMPTY_ROOT)
                state.commit(EMPTY_ROOT)
                state.set_head_hash(EMPTY_ROOT)
                from_size = 0
            if from_size >= ledger.size:
                continue
            logger.info("ledger %d: replaying txns %d..%d into state",
                        lid, from_size + 1, ledger.size)
            for seq in range(from_size + 1, ledger.size + 1):
                self._update_state_for(ledger.get_by_seq_no(seq))
            state.commit()

    @property
    def committed_pp_seq_no(self) -> int:
        return self.write_manager.committed_pp_seq_no()
