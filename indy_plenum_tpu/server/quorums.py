"""Quorum arithmetic for n = 3f + 1 Byzantine fault tolerance.

Reference: plenum/server/quorums.py (`Quorums`, `Quorum`). All thresholds
are pure functions of the pool size n; they are used both by the host
protocol state machines and (as integers baked into jitted closures) by the
device-plane quorum tally in `indy_plenum_tpu.models.quorum_plane`.
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class Quorum:
    """A single threshold: satisfied when votes >= value."""

    value: int

    def is_reached(self, votes: int) -> bool:
        return votes >= self.value


@dataclass(frozen=True)
class Quorums:
    """All protocol thresholds derived from pool size ``n``.

    f = (n - 1) // 3 is the max number of byzantine nodes tolerated.

    weak (f+1): at least one honest node among the voters.
    strong (n-f): a majority of honest nodes among the voters.
    """

    n: int
    f: int = field(init=False)
    weak: Quorum = field(init=False)
    strong: Quorum = field(init=False)
    propagate: Quorum = field(init=False)
    prepare: Quorum = field(init=False)
    commit: Quorum = field(init=False)
    checkpoint: Quorum = field(init=False)
    view_change: Quorum = field(init=False)
    new_view: Quorum = field(init=False)
    view_change_ack: Quorum = field(init=False)
    view_change_done: Quorum = field(init=False)
    election: Quorum = field(init=False)
    reply: Quorum = field(init=False)
    consistency_proof: Quorum = field(init=False)
    ledger_status: Quorum = field(init=False)
    backup_instance_faulty: Quorum = field(init=False)
    timestamp: Quorum = field(init=False)
    bls_signatures: Quorum = field(init=False)
    observer_data: Quorum = field(init=False)
    same_consistency_proof: Quorum = field(init=False)

    def __post_init__(self):
        n = self.n
        if n < 1:
            raise ValueError(f"pool size must be >= 1, got {n}")
        f = (n - 1) // 3
        object.__setattr__(self, "f", f)
        set_ = object.__setattr__
        set_(self, "weak", Quorum(f + 1))
        set_(self, "strong", Quorum(n - f))
        set_(self, "propagate", Quorum(f + 1))
        set_(self, "prepare", Quorum(n - f - 1))
        set_(self, "commit", Quorum(n - f))
        # checkpoint/ledger_status/view_change_ack count only OTHER nodes'
        # messages (a node does not message itself), hence n - f - 1.
        set_(self, "checkpoint", Quorum(n - f - 1))
        set_(self, "view_change", Quorum(n - f))
        set_(self, "new_view", Quorum(n - f))
        set_(self, "view_change_ack", Quorum(n - f - 1))
        set_(self, "view_change_done", Quorum(n - f))
        set_(self, "election", Quorum(n - f))
        set_(self, "reply", Quorum(f + 1))
        set_(self, "consistency_proof", Quorum(f + 1))
        set_(self, "ledger_status", Quorum(n - f - 1))
        set_(self, "backup_instance_faulty", Quorum(f + 1))
        set_(self, "timestamp", Quorum(f + 1))
        set_(self, "bls_signatures", Quorum(n - f))
        set_(self, "observer_data", Quorum(f + 1))
        set_(self, "same_consistency_proof", Quorum(f + 1))
