"""Client request authentication: the north-star hot path, device-batched.

Reference: plenum/server/client_authn.py (`ClientAuthNr`, `CoreAuthNr`) and
plenum/server/req_authenticator.py (`ReqAuthenticator`).
``CoreAuthNr.authenticate`` is BASELINE.json's north-star symbol: resolve
the signer's verkey from domain state (the NYM record written by
``NymHandler``) and Ed25519-verify the request's canonical signing bytes.

TPU-first redesign: verification is BATCHED — inbound requests queue up and
one jitted kernel (:mod:`indy_plenum_tpu.tpu.ed25519`) verifies the whole
pending set; only a verdict vector returns. ``authenticate`` (single, host
path) exists for compatibility and as the oracle; ``authenticate_batch`` is
the hot path the node ingress uses. Batches are padded to fixed bucket
sizes so XLA compiles a handful of programs, not one per batch length.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..common.exceptions import (
    CouldNotAuthenticate,
    InsufficientSignatures,
    InvalidSignature,
    MissingSignature,
)
from ..common.request import Request
from ..crypto import ed25519 as ed
from ..crypto.signers import resolve_verkey_bytes
from ..utils.base58 import b58decode

logger = logging.getLogger(__name__)

# batch bucket sizes: pad to the smallest fitting bucket (fixed XLA shapes)
_BUCKETS = (8, 32, 128, 512, 2048, 8192)

def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + _BUCKETS[-1] - 1) // _BUCKETS[-1]) * _BUCKETS[-1]


# (size, max_blocks) shapes pre-compiled by warm_device_auth_path: the
# device-hash tier runs ONLY for these, every other shape takes the host
# tier — an unwarmed shape must degrade gracefully, never stall the
# protocol thread on a synchronous XLA compile (batch size and message
# length are both client-controlled)
_WARMED_SHAPES: set = set()


def warm_device_auth_path(sizes: Sequence[int] = (512, 2048, 8192),
                          block_buckets: Sequence[int] = (1, 2, 4, 8)
                          ) -> None:
    """Pre-compile the device-hash verify shapes OFF the protocol path.

    Every new (batch, max_blocks) shape is a synchronous XLA compile; a
    deployed node calls this at startup (scripts/start_node.py) so no
    ingress batch ever waits on one — shapes NOT warmed here simply take
    the host-hash tier."""
    from ..tpu import ed25519 as ted

    for size in sizes:
        for mb in block_buckets:
            pks = [b"\x00" * 32] * size
            msgs = [b""] * size
            sigs = [b"\x00" * 64] * size
            (pk_a, r_a, s_a, blocks, counts,
             pre) = ted.prepare_batch_device(pks, msgs, sigs, mb)
            # da: allow[device-sync] -- warm-up compile must resolve before the shape is marked warm; runs at startup, never on the tick loop
            np.asarray(ted.verify_kernel_full(
                pk_a, r_a, s_a, blocks, counts))
            _WARMED_SHAPES.add((size, mb))


class ClientAuthNr:
    """Authenticator interface (reference: ClientAuthNr ABC)."""

    def authenticate(self, req: Request) -> List[str]:
        raise NotImplementedError

    def authenticate_batch(self, reqs: Sequence[Request]) -> np.ndarray:
        raise NotImplementedError


class CoreAuthNr(ClientAuthNr):
    """Verkey resolution from domain state + Ed25519 verification.

    ``verkey_source`` is any object with ``get_nym_data(idr, is_committed)``
    returning the NYM record dict (NymHandler provides this); ``seed_keys``
    maps genesis identifiers to wire verkeys for identities that predate any
    NYM txn (e.g. the genesis trustee bootstrapping the first NYMs).
    """

    def __init__(self, verkey_source=None,
                 seed_keys: Optional[Dict[str, str]] = None):
        self._source = verkey_source
        self._seed_keys = dict(seed_keys or {})

    # --- verkey resolution ---------------------------------------------

    def resolve_verkey(self, identifier: str) -> Optional[bytes]:
        if self._source is not None:
            data = self._source.get_nym_data(identifier, is_committed=True)
            if data is not None:
                try:
                    return resolve_verkey_bytes(
                        identifier, data.get("verkey"))
                except ValueError:
                    return None
        wire = self._seed_keys.get(identifier)
        if wire is not None:
            try:
                return resolve_verkey_bytes(identifier, wire)
            except ValueError:
                return None
        # cryptonym: the identifier may itself be a full verkey
        try:
            raw = b58decode(identifier)
        except ValueError:
            return None
        return raw if len(raw) == 32 else None

    # --- single (host oracle / compat) ---------------------------------

    def authenticate(self, req: Request) -> List[str]:
        """Verify all signatures on one request; return verified idrs."""
        sigs = dict(req.signatures or {})
        if req.signature:
            sigs.setdefault(req.identifier, req.signature)
        if not sigs:
            raise MissingSignature(req.identifier)
        data = req.signing_bytes()
        verified = []
        for idr, sig_b58 in sigs.items():
            vk = self.resolve_verkey(idr)
            if vk is None:
                raise CouldNotAuthenticate(idr)
            try:
                sig = b58decode(sig_b58)
            except ValueError:
                raise InvalidSignature(idr) from None
            if not ed.fast_verify(vk, data, sig):
                raise InvalidSignature(idr)
            verified.append(idr)
        if not verified:
            raise InsufficientSignatures(0, 1)
        return verified

    # --- batched (the device hot path) ---------------------------------

    def authenticate_batch(self, reqs: Sequence[Request]) -> np.ndarray:
        """Device-verify a request batch; (B,) bool verdicts.

        Every attached signature — the single ``signature`` AND each
        multi-sig endorsement in ``signatures`` — becomes one batch entry;
        a request verifies only if ALL of its entries verify (reference:
        ReqAuthenticator verifies every attached signature). Requests whose
        verkey cannot be resolved or whose signature is structurally
        invalid fail without touching the device; the rest are verified in
        one jitted kernel call (bucketed padding).
        """
        from ..tpu import ed25519 as ted

        n = len(reqs)
        verdict = np.zeros(n, bool)
        entry_req: List[int] = []  # owning request index per entry
        pks, msgs, sigs = [], [], []
        candidate = np.zeros(n, bool)
        for i, req in enumerate(reqs):
            pairs = dict(req.signatures or {})
            if req.signature:
                pairs.setdefault(req.identifier, req.signature)
            if not pairs:
                continue
            data = req.signing_bytes()
            local = []
            for idr in sorted(pairs):
                vk = self.resolve_verkey(idr)
                if vk is None:
                    break
                try:
                    sig = b58decode(pairs[idr])
                except ValueError:
                    break
                if len(sig) != 64:
                    break
                local.append((vk, sig))
            else:
                candidate[i] = True
                for vk, sig in local:
                    entry_req.append(i)
                    pks.append(vk)
                    msgs.append(data)
                    sigs.append(sig)
        if not entry_req:
            return verdict

        m = len(entry_req)
        size = _bucket(m)
        pad = size - m
        pks += [pks[0]] * pad
        msgs += [msgs[0]] * pad
        sigs += [sigs[0]] * pad
        # full-device path: SHA512(R||A||M) mod L is computed ON CHIP —
        # the round-4 host hash loop no longer rides the protocol thread.
        # Tiered: tiny batches keep the host-hash path (hashlib on a few
        # messages is cheaper than widening the jit-shape zoo; device
        # hashing pays off exactly where the host loop was the wall —
        # full ingress batches). Only shapes PRE-COMPILED by
        # warm_device_auth_path are eligible: batch size and message
        # length are client-controlled, and an unwarmed shape would stall
        # the protocol thread on a synchronous XLA compile.
        max_blocks = ted.max_blocks_for(msgs)
        if (size, max_blocks) in _WARMED_SHAPES:
            (pk_a, r_a, s_a, blocks, counts,
             pre) = ted.prepare_batch_device(pks, msgs, sigs, max_blocks)
            # da: allow[device-sync] -- auth verdicts MUST resolve before admission decides this batch; one batched sync per ingress drain, not per message
            ok = np.asarray(ted.verify_kernel_full(
                pk_a, r_a, s_a, blocks, counts)) & pre
        else:
            pk_a, r_a, s_a, h_a, pre = ted.prepare_batch(pks, msgs, sigs)
            # da: allow[device-sync] -- auth verdict resolve, host-hash tier (see above)
            ok = np.asarray(ted.verify_kernel(pk_a, r_a, s_a, h_a)) & pre
        # da: allow[device-sync] -- entry_req is a host list; asarray here never touches the device
        owners = np.asarray(entry_req)
        bad_per_req = np.bincount(owners[~ok[:m]], minlength=n)
        return candidate & (bad_per_req == 0)


class ReqAuthenticator:
    """Registry composing authenticators (reference: ReqAuthenticator)."""

    def __init__(self):
        self._authenticators: List[ClientAuthNr] = []

    def register_authenticator(self, authnr: ClientAuthNr) -> None:
        self._authenticators.append(authnr)

    @property
    def core_authenticator(self) -> Optional[CoreAuthNr]:
        for a in self._authenticators:
            if isinstance(a, CoreAuthNr):
                return a
        return None

    def authenticate(self, req: Request) -> List[str]:
        if not self._authenticators:
            raise CouldNotAuthenticate(req.identifier)
        identifiers: List[str] = []
        for authnr in self._authenticators:
            identifiers.extend(authnr.authenticate(req))
        return identifiers
