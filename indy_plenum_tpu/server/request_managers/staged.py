"""Bookkeeping for speculatively-applied (uncommitted) 3PC batches."""
from __future__ import annotations

from typing import NamedTuple, Optional

from ..batch_handlers.three_pc_batch import ThreePcBatch


class StagedBatch(NamedTuple):
    ledger_id: int
    pp_seq_no: int
    view_no: int
    txn_count: int
    pre_state_root: Optional[bytes]  # state head before this batch applied
    state_root: Optional[bytes]  # state head after
    batch: ThreePcBatch
