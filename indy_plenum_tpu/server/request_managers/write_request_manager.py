"""Write-request execution: validate -> apply (staged) -> commit/revert.

Reference: plenum/server/request_managers/write_request_manager.py
(`WriteRequestManager`). Dispatches per-txn-type handlers for validation
and state updates, stages txns on the ledger's uncommitted tail, writes the
audit txn per batch (AuditBatchHandler), and moves batches between staged
and committed as 3PC orders or reverts them. The LIFO revert uses the
sparse-Merkle state's content-addressed roots: rewinding is a pointer move
(``set_head_hash``), not a walk.

``NodeExecutor`` adapts this to the ``Executor`` seam of
:class:`~indy_plenum_tpu.server.consensus.ordering_service.OrderingService`:
speculative apply returns the (state_root, txn_root) the PRE-PREPARE
carries; a re-apply at or below the committed height returns the historical
roots from the audit ledger (post-view-change re-ordering safety).
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from ...common.constants import (
    AUDIT_LEDGER_ID,
    AUDIT_TXN_LEDGER_ROOT,
    AUDIT_TXN_STATE_ROOT,
)
from ...common.request import Request
from ...common.txn_util import append_txn_metadata, reqToTxn
from ...utils.base58 import b58encode
from ..batch_handlers.batch_handlers import (
    AuditBatchHandler,
    LedgerBatchHandler,
)
from ..batch_handlers.three_pc_batch import ThreePcBatch
from ..database_manager import DatabaseManager
from ..request_handlers.handler_interfaces import WriteRequestHandler
from .staged import StagedBatch

logger = logging.getLogger(__name__)


class WriteRequestManager:
    def __init__(self, database_manager: DatabaseManager):
        self.db = database_manager
        self.handlers: Dict[str, WriteRequestHandler] = {}
        self.batch_handlers: Dict[int, LedgerBatchHandler] = {}
        self.audit_handler: Optional[AuditBatchHandler] = None
        self._staged: List[StagedBatch] = []
        # set post-construction by the owning node (node.py / SimNode):
        # per-batch state-commit meters land here when present
        self.metrics = None

    # --- registration ---------------------------------------------------

    def register_req_handler(self, handler: WriteRequestHandler) -> None:
        self.handlers[handler.txn_type] = handler

    def register_batch_handler(self, handler: LedgerBatchHandler) -> None:
        self.batch_handlers[handler.ledger_id] = handler

    def register_audit_handler(self, handler: AuditBatchHandler) -> None:
        self.audit_handler = handler

    def ledger_id_for_request(self, request: Request) -> Optional[int]:
        h = self.handlers.get(request.txn_type)
        return h.ledger_id if h else None

    # --- validation -----------------------------------------------------

    def _handler(self, request: Request) -> WriteRequestHandler:
        h = self.handlers.get(request.txn_type)
        if h is None:
            from ...common.exceptions import InvalidClientRequest

            raise InvalidClientRequest(
                request.identifier, request.reqId,
                f"no handler for txn type {request.txn_type!r}")
        return h

    def static_validation(self, request: Request) -> None:
        self._handler(request).static_validation(request)

    def dynamic_validation(self, request: Request,
                           req_pp_time: Optional[int]) -> None:
        # pool-wide write switch, enforced IN CONSENSUS (not only at
        # ingress): a request smuggled in through a faulty node's
        # PROPAGATE must still be rejected by every honest replica's
        # dynamic validation, deterministically (uncommitted state).
        # POOL_CONFIG itself stays writable or the pool could never
        # re-enable.
        from ...common.constants import POOL_CONFIG
        from ...common.exceptions import UnauthorizedClientRequest

        if request.txn_type != POOL_CONFIG:
            cfg = self.handlers.get(POOL_CONFIG)
            if cfg is not None and not cfg.writes_enabled(
                    is_committed=False):
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "pool writes are disabled (POOL_CONFIG)")
        self._handler(request).dynamic_validation(request, req_pp_time)

    # --- apply (staged) -------------------------------------------------

    def apply_request(self, request: Request,
                      pp_time: int) -> Dict[str, Any]:
        handler = self._handler(request)
        txn = append_txn_metadata(reqToTxn(request), txn_time=pp_time)
        handler.ledger.append_txns([txn])  # assigns provisional seqNo
        handler.update_state(txn, None, request=request)
        return txn

    def apply_batch(self, batch: ThreePcBatch, reqs: List[Request]
                    ) -> Tuple[bytes, bytes, List[Tuple[Request, Exception]]]:
        """Speculatively apply a whole 3PC batch; returns the raw
        (state_root, txn_root) every replica must reproduce plus the
        requests rejected by dynamic validation.

        Validation is interleaved with application in request order, so the
        valid/invalid split is a deterministic function of (pre-state,
        request sequence): every replica re-running this loop reaches the
        same split and the same roots. A rejected request is simply not
        applied (the reference tracks these via the PRE-PREPARE ``discarded``
        field and sends Rejects at execution); an *unexpected* failure rolls
        the ledger and state back to the pre-batch roots and re-raises —
        never leave half a batch applied without a staged record.
        """
        from ...common.exceptions import InvalidClientRequest

        ledger = self.db.get_ledger(batch.ledger_id)
        state = self.db.get_state(batch.ledger_id)
        pre_state_root = state.head_hash if state is not None else None
        pre_uncommitted = ledger.uncommitted_size
        # batched state commit: buffer the batch's writes and flush them
        # through ONE bottom-up tree walk (SparseMerkleState.apply_batch)
        # instead of a 256-hash path walk per write; reads during dynamic
        # validation see the pending overlay, so the valid/invalid split
        # (and therefore the root) is unchanged from sequential apply
        pre_hashes = state.hashes_total if state is not None else 0
        in_batch = state.begin_batch() if state is not None else False
        valid: List[Request] = []
        rejected: List[Tuple[Request, Exception]] = []
        try:
            for req in reqs:
                try:
                    self.dynamic_validation(req, batch.pp_time)
                except InvalidClientRequest as ex:
                    rejected.append((req, ex))
                    continue
                self.apply_request(req, batch.pp_time)
                valid.append(req)
            if in_batch:
                state.flush_batch()
        except Exception:
            # discard down to the pre-batch size, not len(valid): the
            # failing request's txn may already be appended (apply_request
            # appends before update_state runs)
            ledger.discard_txns(ledger.uncommitted_size - pre_uncommitted)
            if state is not None and pre_state_root is not None:
                # set_head_hash also discards any still-buffered writes
                state.set_head_hash(pre_state_root)
            raise
        state_root = state.head_hash if state is not None else b""
        if state is not None and self.metrics is not None:
            from ...common.metrics_collector import MetricsName

            self.metrics.add_event(MetricsName.STATE_COMMIT_HASHES,
                                   state.hashes_total - pre_hashes)
            self.metrics.add_event(MetricsName.STATE_COMMIT_BATCH_SIZE,
                                   len(valid))
        txn_root = ledger.uncommitted_root_hash
        batch.state_root = state_root
        batch.txn_root = txn_root
        batch.valid_digests = [r.digest for r in valid]
        if self.audit_handler is not None:
            self.audit_handler.post_batch_applied(batch)
        self._staged.append(StagedBatch(
            ledger_id=batch.ledger_id,
            pp_seq_no=batch.pp_seq_no,
            view_no=batch.view_no,
            txn_count=len(valid),
            pre_state_root=pre_state_root,
            state_root=state_root,
            batch=batch,
        ))
        return state_root, txn_root, rejected

    # --- revert (LIFO) --------------------------------------------------

    def revert_last_batch(self) -> None:
        staged = self._staged.pop()
        ledger = self.db.get_ledger(staged.ledger_id)
        state = self.db.get_state(staged.ledger_id)
        ledger.discard_txns(staged.txn_count)
        if state is not None and staged.pre_state_root is not None:
            state.set_head_hash(staged.pre_state_root)
        if self.audit_handler is not None:
            self.audit_handler.post_batch_rejected(staged.ledger_id)

    def revert_batches(self, ledger_id: int, count: int) -> None:
        """Revert up to ``count`` newest staged batches of ``ledger_id``.

        Staged batches for other ledgers above them must not exist when
        this is called per-ledger (the ordering service reverts newest
        first, grouped by ledger) — assert the LIFO discipline instead of
        silently corrupting roots.
        """
        for _ in range(count):
            if not self._staged:
                return
            assert self._staged[-1].ledger_id == ledger_id, (
                "revert discipline violated: top staged batch is for "
                f"ledger {self._staged[-1].ledger_id}, not {ledger_id}")
            self.revert_last_batch()

    # --- commit (FIFO) --------------------------------------------------

    def commit_next_batch(self) -> StagedBatch:
        staged = self._staged.pop(0)
        handler = self.batch_handlers.get(staged.ledger_id)
        if handler is None:
            handler = LedgerBatchHandler(self.db, staged.ledger_id)
        handler.commit_batch(staged.batch)
        if self.audit_handler is not None:
            self.audit_handler.commit_batch(staged.batch)
        return staged

    @property
    def staged_batches(self) -> List[StagedBatch]:
        return list(self._staged)

    def committed_pp_seq_no(self) -> int:
        if self.audit_handler is None:
            return 0
        return self.audit_handler.committed_pp_seq_no()


class NodeExecutor:
    """Adapter: OrderingService ``Executor`` seam -> WriteRequestManager.

    ``get_view_info`` supplies (view_no, primaries) for the audit txn.
    """

    def __init__(self, manager: WriteRequestManager, get_view_info=None):
        self.manager = manager
        self._get_view_info = get_view_info or (lambda: (0, []))
        # requests the last apply_batch rejected in dynamic validation —
        # the ordering service reads this to fill PrePrepare.discarded (on
        # the primary) and to cross-check it on re-apply (replicas)
        self.last_rejected: List[Tuple[Request, Exception]] = []

    def apply_batch(self, reqs: List[Request], ledger_id: int,
                    pp_time: int, pp_seq_no: int
                    ) -> Tuple[Optional[str], Optional[str]]:
        self.last_rejected = []
        committed = self.committed_seq()
        if pp_seq_no <= committed:
            # historical: already durably executed (pre-view-change); the
            # audit ledger knows the roots this batch must carry
            audit = self.manager.audit_handler
            data = audit.audit_data_for_seq(pp_seq_no) if audit else None
            if data is None:
                return None, None
            return (data[AUDIT_TXN_STATE_ROOT].get(str(ledger_id)),
                    data[AUDIT_TXN_LEDGER_ROOT].get(str(ledger_id)))
        view_no, primaries = self._get_view_info()
        batch = ThreePcBatch(
            ledger_id=ledger_id,
            inst_id=0,
            view_no=view_no,
            pp_seq_no=pp_seq_no,
            pp_time=pp_time,
            state_root=None,
            txn_root=None,
            valid_digests=[r.digest for r in reqs],
            primaries=primaries,
        )
        state_root, txn_root, rejected = self.manager.apply_batch(batch, reqs)
        self.last_rejected = rejected
        return b58encode(state_root), b58encode(txn_root)

    def revert_batches(self, ledger_id: int, count: int) -> None:
        self.manager.revert_batches(ledger_id, count)

    def committed_seq(self) -> int:
        return self.manager.committed_pp_seq_no()

    def commit_batch(self, pp_seq_no: int) -> Optional[StagedBatch]:
        if pp_seq_no <= self.committed_seq():
            return None  # already durable (re-ordered after view change)
        staged = self.manager.commit_next_batch()
        assert staged.pp_seq_no == pp_seq_no, (staged.pp_seq_no, pp_seq_no)
        return staged
