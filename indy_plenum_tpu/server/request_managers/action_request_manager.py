"""Action requests: privileged immediate commands, never ledger-written.

Reference: plenum/server/request_managers/action_request_manager.py
(`ActionRequestManager`) with the pool-restart and validator-info handlers
(plenum/server/pool_req_handler.py analogs). An ACTION is executed by the
RECEIVING node right away — no consensus round, no txn — but unlike reads
it is PRIVILEGED: the signed request must authenticate AND its author must
hold an authorized role (TRUSTEE, or STEWARD for info).

- VALIDATOR_INFO: a status snapshot (view, last ordered, ledger sizes,
  freshness, mode) — the operational "how is this node doing" surface.
- POOL_RESTART: schedules a restart callback at ``datetime`` (seconds on
  the node clock; 0/absent = now). The composition injects what restart
  MEANS (systemd unit, container exit, test flag) via ``restart_sink``.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional

from ...common.constants import (
    POOL_RESTART,
    STEWARD,
    TRUSTEE,
    VALIDATOR_INFO,
)
from ...common.exceptions import (
    InvalidClientRequest,
    UnauthorizedClientRequest,
)
from ...common.request import Request

logger = logging.getLogger(__name__)

_INFO_ROLES = (TRUSTEE, STEWARD)
_RESTART_ROLES = (TRUSTEE,)


class ActionRequestManager:
    def __init__(self,
                 node_status_provider: Callable[[], Dict[str, Any]],
                 get_nym_data,
                 timer,
                 restart_sink: Optional[Callable[[], None]] = None):
        self._status = node_status_provider
        self._get_nym_data = get_nym_data  # (idr, is_committed) -> record
        self._timer = timer
        self._restart_sink = restart_sink or (lambda: None)
        self.restarts_scheduled = 0
        self._handlers = {
            VALIDATOR_INFO: self._handle_validator_info,
            POOL_RESTART: self._handle_pool_restart,
        }

    def is_action(self, txn_type: Optional[str]) -> bool:
        return txn_type in self._handlers

    def handle(self, request: Request) -> Dict[str, Any]:
        handler = self._handlers.get(request.txn_type)
        if handler is None:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                f"no action handler for {request.txn_type!r}")
        roles = (_RESTART_ROLES if request.txn_type == POOL_RESTART
                 else _INFO_ROLES)
        self._authorize(request, roles)
        return handler(request)

    def _authorize(self, request: Request, roles) -> None:
        record = self._get_nym_data(request.identifier, True) \
            if self._get_nym_data else None
        role = (record or {}).get("role")
        if role not in roles:
            raise UnauthorizedClientRequest(
                request.identifier, request.reqId,
                f"role {role!r} may not run action {request.txn_type}")

    # ------------------------------------------------------------------

    def _handle_validator_info(self, request: Request) -> Dict[str, Any]:
        return {"type": VALIDATOR_INFO, "data": self._status()}

    def _handle_pool_restart(self, request: Request) -> Dict[str, Any]:
        when = request.operation.get("datetime")
        now = self._timer.get_current_time()
        if when in (None, 0, ""):
            delay = 0.0
        elif isinstance(when, (int, float)) and when >= now:
            delay = when - now
        else:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "datetime must be absent/0 (now) or a future node-clock "
                "timestamp")
        self.restarts_scheduled += 1
        logger.info("POOL_RESTART scheduled in %.1fs", delay)
        self._timer.schedule(delay, self._restart_sink)
        return {"type": POOL_RESTART, "scheduled_in": delay}
