"""Read requests: proved state reads and ledger txn lookups.

Reference: plenum/server/request_managers/read_request_manager.py
(`ReadRequestManager`) + the GET_TXN handler
(plenum/server/request_handlers/get_txn_handler.py). Reads are served by
the RECEIVING node alone — no consensus round — because every answer
carries proof material making it as trustworthy as f+1 matching replies:

- GET_NYM: {value, sparse-Merkle inclusion/absence proof, the pool's BLS
  multi-signature over the committed state root} — the client checks both
  (client/state_proof.verify_proved_reply) and can trust one node.
- GET_TXN: {txn, RFC 6962 audit path against the ledger root} — the root
  itself is bound into the audit ledger chain each batch.

Reads are permitted unsigned (reference behaviour: reading is public).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ...common.constants import (
    DOMAIN_LEDGER_ID,
    GET_NYM,
    GET_TXN,
    TARGET_NYM,
)
from ...common.exceptions import InvalidClientRequest
from ...common.request import Request
from ...utils.base58 import b58encode
from ..database_manager import DatabaseManager


class ReadRequestManager:
    def __init__(self, db: DatabaseManager,
                 bls_multi_sig_getter: Optional[
                     Callable[[str], Optional[dict]]] = None):
        """``bls_multi_sig_getter(state_root_b58) -> MultiSignature dict``
        (the BlsStore lookup) — None when the pool runs without BLS."""
        self._db = db
        self._get_multi_sig = bls_multi_sig_getter or (lambda root: None)
        self._handlers: Dict[str, Callable[[Request], Dict[str, Any]]] = {
            GET_NYM: self.handle_get_nym,
            GET_TXN: self.handle_get_txn,
        }

    def is_read(self, txn_type: Optional[str]) -> bool:
        return txn_type in self._handlers

    def handle(self, request: Request) -> Dict[str, Any]:
        handler = self._handlers.get(request.txn_type)
        if handler is None:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                f"no read handler for txn type {request.txn_type!r}")
        return handler(request)

    # ------------------------------------------------------------------

    def handle_get_nym(self, request: Request) -> Dict[str, Any]:
        """Proved read of a NYM record from committed domain state."""
        dest = request.operation.get(TARGET_NYM)
        # reads are unsigned and unauthenticated: every field is hostile
        # until type-checked (a non-str dest would raise deep inside)
        if not dest or not isinstance(dest, str):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "GET_NYM needs a string dest")
        state = self._db.get_state(DOMAIN_LEDGER_ID)
        root = state.committed_head_hash
        key = dest.encode()
        value = state.get(key, is_committed=True)
        proof = state.generate_state_proof(key, root=root, serialize=True)
        return {
            "type": GET_NYM,
            "dest": dest,
            "data": value,
            "state_proof": {
                "root_hash": b58encode(root),
                "proof_nodes": proof,
                "multi_signature": self._get_multi_sig(b58encode(root)),
            },
        }

    def handle_get_txn(self, request: Request) -> Dict[str, Any]:
        """A committed txn by seqNo + its audit path to the ledger root.

        When the pool runs BLS, the reply also carries the multi-signature
        over the LATEST batch of this ledger — its co-signed
        ``txn_root_hash`` is this ledger root, which upgrades GET_TXN to a
        proved single-node read (the client checks audit path -> co-signed
        root -> pool keys; see Client._verify_proved_get_txn)."""
        ledger_id = request.operation.get("ledgerId", DOMAIN_LEDGER_ID)
        seq_no = request.operation.get("data")
        if not isinstance(ledger_id, int):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "GET_TXN ledgerId must be an int")
        if not isinstance(seq_no, int) or isinstance(seq_no, bool) \
                or seq_no < 1:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "GET_TXN needs a positive seqNo in 'data'")
        ledger = self._db.get_ledger(ledger_id)
        if ledger is None or seq_no > ledger.size:
            return {"type": GET_TXN, "ledgerId": ledger_id,
                    "seqNo": seq_no, "data": None}
        txn = ledger.get_by_seq_no(seq_no)
        size = ledger.size
        multi_sig = None
        state = self._db.get_state(ledger_id)
        if state is not None:
            ms = self._get_multi_sig(b58encode(state.committed_head_hash))
            # only attach when it actually covers THIS ledger root (the
            # store is keyed by state root; its value co-signs the txn
            # root of the same batch)
            if ms and ms.get("value", {}).get("txn_root_hash") \
                    == b58encode(ledger.root_hash):
                multi_sig = ms
        return {
            "type": GET_TXN,
            "ledgerId": ledger_id,
            "seqNo": seq_no,
            "data": txn,
            "auditProof": {
                "rootHash": b58encode(ledger.root_hash),
                "ledgerSize": size,
                "auditPath": [b58encode(h)
                              for h in ledger.audit_path(seq_no, size)],
                "multi_signature": multi_sig,
            },
        }
