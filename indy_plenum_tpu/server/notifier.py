"""Notifier: operator-facing events pushed to pluggable sinks.

Reference: plenum/server/notifier_plugin_manager.py — monitor degradation
and view-change events are forwarded to registered notifier plugins
(upstream: agent webhooks/email) rather than living only in logs. Here a
sink is any callable taking one event dict; plugins register theirs via
``plugin_entry(node)`` -> ``node.notifier.register_sink(fn)`` (same
plugin seam as request handlers, :mod:`indy_plenum_tpu.plugins`).

Event kinds mirror the operationally-interesting internal-bus traffic:
master degradation votes, view-change lifecycle, catchup failure (the
fail-closed alarm), and byzantine suspicions. A raising sink is isolated
and logged — an operator webhook must never stall consensus.
"""
from __future__ import annotations

import logging
from collections import deque
from typing import Any, Callable, Dict, List

from ..common.event_bus import InternalBus
from ..common.messages.internal_messages import (
    NodeNeedViewChange,
    RaisedSuspicion,
    ViewChangeFinished,
    ViewChangeStarted,
    VoteForViewChange,
)
from .suspicion_codes import Suspicions

logger = logging.getLogger(__name__)

# event kinds (reference: the notifier plugin event names)
MASTER_DEGRADED = "master_degraded"
VIEW_CHANGE_VOTE = "view_change_vote"
VIEW_CHANGE_STARTED = "view_change_started"
VIEW_CHANGE_COMPLETE = "view_change_complete"
CATCHUP_FAILED = "catchup_failed"
SUSPICION = "suspicion"


class NotifierService:
    def __init__(self, node_name: str, bus: InternalBus,
                 timer=None, history: int = 200):
        self._name = node_name
        self._timer = timer
        self._sinks: List[Callable[[Dict[str, Any]], None]] = []
        # bounded in-process history: VALIDATOR_INFO / tests read it
        self.events: deque = deque(maxlen=history)

        bus.subscribe(VoteForViewChange, self._on_vote_for_view_change)
        bus.subscribe(NodeNeedViewChange, self._on_need_view_change)
        bus.subscribe(ViewChangeStarted, self._on_view_change_started)
        bus.subscribe(ViewChangeFinished, self._on_view_change_finished)
        bus.subscribe(RaisedSuspicion, self._on_raised_suspicion)

    def register_sink(self, sink: Callable[[Dict[str, Any]], None]) -> None:
        self._sinks.append(sink)

    # ------------------------------------------------------------------

    def _emit(self, kind: str, **data: Any) -> None:
        event = {"node": self._name, "kind": kind, **data}
        if self._timer is not None:
            event["timestamp"] = self._timer.get_current_time()
        self.events.append(event)
        for sink in self._sinks:
            try:
                sink(dict(event))
            except Exception:  # noqa: BLE001 — a webhook must never
                # stall consensus
                logger.exception("%s: notifier sink raised", self._name)

    def _on_vote_for_view_change(self, msg: VoteForViewChange,
                                 *args) -> None:
        suspicion = msg.suspicion
        code = getattr(suspicion, "code", None)
        if code == Suspicions.PRIMARY_DEGRADED.code:
            self._emit(MASTER_DEGRADED,
                       reason=getattr(suspicion, "reason", ""))
        else:
            self._emit(VIEW_CHANGE_VOTE, code=code,
                       reason=getattr(suspicion, "reason", ""))

    def _on_need_view_change(self, msg: NodeNeedViewChange, *args) -> None:
        self._emit(VIEW_CHANGE_STARTED, view_no=msg.view_no)

    def _on_view_change_started(self, msg: ViewChangeStarted,
                                *args) -> None:
        pass  # covered by NodeNeedViewChange (quorum reached)

    def _on_view_change_finished(self, msg: ViewChangeFinished,
                                 *args) -> None:
        self._emit(VIEW_CHANGE_COMPLETE, view_no=msg.view_no)

    def _on_raised_suspicion(self, msg: RaisedSuspicion, *args) -> None:
        ex = msg.ex
        suspicion = getattr(ex, "suspicion", None)
        code = getattr(suspicion, "code", None)
        if code == Suspicions.CATCHUP_FAILED.code:
            # the fail-closed alarm: the node is out of the protocol
            # until catchup succeeds — the one event an operator must see
            self._emit(CATCHUP_FAILED,
                       reason=getattr(suspicion, "reason", ""))
        else:
            self._emit(SUSPICION, code=code,
                       peer=getattr(ex, "node", None),
                       reason=getattr(suspicion, "reason", ""))
