"""The Node: composition root of one validator.

Reference: plenum/server/node.py (`Node`) — there a ~4000-line god class;
here a thin composition root that OWNS the seams the simulation previously
faked (SimRequestsPool's shared-pool fiction): client ingress with
device-batched authentication, PROPAGATE dissemination with per-node f+1
finalisation, replay protection, execution with Reply emission, and the
full consensus service stack.

Ingress pipeline (the north-star hot path):
    client request -> replay check (ReqIdrToTxn) -> auth queue ->
    [one device batch per PropagateBatchWait tick:
     CoreAuthNr.authenticate_batch] -> Propagator.propagate ->
    f+1 PROPAGATE quorum -> finalised -> NodeRequestsPool ->
    OrderingService 3PC -> Ordered -> execute/commit -> Reply.

Verkey resolution is STATE-BACKED: CoreAuthNr reads the signer's NYM from
the domain SMT (NymHandler.get_nym_data), so an identity written by a
committed NYM txn can authenticate follow-up requests with no static key
material beyond the genesis seed.
"""
from __future__ import annotations

import logging
import os
from typing import Any, Callable, Dict, List, Optional

from ..common.constants import DOMAIN_LEDGER_ID, POOL_LEDGER_ID
from ..common.event_bus import InternalBus
from ..common.messages.internal_messages import (
    CatchupFinished,
    MissingMessage,
    RequestPropagates,
)
from ..common.messages.node_messages import (
    Ordered,
    Propagate,
    Reply,
    RequestAck,
    RequestNack,
)
from ..common.exceptions import InvalidClientRequest
from ..common.metrics_collector import MetricsCollector, MetricsName
from ..common.request import Request
from ..common.stashing_router import StashingRouter
from ..common.txn_util import get_from, get_req_id
from ..common.timer import RepeatingTimer, TimerService
from ..config import Config, getConfig
from ..observability.trace import _NO_SPAN
from ..storage.req_id_to_txn import ReqIdrToTxn
from .client_authn import CoreAuthNr
from .consensus.checkpoint_service import CheckpointService
from .consensus.consensus_shared_data import ConsensusSharedData
from .consensus.message_req_service import MessageReqService
from .consensus.ordering_service import OrderingService, RequestsPool
from .consensus.primary_connection_monitor_service import (
    PrimaryConnectionMonitorService,
)
from .consensus.primary_selector import (
    RoundRobinConstantNodesPrimariesSelector,
)
from .consensus.view_change_service import ViewChangeService
from .consensus.view_change_trigger_service import ViewChangeTriggerService
from .ledgers_bootstrap import LedgersBootstrap, NodeStorage
from .propagator import Propagator
from .request_managers.write_request_manager import NodeExecutor

logger = logging.getLogger(__name__)


class NodeRequestsPool(RequestsPool):
    """Per-INSTANCE finalised-request queues (replaces the simulation's
    shared-pool fiction). Requests are pinned here until this instance
    orders them: the master may execute and GC the propagator's copy while
    a backup instance is still ordering the same request independently."""

    def __init__(self, propagator: Propagator, classify,
                 bound: Optional[int] = None):
        self._propagator = propagator
        self._classify = classify  # Request -> ledger_id
        self._bound = bound  # drop-oldest cap (backup instances)
        self._queues: Dict[int, List[str]] = {}
        self._by_digest: Dict[str, Request] = {}

    def enqueue(self, request: Request) -> None:
        lid = self._classify(request)
        if lid is None:
            lid = DOMAIN_LEDGER_ID
        q = self._queues.setdefault(lid, [])
        q.append(request.digest)
        self._by_digest[request.digest] = request
        if self._bound is not None and len(q) > self._bound:
            dropped = q.pop(0)
            self._by_digest.pop(dropped, None)

    def pop_ready(self, ledger_id: int, max_count: int) -> List[Request]:
        q = self._queues.get(ledger_id, [])
        take, self._queues[ledger_id] = q[:max_count], q[max_count:]
        return [self._by_digest.get(d) or self._propagator.get(d)
                for d in take]

    def get(self, digest: str) -> Optional[Request]:
        return self._by_digest.get(digest) or self._propagator.get(digest)

    def has_ready(self, ledger_id: int) -> bool:
        return bool(self._queues.get(ledger_id))

    def ledger_ids_with_ready(self) -> List[int]:
        return [lid for lid, q in self._queues.items() if q]

    def mark_ordered(self, digests) -> None:
        gone = set(digests)
        for lid, q in self._queues.items():
            self._queues[lid] = [d for d in q if d not in gone]
        for d in gone:
            self._by_digest.pop(d, None)


class Node:
    """One validator: ingress + propagation + consensus + execution."""

    def __init__(self,
                 name: str,
                 validators: List[str],
                 timer: TimerService,
                 network,  # provides create_peer(name) -> ExternalBus
                 config: Optional[Config] = None,
                 storage: Optional[NodeStorage] = None,
                 pool_genesis: Optional[List[Dict]] = None,
                 domain_genesis: Optional[List[Dict]] = None,
                 seed_keys: Optional[Dict[str, str]] = None,
                 bls_keys=None,
                 vote_plane=None,
                 drive_quorum_ticks: bool = True,
                 num_instances: int = 1,
                 metrics=None,
                 backup_vote_plane_factory=None,
                 trace=None):
        self.name = name
        self.config = config or getConfig()
        self.timer = timer
        # injectable: pass a NullMetricsCollector to disable collection,
        # or a shared collector to aggregate across components
        self.metrics = metrics if metrics is not None else MetricsCollector()
        # consensus flight recorder: a pool composition injects its
        # shared virtual-clock recorder (deterministic traces); a
        # standalone deployed node builds its own on perf_counter when
        # config enables it (real durations, no determinism claim)
        from ..observability.trace import NULL_TRACE, TraceRecorder

        if trace is not None:
            self.trace = trace
        elif self.config.TraceRecorderEnabled:
            import time as _time

            self.trace = TraceRecorder(
                _time.perf_counter,
                capacity=self.config.TraceRecorderCapacity, node=name)
        else:
            self.trace = NULL_TRACE
        # f+1 protocol instances (RBFT): instance i's primary is offset i
        # in the round-robin; only the master (inst 0) executes
        if num_instances <= 0:
            num_instances = self.config.replicas_count(len(validators))
        self.num_instances = num_instances
        self.data = ConsensusSharedData(
            name, validators, inst_id=0, is_master=True,
            log_size=self.config.LOG_SIZE)
        selector = RoundRobinConstantNodesPrimariesSelector(validators)
        self.data.primaries = selector.select_primaries(0, num_instances)

        self.internal_bus = InternalBus()
        self.external_bus = network.create_peer(name)
        self.stasher = StashingRouter(
            limit=1000, buses=[self.internal_bus, self.external_bus])
        # 3PC traffic is demuxed by instId BEFORE any router runs: the
        # master's per-instance services get their own router (registered
        # as instance 0) and backups register theirs, so an inbound
        # PREPARE costs one dict hop + one router pass, not one pass per
        # live instance (reference: Node.sendToReplica)
        from .instance_demux import Instance3PCDemux

        self.demux = Instance3PCDemux(self.external_bus)
        self.stasher3pc = StashingRouter(
            limit=1000, buses=[self.internal_bus])
        self.demux.register(0, self.stasher3pc)

        # --- persistence + execution -----------------------------------
        self.boot = LedgersBootstrap(
            storage=storage, pool_genesis=pool_genesis,
            domain_genesis=domain_genesis, config=self.config).build()
        self.boot.write_manager.metrics = self.metrics
        self.executor = NodeExecutor(
            self.boot.write_manager,
            get_view_info=lambda: (self.data.view_no,
                                   list(self.data.primaries)))
        self.req_idr_to_txn = ReqIdrToTxn()
        from .request_managers.read_request_manager import (
            ReadRequestManager,
        )

        self.read_manager = ReadRequestManager(
            self.boot.db, bls_multi_sig_getter=self._find_multi_sig)
        from .request_managers.action_request_manager import (
            ActionRequestManager,
        )

        self.restart_requested = False

        def _restart_sink():
            self.restart_requested = True  # composition reacts (test flag
            # in-process; a deployment wires a process-exit/systemd hook)

        self.action_manager = ActionRequestManager(
            node_status_provider=self.node_status,
            get_nym_data=self.boot.nym_handler.get_nym_data,
            timer=timer, restart_sink=_restart_sink)
        from collections import deque

        self._seen_action_digests = deque(maxlen=1000)

        # --- ingress: state-backed authn + propagation ------------------
        self.authnr = CoreAuthNr(verkey_source=self.boot.nym_handler,
                                 seed_keys=seed_keys)
        self.propagator = Propagator(
            name, lambda: self.data.quorums, self.external_bus,
            on_finalised=self._on_request_finalised,
            on_needs_auth=self._enqueue_for_auth,
            is_already_committed=lambda r: self.req_idr_to_txn
            .get_by_payload_digest(r.payload_digest) is not None,
            is_validator=lambda s: s in self.data.validators)
        self.requests_pool = NodeRequestsPool(
            self.propagator,
            classify=self.boot.write_manager.ledger_id_for_request)
        self.stasher.subscribe(Propagate, self.propagator.process_propagate)
        # _auth_queue holds RELAYED propagates (consensus traffic — never
        # shed); with admission control on, CLIENT writes queue in the
        # bounded AdmissionController instead and overflow sheds
        # deterministically (ingress plane, README "Ingress plane")
        self._auth_queue: List[Request] = []
        self.admission = None
        if self.config.IngressQueueCapacity > 0:
            from ..ingress.admission import AdmissionController

            self.admission = AdmissionController(
                capacity=self.config.IngressQueueCapacity,
                per_client_cap=self.config.IngressPerClientCap,
                seed=self.config.IngressShedSeed,
                clock=timer.get_current_time)
        # client message surface: digest -> client id, and the outbound
        # client messages (REQACK/REQNACK/REPLY) a transport would deliver
        self._req_clients: Dict[str, str] = {}
        self.client_outbox: List[tuple] = []  # (client_id, message)
        self.replies: Dict[str, Reply] = {}  # digest -> Reply

        # --- BLS --------------------------------------------------------
        self.bls_replica = None
        if bls_keys is not None:
            from ..bls.factory import create_bls_bft_replica
            from ..common.messages.internal_messages import RaisedSuspicion
            from ..utils.base58 import b58encode

            own_kp, pool_keys = bls_keys[name], {
                n: (pk, pop) for n, (kp, pk, pop) in bls_keys.items()}

            def pool_root():
                return b58encode(self.boot.db.get_state(
                    POOL_LEDGER_ID).committed_head_hash)

            def bls_suspicion(ex):
                self.internal_bus.send(RaisedSuspicion(inst_id=0, ex=ex))

            self.bls_replica = create_bls_bft_replica(
                name, own_kp[0], pool_keys,
                pool_state_root_provider=pool_root,
                suspicion_sink=bls_suspicion)

        # --- state-proof plane ------------------------------------------
        # per stabilized checkpoint window, cache the pool's BLS
        # multi-sig over the committed domain roots (consensus already
        # aggregated it) and serve externally-verifiable reads against
        # that window via a proof-attaching ReadService. The client
        # reply surface still serves SMT reads (read_manager); wiring
        # ReadService into it is the ROADMAP phase-2 item — the service
        # here is the bench/scripts/pool surface.
        self.proof_cache = None
        self.read_service = None
        if self.bls_replica is not None \
                and self.config.StateProofCacheWindows > 0:
            from ..ingress.read_service import LedgerBacking, ReadService
            from ..proofs import CheckpointProofCache

            self.proof_cache = CheckpointProofCache.for_domain(
                self.boot.db, self.bls_replica, bus=self.internal_bus,
                keep=self.config.StateProofCacheWindows,
                clock=timer.get_current_time,
                metrics=self.metrics, trace=self.trace, node=name)
            self.read_service = ReadService(
                LedgerBacking(self.boot.db.get_ledger(DOMAIN_LEDGER_ID),
                              bus=self.internal_bus),
                clock=timer.get_current_time, metrics=self.metrics,
                trace=self.trace, proof_cache=self.proof_cache,
                capacity=self.config.IngressReadQueueCapacity,
                seed=self.config.IngressShedSeed)

        # --- consensus services -----------------------------------------
        self.ordering = OrderingService(
            data=self.data, timer=timer, bus=self.internal_bus,
            network=self.external_bus, stasher=self.stasher3pc,
            executor=self.executor, requests=self.requests_pool,
            config=self.config, vote_plane=vote_plane,
            bls=self.bls_replica, trace=self.trace)
        self.checkpoints = CheckpointService(
            data=self.data, bus=self.internal_bus,
            network=self.external_bus, stasher=self.stasher3pc,
            config=self.config, vote_plane=vote_plane)
        self.view_changer = ViewChangeService(
            data=self.data, timer=timer, bus=self.internal_bus,
            network=self.external_bus, stasher=self.stasher,
            checkpoint_values_provider=self.checkpoints.own_checkpoint_values,
            config=self.config)
        self.vc_trigger = ViewChangeTriggerService(
            data=self.data, timer=timer, bus=self.internal_bus,
            network=self.external_bus, stasher=self.stasher,
            config=self.config)
        self.primary_monitor = PrimaryConnectionMonitorService(
            data=self.data, timer=timer, bus=self.internal_bus,
            network=self.external_bus, config=self.config)
        self.message_req = MessageReqService(
            data=self.data, bus=self.internal_bus,
            network=self.external_bus, ordering_service=self.ordering,
            view_change_service=self.view_changer,
            propagator=self.propagator)

        # --- pool membership from the pool ledger ------------------------
        from .pool_manager import PoolManager

        self.pool_manager = PoolManager(
            name, self.data,
            bls_key_register=(self.bls_replica.key_register
                              if self.bls_replica else None),
            on_membership_changed=self._on_membership_changed)
        self.pool_manager.init_from_ledger(
            self.boot.db.get_ledger(POOL_LEDGER_ID))
        # composition hook: transports / vote planes react to membership
        self.on_membership_changed_hook: Optional[Callable] = None

        # --- catchup ----------------------------------------------------
        from ..common.messages.internal_messages import RaisedSuspicion
        from .catchup import NodeLeecherService, SeederService

        self.seeder = SeederService(
            self.external_bus, self.boot.db, own_name=name,
            timer=timer, config=self.config, metrics=self.metrics)

        def catchup_suspicion(ex):
            self.internal_bus.send(RaisedSuspicion(inst_id=0, ex=ex))

        self.leecher = NodeLeecherService(
            data=self.data, bus=self.internal_bus,
            network=self.external_bus, timer=timer, bootstrap=self.boot,
            config=self.config, suspicion_sink=catchup_suspicion,
            metrics=self.metrics, trace=self.trace)

        # --- RBFT: monitor + backup instances ----------------------------
        from ..common.messages.internal_messages import (
            ViewChangeFinished,
            ViewChangeStarted,
        )
        from .monitor import Monitor
        from .replicas import Replicas

        self.monitor = Monitor(name, timer, self.internal_bus, self.config,
                               num_instances=num_instances,
                               metrics=self.metrics, trace=self.trace)
        # backup pools are bounded drop-oldest: a stalled backup primary
        # must read as a SLOW instance, not as unbounded node memory
        self.replicas = Replicas(
            name, lambda: self.data.validators, timer, self.external_bus,
            self.config,
            make_requests_pool=lambda: NodeRequestsPool(
                self.propagator,
                classify=self.boot.write_manager.ledger_id_for_request,
                bound=10 * self.config.LOG_SIZE),
            on_backup_ordered=self._on_backup_ordered,
            forward_request_propagates=self._on_request_propagates,
            num_instances=num_instances,
            vote_plane_factory=backup_vote_plane_factory,
            demux=self.demux)
        if num_instances > 1:
            self.replicas.build(0, self.data.primaries)
        self.internal_bus.subscribe(ViewChangeStarted,
                                    self._on_view_change_started)
        self.internal_bus.subscribe(ViewChangeFinished,
                                    self._on_view_change_finished)

        # --- execution + client replies ---------------------------------
        self.ordered_log: List[Ordered] = []
        self.executed_upto = self.executor.committed_seq()
        self.internal_bus.subscribe(Ordered, self._on_ordered)
        self.internal_bus.subscribe(CatchupFinished,
                                    self._on_catchup_finished)
        self.internal_bus.subscribe(RequestPropagates,
                                    self._on_request_propagates)
        # bounded byzantine-evidence log (observability; the view-change
        # vote on primary-convicting codes lives in the trigger service)
        from collections import deque

        self.suspicions = deque(maxlen=1000)
        self.internal_bus.subscribe(RaisedSuspicion,
                                    self._on_raised_suspicion)

        self._ingress_timer = RepeatingTimer(
            timer, self.config.PropagateBatchWait, self._flush_auth_queue,
            active=False)
        # tick-batched quorum mode for a standalone vote plane; a pool
        # composition that shares a grouped plane drives ticks itself.
        # Over the zstack transport this tick is the deployed node's
        # dispatch barrier: the Looper drains every pending socket read
        # (handlers enqueue signed ingress + record votes) BEFORE timer
        # events fire, so the tick always evaluates a drained transport.
        self._quorum_tick_timer = None
        self._dispatch_governor = None
        if (drive_quorum_ticks and vote_plane is not None
                and self.config.QuorumTickInterval > 0):
            vote_plane.defer_flush_on_query = True
            from ..tpu.governor import DispatchGovernor

            self._dispatch_governor = DispatchGovernor.from_config(
                self.config, metrics=self.metrics, trace=self.trace)
            interval = (self._dispatch_governor.interval
                        if self._dispatch_governor
                        else self.config.QuorumTickInterval)
            # barrier: deliveries due at the tick instant drain first, so
            # the tick evaluates a complete delivery set (dispatch plane)
            self._quorum_tick_timer = RepeatingTimer(
                timer, interval, self._quorum_tick,
                active=False, barrier=True)
        self.vote_plane = vote_plane

        # --- notifier: operator events -> pluggable sinks ----------------
        from .notifier import NotifierService

        self.notifier = NotifierService(name, self.internal_bus,
                                        timer=timer)

        # --- observers: committed batches pushed to read replicas --------
        from .observer import ObserverRegistry

        self.observer_registry = ObserverRegistry(
            self.external_bus, find_multi_sig=self._find_multi_sig)

        # --- plugins (LAST: entries get a fully constructed node) -------
        from ..plugins import load_plugins

        load_plugins(self, self.config.PluginModules)

    # ------------------------------------------------------------------

    def start(self) -> None:
        self.ordering.start()
        self._ingress_timer.start()
        if self.num_instances > 1:
            if not self.replicas.backups:  # restart after stop()
                self.replicas.build(self.data.view_no, self.data.primaries)
            self.monitor.start()
        if self._quorum_tick_timer is not None:
            self._quorum_tick_timer.start()

    def stop(self) -> None:
        self.ordering.stop()
        self._ingress_timer.stop()
        self.monitor.stop()
        self.replicas.teardown()
        if self._quorum_tick_timer is not None:
            self._quorum_tick_timer.stop()
        # teardown flush: a KV-backed collector loses up to
        # flush_every - 1 events otherwise (no-op on the plain collector)
        self.metrics.close()

    def install_signal_handlers(self,
                                dump_dir: Optional[str] = None) -> bool:
        """Deployed-node flight dump on ``SIGUSR2``: an operator can
        snapshot the ring on a LIVE node (``kill -USR2 <pid>``) without
        stopping it — the handler rides the existing ``trigger_dump``
        path (a ``flight.signal`` mark + bounded ring-tail snapshot) and,
        with ``dump_dir``, writes the full JSONL dump for ``trace_tool``.

        Deliberately NOT called by Node.__init__: only process entry
        points (``scripts/start_node.py``) install handlers — SimPool /
        NodePool / tests must never mutate process-global signal state.
        Returns False (and installs nothing) off the main thread or on
        platforms without SIGUSR2."""
        import signal
        import threading

        if not hasattr(signal, "SIGUSR2") \
                or threading.current_thread() is not threading.main_thread():
            return False

        def _on_usr2(signum, frame):
            self.trace.trigger_dump("signal", node=self.name)
            if dump_dir is not None and self.trace.enabled:
                self.trace.dump(os.path.join(
                    dump_dir, f"{self.name}.flight.jsonl"))

        signal.signal(signal.SIGUSR2, _on_usr2)
        return True

    def _quorum_tick(self) -> None:
        # dispatch-plane order: drain the signed-request ingress (one
        # device auth batch), scatter buffered votes (one grouped device
        # step), then evaluate quorums against the fresh snapshot
        trace_on = self.trace.enabled
        if trace_on:
            with self.trace.span("tick.drain", node=self.name):
                signal = self._flush_auth_queue()
        else:
            signal = self._flush_auth_queue()
        plane = self.vote_plane
        before = (plane.flushes, plane.flush_votes_total,
                  plane.flush_capacity_total, plane.readback_bytes_total)
        plane.sync()
        dispatches = plane.flushes - before[0]
        self.metrics.add_event(MetricsName.DEVICE_DISPATCHES_PER_TICK,
                               dispatches)
        # ordering fast path: the tick's actual device->host transfer —
        # O(newly certified + frontier) in device-eval mode, the full
        # event matrix under the host_eval fallback
        readback_bytes = plane.readback_bytes_total - before[3]
        self.metrics.add_event(MetricsName.DEVICE_READBACK_BYTES,
                               readback_bytes)
        self.metrics.add_event(MetricsName.DEVICE_READBACK_COMPACT,
                               0 if plane.host_eval else 1)
        if trace_on:
            # ring order matters: overlap_report closes a tick bucket at
            # each tick.flush mark, so the readback must precede it
            self.trace.record(
                "flush.readback", cat="dispatch", node=self.name,
                args={"bytes": readback_bytes, "overlapped": False})
            self.trace.record(
                "tick.flush", cat="dispatch", node=self.name,
                args={"dispatches": dispatches,
                      "votes": plane.flush_votes_total - before[1]})
        if self._dispatch_governor is not None:
            if signal is not None:
                # the tick's ingress pressure joins the occupancy the
                # governor already observes (same law as the pool driver)
                self._dispatch_governor.feed_backpressure(signal)
            self._quorum_tick_timer.update_interval(
                self._dispatch_governor.observe(
                    plane.flush_votes_total - before[1],
                    plane.flush_capacity_total - before[2], dispatches,
                    inflight=plane.lagging))
            if trace_on:
                self.trace.record(
                    "tick.governor", cat="dispatch", node=self.name,
                    args={"interval": round(
                        self._dispatch_governor.interval, 9)})
        with self.trace.span("tick.eval", node=self.name,
                             args={"nodes": 1}) if trace_on else _NO_SPAN:
            self.ordering.service_quorum_tick()
            self.checkpoints.service_quorum_tick()
            for backup in self.replicas.backups:
                if backup.vote_plane is not None:
                    backup.ordering.service_quorum_tick()
                    backup.checkpoints.service_quorum_tick()

    # ------------------------------------------------------------------
    # client ingress
    # ------------------------------------------------------------------

    def _find_multi_sig(self, state_root_b58: str) -> Optional[dict]:
        if self.bls_replica is None:
            return None
        found = self.bls_replica.store.get(state_root_b58)
        return found.as_dict() if found else None

    def submit_client_request(self, req: Request,
                              client_id: Optional[str] = None) -> bool:
        """Entry point a client transport calls. Returns False iff the
        request was NACKed synchronously (replay/bad read); authentication
        of writes is asynchronous (device-batched on the ingress tick).
        Reads are served immediately by THIS node — the reply carries the
        proof material that makes one answer trustworthy."""
        if self.action_manager.is_action(req.txn_type):
            return self._handle_action_request(req, client_id)
        if self.read_manager.is_read(req.txn_type):
            if not self.data.is_participating:
                # fail closed: while catching up (or after a FAILED catchup
                # with convicted history) our committed state is not
                # trustworthy — never answer reads from it
                self._to_client(client_id, RequestNack(
                    identifier=req.identifier, reqId=req.reqId,
                    reason="node is catching up; reads unavailable"))
                return False
            try:
                result = self.read_manager.handle(req)
            except InvalidClientRequest as ex:
                self._to_client(client_id, RequestNack(
                    identifier=req.identifier, reqId=req.reqId,
                    reason=str(ex)))
                return False
            except Exception:  # noqa: BLE001 — reads are unauthenticated;
                # a malformed one must NACK, never crash the ingress path
                logger.exception("%s: read request failed", self.name)
                self._to_client(client_id, RequestNack(
                    identifier=req.identifier, reqId=req.reqId,
                    reason="malformed read request"))
                return False
            result.update(identifier=req.identifier, reqId=req.reqId)
            self._to_client(client_id, Reply(result=result))
            return True
        # replay check FIRST: a client retrying an already-committed write
        # (lost REPLY) must learn its fate even while writes are disabled
        seen = self.req_idr_to_txn.get_by_payload_digest(req.payload_digest)
        if seen is not None:
            lid, seq = seen
            self._to_client(client_id, RequestNack(
                identifier=req.identifier, reqId=req.reqId,
                reason=f"already processed: ledger {lid} seqNo {seq}"))
            return False
        # pool-wide write switch (config ledger, POOL_CONFIG): when a
        # trustee disabled writes, every node NACKs write ingress — except
        # POOL_CONFIG itself, or the pool could never be re-enabled
        from ..common.constants import POOL_CONFIG

        if req.txn_type != POOL_CONFIG \
                and not self.boot.pool_config_handler.writes_enabled():
            self._to_client(client_id, RequestNack(
                identifier=req.identifier, reqId=req.reqId,
                reason="pool writes are disabled (POOL_CONFIG)"))
            return False
        if client_id is not None:
            self._req_clients[req.digest] = client_id
        if self.trace.enabled:
            # rid: the "identifier|reqId" pair the wire-level PROPAGATE
            # marks carry (the envelope never sees the digest) — the
            # causal plane's ingress->propagate join key
            self.trace.record("req.ingress", cat="req", node=self.name,
                              key=(req.digest,),
                              args={"rid": "%s|%s" % (req.identifier,
                                                      req.reqId)})
        if self.admission is not None:
            # bounded ingress: the shed decision is made NOW (drop-newest,
            # seeded tiebreak); the client's NACK and the shed accounting
            # ride the next auth flush so the hot path stays one offer call
            return self.admission.offer(req, client_id)
        self._auth_queue.append(req)
        return True

    def _handle_action_request(self, req: Request,
                               client_id: Optional[str]) -> bool:
        """Actions are privileged and rare: authenticate synchronously
        (host path), authorize by role, execute immediately."""

        def nack(reason: str) -> bool:
            self._to_client(client_id, RequestNack(
                identifier=req.identifier, reqId=req.reqId, reason=reason))
            return False

        try:
            verified = self.authnr.authenticate(req)
        except Exception:  # noqa: BLE001 — any auth failure is a NACK
            return nack("signature verification failed")
        if req.identifier not in verified:
            # the AUTHOR must be among the verified signers: authorization
            # reads request.identifier's role, and a multi-sig endorsement
            # by someone else must not let an attacker borrow a privileged
            # identifier (privilege escalation found in review)
            return nack("author did not sign the request")
        # replay protection: actions never hit the ledger dedup, so a
        # captured signed POOL_RESTART would otherwise be replayable
        # forever — require a fresh node-clock timestamp and reject
        # digests seen inside the freshness window
        ts = req.operation.get("timestamp")
        now = self.timer.get_current_time()
        window = self.config.ActionFreshnessWindow
        if not isinstance(ts, (int, float)) or not (
                now - window <= ts <= now + window):
            return nack("action needs a fresh 'timestamp' (node clock, "
                        f"within {window}s)")
        if req.digest in self._seen_action_digests:
            return nack("action replayed")
        self._seen_action_digests.append(req.digest)
        try:
            result = self.action_manager.handle(req)
        except InvalidClientRequest as ex:  # incl. Unauthorized subclass
            return nack(str(ex))
        except Exception:  # noqa: BLE001
            logger.exception("%s: action request failed", self.name)
            return nack("malformed action request")
        result.update(identifier=req.identifier, reqId=req.reqId)
        self._to_client(client_id, Reply(result=result))
        return True

    def node_status(self) -> Dict[str, Any]:
        """VALIDATOR_INFO payload: the operational snapshot."""
        ledgers = {}
        for lid in self.boot.db.ledger_ids:
            ledger = self.boot.db.get_ledger(lid)
            if ledger is not None:
                ledgers[str(lid)] = ledger.size
        return {
            "name": self.name,
            "view_no": self.data.view_no,
            "last_ordered_3pc": list(self.data.last_ordered_3pc),
            "stable_checkpoint": self.data.stable_checkpoint,
            "validators": list(self.data.validators),
            "primaries": list(self.data.primaries),
            "is_participating": self.data.is_participating,
            "ledger_sizes": ledgers,
            "num_instances": self.num_instances,
            "metrics": self.metrics.summary(),
            "recent_events": list(self.notifier.events)[-20:],
        }

    def _enqueue_for_auth(self, req: Request) -> None:
        """Relayed PROPAGATE whose request we haven't authenticated."""
        self._auth_queue.append(req)

    def _flush_auth_queue(self):
        """ONE device batch authenticates everything queued this tick.

        With admission control on, the tick's sheds settle here too —
        under the dedicated ``req.shed`` trace event / ``ingress.shed``
        metric and a client NACK, never under ``AUTH_BATCH_*`` (those
        stats measure only work the device actually verified) — and the
        drain returns the tick's :class:`~indy_plenum_tpu.ingress
        .admission.BackpressureSignal` (pre-drain depth, sheds, leeching)
        so the standalone quorum tick can feed the dispatch governor the
        same pressure the pool-level driver does. Returns ``None`` when
        admission is off."""
        batch, self._auth_queue = self._auth_queue, []
        signal = None
        if self.admission is not None:
            from ..ingress.admission import BackpressureSignal

            depth = self.admission.depth
            admitted, shed = self.admission.drain()
            signal = BackpressureSignal(
                queue_depth=depth,
                capacity=self.admission.capacity,
                shed_delta=len(shed),
                leeching=not self.data.is_participating)
            self.metrics.add_event(MetricsName.INGRESS_QUEUE_DEPTH, depth)
            if admitted:
                self.metrics.add_event(MetricsName.INGRESS_ADMITTED,
                                       len(admitted))
            if shed:
                self.metrics.add_event(MetricsName.INGRESS_SHED,
                                       len(shed))
                for req, _cid, reason in shed:
                    if self.trace.enabled:
                        self.trace.record("req.shed", cat="req",
                                          node=self.name,
                                          key=(req.digest,),
                                          args={"reason": reason})
                    self._to_client(
                        self._req_clients.pop(req.digest, None),
                        RequestNack(
                            identifier=req.identifier, reqId=req.reqId,
                            reason="ingress overloaded: request shed "
                                   f"({reason})"))
            # client writes first, then relayed propagates: both verify
            # in the same device batch either way
            batch = admitted + batch
        if not batch:
            return signal
        if self.trace.enabled:
            # journey hop boundary: admission wait ends, auth batch
            # begins — one mark per request entering the device batch
            for req in batch:
                self.trace.record("req.admitted", cat="req",
                                  node=self.name, key=(req.digest,))
        self.metrics.add_event(MetricsName.AUTH_BATCH_SIZE, len(batch))
        with self.metrics.measure_time(MetricsName.AUTH_BATCH_TIME):
            verdicts = self.authnr.authenticate_batch(batch)
        for req, ok in zip(batch, verdicts):
            client = self._req_clients.get(req.digest)
            if not ok:
                state = self.propagator.requests.get(req.digest)
                if state is not None:
                    state.auth_pending = False
                self._to_client(client, RequestNack(
                    identifier=req.identifier, reqId=req.reqId,
                    reason="signature verification failed"))
                continue
            self._to_client(client, RequestAck(
                identifier=req.identifier, reqId=req.reqId))
            self.propagator.propagate(req, sender_client=client)
        return signal

    def _to_client(self, client_id: Optional[str], msg) -> None:
        if client_id is None:
            return  # relayed request: no client of ours is waiting on it
        self.client_outbox.append((client_id, msg))

    # ------------------------------------------------------------------
    # propagation -> ordering
    # ------------------------------------------------------------------

    def _on_request_finalised(self, request: Request) -> None:
        self.requests_pool.enqueue(request)
        self.ordering.on_request_finalised()
        self.monitor.request_finalised(request.digest)
        if self.trace.enabled:
            self.trace.record("req.finalised", cat="req", node=self.name,
                              key=(request.digest,))
        self.replicas.enqueue_finalised(request)

    def _on_backup_ordered(self, inst_id: int, ordered: Ordered) -> None:
        self.metrics.add_event(MetricsName.BACKUP_ORDERED,
                               len(ordered.reqIdr))
        self.monitor.requests_ordered(inst_id, list(ordered.reqIdr))

    def _on_membership_changed(self, validators: List[str],
                               registry: Dict[str, dict],
                               set_changed: bool = True) -> None:
        """A committed NODE txn changed the pool. ``set_changed`` is False
        for record-only changes (key/address rotation): those rewire the
        transport but must NOT tear down backup instances or reset the
        monitor — a stream of rotation txns would otherwise keep the
        degradation detector's baselines permanently empty."""
        if set_changed:
            primary = self.data.primary_name
            if primary is not None and primary not in validators:
                # the master primary was demoted: it must not keep minting
                # batches the pool accepts — vote it out now (reference:
                # plenum starts a view change when the primary leaves the
                # set)
                from ..common.messages.internal_messages import (
                    VoteForViewChange,
                )
                from .suspicion_codes import Suspicions

                logger.info("%s: primary %s demoted -> vote view change",
                            self.name, primary)
                self.internal_bus.send(VoteForViewChange(
                    suspicion=Suspicions.PRIMARY_DEMOTED))
            if self.num_instances > 1 and self.replicas.backups:
                # live backup instances still hold the old validator set
                # (and would discard the new member's votes) — rebuild
                self.replicas.build(self.data.view_no, self.data.primaries)
                self.monitor.reset(self.num_instances)
        if self.on_membership_changed_hook is not None:
            self.on_membership_changed_hook(validators, registry)

    def _on_raised_suspicion(self, msg, *args) -> None:
        ex = msg.ex
        self.suspicions.append((getattr(ex, "node", None),
                                getattr(ex, "suspicion", None)))

    def _on_view_change_started(self, msg, *args) -> None:
        # backups' votes are void in the new view; they rebuild at finish
        self.replicas.teardown()

    def _on_view_change_finished(self, msg, *args) -> None:
        self.monitor.reset(self.num_instances)
        if self.num_instances > 1:
            self.replicas.build(msg.view_no, self.data.primaries)

    def _on_request_propagates(self, msg: RequestPropagates) -> None:
        """Ordering saw a PRE-PREPARE referencing requests we lack: fetch
        peers' PROPAGATEs (digest-authenticated on the way back)."""
        for digest in msg.bad_requests:
            self.internal_bus.send(MissingMessage(
                msg_type="PROPAGATE", key=digest,
                inst_id=self.data.inst_id, dst=None))

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------

    def _on_ordered(self, ordered: Ordered, *args) -> None:
        self.requests_pool.mark_ordered(ordered.reqIdr)
        self.monitor.requests_ordered(0, list(ordered.reqIdr))
        if ordered.ppSeqNo <= self.executed_upto:
            return  # already executed (re-ordered after view change)
        self.executed_upto = ordered.ppSeqNo
        self.ordered_log.append(ordered)
        self.metrics.add_event(MetricsName.ORDERED_BATCH_SIZE,
                               len(ordered.reqIdr))
        with self.metrics.measure_time(MetricsName.COMMIT_TIME):
            staged = self.executor.commit_batch(ordered.ppSeqNo)
        if self.trace.enabled:
            self.trace.record(
                "3pc.executed", node=self.name,
                key=(ordered.viewNo, ordered.ppSeqNo, ordered.digest))
        if staged is None:
            return
        if self.trace.enabled:
            # the executed -> durable-state-root hop (STATE_PHASE joins
            # this to 3pc.executed per (view, seq) in phase_durations)
            state = self.boot.db.get_state(staged.ledger_id)
            self.trace.record(
                "state.commit", cat="state", node=self.name,
                key=(ordered.viewNo, ordered.ppSeqNo),
                args={"ledger": staged.ledger_id,
                      "hashes": state.hashes_total if state is not None
                      else 0})
        ledger = self.boot.db.get_ledger(staged.ledger_id)
        valid = list(staged.batch.valid_digests)
        first_seq = ledger.size - len(valid) + 1
        committed_txns: List[Dict] = []
        for offset, digest in enumerate(valid):
            seq_no = first_seq + offset
            txn = ledger.get_by_seq_no(seq_no)
            committed_txns.append(txn)
            if staged.ledger_id == POOL_LEDGER_ID:
                # membership authority: committed NODE txns reconfigure
                self.pool_manager.process_committed_txn(txn)
            req = self.propagator.get(digest)
            payload_digest = req.payload_digest if req is not None else digest
            self.req_idr_to_txn.add(
                digest, payload_digest, staged.ledger_id, seq_no)
            reply = Reply(result=dict(
                txn,
                identifier=get_from(txn),
                reqId=get_req_id(txn),
                stateRootHash=ordered.stateRootHash,
                txnRootHash=ordered.txnRootHash))
            self.replies[digest] = reply
            self._to_client(self._req_clients.pop(digest, None), reply)
        self.propagator.gc(list(ordered.reqIdr))
        # read replicas get every committed batch (each-batch sync policy)
        self.observer_registry.push_batch(
            staged.ledger_id, ordered.ppSeqNo, ordered.ppTime,
            committed_txns, ordered.stateRootHash, ordered.txnRootHash)

    def _on_catchup_finished(self, msg: CatchupFinished, *args) -> None:
        self.executed_upto = max(self.executed_upto,
                                 msg.last_caught_up_3pc[1])
        # txns fetched during catchup bypassed the execution hook; the
        # pool ledger may carry membership changes we haven't absorbed
        self.pool_manager.refresh_from_ledger(
            self.boot.db.get_ledger(POOL_LEDGER_ID))

    # ------------------------------------------------------------------

    @property
    def ordered_digests(self) -> List[str]:
        out: List[str] = []
        for o in self.ordered_log:
            out.extend(o.reqIdr)
        return out

    def get_nym_data(self, did: str) -> Optional[Dict[str, Any]]:
        return self.boot.nym_handler.get_nym_data(did, is_committed=True)
