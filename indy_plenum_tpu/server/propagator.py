"""Request dissemination and f+1 finalisation (the PROPAGATE phase).

Reference: plenum/server/propagator.py (`Propagator` mixin + `Requests`
container). A client request received by any node is broadcast as
PROPAGATE(request, clientName); each node counts distinct senders per
request digest (its own PROPAGATE included) and *finalises* the request
once the f+1 propagate quorum is reached — only finalised requests are
eligible for 3PC batching. A node seeing a PROPAGATE for a request it has
not itself relayed relays it, so an honest request reaches quorum even if
the client talked to a single node.

The digest is recomputed locally from the carried request content, so a
byzantine node cannot poison another request's tally: lying about the
digest only creates a tally for the digest its content actually hashes to.

TPU-first note: propagation is pure bookkeeping and stays on the host; the
expensive part of ingress — signature verification — happened before
``propagate()`` via ``CoreAuthNr.authenticate_batch`` on the device.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Set

from ..common.event_bus import ExternalBus
from ..common.messages.node_messages import Propagate
from ..common.request import Request
from ..common.stashing_router import DISCARD, PROCESS

logger = logging.getLogger(__name__)


class ReqState:
    __slots__ = ("request", "propagates", "finalised", "sent",
                 "auth_pending", "sender_client")

    def __init__(self, request: Request):
        self.request = request
        self.propagates: Set[str] = set()  # nodes whose PROPAGATE we saw
        self.finalised = False
        self.sent = False  # our own PROPAGATE broadcast already went out
        self.auth_pending = False  # queued in the node's auth pipeline
        self.sender_client: Optional[str] = None


class Requests(Dict[str, ReqState]):
    """digest -> ReqState (reference: plenum/server/propagator.py Requests)."""

    def add(self, request: Request) -> ReqState:
        state = self.get(request.digest)
        if state is None:
            state = ReqState(request)
            self[request.digest] = state
        return state

    def add_propagate(self, request: Request, sender: str) -> ReqState:
        state = self.add(request)
        state.propagates.add(sender)
        return state

    def votes(self, digest: str) -> int:
        state = self.get(digest)
        return len(state.propagates) if state else 0


class Propagator:
    """One node's propagation engine; plugs into the node's external bus.

    ``on_finalised(request)`` fires exactly once per digest when the f+1
    quorum is reached — the Node routes it into its requests pool.
    """

    def __init__(self,
                 name: str,
                 quorums,
                 network: ExternalBus,
                 on_finalised: Callable[[Request], None],
                 on_needs_auth: Optional[Callable[[Request], None]] = None,
                 is_already_committed: Optional[
                     Callable[[Request], bool]] = None,
                 is_validator: Optional[Callable[[str], bool]] = None):
        self._name = name
        # a Quorums object or a zero-arg provider returning the CURRENT
        # one — membership changes replace the node's Quorums instance,
        # and finalisation must follow the live f+1 threshold
        self._quorums = (quorums if callable(quorums)
                         else (lambda: quorums))
        self._network = network
        self._on_finalised = on_finalised
        # replay floor: once a request executes, its propagator state is
        # GC'd — late-arriving PROPAGATEs must not recreate it and
        # re-finalise the same request into a fresh batch
        self._is_already_committed = is_already_committed or (lambda r: False)
        # only CURRENT validators' propagates count toward f+1 (a demoted
        # node keeps its transport identity but loses its vote)
        self._is_validator = is_validator or (lambda s: True)
        # a relayed request we have NOT authenticated must pass through the
        # node's (device-batched) auth pipeline before we add our own vote:
        # relaying blindly would let f byzantine propagates + our echo
        # finalise an unauthenticated request. None = trust-the-carrier
        # mode for compositions without an authenticator.
        self._on_needs_auth = on_needs_auth
        self.requests = Requests()

    # --- ingress (a client request authenticated by this node) ---------

    def propagate(self, request: Request,
                  sender_client: Optional[str] = None) -> None:
        """Record our own propagate vote and broadcast it (once)."""
        if self._is_already_committed(request):
            return
        state = self.requests.add_propagate(request, self._name)
        if sender_client is not None:
            state.sender_client = sender_client
        if not state.sent:
            state.sent = True
            self._network.send(Propagate(
                request=request.as_dict(),
                senderClient=state.sender_client))
        self._try_finalise(state)

    # --- peer PROPAGATEs ------------------------------------------------

    def process_propagate(self, msg: Propagate, sender: str):
        try:
            request = Request.from_dict(dict(msg.request))
            digest = request.digest
        except Exception as exc:  # noqa: BLE001 — wire data is untrusted
            return DISCARD, f"malformed PROPAGATE: {exc}"
        if self._is_already_committed(request):
            return DISCARD, "request already committed"
        state = self.requests.add(request)
        if self._is_validator(sender):
            state.propagates.add(sender)
        if state.sender_client is None and msg.senderClient:
            state.sender_client = msg.senderClient
        # relay: our own vote is what lets the pool converge when only one
        # node heard the client (reference: Propagator.propagate on receipt)
        if not state.sent and not state.auth_pending:
            if self._on_needs_auth is not None:
                state.auth_pending = True
                self._on_needs_auth(state.request)
            else:
                state.sent = True
                state.propagates.add(self._name)
                self._network.send(Propagate(
                    request=request.as_dict(),
                    senderClient=state.sender_client))
        self._try_finalise(state)
        return PROCESS

    def _try_finalise(self, state: ReqState) -> None:
        if state.finalised:
            return
        if self._quorums().propagate.is_reached(len(state.propagates)):
            state.finalised = True
            logger.debug("%s finalised request %s (%d propagates)",
                         self._name, state.request.digest,
                         len(state.propagates))
            self._on_finalised(state.request)

    # --- recovery: a PRE-PREPARE referenced requests we lack ------------

    def is_finalised(self, digest: str) -> bool:
        state = self.requests.get(digest)
        return bool(state and state.finalised)

    def get(self, digest: str) -> Optional[Request]:
        state = self.requests.get(digest)
        return state.request if state else None

    def find_propagate(self, digest: str) -> Optional[Propagate]:
        """Serve a peer's MessageReq(PROPAGATE, digest) from our container.

        Only requests we VOUCH for are served: ones we propagated ourselves
        (sent => authenticated here) or that reached the f+1 quorum. A
        request merely stored pending authentication must not be servable —
        the fetched reply credits OUR propagate vote at the requester, and
        f byzantine propagates + our unvouched echo would finalise a
        request no honest node ever verified."""
        state = self.requests.get(digest)
        if state is None or not (state.sent or state.finalised):
            return None
        return Propagate(request=state.request.as_dict(),
                         senderClient=state.sender_client)

    def gc(self, digests: List[str]) -> None:
        """Ordered requests leave the container (reference: free after
        execution; MessageReq for them is no longer served)."""
        for d in digests:
            self.pop_state(d)

    def pop_state(self, digest: str) -> Optional[ReqState]:
        return self.requests.pop(digest, None)
