"""Observer: a non-validator read replica fed by the pool.

Reference: plenum/server/observer/ (`ObserverSyncPolicyEachBatch`,
ObservedData) — nodes push each committed batch to registered observers,
which apply it WITHOUT participating in consensus. The TPU-era redesign
makes the push proof-carrying instead of policy-trusted:

- With the pool's BLS keys, ONE validator's push suffices: the attached
  multi-signature co-signs (state_root, txn_root, ledger_id, timestamp),
  and the observer re-applies the txns and checks its OWN recomputed
  roots against the co-signed ones (client/state_proof's
  verify_pool_multi_sig — the same trust anchor proved reads use).
- Without BLS keys it falls back to the reference's quorum shape:
  ``weak_quorum`` (f+1) IDENTICAL pushes from distinct validators.

Out-of-order batches are stashed until their predecessor arrives, so an
observer fed by racing validators still applies the total order.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ..common.constants import POOL_LEDGER_ID
from ..common.messages.node_messages import ObservedData
from ..crypto.bls.bls_crypto import MultiSignature
from ..utils.base58 import b58decode, b58encode
from .ledgers_bootstrap import LedgersBootstrap, NodeStorage

logger = logging.getLogger(__name__)

# bound on stashed future batches (a byzantine feeder must not grow RAM)
MAX_STASHED = 1000


class Observer:
    def __init__(self,
                 name: str,
                 network,  # provides create_peer(name) -> ExternalBus
                 pool_bls_keys: Optional[Dict[str, str]] = None,
                 weak_quorum: Optional[int] = None,
                 storage: Optional[NodeStorage] = None,
                 pool_genesis: Optional[list] = None,
                 domain_genesis: Optional[list] = None,
                 timer=None,
                 pool_size: Optional[int] = None,
                 gap_timeout: float = 5.0,
                 validators: Optional[list] = None):
        """``pool_bls_keys``: node name -> BLS pk b58 (trust anchor for
        single-push mode); ``weak_quorum``: f+1 of the pool, used when no
        BLS keys are available — derived from ``pool_size`` /
        ``validators`` when not given, so constructing an Observer with a
        validator set never silently trusts a single push (round-4
        advisor finding). With ``timer`` + ``pool_size`` the
        observer self-heals gaps: an observer registered mid-stream (or
        one that missed pushes) runs the ordinary catchup plane against
        the validators' seeders instead of stalling forever."""
        self.name = name
        self.boot = LedgersBootstrap(
            storage=storage, pool_genesis=pool_genesis,
            domain_genesis=domain_genesis).build()
        self._bls_keys = dict(pool_bls_keys or {})
        # weak-quorum mode counts only VALIDATOR senders: without this,
        # f+1 arbitrary connected peers (other observers, clients) could
        # co-push fabricated batches whose self-consistent roots pass the
        # re-apply check. BLS keys double as the validator set.
        self._validators = set(validators) if validators is not None \
            else set(self._bls_keys) or None
        if weak_quorum is None:
            n = pool_size if pool_size is not None \
                else len(self._validators or ())
            weak_quorum = (n - 1) // 3 + 1 if n else 1
        self._weak_quorum = max(1, weak_quorum)
        self.bus = network.create_peer(name)
        self.bus.subscribe(ObservedData, self.process_observed_data)
        self.last_applied_pp_seq_no = self.boot.committed_pp_seq_no
        # ppSeqNo -> {digest(batch content) -> (data, senders)}
        self._stashed: Dict[int, Dict[str, Tuple[ObservedData, set]]] = {}
        self.batches_applied = 0
        self.batches_rejected = 0
        self.catchups = 0

        self.leecher = None
        if timer is not None and pool_size is not None:
            from ..common.event_bus import InternalBus
            from ..common.messages.internal_messages import CatchupFinished
            from ..common.timer import RepeatingTimer
            from .catchup import NodeLeecherService
            from .quorums import Quorums

            class _ObserverData:
                """The slice of ConsensusSharedData catchup reads."""

                def __init__(self, obs_name: str, n: int):
                    self.name = obs_name
                    self.quorums = Quorums(n)
                    self.is_participating = False
                    self.view_no = 0
                    self.last_ordered_3pc = (0, 0)
                    self.primaries: list = []

            self._data = _ObserverData(name, pool_size)
            self.internal_bus = InternalBus()
            self.leecher = NodeLeecherService(
                data=self._data, bus=self.internal_bus, network=self.bus,
                timer=timer, bootstrap=self.boot)
            self.internal_bus.subscribe(CatchupFinished,
                                        self._on_catchup_finished)
            self._gap_marker = None
            self._gap_timer = RepeatingTimer(timer, gap_timeout,
                                             self._check_gap)

    def _check_gap(self) -> None:
        """A stall — stashed batches exist but nothing applied between
        two checks — triggers catchup. That covers BOTH shapes: a missing
        predecessor (validators push each batch exactly once, so a missed
        push never resends) AND a present-but-untrusted head (e.g. a
        BLS-mode push whose multi-signature was absent)."""
        if not self._stashed:
            self._gap_marker = None
            return
        marker = (self.last_applied_pp_seq_no, min(self._stashed))
        if marker == self._gap_marker and self.leecher is not None:
            logger.info("%s: push stall at %s; running catchup", self.name,
                        marker)
            self.leecher.start()
            self._gap_marker = None
        else:
            self._gap_marker = marker

    def _on_catchup_finished(self, msg, *args) -> None:
        self.catchups += 1
        self.last_applied_pp_seq_no = max(self.last_applied_pp_seq_no,
                                          self.boot.committed_pp_seq_no)
        for pp in [p for p in self._stashed
                   if p <= self.last_applied_pp_seq_no]:
            del self._stashed[pp]
        self._drain()

    # ------------------------------------------------------------------

    def _content_key(self, data: ObservedData) -> str:
        import hashlib

        from ..common.serializers.serialization import ledger_txn_serializer

        # the TXNS are part of the identity: a byzantine push with
        # genuine roots but fabricated txns must not merge with (and
        # mask) honest pushes for the same batch. Canonical (key-sorted)
        # serialization: honest validators whose txn dicts were built in
        # different insertion orders (live execution vs catchup rebuild)
        # must still merge toward f+1 (round-4 advisor finding). The
        # LEDGER's serializer, not the None-dropping signing one: content
        # identity here must match what _apply hands to ledger.add, or a
        # byzantine first push ({"a":1,"b":None}) could absorb honest
        # senders ({"a":1}) into an entry whose txn root can't verify.
        # Raises on non-JSON txns (mixed-type keys etc.) — the caller
        # treats that as a rejected push, honest txns are JSON by
        # construction (ledger storage is JSON).
        return hashlib.sha256(ledger_txn_serializer.dumps({
            "l": data.ledgerId, "p": data.ppSeqNo,
            "s": data.stateRootHash, "t": data.txnRootHash,
            "x": list(data.txns),
        })).hexdigest()

    def process_observed_data(self, data: ObservedData, sender: str
                              ) -> None:
        if data.ppSeqNo <= self.last_applied_pp_seq_no:
            return  # duplicate push (several validators feed us)
        if len(self._stashed) >= MAX_STASHED \
                and data.ppSeqNo not in self._stashed:
            # bounded stash: evict the FARTHEST-future slot for a nearer
            # batch (refusing the needed next-in-order push would let a
            # far-future flood block honest traffic permanently)
            farthest = max(self._stashed)
            if data.ppSeqNo >= farthest:
                return
            del self._stashed[farthest]
        try:
            key = self._content_key(data)
        except Exception:  # noqa: BLE001 — pushed content is untrusted;
            # a non-JSON-serializable txn (mixed-type dict keys survive
            # msgpack) must reject the push, not crash the service loop
            self.batches_rejected += 1
            return
        slot = self._stashed.setdefault(data.ppSeqNo, {})
        entry = slot.get(key)
        if entry is None:
            slot[key] = (data, {sender})
        else:
            entry[1].add(sender)
        self._drain()

    def _drain(self) -> None:
        while True:
            nxt = self.last_applied_pp_seq_no + 1
            slot = self._stashed.get(nxt)
            if not slot:
                return
            applied = False
            for key, (data, senders) in list(slot.items()):
                if not self._trusted(data, senders):
                    continue
                if self._apply(data):
                    applied = True
                    break
                # garbage despite passing the trust gate (e.g. a valid
                # multi-sig over roots but fabricated txns): discard ONLY
                # this entry — an honest push for the same batch may sit
                # (or arrive) under a different content key
                self.batches_rejected += 1
                del slot[key]
            if not slot:
                del self._stashed[nxt]  # an empty slot must not mask the
                # gap from the watchdog
            if not applied:
                return  # wait for a proof / more matching pushes
            del self._stashed[nxt]
            self.last_applied_pp_seq_no = nxt
            self.batches_applied += 1

    # ------------------------------------------------------------------

    def _trusted(self, data: ObservedData, senders: set) -> bool:
        if self._bls_keys:
            ms_dict = data.multiSignature
            if not ms_dict:
                return False
            try:
                ms = MultiSignature.from_dict(ms_dict)
            except Exception:  # noqa: BLE001 — pushed content is untrusted
                return False
            if ms.value.ledger_id != data.ledgerId \
                    or ms.value.state_root_hash != data.stateRootHash \
                    or ms.value.txn_root_hash != data.txnRootHash:
                return False
            from ..client.state_proof import verify_pool_multi_sig

            n = len(self._bls_keys)
            return verify_pool_multi_sig(
                ms, self._bls_keys,
                min_participants=n - (n - 1) // 3)
        if self._validators is None:
            return False  # weak mode with no validator set: trust nothing
        return len(senders & self._validators) >= self._weak_quorum

    def _apply(self, data: ObservedData) -> bool:
        """Re-apply the batch and check our OWN roots against the
        (verified) claimed ones — an observer never trusts content it can
        recompute."""
        ledger = self.boot.db.get_ledger(data.ledgerId)
        state = self.boot.db.get_state(data.ledgerId)
        pre_size = ledger.size
        pre_state = state.head_hash if state is not None else None
        try:
            for txn in data.txns:
                ledger.add(dict(txn))
                self.boot._update_state_for(txn)
            if data.txnRootHash is not None \
                    and b58encode(ledger.root_hash) != data.txnRootHash:
                raise ValueError("txn root mismatch")
            if state is not None and data.stateRootHash is not None \
                    and b58encode(state.head_hash) != data.stateRootHash:
                raise ValueError("state root mismatch")
        except Exception as exc:  # noqa: BLE001 — pushed content is
            # untrusted; roll back whatever half-applied
            logger.warning("%s: observed batch %d rejected: %s",
                           self.name, data.ppSeqNo, exc)
            ledger.reset_to(pre_size)
            if state is not None and pre_state is not None:
                state.set_head_hash(pre_state)
            return False
        if state is not None:
            state.commit()
        if data.ledgerId == POOL_LEDGER_ID:
            pass  # observers track membership reads via get_node_data
        return True

    # ------------------------------------------------------------------

    def get_nym_data(self, did: str):
        return self.boot.nym_handler.get_nym_data(did, is_committed=True)


class ObserverRegistry:
    """The validator-side half: push each committed batch to registered
    observers (reference: Node.send_to_observers)."""

    def __init__(self, external_bus, find_multi_sig=None):
        self._bus = external_bus
        self._find_multi_sig = find_multi_sig or (lambda root: None)
        self.observers: List[str] = []

    def add(self, name: str) -> None:
        if name not in self.observers:
            self.observers.append(name)

    def remove(self, name: str) -> None:
        if name in self.observers:
            self.observers.remove(name)

    def push_batch(self, ledger_id: int, pp_seq_no: int, pp_time,
                   txns: List[dict], state_root_b58: Optional[str],
                   txn_root_b58: Optional[str]) -> None:
        if not self.observers:
            return
        self._bus.send(ObservedData(
            ledgerId=ledger_id,
            ppSeqNo=pp_seq_no,
            ppTime=pp_time,
            txns=[dict(t) for t in txns],
            stateRootHash=state_root_b58,
            txnRootHash=txn_root_b58,
            multiSignature=self._find_multi_sig(state_root_b58)
            if state_root_b58 else None,
        ), list(self.observers))
