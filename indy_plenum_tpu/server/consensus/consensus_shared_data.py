"""Shared per-protocol-instance consensus state.

Reference: plenum/server/consensus/consensus_shared_data.py
(`ConsensusSharedData`) and plenum/server/consensus/batch_id.py (`BatchID`).
One instance of this object is shared by the ordering / checkpoint /
view-change services of a single protocol instance (replica); it is the
single source of truth for view number, primaries, watermarks and
in-flight batch certificates.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ...common.messages.node_messages import PrePrepare
from ..quorums import Quorums

# BatchID = [view_no, pp_view_no, pp_seq_no, pp_digest] (plain list on the
# wire; helpers in node_messages). Stored here as tuples for hashability.
BatchID = Tuple[int, int, int, str]


def preprepare_to_batch_id(pp: PrePrepare) -> BatchID:
    orig = pp.originalViewNo if pp.originalViewNo is not None else pp.viewNo
    return (pp.viewNo, orig, pp.ppSeqNo, pp.digest)


class ConsensusSharedData:
    def __init__(self, name: str, validators: List[str], inst_id: int,
                 is_master: bool = True, log_size: int = 300):
        self.name = name
        self.inst_id = inst_id
        self.is_master = is_master
        self.log_size = log_size

        self.view_no = 0
        self.waiting_for_new_view = False
        self.primaries: List[str] = []
        self.legacy_vc_in_progress = False

        self.validators: List[str] = []
        self.quorums: Quorums = Quorums(len(validators) or 1)
        self.set_validators(validators)

        # watermarks: batches may be 3PC-processed for h < seqNo <= H
        self.low_watermark = 0
        self.stable_checkpoint = 0

        # certificates held by this replica (ordered lists of BatchID)
        self.preprepared: List[BatchID] = []
        self.prepared: List[BatchID] = []

        self.last_ordered_3pc: Tuple[int, int] = (0, 0)
        self.last_completed_view_no = 0
        self.pp_seq_no = 0  # last pp seq no this primary assigned

        # node-level flags the services consult
        self.is_participating = True  # False while catching up
        self.is_synced = True
        self.node_mode_ready = True

        self.prev_view_prepare_cert: Optional[int] = None

    # --- validators / primaries ------------------------------------------

    def set_validators(self, validators: List[str]) -> None:
        self.validators = list(validators)
        self.quorums = Quorums(len(validators))

    @property
    def total_nodes(self) -> int:
        return len(self.validators)

    @property
    def primary_name(self) -> Optional[str]:
        if self.inst_id < len(self.primaries):
            return self.primaries[self.inst_id]
        return None

    def is_primary(self, name: Optional[str] = None) -> bool:
        return (name or self.name) == self.primary_name

    @property
    def is_primary_in_view(self) -> bool:
        return self.primary_name == self.name

    # --- watermarks -------------------------------------------------------

    @property
    def high_watermark(self) -> int:
        return self.low_watermark + self.log_size

    def is_in_watermarks(self, pp_seq_no: int) -> bool:
        return self.low_watermark < pp_seq_no <= self.high_watermark

    # --- certificates -----------------------------------------------------

    def preprepare_batch(self, bid: BatchID) -> None:
        if bid not in self.preprepared:
            self.preprepared.append(bid)

    def prepare_batch(self, bid: BatchID) -> None:
        if bid not in self.prepared:
            self.prepared.append(bid)

    def free_batch(self, bid: BatchID) -> None:
        if bid in self.preprepared:
            self.preprepared.remove(bid)
        if bid in self.prepared:
            self.prepared.remove(bid)

    def free_upto(self, pp_seq_no: int) -> None:
        self.preprepared = [b for b in self.preprepared if b[2] > pp_seq_no]
        self.prepared = [b for b in self.prepared if b[2] > pp_seq_no]

    def clear_batches(self) -> None:
        self.preprepared.clear()
        self.prepared.clear()

    def __repr__(self):
        return (f"ConsensusSharedData({self.name}, inst={self.inst_id}, "
                f"view={self.view_no}, h={self.low_watermark})")
