"""Checkpointing: periodic digests, stabilization, watermark advance.

Reference: plenum/server/consensus/checkpoint_service.py
(`CheckpointService`). Every CHK_FREQ ordered batches the replica emits
CHECKPOINT(seqNoEnd, digest); on a quorum (n-f-1 others + own match) the
checkpoint becomes *stable*: 3PC logs at or below it are garbage-collected
and the watermarks advance (emitted as ``CheckpointStabilized`` for the
OrderingService). If f+1 nodes checkpoint beyond our high watermark we are
lagging and need catchup (``NeedMasterCatchup``).
"""
from __future__ import annotations

import hashlib
import logging
from typing import Dict, Optional, Tuple

from ...common.event_bus import ExternalBus, InternalBus
from ...common.messages.internal_messages import (
    CatchupFinished,
    CheckpointStabilized,
    NeedMasterCatchup,
    ViewChangeStarted,
)
from ...common.messages.node_messages import Checkpoint, Ordered
from ...common.stashing_router import (
    DISCARD,
    PROCESS,
    StashingRouter,
)
from .consensus_shared_data import ConsensusSharedData

logger = logging.getLogger(__name__)

CheckpointKey = Tuple[int, int, str]  # (view_no, seq_no_end, digest)


class CheckpointService:
    def __init__(self,
                 data: ConsensusSharedData,
                 bus: InternalBus,
                 network: ExternalBus,
                 stasher: StashingRouter,
                 config=None,
                 vote_plane=None,
                 shadow_check: bool = False,
                 barrier=None,
                 lane: int = 0):
        from ...config import getConfig

        self._data = data
        self._bus = bus
        self._network = network
        self._stasher = stasher
        self._config = config or getConfig()
        # cross-lane checkpoint barrier (ordering lanes, lanes/barrier.py):
        # when set, a locally-quorate checkpoint window may not stabilize
        # (GC + watermark advance + CheckpointStabilized) until the
        # barrier has SEALED that window across every lane — the lane's
        # ordering then stalls at its high watermark instead of running
        # more than LOG_SIZE past the slowest lane. None = single-lane
        # behaviour, bit-identical to the pre-lanes service.
        self._barrier = barrier
        self._lane = lane
        # device checkpoint tally (tpu.vote_plane). Only digest-matching
        # votes are scattered (the tensor is digest-blind), own vote
        # included per the vote-inclusion contract: device n-f == host
        # n-f-1 others + own.
        self._vote_plane = vote_plane
        self._shadow_check = shadow_check
        # tick-batched mode: a stabilization attempt that fails against the
        # stale snapshot is retried on the next tick (see service_tick)
        self._tick_mode = (vote_plane is not None
                           and self._config.QuorumTickInterval > 0)
        self._dirty_stabilize: set = set()  # (view_no, seq_no_end)

        # digests of ordered batches since the last checkpoint boundary
        self._digests_since: list[str] = []
        self._own_checkpoints: Dict[int, Checkpoint] = {}  # seqNoEnd -> msg
        # votes: (view, seq_end, digest) -> set of senders
        self._received: Dict[CheckpointKey, set] = {}

        stasher.subscribe(Checkpoint, self.process_checkpoint)
        bus.subscribe(Ordered, self.process_ordered)
        bus.subscribe(ViewChangeStarted, self.process_view_change_started)
        bus.subscribe(CatchupFinished, self.process_catchup_finished)

    @property
    def _chk_freq(self) -> int:
        return self._config.CHK_FREQ

    # ------------------------------------------------------------------

    def process_ordered(self, ordered: Ordered, *args) -> None:
        if ordered.instId != self._data.inst_id:
            return
        self._digests_since.append(ordered.digest or "")
        seq_no = ordered.ppSeqNo
        if seq_no % self._chk_freq == 0:
            self._make_checkpoint(ordered.viewNo, seq_no)

    def _make_checkpoint(self, view_no: int, seq_no_end: int) -> None:
        digest = hashlib.sha256(
            "".join(self._digests_since).encode()).hexdigest()
        self._digests_since.clear()
        cp = Checkpoint(
            instId=self._data.inst_id,
            viewNo=view_no,
            seqNoStart=max(1, seq_no_end - self._chk_freq + 1),
            seqNoEnd=seq_no_end,
            digest=digest,
        )
        self._own_checkpoints[seq_no_end] = cp
        logger.debug("%s checkpoint at %d", self._data.name, seq_no_end)
        if self._vote_plane is not None:
            self._vote_plane.record_checkpoint_vote(
                self._data.name, seq_no_end, self._chk_freq)
            # replay received votes that arrived before our own checkpoint
            # existed (only now can their digests be validated)
            key = (view_no, seq_no_end, cp.digest)
            for sender in self._received.get(key, ()):
                self._vote_plane.record_checkpoint_vote(
                    sender, seq_no_end, self._chk_freq)
        self._network.send(cp)
        self._try_stabilize(view_no, seq_no_end)

    def process_checkpoint(self, cp: Checkpoint, sender: str):
        if getattr(cp, "instId", self._data.inst_id) != self._data.inst_id:
            return DISCARD, "other instance"
        if sender not in self._data.validators:
            return DISCARD, "CHECKPOINT from non-validator"
        if cp.viewNo < self._data.view_no:
            return DISCARD, "old view"
        if cp.seqNoEnd <= self._data.stable_checkpoint:
            return DISCARD, "already stable"
        key: CheckpointKey = (cp.viewNo, cp.seqNoEnd, cp.digest)
        self._received.setdefault(key, set()).add(sender)
        if self._vote_plane is not None:
            own = self._own_checkpoints.get(cp.seqNoEnd)
            if own is not None and own.viewNo == cp.viewNo \
                    and own.digest == cp.digest:
                self._vote_plane.record_checkpoint_vote(
                    sender, cp.seqNoEnd, self._chk_freq)
        self._check_lag(cp.viewNo, cp.seqNoEnd)
        self._try_stabilize(cp.viewNo, cp.seqNoEnd)
        return PROCESS

    def _has_quorum(self, view_no: int, seq_no_end: int, digest: str) -> bool:
        key: CheckpointKey = (view_no, seq_no_end, digest)
        host = self._data.quorums.checkpoint.is_reached(
            len(self._received.get(key, set())))
        if self._vote_plane is None:
            return host
        dev = (view_no == self._data.view_no
               and self._vote_plane.has_checkpoint_quorum(
                   seq_no_end, self._chk_freq))
        if self._shadow_check:
            assert dev == host, (
                "checkpoint quorum divergence", key, dev, host)
        return dev

    def service_quorum_tick(self) -> None:
        """Tick-batched mode: retry stabilizations that failed against the
        previous snapshot (the caller has already synced the vote plane)."""
        if not self._dirty_stabilize:
            return
        pending, self._dirty_stabilize = self._dirty_stabilize, set()
        for view_no, seq_no_end in sorted(pending):
            if seq_no_end > self._data.stable_checkpoint:
                self._try_stabilize(view_no, seq_no_end)

    def _try_stabilize(self, view_no: int, seq_no_end: int) -> None:
        own = self._own_checkpoints.get(seq_no_end)
        if own is None or own.viewNo != view_no:
            return
        if self._tick_mode:
            self._dirty_stabilize.add((view_no, seq_no_end))
        if not self._has_quorum(view_no, seq_no_end, own.digest):
            # byzantine check: quorum formed on a DIFFERENT digest for the
            # same seqNoEnd means we diverged
            for (v, s, d), senders in self._received.items():
                if v == view_no and s == seq_no_end and d != own.digest \
                        and self._data.quorums.checkpoint.is_reached(
                            len(senders)):
                    logger.warning("%s checkpoint digest divergence at %d",
                                   self._data.name, seq_no_end)
                    self._bus.send(NeedMasterCatchup())
            return
        self._mark_stable(view_no, seq_no_end)

    def _mark_stable(self, view_no: int, seq_no_end: int) -> None:
        if seq_no_end <= self._data.stable_checkpoint:
            return
        if self._barrier is not None:
            own = self._own_checkpoints.get(seq_no_end)
            digest = own.digest if own is not None else ""
            admitted = self._barrier.offer(
                self._lane, self._data.name, seq_no_end, digest,
                lambda: self._finish_stable(view_no, seq_no_end))
            if not admitted:
                return  # held: released when the barrier seals the window
        self._finish_stable(view_no, seq_no_end)

    def _finish_stable(self, view_no: int, seq_no_end: int) -> None:
        if seq_no_end <= self._data.stable_checkpoint:
            return
        logger.debug("%s stable checkpoint %d", self._data.name, seq_no_end)
        # GC own/received checkpoint state at or below
        self._own_checkpoints = {
            s: c for s, c in self._own_checkpoints.items() if s > seq_no_end}
        self._received = {
            k: v for k, v in self._received.items() if k[1] > seq_no_end}
        self._bus.send(CheckpointStabilized(
            inst_id=self._data.inst_id,
            last_stable_3pc=(view_no, seq_no_end)))
        if self._vote_plane is not None:
            # the bus dispatch above slid the plane's window (zeroing all
            # checkpoint columns); re-scatter the surviving votes for
            # boundaries above the new stable point
            for seq, own in self._own_checkpoints.items():
                if own.viewNo != self._data.view_no:
                    continue
                self._vote_plane.record_checkpoint_vote(
                    self._data.name, seq, self._chk_freq)
                key = (own.viewNo, seq, own.digest)
                for sender in self._received.get(key, ()):
                    self._vote_plane.record_checkpoint_vote(
                        sender, seq, self._chk_freq)

    def _check_lag(self, view_no: int, seq_no_end: int) -> None:
        """f+1 distinct nodes checkpointing beyond our H => we are behind."""
        if seq_no_end <= self._data.high_watermark:
            return
        voters = set()
        for (v, s, _), senders in self._received.items():
            if s > self._data.high_watermark:
                voters |= senders
        if self._data.quorums.weak.is_reached(len(voters)):
            logger.info("%s lagging (checkpoints beyond H=%d) -> catchup",
                        self._data.name, self._data.high_watermark)
            self._bus.send(NeedMasterCatchup())

    def process_view_change_started(self, msg: ViewChangeStarted) -> None:
        # checkpoints from the old view are void (digest chain broken),
        # except the stable one which is carried by the VIEW_CHANGE msgs
        self._digests_since.clear()

    def process_catchup_finished(self, msg: CatchupFinished) -> None:
        """Catchup moved the stable floor (set by the leecher on shared
        data); the digest chain below it is void, votes at/below it are
        stale."""
        _, pp_seq_no = msg.last_caught_up_3pc
        self._digests_since.clear()
        self._own_checkpoints = {
            s: c for s, c in self._own_checkpoints.items() if s > pp_seq_no}
        self._received = {
            k: v for k, v in self._received.items() if k[1] > pp_seq_no}
        if self._barrier is not None:
            # the leeched state is pool-verified up to pp_seq_no: the
            # lane is vacuously ready for every window it covers (the
            # seeders' stabilizations already passed the barrier)
            self._barrier.lane_caught_up(self._lane, pp_seq_no)

    # --- introspection -------------------------------------------------

    def own_checkpoint_values(self) -> list:
        """[(view_no, seqNoEnd, digest)] incl. the stable floor, for
        VIEW_CHANGE messages."""
        out = [(c.viewNo, c.seqNoEnd, c.digest)
               for c in self._own_checkpoints.values()]
        out.append((self._data.view_no, self._data.stable_checkpoint, "stable"))
        return sorted(out, key=lambda t: t[1])
