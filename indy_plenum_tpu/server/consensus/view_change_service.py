"""View change: VIEW_CHANGE collection, NEW_VIEW computation & validation.

Reference: plenum/server/consensus/view_change_service.py
(`ViewChangeService`) and the batch/checkpoint selection math. The
selection functions are pure (unit-test exhaustively — SURVEY.md §7 hard
part #4):

- checkpoint selection: the highest checkpoint value present in >= f+1
  VIEW_CHANGE messages (some honest node has it; safe to start from).
- batch selection, per seqNo above that checkpoint: a batch is selected if
  it is *prepared* in >= 1 collected VIEW_CHANGE AND *preprepared* in >=
  f+1 of them. (A batch ordered anywhere must appear prepared in every
  n-f subset, and weak-quorum preprepare support authenticates the digest.)

All replicas run the same math over the same n-f VIEW_CHANGE set listed in
NEW_VIEW, so validation = recomputation.
"""
from __future__ import annotations

import hashlib
import logging
from typing import Dict, List, Optional, Tuple

from ...common.event_bus import ExternalBus, InternalBus
from ...common.messages.internal_messages import (
    NewViewAccepted,
    NewViewCheckpointsApplied,
    NodeNeedViewChange,
    PrimarySelected,
    ViewChangeFinished,
    ViewChangeStarted,
    VoteForViewChange,
)
from ...common.messages.node_messages import NewView, ViewChange
from ...common.serializers.serialization import serialize_for_signing
from ...common.stashing_router import (
    DISCARD,
    PROCESS,
    STASH_WAITING_VIEW_CHANGE,
    StashingRouter,
)
from ...common.timer import RepeatingTimer, TimerService
from ..quorums import Quorums
from .consensus_shared_data import ConsensusSharedData
from .primary_selector import RoundRobinConstantNodesPrimariesSelector

logger = logging.getLogger(__name__)

CheckpointValue = Tuple[int, int, str]
BatchIDList = List[list]


def view_change_digest(vc: ViewChange) -> str:
    return hashlib.sha256(
        serialize_for_signing(vc.as_dict())).hexdigest()


def calc_checkpoint(view_changes: List[ViewChange],
                    quorums: Quorums) -> Optional[CheckpointValue]:
    """Highest checkpoint supported by >= f+1 VIEW_CHANGEs."""
    counts: Dict[CheckpointValue, int] = {}
    for vc in view_changes:
        # dedup within each VIEW_CHANGE: one sender contributes at most one
        # vote per checkpoint value (else a single byzantine VC listing the
        # same checkpoint f+1 times fabricates weak-quorum support alone).
        # Order-preserving dedup: set iteration is hash-seed-dependent and
        # every replica must compute identical results.
        for cp in dict.fromkeys(map(tuple, vc.checkpoints)):
            counts[cp] = counts.get(cp, 0) + 1
    supported = [cp for cp, cnt in counts.items()
                 if quorums.weak.is_reached(cnt)]
    if not supported:
        return None
    return max(supported, key=lambda cp: cp[1])


def calc_batches(checkpoint: CheckpointValue,
                 view_changes: List[ViewChange],
                 quorums: Quorums) -> BatchIDList:
    """Batches to re-order in the new view, ascending by seqNo."""
    _, cp_seq, _ = checkpoint
    # candidate digests per seqNo with their support
    prepared_by_seq: Dict[int, Dict[str, int]] = {}
    preprepared_by_seq: Dict[int, Dict[str, int]] = {}
    batch_info: Dict[Tuple[int, str], list] = {}
    def _dedup_by_vote_key(batch_ids):
        # one vote per sender per COUNTING key (seq, digest) — deduping on
        # the full batch-id tuple would let a byzantine VC fabricate extra
        # votes by varying the view fields of the same (seq, digest).
        # Order-preserving (dict) so batch_info tie-breaks are identical on
        # every replica regardless of hash seed.
        seen = {}
        for b in batch_ids:
            t = tuple(b)
            seen.setdefault((t[2], t[3]), t)
        return seen.values()

    for vc in view_changes:
        for b in _dedup_by_vote_key(vc.prepared):
            _, pp_view, seq, digest = b
            prepared_by_seq.setdefault(seq, {})
            prepared_by_seq[seq][digest] = \
                prepared_by_seq[seq].get(digest, 0) + 1
            batch_info.setdefault((seq, digest), list(b))
        for b in _dedup_by_vote_key(vc.preprepared):
            _, pp_view, seq, digest = b
            preprepared_by_seq.setdefault(seq, {})
            preprepared_by_seq[seq][digest] = \
                preprepared_by_seq[seq].get(digest, 0) + 1
            batch_info.setdefault((seq, digest), list(b))

    out: BatchIDList = []
    for seq in sorted(set(prepared_by_seq) | set(preprepared_by_seq)):
        if seq <= cp_seq:
            continue
        for digest, prep_cnt in sorted(prepared_by_seq.get(seq, {}).items()):
            pp_cnt = preprepared_by_seq.get(seq, {}).get(digest, 0)
            if prep_cnt >= 1 and quorums.weak.is_reached(pp_cnt):
                out.append(batch_info[(seq, digest)])
                break  # at most one batch per seqNo can satisfy this
    # gaps are allowed to remain: the new primary fills them with its own
    # batches after re-ordering (reference does the same)
    return out


class ViewChangeService:
    def __init__(self,
                 data: ConsensusSharedData,
                 timer: TimerService,
                 bus: InternalBus,
                 network: ExternalBus,
                 stasher: StashingRouter,
                 checkpoint_values_provider=None,
                 config=None):
        from ...config import getConfig

        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._stasher = stasher
        self._config = config or getConfig()
        self._selector = RoundRobinConstantNodesPrimariesSelector(
            lambda: self._data.validators)
        # () -> list of checkpoint values for the VIEW_CHANGE msg
        self._checkpoint_values = checkpoint_values_provider or (
            lambda: [(self._data.view_no, self._data.stable_checkpoint, "stable")])

        self._view_changes: Dict[str, ViewChange] = {}  # sender -> VC
        self._new_view: Optional[NewView] = None
        self._timeout_generation = 0  # invalidates stale NewView timeouts

        stasher.subscribe(ViewChange, self.process_view_change)
        stasher.subscribe(NewView, self.process_new_view)
        bus.subscribe(NodeNeedViewChange, self.process_need_view_change)

    @property
    def name(self) -> str:
        return self._data.name

    # ------------------------------------------------------------------

    def process_need_view_change(self, msg: NodeNeedViewChange) -> None:
        proposed = msg.view_no if msg.view_no is not None \
            else self._data.view_no + 1
        if proposed <= self._data.view_no:
            return
        self.start_view_change(proposed)

    def start_view_change(self, proposed_view_no: int) -> None:
        logger.info("%s starting view change to view %d", self.name,
                    proposed_view_no)
        old_view = self._data.view_no
        self._data.view_no = proposed_view_no
        self._data.waiting_for_new_view = True
        self._data.primaries = self._selector.select_primaries(
            proposed_view_no, max(1, len(self._data.primaries) or 1))
        self._view_changes.clear()
        self._new_view = None

        # ordering service reverts; checkpoint service resets
        self._bus.send(ViewChangeStarted(view_no=proposed_view_no))

        vc = ViewChange(
            viewNo=proposed_view_no,
            stableCheckpoint=self._data.stable_checkpoint,
            prepared=[list(b) for b in self._data.prepared],
            preprepared=[list(b) for b in self._data.preprepared],
            checkpoints=[list(c) for c in self._checkpoint_values()],
        )
        self._view_changes[self.name] = vc
        self._network.send(vc)

        # liveness: if NEW_VIEW does not arrive in time (e.g. the new
        # primary is dead too), vote to skip to the next view
        self._timeout_generation += 1
        generation = self._timeout_generation

        def on_timeout():
            if (self._data.waiting_for_new_view
                    and generation == self._timeout_generation):
                logger.info("%s NEW_VIEW timeout in view %d", self.name,
                            self._data.view_no)
                self._bus.send(VoteForViewChange(
                    suspicion=None, view_no=self._data.view_no + 1))

        self._timer.schedule(self._config.NewViewTimeout, on_timeout)
        self._stasher.process_stashed(STASH_WAITING_VIEW_CHANGE)
        self._try_build_or_validate()

    def process_view_change(self, vc: ViewChange, sender: str):
        if vc.viewNo < self._data.view_no:
            return DISCARD, "old view"
        if vc.viewNo > self._data.view_no:
            return STASH_WAITING_VIEW_CHANGE, "future view"
        if not self._data.waiting_for_new_view:
            return DISCARD, "no view change in progress"
        self._view_changes[sender] = vc
        self._try_build_or_validate()
        return PROCESS

    def process_new_view(self, nv: NewView, sender: str):
        if nv.viewNo < self._data.view_no:
            return DISCARD, "old view"
        if nv.viewNo > self._data.view_no:
            return STASH_WAITING_VIEW_CHANGE, "future view"
        expected_primary = self._selector.select_master_primary(nv.viewNo)
        if sender != expected_primary or nv.primary != expected_primary:
            return DISCARD, "NEW_VIEW not from the expected primary"
        self._new_view = nv
        self._try_build_or_validate()
        return PROCESS

    # ------------------------------------------------------------------

    def _is_new_primary(self) -> bool:
        return self._selector.select_master_primary(
            self._data.view_no) == self.name

    def _try_build_or_validate(self) -> None:
        if not self._data.waiting_for_new_view:
            return
        if not self._data.quorums.view_change.is_reached(
                len(self._view_changes)):
            return
        if self._is_new_primary():
            self._build_new_view()
        elif self._new_view is not None:
            self._validate_new_view()

    def _build_new_view(self) -> None:
        vcs = list(self._view_changes.values())
        checkpoint = calc_checkpoint(vcs, self._data.quorums)
        if checkpoint is None:
            return
        batches = calc_batches(checkpoint, vcs, self._data.quorums)
        nv = NewView(
            viewNo=self._data.view_no,
            viewChanges=sorted(
                [s, view_change_digest(vc)]
                for s, vc in self._view_changes.items()),
            checkpoint=list(checkpoint),
            batches=batches,
            primary=self.name,
        )
        self._new_view = nv
        self._network.send(nv)
        self._finish(nv)

    def _validate_new_view(self) -> None:
        nv = self._new_view
        assert nv is not None
        # need every VIEW_CHANGE the primary claims to have used
        listed = {tuple(x) for x in nv.viewChanges}
        have = {(s, view_change_digest(vc))
                for s, vc in self._view_changes.items()}
        missing = listed - have
        if missing:
            logger.debug("%s waiting for %d VIEW_CHANGEs used by NEW_VIEW",
                         self.name, len(missing))
            return
        vcs = [vc for s, vc in self._view_changes.items()
               if (s, view_change_digest(vc)) in listed]
        checkpoint = calc_checkpoint(vcs, self._data.quorums)
        if checkpoint is None or list(checkpoint) != list(nv.checkpoint):
            logger.warning("%s NEW_VIEW checkpoint mismatch", self.name)
            self._start_next_view_change()
            return
        batches = calc_batches(tuple(nv.checkpoint), vcs, self._data.quorums)
        if [list(b) for b in batches] != [list(b) for b in nv.batches]:
            logger.warning("%s NEW_VIEW batches mismatch", self.name)
            self._start_next_view_change()
            return
        self._finish(nv)

    def _start_next_view_change(self) -> None:
        """Bad NEW_VIEW from the would-be primary: vote for the next view."""
        self._bus.send(NodeNeedViewChange(view_no=self._data.view_no + 1))

    def _finish(self, nv: NewView) -> None:
        self._data.waiting_for_new_view = False
        self._data.last_completed_view_no = self._data.view_no
        self._timeout_generation += 1  # cancel the pending NEW_VIEW timeout
        logger.info("%s completed view change to view %d (primary %s)",
                    self.name, nv.viewNo, nv.primary)
        self._bus.send(NewViewAccepted(
            view_no=nv.viewNo,
            checkpoint=tuple(nv.checkpoint),
            batches=[list(b) for b in nv.batches],
            primary=nv.primary,
        ))
        self._bus.send(NewViewCheckpointsApplied(
            view_no=nv.viewNo,
            checkpoint=tuple(nv.checkpoint),
            batches=[list(b) for b in nv.batches],
        ))
        self._bus.send(ViewChangeFinished(view_no=nv.viewNo))
        # lets the primary-connection monitor re-evaluate reachability
        self._bus.send(PrimarySelected())
