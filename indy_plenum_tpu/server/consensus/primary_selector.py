"""Deterministic primary selection: round-robin over the validator list.

Reference: plenum/server/consensus/primary_selector.py
(`RoundRobinConstantNodesPrimariesSelector`). Master primary for view v is
validators[v mod N]; backup instance i gets validators[(v + i) mod N].
All nodes compute the same list with no communication.
"""
from __future__ import annotations

from typing import List


class RoundRobinConstantNodesPrimariesSelector:
    def __init__(self, validators):
        """``validators``: a list, or a zero-arg callable returning the
        CURRENT list — the pool manager can change membership between view
        changes, and primaries must be picked from the live set."""
        self._validators = validators

    @property
    def validators(self) -> List[str]:
        if callable(self._validators):
            return list(self._validators())
        return list(self._validators)

    def select_primaries(self, view_no: int, instance_count: int) -> List[str]:
        validators = self.validators
        n = len(validators)
        return [validators[(view_no + i) % n]
                for i in range(instance_count)]

    def select_master_primary(self, view_no: int) -> str:
        validators = self.validators
        return validators[view_no % len(validators)]
