"""Deterministic primary selection: round-robin over the validator list.

Reference: plenum/server/consensus/primary_selector.py
(`RoundRobinConstantNodesPrimariesSelector`). Master primary for view v is
validators[v mod N]; backup instance i gets validators[(v + i) mod N].
All nodes compute the same list with no communication.
"""
from __future__ import annotations

from typing import List


class RoundRobinConstantNodesPrimariesSelector:
    def __init__(self, validators: List[str]):
        self.validators = list(validators)

    def select_primaries(self, view_no: int, instance_count: int) -> List[str]:
        n = len(self.validators)
        return [self.validators[(view_no + i) % n]
                for i in range(instance_count)]

    def select_master_primary(self, view_no: int) -> str:
        return self.validators[view_no % len(self.validators)]
