"""Instance-change voting: degradation evidence -> view change.

Reference: plenum/server/consensus/view_change_trigger_service.py
(`ViewChangeTriggerService`). Nodes vote INSTANCE_CHANGE(v) on master
degradation (Monitor), primary disconnect, or other suspicion; with f+1
votes for a view we join the vote (so slow nodes catch up the vote); with
n-f votes we start the view change (`NodeNeedViewChange` on the internal
bus). Votes expire after INSTANCE_CHANGE_TIMEOUT.
"""
from __future__ import annotations

import logging
from typing import Dict, Tuple

from ...common.event_bus import ExternalBus, InternalBus
from ...common.messages.internal_messages import (
    NodeNeedViewChange,
    PrimaryDisconnected,
    RaisedSuspicion,
    VoteForViewChange,
)
from ...common.messages.node_messages import InstanceChange
from ...common.stashing_router import DISCARD, PROCESS, StashingRouter
from ...common.timer import TimerService
from ..suspicion_codes import Suspicions
from .consensus_shared_data import ConsensusSharedData

logger = logging.getLogger(__name__)


class ViewChangeTriggerService:
    def __init__(self,
                 data: ConsensusSharedData,
                 timer: TimerService,
                 bus: InternalBus,
                 network: ExternalBus,
                 stasher: StashingRouter,
                 config=None):
        from ...config import getConfig

        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._stasher = stasher
        self._config = config or getConfig()

        # proposed_view -> {sender -> vote time}
        self._votes: Dict[int, Dict[str, float]] = {}

        stasher.subscribe(InstanceChange, self.process_instance_change)
        bus.subscribe(VoteForViewChange, self.process_vote_for_view_change)
        bus.subscribe(PrimaryDisconnected, self.process_primary_disconnected)
        bus.subscribe(RaisedSuspicion, self.process_raised_suspicion)

    # suspicions that convict the PRIMARY of protocol fraud for the
    # current view (reference: the instance-change-provoking suspicion set
    # consumed by Node.reportSuspiciousNodeEx): equivocation, forged
    # digests/roots/times, wrong discarded counts, bad multi-sigs in
    # PRE-PREPAREs. Derived from the named catalogue so a renumbering in
    # suspicion_codes.py cannot silently desync this set.
    PRIMARY_FAULT_CODES = frozenset(s.code for s in (
        Suspicions.DUPLICATE_PPR_SENT,
        Suspicions.PPR_DIGEST_WRONG,
        Suspicions.PPR_STATE_WRONG,
        Suspicions.PPR_TXN_WRONG,
        Suspicions.PPR_TIME_WRONG,
        Suspicions.PPR_BLS_MULTISIG_WRONG,
        Suspicions.PPR_AUDIT_TXN_ROOT_WRONG,
        Suspicions.PPR_DISCARDED_WRONG,
    ))

    def process_raised_suspicion(self, msg: RaisedSuspicion, *args) -> None:
        """Byzantine evidence that convicts the master primary becomes a
        view-change vote — without this, an equivocating primary stalls
        the pool in silence."""
        if msg.inst_id != self._data.inst_id or not self._data.is_master:
            return
        ex = msg.ex
        code = getattr(getattr(ex, "suspicion", None), "code", None)
        if code in self.PRIMARY_FAULT_CODES \
                and getattr(ex, "node", None) == self._data.primary_name:
            logger.info("%s: primary %s convicted (%s) -> view change",
                        self._data.name, ex.node, ex.suspicion)
            self._send_instance_change(self._data.view_no + 1, ex.suspicion)

    # ------------------------------------------------------------------

    def process_vote_for_view_change(self, msg: VoteForViewChange) -> None:
        view_no = msg.view_no if msg.view_no is not None \
            else self._data.view_no + 1
        suspicion = msg.suspicion
        self._send_instance_change(view_no, suspicion)

    def process_primary_disconnected(self, msg: PrimaryDisconnected) -> None:
        self._send_instance_change(
            self._data.view_no + 1, Suspicions.PRIMARY_DISCONNECTED)

    def _send_instance_change(self, view_no: int, suspicion) -> None:
        code = getattr(suspicion, "code", 0)
        ic = InstanceChange(viewNo=view_no, reason=code)
        self._record_vote(view_no, self._data.name)
        self._network.send(ic)
        logger.info("%s voted INSTANCE_CHANGE for view %d (%s)",
                    self._data.name, view_no,
                    getattr(suspicion, "reason", suspicion))
        self._try_start(view_no)

    def process_instance_change(self, ic: InstanceChange, sender: str):
        if sender != self._data.name \
                and sender not in self._data.validators:
            return DISCARD, "INSTANCE_CHANGE from non-validator"
        if ic.viewNo <= self._data.view_no:
            return DISCARD, "proposed view not ahead"
        self._record_vote(ic.viewNo, sender)
        # join the vote with weak-quorum evidence (someone honest voted)
        votes = self._votes.get(ic.viewNo, {})
        if (self._data.quorums.weak.is_reached(len(votes))
                and self._data.name not in votes):
            self._send_instance_change(
                ic.viewNo, Suspicions.get_by_code(ic.reason)
                or Suspicions.VIEW_CHANGE_WRONG)
        self._try_start(ic.viewNo)
        return PROCESS

    # ------------------------------------------------------------------

    def _record_vote(self, view_no: int, sender: str) -> None:
        self._gc_expired()
        self._votes.setdefault(view_no, {})[sender] = \
            self._timer.get_current_time()

    def _gc_expired(self) -> None:
        ttl = self._config.INSTANCE_CHANGE_TIMEOUT
        now = self._timer.get_current_time()
        for view_no in list(self._votes):
            votes = self._votes[view_no]
            for sender in [s for s, t in votes.items() if now - t > ttl]:
                del votes[sender]
            if not votes:
                del self._votes[view_no]

    def _try_start(self, view_no: int) -> None:
        if view_no <= self._data.view_no:
            return
        votes = self._votes.get(view_no, {})
        if self._data.quorums.view_change.is_reached(len(votes)):
            logger.info("%s instance-change quorum for view %d",
                        self._data.name, view_no)
            self._votes.pop(view_no, None)
            self._bus.send(NodeNeedViewChange(view_no=view_no))
