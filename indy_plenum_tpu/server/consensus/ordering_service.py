"""The 3PC ordering engine: PRE-PREPARE / PREPARE / COMMIT.

Reference: plenum/server/consensus/ordering_service.py (`OrderingService`).
Host-side protocol state machine; the bulk math it used to do per-message
(signature checks, vote counting at scale) lives in the device plane
(:mod:`indy_plenum_tpu.tpu.ed25519`, :mod:`indy_plenum_tpu.tpu.quorum`) —
this service handles the per-batch protocol logic: speculative execution,
root comparison, certificates, in-order delivery, view-change revert and
re-ordering.

Roles:
- primary: batches finalised requests (Max3PCBatchSize / Max3PCBatchWait),
  applies them speculatively via the executor seam, emits PRE-PREPARE with
  the uncommitted state/txn roots every replica must reproduce;
- non-primary: re-applies the batch, compares roots (byzantine check),
  sends PREPARE; on prepare quorum sends COMMIT (BLS-signed via the bls
  seam); on commit quorum orders IN SEQUENCE and emits ``Ordered`` on the
  internal bus (the node executes/commits);
- on ViewChangeStarted: reverts uncommitted batches; on
  NewViewCheckpointsApplied: re-orders the selected batches in the new view.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from ...common.event_bus import ExternalBus, InternalBus
from ...common.exceptions import SuspiciousNode
from ...common.messages.internal_messages import (
    CatchupFinished,
    CheckpointStabilized,
    MissingMessage,
    NewViewCheckpointsApplied,
    RaisedSuspicion,
    RequestPropagates,
    ViewChangeStarted,
    VoteForViewChange,
)
from ...common.messages.node_messages import (
    Commit,
    Ordered,
    PrePrepare,
    Prepare,
)
from ...common.request import Request
from ...common.stashing_router import (
    DISCARD,
    PROCESS,
    STASH_CATCH_UP,
    STASH_VIEW_3PC,
    STASH_WAITING_NEW_VIEW,
    STASH_WATERMARKS,
    StashingRouter,
)
from ...common.timer import RepeatingTimer, TimerService
from ...common.constants import DOMAIN_LEDGER_ID
from ...observability.trace import NULL_TRACE
from ..suspicion_codes import Suspicions
from .consensus_shared_data import (
    BatchID,
    ConsensusSharedData,
    preprepare_to_batch_id,
)

logger = logging.getLogger(__name__)

STASH_WAITING_REQUESTS = 6
STASH_WAITING_PREV_PP = 7


class NoOpBlsBftReplica:
    """BLS protocol seam; the real implementation is in
    indy_plenum_tpu.bls.bls_bft_replica (reference: plenum/bls/)."""

    def update_pre_prepare(self, params: dict, ledger_id) -> dict:
        return params

    def validate_pre_prepare(self, pp, sender) -> None:
        pass

    def process_pre_prepare(self, pp, sender) -> None:
        pass

    def process_prepare(self, prepare, sender) -> None:
        pass

    def update_commit(self, params: dict, pp) -> dict:
        return params

    def validate_commit(self, commit, sender, pp) -> None:
        pass

    def process_commit(self, commit, sender) -> None:
        pass

    def process_order(self, key, quorums, pp) -> None:
        pass

    def flush(self) -> None:
        pass

    def gc(self, key_3pc) -> None:
        pass


class Executor:
    """Execution seam (reference: WriteRequestManager + ledgers).

    ``apply_batch`` speculatively applies finalised requests and returns the
    resulting (state_root_b58, txn_root_b58) uncommitted roots. For a
    ``pp_seq_no`` at or below the already-committed height it must NOT
    re-apply — it returns the historical roots (the audit ledger knows them);
    this is what makes post-view-change re-ordering of batches some nodes
    already executed safe. ``revert_batches`` undoes up to ``count``
    uncommitted batches (LIFO). The master instance executes; backups pass
    and receive None roots.
    """

    def apply_batch(self, reqs: List[Request], ledger_id: int,
                    pp_time: int, pp_seq_no: int
                    ) -> Tuple[Optional[str], Optional[str]]:
        raise NotImplementedError

    def revert_batches(self, ledger_id: int, count: int) -> None:
        raise NotImplementedError

    def committed_seq(self) -> int:
        """Highest pp_seq_no whose batch is durably committed."""
        raise NotImplementedError


class RequestsPool:
    """Finalised-request source (reference: propagator's Requests container)."""

    def pop_ready(self, ledger_id: int, max_count: int) -> List[Request]:
        raise NotImplementedError

    def get(self, digest: str) -> Optional[Request]:
        raise NotImplementedError

    def has_ready(self, ledger_id: int) -> bool:
        raise NotImplementedError

    def ledger_ids_with_ready(self) -> List[int]:
        raise NotImplementedError


class OrderingService:
    def __init__(self,
                 data: ConsensusSharedData,
                 timer: TimerService,
                 bus: InternalBus,
                 network: ExternalBus,
                 stasher: StashingRouter,
                 executor: Optional[Executor] = None,
                 requests: Optional[RequestsPool] = None,
                 bls=None,
                 config=None,
                 get_time=None,
                 vote_plane=None,
                 shadow_check: bool = False,
                 trace=None):
        from ...config import getConfig

        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._stasher = stasher
        self._executor = executor
        self._requests = requests
        self._bls = bls or NoOpBlsBftReplica()
        self._config = config or getConfig()
        self._get_time = get_time or timer.get_current_time
        # Device quorum plane (tpu.vote_plane.DeviceVotePlane). When set,
        # prepare/commit certificates are DECIDED by the device tensors;
        # the dicts below remain as message logs (MessageReq, duplicate
        # detection). shadow_check additionally asserts dict-derived quorum
        # == device verdict on every query (sim/test mode).
        self._vote_plane = vote_plane
        self._shadow_check = shadow_check
        # flight recorder (observability.trace): 3PC lifecycle marks keyed
        # (view_no, pp_seq_no, digest). NULL_TRACE when tracing is off —
        # every record below guards arg construction on trace.enabled.
        self._trace = trace if trace is not None else NULL_TRACE
        # keys whose commit-quorum observation is already marked: the
        # quorum for seq k can become visible while k-1 still blocks
        # in-order delivery — the mark must land at OBSERVATION so the
        # derived "order" phase measures that head-of-line wait
        self._commit_quorum_marked: set = set()
        # tick-batched quorum evaluation (config.QuorumTickInterval > 0):
        # message handlers only RECORD votes; the runtime composition (the
        # SimPool / Node event loop) syncs the vote plane once per tick and
        # then calls service_quorum_tick(), so every vote recorded in the
        # interval rides one device flush instead of one per message.
        # Queries read the last-synced snapshot (plane.defer_flush_on_query).
        self._tick_mode = (vote_plane is not None
                           and self._config.QuorumTickInterval > 0)
        if self._tick_mode and hasattr(self._bls, "defer_verification"):
            # batch the per-ordered-batch BLS aggregate checks per tick:
            # service_quorum_tick flushes them through ONE multi-pairing
            self._bls.defer_verification = True
        self._dirty_prepare_keys: set = set()
        self._order_dirty = False

        # 3PC logs, keyed (view_no, pp_seq_no)
        self.sent_preprepares: Dict[Tuple[int, int], PrePrepare] = {}
        self.prePrepares: Dict[Tuple[int, int], PrePrepare] = {}
        self.prepares: Dict[Tuple[int, int], Dict[str, Prepare]] = {}
        self.commits: Dict[Tuple[int, int], Dict[str, Commit]] = {}
        self.ordered: set = set()
        self.batches: Dict[Tuple[int, int], int] = {}  # key -> ledger_id
        self.requested_pre_prepares: set = set()
        # PrePrepares retained across a view change for re-ordering
        self.old_view_preprepares: Dict[Tuple[int, int, str], PrePrepare] = {}
        # NEW_VIEW batches whose old PrePrepare we lack, awaiting fetch:
        # (orig_view, pp_seq_no, digest) -> new view_no
        self._pending_old_view_bids: Dict[Tuple[int, int, str], int] = {}
        # highest seq speculatively applied (or committed) — the in-order
        # apply guard for non-primary re-application
        self._last_applied_seq = 0
        # when this primary last minted a batch (freshness cadence base)
        self._last_batch_time = self._get_time()

        stasher.subscribe(PrePrepare, self.process_preprepare)
        stasher.subscribe(Prepare, self.process_prepare)
        stasher.subscribe(Commit, self.process_commit)
        bus.subscribe(ViewChangeStarted, self.process_view_change_started)
        bus.subscribe(NewViewCheckpointsApplied,
                      self.process_new_view_checkpoints_applied)
        bus.subscribe(CheckpointStabilized, self.process_checkpoint_stabilized)
        bus.subscribe(CatchupFinished, self.process_catchup_finished)

        self._batch_timer = RepeatingTimer(
            timer, self._config.Max3PCBatchWait, self._on_batch_timer,
            active=False)
        # liveness: a lost OLD_VIEW_PREPREPARE response must not leave the
        # node (or a mute primary) waiting forever — re-request periodically
        # until every pending NEW_VIEW-selected batch is fetched
        self._fetch_timer = RepeatingTimer(
            timer, self._config.OldViewPPRequestInterval,
            self._refetch_pending_old_view_pps, active=False)
        # the canonical PBFT liveness timer (Castro & Liskov §4.5.2): with
        # requests pending (or batches in flight) and NO ordering progress
        # across a full interval, vote for a view change. This is what
        # recovers a pool whose in-flight 3PC messages were lost for good
        # (partition heal, crashed links) — nobody retransmits them; the
        # new view re-proposes and stragglers fetch. Votes repeat while
        # the stall persists, so votes lost IN the partition don't matter.
        self._stall_snapshot: Optional[Tuple[int, Tuple[int, int]]] = None
        self._stall_timer = None
        if getattr(self._config, "OrderingStallTimeout", 0) > 0:
            self._stall_timer = RepeatingTimer(
                timer, self._config.OrderingStallTimeout,
                self._on_stall_check, active=False)

    # ------------------------------------------------------------------
    # primary: batch creation
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._batch_timer.start()
        if self._stall_timer is not None and self._is_master:
            self._stall_timer.start()

    def stop(self) -> None:
        self._batch_timer.stop()
        if self._stall_timer is not None:
            self._stall_timer.stop()

    # --- ordering-stall watchdog ---------------------------------------

    def _on_stall_check(self) -> None:
        if (not self._is_master or self._data.waiting_for_new_view
                or not self._data.is_participating):
            self._stall_snapshot = None
            return
        pending = ((self._requests is not None
                    and bool(self._requests.ledger_ids_with_ready()))
                   # in-flight batches count as pending work on replicas
                   # (prePrepares) AND on the primary itself, whose own
                   # unacked batches live in sent_preprepares
                   or any(key not in self.ordered
                          for key in self.prePrepares)
                   or any(key not in self.ordered
                          for key in self.sent_preprepares))
        if not pending:
            self._stall_snapshot = None
            return
        marker = (self._data.view_no, self._data.last_ordered_3pc)
        if self._stall_snapshot == marker:
            logger.info("%s: no ordering progress for %.1fs with work "
                        "pending -> vote view change", self.name,
                        self._config.OrderingStallTimeout)
            # reset so the NEXT vote needs two more stalled checks — the
            # repeat cadence that survives votes lost mid-partition
            # without spamming an instance change every interval
            self._stall_snapshot = None
            if self._trace.enabled:
                # flight-recorder trigger: the trace tail at the moment
                # the watchdog fired IS the stall's forensic record
                self._trace.trigger_dump(
                    "ordering_stall", node=self.name,
                    args={"view_no": self._data.view_no,
                          "last_ordered":
                              list(self._data.last_ordered_3pc)})
            self._bus.send(VoteForViewChange(
                suspicion=Suspicions.ORDERING_STALLED))
            return
        self._stall_snapshot = marker
        # before escalating: try a cheap self-heal. A replica that missed
        # in-flight 3PC messages for good (partition, crash window) can
        # re-request them — peers keep everything above the stable
        # checkpoint, and each response re-enters the normal validated
        # processing path. A pool-wide outage still escalates to the vote
        # above; a single straggler resyncs without disturbing the view.
        self._rerequest_inflight_3pc()

    def _rerequest_inflight_3pc(self) -> None:
        view_no = self._data.view_no
        last_seq = self._data.last_ordered_3pc[1]
        seen = (set(self.prePrepares) | set(self.sent_preprepares)
                | set(self.prepares) | set(self.commits))
        hi = max((seq for v, seq in seen if v == view_no),
                 default=last_seq)
        hi = min(hi, last_seq + self._data.log_size)
        for seq in range(last_seq + 1, hi + 1):
            key = (view_no, seq)
            if key in self.ordered:
                continue
            if key not in self.prePrepares \
                    and key not in self.sent_preprepares:
                # dst resolution sends this to the primary only (the one
                # authoritative author of a PRE-PREPARE)
                self._bus.send(MissingMessage(
                    msg_type="PREPREPARE", key=key,
                    inst_id=self._data.inst_id, dst=None))
            self._bus.send(MissingMessage(
                msg_type="PREPARE", key=key,
                inst_id=self._data.inst_id, dst=None))
            self._bus.send(MissingMessage(
                msg_type="COMMIT", key=key,
                inst_id=self._data.inst_id, dst=None))

    # --- tick-batched quorum evaluation --------------------------------

    def _note_prepare_activity(self, key: Tuple[int, int]) -> None:
        if self._tick_mode:
            # ordering fast path: a delta-feed plane reports certificate
            # COMPLETIONS itself (device-side quorum eval) — host-side
            # activity tracking would re-evaluate every in-flight key
            # every tick for nothing
            if not self._vote_plane.delta_feed:
                self._dirty_prepare_keys.add(key)
        else:
            self._try_prepared(key)

    def _note_commit_activity(self, key: Tuple[int, int]) -> None:
        if self._tick_mode:
            if not self._vote_plane.delta_feed:
                self._order_dirty = True
        else:
            self._try_order(key)
            if self._trace.enabled and key not in self.ordered:
                # per-message mode: the quorum this COMMIT may have
                # completed is observable NOW even when in-order delivery
                # still blocks — mark the observation, not the ordering
                self._mark_commit_quorum_observed(key)

    def _mark_commit_quorum_observed(self, key: Tuple[int, int]) -> None:
        """Record ``3pc.commit_quorum`` ONCE per key, at the instant the
        service first sees the quorum (trace-gated: pure observability,
        the ordering path never depends on it)."""
        if not self._trace.enabled:
            return  # keeps the guard local: callers need not re-check
        if key in self._commit_quorum_marked:
            return
        pp = self.prePrepares.get(key)
        if pp is None \
                or preprepare_to_batch_id(pp) not in self._data.prepared:
            return
        if not self._has_commit_quorum(key):
            return
        self._commit_quorum_marked.add(key)
        self._trace.record("3pc.commit_quorum", node=self.name,
                           key=(pp.viewNo, pp.ppSeqNo, pp.digest))

    def _probe_commit_quorums(self) -> None:
        """Tick mode: sweep the unordered in-flight window for commit
        quorums that became visible this tick (bounded by the watermark
        window; snapshot reads only)."""
        for key in sorted(self.prePrepares):
            if key not in self.ordered \
                    and key not in self._commit_quorum_marked:
                self._mark_commit_quorum_observed(key)

    def service_quorum_tick(self) -> None:
        """Evaluate quorums for everything that moved since the last tick.
        The caller has already synced the vote plane; queries here (and any
        triggered by messages until the next tick) read that snapshot, so
        votes recorded during the tick wave buffer for the next flush.

        With a delta-feed plane (device-side quorum eval, the default)
        the tick consumes the plane's newly-completed-certificate deltas
        instead: the device already decided WHICH slots crossed their
        thresholds this tick, so evaluation is O(completions), not
        O(keys-with-activity) re-checked every tick until they order.
        The lost-wakeup guard is structural there — a vote recorded
        during this tick's wave flushes next tick and its transition
        arrives in that tick's delta."""
        plane = self._vote_plane
        if plane is not None and plane.delta_feed:
            deltas = plane.poll_deltas()
            committed_keys: list = []
            if deltas is not None:
                # resolve slots -> keys BEFORE evaluating: ordering below
                # can stabilize a checkpoint and slide the plane, and the
                # delta slots are relative to the PRE-slide h
                view_no, h = self._data.view_no, plane.h
                prepared_keys = [(view_no, h + slot + 1)
                                 for slot in deltas.prepared]
                committed_keys = [(view_no, h + slot + 1)
                                  for slot in deltas.committed]
                for key in prepared_keys:
                    self._try_prepared(key)
                if committed_keys:
                    self._try_order(self._data.last_ordered_3pc)
            # dirt accumulated while the feed was not yet authoritative
            # (plane armed mid-run) drains once; _note_* keeps it empty
            if self._dirty_prepare_keys:
                keys, self._dirty_prepare_keys = \
                    self._dirty_prepare_keys, set()
                for key in sorted(keys):
                    self._try_prepared(key)
            if self._order_dirty:
                self._order_dirty = False
                self._try_order(self._data.last_ordered_3pc)
            if self._trace.enabled:
                # commit quorums that can NOT order yet (head-of-line
                # wait): the delta names exactly the quorums that became
                # visible this tick, so no O(window) prePrepares sweep
                for key in committed_keys:
                    if key not in self.ordered:
                        self._mark_commit_quorum_observed(key)
            self._bls.flush()
            return
        keys: set = set()
        if self._dirty_prepare_keys:
            keys, self._dirty_prepare_keys = self._dirty_prepare_keys, set()
            for key in sorted(keys):
                self._try_prepared(key)
            self._order_dirty = True
        if self._order_dirty:
            self._order_dirty = False
            self._try_order(self._data.last_ordered_3pc)
        if self._vote_plane is not None \
                and self._vote_plane.has_buffered_votes:
            # votes recorded DURING this tick (e.g. our own COMMIT sent by
            # _try_prepared above) are not in the snapshot we just read;
            # they may complete a quorum with no further inbound message,
            # so re-arm evaluation for the next tick (lost-wakeup guard)
            self._order_dirty = True
            self._dirty_prepare_keys |= {
                k for k in keys if k not in self.ordered}
        if self._trace.enabled:
            # commit quorums visible in this tick's snapshot for batches
            # that can NOT order yet (a predecessor blocks in-order
            # delivery): mark the observation now, so commit_quorum →
            # ordered measures the head-of-line wait. Snapshot reads are
            # free in tick mode (defer_flush_on_query).
            self._probe_commit_quorums()
        # every batch _try_order delivered above queued its BLS aggregate
        # check (deferred mode): ONE multi-pairing proves them all
        self._bls.flush()

    @property
    def name(self) -> str:
        return self._data.name

    @property
    def _is_master(self) -> bool:
        return self._data.is_master

    def _can_send_batch(self) -> bool:
        return (self._data.is_primary_in_view
                and self._data.is_participating
                and not self._data.waiting_for_new_view
                # NEW_VIEW-selected batches still being fetched own their
                # seqNos; minting a fresh batch now would collide with them
                and not self._pending_old_view_bids
                and self._data.pp_seq_no < self._data.high_watermark)

    def _on_batch_timer(self) -> None:
        if not self._can_send_batch() or self._requests is None:
            return
        for ledger_id in self._requests.ledger_ids_with_ready():
            if not self._can_send_batch():
                break
            self.send_3pc_batch(ledger_id)
        self._maybe_send_freshness_batch()

    def _maybe_send_freshness_batch(self) -> None:
        """Idle primary: re-sign the state roots periodically with an EMPTY
        batch (reference: freshness updates). Without this, BLS multi-sigs
        over the committed roots age out and proved reads from an idle
        pool stop verifying against any freshness window."""
        interval = self._config.StateFreshnessUpdateInterval
        if interval <= 0 or not self._is_master:
            return
        if not self._can_send_batch():
            return
        now = self._get_time()
        if now - self._last_batch_time < interval:
            return
        self.send_3pc_batch(DOMAIN_LEDGER_ID, allow_empty=True)

    def send_3pc_batch(self, ledger_id: int = DOMAIN_LEDGER_ID,
                       allow_empty: bool = False) -> Optional[PrePrepare]:
        """Primary: pop finalised requests, apply, emit PRE-PREPARE."""
        if not self._can_send_batch() or self._requests is None:
            return None
        reqs = self._requests.pop_ready(
            ledger_id, self._config.Max3PCBatchSize)
        if not reqs and not allow_empty:
            return None
        pp_time = int(self._get_time())
        self._last_batch_time = pp_time
        self._data.pp_seq_no += 1
        state_root = txn_root = None
        discarded = 0
        if self._is_master and self._executor is not None:
            state_root, txn_root = self._executor.apply_batch(
                reqs, ledger_id, pp_time, self._data.pp_seq_no)
            discarded = len(getattr(self._executor, "last_rejected", []))
            self._last_applied_seq = max(self._last_applied_seq,
                                         self._data.pp_seq_no)
        params = dict(
            instId=self._data.inst_id,
            viewNo=self._data.view_no,
            ppSeqNo=self._data.pp_seq_no,
            ppTime=pp_time,
            reqIdr=[r.digest for r in reqs],
            discarded=discarded,
            digest=self._batch_digest([r.digest for r in reqs], pp_time,
                                      state_root, txn_root, ledger_id,
                                      discarded),
            ledgerId=ledger_id,
            stateRootHash=state_root,
            txnRootHash=txn_root,
            sub_seq_no=0,
            final=True,
        )
        params = self._bls.update_pre_prepare(params, ledger_id)
        pp = PrePrepare(**params)
        key = (pp.viewNo, pp.ppSeqNo)
        self.sent_preprepares[key] = pp
        self.prePrepares[key] = pp
        self.batches[key] = ledger_id
        self._data.preprepare_batch(preprepare_to_batch_id(pp))
        if self._vote_plane is not None:
            self._vote_plane.record_preprepare(pp.ppSeqNo)
        self._network.send(pp)
        if self._trace.enabled:
            # reqIdr rides the primary's send mark ONCE per batch: the
            # causal plane's request->batch join (journeys need to know
            # which requests a (view, seq, digest) batch carried, and
            # the batch digest is not invertible)
            self._trace.record("3pc.preprepare_sent", node=self.name,
                               key=(pp.viewNo, pp.ppSeqNo, pp.digest),
                               args={"reqs": len(reqs),
                                     "reqIdr": [r.digest for r in reqs]})
        logger.debug("%s sent PRE-PREPARE %s (%d reqs)", self.name, key,
                     len(reqs))
        return pp

    @staticmethod
    def _batch_digest(req_digests: List[str], pp_time=None,
                      state_root=None, txn_root=None, ledger_id=None,
                      discarded=None) -> str:
        """Digest binding the FULL batch content: request ids, ppTime, both
        roots, the ledger and the discarded count. Because PREPARE/COMMIT
        and NEW_VIEW BatchIDs carry this digest, a fetched
        OLD_VIEW_PREPREPARE with ANY field forged by the responder cannot
        match it (advisor r2 finding)."""
        import hashlib

        payload = "|".join(
            ["".join(req_digests), str(pp_time), str(state_root),
             str(txn_root), str(ledger_id), str(discarded)]).encode()
        return hashlib.sha256(payload).hexdigest()

    # ------------------------------------------------------------------
    # 3PC message processing
    # ------------------------------------------------------------------

    def _common_checks(self, msg, key: Tuple[int, int]):
        """Shared view/watermark admission checks; verdict or None=pass."""
        # multi-instance: every replica's services share the node's external
        # bus; messages of other protocol instances are not ours to handle
        if getattr(msg, "instId", self._data.inst_id) != self._data.inst_id:
            return DISCARD, "other instance"
        view_no, pp_seq_no = key
        if view_no < self._data.view_no:
            return DISCARD, "old view"
        if view_no > self._data.view_no:
            return STASH_VIEW_3PC, "future view"
        if self._data.waiting_for_new_view:
            return STASH_WAITING_NEW_VIEW, "waiting for NEW_VIEW"
        if not self._data.is_participating:
            return STASH_CATCH_UP, "catching up"
        if pp_seq_no <= self._data.low_watermark:
            return DISCARD, "below watermark"
        if pp_seq_no > self._data.high_watermark:
            return STASH_WATERMARKS, "above high watermark"
        return None

    def _raise_suspicion(self, sender: str, suspicion) -> None:
        self._bus.send(RaisedSuspicion(
            inst_id=self._data.inst_id,
            ex=SuspiciousNode(sender, suspicion)))

    def process_preprepare(self, pp: PrePrepare, sender: str):
        key = (pp.viewNo, pp.ppSeqNo)
        verdict = self._common_checks(pp, key)
        if verdict is not None:
            return verdict
        if sender != self._data.primary_name:
            self._raise_suspicion(sender, Suspicions.PPR_FRM_NON_PRIMARY)
            return DISCARD, "PRE-PREPARE from non-primary"
        existing = self.prePrepares.get(key)
        if existing is not None:
            if existing.digest != pp.digest:
                self._raise_suspicion(sender, Suspicions.DUPLICATE_PPR_SENT)
            return DISCARD, "duplicate PRE-PREPARE"
        try:
            self._bls.validate_pre_prepare(pp, sender)
        except SuspiciousNode as ex:
            self._bus.send(RaisedSuspicion(self._data.inst_id, ex))
            return DISCARD, "bad BLS multi-sig"

        # all referenced requests must be finalised here too — EXCEPT for
        # batches at/below our committed height (post-view-change
        # re-ordering of already-executed batches): their roots come from
        # the audit ledger and their content may be GC'd after execution
        committed = (self._executor.committed_seq()
                     if self._executor is not None else 0)
        if self._requests is not None and pp.ppSeqNo > committed:
            missing = [d for d in pp.reqIdr
                       if self._requests.get(d) is None]
            if missing:
                self._bus.send(RequestPropagates(missing))
                return STASH_WAITING_REQUESTS, f"missing {len(missing)} reqs"

        if pp.digest != self._batch_digest(list(pp.reqIdr), pp.ppTime,
                                           pp.stateRootHash, pp.txnRootHash,
                                           pp.ledgerId, pp.discarded):
            self._raise_suspicion(sender, Suspicions.PPR_DIGEST_WRONG)
            return DISCARD, "digest mismatch"

        # speculative re-apply on master: roots must match the primary's.
        # Application MUST be in ppSeqNo order (roots chain) — a PRE-PREPARE
        # arriving ahead of its predecessor is stashed, not mis-applied.
        if self._is_master and self._executor is not None \
                and self._requests is not None:
            committed = self._executor.committed_seq()
            floor = max(committed, self._last_applied_seq)
            historical = pp.ppSeqNo <= committed
            if not historical and pp.ppSeqNo != floor + 1:
                return STASH_WAITING_PREV_PP, (
                    f"out-of-order apply: {pp.ppSeqNo} after {floor}")
            reqs = [self._requests.get(d) for d in pp.reqIdr]
            state_root, txn_root = self._executor.apply_batch(
                reqs, pp.ledgerId, pp.ppTime, pp.ppSeqNo)
            # a HISTORICAL batch (<= committed: post-view-change re-order
            # of something already executed) stages nothing — the roots
            # come from the audit ledger, and on mismatch there is nothing
            # of ours to revert (reverting would pop an unrelated
            # genuinely-staged batch and corrupt the uncommitted roots)
            if state_root != pp.stateRootHash:
                if not historical:
                    self._executor.revert_batches(pp.ledgerId, 1)
                self._raise_suspicion(sender, Suspicions.PPR_STATE_WRONG)
                return DISCARD, "state root mismatch"
            if txn_root != pp.txnRootHash:
                if not historical:
                    self._executor.revert_batches(pp.ledgerId, 1)
                self._raise_suspicion(sender, Suspicions.PPR_TXN_WRONG)
                return DISCARD, "txn root mismatch"
            # the rejection split is deterministic: a primary lying about
            # the discarded count cannot hide behind matching roots
            my_discarded = len(getattr(self._executor, "last_rejected", []))
            if pp.ppSeqNo > committed and pp.discarded != my_discarded:
                self._executor.revert_batches(pp.ledgerId, 1)
                self._raise_suspicion(sender, Suspicions.PPR_DISCARDED_WRONG)
                return DISCARD, "discarded count mismatch"
            self._last_applied_seq = max(floor, pp.ppSeqNo)

        self.prePrepares[key] = pp
        self.batches[key] = pp.ledgerId
        self._data.preprepare_batch(preprepare_to_batch_id(pp))
        if self._vote_plane is not None:
            self._vote_plane.record_preprepare(pp.ppSeqNo)
            # replay digest-matching PREPAREs that arrived before the
            # PRE-PREPARE (they were logged but never scattered — only
            # validated votes reach the device)
            for s, p in self.prepares.get(key, {}).items():
                if p.digest == pp.digest:
                    self._vote_plane.record_prepare(s, pp.ppSeqNo)
        self._bls.process_pre_prepare(pp, sender)
        if self._trace.enabled:
            self._trace.record("3pc.preprepare", node=self.name,
                               key=(pp.viewNo, pp.ppSeqNo, pp.digest),
                               args={"reqs": len(pp.reqIdr)})

        if not self._data.is_primary_in_view:
            self._send_prepare(pp)
        self._note_prepare_activity(key)
        # the successor PRE-PREPARE may be waiting on this one
        self._stasher.process_stashed(STASH_WAITING_PREV_PP)
        return PROCESS

    def on_request_finalised(self) -> None:
        """Node hook: newly finalised requests may unblock stashed PPs."""
        self._stasher.process_stashed(STASH_WAITING_REQUESTS)

    def _send_prepare(self, pp: PrePrepare) -> None:
        prepare = Prepare(
            instId=self._data.inst_id,
            viewNo=pp.viewNo,
            ppSeqNo=pp.ppSeqNo,
            ppTime=pp.ppTime,
            digest=pp.digest,
            stateRootHash=pp.stateRootHash,
            txnRootHash=pp.txnRootHash,
        )
        key = (pp.viewNo, pp.ppSeqNo)
        self.prepares.setdefault(key, {})[self.name] = prepare
        if self._vote_plane is not None:
            self._vote_plane.record_prepare(self.name, pp.ppSeqNo)
        self._network.send(prepare)

    def process_prepare(self, prepare: Prepare, sender: str):
        key = (prepare.viewNo, prepare.ppSeqNo)
        verdict = self._common_checks(prepare, key)
        if verdict is not None:
            return verdict
        if sender not in self._data.validators:
            # a demoted (or never-admitted) node's votes must not count
            # toward any certificate
            return DISCARD, "PREPARE from non-validator"
        primary_name = self._data.primary_name
        if sender == primary_name:
            self._raise_suspicion(sender, Suspicions.PR_FRM_PRIMARY)
            return DISCARD, "PREPARE from primary"
        votes = self.prepares.setdefault(key, {})
        if sender in votes:
            self._raise_suspicion(sender, Suspicions.DUPLICATE_PR_SENT)
            return DISCARD, "duplicate PREPARE"
        pp = self.prePrepares.get(key)
        if pp is not None and prepare.digest != pp.digest:
            self._raise_suspicion(sender, Suspicions.PR_DIGEST_WRONG)
            return DISCARD, "PREPARE digest mismatch"
        votes[sender] = prepare
        if self._vote_plane is not None and pp is not None:
            # pp present => digest checked above; safe to scatter
            self._vote_plane.record_prepare(sender, prepare.ppSeqNo)
        self._bls.process_prepare(prepare, sender)
        self._note_prepare_activity(key)
        return PROCESS

    def _dict_prepare_quorum(self, key: Tuple[int, int]) -> bool:
        # Only votes whose digest matches the accepted PRE-PREPARE count:
        # PREPAREs can arrive before the PRE-PREPARE (and are recorded), so
        # a byzantine node must not be able to inflate the certificate with
        # arbitrary-digest early votes.
        pp = self.prePrepares.get(key)
        if pp is None:
            return False
        votes = self.prepares.get(key, {})
        others = [s for s, p in votes.items()
                  if s != self._data.primary_name and p.digest == pp.digest]
        return self._data.quorums.prepare.is_reached(len(others))

    def _has_prepare_quorum(self, key: Tuple[int, int]) -> bool:
        if self._vote_plane is None:
            return self._dict_prepare_quorum(key)
        dev = (key[0] == self._data.view_no
               and self._vote_plane.has_prepare_quorum(key[1]))
        if self._shadow_check:
            host = self._dict_prepare_quorum(key)
            assert dev == host, ("prepare quorum divergence", key, dev, host)
        return dev

    def _try_prepared(self, key: Tuple[int, int]) -> None:
        pp = self.prePrepares.get(key)
        if pp is None or not self._has_prepare_quorum(key):
            return
        bid = preprepare_to_batch_id(pp)
        if bid in self._data.prepared:
            return
        # votes must match the accepted PRE-PREPARE digest
        self._data.prepare_batch(bid)
        if self._trace.enabled:
            self._trace.record("3pc.prepare_quorum", node=self.name,
                               key=(pp.viewNo, pp.ppSeqNo, pp.digest))
        self._send_commit(pp)

    def _send_commit(self, pp: PrePrepare) -> None:
        key = (pp.viewNo, pp.ppSeqNo)
        params = dict(instId=self._data.inst_id, viewNo=pp.viewNo,
                      ppSeqNo=pp.ppSeqNo)
        params = self._bls.update_commit(params, pp)
        commit = Commit(**params)
        self.commits.setdefault(key, {})[self.name] = commit
        if self._vote_plane is not None:
            self._vote_plane.record_commit(self.name, pp.ppSeqNo)
        self._network.send(commit)
        self._note_commit_activity(key)

    def process_commit(self, commit: Commit, sender: str):
        key = (commit.viewNo, commit.ppSeqNo)
        verdict = self._common_checks(commit, key)
        if verdict is not None:
            return verdict
        if sender not in self._data.validators:
            return DISCARD, "COMMIT from non-validator"
        votes = self.commits.setdefault(key, {})
        if sender in votes:
            self._raise_suspicion(sender, Suspicions.DUPLICATE_CM_SENT)
            return DISCARD, "duplicate COMMIT"
        pp = self.prePrepares.get(key)
        try:
            self._bls.validate_commit(commit, sender, pp)
        except SuspiciousNode as ex:
            self._bus.send(RaisedSuspicion(self._data.inst_id, ex))
            return DISCARD, "bad BLS sig in COMMIT"
        votes[sender] = commit
        if self._vote_plane is not None:
            self._vote_plane.record_commit(sender, commit.ppSeqNo)
        self._bls.process_commit(commit, sender)
        self._note_commit_activity(key)
        return PROCESS

    # ------------------------------------------------------------------
    # ordering
    # ------------------------------------------------------------------

    def _dict_commit_quorum(self, key: Tuple[int, int]) -> bool:
        return self._data.quorums.commit.is_reached(
            len(self.commits.get(key, {})))

    def _has_commit_quorum(self, key: Tuple[int, int]) -> bool:
        if self._vote_plane is None:
            return self._dict_commit_quorum(key)
        dev = (key[0] == self._data.view_no
               and self._vote_plane.has_commit_quorum(key[1]))
        if self._shadow_check:
            host = self._dict_commit_quorum(key)
            assert dev == host, ("commit quorum divergence", key, dev, host)
        return dev

    def _can_order(self, key: Tuple[int, int]) -> bool:
        pp = self.prePrepares.get(key)
        if pp is None:
            return False
        bid = preprepare_to_batch_id(pp)
        if bid not in self._data.prepared:
            return False
        if not self._has_commit_quorum(key):
            return False
        if key in self.ordered:
            return False
        # strict in-order delivery within the view
        view_no, pp_seq_no = key
        last_view, last_seq = self._data.last_ordered_3pc
        return pp_seq_no == last_seq + 1

    def _try_order(self, key: Tuple[int, int]) -> None:
        # drain in order: the commit quorum for seq k may have arrived
        # before k-1 ordered
        progressed = True
        while progressed:
            progressed = False
            nxt = (self._data.view_no, self._data.last_ordered_3pc[1] + 1)
            if self._can_order(nxt):
                self._order_3pc_key(nxt)
                progressed = True

    def _order_3pc_key(self, key: Tuple[int, int]) -> None:
        pp = self.prePrepares[key]
        self.ordered.add(key)
        self._data.last_ordered_3pc = key
        if self._trace.enabled:
            # the quorum observation usually coincides with ordering
            # (head-of-line batch); when it was visible EARLIER while a
            # predecessor blocked, _mark_commit_quorum_observed already
            # stamped it and the dedupe keeps that earlier timestamp
            self._mark_commit_quorum_observed(key)
            self._trace.record("3pc.ordered", node=self.name,
                               key=(pp.viewNo, pp.ppSeqNo, pp.digest),
                               args={"reqs": len(pp.reqIdr)})
        self._bls.process_order(key, self._data.quorums, pp)
        ordered = Ordered(
            instId=self._data.inst_id,
            viewNo=pp.viewNo,
            ppSeqNo=pp.ppSeqNo,
            ppTime=pp.ppTime,
            reqIdr=list(pp.reqIdr),
            discarded=pp.discarded,
            ledgerId=pp.ledgerId,
            stateRootHash=pp.stateRootHash,
            txnRootHash=pp.txnRootHash,
            auditTxnRootHash=pp.auditTxnRootHash,
            originalViewNo=pp.originalViewNo,
            digest=pp.digest,
        )
        logger.debug("%s ordered %s", self.name, key)
        self._bus.send(ordered)

    # ------------------------------------------------------------------
    # view change integration
    # ------------------------------------------------------------------

    def process_view_change_started(self, msg: ViewChangeStarted) -> None:
        """Revert uncommitted batches; retain PrePrepares for re-ordering."""
        if self._trace.enabled:
            self._trace.record("vc.started", cat="vc", node=self.name,
                               args={"view_no": self._data.view_no})
        if self._is_master and self._executor is not None:
            # revert unordered speculatively-applied batches (newest first)
            unordered = [k for k in self.prePrepares
                         if k not in self.ordered]
            by_ledger: Dict[int, int] = {}
            for k in unordered:
                lid = self.batches.get(k, DOMAIN_LEDGER_ID)
                by_ledger[lid] = by_ledger.get(lid, 0) + 1
            for lid, count in by_ledger.items():
                self._executor.revert_batches(lid, count)
            self._last_applied_seq = self._executor.committed_seq()
        for key, pp in self.prePrepares.items():
            orig = pp.originalViewNo if pp.originalViewNo is not None \
                else pp.viewNo
            self.old_view_preprepares[(orig, pp.ppSeqNo, pp.digest)] = pp
        if self._vote_plane is not None:
            # old-view votes are void; slots refill during re-ordering
            self._vote_plane.reset(h=self._data.low_watermark)
        self._pending_old_view_bids.clear()
        self._dirty_prepare_keys.clear()
        self._commit_quorum_marked.clear()
        self._fetch_timer.stop()
        self.sent_preprepares.clear()
        self.prePrepares.clear()
        self.prepares.clear()
        self.commits.clear()
        self.batches.clear()

    def process_new_view_checkpoints_applied(
            self, msg: NewViewCheckpointsApplied) -> None:
        """Re-order the batches selected by NEW_VIEW in the new view."""
        cp_view, cp_seq, _ = msg.checkpoint
        # EVERY batch above the checkpoint is re-ordered in the new view,
        # including ones this node already ordered — its 3PC votes are
        # needed by peers that had not. Double-execution is prevented by
        # the executor seam (historical roots) and the node-level ordered
        # dedup on ppSeqNo.
        self._data.pp_seq_no = cp_seq
        self._data.last_ordered_3pc = (msg.view_no, cp_seq)
        self.ordered.clear()  # keys were in the old view; all re-keyed now
        self._data.clear_batches()
        for bid in msg.batches:
            view_no, pp_view_no, pp_seq_no, digest = bid
            old_key = (pp_view_no, pp_seq_no, digest)
            old_pp = self.old_view_preprepares.get(old_key)
            if old_pp is None:
                # liveness: with strict in-order ordering, a hole here would
                # stall everything at/past this seqNo. ANY node that listed
                # the batch in its VIEW_CHANGE holds the old PrePrepare (the
                # new primary may itself lack it), so fetch it from the pool
                # — the digest in the batch id authenticates the content.
                logger.warning("%s missing old PrePrepare for %s, requesting",
                               self.name, bid)
                self._pending_old_view_bids[old_key] = msg.view_no
                self._bus.send(MissingMessage(
                    msg_type="OLD_VIEW_PREPREPARE",
                    key=old_key,
                    inst_id=self._data.inst_id,
                    dst=None))
                continue
            self._apply_new_view_batch(old_pp, msg.view_no, pp_view_no)
        if self._pending_old_view_bids:
            self._fetch_timer.start()
        self._stasher.process_all_stashed()

    def _refetch_pending_old_view_pps(self) -> None:
        if not self._pending_old_view_bids:
            self._fetch_timer.stop()
            return
        for old_key in list(self._pending_old_view_bids):
            self._bus.send(MissingMessage(
                msg_type="OLD_VIEW_PREPREPARE",
                key=old_key,
                inst_id=self._data.inst_id,
                dst=None))

    def _apply_new_view_batch(self, old_pp: PrePrepare, new_view_no: int,
                              orig_view_no: int) -> None:
        """Re-key one NEW_VIEW-selected batch into the new view and process
        it (primary: re-broadcast; replica: run the normal PP path)."""
        params = old_pp._fields
        params.update(viewNo=new_view_no, originalViewNo=orig_view_no)
        new_pp = PrePrepare(**params)
        self._data.pp_seq_no = max(self._data.pp_seq_no, new_pp.ppSeqNo)
        if self._data.is_primary_in_view:
            self.sent_preprepares[(new_pp.viewNo, new_pp.ppSeqNo)] = new_pp
            self._network.send(new_pp)
            # these requests are owned by the re-keyed batch now; minting a
            # fresh batch from them later would double-order them
            if self._requests is not None:
                self._requests.mark_ordered(list(new_pp.reqIdr))
        # BOTH primary and replicas run the normal PP path through the
        # stasher: the primary must re-APPLY the batch (its speculative
        # state was reverted at view-change start) under the same in-order
        # discipline, and out-of-order/early verdicts must stash, not
        # vanish (a direct handler call would drop the verdict)
        self._stasher.process(new_pp, self._data.primary_name)

    def process_requested_old_view_pp(self, pp: PrePrepare) -> None:
        """A fetched old-view PrePrepare arrived (MessageReqService validated
        the digest against what we asked for)."""
        orig = pp.originalViewNo if pp.originalViewNo is not None \
            else pp.viewNo
        old_key = (orig, pp.ppSeqNo, pp.digest)
        self.old_view_preprepares[old_key] = pp
        new_view_no = self._pending_old_view_bids.pop(old_key, None)
        if not self._pending_old_view_bids:
            self._fetch_timer.stop()
        if new_view_no is None or new_view_no != self._data.view_no:
            return  # no longer waiting (another view change happened)
        self._apply_new_view_batch(pp, new_view_no, orig)
        self._stasher.process_stashed(STASH_WAITING_PREV_PP)

    def process_catchup_finished(self, msg: CatchupFinished) -> None:
        """Resync 3PC state to the durably caught-up point: everything at
        or below it is already executed (the ledgers ARE the certificates);
        stashed messages for the live tail replay through the normal path."""
        view_no, pp_seq_no = msg.last_caught_up_3pc
        if pp_seq_no > self._data.last_ordered_3pc[1]:
            self._data.last_ordered_3pc = (view_no, pp_seq_no)
        self._data.pp_seq_no = max(self._data.pp_seq_no, pp_seq_no)
        self._data.low_watermark = max(self._data.low_watermark, pp_seq_no)
        self._data.stable_checkpoint = max(self._data.stable_checkpoint,
                                           pp_seq_no)
        self._data.free_upto(pp_seq_no)
        for store in (self.sent_preprepares, self.prePrepares,
                      self.prepares, self.commits, self.batches):
            for key in [k for k in store if k[1] <= pp_seq_no]:
                del store[key]
        self.ordered = {k for k in self.ordered if k[1] > pp_seq_no}
        self._commit_quorum_marked = {
            k for k in self._commit_quorum_marked if k[1] > pp_seq_no}
        if self._executor is not None:
            # the leecher reverted EVERY staged speculative apply before
            # fetching (catchup writes committed txns): nothing above the
            # durable floor is applied anymore, whatever the pre-catchup
            # bookkeeping said — a stale higher floor would let a
            # retained batch order against a staged list that is empty
            self._last_applied_seq = self._executor.committed_seq()
        # retained PRE-PREPAREs above the caught-up point were applied
        # BEFORE the leecher's revert (a mid-stream second catchup hits
        # this): their staged batches are gone, so ordering them now
        # would commit nothing. Drop the PP records (their PREPARE/COMMIT
        # votes stay — the stall watchdog's in-flight re-request sees
        # those keys, re-fetches each PP from the primary, and the normal
        # processing path re-APPLIES it under the in-order discipline).
        dropped = [k for k in self.prePrepares if k not in self.ordered]
        for key in dropped:
            pp = self.prePrepares.pop(key)
            self.batches.pop(key, None)
            self._data.free_batch(preprepare_to_batch_id(pp))
        if self._vote_plane is not None:
            self._vote_plane.reset(h=pp_seq_no)
        self._bls.gc((view_no, pp_seq_no))
        self._stasher.process_all_stashed()

    def process_checkpoint_stabilized(self, msg: CheckpointStabilized) -> None:
        """GC 3PC logs at or below the new stable checkpoint."""
        stable_seq = msg.last_stable_3pc[1]
        self._data.low_watermark = stable_seq
        self._data.stable_checkpoint = stable_seq
        self._data.free_upto(stable_seq)
        for store in (self.sent_preprepares, self.prePrepares,
                      self.prepares, self.commits, self.batches):
            for key in [k for k in store if k[1] <= stable_seq]:
                del store[key]
        self.ordered = {k for k in self.ordered if k[1] > stable_seq}
        self._commit_quorum_marked = {
            k for k in self._commit_quorum_marked if k[1] > stable_seq}
        self.old_view_preprepares = {
            k: v for k, v in self.old_view_preprepares.items()
            if k[1] > stable_seq}
        if self._vote_plane is not None:
            self._vote_plane.slide_to(stable_seq)
        self._bls.gc(msg.last_stable_3pc)
        self._stasher.process_stashed(STASH_WATERMARKS)

    # --- introspection (tests / monitor) ------------------------------

    def l_last_ordered(self) -> Tuple[int, int]:
        return self._data.last_ordered_3pc
