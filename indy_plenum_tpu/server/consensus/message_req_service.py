"""Fetching missing protocol messages from peers.

Reference: plenum/server/consensus/message_request_service.py
(`MessageReqService`). MESSAGE_REQUEST(type, params) asks peers for a
message we should have (a 3PC message for a key, a VIEW_CHANGE we lack);
MESSAGE_RESPONSE carries it back and it is re-injected through the normal
processing path (so all validation still applies).
"""
from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from ...common.event_bus import ExternalBus, InternalBus
from ...common.messages.internal_messages import MissingMessage
from ...common.messages.message_base import node_message_registry
from ...common.messages.node_messages import (
    MessageRep,
    MessageReq,
    PrePrepare,
    Prepare,
    Commit,
    Propagate,
    ViewChange,
)
from ...common.stashing_router import DISCARD, PROCESS, StashingRouter

logger = logging.getLogger(__name__)

PREPREPARE = "PREPREPARE"
PREPARE = "PREPARE"
COMMIT = "COMMIT"
VIEW_CHANGE = "VIEW_CHANGE"
OLD_VIEW_PREPREPARE = "OLD_VIEW_PREPREPARE"
PROPAGATE = "PROPAGATE"


class MessageReqService:
    """Answers peers' requests from our logs; asks peers for what we lack."""

    def __init__(self,
                 data,
                 bus: InternalBus,
                 network: ExternalBus,
                 ordering_service=None,
                 view_change_service=None,
                 propagator=None):
        self._data = data
        self._bus = bus
        self._network = network
        self._ordering = ordering_service
        self._view_change = view_change_service
        self._propagator = propagator
        # (msg_type, params_key) we actually asked for; unsolicited
        # MESSAGE_RESPONSEs are dropped
        self._outstanding: set = set()

        network.subscribe(MessageReq, self.process_message_req)
        network.subscribe(MessageRep, self.process_message_rep)
        bus.subscribe(MissingMessage, self.process_missing_message)

    # --- outbound requests ---------------------------------------------

    def process_missing_message(self, msg: MissingMessage) -> None:
        params: Dict[str, Any]
        dst = msg.dst
        if msg.msg_type in (PREPREPARE, PREPARE, COMMIT):
            view_no, pp_seq_no = msg.key
            params = {"viewNo": view_no, "ppSeqNo": pp_seq_no,
                      "instId": str(msg.inst_id)}
            if msg.msg_type == PREPREPARE and self._data.primaries:
                # Only the primary's PRE-PREPARE is authoritative: asking
                # anyone else would let a relayer forge primary-attributed
                # content (its roots/digest failures would be blamed on the
                # primary).
                dst = [self._data.primaries[self._data.inst_id]]
        elif msg.msg_type == VIEW_CHANGE:
            sender, digest = msg.key
            params = {"sender": sender, "digest": digest}
        elif msg.msg_type == OLD_VIEW_PREPREPARE:
            # broadcast: ANY node that prepared the batch holds it; the
            # digest in the key authenticates whatever comes back
            orig_view, pp_seq_no, digest = msg.key
            params = {"originalViewNo": orig_view, "ppSeqNo": pp_seq_no,
                      "digest": digest}
        elif msg.msg_type == PROPAGATE:
            # broadcast: the digest authenticates the carried request
            params = {"digest": str(msg.key)}
        else:
            return
        self._outstanding.add((msg.msg_type, self._params_key(params)))
        req = MessageReq(msg_type=msg.msg_type, params=params)
        self._network.send(req, dst)

    @staticmethod
    def _params_key(params: Dict[str, Any]):
        return tuple(sorted((k, str(v)) for k, v in params.items()))

    # --- inbound requests ----------------------------------------------

    def process_message_req(self, req: MessageReq, sender: str):
        handler = {
            PREPREPARE: self._find_preprepare,
            PREPARE: self._find_prepare,
            COMMIT: self._find_commit,
            VIEW_CHANGE: self._find_view_change,
            OLD_VIEW_PREPREPARE: self._find_old_view_preprepare,
            PROPAGATE: self._find_propagate,
        }.get(req.msg_type)
        if handler is None:
            return DISCARD, f"unknown msg_type {req.msg_type}"
        found = handler(req.params)
        if found is None:
            return DISCARD, "not found"
        rep = MessageRep(msg_type=req.msg_type, params=req.params,
                         msg=found.as_dict())
        self._network.send(rep, [sender])
        return PROCESS

    def _key_from(self, params) -> Optional[tuple]:
        try:
            return int(params["viewNo"]), int(params["ppSeqNo"])
        except (KeyError, ValueError, TypeError):
            return None

    def _find_preprepare(self, params):
        key = self._key_from(params)
        if key is None or self._ordering is None:
            return None
        # the primary keeps its OWN batches in sent_preprepares, not
        # prePrepares — and PRE-PREPARE requests go only to the primary,
        # so that log is the one that matters for a straggler's re-sync
        return (self._ordering.prePrepares.get(key)
                or self._ordering.sent_preprepares.get(key))

    def _find_prepare(self, params):
        key = self._key_from(params)
        if key is None or self._ordering is None:
            return None
        votes = self._ordering.prepares.get(key, {})
        return votes.get(self._data.name)

    def _find_commit(self, params):
        key = self._key_from(params)
        if key is None or self._ordering is None:
            return None
        votes = self._ordering.commits.get(key, {})
        return votes.get(self._data.name)

    def _find_old_view_preprepare(self, params):
        if self._ordering is None:
            return None
        try:
            key = (int(params["originalViewNo"]), int(params["ppSeqNo"]),
                   str(params["digest"]))
        except (KeyError, ValueError, TypeError):
            return None
        found = self._ordering.old_view_preprepares.get(key)
        if found is None:
            # the batch may still be live in the current-view log
            for pp in self._ordering.prePrepares.values():
                orig = pp.originalViewNo if pp.originalViewNo is not None \
                    else pp.viewNo
                if (orig, pp.ppSeqNo, pp.digest) == key:
                    return pp
        return found

    def _find_propagate(self, params):
        if self._propagator is None:
            return None
        digest = params.get("digest")
        if not digest:
            return None
        return self._propagator.find_propagate(str(digest))

    def _find_view_change(self, params):
        if self._view_change is None:
            return None
        from .view_change_service import view_change_digest

        sender = params.get("sender")
        digest = params.get("digest")
        vc = self._view_change._view_changes.get(sender)
        if vc is not None and view_change_digest(vc) == digest:
            return vc
        return None

    @staticmethod
    def _batch_digest_of(pp: PrePrepare) -> str:
        from .ordering_service import OrderingService

        return OrderingService._batch_digest(
            list(pp.reqIdr), pp.ppTime, pp.stateRootHash, pp.txnRootHash,
            pp.ledgerId, pp.discarded)

    # --- inbound responses ---------------------------------------------

    def process_message_rep(self, rep: MessageRep, sender: str):
        if rep.msg is None:
            return DISCARD, "empty MESSAGE_RESPONSE"
        key = (rep.msg_type, self._params_key(dict(rep.params)))
        if key not in self._outstanding:
            return DISCARD, "unsolicited MESSAGE_RESPONSE"
        try:
            msg = node_message_registry.obj_from_dict(dict(rep.msg))
        except Exception as exc:  # noqa: BLE001 - wire data is untrusted
            return DISCARD, f"bad payload: {exc}"
        expected = {PREPREPARE: PrePrepare, PREPARE: Prepare,
                    COMMIT: Commit, VIEW_CHANGE: ViewChange,
                    OLD_VIEW_PREPREPARE: PrePrepare,
                    PROPAGATE: Propagate}.get(rep.msg_type)
        if expected is None or not isinstance(msg, expected):
            return DISCARD, "payload type mismatch"
        if rep.msg_type == PROPAGATE:
            # the carried request must hash to the digest we asked for —
            # the responder cannot substitute a different request
            from ...common.request import Request

            try:
                digest = Request.from_dict(dict(msg.request)).digest
            except Exception as exc:  # noqa: BLE001 — untrusted wire data
                return DISCARD, f"bad PROPAGATE payload: {exc}"
            if digest != str(rep.params.get("digest")):
                return DISCARD, "PROPAGATE digest mismatch"
            self._outstanding.discard(key)
            self._network.process_incoming(msg, sender)
            return PROCESS
        if rep.msg_type == OLD_VIEW_PREPREPARE:
            # content is authenticated by the digest we asked for (it came
            # out of NEW_VIEW's weak-quorum-supported batch id)
            orig = msg.originalViewNo if msg.originalViewNo is not None \
                else msg.viewNo
            want = rep.params
            if (str(msg.digest) != str(want.get("digest"))
                    or int(orig) != int(want.get("originalViewNo", -1))
                    or int(msg.ppSeqNo) != int(want.get("ppSeqNo", -1))):
                return DISCARD, "old-view PRE-PREPARE mismatch"
            if msg.digest != self._batch_digest_of(msg):
                return DISCARD, "old-view PRE-PREPARE digest forged"
            self._outstanding.discard(key)
            if self._ordering is not None:
                self._ordering.process_requested_old_view_pp(msg)
            return PROCESS
        if isinstance(msg, PrePrepare):
            # Requests for PRE-PREPAREs only go to the primary (see
            # process_missing_message), so the relayer IS the claimed
            # author; require the key to match what we asked for.
            requested_key = self._key_from(rep.params)
            if requested_key != (msg.viewNo, msg.ppSeqNo):
                return DISCARD, "PRE-PREPARE key mismatch"
            if self._data.primaries and \
                    sender != self._data.primaries[self._data.inst_id]:
                return DISCARD, "PRE-PREPARE response not from primary"
            frm = sender
        elif isinstance(msg, ViewChange):
            # digest binds the content: any relayer is safe
            from .view_change_service import view_change_digest

            claimed_sender = rep.params.get("sender", sender)
            if view_change_digest(msg) != rep.params.get("digest"):
                return DISCARD, "VIEW_CHANGE digest mismatch"
            frm = claimed_sender
        else:
            # a peer's own PREPARE/COMMIT: attributed to the relayer, which
            # is exactly whose vote it is
            frm = sender
        self._outstanding.discard(key)
        self._network.process_incoming(msg, frm)
        return PROCESS
