"""Primary liveness watchdog.

Reference: plenum/server/consensus/primary_connection_monitor_service.py.
If the master primary stays unreachable for ToleratePrimaryDisconnection
seconds, propose an instance change (PrimaryDisconnected -> trigger
service). Connection state comes from the ExternalBus Connected /
Disconnected events fed by the network stack.
"""
from __future__ import annotations

import logging

from ...common.event_bus import ExternalBus, InternalBus
from ...common.messages.internal_messages import (
    PrimaryDisconnected,
    PrimarySelected,
)
from ...common.timer import TimerService

logger = logging.getLogger(__name__)


class PrimaryConnectionMonitorService:
    def __init__(self,
                 data,
                 timer: TimerService,
                 bus: InternalBus,
                 network: ExternalBus,
                 config=None):
        from ...config import getConfig

        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._config = config or getConfig()
        self._primary_disconnection_time = timer.get_current_time()

        network.subscribe(ExternalBus.Connected, self._on_connected)
        network.subscribe(ExternalBus.Disconnected, self._on_disconnected)
        bus.subscribe(PrimarySelected, self._on_primary_selected)

    def _primary_connected(self) -> bool:
        primary = self._data.primary_name
        return primary is not None and (
            primary == self._data.name
            or primary in self._network.connecteds)

    def _on_connected(self, msg: ExternalBus.Connected, frm: str) -> None:
        if msg.name == self._data.primary_name:
            self._primary_disconnection_time = None
            self._timer.cancel(self._propose_view_change)

    def _on_disconnected(self, msg: ExternalBus.Disconnected,
                         frm: str) -> None:
        if msg.name == self._data.primary_name:
            self._schedule_proposal()

    def _on_primary_selected(self, msg, *args) -> None:
        if self._primary_connected():
            self._primary_disconnection_time = None
        else:
            self._schedule_proposal()

    def _schedule_proposal(self) -> None:
        self._primary_disconnection_time = self._timer.get_current_time()
        self._timer.cancel(self._propose_view_change)
        self._timer.schedule(
            self._config.ToleratePrimaryDisconnection,
            self._propose_view_change)

    def _propose_view_change(self) -> None:
        if self._primary_connected():
            return
        logger.info("%s primary %s unreachable -> propose view change",
                    self._data.name, self._data.primary_name)
        self._bus.send(PrimaryDisconnected(inst_id=self._data.inst_id))
        # keep proposing while still disconnected
        self._timer.schedule(
            self._config.ToleratePrimaryDisconnection,
            self._propose_view_change)
