"""The R in RBFT: compare the master instance against the backups.

Reference: plenum/server/monitor.py (`Monitor`). Every protocol instance
orders the same client requests under a different primary; the monitor
measures per-instance throughput (and master-vs-backup request latency)
and, when the master's ratio drops below Delta — a slow-but-alive
byzantine (or just slow) master primary — votes for a view change so a
backup's primary takes over. Crash faults are caught by the primary
connection monitor; THIS is what catches a primary that stays alive but
throttles the pool.

Checks (reference Monitor.isMasterDegraded):
- throughput: master_tp / avg(backup_tps) < DELTA
- latency: avg master latency - avg backup latency > OMEGA  (per-request
  durations from finalisation to ordering)
Both sides must be warmed up (ThroughputMinCnt events) before judging.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from ..common.event_bus import InternalBus
from ..common.messages.internal_messages import VoteForViewChange
from ..common.timer import RepeatingTimer, TimerService
from .suspicion_codes import Suspicions
from .throughput_measurement import (
    LatencyMeasurement,
    WindowedThroughputMeasurement,
)

logger = logging.getLogger(__name__)


class Monitor:
    def __init__(self,
                 name: str,
                 timer: TimerService,
                 bus: InternalBus,
                 config,
                 num_instances: int,
                 metrics=None,
                 trace=None):
        self._name = name
        self._timer = timer
        self._bus = bus
        self._config = config
        # dispatch-plane observability: when the node's collector is
        # handed in, snapshot() surfaces the device amortization numbers
        # (dispatches per tick, flush occupancy) next to the RBFT ratios
        self._metrics = metrics
        # consensus flight recorder: snapshot() derives the per-phase
        # 3PC latency percentiles from its lifecycle marks
        self._trace = trace
        # digest -> finalisation timestamp (latency measurement base)
        self._finalised_at: Dict[str, float] = {}
        self._throughputs: List[WindowedThroughputMeasurement] = []
        self._latencies: List[LatencyMeasurement] = []
        self.reset(num_instances)
        self.degradation_votes = 0  # observability / tests

        self._check_timer = RepeatingTimer(
            timer, config.PerfCheckFreq, self.service_check, active=False)

    def start(self) -> None:
        self._check_timer.start()

    def stop(self) -> None:
        self._check_timer.stop()

    # ------------------------------------------------------------------
    # feeding
    # ------------------------------------------------------------------

    def reset(self, num_instances: Optional[int] = None) -> None:
        """View change / instance count change: all measurements restart
        (the old master's stats must not taint the new one)."""
        if num_instances is None:
            num_instances = len(self._throughputs)
        now = self._timer.get_current_time()
        self._throughputs = [
            WindowedThroughputMeasurement(
                window_size=self._config.ThroughputWindowSize,
                min_cnt=self._config.ThroughputMinCnt,
                first_ts=now)
            for _ in range(num_instances)]
        self._latencies = [
            LatencyMeasurement(self._config.LatencyWindowSize)
            for _ in range(num_instances)]
        # latency bases from before the reset are meaningless against the
        # new measurements (and would otherwise leak across view changes)
        self._finalised_at.clear()

    def request_finalised(self, digest: str) -> None:
        self._finalised_at.setdefault(
            digest, self._timer.get_current_time())
        # opportunistic TTL pruning: on a single-instance node the check
        # timer never runs, and digests executed via catchup emit no
        # master Ordered — without this the dict grows for the process
        # lifetime
        if len(self._finalised_at) % 1024 == 0:
            self._prune_finalised()

    def _prune_finalised(self) -> None:
        now = self._timer.get_current_time()
        ttl = self._config.INSTANCE_CHANGE_TIMEOUT
        for d in [d for d, t in self._finalised_at.items()
                  if now - t > ttl]:
            del self._finalised_at[d]

    def requests_ordered(self, inst_id: int, digests: List[str]) -> None:
        if inst_id >= len(self._throughputs):
            return
        now = self._timer.get_current_time()
        self._throughputs[inst_id].add_request(now, count=len(digests))
        lat = self._latencies[inst_id]
        for d in digests:
            t0 = self._finalised_at.get(d)
            if t0 is not None:
                lat.add_duration(now - t0)
        if inst_id == 0:  # master ordered: the latency base is consumed
            for d in digests:
                self._finalised_at.pop(d, None)

    # ------------------------------------------------------------------
    # judging
    # ------------------------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        """Observability view for reports (chaos runs, diagnostics):
        per-instance throughput, the master/backup ratio the Delta check
        judges, and how often this node voted the master degraded."""
        now = self._timer.get_current_time()
        snap = {
            "throughput_per_instance": [
                t.get_throughput(now) for t in self._throughputs],
            "master_throughput_ratio": self.master_throughput_ratio(),
            "degradation_votes": self.degradation_votes,
        }
        if self._metrics is not None:
            from ..common.metrics_collector import MetricsName

            device = {}
            for label, name in (
                    ("dispatches_per_tick",
                     MetricsName.DEVICE_DISPATCHES_PER_TICK),
                    ("flush_occupancy",
                     MetricsName.DEVICE_FLUSH_OCCUPANCY),
                    ("flushes", MetricsName.DEVICE_FLUSH)):
                stat = self._metrics.stat(name)
                if stat is not None:
                    device[label] = {"count": stat.count,
                                     "avg": round(stat.avg, 4)}
            # adaptive tick (dispatch governor): the CURRENT effective
            # interval plus the dwell histogram — how the pool's tick
            # travelled between its bounds this run
            tick = self._metrics.stat(MetricsName.GOVERNOR_TICK_INTERVAL)
            if tick is not None:
                device["tick_interval"] = {
                    "current": tick.last,
                    "min": tick.min,
                    "max": tick.max,
                    "histogram": self._metrics.histogram(
                        MetricsName.GOVERNOR_TICK_INTERVAL),
                }
                ewma = self._metrics.stat(
                    MetricsName.GOVERNOR_OCCUPANCY_EWMA)
                if ewma is not None:
                    device["occupancy_ewma"] = round(ewma.last, 4)
            # mesh-sharded dispatch plane: mesh width + each shard's
            # CUMULATIVE occupancy (sum votes / sum real-row capacity —
            # the same VotePlaneGroup.shard_occupancy number bench, the
            # budget gate and profile_rbft report, NOT an average of
            # per-dispatch ratios, which diverges once flush shapes vary)
            # ordering fast path: what actually crosses the device->host
            # boundary per absorb, and which eval mode produced it
            # (compact deltas by default; the full event matrix under
            # the host_eval differential fallback)
            rb = self._metrics.stat(MetricsName.DEVICE_READBACK_BYTES)
            if rb is not None:
                device["readback"] = {
                    "bytes_total": int(rb.total),
                    "bytes_per_readback": round(rb.avg, 1),
                    "readbacks": rb.count,
                }
                mode = self._metrics.stat(
                    MetricsName.DEVICE_READBACK_COMPACT)
                if mode is not None:
                    device["eval_mode"] = ("device" if mode.last
                                           else "host")
            # multi-tick residency: present only when a group ran with
            # ResidentTickDepth > 1 — zero-residency snapshots stay
            # byte-compatible
            depth = self._metrics.stat(MetricsName.DEVICE_RESIDENT_DEPTH)
            if depth is not None and depth.last:
                rt = self._metrics.stat(MetricsName.DEVICE_RESIDENT_TICKS)
                rd = self._metrics.stat(
                    MetricsName.DEVICE_READBACKS_DEFERRED)
                device["residency"] = {
                    "resident_depth": int(depth.last),
                    "resident_ticks": int(rt.count) if rt else 0,
                    "readbacks_deferred": int(rd.count) if rd else 0,
                }
            shard_count = self._metrics.stat(MetricsName.DEVICE_SHARD_COUNT)
            if shard_count is not None and shard_count.last:
                n_shards = int(shard_count.last)
                occ_per_shard = []
                for si in range(n_shards):
                    votes = self._metrics.stat(
                        f"{MetricsName.DEVICE_SHARD_FLUSH_VOTES}.{si}")
                    cap = self._metrics.stat(
                        f"{MetricsName.DEVICE_SHARD_FLUSH_CAPACITY}.{si}")
                    occ_per_shard.append(
                        round(votes.total / cap.total, 4)
                        if votes and cap and cap.total else None)
                device["shards"] = n_shards
                device["shard_occupancy"] = occ_per_shard
            if device:
                snap["device_dispatch"] = device
            # ingress plane (admission control + device-proof reads):
            # the bounded queue's current/peak depth, the admitted/shed
            # totals the shed policy produced, and the read path's
            # served count + qps gauge (virtual-clock derived, so
            # snapshots replay byte-identically). Absent entirely when
            # the run never recorded ingress metrics (admission off, no
            # reads) — existing snapshots stay byte-compatible.
            ingress = {}
            depth = self._metrics.stat(MetricsName.INGRESS_QUEUE_DEPTH)
            if depth is not None:
                ingress["queue_depth"] = {"current": depth.last,
                                          "max": depth.max}
            for label, name in (
                    ("admitted", MetricsName.INGRESS_ADMITTED),
                    ("shed", MetricsName.INGRESS_SHED),
                    ("retries", MetricsName.INGRESS_RETRIES),
                    ("retry_exhausted",
                     MetricsName.INGRESS_RETRY_EXHAUSTED),
                    ("read_served", MetricsName.READ_SERVED)):
                stat = self._metrics.stat(name)
                if stat is not None:
                    ingress[label] = int(stat.total)
            # closed-loop retry goodput: the share of admitted work that
            # got in on its FIRST attempt (retry admissions are recovered
            # capacity, not fresh goodput) — present only when the run
            # recorded retries, so pre-overload-plane snapshots stay
            # byte-compatible
            if "retries" in ingress and ingress.get("admitted"):
                readmitted = self._metrics.stat(
                    MetricsName.INGRESS_RETRY_ADMITTED)
                readmitted_n = int(readmitted.total) \
                    if readmitted is not None else 0
                ingress["goodput_fraction"] = round(
                    (ingress["admitted"] - readmitted_n)
                    / ingress["admitted"], 4)
            read_qps = self._metrics.stat(MetricsName.READ_QPS)
            if read_qps is not None:
                ingress["read_qps"] = round(read_qps.last, 1)
            # read-path backpressure (state-proof plane satellite): the
            # read queue's own bounded-queue numbers, segregated from
            # the write side's
            read_depth = self._metrics.stat(MetricsName.READ_QUEUE_DEPTH)
            if read_depth is not None:
                ingress["read_queue_depth"] = {"current": read_depth.last,
                                               "max": read_depth.max}
            read_shed = self._metrics.stat(MetricsName.READ_SHED)
            if read_shed is not None:
                ingress["read_shed"] = int(read_shed.total)
            if ingress:
                snap["ingress"] = ingress
            # state-proof plane: windows captured, serve-path hit/miss
            # split, reads served WITH a pool proof, and the pairing
            # work the batched verifier performed — absent entirely when
            # the run never recorded proof metrics (plane off)
            proofs = {}
            for label, name in (
                    ("windows_signed", MetricsName.PROOF_WINDOWS_SIGNED),
                    ("cache_hits", MetricsName.PROOF_CACHE_HIT),
                    ("cache_misses", MetricsName.PROOF_CACHE_MISS),
                    ("proofs_served", MetricsName.PROOF_SERVED),
                    ("pairings", MetricsName.PROOF_PAIRINGS)):
                stat = self._metrics.stat(name)
                if stat is not None:
                    proofs[label] = int(stat.total)
            if proofs:
                snap["proofs"] = proofs
            # catchup plane (chaos-hardened recovery): leecher rounds
            # completed, txns fetched+applied, audit proofs verified on
            # leeched batches, byzantine reps rejected, and retry-law
            # re-requests — absent entirely when the node never leeched
            catchup = {}
            for label, name in (
                    ("rounds", MetricsName.CATCHUP_ROUNDS),
                    ("txns_leeched", MetricsName.CATCHUP_TXNS_LEECHED),
                    ("proofs_verified",
                     MetricsName.CATCHUP_PROOFS_VERIFIED),
                    ("reps_rejected", MetricsName.CATCHUP_REPS_REJECTED),
                    ("retries", MetricsName.CATCHUP_RETRIES),
                    ("failed", MetricsName.CATCHUP_FAILED)):
                stat = self._metrics.stat(name)
                if stat is not None:
                    catchup[label] = int(stat.total)
            if catchup:
                snap["catchup"] = catchup
            # ordering lanes (lanes/): lane count, per-lane ordered
            # totals and router assignments, and the cross-lane
            # barrier's sealed window + seal lag (first lane ready ->
            # sealed, virtual seconds) — absent entirely when the run
            # never recorded lane metrics (single-lane pools)
            lane_count = self._metrics.stat(MetricsName.LANE_COUNT)
            if lane_count is not None and lane_count.last:
                n_lanes = int(lane_count.last)
                lanes: Dict[str, object] = {"count": n_lanes}
                ordered, routed = [], []
                for li in range(n_lanes):
                    stat = self._metrics.stat(
                        f"{MetricsName.LANE_ORDERED}.{li}")
                    ordered.append(int(stat.last) if stat else 0)
                    stat = self._metrics.stat(
                        f"{MetricsName.LANE_ROUTED}.{li}")
                    routed.append(int(stat.total) if stat else 0)
                lanes["ordered_per_lane"] = ordered
                lanes["router_distribution"] = routed
                barrier = {}
                sealed = self._metrics.stat(
                    MetricsName.LANE_SEALED_WINDOW)
                if sealed is not None:
                    barrier["sealed_window"] = int(sealed.last)
                    barrier["seals"] = sealed.count
                lag = self._metrics.stat(
                    MetricsName.LANE_BARRIER_SEAL_LAG)
                if lag is not None:
                    barrier["seal_lag"] = {
                        "last": round(lag.last, 6),
                        "avg": round(lag.avg, 6),
                        "max": round(lag.max, 6),
                    }
                if barrier:
                    lanes["barrier"] = barrier
                snap["lanes"] = lanes
            # long-horizon telemetry plane (observability/telemetry.py):
            # rolled-window / fired-anomaly meters plus per-resource
            # occupancy gauges (last = at the latest roll, high_water =
            # max over rolls) — absent entirely when the run never
            # armed the plane (TelemetryWindowSec = 0)
            windows = self._metrics.stat(MetricsName.TELEMETRY_WINDOWS)
            if windows is not None and windows.count:
                from ..observability.telemetry import (
                    RESOURCE_METRIC_PREFIX,
                )

                telemetry: Dict[str, object] = {
                    "windows": int(windows.total)}
                fired = self._metrics.stat(
                    MetricsName.TELEMETRY_ANOMALIES)
                telemetry["anomalies"] = \
                    int(fired.total) if fired is not None else 0
                resources: Dict[str, object] = {}
                for name, stat in self._metrics.summary().items():
                    if name.startswith(RESOURCE_METRIC_PREFIX):
                        resources[name[len(RESOURCE_METRIC_PREFIX):]] = {
                            "last": int(stat["last"]),
                            "high_water": int(stat["max"]),
                        }
                if resources:
                    telemetry["resources"] = resources
                snap["telemetry"] = telemetry
        if self._trace is not None and self._trace.enabled:
            # per-phase latency attribution (flight recorder): where this
            # node's ordered batches spent their time — prepare / commit
            # / order / execute (+ the pool's auth phase) as p50/p90/p99
            from ..observability.trace import phase_percentiles

            phases = phase_percentiles(self._trace.events(),
                                       node=self._name)
            if phases:
                snap["phase_latency"] = phases
            # pool-rollup end-to-end latency (causal tracing plane):
            # journeys join the recorder's cross-node marks, so the
            # block reports what a CLIENT experienced — e2e percentiles
            # per request class, per-hop percentiles, and where the
            # time went (network / queue / compute / device-dispatch).
            # Pool-level on purpose: a journey spans nodes, so every
            # node's snapshot reports the same rollup.
            from ..observability.causal import journey_summary

            # the rollup is pool-level and the recorder is pool-shared:
            # cache it ON the recorder keyed by its event seq, so an
            # n-node snapshot sweep computes the journey table once per
            # ring generation instead of n times
            cache = getattr(self._trace, "_journey_rollup", None)
            if cache is not None and cache[0] == self._trace._seq:
                js = cache[1]
            else:
                js = journey_summary(self._trace.events())
                self._trace._journey_rollup = (self._trace._seq, js)
            if js["count"] or js["e2e"]["read"]["count"]:
                snap["e2e_latency"] = {
                    "write": js["e2e"]["write"],
                    "read": js["e2e"]["read"],
                    "complete": js["complete"],
                    "orphan_spans": js["orphan_spans"],
                    "hop_percentiles": js["hop_percentiles"],
                    "attribution_share": js["attribution_share"],
                    "journey_hash": js["journey_hash"],
                }
        return snap

    def master_throughput_ratio(self) -> Optional[float]:
        if len(self._throughputs) < 2:
            return None
        now = self._timer.get_current_time()
        master = self._throughputs[0].get_throughput(now)
        backups = [t.get_throughput(now) for t in self._throughputs[1:]]
        backups = [b for b in backups if b is not None]
        if not backups:
            return None
        avg = sum(backups) / len(backups)
        if avg == 0:
            return None
        if master is None:
            master = 0.0  # backups warmed up, master ordered ~nothing
        return master / avg

    def is_master_degraded(self) -> bool:
        ratio = self.master_throughput_ratio()
        if ratio is not None and ratio < self._config.DELTA:
            return True
        return self._is_master_latency_high()

    def _is_master_latency_high(self) -> bool:
        if len(self._latencies) < 2:
            return False
        master = self._latencies[0].get_avg_latency()
        if master is not None and master > self._config.LAMBDA:
            # RBFT Λ (Aublin et al. §IV): the master's ABSOLUTE request
            # latency bound — a master slow against the wall even when
            # every backup is equally slow (Ω alone cannot see that)
            return True
        backups = [l.get_avg_latency() for l in self._latencies[1:]]
        backups = [b for b in backups if b is not None]
        if master is None or not backups:
            return False
        return master - (sum(backups) / len(backups)) > self._config.OMEGA

    def service_check(self) -> None:
        self._prune_finalised()
        if self.is_master_degraded():
            self.degradation_votes += 1
            ratio = self.master_throughput_ratio()
            logger.info("%s master degraded (ratio=%s) -> vote view change",
                        self._name, ratio)
            self._bus.send(VoteForViewChange(
                view_no=None, suspicion=Suspicions.PRIMARY_DEGRADED))
