"""Per-ledger batch lifecycle hooks, including the audit-ledger spine.

Reference: plenum/server/batch_handlers/ — ``post_batch_applied`` /
``commit_batch`` / ``post_batch_rejected`` per ledger, and
``AuditBatchHandler``: one AUDIT txn per 3PC batch binding (viewNo,
ppSeqNo, every ledger's size+root, the state roots, primaries). The audit
ledger is the restart-recovery spine: on boot a node reads its last audit
txn to learn its committed 3PC height and the matching roots.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ...common.constants import (
    AUDIT,
    AUDIT_LEDGER_ID,
    AUDIT_TXN_DIGEST,
    AUDIT_TXN_LEDGER_ROOT,
    AUDIT_TXN_LEDGERS_SIZE,
    AUDIT_TXN_PP_SEQ_NO,
    AUDIT_TXN_PRIMARIES,
    AUDIT_TXN_STATE_ROOT,
    AUDIT_TXN_VIEW_NO,
    CURRENT_TXN_VERSION,
    TXN_METADATA,
    TXN_PAYLOAD,
    TXN_PAYLOAD_DATA,
    TXN_PAYLOAD_METADATA,
    TXN_SIGNATURE,
    TXN_TYPE,
    TXN_VERSION,
)
from ...common.txn_util import get_payload_data
from ...utils.base58 import b58encode
from ..database_manager import DatabaseManager
from .three_pc_batch import ThreePcBatch


class BatchHandler:
    """Lifecycle hooks one ledger (or cross-cutting store) implements."""

    def __init__(self, database_manager: DatabaseManager, ledger_id: int):
        self.database_manager = database_manager
        self.ledger_id = ledger_id

    @property
    def ledger(self):
        return self.database_manager.get_ledger(self.ledger_id)

    @property
    def state(self):
        return self.database_manager.get_state(self.ledger_id)

    def post_batch_applied(self, batch: ThreePcBatch,
                           prev_result: Any = None) -> Any:
        """Batch speculatively applied (uncommitted)."""

    def post_batch_rejected(self, ledger_id: int,
                            prev_result: Any = None) -> Any:
        """The LAST applied batch for ledger_id is being reverted."""

    def commit_batch(self, batch: ThreePcBatch,
                     prev_result: Any = None) -> Any:
        """Batch ordered: move staged txns/state to committed."""


class LedgerBatchHandler(BatchHandler):
    """Generic domain/pool/config handler: commit/discard staged txns and
    advance the state's committed head to the batch's recorded root."""

    def post_batch_applied(self, batch: ThreePcBatch, prev_result=None):
        pass  # txns were staged by WriteRequestManager.apply_request

    def post_batch_rejected(self, ledger_id: int, prev_result=None):
        pass  # ledger discard + state head rewind handled by the manager

    def commit_batch(self, batch: ThreePcBatch, prev_result=None):
        count = len(batch.valid_digests)
        if count:
            self.ledger.commit_txns(count)
        if self.state is not None and batch.state_root is not None:
            self.state.commit(batch.state_root)


class AuditBatchHandler(BatchHandler):
    """Writes one AUDIT txn per 3PC batch (any ledger) — the recovery spine.

    Reference: plenum/server/batch_handlers/audit_batch_handler.py. The
    audit ledger has no state; its txns bind everything needed to restore
    a node's 3PC position and root expectations after restart.
    """

    def __init__(self, database_manager: DatabaseManager):
        super().__init__(database_manager, AUDIT_LEDGER_ID)

    def build_audit_txn(self, batch: ThreePcBatch) -> Dict[str, Any]:
        sizes: Dict[str, int] = {}
        roots: Dict[str, str] = {}
        states: Dict[str, str] = {}
        for lid in self.database_manager.ledger_ids:
            if lid == AUDIT_LEDGER_ID:
                continue
            ledger = self.database_manager.get_ledger(lid)
            sizes[str(lid)] = ledger.uncommitted_size
            roots[str(lid)] = b58encode(ledger.uncommitted_root_hash)
            state = self.database_manager.get_state(lid)
            if state is not None:
                states[str(lid)] = b58encode(state.head_hash)
        return {
            TXN_VERSION: CURRENT_TXN_VERSION,
            TXN_PAYLOAD: {
                TXN_TYPE: AUDIT,
                TXN_PAYLOAD_DATA: {
                    AUDIT_TXN_VIEW_NO: batch.view_no,
                    AUDIT_TXN_PP_SEQ_NO: batch.pp_seq_no,
                    AUDIT_TXN_LEDGERS_SIZE: sizes,
                    AUDIT_TXN_LEDGER_ROOT: roots,
                    AUDIT_TXN_STATE_ROOT: states,
                    AUDIT_TXN_PRIMARIES: list(batch.primaries),
                    AUDIT_TXN_DIGEST: batch.pp_digest,
                },
                TXN_PAYLOAD_METADATA: {},
            },
            TXN_METADATA: {},
            TXN_SIGNATURE: {},
        }

    def post_batch_applied(self, batch: ThreePcBatch, prev_result=None):
        txn = self.build_audit_txn(batch)
        self.ledger.append_txns([txn])
        return txn

    def post_batch_rejected(self, ledger_id: int, prev_result=None):
        self.ledger.discard_txns(1)

    def commit_batch(self, batch: ThreePcBatch, prev_result=None):
        _, committed = self.ledger.commit_txns(1)
        return committed[0]

    # --- recovery reads -------------------------------------------------

    def last_committed_audit_data(self) -> Optional[Dict[str, Any]]:
        if self.ledger.size == 0:
            return None
        return get_payload_data(self.ledger.get_by_seq_no(self.ledger.size))

    def committed_pp_seq_no(self) -> int:
        data = self.last_committed_audit_data()
        return data[AUDIT_TXN_PP_SEQ_NO] if data else 0

    def audit_data_for_seq(self, pp_seq_no: int) -> Optional[Dict[str, Any]]:
        """Audit txns are 1:1 with 3PC batches, so ledger seqNo == the
        batch's position in the total order; ppSeqNo is monotone across
        views but may skip after view changes, so scan back when needed."""
        size = self.ledger.size
        if size == 0:
            return None
        guess = min(pp_seq_no, size)
        for seq in range(guess, 0, -1):
            data = get_payload_data(self.ledger.get_by_seq_no(seq))
            if data[AUDIT_TXN_PP_SEQ_NO] == pp_seq_no:
                return data
            if data[AUDIT_TXN_PP_SEQ_NO] < pp_seq_no:
                return None
        return None
