"""Value object describing one 3PC batch flowing through the batch handlers.

Reference: plenum/server/batch_handlers/three_pc_batch.py (`ThreePcBatch`).
"""
from __future__ import annotations

from typing import List, Optional


class ThreePcBatch:
    def __init__(self,
                 ledger_id: int,
                 inst_id: int,
                 view_no: int,
                 pp_seq_no: int,
                 pp_time: int,
                 state_root: Optional[bytes],
                 txn_root: Optional[bytes],
                 valid_digests: List[str],
                 pp_digest: str = "",
                 primaries: Optional[List[str]] = None,
                 original_view_no: Optional[int] = None):
        self.ledger_id = ledger_id
        self.inst_id = inst_id
        self.view_no = view_no
        self.pp_seq_no = pp_seq_no
        self.pp_time = pp_time
        self.state_root = state_root
        self.txn_root = txn_root
        self.valid_digests = list(valid_digests)
        self.pp_digest = pp_digest
        self.primaries = primaries or []
        self.original_view_no = original_view_no \
            if original_view_no is not None else view_no

    def __repr__(self):
        return (f"ThreePcBatch(lid={self.ledger_id}, "
                f"3pc=({self.view_no},{self.pp_seq_no}), "
                f"n={len(self.valid_digests)})")
