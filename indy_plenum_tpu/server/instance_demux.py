"""Per-instance routing of 3PC traffic: one subscription, one O(1) hop.

Reference: plenum's Node delivers replica-bound messages into the TARGET
replica's inbox keyed by ``instId`` (plenum/server/node.py `sendToReplica`
/ `msgHasAcceptableInstId`); it never lets every replica inspect every
message. Without this, k protocol instances subscribed to one shared
external bus each run their full router pass over EVERY inbound 3PC
message and k-1 of them discard it — measured 22x handler amplification at
f+1=22 instances (n=64), the single largest host cost in the full-RBFT
configuration.

The demux owns the ONLY external-bus subscription for the per-instance
message types; each instance (master included) registers its
StashingRouter under its ``inst_id``. Messages for unknown instances are
dropped (the reference discards those too — a byzantine peer must not
make a node pay for instances it doesn't run).
"""
from __future__ import annotations

import logging
from typing import Dict

from ..common.messages.node_messages import (
    Checkpoint,
    Commit,
    PrePrepare,
    Prepare,
)

logger = logging.getLogger(__name__)

# every message type whose schema carries ``instId`` and which a
# per-instance service consumes from the network
INSTANCE_TYPES = (PrePrepare, Prepare, Commit, Checkpoint)


class Instance3PCDemux:
    def __init__(self, external_bus):
        self._bus = external_bus
        self._stashers: Dict[int, object] = {}
        for mtype in INSTANCE_TYPES:
            external_bus.subscribe(mtype, self._route)

    def register(self, inst_id: int, stasher) -> None:
        self._stashers[inst_id] = stasher

    def unregister(self, inst_id: int) -> None:
        self._stashers.pop(inst_id, None)

    def close(self) -> None:
        for mtype in INSTANCE_TYPES:
            self._bus.unsubscribe(mtype, self._route)
        self._stashers.clear()

    def _route(self, msg, frm: str) -> None:
        stasher = self._stashers.get(getattr(msg, "instId", 0))
        if stasher is None:
            logger.debug("dropping %s for unknown instance %s",
                         type(msg).__name__, getattr(msg, "instId", 0))
            return
        stasher.process(msg, frm)
