"""Pool manager: committed NODE txns become live membership.

Reference: plenum/server/pool_manager.py (`TxnPoolManager`). The pool
ledger IS the membership authority: every committed NODE txn (added,
edited, promoted, demoted via the services field) updates the validator
registry, and from it the consensus quorums (ConsensusSharedData), the
BLS key register (PoP-checked), and — through the composition callback —
transport connections and the device vote plane's validator axis.

Validator ORDER is the pool-ledger first-seen order (round-robin primary
selection must be identical on every node); demotion removes a name from
the active set but keeps its slot in the ordering history.

The registry keys validators by ALIAS (protocol names); the NODE txn's
dest nym identifies the node's signing identity.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

from ..common.constants import (
    ALIAS,
    BLS_KEY,
    BLS_KEY_PROOF,
    NODE,
    SERVICES,
    TARGET_NYM,
    VALIDATOR,
)
from ..common.txn_util import get_payload_data, get_type
from .consensus.consensus_shared_data import ConsensusSharedData

logger = logging.getLogger(__name__)


class PoolManager:
    def __init__(self,
                 node_name: str,
                 data: ConsensusSharedData,
                 bls_key_register=None,
                 on_membership_changed: Optional[
                     Callable[[List[str], Dict[str, dict]], None]] = None):
        self._node_name = node_name
        self._data = data
        self._bls_register = bls_key_register
        self._on_changed = on_membership_changed
        # alias -> merged record, insertion-ordered (= pool ledger order)
        self.registry: Dict[str, dict] = {}

    # ------------------------------------------------------------------

    @staticmethod
    def _is_active(record: dict) -> bool:
        services = record.get(SERVICES)
        if services is None:
            return True  # never demoted
        return VALIDATOR in services

    @property
    def validators(self) -> List[str]:
        return [alias for alias, rec in self.registry.items()
                if self._is_active(rec)]

    def node_record(self, alias: str) -> Optional[dict]:
        return self.registry.get(alias)

    # ------------------------------------------------------------------

    def init_from_ledger(self, pool_ledger) -> None:
        """Replay the committed pool ledger into the registry (node boot /
        restart). An empty pool ledger leaves static membership in place."""
        for _, txn in pool_ledger.get_all_txn():
            self._absorb(txn)
        if self.registry:
            self._reconfigure(notify=False)

    def refresh_from_ledger(self, pool_ledger) -> None:
        """Re-absorb the whole committed pool ledger (post-catchup: txns
        fetched by the leecher bypass the execution hook)."""
        before = self._snapshot()
        for _, txn in pool_ledger.get_all_txn():
            self._absorb(txn)
        if self.registry:
            self._reconfigure(notify=True,
                              records_changed=self._snapshot() != before)

    def process_committed_txn(self, txn: Dict[str, Any]) -> None:
        """Feed from execution: a NODE txn just committed on this node."""
        if get_type(txn) != NODE:
            return
        before = self._snapshot()
        self._absorb(txn)
        self._reconfigure(notify=True,
                          records_changed=self._snapshot() != before)

    def _snapshot(self) -> Dict[str, dict]:
        return {alias: dict(rec) for alias, rec in self.registry.items()}

    def _absorb(self, txn: Dict[str, Any]) -> None:
        if get_type(txn) != NODE:
            return
        payload = get_payload_data(txn)
        node_data = dict(payload.get("data") or {})
        alias = node_data.get(ALIAS)
        if not alias:
            return
        record = {**self.registry.get(alias, {}), **node_data,
                  "nym": payload.get(TARGET_NYM)}
        self.registry[alias] = record

    def _reconfigure(self, notify: bool,
                     records_changed: bool = False) -> None:
        """``records_changed``: a NODE txn altered a record WITHOUT
        changing the active set — key/address rotation. The composition
        hook must still fire (peers restart that connection with the new
        key), even though quorums are untouched."""
        new_validators = self.validators
        if not new_validators:
            logger.warning("%s: pool ledger yields an EMPTY validator set; "
                           "keeping current membership", self._node_name)
            return
        changed = new_validators != self._data.validators
        if changed:
            logger.info("%s: pool membership -> %s (n=%d, f=%d)",
                        self._node_name, new_validators,
                        len(new_validators),
                        (len(new_validators) - 1) // 3)
            self._data.set_validators(new_validators)
        self._sync_bls_keys()
        if (changed or records_changed) and notify \
                and self._on_changed is not None:
            self._on_changed(new_validators, dict(self.registry),
                             set_changed=changed)

    def _sync_bls_keys(self) -> None:
        if self._bls_register is None:
            return
        for alias, rec in self.registry.items():
            pk = rec.get(BLS_KEY)
            if not self._is_active(rec):
                self._bls_register.remove_key(alias)
            elif pk and self._bls_register.get_key(alias) != pk:
                # PoP required: a rogue-key aggregation needs possession
                self._bls_register.add_key(
                    alias, pk, rec.get(BLS_KEY_PROOF), require_pop=True)
