"""Backup protocol instances: the parallel half of RBFT.

Reference: plenum/server/replicas.py (`Replicas`) + plenum/server/replica.py
(the per-instance `Replica`). The pool runs f+1 protocol instances over the
SAME finalised requests under DIFFERENT primaries (round-robin offset by
instance id); only the master's (inst 0) ordering executes, the backups
exist so the :class:`~indy_plenum_tpu.server.monitor.Monitor` has a live
baseline to judge the master against — a slow-but-alive byzantine master
primary is caught because some backup keeps ordering at full speed.

Each backup bundles its own ConsensusSharedData / StashingRouter /
OrderingService / CheckpointService on a PRIVATE internal bus (its Ordered
events feed the monitor, never the executor), sharing the node's external
bus; instance isolation is by ``instId`` filtering in the services. On a
view change backups are torn down and rebuilt for the new view (reference:
Replicas.remove_replica/grow on view change), restarting their
measurements with the new primaries.

TPU note: with a ``vote_plane_factory`` the backups' quorum tallies ride
the device plane's (node x instance) member axis
(tpu.vote_plane.VotePlaneGroup) in the SAME vmapped dispatch as the
master's — the RBFT instance axis is a leading tensor dimension, so the
monitor's baseline is measured against an equally-fast tally path (SURVEY
§2.6 TPU mapping). Without a factory, backups fall back to host dicts.
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional

from ..common.event_bus import ExternalBus, InternalBus
from ..common.messages.internal_messages import RequestPropagates
from ..common.messages.node_messages import Ordered
from ..common.request import Request
from ..common.stashing_router import StashingRouter
from ..common.timer import TimerService
from .consensus.checkpoint_service import CheckpointService
from .consensus.consensus_shared_data import ConsensusSharedData
from .consensus.ordering_service import OrderingService

logger = logging.getLogger(__name__)


class BackupReplica:
    """One backup instance's service bundle."""

    def __init__(self,
                 node_name: str,
                 validators: List[str],
                 inst_id: int,
                 view_no: int,
                 primaries: List[str],
                 timer: TimerService,
                 external_bus: ExternalBus,
                 config,
                 requests_pool,
                 on_ordered: Callable[[Ordered], None],
                 forward_request_propagates: Optional[Callable] = None,
                 vote_plane=None,
                 demux=None):
        self.inst_id = inst_id
        self.data = ConsensusSharedData(
            node_name, validators, inst_id=inst_id, is_master=False,
            log_size=config.LOG_SIZE)
        self.data.view_no = view_no
        self.data.primaries = list(primaries)
        self.internal_bus = InternalBus()
        # with a demux (Instance3PCDemux), inbound 3PC traffic reaches
        # THIS instance's router via one O(1) instId hop instead of every
        # instance running its router over every message — without it
        # (demux=None, the pre-round-5 shape) the stasher subscribes the
        # shared external bus directly
        self._demux = demux
        buses = [self.internal_bus] if demux is not None \
            else [self.internal_bus, external_bus]
        self.stasher = StashingRouter(limit=1000, buses=buses)
        if demux is not None:
            demux.register(inst_id, self.stasher)
        self.requests_pool = requests_pool
        self.vote_plane = vote_plane
        self.ordering = OrderingService(
            data=self.data, timer=timer, bus=self.internal_bus,
            network=external_bus, stasher=self.stasher,
            executor=None, requests=requests_pool, config=config,
            vote_plane=vote_plane)
        self.checkpoints = CheckpointService(
            data=self.data, bus=self.internal_bus,
            network=external_bus, stasher=self.stasher, config=config,
            vote_plane=vote_plane)
        self._on_ordered = on_ordered
        self.internal_bus.subscribe(Ordered, self._handle_ordered)
        if forward_request_propagates is not None:
            self.internal_bus.subscribe(RequestPropagates,
                                        forward_request_propagates)

    def _handle_ordered(self, ordered: Ordered, *args) -> None:
        self.requests_pool.mark_ordered(ordered.reqIdr)
        self._on_ordered(ordered)

    def start(self) -> None:
        self.ordering.start()

    def stop(self) -> None:
        self.ordering.stop()
        if self._demux is not None:
            self._demux.unregister(self.inst_id)
        self.stasher.unsubscribe_all()


class Replicas:
    """Grow/shrink/rebuild the backup instances of one node."""

    def __init__(self,
                 node_name: str,
                 validators: List[str],
                 timer: TimerService,
                 external_bus: ExternalBus,
                 config,
                 make_requests_pool: Callable[[], object],
                 on_backup_ordered: Callable[[int, Ordered], None],
                 forward_request_propagates: Optional[Callable] = None,
                 num_instances: Optional[int] = None,
                 vote_plane_factory: Optional[Callable] = None,
                 demux=None):
        self._node_name = node_name
        # a list, or a zero-arg provider of the CURRENT validator set —
        # rebuilt backups must see live membership, not the boot-time list
        self._validators = (validators if callable(validators)
                            else (lambda: validators))
        self._timer = timer
        self._external_bus = external_bus
        self._config = config
        self._make_requests_pool = make_requests_pool
        self._on_backup_ordered = on_backup_ordered
        self._forward_request_propagates = forward_request_propagates
        # inst_id -> DeviceVotePlane view: backups' tallies ride the SAME
        # vmapped (node x instance) group dispatch as the master's (SURVEY
        # §2.6's TPU mapping: instances = leading axis on the vote tensors)
        self._vote_plane_factory = vote_plane_factory
        self._demux = demux
        # instance count the NODE was sized for (monitor slots, primaries
        # list length) — not re-derived here, or the two could disagree
        self._num_instances = (
            num_instances if num_instances is not None
            else config.replicas_count(len(self._validators())))
        self.backups: List[BackupReplica] = []

    @property
    def num_instances(self) -> int:
        return self._num_instances

    def build(self, view_no: int, primaries: List[str]) -> None:
        """(Re)create backups for ``view_no`` with CURRENT membership."""
        self.teardown()
        for inst_id in range(1, self._num_instances):
            plane = None
            if self._vote_plane_factory is not None:
                plane = self._vote_plane_factory(inst_id)
                if plane is not None:
                    # a rebuilt instance must not inherit the old view's
                    # votes (the master's plane resets on view change too)
                    plane.reset(h=0)
            replica = BackupReplica(
                self._node_name, self._validators(), inst_id, view_no,
                primaries, self._timer, self._external_bus, self._config,
                requests_pool=self._make_requests_pool(),
                on_ordered=lambda o, i=inst_id: self._on_backup_ordered(i, o),
                forward_request_propagates=self._forward_request_propagates,
                vote_plane=plane,
                demux=self._demux)
            replica.start()
            self.backups.append(replica)
        logger.debug("%s built %d backup instance(s) for view %d",
                     self._node_name, len(self.backups), view_no)

    def teardown(self) -> None:
        for replica in self.backups:
            replica.stop()
        self.backups.clear()

    def enqueue_finalised(self, request: Request) -> None:
        for replica in self.backups:
            replica.requests_pool.enqueue(request)
            replica.ordering.on_request_finalised()
