"""Catalogued byzantine-evidence codes.

Reference: plenum/server/suspicion_codes.py (`Suspicion`, `Suspicions`).
Raised as :class:`indy_plenum_tpu.common.exceptions.SuspiciousNode`; the node
counts them per peer and can blacklist.
"""
from __future__ import annotations

from typing import NamedTuple


class Suspicion(NamedTuple):
    code: int
    reason: str


class Suspicions:
    PPR_FRM_NON_PRIMARY = Suspicion(1, "PRE-PREPARE from a non-primary")
    PR_FRM_PRIMARY = Suspicion(2, "PREPARE from the primary")
    DUPLICATE_PPR_SENT = Suspicion(3, "duplicate PRE-PREPARE for a 3PC key")
    DUPLICATE_PR_SENT = Suspicion(4, "duplicate PREPARE from one sender")
    DUPLICATE_CM_SENT = Suspicion(5, "duplicate COMMIT from one sender")
    PPR_DIGEST_WRONG = Suspicion(6, "PRE-PREPARE request digest mismatch")
    PR_DIGEST_WRONG = Suspicion(7, "PREPARE digest mismatch")
    CM_DIGEST_WRONG = Suspicion(8, "COMMIT digest mismatch")
    PPR_STATE_WRONG = Suspicion(9, "PRE-PREPARE state root mismatch on re-apply")
    PPR_TXN_WRONG = Suspicion(10, "PRE-PREPARE txn root mismatch on re-apply")
    PR_STATE_WRONG = Suspicion(11, "PREPARE state root mismatch")
    PR_TXN_WRONG = Suspicion(12, "PREPARE txn root mismatch")
    PPR_TIME_WRONG = Suspicion(13, "PRE-PREPARE timestamp out of bounds")
    CM_BLS_WRONG = Suspicion(14, "COMMIT BLS signature invalid")
    PPR_BLS_MULTISIG_WRONG = Suspicion(15, "PRE-PREPARE BLS multi-sig invalid")
    PPR_AUDIT_TXN_ROOT_WRONG = Suspicion(16, "PRE-PREPARE audit root mismatch")
    PPR_DISCARDED_WRONG = Suspicion(
        17, "PRE-PREPARE discarded count mismatch on re-apply")
    INSTANCE_CHANGE_SPOOFED = Suspicion(20, "INSTANCE_CHANGE signature bad")
    VIEW_CHANGE_WRONG = Suspicion(21, "VIEW_CHANGE malformed or inconsistent")
    NEW_VIEW_INVALID = Suspicion(22, "NEW_VIEW does not match VIEW_CHANGEs")
    NEW_VIEW_CHECKPOINT_WRONG = Suspicion(
        23, "NEW_VIEW checkpoint not supported by view-change quorum")
    CHK_DIGEST_WRONG = Suspicion(24, "CHECKPOINT digest mismatch at stable")
    PRIMARY_DEGRADED = Suspicion(
        25, "master primary degraded (throughput/latency vs backups)")
    PRIMARY_DEMOTED = Suspicion(
        26, "master primary left the validator set (NODE txn demotion)")
    PRIMARY_DISCONNECTED = Suspicion(
        27, "primary unreachable past ToleratePrimaryDisconnection")
    ORDERING_STALLED = Suspicion(
        28, "no ordering progress with requests pending "
            "(PBFT liveness timer expired)")
    SEQ_NO_OLD = Suspicion(30, "3PC message below watermark")
    SEQ_NO_FUTURE = Suspicion(31, "3PC message above watermark")
    CATCHUP_REP_WRONG = Suspicion(40, "CATCHUP_REP txns fail audit proof")
    LEDGER_STATUS_WRONG = Suspicion(41, "LEDGER_STATUS inconsistent")
    CATCHUP_FAILED = Suspicion(
        42, "catchup failed after divergence conviction; node stays "
            "non-participating (fail-closed) and retries on backoff")
    PROPAGATE_DIGEST_WRONG = Suspicion(50, "PROPAGATE digest != request digest")

    @classmethod
    def get_by_code(cls, code: int) -> Suspicion | None:
        for val in vars(cls).values():
            if isinstance(val, Suspicion) and val.code == code:
                return val
        return None
