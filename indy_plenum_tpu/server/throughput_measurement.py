"""Windowed throughput / latency measurement for the RBFT monitor.

Reference: plenum/server/throughput_measurement.py
(`ThroughputMeasurement`, the windowed/EMA variants behind Monitor's
Delta check). Events (ordered requests) are accumulated into fixed-size
time windows; throughput is the event rate over the completed windows
inside the lookback horizon. Until ``min_cnt`` events have been observed
the measurement is None — a fresh instance must not be judged degraded
against an established one (the reference's "revival spike" guard).
"""
from __future__ import annotations

from collections import deque
from typing import Deque, Optional, Tuple


class WindowedThroughputMeasurement:
    def __init__(self, window_size: float = 15.0, lookback_windows: int = 4,
                 min_cnt: int = 16, first_ts: float = 0.0):
        self._window = window_size
        self._lookback = lookback_windows
        self._min_cnt = min_cnt
        self._start = first_ts
        self._windows: Deque[Tuple[int, int]] = deque()  # (win_idx, count)
        self._total = 0

    def _win(self, ts: float) -> int:
        return int((ts - self._start) // self._window)

    def add_request(self, ts: float, count: int = 1) -> None:
        w = self._win(ts)
        if self._windows and self._windows[-1][0] == w:
            idx, c = self._windows[-1]
            self._windows[-1] = (idx, c + count)
        else:
            self._windows.append((w, count))
        self._total += count
        self._gc(w)

    def _gc(self, current_win: int) -> None:
        floor = current_win - self._lookback
        while self._windows and self._windows[0][0] < floor:
            self._windows.popleft()

    def get_throughput(self, now: float) -> Optional[float]:
        """Events/sec over the lookback horizon; None until warmed up."""
        if self._total < self._min_cnt:
            return None
        current = self._win(now)
        self._gc(current)
        # completed windows only: the in-progress window undercounts
        counted = sum(c for w, c in self._windows if w < current)
        span = self._lookback * self._window
        return counted / span


class LatencyMeasurement:
    """Average request latency over a sliding event window."""

    def __init__(self, window_count: int = 15):
        self._samples: Deque[float] = deque(maxlen=window_count)

    def add_duration(self, seconds: float) -> None:
        self._samples.append(seconds)

    def get_avg_latency(self) -> Optional[float]:
        if not self._samples:
            return None
        return sum(self._samples) / len(self._samples)
