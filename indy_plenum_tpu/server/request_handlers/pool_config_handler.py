"""POOL_CONFIG handler: the config ledger's write path.

Reference: the config-ledger request handlers under
plenum/server/request_handlers/ (+ indy-node's pool_config handler, whose
``writes`` flag semantics this follows) and
plenum/server/batch_handlers/config_batch_handler.py (the batch side here
is the generic :class:`LedgerBatchHandler` registered for
CONFIG_LEDGER_ID — the config ledger commits like any stateful ledger).

A committed ``{writes: false}`` observably changes behaviour on every
node: client WRITE requests are NACKed at ingress
(`Node.submit_client_request`) until a trustee re-enables them. The flag
lives in config STATE, so it survives restart (state rebuild from the
config ledger) and reaches lagging nodes through catchup.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import msgpack

from ...common.constants import (
    CONFIG_LEDGER_ID,
    POOL_CONFIG,
    TRUSTEE,
    WRITES,
)
from ...common.exceptions import (
    InvalidClientRequest,
    UnauthorizedClientRequest,
)
from ...common.request import Request
from ...common.txn_util import get_payload_data
from .handler_interfaces import WriteRequestHandler

_STATE_KEY = b"config:writes"


class PoolConfigHandler(WriteRequestHandler):
    def __init__(self, database_manager, get_nym_data=None):
        super().__init__(database_manager, POOL_CONFIG, CONFIG_LEDGER_ID)
        # (nym, is_committed) -> dict | None; injected from the NymHandler
        self._get_nym_data = get_nym_data
        # is_committed -> (state root the value was read at, value)
        self._cache = {}

    def static_validation(self, request: Request) -> None:
        self._validate_type(request)
        writes = request.operation.get(WRITES)
        if not isinstance(writes, bool):
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                "POOL_CONFIG needs a boolean 'writes'")

    def dynamic_validation(self, request: Request,
                           req_pp_time: Optional[int]) -> None:
        """Only a TRUSTEE may change pool-wide parameters (reference auth
        rule for config writes)."""
        if self._get_nym_data is None:
            return
        author = self._get_nym_data(request.identifier, False)
        if author is None or author.get("role") != TRUSTEE:
            raise UnauthorizedClientRequest(
                request.identifier, request.reqId,
                "only a TRUSTEE may write POOL_CONFIG")

    def update_state(self, txn: Dict[str, Any], prev_result: Any,
                     request: Optional[Request] = None,
                     is_committed: bool = False) -> Any:
        data = get_payload_data(txn)
        record = {WRITES: bool(data.get(WRITES, True))}
        self.state.set(_STATE_KEY,
                       msgpack.packb(record, use_bin_type=True))
        return record

    # ------------------------------------------------------------------

    def writes_enabled(self, is_committed: bool = True) -> bool:
        """The live flag (default True when never set). Root-keyed cache:
        this sits on the per-request ingress hot path, and an SMT walk +
        msgpack unpack per request would tax the north-star throughput for
        a flag that changes only when a POOL_CONFIG txn commits."""
        if self.state is None:
            return True
        root = (self.state.committed_head_hash if is_committed
                else self.state.head_hash)
        cached = self._cache.get(is_committed)
        if cached is not None and cached[0] == root:
            return cached[1]
        raw = self.state.get(_STATE_KEY, is_committed=is_committed)
        value = True if raw is None else bool(
            msgpack.unpackb(raw, raw=False).get(WRITES, True))
        self._cache[is_committed] = (root, value)
        return value
