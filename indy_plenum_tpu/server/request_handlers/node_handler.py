"""NODE handler: validator membership on the pool ledger.

Reference: plenum/server/request_handlers/node_handler.py (`NodeHandler`).
State layout: key = node nym, value = msgpack {alias, node_ip, node_port,
client_ip, client_port, services, blskey, blskey_pop, steward}.
Membership changes flow through consensus itself; the pool manager watches
committed NODE txns and reconfigures stacks/replicas.

Rules (reference semantics): only a STEWARD may add a node; one node per
steward; only the owning steward may edit its node; demotion/promotion via
the services field.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import msgpack

from ...common.constants import (
    ALIAS,
    BLS_KEY,
    BLS_KEY_PROOF,
    CLIENT_IP,
    CLIENT_PORT,
    NODE,
    NODE_IP,
    NODE_PORT,
    POOL_LEDGER_ID,
    SERVICES,
    STEWARD,
    TARGET_NYM,
    VALIDATOR,
)
from ...common.exceptions import (
    InvalidClientRequest,
    UnauthorizedClientRequest,
)
from ...common.request import Request
from ...common.txn_util import get_payload_data
from .handler_interfaces import WriteRequestHandler


class NodeHandler(WriteRequestHandler):
    def __init__(self, database_manager, get_nym_data=None):
        super().__init__(database_manager, NODE, POOL_LEDGER_ID)
        # (nym, is_committed) -> dict | None; injected from the NymHandler
        self._get_nym_data = get_nym_data

    def static_validation(self, request: Request) -> None:
        self._validate_type(request)
        op = request.operation
        if not op.get(TARGET_NYM):
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "dest (node nym) is required")
        data = op.get("data") or {}
        if not isinstance(data, dict) or not data.get(ALIAS):
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "data.alias is required")
        services = data.get(SERVICES)
        if services is not None:
            if not isinstance(services, list) or \
                    any(s != VALIDATOR for s in services):
                raise InvalidClientRequest(
                    request.identifier, request.reqId,
                    f"services may only contain {VALIDATOR!r}")
        for port_field in (NODE_PORT, CLIENT_PORT):
            port = data.get(port_field)
            if port is not None and not (0 < int(port) < 65536):
                raise InvalidClientRequest(request.identifier, request.reqId,
                                           f"bad {port_field}: {port}")

    def dynamic_validation(self, request: Request,
                           req_pp_time: Optional[int]) -> None:
        op = request.operation
        dest = op[TARGET_NYM]
        author_nym = None
        if self._get_nym_data is not None:
            author_nym = self._get_nym_data(request.identifier, False)
        existing = self.get_node_data(dest, is_committed=False)
        if existing is None:
            if self._get_nym_data is not None and (
                    author_nym is None or author_nym.get("role") != STEWARD):
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "only a STEWARD may add a node")
            if self._steward_has_node(request.identifier):
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "steward already operates a node")
        else:
            if existing.get("steward") != request.identifier:
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "only the owning steward may edit its node")

    def update_state(self, txn: Dict[str, Any], prev_result,
                     request=None, is_committed: bool = False):
        data = get_payload_data(txn)
        dest = data[TARGET_NYM]
        node_data = dict(data.get("data") or {})
        existing = self.get_node_data(dest, is_committed=False) or {}
        record = {**existing, **node_data}
        from ...common.txn_util import get_from

        record.setdefault("steward", get_from(txn))
        self.state.set(dest.encode(),
                       msgpack.packb(record, use_bin_type=True))
        return record

    # ------------------------------------------------------------------

    def get_node_data(self, nym: str, is_committed: bool = True
                      ) -> Optional[Dict]:
        raw = self.state.get(nym.encode(), is_committed=is_committed)
        return msgpack.unpackb(raw, raw=False) if raw is not None else None

    def _steward_has_node(self, steward_nym: Optional[str]) -> bool:
        # linear scan over committed pool ledger (pool is small)
        ledger = self.ledger
        if ledger is None or steward_nym is None:
            return False
        for _, txn in ledger.get_all_txn():
            from ...common.txn_util import get_from, get_type

            if get_type(txn) == NODE and get_from(txn) == steward_nym:
                return True
        return False
