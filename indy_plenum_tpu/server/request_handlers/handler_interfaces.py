"""Request handler base classes: the per-txn-type execution plugin seam.

Reference: plenum/server/request_handlers/handler_interfaces/ --
`WriteRequestHandler` (static_validation / dynamic_validation /
update_state hooks) and `ReadRequestHandler` (get_result + state proofs).
Handlers are registered per txn type with the request managers; adding a
new transaction type is: subclass, register (same plugin model as the
reference's ledger request handlers).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, Optional

from ...common.exceptions import InvalidClientRequest
from ...common.request import Request
from ..database_manager import DatabaseManager


class RequestHandler(ABC):
    def __init__(self, database_manager: DatabaseManager, txn_type: str,
                 ledger_id: Optional[int]):
        self.database_manager = database_manager
        self.txn_type = txn_type
        self.ledger_id = ledger_id

    @property
    def ledger(self):
        return self.database_manager.get_ledger(self.ledger_id)

    @property
    def state(self):
        return self.database_manager.get_state(self.ledger_id)


class WriteRequestHandler(RequestHandler):
    @abstractmethod
    def static_validation(self, request: Request) -> None:
        """Schema-level checks, no state access. Raise InvalidClientRequest."""

    @abstractmethod
    def dynamic_validation(self, request: Request,
                           req_pp_time: Optional[int]) -> None:
        """Checks against *uncommitted* state (auth rules, conflicts).
        Raise UnauthorizedClientRequest / InvalidClientRequest."""

    @abstractmethod
    def update_state(self, txn: Dict[str, Any], prev_result: Any,
                     request: Optional[Request] = None,
                     is_committed: bool = False) -> Any:
        """Apply the txn to the (uncommitted) state."""

    # helpers
    def _validate_type(self, request: Request) -> None:
        if request.txn_type != self.txn_type:
            raise InvalidClientRequest(
                request.identifier, request.reqId,
                f"handler for {self.txn_type} got {request.txn_type}")


class ReadRequestHandler(RequestHandler):
    @abstractmethod
    def get_result(self, request: Request) -> Dict[str, Any]:
        ...


class ActionHandler(RequestHandler):
    """Pool actions (restart etc.) — validated + executed, never ledgered."""

    @abstractmethod
    def process_action(self, request: Request) -> Dict[str, Any]:
        ...
