"""NYM handler: identity (DID) create/update on the domain ledger.

Reference: plenum/server/request_handlers/nym_handler.py (`NymHandler`).
State layout: key = DID utf-8, value = msgpack {verkey, role, seqNo,
txnTime} — the verkey source for client authentication
(`CoreAuthNr.authenticate` resolves signers from here).

Authorization rules (reference semantics):
- new NYM: creator must hold TRUSTEE or STEWARD role; only a TRUSTEE may
  grant a role (STEWARD creates plain identity owners);
- existing NYM: the owner may rotate its own verkey; only a TRUSTEE may
  change a role.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import msgpack

from ...common.constants import (
    DOMAIN_LEDGER_ID,
    NYM,
    ROLE,
    STEWARD,
    TARGET_NYM,
    TRUSTEE,
    VERKEY,
)
from ...common.exceptions import (
    InvalidClientRequest,
    UnauthorizedClientRequest,
)
from ...common.request import Request
from ...common.txn_util import get_payload_data, get_seq_no, get_txn_time
from .handler_interfaces import WriteRequestHandler


class NymHandler(WriteRequestHandler):
    def __init__(self, database_manager):
        super().__init__(database_manager, NYM, DOMAIN_LEDGER_ID)

    # ------------------------------------------------------------------

    def static_validation(self, request: Request) -> None:
        self._validate_type(request)
        op = request.operation
        if not op.get(TARGET_NYM):
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       "dest is required")
        role = op.get(ROLE)
        if role not in (None, TRUSTEE, STEWARD):
            raise InvalidClientRequest(request.identifier, request.reqId,
                                       f"unknown role {role!r}")

    def dynamic_validation(self, request: Request,
                           req_pp_time: Optional[int]) -> None:
        op = request.operation
        dest = op[TARGET_NYM]
        existing = self.get_nym_data(dest, is_committed=False)
        author = self.get_nym_data(request.identifier, is_committed=False)
        author_role = author.get(ROLE) if author else None
        if existing is None:
            if author_role not in (TRUSTEE, STEWARD):
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "only TRUSTEE or STEWARD may create identities")
            if op.get(ROLE) is not None and author_role != TRUSTEE:
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "only TRUSTEE may grant roles")
        else:
            is_owner = request.identifier == dest
            if ROLE in op and op.get(ROLE) != existing.get(ROLE):
                if author_role != TRUSTEE:
                    raise UnauthorizedClientRequest(
                        request.identifier, request.reqId,
                        "only TRUSTEE may change a role")
            elif not is_owner and author_role != TRUSTEE:
                raise UnauthorizedClientRequest(
                    request.identifier, request.reqId,
                    "only the owner may edit its NYM")

    def update_state(self, txn: Dict[str, Any], prev_result,
                     request=None, is_committed: bool = False):
        data = get_payload_data(txn)
        dest = data[TARGET_NYM]
        existing = self.get_nym_data(dest, is_committed=False) or {}
        record = {
            VERKEY: data.get(VERKEY, existing.get(VERKEY)),
            ROLE: data.get(ROLE, existing.get(ROLE)),
            "seqNo": get_seq_no(txn),
            "txnTime": get_txn_time(txn),
        }
        self.state.set(dest.encode(), msgpack.packb(record, use_bin_type=True))
        return record

    # ------------------------------------------------------------------

    def get_nym_data(self, nym: Optional[str],
                     is_committed: bool = True) -> Optional[Dict]:
        if nym is None:
            return None
        raw = self.state.get(nym.encode(), is_committed=is_committed)
        if raw is None:
            return None
        return msgpack.unpackb(raw, raw=False)
