"""Registry of ledgers, states and auxiliary stores per ledger id.

Reference: plenum/server/database_manager.py (`DatabaseManager`). Also
holds the cross-cutting stores: the BLS multi-signature store (state-proof
reads) and the timestamp->state-root index.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..ledger.ledger import Ledger
from ..state.state import State


class Database:
    def __init__(self, ledger: Ledger, state: Optional[State]):
        self.ledger = ledger
        self.state = state


class DatabaseManager:
    def __init__(self):
        self.databases: Dict[int, Database] = {}
        self.stores: Dict[str, object] = {}
        self._init_hooks: List = []

    def register_new_database(self, lid: int, ledger: Ledger,
                              state: Optional[State] = None) -> None:
        if lid in self.databases:
            raise ValueError(f"ledger {lid} already registered")
        self.databases[lid] = Database(ledger, state)

    def get_database(self, lid: int) -> Optional[Database]:
        return self.databases.get(lid)

    def get_ledger(self, lid: int) -> Optional[Ledger]:
        db = self.databases.get(lid)
        return db.ledger if db else None

    def get_state(self, lid: int) -> Optional[State]:
        db = self.databases.get(lid)
        return db.state if db else None

    def register_new_store(self, label: str, store) -> None:
        self.stores[label] = store

    def get_store(self, label: str):
        return self.stores.get(label)

    @property
    def ledger_ids(self) -> List[int]:
        return sorted(self.databases)

    # convenience used by handlers
    @property
    def ts_store(self):
        return self.stores.get("ts")

    @property
    def bls_store(self):
        return self.stores.get("bls")

    @property
    def idr_cache(self):
        return self.stores.get("idr")
