"""Deterministic retry/timeout/backoff law for leecher requests.

The chaos plane's catchup scenarios need recovery that neither stalls on
a silent seeder nor diverges between replays of the same seed: every
re-request decision here is a pure function of (seed, slice key, attempt
number), so a seeded simulation run reproduces the identical retry
schedule bit-for-bit, and a budget of ``max_retries`` turns "re-ask
forever" into a fail-closed round (the leecher's
``CatchupFailedRetryBackoff`` path then owns when to try again).

Delay for attempt ``k`` (1-based, the wait AFTER the k-th send):

    base * mult^(k-1), capped at ``max_delay``, then stretched by a
    seeded jitter in [0, jitter_frac] of itself — sha256(seed|key|k)
    drives the stretch, so concurrent slices (and concurrent leechers
    with different seeds) desynchronize instead of thundering together.
"""
from __future__ import annotations

import hashlib


class RetryLaw:
    """Seeded, deterministic per-key exponential backoff with a budget."""

    def __init__(self, base: float, mult: float = 1.5,
                 max_delay: float = 60.0, jitter_frac: float = 0.25,
                 seed: int = 0, max_retries: int = 10):
        if base <= 0:
            raise ValueError("base delay must be positive")
        self.base = base
        self.mult = max(mult, 1.0)
        self.max_delay = max(max_delay, base)
        self.jitter_frac = max(jitter_frac, 0.0)
        self.seed = seed
        self.max_retries = max_retries

    @classmethod
    def from_config(cls, config) -> "RetryLaw":
        # CatchupRequestTimeout 0 = inherit the pre-retry-law knob, so
        # existing configs keep their observed re-request cadence
        base = config.CatchupRequestTimeout \
            or config.CatchupTransactionsTimeout
        return cls(base=base,
                   mult=config.CatchupRetryBackoffMult,
                   max_delay=config.CatchupRetryBackoffMax,
                   jitter_frac=config.CatchupRetryJitterFrac,
                   seed=config.CatchupRetryJitterSeed,
                   max_retries=config.CatchupMaxRetries)

    def _jitter_unit(self, key, attempt: int) -> float:
        """[0, 1) drawn from sha256(seed|key|attempt) — no shared RNG
        state, so delays are replayable per key regardless of the order
        slices hit their deadlines."""
        h = hashlib.sha256(
            f"{self.seed}|{key}|{attempt}".encode()).digest()
        return int.from_bytes(h[:8], "big") / float(1 << 64)

    def delay(self, key, attempt: int) -> float:
        """Seconds to wait after the ``attempt``-th (1-based) send of
        ``key`` before re-asking someone else."""
        attempt = max(attempt, 1)
        raw = min(self.base * (self.mult ** (attempt - 1)), self.max_delay)
        return raw * (1.0 + self.jitter_frac
                      * self._jitter_unit(key, attempt))

    def exhausted(self, attempt: int) -> bool:
        """True once ``attempt`` sends have gone unanswered and the
        budget says stop re-asking (fail the round closed instead)."""
        return attempt > self.max_retries
