"""Catchup: trustless ledger synchronization for lagging/diverged nodes.

Reference: plenum/server/catchup/ (node_leecher_service.py,
ledger_leecher_service.py, cons_proof_service.py, catchup_rep_service.py,
seeder_service.py). The per-ledger LedgerLeecher layer is folded into
NodeLeecherService here; verification of fetched txns is the batched
device audit-path kernel (tpu/sha256.py).
"""
from .catchup_rep_service import CatchupRepService, verify_audit_paths_batch
from .cons_proof_service import ConsProofService
from .node_leecher_service import NodeLeecherService
from .retry import RetryLaw
from .seeder_service import SeederService

__all__ = [
    "CatchupRepService",
    "ConsProofService",
    "NodeLeecherService",
    "RetryLaw",
    "SeederService",
    "verify_audit_paths_batch",
]
