"""Fetching and verifying txn ranges toward an agreed catchup target.

Reference: plenum/server/catchup/catchup_rep_service.py
(`CatchupRepService`). The range (own_size, target_size] is sliced into
``CatchupBatchSize`` chunks assigned round-robin over the connected peers;
each ``CATCHUP_REP`` is verified and applied IN ORDER (out-of-order reps
are buffered); unanswered or bad slices are re-assigned to the next peer on
a timer.

TPU-first verification: every txn in a rep carries its audit path against
the quorum-agreed target root, so the whole slice is checked by ONE call
into the batched device kernel
(:func:`indy_plenum_tpu.tpu.sha256.verify_audit_paths`) — leaf hashes,
indices and padded sibling stacks are assembled host-side, verdicts come
back as a bool vector. This is BASELINE config 5's hot loop (audit-path
batch verify at 1M txns). A scalar host fallback (MerkleVerifier) remains
for tiny slices where the device round-trip outweighs the math.
"""
# da: allow-file[nondet-source] -- _AdaptiveOffload's perf_counter probes STEER device-vs-host placement only: both paths verify identical proofs to identical verdicts, so ordering/ledger state and every fingerprint replay bit-identically under either choice
# da: allow-file[device-sync] -- the chunked audit-proof offload deliberately syncs (block_until_ready warm-up, np.asarray verdict resolve): catchup runs OFF the ordering tick loop, and the resolved verdict vector IS the product — the pipelined-readback contract governs the vote plane, not this recovery path
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ...common.event_bus import ExternalBus
from ...common.messages.node_messages import CatchupRep, CatchupReq
from ...common.metrics_collector import MetricsName
from ...common.timer import RepeatingTimer, TimerService
from ...ledger.merkle_verifier import STH, MerkleVerifier
from ...utils.base58 import b58decode
from ..suspicion_codes import Suspicions

logger = logging.getLogger(__name__)

# below this many proofs the host scalar loop beats the device dispatch
DEVICE_MIN_BATCH = 32
# static audit-path depth the kernel is compiled for (2^48 txns); padded
_MAX_DEPTH = 48
_BUCKETS = (64, 256, 1024, 4096, 16384)


class _AdaptiveOffload:
    """MEASURED device-vs-host selection for the proof-verify offload.

    The device path's value is what it frees on the protocol thread, so
    the comparison is host-BLOCKING nanoseconds per proof: pack +
    dispatch + the resolve-time force for the device path, vs the scalar
    verify loop for the host path. EMAs of both are kept from real
    traffic; the device path is kept only while it blocks the loop less
    than host verification would (round-4 verdict: on a contended remote
    device link the offload measured SLOWER end-to-end — selection must
    be measured, not configured). Every PROBE_EVERYth batch re-tries the
    losing mode so a recovered link is noticed.
    """

    PROBE_EVERY = 16
    _ALPHA = 0.3  # EMA weight for new samples

    def __init__(self):
        self.host_ns = None  # EMA ns/proof, host scalar verify
        self.dev_ns = None  # EMA ns/proof, device-path host-blocking time
        self.kernel_ns = None  # ns/proof of device OCCUPANCY, measured
        self._batches = 0
        self._link_bw = None  # bytes/sec, measured once

    def link_bandwidth(self) -> float:
        """Host->device bandwidth, measured ONCE with a real transfer.

        The proof upload rides the same link as the latency-critical
        vote-plane flushes, so its occupancy is a cost to the node even
        though the dispatch itself returns asynchronously. On a locally
        attached device this measures GB/s and the charge vanishes; on
        a remote tunnel it is what makes the offload lose."""
        if self._link_bw is None:
            import time as _t

            import jax

            buf = np.zeros(1 << 20, np.uint8)
            jax.device_put(buf).block_until_ready()  # warm the path
            t0 = _t.perf_counter()
            jax.device_put(buf).block_until_ready()
            self._link_bw = max(len(buf) / (_t.perf_counter() - t0), 1.0)
        return self._link_bw

    def note_host(self, ns_per_proof: float) -> None:
        self.host_ns = (ns_per_proof if self.host_ns is None else
                        (1 - self._ALPHA) * self.host_ns
                        + self._ALPHA * ns_per_proof)

    def note_device(self, ns_per_proof: float) -> None:
        self.dev_ns = (ns_per_proof if self.dev_ns is None else
                       (1 - self._ALPHA) * self.dev_ns
                       + self._ALPHA * ns_per_proof)

    def use_device(self) -> bool:
        self._batches += 1
        if self.dev_ns is None or self.host_ns is None:
            return True  # no data yet: try the offload, measurements follow
        if self._batches % self.PROBE_EVERY == 0:
            # periodic probe of the currently-losing mode
            return self.dev_ns >= self.host_ns
        return self.dev_ns < self.host_ns


OFFLOAD_POLICY = _AdaptiveOffload()


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + _BUCKETS[-1] - 1) // _BUCKETS[-1]) * _BUCKETS[-1]


def verify_audit_paths_batch(leaf_data: List[bytes], indices: List[int],
                             paths: List[List[bytes]], tree_size: int,
                             root: bytes,
                             mode: str = "device") -> np.ndarray:
    """Verify many RFC 6962 audit paths at once; returns (B,) bool.

    Synchronous wrapper over :func:`dispatch_audit_paths_batch`, FORCED
    to the device kernel by default: explicit batch-verify callers (and
    the benches named after the kernel) want the kernel, not whatever
    the catchup pipeline's adaptive policy currently favors — pass
    mode="auto" to consult it. Callers that can overlap device compute
    with other work should dispatch instead and resolve later.
    """
    return dispatch_audit_paths_batch(
        leaf_data, indices, paths, tree_size, root, mode=mode)(force=True)


def dispatch_audit_paths_batch(leaf_data: List[bytes], indices: List[int],
                               paths: List[List[bytes]], tree_size: int,
                               root: bytes, mode: str = "auto"):
    """Start verifying many audit paths; returns ``resolve() -> (B,) bool``.

    Host-side assembly + one jitted device call (bucketed padding keeps
    the compile cache small). The device call is ASYNCHRONOUS — XLA
    dispatch returns a future — so the protocol thread keeps running
    while the device grinds; forcing happens inside ``resolve()``. This
    is what makes the device path a true offload rather than a blocking
    substitute (BASELINE config 5's offload claim, measured by
    bench.py's catchup_offload_ordered_txns_ratio). Tiny batches verify
    synchronously on the host (the round-trip would dominate).
    """
    import time as _time

    n = len(leaf_data)
    if n == 0:
        empty = np.zeros(0, bool)
        return lambda force=False: empty
    # size gate FIRST: tiny batches must not consume the policy's batch
    # counts/probe slots (the device path can never run for them anyway)
    want_device = n >= DEVICE_MIN_BATCH and (
        mode == "device" or
        (mode == "auto" and OFFLOAD_POLICY.use_device()))
    if want_device:
        if mode == "auto" and OFFLOAD_POLICY.host_ns is None:
            # one-time calibration: the policy can't compare modes until
            # it has a host sample — verify a small slice on the host
            # (re-verified on device below; ~2ms once per process)
            sample = min(256, n)
            v = MerkleVerifier()
            sth = STH(tree_size=tree_size, sha256_root_hash=root)
            t0 = _time.perf_counter()
            for d, i, p in zip(leaf_data[:sample], indices[:sample],
                               paths[:sample]):
                v.verify_leaf_inclusion(d, i, p, sth)
            OFFLOAD_POLICY.note_host(
                (_time.perf_counter() - t0) * 1e9 / sample)
        return _ChunkedDeviceVerify(leaf_data, indices, paths, tree_size,
                                    root)

    # host scalar path: tiny batches, or the measured policy says the
    # device link currently blocks the loop more than hashing would
    v = MerkleVerifier()
    sth = STH(tree_size=tree_size, sha256_root_hash=root)
    t0 = _time.perf_counter()
    host = np.array([
        v.verify_leaf_inclusion(d, i, p, sth)
        for d, i, p in zip(leaf_data, indices, paths)], bool)
    if n >= DEVICE_MIN_BATCH:  # tiny batches would skew the EMA
        OFFLOAD_POLICY.note_host((_time.perf_counter() - t0) * 1e9 / n)
    return lambda force=False: host


class _ChunkedDeviceVerify:
    """Incremental device verification with BOUNDED device occupancy.

    One monolithic kernel over a 16k-proof slice holds the shared device
    stream for ~100ms — every latency-critical vote-plane step dispatched
    behind it waits, which is exactly how round 4's offload made the node
    SLOWER while catching up. Each __call__ dispatches ONE small
    sub-kernel and returns None (call again next loop pass), so vote
    steps interleave between chunks; ``force=True`` pumps to completion
    and blocks. Dispatch/link costs feed OFFLOAD_POLICY.
    """

    CHUNK = 4096  # = a pack bucket; ~27ms of device work per sub-kernel

    def __init__(self, leaf_data, indices, paths, tree_size, root):
        self._data = leaf_data
        self._idx = indices
        self._paths = paths
        self._ts = tree_size
        self._root = root
        self._n = len(leaf_data)
        self._pos = 0
        self._futures: List[tuple] = []
        self._blocking_ns = 0.0
        self._bad = False
        self._dispatch_next()  # first chunk rides the dispatch call

    def _dispatch_next(self) -> None:
        import time as _time

        if self._bad or self._pos >= self._n:
            return
        from ...tpu.sha256 import verify_audit_paths_indexed

        lo, hi = self._pos, min(self._pos + self.CHUNK, self._n)
        t0 = _time.perf_counter()
        packed = pack_audit_batch(
            self._data[lo:hi], self._idx[lo:hi], self._paths[lo:hi],
            self._ts, self._root)
        if packed is None:
            self._bad = True
            return
        fut = verify_audit_paths_indexed(*packed)
        m = hi - lo
        if OFFLOAD_POLICY.kernel_ns is None:
            # one-time occupancy calibration: block on this chunk to
            # measure what each chunk COSTS the shared device stream —
            # every vote-plane step dispatched behind a chunk waits that
            # long, a real tax on consensus even though our own dispatch
            # is async (it is why round 4's offload slowed the node)
            tk = _time.perf_counter()
            try:
                fut.block_until_ready()
                OFFLOAD_POLICY.kernel_ns = max(
                    (_time.perf_counter() - tk) * 1e9 / m, 1.0)
            except Exception:  # noqa: BLE001
                OFFLOAD_POLICY.kernel_ns = 1.0
        else:
            self._blocking_ns += m * OFFLOAD_POLICY.kernel_ns
        try:
            fut.copy_to_host_async()  # verdict bytes ready by collection
        except Exception:  # noqa: BLE001 — backend without async copy
            pass
        self._blocking_ns += (_time.perf_counter() - t0) * 1e9
        # the upload occupies the shared host<->device link even though
        # dispatch is async — charge it at measured bandwidth (the charge
        # vanishes on locally attached devices)
        self._blocking_ns += (sum(a.nbytes for a in packed)
                              / OFFLOAD_POLICY.link_bandwidth() * 1e9)
        self._futures.append((fut, hi - lo))
        self._pos = hi

    def __call__(self, force: bool = False):
        import time as _time

        if self._bad:
            return np.zeros(self._n, bool)
        if force:
            while self._pos < self._n and not self._bad:
                self._dispatch_next()
            if self._bad:
                return np.zeros(self._n, bool)
        elif self._pos < self._n:
            self._dispatch_next()
            return None if not self._bad else np.zeros(self._n, bool)
        t1 = _time.perf_counter()
        out = (np.concatenate([np.asarray(f)[:m] for f, m in self._futures])
               if self._futures else np.zeros(0, bool))
        self._blocking_ns += (_time.perf_counter() - t1) * 1e9
        OFFLOAD_POLICY.note_device(self._blocking_ns / max(self._n, 1))
        return out


def pack_audit_batch(leaf_data: List[bytes], indices: List[int],
                     paths: List[List[bytes]], tree_size: int,
                     root: bytes):
    """Host-side assembly for the device kernel: bucketed padding, leaf
    hashing, and sibling-node deduplication. Returns the positional args
    of :func:`indy_plenum_tpu.tpu.sha256.verify_audit_paths_indexed`, or
    None for malformed (too-deep) paths. Split out so the bench can time
    packing+transfer and the kernel separately."""
    from ...ledger.tree_hasher import TreeHasher

    n = len(leaf_data)
    hasher = TreeHasher()
    if any(len(p) > _MAX_DEPTH for p in paths):
        return None
    size = _bucket(n)
    # vectorized packing: one frombuffer over the concatenated path bytes +
    # a single fancy-index scatter (the per-node Python loop used to cost
    # more than the device verify itself)
    leaf = np.zeros((size, 32), np.uint8)
    leaf[:n] = np.frombuffer(
        b"".join(hasher.hash_leaf(d) for d in leaf_data),
        np.uint8).reshape(n, 32)
    idx = np.zeros(size, np.int32)
    idx[:n] = indices
    plen = np.fromiter((len(p) for p in paths), np.int32, count=n)
    # depth bucketed tight (a 2^17 tree needs 17 levels, not _MAX_DEPTH=48:
    # every padded level costs two full SHA-256 compressions on device)
    dmax = int(plen.max()) if n else 1
    depth = next(d for d in (16, 20, 24, 32, _MAX_DEPTH) if d >= dmax)
    flat = np.frombuffer(
        b"".join(node for p in paths for node in p), np.uint8).reshape(-1, 32)
    # dedup sibling nodes: consecutive txn ranges (the catchup shape) share
    # almost all of them, so the device receives a (U, 32) unique-node table
    # + (B, D) int32 indices — ~10x less transfer than dense (B, D, 32)
    table, inverse = np.unique(
        np.ascontiguousarray(flat).view("V32").ravel(), return_inverse=True)
    table = np.vstack([table.view(np.uint8).reshape(-1, 32),
                       np.zeros((1, 32), np.uint8)])  # last row = padding
    pad_node = len(table) - 1
    tsize = _bucket(len(table))
    table = np.vstack(
        [table, np.zeros((tsize - len(table), 32), np.uint8)])
    path_idx = np.full((size, depth), pad_node, np.int32)
    rows = np.repeat(np.arange(n), plen)
    cols = np.concatenate([np.arange(l) for l in plen]) if n else rows
    path_idx[rows, cols] = inverse
    path_len = np.zeros(size, np.int32)
    path_len[:n] = plen
    ts = np.full(size, tree_size, np.int32)
    root_arr = np.ascontiguousarray(np.broadcast_to(
        np.frombuffer(root, np.uint8), (size, 32)))
    return leaf, idx, table, path_idx, path_len, ts, root_arr


class CatchupRepService:
    def __init__(self,
                 ledger_id: int,
                 network: ExternalBus,
                 timer: TimerService,
                 db,
                 config=None,
                 suspicion_sink=None,
                 apply_txn: Optional[Callable[[dict], None]] = None,
                 metrics=None,
                 trace=None,
                 node: str = ""):
        from ...common.metrics_collector import NullMetricsCollector
        from ...config import getConfig
        from ...observability.trace import NULL_TRACE
        from .retry import RetryLaw

        self._ledger_id = ledger_id
        self._network = network
        self._timer = timer
        self._db = db
        self._config = config or getConfig()
        self._suspicion = suspicion_sink or (lambda ex: None)
        # called per applied txn (state updates on stateful ledgers)
        self._apply_txn = apply_txn
        self._metrics = metrics if metrics is not None \
            else NullMetricsCollector()
        self._trace = trace if trace is not None else NULL_TRACE
        self._node = node

        self._running = False
        self._on_done: Optional[Callable[[], None]] = None
        self._on_fail: Optional[Callable[[], None]] = None
        self._target_size = 0
        self._target_root = b""
        # slice start -> (end, assigned peer)
        self._outstanding: Dict[int, Tuple[int, str]] = {}
        # retry law bookkeeping: slice start -> sends so far / deadline
        # after which the slice is re-assigned (seeded, deterministic)
        self._attempts: Dict[int, int] = {}
        self._due: Dict[int, float] = {}
        # verified-but-early reps: start seq -> ordered txns
        self._ready: Dict[int, List[dict]] = {}
        # ONE in-flight async device verification (sender, start, end,
        # seqs, txns, resolve): dispatched on rep receipt, resolved when
        # the next rep arrives or the retry timer fires — device compute
        # overlaps network wait + host packing of the next slice
        self._inflight: Optional[tuple] = None
        self._peer_rr: List[str] = []
        self._law = RetryLaw.from_config(self._config)
        # the poll runs at half the base timeout so backoff deadlines
        # resolve within one poll step; re-asks fire only when a slice's
        # seeded deadline has actually passed
        self._retry = RepeatingTimer(
            timer, max(self._law.base / 2.0, 0.01),
            self._service_retries, active=False)
        # lifetime meters (observability: Monitor catchup block, chaos
        # report catchup block, the bench's verified-proofs/sec)
        self.txns_leeched = 0
        self.proofs_verified = 0
        self.reps_rejected = 0
        self.retries = 0

        network.subscribe(CatchupRep, self.process_catchup_rep)

    # ------------------------------------------------------------------

    @property
    def _ledger(self):
        return self._db.get_ledger(self._ledger_id)

    def start(self, target_size: int, target_root: bytes,
              on_done: Callable[[], None],
              on_fail: Optional[Callable[[], None]] = None) -> None:
        """``on_fail`` fires when a slice exhausts ``CatchupMaxRetries``
        re-assignments: the round FAILS CLOSED (the leecher's backoff
        path owns the next attempt) instead of re-asking forever."""
        ledger = self._ledger
        self._target_size = target_size
        self._target_root = target_root
        self._on_done = on_done
        self._on_fail = on_fail
        self._outstanding.clear()
        self._attempts.clear()
        self._due.clear()
        self._ready.clear()
        self._running = True
        if ledger.size >= target_size:
            self._finish()
            return
        self._peer_rr = sorted(self._network.connecteds)
        if not self._peer_rr:
            logger.warning("catchup ledger %d: no peers connected",
                           self._ledger_id)
        self._send_requests(ledger.size + 1, target_size)
        self._retry.start()

    def stop(self) -> None:
        self._running = False
        self._inflight = None
        self._retry.stop()

    def _send_slice(self, start: int, end: int, peer: str) -> None:
        """One slice to one peer, with its retry-law deadline armed."""
        attempt = self._attempts.get(start, 0) + 1
        self._attempts[start] = attempt
        self._due[start] = self._timer.get_current_time() \
            + self._law.delay((self._ledger_id, start), attempt)
        self._outstanding[start] = (end, peer)
        self._network.send(CatchupReq(
            ledgerId=self._ledger_id, seqNoStart=start, seqNoEnd=end,
            catchupTill=self._target_size), [peer])
        if attempt > 1:
            self.retries += 1
            self._metrics.add_event(MetricsName.CATCHUP_RETRIES)

    def _send_requests(self, frm: int, to: int) -> None:
        if not self._peer_rr:
            return
        batch = self._config.CatchupBatchSize
        i = 0
        for start in range(frm, to + 1, batch):
            end = min(start + batch - 1, to)
            peer = self._peer_rr[i % len(self._peer_rr)]
            i += 1
            self._send_slice(start, end, peer)

    def _give_up(self) -> None:
        """A slice ran out of retry budget: fail the whole round closed.
        Re-asking forever would leave the node non-participating but
        "recovering" indefinitely; the leecher's failed-catchup backoff
        owns when to try the pool again."""
        logger.error(
            "catchup ledger %d: slice exhausted %d retries; failing the "
            "round (leecher backoff takes over)", self._ledger_id,
            self._law.max_retries)
        cb = self._on_fail
        self.stop()
        self._on_done = None
        self._on_fail = None
        if cb is not None:
            cb()

    def _service_retries(self) -> None:
        """Re-assign every slice whose seeded retry deadline has passed
        to the next peer; exhaust the budget => fail the round closed."""
        self._resolve_inflight()
        if not self._running or not self._outstanding:
            return
        now = self._timer.get_current_time()
        due = [start for start in self._outstanding
               if now >= self._due.get(start, 0.0)]
        if not due:
            return
        self._peer_rr = sorted(self._network.connecteds)
        if not self._peer_rr:
            return
        for start in due:
            if start not in self._outstanding:
                continue  # an earlier give-up stopped the round
            if self._law.exhausted(self._attempts.get(start, 0)):
                self._give_up()
                return
            end, old_peer = self._outstanding[start]
            others = [p for p in self._peer_rr if p != old_peer] \
                or self._peer_rr
            peer = others[start % len(others)]
            self._send_slice(start, end, peer)
            logger.info("catchup ledger %d: re-requesting %d..%d from %s "
                        "(attempt %d)", self._ledger_id, start, end, peer,
                        self._attempts[start])

    # ------------------------------------------------------------------

    def process_catchup_rep(self, rep: CatchupRep, sender: str):
        if not self._running or rep.ledgerId != self._ledger_id:
            return
        if rep.catchupTill != self._target_size:
            return
        try:
            seqs = sorted(int(s) for s in dict(rep.txns))
        except (TypeError, ValueError):
            return
        if not seqs:
            return
        start = seqs[0]
        expected = self._outstanding.get(start)
        if expected is None or expected[1] != sender:
            return  # unsolicited (or already satisfied)
        end = expected[0]
        if seqs != list(range(start, min(end, seqs[-1]) + 1)):
            return  # holes — treat like silence; the retry timer reassigns

        txns = dict(rep.txns)
        paths_raw = dict(rep.auditPaths or {})
        ledger = self._ledger
        leaf_data, indices, paths = [], [], []
        try:
            for s in seqs:
                leaf_data.append(ledger.serializer.dumps(txns[str(s)]))
                indices.append(s - 1)
                paths.append([b58decode(h) for h in paths_raw[str(s)]])
        except (KeyError, ValueError):
            self._bad_rep(sender, start)
            return

        # pipeline: resolve the PREVIOUS slice's device verdict (its
        # compute overlapped this rep's network+packing time), then
        # dispatch this slice asynchronously
        # a NEW slice arrived: the previous one must fully resolve first
        # (pipeline depth is one) — force pumps any remaining chunks
        self._resolve_inflight(force=True)
        if not self._running:
            return  # resolution completed the ledger
        if self._outstanding.get(start) != (end, sender):
            return  # resolution re-assigned or satisfied this slice
        resolve = dispatch_audit_paths_batch(
            leaf_data, indices, paths, self._target_size, self._target_root)
        self._inflight = (sender, start, end, seqs, txns, resolve)
        # backstop: if no further rep arrives to trigger resolution (the
        # final slice), resolve shortly — by then the device is done or
        # nearly so
        self._timer.schedule(0.05, self._resolve_inflight)

    def _resolve_inflight(self, force: bool = False) -> None:
        if self._inflight is None or not self._running:
            self._inflight = None
            return
        sender, start, end, seqs, txns, resolve = self._inflight
        self._inflight = None
        expected = self._outstanding.get(start)
        if expected is None or expected != (end, sender):
            return  # superseded while in flight (reassigned / satisfied)
        ok = resolve(force=force)
        if ok is None:
            # chunked device verify still pumping: keep it in flight and
            # come back next pass (vote steps interleave between chunks)
            self._inflight = (sender, start, end, seqs, txns, resolve)
            self._timer.schedule(0.02, self._resolve_inflight)
            return
        if not ok.all():
            logger.warning(
                "catchup ledger %d: %d/%d txns from %s FAIL audit proof",
                self._ledger_id, int((~ok).sum()), len(ok), sender)
            self._bad_rep(sender, start)
            return
        self.proofs_verified += len(ok)
        self._metrics.add_event(MetricsName.CATCHUP_PROOFS_VERIFIED,
                                len(ok))
        del self._outstanding[start]
        self._due.pop(start, None)
        self._ready[start] = [txns[str(s)] for s in seqs]
        if seqs[-1] < end:
            # short (clamped) rep: re-request the tail (a fresh slice —
            # its retry budget starts from scratch)
            peer = self._peer_rr[seqs[-1] % len(self._peer_rr)] \
                if self._peer_rr else sender
            self._send_slice(seqs[-1] + 1, end, peer)
        self._apply_ready()

    def _bad_rep(self, sender: str, start: int) -> None:
        from ...common.exceptions import SuspiciousNode

        self.reps_rejected += 1
        self._metrics.add_event(MetricsName.CATCHUP_REPS_REJECTED)
        self._suspicion(SuspiciousNode(sender, Suspicions.CATCHUP_REP_WRONG))
        # reassign this slice to someone else immediately; a byzantine
        # seeder's rejected reps consume the slice's retry budget too (it
        # must not be able to bounce a slice around forever)
        end, _ = self._outstanding[start]
        if self._law.exhausted(self._attempts.get(start, 0)):
            self._give_up()
            return
        others = [p for p in self._peer_rr if p != sender] or self._peer_rr
        if others:
            self._send_slice(start, end, others[start % len(others)])

    def _apply_ready(self) -> None:
        ledger = self._ledger
        applied = 0
        while True:
            nxt = ledger.size + 1
            txns = self._ready.pop(nxt, None)
            if txns is None:
                break
            for txn in txns:
                ledger.add(txn)
                if self._apply_txn is not None:
                    self._apply_txn(txn)
            applied += len(txns)
        if applied:
            self.txns_leeched += applied
            self._metrics.add_event(MetricsName.CATCHUP_TXNS_LEECHED,
                                    applied)
            if self._trace.enabled:
                self._trace.record(
                    "catchup.txns_leeched", cat="catchup", node=self._node,
                    args={"ledger": self._ledger_id, "txns": applied,
                          "size": ledger.size})
        if ledger.size >= self._target_size:
            self._finish()

    def _finish(self) -> None:
        self.stop()
        cb = self._on_done
        self._on_done = None
        self._on_fail = None
        logger.info("catchup ledger %d complete at size %d", self._ledger_id,
                    self._ledger.size)
        if cb is not None:
            cb()
