"""Agreeing on a catchup target (size, root) for one ledger.

Reference: plenum/server/catchup/cons_proof_service.py (`ConsProofService`).
Broadcast our ``LEDGER_STATUS``; peers ahead of us answer with RFC 6962
``CONSISTENCY_PROOF``s (our size -> their size), peers level with us echo
their ``LEDGER_STATUS``. Every proof is cryptographically verified against
our OWN committed root before it may vote; a weak quorum (f+1) of verified
votes on the same (size, root) decides the target — at least one vote is
then from an honest node, and every fetched txn will later be verified
against that root, so a lying majority-of-f voters cannot poison us.

Divergence detection: a peer's proof whose ``oldMerkleRoot`` (their tree at
OUR size) differs from our root proves our ledger's history itself is wrong
(not merely short). f+1 distinct peers saying so convicts our local state
-> the leecher truncates and re-syncs from scratch.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional, Set, Tuple

from ...common.event_bus import ExternalBus
from ...common.messages.node_messages import (
    ConsistencyProof,
    LedgerStatus,
)
from ...common.timer import RepeatingTimer, TimerService
from ...ledger.merkle_verifier import MerkleVerifier
from ...utils.base58 import b58decode, b58encode

logger = logging.getLogger(__name__)

# target: (size, root_b58); DIVERGED is a sentinel outcome
Target = Tuple[int, str]


class ConsProofService:
    def __init__(self,
                 ledger_id: int,
                 network: ExternalBus,
                 timer: TimerService,
                 db,
                 quorums_provider: Callable[[], object],
                 config=None):
        from ...config import getConfig

        self._ledger_id = ledger_id
        self._network = network
        self._timer = timer
        self._db = db
        self._quorums = quorums_provider
        self._config = config or getConfig()
        self._verifier = MerkleVerifier()

        self._running = False
        self._on_target: Optional[Callable[[Optional[Target], bool], None]] \
            = None
        # (size, root_b58) -> senders with a VERIFIED proof / equal status
        self._votes: Dict[Target, Set[str]] = {}
        # (size, root_b58) below our size -> prefix-matching behind peers
        self._behind_votes: Dict[Target, Set[str]] = {}
        self._divergence_votes: Set[str] = set()
        self._own_size = 0
        self._own_root_b58 = ""
        self._retry = RepeatingTimer(
            timer, self._config.ConsistencyProofsTimeout,
            self._broadcast_status, active=False)

        network.subscribe(ConsistencyProof, self.process_consistency_proof)
        network.subscribe(LedgerStatus, self.process_ledger_status)

    # ------------------------------------------------------------------

    def start(self, on_target: Callable[[Optional[Target], bool], None]
              ) -> None:
        """``on_target(target, diverged)``: target None + diverged=True
        means our own history is provably wrong; target (size, root) means
        fetch up to there (size == own size: already caught up)."""
        ledger = self._db.get_ledger(self._ledger_id)
        self._own_size = ledger.size
        self._own_root_b58 = b58encode(ledger.root_hash)
        self._votes.clear()
        self._behind_votes.clear()
        self._divergence_votes.clear()
        self._on_target = on_target
        self._running = True
        self._broadcast_status()
        self._retry.start()

    def stop(self) -> None:
        self._running = False
        self._retry.stop()

    def _broadcast_status(self) -> None:
        if not self._running:
            self._retry.stop()
            return
        self._network.send(LedgerStatus(
            ledgerId=self._ledger_id,
            txnSeqNo=self._own_size,
            viewNo=None,
            ppSeqNo=None,
            merkleRoot=self._own_root_b58,
            protocolVersion=2,
        ))

    # ------------------------------------------------------------------

    def process_ledger_status(self, status: LedgerStatus, sender: str):
        """A peer's own status: votes 'you are caught up' when it matches
        us; a same-size DIFFERENT root is a divergence vote. A BEHIND
        peer's status is evidence too — if our prefix at their size
        matches their root, they vote for a target at their tip (we are
        AHEAD of the pool: uncommitted/corrupt tail to truncate); if our
        prefix differs, that is a divergence vote."""
        if not self._running or status.ledgerId != self._ledger_id:
            return
        if getattr(status, "probe", None):
            return  # a fork-search QUESTION, not an assertion — no vote
        if status.txnSeqNo > self._own_size:
            return  # ahead peers vote via CONSISTENCY_PROOF instead
        if status.txnSeqNo < self._own_size:
            ledger = self._db.get_ledger(self._ledger_id)
            # root_hash_at(0) is the RFC 6962 empty-tree hash — the same
            # value an empty peer's status carries (no "" sentinel, which
            # would convict healthy nodes against fresh peers)
            ours_at = b58encode(ledger.root_hash_at(status.txnSeqNo))
            if status.merkleRoot == ours_at:
                # prefix matches: the peer is merely behind. These become
                # a BELOW-us truncation target only under a STRONG quorum
                # (n-f distinct peers at the same tip) — with weak (f+1)
                # support, one byzantine peer plus ordinary replication
                # lag could make a caught-up node discard a batch it
                # legitimately committed (review finding); n-f peers at
                # the same tip means no quorum ever EXECUTED past it, so
                # the truncated tail is re-orderable, not lost history
                self._behind_votes.setdefault(
                    (status.txnSeqNo, status.merkleRoot),
                    set()).add(sender)
                self._check_done()
            else:
                self._add_divergence_vote(sender)
            return
        if status.merkleRoot == self._own_root_b58:
            self._add_vote((self._own_size, self._own_root_b58), sender)
        else:
            self._add_divergence_vote(sender)

    def process_consistency_proof(self, proof: ConsistencyProof, sender: str):
        if not self._running or proof.ledgerId != self._ledger_id:
            return
        if proof.seqNoStart != self._own_size \
                or proof.seqNoEnd <= self._own_size:
            return  # stale (our size changed) or useless
        if self._own_size > 0 and proof.oldMerkleRoot != self._own_root_b58:
            # their tree at our size is NOT our tree: one of us diverged.
            # Count it; only f+1 distinct accusers convict us.
            self._add_divergence_vote(sender)
            return
        try:
            ok = self._verifier.verify_consistency(
                self._own_size, proof.seqNoEnd,
                b58decode(self._own_root_b58) if self._own_size else b"",
                b58decode(proof.newMerkleRoot),
                [b58decode(h) for h in proof.hashes])
        except (ValueError, KeyError):
            ok = False
        if not ok:
            logger.warning("bad consistency proof from %s for ledger %d",
                           sender, self._ledger_id)
            return
        self._add_vote((proof.seqNoEnd, proof.newMerkleRoot), sender)

    # ------------------------------------------------------------------

    def _add_vote(self, target: Target, sender: str) -> None:
        self._votes.setdefault(target, set()).add(sender)
        self._check_done()

    def _add_divergence_vote(self, sender: str) -> None:
        self._divergence_votes.add(sender)
        self._check_done()

    def _check_done(self) -> None:
        if not self._running:
            return
        quorums = self._quorums()
        if quorums.weak.is_reached(len(self._divergence_votes)):
            logger.warning("ledger %d DIVERGED (f+1 peers disagree with "
                           "our history)", self._ledger_id)
            self._finish(None, diverged=True)
            return
        # pick the HIGHEST quorum-supported target (peers keep ordering;
        # any f+1-supported root is safe to fetch toward)
        best = None
        for target, senders in self._votes.items():
            if quorums.weak.is_reached(len(senders)):
                if best is None or target[0] > best[0]:
                    best = target
        if best is None:
            # no at-or-above target: a STRONG quorum of prefix-matching
            # behind peers (we are ahead of the whole pool) pins the
            # pool's tip as the target instead
            for target, senders in self._behind_votes.items():
                if quorums.strong.is_reached(len(senders)):
                    if best is None or target[0] > best[0]:
                        best = target
        if best is not None:
            self._finish(best, diverged=False)

    def _finish(self, target: Optional[Target], diverged: bool) -> None:
        self.stop()
        cb = self._on_target
        self._on_target = None
        if cb is not None:
            cb(target, diverged)
