"""Answering peers' catchup requests from our committed ledgers.

Reference: plenum/server/catchup/seeder_service.py (`SeederService`).
Two inbound message types:

- ``LEDGER_STATUS`` from a peer: if the peer is behind us, reply with a
  ``CONSISTENCY_PROOF`` (their size -> our size, RFC 6962) so its
  ConsProofService can agree on a catchup target; if it matches us, echo
  our own ``LEDGER_STATUS`` (an "up to date" vote).
- ``CATCHUP_REQ`` for a txn range: reply with the txns AND a per-txn audit
  path against the requested ``catchupTill`` tree size (the TPU-first
  redesign: the leecher verifies the whole slice in one vmapped device
  kernel call instead of an incremental host tree fold).

Seeder-side throttling (overload robustness plane): serving a leecher is
host work the seeder steals from its own ordering loop — under ingress
saturation an unthrottled seeder can stall the very pool the leecher is
trying to rejoin. With ``CatchupSeederThrottleTxnsPerSec`` > 0 a token
bucket on the node's (virtual) clock bounds the serve rate; a slice the
bucket cannot cover is DEFERRED to the deterministic instant its tokens
accrue — never dropped, so the leecher's retry law sees a slow seeder,
not a silent one. Deferrals are metered (``catchup.seeder_deferred``)
and the chaos plane's catchup-under-saturation gate asserts ordering
kept moving while the meter ran.
"""
from __future__ import annotations

import logging
from typing import Optional

from ...common.event_bus import ExternalBus
from ...common.messages.node_messages import (
    CatchupRep,
    CatchupReq,
    ConsistencyProof,
    LedgerStatus,
)
from ...common.metrics_collector import MetricsName, NullMetricsCollector
from ...server.database_manager import DatabaseManager
from ...utils.base58 import b58encode

logger = logging.getLogger(__name__)

# cap on txns per CATCHUP_REP (the requester also slices; defence in depth)
MAX_TXNS_PER_REP = 10_000

# token-affordability tolerance (in txns — a thousandth of one is float
# debris): a wakeup scheduled for "when the bucket covers the head" must
# FIND it covered despite refill rounding, or it re-defers on a
# vanishing deficit forever
_TOKEN_EPS = 1e-3
# floor on the deferral wakeup delay: the virtual clock runs at epoch
# magnitude (~1.7e9), where one float ULP is ~2.4e-7 s — a deficit-sized
# delay below that rounds the wakeup back to NOW and freezes the clock
# in a same-instant fire loop. 10ms is noise against any real throttle
# rate and keeps every wakeup a genuine clock advance.
_MIN_DEFER_DELAY = 0.01


class SeederService:
    def __init__(self, network: ExternalBus, db: DatabaseManager,
                 own_name: str = "?", timer=None, config=None,
                 metrics=None):
        self._network = network
        self._db = db
        self._name = own_name
        self._metrics = metrics if metrics is not None \
            else NullMetricsCollector()
        # throttle state: armed only when both the knob and a timer are
        # provided (the timer defers replies AND is the bucket's clock —
        # virtual in simulation, so deferral instants replay per seed)
        self._timer = timer
        rate = config.CatchupSeederThrottleTxnsPerSec if config else 0.0
        self._throttle_rate = float(rate) if timer is not None else 0.0
        self._throttle_burst = max(
            1, int(config.CatchupSeederThrottleBurst)) if config else 1
        self._tokens = float(self._throttle_burst)
        self._tokens_at = timer.get_current_time() \
            if timer is not None else 0.0
        # deferred slices drain FIFO off ONE scheduled wakeup: per-slice
        # re-scheduling would let contending slices steal each other's
        # refill and spin sub-second deferral storms under load, and the
        # leecher's retry law re-asking a queued slice must not enqueue
        # a second copy (the dedupe set below)
        from collections import deque

        self._deferred: "deque" = deque()  # (key, req, sender)
        self._deferred_keys = set()
        self._wakeup_pending = False
        self.served_txns = 0
        self.deferred_total = 0
        network.subscribe(LedgerStatus, self.process_ledger_status)
        network.subscribe(CatchupReq, self.process_catchup_req)

    def _ledger(self, ledger_id: int):
        try:
            return self._db.get_ledger(ledger_id)
        except KeyError:
            return None

    # ------------------------------------------------------------------

    def own_ledger_status(self, ledger_id: int) -> Optional[LedgerStatus]:
        ledger = self._ledger(ledger_id)
        if ledger is None:
            return None
        return LedgerStatus(
            ledgerId=ledger_id,
            txnSeqNo=ledger.size,
            viewNo=None,
            ppSeqNo=None,
            merkleRoot=b58encode(ledger.root_hash),
            protocolVersion=2,
        )

    def process_ledger_status(self, status: LedgerStatus, sender: str):
        ledger = self._ledger(status.ledgerId)
        if ledger is None:
            return
        their_size = status.txnSeqNo
        if their_size > ledger.size:
            # the peer claims to be AHEAD of us: echo our own status. An
            # ahead-but-diverged peer (corrupt extra tail) gets no
            # consistency proofs from anyone — without this echo it could
            # never learn the pool's tip and would spin in catchup forever
            self._network.send(self.own_ledger_status(status.ledgerId),
                               [sender])
            return
        if their_size == ledger.size:
            # equality vote (also lets a diverged same-size peer notice the
            # root mismatch in our status)
            self._network.send(self.own_ledger_status(status.ledgerId),
                               [sender])
            return
        proof = ConsistencyProof(
            ledgerId=status.ledgerId,
            seqNoStart=their_size,
            seqNoEnd=ledger.size,
            viewNo=None,
            ppSeqNo=None,
            # root_hash_at(0) is the RFC 6962 empty-tree hash — one
            # convention everywhere (a zero-byte sentinel here would
            # desync from the statuses empty peers genuinely send)
            oldMerkleRoot=b58encode(ledger.root_hash_at(their_size)),
            newMerkleRoot=b58encode(ledger.root_hash),
            hashes=[b58encode(h)
                    for h in ledger.consistency_proof(their_size)],
        )
        self._network.send(proof, [sender])

    # ------------------------------------------------------------------

    def _refill(self) -> None:
        now = self._timer.get_current_time()
        self._tokens = min(
            float(self._throttle_burst),
            self._tokens + (now - self._tokens_at) * self._throttle_rate)
        self._tokens_at = now

    def _servable_range(self, req: CatchupReq):
        """The (start, end) this ledger can actually serve for ``req``
        RIGHT NOW, or None — validity is checked (and the throttle cost
        computed) against current ledger state, so garbage or
        beyond-the-tip requests never drain the bucket or occupy the
        deferral FIFO ahead of real slices."""
        ledger = self._ledger(req.ledgerId)
        if ledger is None:
            return None
        till = min(req.catchupTill, ledger.size)
        start = max(1, req.seqNoStart)
        end = min(req.seqNoEnd, till, start + MAX_TXNS_PER_REP - 1)
        if start > end or till <= 0:
            return None
        return start, end

    def _slice_cost(self, req: CatchupReq) -> int:
        """Token cost of what would actually be SERVED (the clamped
        range, not the raw request), capped at the burst so an
        over-wide slice still serves with a wait bounded by
        burst/rate. 0 = nothing servable."""
        rng = self._servable_range(req)
        if rng is None:
            return 0
        return min(rng[1] - rng[0] + 1, self._throttle_burst)

    def _throttle_defer(self, cost: int, req: CatchupReq,
                        sender: str) -> bool:
        """Token-bucket admission for one slice of ``cost`` txns. False
        = serve now (tokens debited). True = queued on the deferral
        FIFO — the leecher sees a slow seeder, never a silent one. A
        re-ask of a slice already queued (the leecher's retry law
        firing while we throttle) is absorbed into the queued copy."""
        if self._throttle_rate <= 0:
            return False
        if not self._deferred:  # FIFO fairness: never jump the queue
            self._refill()
            if self._tokens >= cost - _TOKEN_EPS:
                self._tokens = max(self._tokens - cost, 0.0)
                return False
        key = (sender, req.ledgerId, req.seqNoStart, req.seqNoEnd)
        if key not in self._deferred_keys:
            # the meter counts DISTINCT slices held back; a retry-law
            # re-ask of a slice already queued is absorbed silently
            self.deferred_total += 1
            self._metrics.add_event(MetricsName.CATCHUP_SEEDER_DEFERRED)
            self._deferred_keys.add(key)
            self._deferred.append((key, req, sender))
        self._schedule_wakeup()
        return True

    def _schedule_wakeup(self) -> None:
        """ONE pending wakeup at the deterministic instant the bucket
        covers the FIFO head (re-armed after each drain) — deferred
        slices never race each other for the refill."""
        if self._wakeup_pending or not self._deferred:
            return
        self._refill()
        head_cost = self._slice_cost(self._deferred[0][1])
        delay = max(max(head_cost - self._tokens, 0.0)
                    / self._throttle_rate, _MIN_DEFER_DELAY)
        self._wakeup_pending = True
        self._timer.schedule(delay, self._drain_deferred)

    def _drain_deferred(self) -> None:
        self._wakeup_pending = False
        while self._deferred:
            key, req, sender = self._deferred[0]
            self._refill()
            cost = self._slice_cost(req)
            if cost == 0:
                # became unservable while queued (ledger reset, stale
                # range): drop without debiting tokens
                self._deferred.popleft()
                self._deferred_keys.discard(key)
                continue
            if self._tokens < cost - _TOKEN_EPS:
                break
            self._deferred.popleft()
            self._deferred_keys.discard(key)
            self._tokens = max(self._tokens - cost, 0.0)
            self._serve_catchup_req(req, sender)
        self._schedule_wakeup()

    def process_catchup_req(self, req: CatchupReq, sender: str):
        if self._throttle_rate > 0:
            cost = self._slice_cost(req)
            if cost == 0:
                return  # nothing servable: never charge the bucket
            if self._throttle_defer(cost, req, sender):
                logger.debug("%s throttled catchup slice %s..%s for %s",
                             self._name, req.seqNoStart, req.seqNoEnd,
                             sender)
                return
        self._serve_catchup_req(req, sender)

    def _serve_catchup_req(self, req: CatchupReq, sender: str):
        rng = self._servable_range(req)
        if rng is None:
            return  # nothing we can serve
        start, end = rng
        ledger = self._ledger(req.ledgerId)
        till = min(req.catchupTill, ledger.size)
        self.served_txns += end - start + 1
        self._metrics.add_event(MetricsName.CATCHUP_SEEDER_TXNS,
                                end - start + 1)
        txns = {}
        paths = {}
        for seq in range(start, end + 1):
            txns[str(seq)] = ledger.get_by_seq_no(seq)
            paths[str(seq)] = [
                b58encode(h) for h in ledger.audit_path(seq, till)]
        rep = CatchupRep(ledgerId=req.ledgerId, txns=txns,
                         auditPaths=paths, catchupTill=till)
        self._network.send(rep, [sender])
        logger.debug("%s seeded %d..%d of ledger %d to %s", self._name,
                     start, end, req.ledgerId, sender)
