"""Answering peers' catchup requests from our committed ledgers.

Reference: plenum/server/catchup/seeder_service.py (`SeederService`).
Two inbound message types:

- ``LEDGER_STATUS`` from a peer: if the peer is behind us, reply with a
  ``CONSISTENCY_PROOF`` (their size -> our size, RFC 6962) so its
  ConsProofService can agree on a catchup target; if it matches us, echo
  our own ``LEDGER_STATUS`` (an "up to date" vote).
- ``CATCHUP_REQ`` for a txn range: reply with the txns AND a per-txn audit
  path against the requested ``catchupTill`` tree size (the TPU-first
  redesign: the leecher verifies the whole slice in one vmapped device
  kernel call instead of an incremental host tree fold).
"""
from __future__ import annotations

import logging
from typing import Optional

from ...common.event_bus import ExternalBus
from ...common.messages.node_messages import (
    CatchupRep,
    CatchupReq,
    ConsistencyProof,
    LedgerStatus,
)
from ...server.database_manager import DatabaseManager
from ...utils.base58 import b58encode

logger = logging.getLogger(__name__)

# cap on txns per CATCHUP_REP (the requester also slices; defence in depth)
MAX_TXNS_PER_REP = 10_000


class SeederService:
    def __init__(self, network: ExternalBus, db: DatabaseManager,
                 own_name: str = "?"):
        self._network = network
        self._db = db
        self._name = own_name
        network.subscribe(LedgerStatus, self.process_ledger_status)
        network.subscribe(CatchupReq, self.process_catchup_req)

    def _ledger(self, ledger_id: int):
        try:
            return self._db.get_ledger(ledger_id)
        except KeyError:
            return None

    # ------------------------------------------------------------------

    def own_ledger_status(self, ledger_id: int) -> Optional[LedgerStatus]:
        ledger = self._ledger(ledger_id)
        if ledger is None:
            return None
        return LedgerStatus(
            ledgerId=ledger_id,
            txnSeqNo=ledger.size,
            viewNo=None,
            ppSeqNo=None,
            merkleRoot=b58encode(ledger.root_hash),
            protocolVersion=2,
        )

    def process_ledger_status(self, status: LedgerStatus, sender: str):
        ledger = self._ledger(status.ledgerId)
        if ledger is None:
            return
        their_size = status.txnSeqNo
        if their_size > ledger.size:
            # the peer claims to be AHEAD of us: echo our own status. An
            # ahead-but-diverged peer (corrupt extra tail) gets no
            # consistency proofs from anyone — without this echo it could
            # never learn the pool's tip and would spin in catchup forever
            self._network.send(self.own_ledger_status(status.ledgerId),
                               [sender])
            return
        if their_size == ledger.size:
            # equality vote (also lets a diverged same-size peer notice the
            # root mismatch in our status)
            self._network.send(self.own_ledger_status(status.ledgerId),
                               [sender])
            return
        proof = ConsistencyProof(
            ledgerId=status.ledgerId,
            seqNoStart=their_size,
            seqNoEnd=ledger.size,
            viewNo=None,
            ppSeqNo=None,
            # root_hash_at(0) is the RFC 6962 empty-tree hash — one
            # convention everywhere (a zero-byte sentinel here would
            # desync from the statuses empty peers genuinely send)
            oldMerkleRoot=b58encode(ledger.root_hash_at(their_size)),
            newMerkleRoot=b58encode(ledger.root_hash),
            hashes=[b58encode(h)
                    for h in ledger.consistency_proof(their_size)],
        )
        self._network.send(proof, [sender])

    # ------------------------------------------------------------------

    def process_catchup_req(self, req: CatchupReq, sender: str):
        ledger = self._ledger(req.ledgerId)
        if ledger is None:
            return
        till = min(req.catchupTill, ledger.size)
        start = max(1, req.seqNoStart)
        end = min(req.seqNoEnd, till, start + MAX_TXNS_PER_REP - 1)
        if start > end or till <= 0:
            return  # nothing we can serve
        txns = {}
        paths = {}
        for seq in range(start, end + 1):
            txns[str(seq)] = ledger.get_by_seq_no(seq)
            paths[str(seq)] = [
                b58encode(h) for h in ledger.audit_path(seq, till)]
        rep = CatchupRep(ledgerId=req.ledgerId, txns=txns,
                         auditPaths=paths, catchupTill=till)
        self._network.send(rep, [sender])
        logger.debug("%s seeded %d..%d of ledger %d to %s", self._name,
                     start, end, req.ledgerId, sender)
