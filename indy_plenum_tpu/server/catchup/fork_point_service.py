"""Finding where a diverged ledger forked from the honest chain.

Round-3 verdict weakness: divergence recovery was nuke-and-refetch —
``reset_to(0)`` and re-download the ENTIRE ledger, a full 1M-txn transfer
where a fork-point search would fetch a suffix. (The reference sidesteps
the problem by refusing to run with a diverged ledger at all; this is a
capability the redesign adds on top of
plenum/server/catchup/cons_proof_service.py's machinery.)

Binary search over prefix sizes, driven by the same wire messages catchup
already uses: probing size ``s`` means broadcasting ``LEDGER_STATUS
(txnSeqNo=s)``; peers ahead of ``s`` answer with a ``CONSISTENCY_PROOF``
whose ``oldMerkleRoot`` is THEIR root at ``s`` (SeederService builds
exactly that), and peers level with ``s`` echo their status. A weak
quorum (f+1) of matching roots at ``s`` contains at least one honest
node, so the agreed value IS the honest chain's root at ``s``:

    agreed root == our root_hash_at(s)  =>  our prefix is honest to s
    else                                =>  the fork is at or below s

Safety does not rest on this search: every fetched txn is still verified
against the (weak-quorum) target root via audit paths, and a post-fetch
root mismatch falls back to truncating deeper. The search only bounds how
much gets re-downloaded — log2(size) probe rounds instead of a full
ledger transfer.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional, Set

from ...common.event_bus import ExternalBus
from ...common.messages.node_messages import (
    ConsistencyProof,
    LedgerStatus,
)
from ...common.timer import RepeatingTimer, TimerService
from ...utils.base58 import b58encode

logger = logging.getLogger(__name__)

# give up the search (and fall back to size 0) after this many silent
# rebroadcasts of one probe
MAX_PROBE_RETRIES = 5


class ForkPointService:
    def __init__(self,
                 ledger_id: int,
                 network: ExternalBus,
                 timer: TimerService,
                 db,
                 quorums_provider: Callable[[], object],
                 config=None):
        from ...config import getConfig

        self._ledger_id = ledger_id
        self._network = network
        self._timer = timer
        self._db = db
        self._quorums = quorums_provider
        self._config = config or getConfig()

        self._running = False
        self._on_found: Optional[Callable[[int], None]] = None
        self._lo = 0  # invariant: prefix at _lo matches the honest chain
        self._hi = 0  # invariant: prefix at _hi is (convicted) diverged
        self._mid = 0
        self._probe_retries = 0
        # root_b58 at _mid -> senders voting for it
        self._votes: Dict[str, Set[str]] = {}
        # (tip_size, root_b58) votes from peers whose whole ledger is
        # BELOW the probe (we are ahead of the pool): their tip decides
        self._tip_votes: Dict[tuple, Set[str]] = {}
        self._retry = RepeatingTimer(
            timer, self._config.ConsistencyProofsTimeout,
            self._rebroadcast, active=False)

        network.subscribe(ConsistencyProof, self.process_consistency_proof)
        network.subscribe(LedgerStatus, self.process_ledger_status)

    # ------------------------------------------------------------------

    @property
    def _ledger(self):
        return self._db.get_ledger(self._ledger_id)

    def start(self, on_found: Callable[[int], None]) -> None:
        """``on_found(fork_size)``: truncating to ``fork_size`` leaves
        only honest history (0 = nothing salvageable / search failed)."""
        self._on_found = on_found
        self._lo = 0
        self._hi = self._ledger.size
        self._running = True
        if self._hi <= 1:
            self._finish(0)
            return
        self._retry.start()
        self._next_probe()

    def stop(self) -> None:
        self._running = False
        self._retry.stop()

    def _finish(self, fork: int) -> None:
        self.stop()
        cb, self._on_found = self._on_found, None
        logger.info("ledger %d fork point: honest prefix ends at %d",
                    self._ledger_id, fork)
        if cb is not None:
            cb(fork)

    # ------------------------------------------------------------------

    def _next_probe(self) -> None:
        if self._hi - self._lo <= 1:
            self._finish(self._lo)
            return
        self._mid = (self._lo + self._hi) // 2
        self._votes.clear()
        self._tip_votes.clear()
        self._probe_retries = 0
        self._broadcast()

    def _broadcast(self) -> None:
        self._network.send(LedgerStatus(
            ledgerId=self._ledger_id,
            txnSeqNo=self._mid,
            viewNo=None,
            ppSeqNo=None,
            merkleRoot=b58encode(self._ledger.root_hash_at(self._mid)),
            protocolVersion=2,
            # marked as a QUESTION: our root at mid may come from the
            # corrupt prefix under investigation — peers must answer it
            # but never count it as evidence about anyone's ledger
            probe=True,
        ))

    def _rebroadcast(self) -> None:
        if not self._running:
            self._retry.stop()
            return
        self._probe_retries += 1
        if self._probe_retries > MAX_PROBE_RETRIES:
            logger.warning("ledger %d fork search: no quorum at %d; "
                           "falling back to full resync",
                           self._ledger_id, self._mid)
            self._finish(0)
            return
        self._broadcast()

    # ------------------------------------------------------------------

    def process_consistency_proof(self, proof: ConsistencyProof,
                                  sender: str) -> None:
        """A peer ahead of the probe answers with ITS root at our claimed
        size (the probe) in ``oldMerkleRoot``."""
        if not self._running or proof.ledgerId != self._ledger_id:
            return
        if proof.seqNoStart != self._mid:
            return  # stale (an earlier probe's answer)
        self._add_vote(sender, proof.oldMerkleRoot)

    def process_ledger_status(self, status: LedgerStatus,
                              sender: str) -> None:
        """A peer exactly AT the probe size echoes its status (its tip
        root is its root at the probe); a peer whose whole ledger sits
        BELOW the probe reveals the pool's tip — the honest chain simply
        ends there, so f+1 agreeing tips settle the search outright."""
        if not self._running or status.ledgerId != self._ledger_id:
            return
        if getattr(status, "probe", None):
            return  # another searcher's question, not a tip assertion
        if status.txnSeqNo == self._mid:
            self._add_vote(sender, status.merkleRoot)
            return
        if status.txnSeqNo < self._mid:
            key = (status.txnSeqNo, status.merkleRoot)
            self._tip_votes.setdefault(key, set()).add(sender)
            quorums = self._quorums()
            for (tip, root), senders in self._tip_votes.items():
                # STRONG quorum: settling the search below the probe
                # truncates past the pool tip, the same commitment a
                # below-us catchup target makes (see cons_proof_service)
                if quorums.strong.is_reached(len(senders)):
                    # root_hash_at(0) = the RFC 6962 empty-tree hash
                    ours = b58encode(self._ledger.root_hash_at(tip))
                    if root == ours:
                        self._finish(tip)  # honest chain ends at tip
                    else:
                        self._hi = tip  # fork strictly below their tip
                        self._next_probe()
                    return

    def _add_vote(self, sender: str, root_b58: str) -> None:
        self._votes.setdefault(root_b58, set()).add(sender)
        quorums = self._quorums()
        for root, senders in self._votes.items():
            if quorums.weak.is_reached(len(senders)):
                ours = b58encode(self._ledger.root_hash_at(self._mid))
                if root == ours:
                    self._lo = self._mid  # prefix honest up to mid
                else:
                    self._hi = self._mid  # fork at or below mid
                self._next_probe()
                return
