"""Whole-node catchup orchestration: AUDIT first, then the rest.

Reference: plenum/server/catchup/node_leecher_service.py
(`NodeLeecherService`) + ledger_leecher_service.py (merged: one ledger's
pipeline is just ConsProof -> CatchupRep here). Sequencing (reference
order): the AUDIT ledger is synced first via a peer quorum
(ConsProofService), because its last txn — the recovery spine written by
AuditBatchHandler per 3PC batch — pins the exact (size, root) every other
ledger must reach, plus the (viewNo, ppSeqNo, primaries) the consensus
layer must resume from. The other ledgers then sync against those pinned
targets with no further quorum rounds.

Divergence recovery: if the cons-proof phase convicts our own history
(f+1 peers' trees disagree with ours at our size), or a ledger's
post-fetch root mismatches its audit-pinned target, the ledger is
truncated (``Ledger.reset_to(0)``) and re-fetched from scratch — states
are derived data and rebuilt from the ledgers afterwards.

Consumes ``NeedMasterCatchup`` (checkpoint lag / checkpoint digest
divergence — both emit sites in checkpoint_service.py); emits
``CatchupFinished`` for the consensus services to resync their 3PC state.
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional

from ...common.constants import (
    AUDIT_LEDGER_ID,
    AUDIT_TXN_LEDGER_ROOT,
    AUDIT_TXN_LEDGERS_SIZE,
    AUDIT_TXN_PP_SEQ_NO,
    AUDIT_TXN_PRIMARIES,
    AUDIT_TXN_VIEW_NO,
    CONFIG_LEDGER_ID,
    DOMAIN_LEDGER_ID,
    POOL_LEDGER_ID,
)
from ...common.event_bus import ExternalBus, InternalBus
from ...common.messages.internal_messages import (
    CatchupFinished,
    NeedMasterCatchup,
)
from ...common.exceptions import SuspiciousNode
from ...common.metrics_collector import MetricsName
from ...common.timer import TimerService
from ...common.txn_util import get_payload_data
from ..suspicion_codes import Suspicions
from ...utils.base58 import b58decode, b58encode
from .catchup_rep_service import CatchupRepService
from .cons_proof_service import ConsProofService

logger = logging.getLogger(__name__)

# catchup order after AUDIT (reference: audit pins the others' targets)
LEDGER_ORDER = (POOL_LEDGER_ID, CONFIG_LEDGER_ID, DOMAIN_LEDGER_ID)


class NodeLeecherService:
    def __init__(self,
                 data,
                 bus: InternalBus,
                 network: ExternalBus,
                 timer: TimerService,
                 bootstrap,
                 config=None,
                 suspicion_sink=None,
                 metrics=None,
                 trace=None):
        """``bootstrap`` is the node's LedgersBootstrap (ledgers, states,
        write manager, state-rebuild)."""
        from ...common.metrics_collector import NullMetricsCollector
        from ...config import getConfig
        from ...observability.trace import NULL_TRACE

        self._data = data
        self._bus = bus
        self._network = network
        self._timer = timer
        self._boot = bootstrap
        self._config = config or getConfig()
        self._suspicion = suspicion_sink or (lambda ex: None)
        self._metrics = metrics if metrics is not None \
            else NullMetricsCollector()
        self._trace = trace if trace is not None else NULL_TRACE

        self._running = False
        self._audit_attempts = 0
        self._remaining: List[int] = []
        self.catchups_completed = 0  # observability / tests
        self.catchups_failed = 0  # consecutive failures (backoff exponent)
        self.rounds_started = 0  # every start(), completed or not

        self._cons_proof = ConsProofService(
            AUDIT_LEDGER_ID, network, timer, self._boot.db,
            quorums_provider=lambda: self._data.quorums,
            config=self._config)
        self._rep_services = {
            lid: CatchupRepService(
                lid, network, timer, self._boot.db, config=self._config,
                suspicion_sink=self._suspicion, metrics=self._metrics,
                trace=self._trace, node=self._data.name)
            for lid in (AUDIT_LEDGER_ID,) + LEDGER_ORDER}
        # divergence recovery: find the fork point and refetch a SUFFIX
        # instead of nuking the whole ledger (r3 verdict weakness 7)
        from .fork_point_service import ForkPointService

        self._fork_services = {
            lid: ForkPointService(
                lid, network, timer, self._boot.db,
                quorums_provider=lambda: self._data.quorums,
                config=self._config)
            for lid in (AUDIT_LEDGER_ID,) + LEDGER_ORDER}

        bus.subscribe(NeedMasterCatchup, self._on_need_catchup)

    # ------------------------------------------------------------------

    def _on_need_catchup(self, msg: NeedMasterCatchup, *args) -> None:
        # DEFERRED start: NeedMasterCatchup can fire in the middle of an
        # Ordered dispatch (the checkpoint service sees the boundary batch
        # before the executor commits it) — starting synchronously would
        # revert a staged batch that is EN ROUTE to commit in the same
        # bus dispatch, and the commit then pops an empty staged list.
        # One 0-delay timer hop lands the start after the current event
        # completes; same virtual instant, so seeded runs stay
        # deterministic, and start() is idempotent under a burst of
        # triggers.
        self._timer.schedule(0.0, self.start)

    def _retry_after_failure(self) -> None:
        # only act if the node is still in the failed state: a catchup
        # triggered by other means (checkpoint lag) may have succeeded
        # since this timer was scheduled, and a healthy participating
        # node must not be yanked back into catchup by a stale timer
        if not self._running and self.catchups_failed > 0:
            self.start()

    def start(self) -> None:
        """Idempotent: a second trigger while catching up is a no-op."""
        if self._running:
            return
        self._running = True
        self.rounds_started += 1
        logger.info("%s starting catchup", self._data.name)
        if self._trace.enabled:
            # leecher rounds are trace spans: started -> txns_leeched* ->
            # completed, keyed by the round ordinal so the phase-latency
            # machinery can join start/end per (node, round)
            self._trace.record("catchup.started", cat="catchup",
                               node=self._data.name,
                               key=(self.rounds_started,))
        self._data.is_participating = False
        # uncommitted 3PC work is void — catchup writes committed txns and
        # Ledger.add() requires nothing staged
        self._revert_all_staged()
        self._audit_attempts = 0
        self._start_audit_phase()

    def _revert_all_staged(self) -> None:
        wm = self._boot.write_manager
        for staged in reversed(wm.staged_batches):
            wm.revert_batches(staged.ledger_id, 1)

    # ------------------------------------------------------------------
    # phase 1: AUDIT ledger via peer quorum
    # ------------------------------------------------------------------

    def _start_audit_phase(self) -> None:
        self._cons_proof.start(self._on_audit_target)

    def _on_audit_target(self, target, diverged: bool) -> None:
        audit = self._boot.db.get_ledger(AUDIT_LEDGER_ID)
        if diverged:
            logger.warning("%s: audit ledger diverged; searching for the "
                           "fork point", self._data.name)

            def on_fork(fork: int) -> None:
                audit.reset_to(fork)
                self._restart_audit_phase()

            self._fork_services[AUDIT_LEDGER_ID].start(on_fork)
            return
        size, root_b58 = target
        self._audit_target = (size, b58decode(root_b58))
        if size < audit.size:
            # the quorum target sits BELOW us: we are ahead of the pool
            # (crash before peers committed, or a corrupt tail). If our
            # prefix at the target matches, truncate to it — the txns
            # either re-order identically or were never honest; keeping a
            # tail no quorum vouches for would fail the fetch check anyway
            if size > 0 and audit.root_hash_at(size) \
                    == self._audit_target[1]:
                audit.reset_to(size)
            else:
                audit.reset_to(0)  # ahead AND diverged below the target
        self._rep_services[AUDIT_LEDGER_ID].start(
            size, self._audit_target[1], self._on_audit_fetched,
            on_fail=self._on_round_failed)

    def _restart_audit_phase(self) -> None:
        self._audit_attempts += 1
        if self._audit_attempts > 3:
            logger.error("%s: audit catchup failed %d times; giving up "
                         "this round", self._data.name, self._audit_attempts)
            self._finish(failed=True)
            return
        self._start_audit_phase()

    def _on_audit_fetched(self) -> None:
        audit = self._boot.db.get_ledger(AUDIT_LEDGER_ID)
        size, root = self._audit_target
        if audit.size >= size and audit.root_hash != root:
            # our pre-existing prefix was wrong (behind AND diverged)
            logger.warning("%s: audit root mismatch after fetch; resync",
                           self._data.name)
            audit.reset_to(0)
            self._restart_audit_phase()
            return
        self._remaining = list(LEDGER_ORDER)
        self._next_ledger()

    # ------------------------------------------------------------------
    # phase 2: remaining ledgers against audit-pinned targets
    # ------------------------------------------------------------------

    def _audit_pinned_target(self, lid: int):
        audit = self._boot.db.get_ledger(AUDIT_LEDGER_ID)
        if audit.size == 0:
            return None
        data = get_payload_data(audit.get_by_seq_no(audit.size))
        size = data.get(AUDIT_TXN_LEDGERS_SIZE, {}).get(str(lid))
        root = data.get(AUDIT_TXN_LEDGER_ROOT, {}).get(str(lid))
        if size is None or root is None:
            return None
        # ledgerRoot may be recorded as a delta reference (int = audit seq
        # of the batch that last changed it) in the reference; here it is
        # always the b58 root string
        return int(size), b58decode(root)

    def _next_ledger(self) -> None:
        while self._remaining:
            lid = self._remaining.pop(0)
            target = self._audit_pinned_target(lid)
            ledger = self._boot.db.get_ledger(lid)
            if target is None:
                continue  # ledger never touched by a batch: genesis only
            size, root = target
            if ledger.size > size or (
                    ledger.size == size and ledger.root_hash != root):
                logger.warning("%s: ledger %d diverged from audit target; "
                               "searching for the fork point",
                               self._data.name, lid)

                def on_fork(fork: int, lid=lid, size=size) -> None:
                    # never keep more than the target prefix: beyond it we
                    # cannot cross-check against the audit-pinned root
                    self._boot.db.get_ledger(lid).reset_to(
                        min(fork, size))
                    self._remaining.insert(0, lid)
                    self._next_ledger()

                self._fork_services[lid].start(on_fork)
                return
            if ledger.size == size:
                continue
            self._current_lid = lid
            self._current_target = (size, root)
            self._rep_services[lid].start(size, root, self._on_ledger_fetched,
                                          on_fail=self._on_round_failed)
            return
        self._finish()

    def _on_ledger_fetched(self) -> None:
        lid = self._current_lid
        size, root = self._current_target
        ledger = self._boot.db.get_ledger(lid)
        if ledger.size >= size and ledger.root_hash != root:
            logger.warning("%s: ledger %d root mismatch after fetch; "
                           "resyncing from scratch", self._data.name, lid)
            ledger.reset_to(0)
            self._rep_services[lid].start(size, root, self._on_ledger_fetched,
                                          on_fail=self._on_round_failed)
            return
        self._next_ledger()

    # ------------------------------------------------------------------
    # phase 3: states + consensus resync
    # ------------------------------------------------------------------

    def _on_round_failed(self) -> None:
        """A ledger fetch exhausted its retry budget (every reachable
        seeder silent or byzantine): fail the round closed."""
        self._finish(failed=True)

    def catchup_stats(self):
        """Aggregate leecher meters (Monitor catchup block, chaos report
        catchup block, bench): rounds + what the rep services counted."""
        reps = list(self._rep_services.values())
        return {
            "rounds_started": self.rounds_started,
            "rounds_completed": self.catchups_completed,
            "rounds_failed_consecutive": self.catchups_failed,
            "txns_leeched": sum(r.txns_leeched for r in reps),
            "proofs_verified": sum(r.proofs_verified for r in reps),
            "reps_rejected": sum(r.reps_rejected for r in reps),
            "retries": sum(r.retries for r in reps),
        }

    def _finish(self, failed: bool = False) -> None:
        self._running = False
        if self._trace.enabled:
            stats = self.catchup_stats()
            self._trace.record(
                "catchup.completed" if not failed else "catchup.failed",
                cat="catchup", node=self._data.name,
                key=(self.rounds_started,),
                args={"txns_leeched": stats["txns_leeched"],
                      "proofs_verified": stats["proofs_verified"],
                      "retries": stats["retries"]})
        if failed:
            # FAIL CLOSED (reference: a node stays in Mode.syncing, never
            # participating, until caught up): our history was convicted as
            # diverged (f+1 peers) but we could not resync to any honest
            # quorum target. Resuming votes/orders/reads from state we KNOW
            # is wrong would be a safety violation — stay out, alert the
            # operator, retry on an exponential backoff.
            self._data.is_participating = False
            self.catchups_failed += 1
            self._metrics.add_event(MetricsName.CATCHUP_FAILED)
            self._suspicion(SuspiciousNode(
                self._data.name, Suspicions.CATCHUP_FAILED))
            delay = min(
                self._config.CatchupFailedRetryBackoff
                * (2 ** (self.catchups_failed - 1)),
                self._config.CatchupFailedRetryBackoffMax)
            logger.error("%s: catchup FAILED (%d consecutive); staying "
                         "non-participating, retrying in %.1fs",
                         self._data.name, self.catchups_failed, delay)
            self._timer.schedule(delay, self._retry_after_failure)
            return
        self.catchups_failed = 0
        self._timer.cancel(self._retry_after_failure)
        # states are derived: replay fetched txns through the handlers
        # (coverage located via the audit spine)
        self._boot._rebuild_states_if_behind()

        audit = self._boot.db.get_ledger(AUDIT_LEDGER_ID)
        view_no, pp_seq_no = self._data.view_no, self._data.last_ordered_3pc[1]
        if audit.size > 0:
            data = get_payload_data(audit.get_by_seq_no(audit.size))
            view_no = data.get(AUDIT_TXN_VIEW_NO, view_no)
            pp_seq_no = data.get(AUDIT_TXN_PP_SEQ_NO, pp_seq_no)
            primaries = data.get(AUDIT_TXN_PRIMARIES)
            if primaries:
                self._data.primaries = list(primaries)
        if view_no > self._data.view_no:
            self._data.view_no = view_no
        self._data.is_participating = True
        self.catchups_completed += 1
        self._metrics.add_event(MetricsName.CATCHUP_ROUNDS)
        logger.info("%s catchup complete: 3pc=(%d,%d)", self._data.name,
                    view_no, pp_seq_no)
        self._bus.send(CatchupFinished(
            last_caught_up_3pc=(view_no, pp_seq_no),
            master_last_ordered=(view_no, pp_seq_no)))
