"""Ingress-plane driver: open-loop load + admission + reads, end to end.

Runs the seeded million-client workload generator against a tick-batched
``SimPool`` with admission control armed, serves the read mix through the
device-proof :class:`~indy_plenum_tpu.ingress.read_service.ReadService`,
and emits ONE machine-readable JSON line: arrivals/admitted/shed, the
shed-set fingerprint, sustained ordered/sim-second, p50/p99
``req.ingress -> req.finalised`` latency from the flight-recorder spans,
read qps (virtual-clock derived), ``ordered_hash`` and ``trace_hash``.
Same seed => byte-identical record fields (only ``wall_s`` is wall time)
— replay a saturation incident exactly.

Workload profiles + closed-loop retry (overload robustness plane):
``--profile diurnal|flash`` modulates the arrival rate (day curve /
crowd spike — the ``WorkloadProfile*`` config knobs shape it), and
``--retry`` arms the per-client seeded-backoff retry of shed requests
(``--retry-max`` attempts). The JSON record then carries a ``retry``
block: attempts, exhausted clients, the first-attempt/retry admission
split, the goodput fraction, and the ``retry_hash`` fingerprint.

Usage:
    python scripts/ingress_run.py --nodes 16 --rate 400 --duration 20 \
        --capacity 256 --read-fraction 0.5 --json
    python scripts/ingress_run.py --profile flash --retry --retry-max 4 \
        --rate 300 --capacity 64 --json
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# shared persistent XLA compile cache: on XLA:CPU the auth/flush kernels
# otherwise cost minutes of cold compile per invocation of this script
from indy_plenum_tpu.utils.jax_env import (  # noqa: E402
    enable_persistent_compile_cache,
)

enable_persistent_compile_cache()

from indy_plenum_tpu.config import getConfig  # noqa: E402
from indy_plenum_tpu.ingress import (  # noqa: E402
    ReadService,
    StaticCorpusBacking,
    WorkloadGenerator,
    WorkloadProfile,
    WorkloadSpec,
)
from indy_plenum_tpu.simulation.pool import SimPool  # noqa: E402


def build_pool(args) -> SimPool:
    config = getConfig({
        "Max3PCBatchSize": args.batch_size,
        "Max3PCBatchWait": 0.05,
        "QuorumTickInterval": args.tick,
        "QuorumTickAdaptive": not args.static_tick,
        "IngressQueueCapacity": args.capacity,
        "IngressPerClientCap": args.per_client_cap,
        "IngressRetryMax": args.retry_max if args.retry else 0,
    })
    return SimPool(n_nodes=args.nodes, seed=args.seed, config=config,
                   device_quorum=True, shadow_check=False,
                   sign_requests=True, num_instances=args.instances,
                   trace=True, trace_capacity=args.trace_capacity)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=16)
    ap.add_argument("--instances", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=80)
    ap.add_argument("--tick", type=float, default=0.1)
    ap.add_argument("--static-tick", action="store_true",
                    help="freeze the tick (skip the adaptive governor)")
    ap.add_argument("--seed", type=int, default=11)
    # workload (open loop — arrivals never wait for completions)
    ap.add_argument("--clients", type=int, default=1_000_000,
                    help="virtual client population (Zipf-skewed)")
    ap.add_argument("--rate", type=float, default=400.0,
                    help="arrivals per sim-second")
    ap.add_argument("--duration", type=float, default=20.0,
                    help="arrival window, sim-seconds")
    ap.add_argument("--settle", type=float, default=20.0,
                    help="extra sim-seconds to drain after arrivals stop")
    ap.add_argument("--read-fraction", type=float, default=0.5)
    ap.add_argument("--zipf-clients", type=float, default=1.1)
    ap.add_argument("--zipf-keys", type=float, default=1.2)
    ap.add_argument("--keys", type=int, default=16384,
                    help="hot-key universe (NYM/attrib read corpus)")
    ap.add_argument("--profile", default="steady",
                    choices=["steady", "diurnal", "flash"],
                    help="arrival-rate modulation: steady (flat), "
                         "diurnal (day curve), flash (crowd spike) — "
                         "shaped by the WorkloadProfile* config knobs")
    # closed-loop retry (overload robustness plane)
    ap.add_argument("--retry", action="store_true",
                    help="arm per-client seeded-backoff retries of shed "
                         "requests (the closed loop real overload "
                         "compounds through)")
    ap.add_argument("--retry-max", type=int, default=3,
                    help="retry budget per request before the client "
                         "gives up (must be >= 1)")
    # admission
    ap.add_argument("--capacity", type=int, default=256,
                    help="bounded auth-queue capacity (per tick drain)")
    ap.add_argument("--per-client-cap", type=int, default=0)
    ap.add_argument("--trace-capacity", type=int, default=1 << 20)
    ap.add_argument("--trace-out", default=None,
                    help="dump the span trace as JSONL (trace_tool.py)")
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable stdout line")
    args = ap.parse_args()
    if args.capacity < 1:
        # SimPool only arms the admission plane for a positive capacity —
        # fail here, not with an AttributeError after the full run
        ap.error("--capacity must be >= 1 (0 disables admission control, "
                 "which this driver exists to measure)")
    if args.retry_max < 1:
        # a zero/negative budget silently disarms the loop the flag
        # asked for — refuse instead of reporting an empty retry block
        ap.error("--retry-max must be >= 1 (a request needs at least "
                 "one retry for the closed loop to exist)")

    pool = build_pool(args)
    reads = ReadService(StaticCorpusBacking(args.keys, seed=args.seed),
                        clock=pool.timer.get_current_time,
                        metrics=pool.metrics, trace=pool.trace)
    # warm the read-verify kernel outside the measured window (first call
    # pays XLA compile)
    reads.submit(0)
    for i in range(63):
        reads.submit(i)
    reads.drain()
    reads.reset_serve_meters()

    seq = [0]

    def on_write(client: int, key: int) -> None:
        seq[0] += 1
        pool.submit_request(seq[0], client_id="c%d" % client)

    gen = WorkloadGenerator(WorkloadSpec(
        n_clients=args.clients, rate=args.rate, duration=args.duration,
        read_fraction=args.read_fraction,
        zipf_clients=args.zipf_clients, zipf_keys=args.zipf_keys,
        n_keys=args.keys, seed=args.seed,
        profile=WorkloadProfile.from_config(args.profile, pool.config)))
    gen.start(pool.timer, on_write,
              on_read=lambda client, key: reads.submit(key))

    sim_t0 = pool.timer.get_current_time()
    wall_t0 = time.perf_counter()
    horizon = args.duration + args.settle
    step = 0.5
    elapsed = 0.0
    # run the arrival window + settle, then keep going until the queue
    # AND the retry storm drain (outstanding seeded re-offers included)
    while elapsed < horizon or pool.admission.depth \
            or (pool.retry is not None and pool.retry.outstanding):
        pool.run_for(step)
        elapsed += step
        reads.drain()  # reads ride the driver loop: zero 3PC involvement
    wall_s = time.perf_counter() - wall_t0
    sim_elapsed = pool.timer.get_current_time() - sim_t0

    assert pool.honest_nodes_agree(), "pool diverged under load"
    ordered = min(len(nd.ordered_digests) for nd in pool.nodes)

    from indy_plenum_tpu.observability.trace import phase_percentiles

    phases = phase_percentiles(pool.trace.events())
    adm = pool.admission
    record = {
        "nodes": args.nodes,
        "instances": args.instances,
        "seed": args.seed,
        "profile": args.profile,
        "workload": gen.counters(),
        "admission": adm.counters(),
        "shed_fraction": round(adm.shed_total / max(adm.offered_total, 1),
                               4),
        "shed_hash": adm.shed_hash(),
        "ordered": ordered,
        "ordered_per_sim_second": round(ordered / sim_elapsed, 2)
        if sim_elapsed else None,
        "sim_elapsed_s": round(sim_elapsed, 2),
        "wall_s": round(wall_s, 2),
        # the acceptance latency: earliest req.ingress anywhere ->
        # earliest req.finalised, per request, from the trace spans
        "ingress_to_finalised": phases.get("auth"),
        "reads": reads.counters(),
        "ordered_hash": pool.ordered_hash(),
        "trace_hash": pool.trace.trace_hash(),
        "governor": (pool.governor.trajectory_summary()
                     if pool.governor is not None else None),
    }
    if pool.retry is not None:
        # the closed-loop record: re-offer counts, the first-attempt vs
        # retry admission split, goodput (unique requests that made it
        # through per unique write arrival), and the retry-storm
        # fingerprint — byte-identical per seed like shed_hash
        from indy_plenum_tpu.common.metrics_collector import MetricsName

        counters = pool.retry.counters()
        readmitted = pool.metrics.stat(
            MetricsName.INGRESS_RETRY_ADMITTED)
        readmitted_n = int(readmitted.total) if readmitted else 0
        record["retry"] = {
            "max_attempts": args.retry_max,
            "attempts": counters["reoffers"],
            "requests_retried": counters["requests_retried"],
            "exhausted": counters["exhausted"],
            "retry_admitted": readmitted_n,
            "first_attempt_admitted": adm.admitted_total - readmitted_n,
            "goodput_fraction": round(
                ordered / max(gen.writes, 1), 4),
            "retry_hash": pool.retry.retry_hash(),
        }
    if args.trace_out:
        pool.trace.dump(args.trace_out)
        record["trace_file"] = args.trace_out
    if args.json:
        print(json.dumps(record, separators=(",", ":")))
    else:
        for key, value in record.items():
            print(f"{key}: {value}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
