"""Consume consensus flight-recorder dumps (observability.trace JSONL).

Usage:
    python scripts/trace_tool.py TRACE.jsonl                 # full report
    python scripts/trace_tool.py TRACE.jsonl --phases        # percentiles
    python scripts/trace_tool.py TRACE.jsonl --critical-path
    python scripts/trace_tool.py TRACE.jsonl --journeys      # e2e table
    python scripts/trace_tool.py TRACE.jsonl --journey DIGEST
    python scripts/trace_tool.py TRACE.jsonl --chrome OUT.json
    python scripts/trace_tool.py TRACE.jsonl --json
    python scripts/trace_tool.py TRACE.jsonl --node node0
    python scripts/trace_tool.py n0.jsonl n1.jsonl n2.jsonl --journeys

Dumps come from ``SimPool(trace=True)`` / ``NodePool(trace=True)``,
``chaos_run.py --trace`` (``<report>.trace.jsonl``),
``profile_rbft.py --trace``, or a deployed node's SIGUSR2 flight dump.
Several dumps (one per node) merge into one deterministic timeline —
the causal plane's cross-node joins work either way. Views:

- **--phases**: per-phase latency percentiles (p50/p90/p99/max) for the
  3PC lifecycle — prepare / commit / order / execute, plus the ingress
  auth phase. Simulation dumps measure VIRTUAL (protocol) time; deployed
  dumps measure perf_counter time.
- **--critical-path**: per ordered batch, which phase dominated its
  latency, plus each phase's share of total attributed time — the view
  that turns "a batch ordered in X ms" into "X went to the prepare wave".
- **--journeys**: the causal journey table (observability.causal) —
  per-request end-to-end latency ACROSS NODES with network / queue /
  compute / device attribution, completeness, and the byte-stable
  ``journey_hash``. Geo dumps add a home-region column per journey and
  a per-region write/read e2e rollup; ``--region R`` (like ``--lane``)
  restricts the table to one region.
- **--journey DIGEST** (prefix ok): one request's full cross-node path —
  every per-node lifecycle mark with its deterministic span id, per-hop
  attribution, and the per-wave network latency samples behind it.
- **--chrome**: Chrome trace-event JSON (one pid per node, one tid per
  category; matched net.send/net.recv marks become flow arrows between
  node tracks), loadable in Perfetto (https://ui.perfetto.dev) or
  chrome://tracing.

Deliberately free of jax imports: the tool must run anywhere a dump
lands, including hosts without the accelerator stack.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from indy_plenum_tpu.observability.causal import (  # noqa: E402
    build_journeys,
    journey_for,
    journey_summary,
    merge_events,
)
from indy_plenum_tpu.observability.trace import (  # noqa: E402
    critical_path,
    load_jsonl,
    overlap_report,
    phase_percentiles,
    rollup_report,
    to_chrome_trace,
)


def _counts(events) -> dict:
    by_cat, by_name = {}, {}
    for ev in events:
        by_cat[ev.get("cat", "")] = by_cat.get(ev.get("cat", ""), 0) + 1
        by_name[ev["name"]] = by_name.get(ev["name"], 0) + 1
    return {"events": len(events), "by_cat": by_cat, "by_name": by_name}


def _flight_events(events) -> list:
    return [ev for ev in events if ev.get("cat") == "flight"]


def _print_journey(detail: dict) -> None:
    j = detail["journey"]
    lane = f" lane={j['lane']}" if "lane" in j else ""
    region = f" region={j['region']}" if "region" in j else ""
    print(f"journey {j['digest'][:16]}… trace_id={j['trace_id']} "
          f"class={j['class']}{lane}{region} batch=(v{j['batch'][0]} "
          f"s{j['batch'][1]} {str(j['batch'][2])[:12]}…)")
    print(f"  e2e={j['e2e']} complete={j['complete']} "
          f"attribution={j['attribution']}"
          + (f" retries={j['retries']}" if j.get("retries") else "")
          + (f" via_catchup={j['catchup']}" if j.get("catchup") else "")
          + (f" proof_after={j['proof_after']}"
             if "proof_after" in j else ""))
    print(f"  {'hop':12s} {'t0':>16s} {'dur':>12s} {'network':>10s} "
          f"{'residual':>16s} span_id")
    for h in j["hops"]:
        residual = next(((k, v) for k, v in h.items()
                         if k in ("queue", "compute", "device")),
                        ("", 0.0))
        print(f"  {h['hop']:12s} {h['t0']:>16.6f} {h['dur']:>12.6f} "
              f"{h['network']:>10.6f} {residual[1]:>10.6f} "
              f"{residual[0]:<5s} {h['span_id']}")
    print("  cross-node marks:")
    for m in detail["marks"]:
        print(f"    t={m['ts']:.6f} {m['node'] or 'pool':10s} "
              f"{m['name']:22s} span={m['span_id']}")
    if detail["net_waves"]:
        print("  network waves (in-flight seconds per delivered copy):")
        for op, lats in detail["net_waves"].items():
            show = ", ".join(f"{v:.4f}" for v in lats[:8])
            more = f" (+{len(lats) - 8} more)" if len(lats) > 8 else ""
            print(f"    {op:12s} n={len(lats):<4d} {show}{more}")


def _print_journey_table(record: dict) -> None:
    js = record["journeys"]
    e2e_w, e2e_r = js["e2e"]["write"], js["e2e"]["read"]
    retried = f", retried={js['retried']}" if js.get("retried") else ""
    print(f"journeys: {js['complete']}/{js['count']} complete "
          f"(orphans={js['orphan_spans']}, pending={js['pending']}, "
          f"shed={js['shed']}, via_catchup={js['catchup_journeys']}"
          f"{retried}) hash={js['journey_hash'][:16]}…")
    print(f"  e2e write: n={e2e_w['count']} p50={e2e_w['p50']} "
          f"p90={e2e_w['p90']} p99={e2e_w['p99']} max={e2e_w['max']}")
    if e2e_r["count"]:
        print(f"  e2e read:  n={e2e_r['count']} p50={e2e_r['p50']} "
              f"p90={e2e_r['p90']} p99={e2e_r['p99']}")
    if js["attribution_share"]:
        print("  attribution: " + "  ".join(
            f"{k}={v:.1%}" for k, v in js["attribution_share"].items()))
    if js.get("critical_path"):
        print("  dominant hop: " + "  ".join(
            f"{k}={v}" for k, v in js["critical_path"].items()))
    lanes = js.get("lanes")
    if lanes:
        per = "  ".join(
            f"L{l}:n={lanes['journeys_per_lane'][l]}"
            f",p99={lanes['e2e_per_lane'][l]['p99']}"
            for l in sorted(lanes["journeys_per_lane"], key=int))
        print(f"  lanes: {lanes['count']} "
              f"(barrier hop on {lanes['with_barrier_hop']}"
              f"/{lanes['with_lane']})  {per}")
    regions = js.get("regions")
    if regions:
        print(f"  regions: {regions['count']} "
              f"(tagged {regions['with_region']}/{js['count']} writes)")
        per_w = regions.get("journeys_per_region") or {}
        e2e_w_r = regions.get("e2e_per_region") or {}
        for r in sorted(per_w, key=int):
            st = e2e_w_r.get(r) or {}
            print(f"    R{r} write: n={per_w[r]} p50={st.get('p50')} "
                  f"p99={st.get('p99')}")
        for r, st in sorted((regions.get("read_e2e_per_region")
                             or {}).items(), key=lambda kv: int(kv[0])):
            print(f"    R{r} read:  n={st['count']} p50={st['p50']} "
                  f"p99={st['p99']}")
    fw = js.get("fault_window")
    if fw:
        print(f"  fault windows: {fw['windows']} — "
              f"{fw['through_fault']['count']} journeys crossed one "
              f"(p50 {fw['through_fault']['p50']} vs "
              f"{fw['clear']['p50']} clear, p50_cost={fw['p50_cost']})")
    for j in record.get("journey_table", []):
        mark = "" if j["complete"] else "  INCOMPLETE"
        catchup = (" catchup=" + ",".join(j["catchup"])
                   if j.get("catchup") else "")
        lane = f"lane={j['lane']} " if "lane" in j else ""
        region = f"region={j['region']} " if "region" in j else ""
        # closed-loop retry: how many re-offers this request took (its
        # hops then carry the `retry` hop's backoff wait)
        retries = f"retries={j['retries']} " if j.get("retries") else ""
        print(f"  {j['digest'][:16]}… {lane}{region}{retries}"
              f"e2e={j['e2e']} "
              f"batch=v{j['batch'][0]}s{j['batch'][1]} "
              f"net={j['attribution']['network']} "
              f"queue={j['attribution']['queue']} "
              f"compute={j['attribution']['compute']} "
              f"device={j['attribution']['device']}{catchup}{mark}")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", nargs="+",
                    help="trace JSONL file(s); several per-node dumps "
                         "merge into one deterministic timeline")
    ap.add_argument("--phases", action="store_true",
                    help="per-phase latency percentiles only")
    ap.add_argument("--critical-path", action="store_true",
                    help="per-batch dominant-phase breakdown only")
    ap.add_argument("--overlap", action="store_true",
                    help="per-tick host/device overlap fraction + "
                         "readback-bytes column (ordering fast path)")
    ap.add_argument("--rollups", action="store_true",
                    help="telemetry windowed rollups: per-window "
                         "ordered/shed/p99/high-water table with drift "
                         "anomaly marks (long-horizon soak dumps)")
    ap.add_argument("--journeys", action="store_true",
                    help="causal journey table: per-request cross-node "
                         "e2e latency with network/queue/compute/device "
                         "attribution + journey_hash")
    ap.add_argument("--journey", metavar="DIGEST", default=None,
                    help="one request's full cross-node path (digest "
                         "prefix ok): per-node marks, span ids, per-hop "
                         "attribution, per-wave network samples")
    ap.add_argument("--lane", type=int, default=None, metavar="L",
                    help="restrict the --journeys table to one ordering "
                         "lane (laned dumps tag every journey with its "
                         "lane; the summary rollup stays pool-wide)")
    ap.add_argument("--region", type=int, default=None, metavar="R",
                    help="restrict the --journeys table to one home "
                         "region (geo dumps tag every journey with the "
                         "submitting client's region; the summary "
                         "rollup stays pool-wide)")
    ap.add_argument("--chrome", metavar="OUT",
                    help="write Chrome trace-event JSON (Perfetto)")
    ap.add_argument("--node", default=None,
                    help="restrict phase views to one node's marks")
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable JSON line on stdout")
    args = ap.parse_args()

    if len(args.dump) == 1:
        events = load_jsonl(args.dump[0])
    else:
        events = merge_events(*[load_jsonl(p) for p in args.dump])
    if not events:
        print(f"{', '.join(args.dump)}: no events", file=sys.stderr)
        return 2

    record = {"dump": args.dump[0] if len(args.dump) == 1
              else list(args.dump), "summary": _counts(events)}
    # --phases/--critical-path/--overlap/--journeys narrow the view;
    # --chrome is orthogonal, --journey replaces the report entirely
    if args.journey is not None:
        detail = journey_for(events, args.journey)
        if detail is None:
            print(f"no journey matches digest prefix {args.journey!r}",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(detail, separators=(",", ":"),
                             sort_keys=True))
            return 0
        _print_journey(detail)
        return 0
    view_selected = (args.phases or args.critical_path or args.overlap
                     or args.rollups or args.journeys)
    if args.phases or not view_selected:
        record["phase_latency"] = phase_percentiles(events, node=args.node)
    if args.critical_path or not view_selected:
        record["critical_path"] = critical_path(events, node=args.node)
    if args.overlap or not view_selected:
        record["overlap"] = overlap_report(events, node=args.node)
    if args.rollups or not view_selected:
        rollups = rollup_report(events, node=args.node)
        if rollups["windows"] or args.rollups:
            record["rollups"] = rollups
    if args.journeys or not view_selected:
        built = build_journeys(events)
        record["journeys"] = journey_summary(events, built=built)
        if args.journeys:
            table = built["journeys"]
            if args.lane is not None:
                table = [j for j in table if j.get("lane") == args.lane]
            if args.region is not None:
                table = [j for j in table
                         if j.get("region") == args.region]
            record["journey_table"] = table
    if not view_selected:
        record["flight_events"] = _flight_events(events)
    if args.chrome:
        chrome = to_chrome_trace(events)
        with open(args.chrome, "w") as fh:
            json.dump(chrome, fh, separators=(",", ":"))
        record["chrome"] = {"file": args.chrome,
                            "events": len(chrome["traceEvents"])}

    if args.json:
        print(json.dumps(record, separators=(",", ":"), sort_keys=True))
        return 0

    summary = record["summary"]
    print(f"{', '.join(args.dump)}: {summary['events']} events "
          f"({', '.join(f'{c}={n}' for c, n in sorted(summary['by_cat'].items()))})")
    if "phase_latency" in record:
        print("phase latency (p50/p90/p99/max, trace clock units):")
        for phase, st in record["phase_latency"].items():
            print(f"  {phase:10s} n={st['count']:<6d} p50={st['p50']:<10g}"
                  f" p90={st['p90']:<10g} p99={st['p99']:<10g}"
                  f" max={st['max']:g}")
    if "critical_path" in record:
        cp = record["critical_path"]
        print(f"critical path over {cp['batches']} batches:")
        for phase, cnt in cp["dominant"].items():
            share = cp["phase_share"].get(phase, 0.0)
            print(f"  {phase:10s} dominated {cnt} batches "
                  f"(share of attributed time: {share:.1%})")
    if "overlap" in record:
        ov = record["overlap"]
        bpt = ov["readback_bytes_per_tick"]
        print(f"dispatch overlap over {ov['ticks']} ticks: "
              f"{ov['overlap_fraction']:.1%} of {ov['readbacks']} "
              f"readbacks overlapped a full tick of host work; "
              f"readback bytes/tick p50={bpt['p50']} max={bpt['max']} "
              f"(total {ov['readback_bytes_total']})")
        resident = "residency" in ov
        if resident:
            rs = ov["residency"]
            print(f"residency: {rs['enqueues']} enqueues over "
                  f"{rs['resident_ticks_total']} resident ticks, "
                  f"{rs['readbacks_deferred']} readbacks deferred")
        if "rebalances" in ov:
            rb = ov["rebalances"]
            print(f"rebalances: {rb['executed']} executed")
            for m in rb["marks"]:
                print(f"  t={m['ts']:.6f} {m['name']} {m['args']}")
        if args.overlap:
            cols = (f"  {'tick_ts':>14s} {'dispatches':>10s} "
                    f"{'votes':>7s} {'readbacks':>9s} {'overlapped':>10s} "
                    f"{'rb_bytes':>9s}")
            if resident:
                cols += (f" {'enqueues':>8s} {'res_ticks':>9s} "
                         f"{'deferred':>8s}")
            print(cols)
            for t in ov["per_tick"]:
                row = (f"  {t.get('ts', 0):>14.6f} {t['dispatches']:>10d} "
                       f"{t['votes']:>7d} {t['readbacks']:>9d} "
                       f"{t['overlapped']:>10d} {t['readback_bytes']:>9d}")
                if resident:
                    row += (f" {t.get('enqueues', 0):>8d} "
                            f"{t.get('resident_ticks', 0):>9d} "
                            f"{t.get('deferred', 0):>8d}")
                print(row)
        if "per_shard" in ov:
            ps = ov["per_shard"]
            print("per-shard (scale-out quorum fabric; a hot shard is "
                  "visible here alone):")
            print(f"  {'member_shard':>12s} {'readbacks':>9s} "
                  f"{'rb_bytes':>9s}")
            for s, (rb, b) in enumerate(zip(ps["readbacks"],
                                            ps["readback_bytes"])):
                print(f"  {s:>12d} {rb:>9d} {b:>9d}")
            print(f"  {'grid_cell':>12s} {'votes':>9s} {'share':>9s}")
            for c, (v, sh) in enumerate(zip(ps["votes"],
                                            ps["vote_share"])):
                print(f"  {c:>12d} {v:>9d} {sh:>9.2%}")
    if "rollups" in record:
        ru = record["rollups"]
        laws = ", ".join(f"{k}={v}" for k, v in
                         ru["anomalies_by_law"].items()) or "none"
        print(f"telemetry rollups over {ru['windows']} windows: "
              f"ordered total={ru['ordered_total']} "
              f"(min={ru['ordered_min']} max={ru['ordered_max']} "
              f"per window), anomalies={ru['anomaly_count']} ({laws})")
        if args.rollups:
            print(f"  {'window':>6s} {'ts':>14s} {'ordered':>8s} "
                  f"{'shed':>6s} {'retry':>6s} {'p99':>10s} "
                  f"{'hw_total':>9s} {'largest resource':<28s} anomalies")
            for r in ru["per_window"]:
                p99 = f"{r['p99']:.4f}" if r.get("p99") is not None \
                    else "-"
                top = (f"{r.get('hw_top') or '-'}"
                       f"={r.get('hw_top_entries', 0)}")
                marks = ",".join(r["anomalies"]) if r["anomalies"] else ""
                print(f"  {r.get('window', 0):>6d} "
                      f"{r.get('ts', 0):>14.3f} "
                      f"{r.get('ordered') or 0:>8d} "
                      f"{r.get('shed') or 0:>6d} "
                      f"{r.get('retry') or 0:>6d} {p99:>10s} "
                      f"{r.get('hw_total', 0):>9d} {top:<28s} {marks}")
            for a in ru["anomalies"]:
                detail = {k: v for k, v in a.items()
                          if k not in ("law", "ts", "window")}
                print(f"  anomaly t={a['ts']:.3f} w={a.get('window')} "
                      f"{a['law']} {detail}")
    if "journeys" in record:
        _print_journey_table(record)
    if record.get("flight_events"):
        print("flight events:")
        for ev in record["flight_events"]:
            print(f"  t={ev['ts']:.3f} {ev['name']} "
                  f"{ev.get('args') or ''}")
    if args.chrome:
        print(f"chrome trace: {args.chrome} "
              f"({record['chrome']['events']} events) — load in Perfetto")
    return 0


if __name__ == "__main__":
    sys.exit(main())
