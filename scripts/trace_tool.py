"""Consume consensus flight-recorder dumps (observability.trace JSONL).

Usage:
    python scripts/trace_tool.py TRACE.jsonl                 # full report
    python scripts/trace_tool.py TRACE.jsonl --phases        # percentiles
    python scripts/trace_tool.py TRACE.jsonl --critical-path
    python scripts/trace_tool.py TRACE.jsonl --chrome OUT.json
    python scripts/trace_tool.py TRACE.jsonl --json
    python scripts/trace_tool.py TRACE.jsonl --node node0

Dumps come from ``SimPool(trace=True)`` / ``NodePool(trace=True)``,
``chaos_run.py --trace`` (``<report>.trace.jsonl``), or
``profile_rbft.py --trace``. Three views:

- **--phases**: per-phase latency percentiles (p50/p90/p99/max) for the
  3PC lifecycle — prepare / commit / order / execute, plus the ingress
  auth phase. Simulation dumps measure VIRTUAL (protocol) time; deployed
  dumps measure perf_counter time.
- **--critical-path**: per ordered batch, which phase dominated its
  latency, plus each phase's share of total attributed time — the view
  that turns "a batch ordered in X ms" into "X went to the prepare wave".
- **--chrome**: Chrome trace-event JSON (one pid per node, one tid per
  category), loadable in Perfetto (https://ui.perfetto.dev) or
  chrome://tracing.

Deliberately free of jax imports: the tool must run anywhere a dump
lands, including hosts without the accelerator stack.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from indy_plenum_tpu.observability.trace import (  # noqa: E402
    critical_path,
    load_jsonl,
    overlap_report,
    phase_percentiles,
    to_chrome_trace,
)


def _counts(events) -> dict:
    by_cat, by_name = {}, {}
    for ev in events:
        by_cat[ev.get("cat", "")] = by_cat.get(ev.get("cat", ""), 0) + 1
        by_name[ev["name"]] = by_name.get(ev["name"], 0) + 1
    return {"events": len(events), "by_cat": by_cat, "by_name": by_name}


def _flight_events(events) -> list:
    return [ev for ev in events if ev.get("cat") == "flight"]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", help="trace JSONL file")
    ap.add_argument("--phases", action="store_true",
                    help="per-phase latency percentiles only")
    ap.add_argument("--critical-path", action="store_true",
                    help="per-batch dominant-phase breakdown only")
    ap.add_argument("--overlap", action="store_true",
                    help="per-tick host/device overlap fraction + "
                         "readback-bytes column (ordering fast path)")
    ap.add_argument("--chrome", metavar="OUT",
                    help="write Chrome trace-event JSON (Perfetto)")
    ap.add_argument("--node", default=None,
                    help="restrict phase views to one node's marks")
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable JSON line on stdout")
    args = ap.parse_args()

    events = load_jsonl(args.dump)
    if not events:
        print(f"{args.dump}: no events", file=sys.stderr)
        return 2

    record = {"dump": args.dump, "summary": _counts(events)}
    # --phases/--critical-path/--overlap narrow the view; --chrome is
    # orthogonal
    view_selected = args.phases or args.critical_path or args.overlap
    if args.phases or not view_selected:
        record["phase_latency"] = phase_percentiles(events, node=args.node)
    if args.critical_path or not view_selected:
        record["critical_path"] = critical_path(events, node=args.node)
    if args.overlap or not view_selected:
        record["overlap"] = overlap_report(events, node=args.node)
    if not view_selected:
        record["flight_events"] = _flight_events(events)
    if args.chrome:
        chrome = to_chrome_trace(events)
        with open(args.chrome, "w") as fh:
            json.dump(chrome, fh, separators=(",", ":"))
        record["chrome"] = {"file": args.chrome,
                            "events": len(chrome["traceEvents"])}

    if args.json:
        print(json.dumps(record, separators=(",", ":"), sort_keys=True))
        return 0

    summary = record["summary"]
    print(f"{args.dump}: {summary['events']} events "
          f"({', '.join(f'{c}={n}' for c, n in sorted(summary['by_cat'].items()))})")
    if "phase_latency" in record:
        print("phase latency (p50/p90/p99/max, trace clock units):")
        for phase, st in record["phase_latency"].items():
            print(f"  {phase:10s} n={st['count']:<6d} p50={st['p50']:<10g}"
                  f" p90={st['p90']:<10g} p99={st['p99']:<10g}"
                  f" max={st['max']:g}")
    if "critical_path" in record:
        cp = record["critical_path"]
        print(f"critical path over {cp['batches']} batches:")
        for phase, cnt in cp["dominant"].items():
            share = cp["phase_share"].get(phase, 0.0)
            print(f"  {phase:10s} dominated {cnt} batches "
                  f"(share of attributed time: {share:.1%})")
    if "overlap" in record:
        ov = record["overlap"]
        bpt = ov["readback_bytes_per_tick"]
        print(f"dispatch overlap over {ov['ticks']} ticks: "
              f"{ov['overlap_fraction']:.1%} of {ov['readbacks']} "
              f"readbacks overlapped a full tick of host work; "
              f"readback bytes/tick p50={bpt['p50']} max={bpt['max']} "
              f"(total {ov['readback_bytes_total']})")
        if args.overlap:
            print(f"  {'tick_ts':>14s} {'dispatches':>10s} {'votes':>7s} "
                  f"{'readbacks':>9s} {'overlapped':>10s} {'rb_bytes':>9s}")
            for t in ov["per_tick"]:
                print(f"  {t.get('ts', 0):>14.6f} {t['dispatches']:>10d} "
                      f"{t['votes']:>7d} {t['readbacks']:>9d} "
                      f"{t['overlapped']:>10d} {t['readback_bytes']:>9d}")
        if "per_shard" in ov:
            ps = ov["per_shard"]
            print("per-shard (scale-out quorum fabric; a hot shard is "
                  "visible here alone):")
            print(f"  {'member_shard':>12s} {'readbacks':>9s} "
                  f"{'rb_bytes':>9s}")
            for s, (rb, b) in enumerate(zip(ps["readbacks"],
                                            ps["readback_bytes"])):
                print(f"  {s:>12d} {rb:>9d} {b:>9d}")
            print(f"  {'grid_cell':>12s} {'votes':>9s} {'share':>9s}")
            for c, (v, sh) in enumerate(zip(ps["votes"],
                                            ps["vote_share"])):
                print(f"  {c:>12d} {v:>9d} {sh:>9.2%}")
    if record.get("flight_events"):
        print("flight events:")
        for ev in record["flight_events"]:
            print(f"  t={ev['ts']:.3f} {ev['name']} "
                  f"{ev.get('args') or ''}")
    if args.chrome:
        print(f"chrome trace: {args.chrome} "
              f"({record['chrome']['events']} events) — load in Perfetto")
    return 0


if __name__ == "__main__":
    sys.exit(main())
