"""Profile the full-RBFT sim loop on CPU: where do 22 instances spend it?

Usage: python scripts/profile_rbft.py [n_nodes] [instances] [txns]
                                      [--json] [--no-baseline]

``--json`` emits ONE machine-readable line on stdout (everything else
goes to stderr): the top-20 cumulative hotspots plus the dispatch-plane
amortization numbers — ``device_dispatches_per_ordered_batch`` for the
tick-batched run and, unless ``--no-baseline``, the same measured on a
short per-message run (``QuorumTickInterval=0``) with the resulting
``amortization_factor``. ``--mesh M`` shards the grouped vote plane over
M host devices (mesh-sharded dispatch plane) and ``--mesh MxV`` runs the
member x validator 2-axis quorum fabric; the record then carries
``shards``, ``mesh_shape`` and per-shard occupancy. ``--trace`` arms the consensus
flight recorder: the span trace dumps to ``--trace-out`` (JSONL for
``scripts/trace_tool.py``) and the ``--json`` record gains
``phase_latency`` percentiles + ``critical_path``. ``--real-execution``
profiles with real ledgers + SMT states; the record's ``state`` block
then carries the batched state-commit plane's hashes/commit, node-cache
hit rate and offload mode (``state: null`` otherwise). The determinism cross-check
(``ordered_digests`` identical between the two modes) lives in
``tests/test_dispatch_plane.py``; the budget gate in
``scripts/check_dispatch_budget.py``.
"""
import argparse
import cProfile
import json
import os
import pstats
import sys
import time

# repo root from this file's location, not a hardcoded absolute path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# a --mesh run needs the virtual host devices provisioned BEFORE jax
# initializes its backend. Provision ONLY then: the default unsharded
# profile's amortization baselines were measured on the unmodified
# topology and must keep measuring there.
if "--mesh" in sys.argv:
    from indy_plenum_tpu.utils.jax_env import (
        ensure_host_platform_devices,
        mesh_devices,
        parse_mesh_shape,
    )

    try:
        _raw = sys.argv[sys.argv.index("--mesh") + 1]
    except IndexError:
        _raw = "0"  # argparse rejects the missing value below
    # "0" is the explicit unsharded sentinel: provision NOTHING (the
    # amortization baselines must keep measuring on the unmodified
    # topology); a malformed value provisions nothing either — main()
    # rejects it with a proper parser error
    if _raw != "0":
        try:
            _width = mesh_devices(parse_mesh_shape(_raw))
        except ValueError:
            _width = 0
        if _width:
            ensure_host_platform_devices(_width)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# shared persistent XLA compile cache: without it every invocation
# re-pays minutes of XLA:CPU kernel compile, and the 240s run deadlines
# can expire mid-compile on a small host
from indy_plenum_tpu.utils.jax_env import (  # noqa: E402
    enable_persistent_compile_cache,
)

enable_persistent_compile_cache()

from indy_plenum_tpu.common.metrics_collector import MetricsName  # noqa: E402
from indy_plenum_tpu.config import getConfig  # noqa: E402
from indy_plenum_tpu.simulation.pool import SimPool  # noqa: E402

BATCH = 160


def _build_pool(n, k, tick_interval, adaptive=False, mesh=None,
                trace=False, ingress_capacity=0, real_execution=False,
                resident_depth=0):
    config = getConfig({
        "Max3PCBatchSize": BATCH,
        "Max3PCBatchWait": 0.05,
        "QuorumTickInterval": tick_interval,
        "QuorumTickAdaptive": adaptive,
        "IngressQueueCapacity": ingress_capacity,
        "ResidentTickDepth": max(resident_depth, 1),
    })
    # a bounded ingress queue only means something on the signed auth
    # path (the admission plane guards the device auth batch)
    return SimPool(n_nodes=n, seed=11, config=config, device_quorum=True,
                   shadow_check=False, num_instances=k, mesh=mesh,
                   trace=trace, sign_requests=ingress_capacity > 0,
                   real_execution=real_execution)


def _run(pool, txns, profile=False):
    """Warm up one batch, then order ``txns`` more; returns the measured
    segment's (ordered, wall_s, device_dispatches, profiler|None)."""
    seq = [0]

    def submit(count):
        for _ in range(count):
            seq[0] += 1
            pool.submit_request(seq[0])

    def min_ordered():
        return min(len(nd.ordered_digests) for nd in pool.nodes)

    def target_after_shed(base):
        # a bounded admission queue (--ingress-capacity) sheds overflow
        # deterministically: only what was ADMITTED can ever order
        adm = pool.admission
        return base - adm.shed_total if adm is not None else base

    # warm-up: compiles the vote-plane step shapes + fills jit caches
    deadline = time.monotonic() + 240
    submit(BATCH)
    while min_ordered() < target_after_shed(BATCH) \
            and time.monotonic() < deadline:
        pool.run_for(0.5)
    warm_got = min_ordered()
    assert warm_got >= target_after_shed(BATCH), "warm-up stalled"

    # sheds are counted at offer() time (only their trace/metric emission
    # waits for the drain): snapshot BEFORE the burst, or the burst's own
    # sheds vanish from the delta and the loop waits on txns that were
    # never admitted until the deadline
    shed0 = pool.admission.shed_total if pool.admission else 0
    submit(txns)
    target = warm_got + txns
    flushes0 = pool.vote_group.flushes
    deadline = time.monotonic() + 240  # fresh budget: warm-up (XLA
    # compile + flaky link) must not silently truncate the profiled run
    prof = cProfile.Profile() if profile else None
    t0 = time.perf_counter()
    if prof:
        prof.enable()
    while min_ordered() < target - (
            (pool.admission.shed_total - shed0) if pool.admission
            else 0) and time.monotonic() < deadline:
        pool.run_for(0.5)
    if prof:
        prof.disable()
    elapsed = time.perf_counter() - t0
    got = min_ordered() - warm_got
    dispatches = pool.vote_group.flushes - flushes0
    return got, elapsed, dispatches, prof


def _hotspots(prof, top=20):
    """Top ``top`` functions by cumulative time, machine-readable."""
    stats = pstats.Stats(prof)
    rows = []
    for (path, line, func), (cc, nc, tt, ct, _callers) in \
            sorted(stats.stats.items(), key=lambda kv: -kv[1][3])[:top]:
        rows.append({
            "func": f"{os.path.basename(path)}:{line}({func})",
            "ncalls": nc,
            "tottime_s": round(tt, 4),
            "cumtime_s": round(ct, 4),
        })
    return rows


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("n_nodes", nargs="?", type=int, default=16)
    ap.add_argument("instances", nargs="?", type=int, default=6)
    ap.add_argument("txns", nargs="?", type=int, default=320)
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable stdout line: top-20 "
                         "hotspots + dispatch amortization metrics")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the per-message baseline run in --json mode")
    ap.add_argument("--static-tick", action="store_true",
                    help="freeze the tick at 0.1 (skip the adaptive "
                         "governor the profiled loop now runs by default)")
    ap.add_argument("--mesh", default="0",
                    help="shard the grouped vote plane: M host devices "
                         "on the member axis (e.g. 8) or an MxV member "
                         "x validator 2-axis fabric (e.g. 4x2); 0 = "
                         "unsharded")
    ap.add_argument("--ingress-capacity", type=int, default=0,
                    help="bound the auth queue (admission control): the "
                         "profiled pool then runs the SIGNED ingress "
                         "path and the --json record's ingress block "
                         "carries queue depth + admitted/shed totals")
    ap.add_argument("--real-execution", action="store_true",
                    help="profile with real ledgers + SMT states (NYM "
                         "writes through the batched state-commit "
                         "plane): the --json record's state block "
                         "carries hashes/commit, node-cache hit rate "
                         "and offload mode")
    ap.add_argument("--resident-depth", type=int, default=0,
                    help="multi-tick device residency: accumulate votes "
                         "in device-side ring slots over this many ticks "
                         "before one fused step consumes them; the "
                         "--json record gains a residency block")
    ap.add_argument("--trace", action="store_true",
                    help="arm the consensus flight recorder: dumps the "
                         "span trace as JSONL (--trace-out) and the "
                         "--json record gains phase_latency percentiles "
                         "+ critical_path attribution")
    ap.add_argument("--trace-out", default="profile_rbft.trace.jsonl",
                    help="trace dump path for --trace (consume with "
                         "scripts/trace_tool.py)")
    args = ap.parse_args()
    n, k, txns = args.n_nodes, args.instances, args.txns

    mesh = None
    if args.mesh not in ("0", 0):
        from indy_plenum_tpu.tpu.quorum import make_fabric_mesh
        from indy_plenum_tpu.utils.jax_env import (
            mesh_devices,
            parse_mesh_shape,
        )

        try:
            shape = parse_mesh_shape(args.mesh)
        except ValueError as exc:
            ap.error(str(exc))
        devices = jax.devices()
        assert len(devices) >= mesh_devices(shape), (
            f"need {mesh_devices(shape)} devices, have {len(devices)}")
        mesh = make_fabric_mesh(devices, shape)

    pool = _build_pool(n, k, tick_interval=0.1,
                       adaptive=not args.static_tick, mesh=mesh,
                       trace=args.trace,
                       ingress_capacity=args.ingress_capacity,
                       real_execution=args.real_execution,
                       resident_depth=args.resident_depth)
    got, elapsed, dispatches, prof = _run(pool, txns, profile=True)
    print(f"n={n} k={k}: {got}/{txns} ordered in {elapsed:.2f}s "
          f"= {got / elapsed:.1f} txns/sec", file=sys.stderr)
    stats = pstats.Stats(prof, stream=sys.stderr)
    stats.sort_stats("cumulative").print_stats(35)
    stats.sort_stats("tottime").print_stats(35)

    trace_block = None
    if args.trace:
        from indy_plenum_tpu.observability.trace import (
            critical_path,
            phase_percentiles,
        )

        events = pool.trace.events()
        pool.trace.dump(args.trace_out)
        trace_block = {
            "trace_file": args.trace_out,
            "trace_hash": pool.trace.trace_hash(),
            "trace_events": len(events),
            # virtual-time attribution: where the protocol pipeline
            # spends its latency, per phase (trace_tool.py renders the
            # same numbers from the dump)
            "phase_latency": phase_percentiles(events),
            "critical_path": critical_path(events),
        }
        print(f"trace: {args.trace_out} "
              f"({trace_block['trace_events']} events, "
              f"hash {trace_block['trace_hash'][:16]}…)", file=sys.stderr)

    if not args.json:
        return

    # fractional batches: a truncated or non-multiple-of-BATCH run must
    # not skew dispatches-per-batch by up to 2x through floor division
    batches = max(got / BATCH, 1e-9)
    per_batch = dispatches / batches
    occ = pool.metrics.stat(MetricsName.DEVICE_FLUSH_OCCUPANCY)
    # adaptive-tick surface: where the governor left the interval and how
    # long the run dwelt on each rung (static runs report the fixed tick
    # and no histogram)
    tick_stat = pool.metrics.stat(MetricsName.GOVERNOR_TICK_INTERVAL)
    record = {
        "n_nodes": n,
        "instances": k,
        "txns_ordered": got,
        "wall_s": round(elapsed, 2),
        "txns_per_sec": round(got / elapsed, 1) if elapsed else 0.0,
        "device_dispatches": dispatches,
        "ordered_batches": round(batches, 2),
        "device_dispatches_per_ordered_batch": round(per_batch, 2),
        "flush_occupancy_avg": round(occ.avg, 4) if occ else None,
        # mesh-sharded dispatch plane: mesh width + each shard's
        # cumulative occupancy (scattered votes / real-row capacity);
        # mesh_shape distinguishes (M,) member sharding from the (M, V)
        # 2-axis quorum fabric behind the same flat shards count
        "shards": pool.vote_group.shards,
        "mesh_shape": list(pool.vote_group.mesh_shape),
        "shard_occupancy": pool.vote_group.shard_occupancy,
        "effective_tick_interval": (tick_stat.last if tick_stat
                                    else pool.config.QuorumTickInterval),
        "tick_interval_histogram": pool.metrics.histogram(
            MetricsName.GOVERNOR_TICK_INTERVAL),
        "governor": (pool.governor.trajectory_summary()
                     if pool.governor is not None else None),
        # multi-tick residency: how much host round-tripping the ring
        # actually saved (None when the run was per-tick)
        "residency": ({
            "resident_depth": pool.vote_group.resident_depth,
            "resident_ticks": pool.vote_group.resident_ticks,
            "readbacks_deferred": pool.vote_group.readbacks_deferred,
        } if pool.vote_group.resident_depth > 1 else None),
        "hotspots_top20_cumulative": _hotspots(prof),
    }
    # ingress plane: the admission queue's depth/admitted/shed and the
    # read path's qps gauge, from the same pool collector every other
    # surface reads (None when the run had no admission and no reads)
    ingress = None
    if pool.admission is not None:
        ingress = pool.admission.counters()
        ingress["shed_hash"] = pool.admission.shed_hash()
    read_qps = pool.metrics.stat(MetricsName.READ_QPS)
    if read_qps is not None:
        ingress = ingress or {}
        ingress["read_qps"] = round(read_qps.last, 1)
    record["ingress"] = ingress
    # state-commit plane: the batched one-walk commit's cost surface,
    # from node0's domain state (every honest node commits the same
    # roots, so one node's meters are THE meters) — None when the run
    # executed nothing real (no ledgers, no states)
    state_block = None
    node0 = pool.nodes[0]
    if getattr(node0, "boot", None) is not None:
        from indy_plenum_tpu.common.constants import DOMAIN_LEDGER_ID

        st = node0.boot.db.get_state(DOMAIN_LEDGER_ID)
        hashes_stat = pool.metrics.stat(MetricsName.STATE_COMMIT_HASHES)
        batch_stat = pool.metrics.stat(MetricsName.STATE_COMMIT_BATCH_SIZE)
        state_block = {
            "hashes_total": st.hashes_total,
            "hashes_per_commit": (round(hashes_stat.avg, 1)
                                  if hashes_stat else None),
            "commits": hashes_stat.count if hashes_stat else 0,
            "writes_per_commit": (round(batch_stat.avg, 1)
                                  if batch_stat else None),
            "node_cache_hit_rate": round(st.cache_hit_rate(), 4),
            "offload_mode": st.commit_mode,
            "wave_host_hashes": st.wave_host_hashes,
            "wave_device_hashes": st.wave_device_hashes,
            "batches_applied": st.batches_applied,
        }
    record["state"] = state_block
    if trace_block is not None:
        record.update(trace_block)
    if not args.no_baseline:
        # per-message baseline: same pool shape, QuorumTickInterval=0 —
        # every quorum query flushes. One post-warm-up batch is enough;
        # dispatches-per-ordered-batch is ~workload-independent.
        base_pool = _build_pool(n, k, tick_interval=0.0)
        bgot, belapsed, bdispatches, _ = _run(base_pool, BATCH)
        base_per_batch = bdispatches / max(bgot / BATCH, 1e-9)
        record.update({
            "baseline_mode": "per_message",
            "baseline_txns_ordered": bgot,
            "baseline_device_dispatches_per_ordered_batch":
                round(base_per_batch, 2),
            "amortization_factor":
                round(base_per_batch / per_batch, 2) if per_batch else None,
        })
    print(json.dumps(record, separators=(",", ":")))


if __name__ == "__main__":
    main()
