"""Profile the full-RBFT sim loop on CPU: where do 22 instances spend it?

Usage: python scripts/profile_rbft.py [n_nodes] [instances] [txns]
"""
import cProfile
import pstats
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, "/root/repo")

from indy_plenum_tpu.config import getConfig  # noqa: E402
from indy_plenum_tpu.simulation.pool import SimPool  # noqa: E402


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 6
    txns = int(sys.argv[3]) if len(sys.argv) > 3 else 320
    batch = 160
    config = getConfig({
        "Max3PCBatchSize": batch,
        "Max3PCBatchWait": 0.05,
        "QuorumTickInterval": 0.1,
    })
    pool = SimPool(n_nodes=n, seed=11, config=config, device_quorum=True,
                   shadow_check=False, num_instances=k)
    seq = 0

    def submit(count):
        nonlocal seq
        for _ in range(count):
            seq += 1
            pool.submit_request(seq)

    def min_ordered():
        return min(len(nd.ordered_digests) for nd in pool.nodes)

    # warm-up
    deadline = time.monotonic() + 240
    submit(batch)
    while min_ordered() < batch and time.monotonic() < deadline:
        pool.run_for(0.5)
    assert min_ordered() >= batch, "warm-up stalled"

    submit(txns)
    target = batch + txns
    deadline = time.monotonic() + 240  # fresh budget: warm-up (XLA
    # compile + flaky link) must not silently truncate the profiled run
    prof = cProfile.Profile()
    t0 = time.perf_counter()
    prof.enable()
    while min_ordered() < target and time.monotonic() < deadline:
        pool.run_for(0.5)
    prof.disable()
    elapsed = time.perf_counter() - t0
    got = min_ordered() - batch
    print(f"n={n} k={k}: {got}/{txns} ordered in {elapsed:.2f}s "
          f"= {got / elapsed:.1f} txns/sec", file=sys.stderr)
    stats = pstats.Stats(prof, stream=sys.stderr)
    stats.sort_stats("cumulative").print_stats(35)
    stats.sort_stats("tottime").print_stats(35)


if __name__ == "__main__":
    main()
