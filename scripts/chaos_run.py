"""Run a chaos scenario against the simulated RBFT pool.

Usage:
    python scripts/chaos_run.py --seed 7 --scenario f_crash_partition
    python scripts/chaos_run.py --list
    python scripts/chaos_run.py --seed 3 --scenario storm --out storm.json

Every run is fully determined by (scenario, seed, nodes): the emitted
JSON report contains the fault plan, the virtual-time event trace,
delivery accounting and all invariant verdicts, plus the exact command
that replays it. Exit status: 0 when the verdicts match the scenario's
design (all PASS for normal scenarios; the designed failures for
checker-vacuity scenarios like broken_agreement), 2 otherwise.
"""
import argparse
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from indy_plenum_tpu.chaos import SCENARIOS, run_scenario  # noqa: E402


def main() -> int:
    parser = argparse.ArgumentParser(
        description="deterministic fault injection for the RBFT sim pool")
    parser.add_argument("--seed", type=int, default=7,
                        help="plan + pool seed (the replay key)")
    parser.add_argument("--scenario", default="f_crash_partition",
                        choices=sorted(SCENARIOS),
                        help="named fault scenario")
    parser.add_argument("--nodes", type=int, default=0,
                        help="pool size (0 = scenario default)")
    parser.add_argument("--out", default=None,
                        help="report path (default: "
                             "chaos_<scenario>_<seed>.json)")
    parser.add_argument("--list", action="store_true",
                        help="list scenarios and exit")
    parser.add_argument("--device-quorum", action="store_true",
                        help="decide quorums on the device vote plane")
    parser.add_argument("--tick", type=float, default=0.0,
                        help="QuorumTickInterval: > 0 routes the scenario "
                             "through the tick-batched dispatch plane "
                             "(requires --device-quorum)")
    parser.add_argument("--adaptive-tick", action="store_true",
                        help="hand the tick to the dispatch governor "
                             "(requires --tick; the report's "
                             "governor.tick_interval metrics record the "
                             "deterministic interval trajectory)")
    parser.add_argument("--mesh", default="0",
                        help="shard the grouped vote plane: M devices on "
                             "the member axis (e.g. 4) or an MxV member "
                             "x validator 2-axis fabric (e.g. 2x2); "
                             "requires --device-quorum; on CPU the host "
                             "platform self-provisions virtual devices")
    parser.add_argument("--lanes", type=int, default=0,
                        help="override the scenario's ordering-lane "
                             "count (> 1 runs the laned path: faults "
                             "inside lane 0, cross_lane invariant "
                             "probed; 0 keeps the scenario's own value)")
    parser.add_argument("--resident-depth", type=int, default=0,
                        help="multi-tick device residency: votes "
                             "accumulate in device-side ring slots over "
                             "this many ticks before one fused step "
                             "consumes them (requires --device-quorum "
                             "and --tick; ordered output is bit-"
                             "identical to the per-tick run)")
    parser.add_argument("--trace", action="store_true",
                        help="arm the consensus flight recorder: the "
                             "report gains trace_hash + flight_recorder "
                             "tail dumps and the full span trace lands "
                             "next to the report as <out>.trace.jsonl "
                             "(consume with scripts/trace_tool.py); "
                             "deterministic — replaying the same seed "
                             "reproduces the dump bit-for-bit")
    args = parser.parse_args()
    if args.tick > 0 and not args.device_quorum:
        parser.error("--tick requires --device-quorum")
    if args.adaptive_tick and args.tick <= 0:
        parser.error("--adaptive-tick requires --tick")
    if args.resident_depth > 1 and args.tick <= 0:
        parser.error("--resident-depth requires --tick")
    mesh_shape = None
    if args.mesh not in ("0", 0):
        from indy_plenum_tpu.utils.jax_env import parse_mesh_shape

        try:
            mesh_shape = parse_mesh_shape(args.mesh)
        except ValueError as exc:
            parser.error(str(exc))
        if not args.device_quorum:
            parser.error("--mesh requires --device-quorum")

    mesh = None
    if mesh_shape is not None:
        # XLA fixes the device topology at backend init; the flag must
        # land before the first device query
        from indy_plenum_tpu.utils.jax_env import (
            ensure_host_platform_devices,
            mesh_devices,
        )

        n_dev = mesh_devices(mesh_shape)
        ensure_host_platform_devices(n_dev)
        from indy_plenum_tpu.tpu.quorum import make_fabric_mesh

        devices = jax.devices()
        if len(devices) < n_dev:
            parser.error(f"need {n_dev} devices, have {len(devices)} "
                         "(XLA_FLAGS was set too late or preset smaller)")
        mesh = make_fabric_mesh(devices, mesh_shape)

    if args.list:
        for name in sorted(SCENARIOS):
            sc = SCENARIOS[name]
            tags = []
            if sc.expect_fail:
                tags.append("expects FAIL: " + ", ".join(sc.expect_fail))
            if sc.lanes > 1:
                tags.append(f"laned x{sc.lanes}; asserts cross_lane")
            if sc.real_execution:
                extra = [flag for flag, on in (
                    ("catchup", sc.require_catchup),
                    ("byz-seeder-rejection", sc.require_rejection),
                    ("retry-law", sc.require_retries),
                    ("proof-read", sc.proof_read)) if on]
                tags.append("real-exec" + ("+bls" if sc.bls else "")
                            + ("; asserts " + ", ".join(extra)
                               if extra else ""))
            tag = "".join(f" [{t}]" for t in tags)
            print(f"{name:24s} {sc.description}{tag}")
        return 0

    out = args.out or f"chaos_{args.scenario}_{args.seed}.json"
    scenario = args.scenario
    if args.lanes:
        import dataclasses

        from indy_plenum_tpu.chaos.scenarios import get_scenario

        scenario = dataclasses.replace(get_scenario(args.scenario),
                                       lanes=args.lanes)
    report = run_scenario(scenario, seed=args.seed,
                          n_nodes=args.nodes, out_path=out,
                          device_quorum=args.device_quorum,
                          quorum_tick_interval=args.tick,
                          quorum_tick_adaptive=args.adaptive_tick,
                          mesh=mesh,
                          trace=args.trace,
                          trace_out=(out + ".trace.jsonl"
                                     if args.trace else None),
                          resident_depth=args.resident_depth)
    for line in report.summary_lines():
        print(line)
    print(f"  report: {out}")
    if report.verdict_as_expected:
        if report.expected_failures:
            print("OK (failed exactly as designed — checker not vacuous)")
        else:
            print("OK (all invariants PASS)")
        return 0
    print(f"UNEXPECTED VERDICT: failed={report.failed} "
          f"expected={report.expected_failures}")
    return 2


if __name__ == "__main__":
    sys.exit(main())
