"""Determinism & hot-path hygiene linter — the analyzer's CLI.

Pure-AST: never imports jax, so it runs in milliseconds anywhere (CI,
pre-commit, the budget script's ``static_gate``). Exit 1 when any
UNSUPPRESSED finding remains — the shipped baseline is empty, so new
findings fail closed; sanctioned sites carry inline
``# da: allow[rule] -- reason`` pragmas (reason required).

Usage:
    python scripts/lint_determinism.py indy_plenum_tpu
    python scripts/lint_determinism.py indy_plenum_tpu --json
    python scripts/lint_determinism.py indy_plenum_tpu --show-suppressed
    python scripts/lint_determinism.py --list-rules
    python scripts/lint_determinism.py indy_plenum_tpu --emit-knobs
    python scripts/lint_determinism.py indy_plenum_tpu \
        --write-baseline /tmp/baseline.json   # staged burn-downs only
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from indy_plenum_tpu.analysis import (  # noqa: E402
    DEFAULT_BASELINE,
    Analyzer,
    load_baseline,
    make_rules,
    write_baseline,
)
from indy_plenum_tpu.analysis.rules_config import ConfigKnobRule  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", default=["indy_plenum_tpu"],
                    help="files or package directories to analyze "
                         "(default: indy_plenum_tpu)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON object")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print pragma/baseline-suppressed findings")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of known findings (default: the "
                         "shipped — empty — baseline)")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="write the current unsuppressed findings as a "
                         "baseline to PATH and exit 0 (staged "
                         "burn-downs; the SHIPPED baseline stays empty)")
    ap.add_argument("--rule", default=None, metavar="NAME[,NAME]",
                    help="run only the named rule(s)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--emit-knobs", action="store_true",
                    help="render the config-knob registry (from the "
                         "config-knob rule's read map) as a markdown "
                         "table and exit")
    args = ap.parse_args()

    rules = make_rules()
    # the pragma self-lint must know the FULL catalog even when --rule
    # narrows the run, or pragmas naming unfiltered rules would
    # false-positive as 'unknown rule'
    catalog = {r.name for r in rules}
    if args.list_rules:
        width = max(len(r.name) for r in rules)
        for r in rules:
            print(f"{r.name:{width}s}  {r.summary}")
        print(f"{'pragma':{width}s}  reasonless or unknown-rule "
              "'# da: allow[...]' pragmas (the suppression layer "
              "self-lints)")
        return 0
    if args.rule:
        chosen = {r.strip() for r in args.rule.split(",") if r.strip()}
        unknown = chosen - {r.name for r in rules}
        if unknown:
            raise SystemExit(f"unknown rule(s): {sorted(unknown)} "
                             "(see --list-rules)")
        rules = [r for r in rules if r.name in chosen]

    analyzer = Analyzer(rules, known_rules=catalog)
    try:
        report = analyzer.analyze_paths(
            args.paths, baseline_keys=load_baseline(args.baseline))
    except FileNotFoundError as err:
        raise SystemExit(f"error: {err}")  # fail CLOSED on a bad path
    if report.files_analyzed == 0:
        raise SystemExit(
            f"error: no .py files under {args.paths} — refusing to "
            "report a clean run over nothing")

    if args.emit_knobs:
        knob_rule = next((r for r in rules
                          if isinstance(r, ConfigKnobRule)), None)
        if knob_rule is None or not knob_rule.knob_defs:
            raise SystemExit("--emit-knobs needs the config-knob rule "
                             "and config.py inside the analyzed paths")
        print(knob_rule.render_registry())
        return 0

    if args.write_baseline:
        write_baseline(args.write_baseline,
                       [f.baseline_key() for f in report.unsuppressed])
        print(f"wrote {len(report.unsuppressed)} baseline entries to "
              f"{args.write_baseline}")
        return 0

    if args.json:
        print(json.dumps(report.to_dict(), sort_keys=True,
                         separators=(",", ":")))
        return 1 if report.unsuppressed else 0

    for f in report.findings:
        if f.suppressed and not args.show_suppressed:
            continue
        print(f.render())
        if f.suppressed == "pragma" and args.show_suppressed and f.reason:
            print(f"    reason: {f.reason}")
    print(f"files: {report.files_analyzed}  findings: "
          f"{len(report.findings)} ({len(report.unsuppressed)} "
          f"unsuppressed, {len(report.suppressed)} suppressed)")
    print(f"findings_hash: {report.findings_hash}")
    return 1 if report.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
