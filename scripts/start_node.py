#!/usr/bin/env python3
"""Run one validator from a provisioned pool directory.

Usage: python scripts/start_node.py DIR NODE_NAME
(reference analog: scripts/start_plenum_node). Runs the Looper forever;
^C to stop. One process per validator; peers may live on other hosts as
long as pool_info.json carries their reachable addresses.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from indy_plenum_tpu.common.looper import Looper  # noqa: E402
from indy_plenum_tpu.tools import build_node  # noqa: E402


def main() -> int:
    if len(sys.argv) != 3:
        print(__doc__)
        return 2
    directory, name = sys.argv[1], sys.argv[2]
    from indy_plenum_tpu.common.log import setup_logging
    from indy_plenum_tpu.config import getConfig

    config = getConfig()
    setup_logging(
        level=config.logLevel,
        log_file=os.path.join(directory, "logs", f"{name}.log"),
        max_bytes=config.logRotationMaxBytes,
        backup_count=config.logRotationBackupCount,
        when=config.logRotationWhen,
        interval=config.logRotationInterval)
    looper = Looper()
    node, stack = build_node(directory, name, looper)
    # compile the device-hash auth shapes BEFORE joining consensus: the
    # first full ingress batch must not stall the protocol thread on a
    # synchronous XLA compile
    from indy_plenum_tpu.server.client_authn import warm_device_auth_path

    warm_device_auth_path()
    node.start()
    # operator flight dump: `kill -USR2 <pid>` snapshots the trace ring
    # (flight.signal mark) and writes <logs>/<name>.flight.jsonl without
    # stopping the node — only the process entry point installs handlers
    node.install_signal_handlers(
        dump_dir=os.path.join(directory, "logs"))
    looper.add(stack)
    looper.add(node.client_surface)
    print(f"{name} listening on {stack.ha[0]}:{stack.ha[1]} "
          f"(clients: {node.client_surface.stack.ha[1]}) — ^C to stop")
    try:
        while True:
            looper.run_for(3600)
    except KeyboardInterrupt:
        pass
    finally:
        node.stop()
        looper.shutdown()
        stack.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
