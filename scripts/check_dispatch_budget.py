"""Dispatch-budget gate: fail if the tick barrier stops amortizing.

Runs a short deterministic ``SimPool`` round through the tick-batched
dispatch plane and computes ``device_dispatches_per_ordered_batch`` (and
dispatches per delivered message). Exit status 1 if either exceeds its
budget — callable from the bench loop, chaos runs, or CI, so a regression
that quietly reverts to per-message flushing turns red instead of slow.

Governor gates (PR 3): unless ``--no-governor-gates``, the script ALSO
runs a bursty profile (burst → trickle → burst — the load shape the
adaptive tick exists for) twice, static vs adaptive, and fails if

- the adaptive run's steady-state ``device.flush_occupancy`` falls below
  ``--occupancy-floor`` (the governor must keep scatters usefully full),
- the adaptive run regresses ``device_dispatches_per_ordered_batch``
  beyond ``--adaptive-tolerance`` of the static-tick run, or
- the adaptive run orders fewer txns per *sim* second than the static
  run allows after ``--adaptive-tolerance`` slack (the governor must not
  trade dispatches for protocol-time throughput).

Sharded gate (PR 4): unless ``--no-sharded-gate``, the script runs the
n=16/k=6 workload twice on the SAME seed — once on one device, once with
the grouped vote plane mesh-sharded over ``--mesh-devices`` host devices
— and fails if the ordered digests diverge (sharding is a placement
choice, never a semantics change), if the mesh run's
``device_dispatches_per_ordered_batch`` drifts beyond
``--sharded-tolerance`` of the 1-device run, or if its flush occupancy
falls below the floor.

Tracing gate (PR 5): unless ``--no-trace-gate``, the script runs the
n=16/k=6 workload twice on the SAME seed — flight recorder disabled vs
enabled — and fails if the ordered digests diverge (observability must
never perturb consensus) or the traced run's ordered/sim-second falls
more than ``--trace-tolerance`` below the untraced run. The wall-clock
ratio is recorded alongside, so the recorder can never silently tax the
hot path.

Ingress gate (PR 6): unless ``--no-ingress-gate``, the script drives the
seeded open-loop workload generator at n=16/k=6 through the SIGNED auth
path twice — unsaturated vs well beyond the bounded admission queue's
drain rate — and fails if overload grows the queue past capacity, if the
shed set / ordering are not byte-identical across two identical
saturated runs, if the unsaturated baseline sheds at all, or if
ordered/sim-second under saturation collapses more than
``--ingress-tolerance`` below the unsaturated run (admission exists to
protect goodput, not to trade it away).

Proof gate (PR 10): unless ``--no-proof-gate``, the script runs the same
seeded real-execution BLS pool twice — once idle, once serving
proof-attached reads through the state-proof plane — and fails if the
ordered digests diverge (reads never perturb consensus), if serving
cache-hit reads performed ANY pairing work (the serve path must be a
dict lookup), if any reply fails client-side end-to-end verification
(``verify_proved_read`` with only the pool's BLS keys), or if the
batched multi-sig verifier falls below 2x the per-root path at batch 64
(the whole point of batching pairings across roots/windows).

Catchup gate (PR 11): unless ``--no-catchup-gate``, the script runs the
seeded GC-crossing crash/restart chaos scenario (a node crashes, >= 2
checkpoint windows stabilize and garbage-collect in its absence, it
restarts and leeches the gap back) and fails if any chaos verdict fails,
if the caught-up node's committed-ledger hash is not bit-identical to
the survivors', if the run does not replay byte-identically
(``trace_hash``) from its seed, if the freshly-caught-up node's
proof-attached read fails ``verify_proved_read``, or if the
byzantine-seeder scenario's corrupted CATCHUP_REPs were not rejected by
proof verification. Catchup throughput is recorded in the gate output.

Fabric gate (PR 9): unless ``--no-fabric-gate``, the script runs the
n=16/k=6 workload on the 2-axis member x validator fabric (half the
sharded gate's devices on each axis) and compares it against the 1-axis
mesh run on the SAME seed — ``ordered_hash`` must match bit-for-bit and
dispatches/ordered-batch + bytes/readback must sit within
``--fabric-tolerance`` (the psum quorum reduction and per-shard
pipelined readbacks may move work between chips, never change or
inflate it).

Latency gate (PR 12): unless ``--no-latency-gate``, the script runs the
n=16/k=6 workload traced TWICE on the SAME seed and fails if the causal
journey tables (observability.causal) are not byte-identical
(``journey_hash``), if any ordered request's journey is incomplete
(orphan spans — every ingress must join a finalisation, batch, ordering
and execution across the pool), if the traced ordered digests diverge
from the untraced run, or if e2e p99 (client ingress -> executed,
virtual protocol time) exceeds ``--e2e-budget``.

Lanes gate (PR 14): unless ``--no-lanes-gate``, the script runs the
same routed workload through 1 and 4 ordering lanes (n=4 per lane,
tiny checkpoint windows so the cross-lane barrier seals continuously)
and fails unless the 4-lane arm's ordered/sim-second clears the
``--lanes-speedup-floor`` (3.0x), a 4-lane replay is byte-identical
(per-lane ordered hashes, the sealed-window fingerprint chain tip, the
journey table), no journey is orphaned, and every journey names its
lane and carries the barrier hop. The latency gate additionally
asserts per-lane e2e p99 at 4 lanes.

Static gate (PR 13): unless ``--no-static-gate``, the pure-AST
determinism & hot-path analyzer (``indy_plenum_tpu.analysis``) runs
over the whole package TWICE and fails if any unsuppressed finding
remains (the shipped baseline is empty — new wall-clock reads, unseeded
RNGs, unordered fingerprint iterations, unguarded hot-path trace args,
stray device syncs, aliasing ``jnp.asarray`` staging hand-offs or
orphan/unknown config knobs fail closed) or if ``findings_hash`` drifts
between the two runs (the analyzer obeys the same byte-identical replay
contract it enforces).

State gate (PR 17): unless ``--no-state-gate``, the batched state-commit
plane proves itself at state scale — identical per-window write sets
driven through sequential ``set()``, batched-host and batched-auto arms
on a 100k-key SMT produce bit-identical per-window roots, the batched
walk performs <= 1/3 the hashes per commit of the sequential loop at
delta=256 (``--state-hash-floor``), and the virtual-time soak arm (a
diurnal workload profile on a real-execution pool across a simulated
multi-hour horizon) holds a flat bounded-structure memory high-water,
<5% ordered-throughput drift first-vs-last simulated hour
(``--state-drift-tolerance``), byte-identical across two same-seed runs.

Geo gate (PR 18): unless ``--no-geo-gate``, the planet-scale read
fabric proves itself on a 3-region pool — the edge arm serves
>= ``--geo-hit-floor`` (default 90%) of a region-spread read storm
from region-local edge proof caches at intra-band p99 while the
same-seed no-edge arm pays the WAN band for non-home regions, the edge
serve path performs ZERO pairing checks (clients amortize one full
multi-sig verify per trusted window and bind every later reply to it
offline), every reply in both arms passes client verification,
ordered/journey/shed fingerprints stay bit-identical between arms (the
fabric models latency on a dedicated seeded RNG — it never touches the
pool's RNG or timer), and two same-seed edge runs produce
byte-identical records.

Residency gate (PR 19): unless ``--no-residency-gate``, the script runs
the n=16/k=6 workload per-tick vs with multi-tick device residency
(``--residency-depth`` ring slots, votes accumulating on device across
ticks before one fused consume) and fails if the ordered digests
diverge, if the resident arm spends more than
``--residency-dispatch-budget`` (1.0) device dispatches per ordered
batch or never defers a readback, or if ordered/sim-second regresses
beyond ``--residency-tolerance``. It also proves the occupancy-driven
rebalance law: a synthetic 8:1 hot member block over threshold 2.0
must plan a rotation whose predicted hottest block drops below the
threshold, and a forced mid-run plane migration on the 4-way member
mesh (executed at a checkpoint-boundary barrier) must keep the ordered
digests bit-identical to the never-rebalanced arm.

Soak gate (PR 20): unless ``--no-soak-gate``, the script runs the
virtual-day soak (simulation/soak.py) — 24 simulated diurnal hours on a
real-execution pool with ONE chaos arc (a GC-crossing crash + catchup
at hour 6, a view change at hour 12, and a forced shard rebalance on
hosts with >= 4 XLA devices) — twice on one seed, and fails if resource
high-water is not flat after hour 1, hour-1 vs hour-24 ordered
throughput drifts >= ``--soak-drift-tolerance`` (1%), any telemetry
anomaly is unexplained by the chaos windows, any declared bound is
violated, the runs are not byte-identical, or a short arm with a
planted leaking resource does NOT trip the leak law (non-vacuity).

Running one gate: ``--only latency`` (or ``--only trace,latency``)
replaces stacking nine ``--no-*-gate`` flags; ``--list-gates`` prints
the names.

Usage:
    python scripts/check_dispatch_budget.py                # defaults
    python scripts/check_dispatch_budget.py --only latency
    python scripts/check_dispatch_budget.py --nodes 16 --instances 6 \
        --budget-per-batch 40 --json
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the sharded/fabric gates need a multi-device host platform, and XLA
# fixes the device topology at backend init — so the flag must be in the
# environment before jax initializes. Provision ONLY when one of those
# gates will actually run: the 1-device budgets and governor gates are
# calibrated on the unmodified topology and must keep measuring there.
if ("--no-sharded-gate" not in sys.argv
        or "--no-fabric-gate" not in sys.argv
        or "--no-residency-gate" not in sys.argv
        or "--no-soak-gate" not in sys.argv):
    from indy_plenum_tpu.utils.jax_env import ensure_host_platform_devices

    _width = 4
    if "--mesh-devices" in sys.argv:
        try:
            _width = int(sys.argv[sys.argv.index("--mesh-devices") + 1])
        except (IndexError, ValueError):
            pass  # argparse will reject the malformed value below
    ensure_host_platform_devices(_width)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from indy_plenum_tpu.common.metrics_collector import MetricsName  # noqa: E402
from indy_plenum_tpu.config import getConfig  # noqa: E402
from indy_plenum_tpu.simulation.pool import SimPool  # noqa: E402


def _submit_bursty(pool, target: int) -> None:
    """Burst → trickle → burst: a third of the load lands at t=0, a third
    trickles one request per 0.25 sim-seconds (sparse ticks — the regime
    the governor widens for), and the rest bursts after the trickle
    (saturation — the regime it narrows for). Deterministic: everything
    rides the pool's virtual timer."""
    seq = [0]

    def submit(count: int) -> None:
        for _ in range(count):
            pool.submit_request(seq[0])
            seq[0] += 1

    burst = max(1, target // 3)
    trickle = max(0, target - 2 * burst)
    submit(burst)
    for i in range(trickle):
        pool.timer.schedule(2.0 + i * 0.25, lambda: submit(1))
    pool.timer.schedule(2.0 + trickle * 0.25 + 2.0,
                        lambda: submit(target - burst - trickle))


def measure(n_nodes: int, instances: int, batches: int, batch_size: int,
            tick_interval: float, seed: int = 11, adaptive: bool = False,
            bursty: bool = False, mesh=None, trace: bool = False,
            host_eval: bool = False, resident_depth: int = 0,
            overrides: "dict | None" = None) -> dict:
    """DELIBERATELY a cold run, unlike profile_rbft's warm-up-excluded
    measurement: the gate counts every dispatch from pool construction on
    (cold-start/compile steps included), because the budget protects the
    whole loop's dispatch discipline, not the steady-state ratio. Budgets
    are calibrated with ~10x headroom over the cold numbers.
    ``resident_depth`` > 1 arms multi-tick device residency;
    ``overrides`` layers extra config knobs (the residency gate forces a
    rebalance with it) on top of the gate's shape."""
    knobs = {
        "Max3PCBatchSize": batch_size,
        "Max3PCBatchWait": 0.05,
        "QuorumTickInterval": tick_interval,
        "QuorumTickAdaptive": adaptive,
    }
    if resident_depth > 1:
        knobs["ResidentTickDepth"] = resident_depth
    if overrides:
        knobs.update(overrides)
    config = getConfig(knobs)
    pool = SimPool(n_nodes=n_nodes, seed=seed, config=config,
                   device_quorum=True, shadow_check=False,
                   num_instances=instances, mesh=mesh, trace=trace,
                   host_eval=host_eval)

    def min_ordered():
        return min(len(nd.ordered_digests) for nd in pool.nodes)

    target = batches * batch_size
    sim_t0 = pool.timer.get_current_time()
    wall_t0 = time.perf_counter()
    if bursty:
        _submit_bursty(pool, target)
    else:
        for i in range(target):
            pool.submit_request(i)
    deadline = time.monotonic() + 240
    while min_ordered() < target and time.monotonic() < deadline:
        pool.run_for(0.5)
    assert min_ordered() >= target, f"stalled at {min_ordered()}/{target}"
    assert pool.honest_nodes_agree()
    sim_elapsed = pool.timer.get_current_time() - sim_t0
    wall_elapsed = time.perf_counter() - wall_t0

    dispatches = pool.vote_group.flushes
    delivered = pool.network.sent
    occ = pool.metrics.stat(MetricsName.DEVICE_FLUSH_OCCUPANCY)
    per_tick = pool.metrics.stat(MetricsName.DEVICE_DISPATCHES_PER_TICK)
    result = {
        "n_nodes": n_nodes,
        "instances": instances,
        "adaptive": adaptive,
        "bursty": bursty,
        "txns_ordered": min_ordered(),
        "ordered_batches": batches,
        "device_dispatches": dispatches,
        "delivered_messages": delivered,
        "device_dispatches_per_ordered_batch": round(
            dispatches / batches, 2),
        "device_dispatches_per_delivered_message": round(
            dispatches / delivered, 4) if delivered else 0.0,
        "flush_occupancy_avg": round(occ.avg, 4) if occ else None,
        "dispatches_per_tick_max": per_tick.max if per_tick else None,
        "ordered_per_sim_second": round(target / sim_elapsed, 2)
        if sim_elapsed else None,
        "wall_s": round(wall_elapsed, 2),
        # agreement is asserted above, so one node's ordered-digest hash
        # identifies the whole pool's ordering (the sharded gate compares
        # it against the 1-device run)
        "ordered_hash": pool.ordered_hash(),
        # ordering fast path: what actually crossed the device->host
        # boundary (compact deltas by default, the full event matrix
        # under host_eval) — the readback gate compares the two
        "eval_mode": pool.vote_group.eval_mode,
        "readback_bytes": pool.vote_group.readback_bytes_total,
        "readbacks": pool.vote_group.readbacks,
        "readbacks_overlapped": pool.vote_group.readbacks_overlapped,
    }
    if mesh is not None:
        result["shards"] = pool.vote_group.shards
        result["mesh_shape"] = list(pool.vote_group.mesh_shape)
        result["shard_occupancy"] = pool.vote_group.shard_occupancy
    vg = pool.vote_group
    if vg.resident_depth > 1 or vg.rebalances:
        # multi-tick residency / rebalancing surface: how many host
        # round-trips the ring deferred and where the planes ended up
        result["residency"] = {
            "resident_depth": vg.resident_depth,
            "resident_ticks": vg.resident_ticks,
            "readbacks_deferred": vg.readbacks_deferred,
            "rebalances": vg.rebalances,
            "row_shift": vg.row_shift,
        }
    if pool.governor is not None:
        result["governor"] = pool.governor.trajectory_summary()
    if trace:
        result["trace_events"] = len(pool.trace)
        result["trace_hash"] = pool.trace.trace_hash()
        # causal request journeys (latency gate): counts + completeness
        # + the byte-stable journey table fingerprint + client-observed
        # e2e percentiles with attribution shares
        from indy_plenum_tpu.observability.causal import journey_summary

        js = journey_summary(pool.trace.events())
        result["journeys"] = {
            "count": js["count"],
            "complete": js["complete"],
            "orphan_spans": js["orphan_spans"],
            "journey_hash": js["journey_hash"],
            "e2e": js["e2e"]["write"],
            "attribution_share": js["attribution_share"],
        }
    return result


def governor_gates(args) -> "tuple[dict, list]":
    """Static vs adaptive on the SAME bursty workload and seed; returns
    (record, failures)."""
    static = measure(args.nodes, args.instances, args.batches,
                     args.batch_size, args.tick, seed=args.seed,
                     adaptive=False, bursty=True)
    adaptive = measure(args.nodes, args.instances, args.batches,
                       args.batch_size, args.tick, seed=args.seed,
                       adaptive=True, bursty=True)
    tol = args.adaptive_tolerance
    failures = []
    occ = adaptive["flush_occupancy_avg"] or 0.0
    if occ < args.occupancy_floor:
        failures.append(
            f"adaptive flush_occupancy {occ} < floor {args.occupancy_floor}")
    s_pb = static["device_dispatches_per_ordered_batch"]
    a_pb = adaptive["device_dispatches_per_ordered_batch"]
    if a_pb > s_pb * (1.0 + tol):
        failures.append(f"adaptive dispatches/batch {a_pb} regresses "
                        f"static {s_pb} beyond {tol:.0%}")
    s_tps = static["ordered_per_sim_second"] or 0.0
    a_tps = adaptive["ordered_per_sim_second"] or 0.0
    if a_tps < s_tps * (1.0 - tol):
        failures.append(f"adaptive ordered/sim-sec {a_tps} regresses "
                        f"static {s_tps} beyond {tol:.0%}")
    record = {
        "static_bursty": static,
        "adaptive_bursty": adaptive,
        "occupancy_floor": args.occupancy_floor,
        "adaptive_tolerance": tol,
        "adaptive_dispatch_ratio": round(a_pb / s_pb, 3) if s_pb else None,
        "adaptive_sim_throughput_ratio": round(a_tps / s_tps, 3)
        if s_tps else None,
    }
    return record, failures


def sharded_gates(args) -> "tuple[dict, list]":
    """1-device vs mesh-sharded on the SAME workload and seed at the
    acceptance shape (n=16, k=6, 4-way host mesh by default); returns
    (record, failures). The digests must be bit-identical and the
    dispatch discipline must survive sharding."""
    from indy_plenum_tpu.tpu.quorum import make_fabric_mesh

    devices = jax.devices()
    if len(devices) < args.mesh_devices:
        return ({"skipped": f"need {args.mesh_devices} devices, "
                            f"have {len(devices)}"},
                [f"sharded gate needs {args.mesh_devices} host devices "
                 f"(have {len(devices)}; XLA_FLAGS set too late?)"])
    mesh = make_fabric_mesh(devices, (args.mesh_devices,))
    single = measure(args.sharded_nodes, args.sharded_instances,
                     args.batches, args.batch_size, args.tick,
                     seed=args.seed)
    sharded = measure(args.sharded_nodes, args.sharded_instances,
                      args.batches, args.batch_size, args.tick,
                      seed=args.seed, mesh=mesh)
    tol = args.sharded_tolerance
    failures = []
    if sharded["ordered_hash"] != single["ordered_hash"]:
        failures.append("sharded ordered digests diverge from the "
                        "1-device run (sharding changed semantics)")
    s_pb = single["device_dispatches_per_ordered_batch"]
    m_pb = sharded["device_dispatches_per_ordered_batch"]
    if s_pb and abs(m_pb - s_pb) > s_pb * tol:
        failures.append(f"sharded dispatches/batch {m_pb} drifts from "
                        f"1-device {s_pb} beyond {tol:.0%}")
    occ = sharded["flush_occupancy_avg"] or 0.0
    if occ < args.occupancy_floor:
        failures.append(
            f"sharded flush_occupancy {occ} < floor {args.occupancy_floor}")
    record = {
        "single_device": single,
        "mesh_sharded": sharded,
        "mesh_devices": args.mesh_devices,
        "sharded_tolerance": tol,
        "digests_match": sharded["ordered_hash"] == single["ordered_hash"],
        "sharded_dispatch_ratio": round(m_pb / s_pb, 3) if s_pb else None,
    }
    return record, failures


def fabric_gate(args, base: "dict | None" = None) -> "tuple[dict, list]":
    """Scale-out quorum fabric gate: the SAME n=16/k=6 workload and seed
    on a 1-axis member mesh vs the 2-axis member x validator fabric
    (both over the sharded gate's device pool). The fabric is a
    PLACEMENT choice: ``ordered_hash`` must match bit-for-bit,
    dispatches/ordered-batch and readback bytes must sit within
    ``--fabric-tolerance`` — the psum quorum reduction and per-shard
    pipelined readbacks may move work, never change or inflate it.
    ``base`` reuses the sharded gate's mesh run (identical arguments)
    as the 1-axis arm instead of re-paying the cold simulation."""
    from indy_plenum_tpu.tpu.quorum import make_fabric_mesh

    devices = jax.devices()
    if len(devices) < args.mesh_devices:
        return ({"skipped": f"need {args.mesh_devices} devices, "
                            f"have {len(devices)}"},
                [f"fabric gate needs {args.mesh_devices} host devices "
                 f"(have {len(devices)}; XLA_FLAGS set too late?)"])
    if base is None:
        base = measure(args.sharded_nodes, args.sharded_instances,
                       args.batches, args.batch_size, args.tick,
                       seed=args.seed,
                       mesh=make_fabric_mesh(devices,
                                             (args.mesh_devices,)))
    # the 2-axis grid over the same device pool: members x validators
    m_axis = max(args.mesh_devices // 2, 1)
    two = measure(args.sharded_nodes, args.sharded_instances,
                  args.batches, args.batch_size, args.tick,
                  seed=args.seed,
                  mesh=make_fabric_mesh(devices, (m_axis, 2)))
    tol = args.fabric_tolerance
    failures = []
    if two["ordered_hash"] != base["ordered_hash"]:
        failures.append("2-axis fabric ordered digests diverge from the "
                        "1-axis mesh run (the validator axis changed "
                        "semantics)")
    b_pb = base["device_dispatches_per_ordered_batch"]
    t_pb = two["device_dispatches_per_ordered_batch"]
    if b_pb and abs(t_pb - b_pb) > b_pb * tol:
        failures.append(f"2-axis dispatches/batch {t_pb} drifts from "
                        f"1-axis {b_pb} beyond {tol:.0%}")
    # TOTAL readback bytes, not bytes/readback: per-shard absorbs split
    # the same bytes across as many readbacks as the mesh has member
    # shards, so the per-readback figure legitimately differs between
    # mesh shapes — what must NOT drift is what crossed the link
    b_rb, t_rb = base["readback_bytes"], two["readback_bytes"]
    if b_rb and abs(t_rb - b_rb) > b_rb * tol:
        failures.append(f"2-axis readback bytes {t_rb} drift from "
                        f"1-axis {b_rb} beyond {tol:.0%} (the compact "
                        "blocks should be identical; the validator axis "
                        "must not be fetched twice)")
    record = {
        "one_axis": base,
        "two_axis": two,
        "fabric_tolerance": tol,
        "digests_match": two["ordered_hash"] == base["ordered_hash"],
        "fabric_dispatch_ratio": round(t_pb / b_pb, 3) if b_pb else None,
        "fabric_readback_ratio": round(t_rb / b_rb, 3) if b_rb else None,
    }
    return record, failures


def tracing_gate(args, base: "dict | None" = None) -> "tuple[dict, list]":
    """Flight recorder disabled vs enabled on the SAME n=16/k=6 workload
    and seed; returns (record, failures). Observability must be free in
    protocol time (identical digests, ordered/sim-second within
    ``--trace-tolerance``) — the wall ratio is recorded so host-side
    drift is visible even when the gate passes. ``base`` reuses the
    sharded gate's single-device run (identical arguments) instead of
    paying the cold n=16/k=6 simulation a third time."""
    if base is None:
        base = measure(args.sharded_nodes, args.sharded_instances,
                       args.batches, args.batch_size, args.tick,
                       seed=args.seed)
    traced = measure(args.sharded_nodes, args.sharded_instances,
                     args.batches, args.batch_size, args.tick,
                     seed=args.seed, trace=True)
    tol = args.trace_tolerance
    failures = []
    if traced["ordered_hash"] != base["ordered_hash"]:
        failures.append("traced ordered digests diverge from the "
                        "untraced run (recording perturbed consensus)")
    b_tps = base["ordered_per_sim_second"] or 0.0
    t_tps = traced["ordered_per_sim_second"] or 0.0
    if t_tps < b_tps * (1.0 - tol):
        failures.append(f"traced ordered/sim-sec {t_tps} regresses "
                        f"untraced {b_tps} beyond {tol:.0%}")
    record = {
        "untraced": base,
        "traced": traced,
        "trace_tolerance": tol,
        "digests_match": traced["ordered_hash"] == base["ordered_hash"],
        "sim_throughput_ratio": round(t_tps / b_tps, 4) if b_tps else None,
        "wall_ratio": (round(traced["wall_s"] / base["wall_s"], 3)
                       if base["wall_s"] else None),
    }
    return record, failures


def _measure_saturation(args, rate: float, seed: int) -> dict:
    """One open-loop ingress run at the acceptance shape (n=16/k=6 by
    default): the seeded workload generator drives the SIGNED auth path
    through a bounded admission queue for ``--ingress-duration`` sim
    seconds at ``rate`` arrivals/sim-second, then the pool settles."""
    from indy_plenum_tpu.ingress import WorkloadGenerator, WorkloadSpec

    config = getConfig({
        "Max3PCBatchSize": 40,
        "Max3PCBatchWait": 0.05,
        "QuorumTickInterval": args.tick,
        "QuorumTickAdaptive": True,
        "IngressQueueCapacity": args.ingress_capacity,
    })
    pool = SimPool(n_nodes=args.sharded_nodes, seed=seed, config=config,
                   device_quorum=True, shadow_check=False,
                   num_instances=args.sharded_instances,
                   sign_requests=True)

    def min_ordered():
        return min(len(nd.ordered_digests) for nd in pool.nodes)

    # warm-up OUTSIDE the measured window: a sub-capacity wave orders
    # once, compiling the signed-ingress + vote-plane shapes (a cold
    # XLA compile would otherwise eat the wall deadline and truncate
    # the measurement). Deterministic: ordering progress is a pure
    # function of the seed, so both saturated runs warm identically.
    warm = max(2, args.ingress_capacity // 2)
    for i in range(warm):
        pool.submit_request(10_000_000 + i, client_id="warm")
    deadline = time.monotonic() + 300
    while min_ordered() < warm and time.monotonic() < deadline:
        pool.run_for(0.5)
    assert min_ordered() >= warm, "ingress-gate warm-up stalled"
    warm_ordered = min_ordered()

    seq = [0]

    def on_write(client: int, key: int) -> None:
        seq[0] += 1
        pool.submit_request(seq[0], client_id="c%d" % client)

    gen = WorkloadGenerator(WorkloadSpec(
        n_clients=100_000, rate=rate, duration=args.ingress_duration,
        read_fraction=0.0, n_keys=64, seed=seed))
    gen.start(pool.timer, on_write)

    sim_t0 = pool.timer.get_current_time()
    horizon = args.ingress_duration + 8.0
    elapsed = 0.0
    deadline = time.monotonic() + 300
    while (elapsed < horizon or pool.admission.depth) \
            and time.monotonic() < deadline:
        pool.run_for(0.5)
        elapsed += 0.5
    assert pool.honest_nodes_agree()
    sim_elapsed = pool.timer.get_current_time() - sim_t0
    adm = pool.admission
    ordered = min_ordered() - warm_ordered
    return {
        "rate": rate,
        "arrivals": gen.arrivals,
        "admitted": adm.admitted_total - warm,  # warm-up wave excluded
        "shed": adm.shed_total,
        "peak_queue_depth": adm.peak_depth,
        "capacity": adm.capacity,
        "shed_hash": adm.shed_hash(),
        "ordered": ordered,
        "ordered_per_sim_second": round(ordered / sim_elapsed, 2)
        if sim_elapsed else None,
        "ordered_hash": pool.ordered_hash(),
        "governor": (pool.governor.trajectory_summary()
                     if pool.governor is not None else None),
    }


def readback_gate(args, base: "dict | None" = None) -> "tuple[dict, list]":
    """Ordering fast path gate: device-side quorum eval (compact delta
    readback, the default) vs the ``host_eval`` full-event-matrix
    fallback on the SAME n=16/k=6 workload and seed. The eval mode may
    change WHAT crosses the device->host link, never the ordering:
    digests must be bit-identical, the compact run's bytes/readback must
    sit under ``--readback-budget`` AND well below the matrix run's, and
    ordered/sim-second must not regress beyond ``--readback-tolerance``.
    ``base`` reuses the sharded gate's single-device run (identical
    arguments, device eval) instead of re-paying the cold simulation."""
    if base is None:
        base = measure(args.sharded_nodes, args.sharded_instances,
                       args.batches, args.batch_size, args.tick,
                       seed=args.seed)
    host = measure(args.sharded_nodes, args.sharded_instances,
                   args.batches, args.batch_size, args.tick,
                   seed=args.seed, host_eval=True)
    failures = []
    if base["ordered_hash"] != host["ordered_hash"]:
        failures.append("device-eval ordered digests diverge from the "
                        "host_eval fallback (fast path changed semantics)")
    d_per = (base["readback_bytes"] / base["readbacks"]
             if base["readbacks"] else 0.0)
    h_per = (host["readback_bytes"] / host["readbacks"]
             if host["readbacks"] else 0.0)
    if d_per > args.readback_budget:
        failures.append(f"device-eval readback {d_per:.0f} bytes/readback "
                        f"over budget {args.readback_budget}")
    # the structural claim: compact deltas, not the event matrix — the
    # fast path must read back a small fraction of the fallback's bytes
    if h_per and d_per > h_per * 0.5:
        failures.append(f"device-eval readback {d_per:.0f} bytes is not "
                        f"compact vs the event matrix {h_per:.0f}")
    tol = args.readback_tolerance
    d_tps = base["ordered_per_sim_second"] or 0.0
    h_tps = host["ordered_per_sim_second"] or 0.0
    if d_tps < h_tps * (1.0 - tol):
        failures.append(f"device-eval ordered/sim-sec {d_tps} regresses "
                        f"host_eval {h_tps} beyond {tol:.0%}")
    record = {
        "device_eval": base,
        "host_eval": host,
        "readback_budget": args.readback_budget,
        "readback_tolerance": tol,
        "digests_match": base["ordered_hash"] == host["ordered_hash"],
        "device_bytes_per_readback": round(d_per, 1),
        "host_bytes_per_readback": round(h_per, 1),
        "readback_compression": round(h_per / d_per, 1) if d_per else None,
        "sim_throughput_ratio": round(d_tps / h_tps, 4) if h_tps else None,
    }
    return record, failures


def ingress_gate(args) -> "tuple[dict, list]":
    """Saturation gate (ingress plane): at n=16/k=6, open-loop overload
    must shed DETERMINISTICALLY behind a bounded queue — never grow it
    past capacity — and goodput under saturation must stay within
    ``--ingress-tolerance`` of the unsaturated run (admission exists to
    protect throughput, not to trade it away). Two saturated runs on the
    same seed must produce the byte-identical shed set and ordering."""
    if args.ingress_capacity < 1:
        raise SystemExit(
            "--ingress-capacity must be >= 1 for the ingress gate "
            "(capacity 0 disables admission control entirely; pass "
            "--no-ingress-gate to skip the gate instead)")
    unsat = _measure_saturation(args, args.ingress_unsat_rate,
                                seed=args.seed)
    sat = _measure_saturation(args, args.ingress_rate, seed=args.seed)
    sat2 = _measure_saturation(args, args.ingress_rate, seed=args.seed)
    failures = []
    if unsat["shed"] > 0:
        failures.append(
            f"unsaturated run shed {unsat['shed']} requests "
            "(gate baseline must run below capacity)")
    if sat["shed"] == 0:
        failures.append("saturated run shed nothing (rate "
                        f"{args.ingress_rate} does not overload capacity "
                        f"{args.ingress_capacity})")
    if sat["peak_queue_depth"] > sat["capacity"]:
        failures.append(
            f"queue grew past capacity: peak {sat['peak_queue_depth']} "
            f"> {sat['capacity']}")
    if sat2["shed_hash"] != sat["shed_hash"]:
        failures.append("shed set is not deterministic across identical "
                        "saturated runs")
    if sat2["ordered_hash"] != sat["ordered_hash"]:
        failures.append("ordering diverged across identical saturated "
                        "runs")
    tol = args.ingress_tolerance
    u_tps = unsat["ordered_per_sim_second"] or 0.0
    s_tps = sat["ordered_per_sim_second"] or 0.0
    if s_tps < u_tps * (1.0 - tol):
        failures.append(f"saturated ordered/sim-sec {s_tps} collapsed "
                        f"below unsaturated {u_tps} beyond {tol:.0%}")
    record = {
        "unsaturated": unsat,
        "saturated": sat,
        "ingress_tolerance": tol,
        "shed_deterministic": sat2["shed_hash"] == sat["shed_hash"],
        "ordered_deterministic":
            sat2["ordered_hash"] == sat["ordered_hash"],
        "saturation_throughput_ratio": round(s_tps / u_tps, 3)
        if u_tps else None,
        "shed_fraction": round(
            sat["shed"] / max(sat["arrivals"], 1), 4),
    }
    return record, failures


def _measure_overload(args, retry: bool, seed: int) -> dict:
    """One flash-crowd overload arm at the gate shape (n=6, bounded
    queue, adaptive tick): a sub-saturation base rate with a hard crowd
    spike, open-loop (``retry=False``, shed requests walk away) or
    closed-loop (``retry=True``, every shed re-offers on the seeded
    backoff). Returns the goodput / recovery / fingerprint record the
    overload gate compares."""
    from indy_plenum_tpu.common.metrics_collector import MetricsName
    from indy_plenum_tpu.ingress import (
        WorkloadGenerator,
        WorkloadProfile,
        WorkloadSpec,
    )

    n_nodes, capacity = 6, 10
    base_rate, duration = 80.0, 7.0
    flash_at, flash_dur, peak = 2.5, 1.25, 10.0
    config = getConfig({
        "Max3PCBatchSize": 40,
        "Max3PCBatchWait": 0.05,
        "QuorumTickInterval": 0.1,
        "QuorumTickAdaptive": True,
        "IngressQueueCapacity": capacity,
        "IngressRetryMax": 4 if retry else 0,
        "IngressRetryBase": 0.2,
        "IngressRetryBackoffMult": 2.0,
        "IngressRetryBackoffMax": 2.0,
    })
    pool = SimPool(n_nodes=n_nodes, seed=seed, config=config,
                   device_quorum=True, shadow_check=False,
                   sign_requests=True)

    def min_ordered():
        return min(len(nd.ordered_digests) for nd in pool.nodes)

    warm = capacity - 4
    for i in range(warm):
        pool.submit_request(10_000_000 + i, client_id="warm")
    deadline = time.monotonic() + 300
    while min_ordered() < warm and time.monotonic() < deadline:
        pool.run_for(0.5)
    assert min_ordered() >= warm, "overload-gate warm-up stalled"
    ordered0 = min_ordered()

    seq = [0]

    def on_write(client: int, key: int) -> None:
        seq[0] += 1
        pool.submit_request(seq[0], client_id="c%d" % client)

    gen = WorkloadGenerator(WorkloadSpec(
        n_clients=100_000, rate=base_rate, duration=duration,
        read_fraction=0.0, n_keys=64, seed=seed,
        profile=WorkloadProfile(kind="flash", peak=peak,
                                flash_at=flash_at,
                                flash_duration=flash_dur)))
    gen.start(pool.timer, on_write)

    sim_t0 = pool.timer.get_current_time()
    samples = {}
    marks = (1.0, flash_at, flash_at + flash_dur, 5.0, duration)
    elapsed = 0.0
    deadline = time.monotonic() + 600
    while (elapsed < duration + 6.0 or pool.admission.depth
           or (pool.retry is not None and pool.retry.outstanding)) \
            and time.monotonic() < deadline:
        pool.run_for(0.5)
        elapsed += 0.5
        for m in marks:
            if m <= elapsed and m not in samples:
                samples[m] = min_ordered()
    assert pool.honest_nodes_agree()
    sim_elapsed = pool.timer.get_current_time() - sim_t0
    adm = pool.admission
    # a wall-deadline exit can leave late marks unsampled — fill them
    # with the final count so the gate fails on its rate floors instead
    # of a KeyError
    for m in marks:
        samples.setdefault(m, min_ordered())
    pre_rate = (samples[flash_at] - samples[1.0]) / (flash_at - 1.0)
    post_rate = (samples[duration] - samples[5.0]) / (duration - 5.0)
    readmitted = pool.metrics.stat(MetricsName.INGRESS_RETRY_ADMITTED)
    return {
        "retry": bool(retry),
        "arrivals": gen.arrivals,
        "admitted": adm.admitted_total - warm,
        "shed": adm.shed_total,
        "ordered": min_ordered() - ordered0,
        "ordered_per_sim_second": round(
            (min_ordered() - ordered0) / sim_elapsed, 2)
        if sim_elapsed else None,
        "pre_spike_rate": round(pre_rate, 2),
        "post_spike_rate": round(post_rate, 2),
        "recovery_ratio": round(post_rate / pre_rate, 3)
        if pre_rate else None,
        "retry_admitted": int(readmitted.total) if readmitted else 0,
        "reoffers": pool.retry.reoffers_total if pool.retry else 0,
        "retry_exhausted": pool.retry.exhausted_total
        if pool.retry else 0,
        "shed_hash": adm.shed_hash(),
        "retry_hash": pool.retry.retry_hash() if pool.retry else None,
        "ordered_hash": pool.ordered_hash(),
        "governor": (pool.governor.trajectory_summary()
                     if pool.governor is not None else None),
    }


def overload_gate(args) -> "tuple[dict, list]":
    """Overload robustness gate (ISSUE 15): the closed-loop retry storm
    must degrade GRACEFULLY, never metastably. On the same seeded
    flash-crowd spike:

    1. the spike must actually overload (open arm sheds, retry arm
       re-offers — a gate that never engages the storm is vacuous);
    2. goodput under the retry storm must hold >=
       ``--overload-goodput-floor`` of the open-loop arm (the storm
       compounds offered load; it must not crush throughput);
    3. ordered/sim-sec must RECOVER after the crowd ends — post-spike
       rate within ``--overload-recovery-tolerance`` of pre-spike on
       both arms (a metastable pool never comes back);
    4. two same-seed retry runs must replay byte-identical
       shed/retry/ordered fingerprints;
    5. the ``f_crash_catchup_under_saturation`` chaos scenario (victim
       crashes across GC'd windows while the crowd spikes and clients
       retry) must PASS every verdict — catchup_recovery included —
       with the seeder throttle's deferral meter engaged (the pool kept
       ordering while it fed the leecher) and a byte-identical replay.
    """
    from indy_plenum_tpu.chaos import run_scenario

    open_arm = _measure_overload(args, retry=False, seed=args.seed)
    storm = _measure_overload(args, retry=True, seed=args.seed)
    storm2 = _measure_overload(args, retry=True, seed=args.seed)

    failures = []
    if open_arm["shed"] == 0:
        failures.append("open-loop arm shed nothing — the flash crowd "
                        "never overloaded the queue (gate vacuous)")
    if storm["reoffers"] == 0:
        failures.append("retry arm re-offered nothing — the closed "
                        "loop never engaged (gate vacuous)")
    floor = args.overload_goodput_floor
    ratio = storm["ordered"] / open_arm["ordered"] \
        if open_arm["ordered"] else 0.0
    if ratio < floor:
        failures.append(
            f"retry-storm goodput {storm['ordered']} fell to "
            f"{ratio:.2f}x of the open-loop arm {open_arm['ordered']} "
            f"(floor {floor})")
    tol = args.overload_recovery_tolerance
    for arm, rec in (("open", open_arm), ("retry", storm)):
        if (rec["recovery_ratio"] or 0.0) < 1.0 - tol:
            failures.append(
                f"metastable collapse on the {arm} arm: post-spike "
                f"rate {rec['post_spike_rate']} never recovered to "
                f"pre-spike {rec['pre_spike_rate']} "
                f"(ratio {rec['recovery_ratio']}, tolerance {tol})")
    for key in ("shed_hash", "retry_hash", "ordered_hash"):
        if storm2[key] != storm[key]:
            failures.append(
                f"retry storm is not deterministic: {key} diverged "
                "across identical same-seed runs")

    t0 = time.perf_counter()
    chaos = run_scenario("f_crash_catchup_under_saturation",
                         seed=args.seed, device_quorum=True,
                         quorum_tick_interval=0.1,
                         quorum_tick_adaptive=True, trace=True)
    chaos_wall = time.perf_counter() - t0
    replay = run_scenario("f_crash_catchup_under_saturation",
                          seed=args.seed, device_quorum=True,
                          quorum_tick_interval=0.1,
                          quorum_tick_adaptive=True, trace=True)
    if not chaos.verdict_as_expected:
        failures.append(
            f"f_crash_catchup_under_saturation verdicts: "
            f"failed={chaos.failed}")
    throttle = chaos.ingress.get("seeder_throttle", {})
    if not throttle.get("deferred"):
        failures.append("seeder throttle never deferred a slice — the "
                        "ordering-protection meter never engaged")
    if not (chaos.ingress.get("retry") or {}).get("reoffers"):
        failures.append("chaos arc saw no closed-loop retries — the "
                        "storm never reached the recovering pool")
    if replay.trace_hash != chaos.trace_hash \
            or replay.ingress.get("shed_hash") \
            != chaos.ingress.get("shed_hash") \
            or replay.ingress.get("retry_hash") \
            != chaos.ingress.get("retry_hash"):
        failures.append("catchup-under-saturation run does not replay "
                        "byte-identically (trace/shed/retry hash)")

    record = {
        "open_loop": open_arm,
        "retry_storm": storm,
        "goodput_floor": floor,
        "goodput_ratio": round(ratio, 3),
        "recovery_tolerance": tol,
        "deterministic": all(storm2[k] == storm[k] for k in
                             ("shed_hash", "retry_hash",
                              "ordered_hash")),
        "chaos": {
            "scenario": "f_crash_catchup_under_saturation",
            "verdicts_pass": chaos.verdict_as_expected,
            "catchup": {k: chaos.catchup.get(k)
                        for k in ("rounds", "txns_leeched",
                                  "proofs_verified")},
            "admission": chaos.ingress.get("admission"),
            "retry": chaos.ingress.get("retry"),
            "seeder_throttle": throttle,
            "replay_identical": replay.trace_hash == chaos.trace_hash,
            "wall_s": round(chaos_wall, 2),
            "replay_command": chaos.replay_command,
        },
    }
    return record, failures


def proof_gate(args) -> "tuple[dict, list]":
    """State-proof plane gate: (1) the SAME seeded real-execution BLS
    pool with and without proof-serving reads must order bit-identical
    digests; (2) serving cache-hit reads must perform ZERO pairing
    checks (``crypto.bls.bls_crypto.PAIRINGS``) — the window's
    aggregation was already paid by consensus; (3) every reply must
    verify end-to-end with only the pool's BLS keys; (4) the batched
    pairing verifier must hold >= ``--proof-speedup-floor`` x the
    per-root path at batch 64."""
    import hashlib as _hashlib

    from indy_plenum_tpu.client.state_proof import verify_proved_read
    from indy_plenum_tpu.crypto.bls.bls_crypto import (
        PAIRINGS,
        BlsCryptoSigner,
        BlsCryptoVerifier,
        BlsKeyPair,
    )
    from indy_plenum_tpu.proofs import verify_multi_sigs_batch

    def run(serve_reads: bool) -> dict:
        config = getConfig({
            "CHK_FREQ": 5, "LOG_SIZE": 15,
            "Max3PCBatchSize": 1, "Max3PCBatchWait": 0.05,
        })
        pool = SimPool(4, seed=args.seed, config=config,
                       real_execution=True, bls=True)
        for i in range(8):
            pool.submit_request(i)
        deadline = time.monotonic() + 240
        while min(len(nd.ordered_digests) for nd in pool.nodes) < 8 \
                and time.monotonic() < deadline:
            pool.run_for(0.5)
        assert pool.honest_nodes_agree()
        out = {"ordered_hash": pool.ordered_hash(),
               "windows_signed":
                   pool.nodes[0].proof_cache.windows_signed}
        if serve_reads:
            rs = pool.make_read_service("node0", mode="host")
            for i in range(32):
                rs.submit(i)
            checks0 = PAIRINGS.checks
            replies = rs.drain()
            out["serve_pairing_checks"] = PAIRINGS.checks - checks0
            pool_keys = {n: pk
                         for n, (kp, pk, pop) in pool.bls_keys.items()}
            out["reads"] = len(replies)
            out["reads_with_proof"] = sum(
                1 for r in replies if r.multi_sig is not None)
            out["reads_client_verified"] = sum(
                1 for r in replies
                if verify_proved_read(r, pool_keys, min_participants=3))
        return out

    idle = run(serve_reads=False)
    serving = run(serve_reads=True)
    failures = []
    if serving["ordered_hash"] != idle["ordered_hash"]:
        failures.append("proof-serving ordered digests diverge from the "
                        "idle run (reads perturbed consensus)")
    if serving["windows_signed"] < 1:
        failures.append("no checkpoint window captured a pool proof "
                        "(the CheckpointStabilized hook is dead)")
    if serving.get("serve_pairing_checks", 0) != 0:
        failures.append(
            f"cache-hit serve path performed "
            f"{serving['serve_pairing_checks']} pairing checks "
            "(must be a dict lookup — zero pairings)")
    if serving.get("reads_with_proof") != serving.get("reads"):
        failures.append(
            f"{serving.get('reads', 0) - serving.get('reads_with_proof', 0)}"
            " replies missing the pool multi-signature")
    if serving.get("reads_client_verified") != serving.get("reads"):
        failures.append("replies failed client-side verify_proved_read")

    # batched vs per-root pairing throughput at batch 64 (synthetic
    # windows: 8 validators, 64 roots — the batching claim is about
    # amortizing pairings ACROSS roots, not about the validator count)
    kps = [BlsKeyPair(_hashlib.sha256(b"proof-gate-%d" % i).digest())
           for i in range(8)]
    pks = [kp.pk_b58 for kp in kps]
    items = []
    for j in range(64):
        msg = b"window-root-%d|%d" % (j, args.seed)
        items.append((BlsCryptoVerifier.aggregate_sigs(
            [BlsCryptoSigner(kp).sign(msg) for kp in kps]), msg, pks))
    # warm both paths (subgroup/apk caches) before timing
    assert BlsCryptoVerifier.verify_multi_sig(*items[0])
    assert all(verify_multi_sigs_batch(items[:2], seed=args.seed))
    t0 = time.perf_counter()
    per_root_ok = [BlsCryptoVerifier.verify_multi_sig(*it)
                   for it in items]
    per_root_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    batched = verify_multi_sigs_batch(items, seed=args.seed)
    batch_s = time.perf_counter() - t0
    assert all(per_root_ok) and all(batched)
    speedup = per_root_s / batch_s if batch_s else 0.0
    if speedup < args.proof_speedup_floor:
        failures.append(
            f"batch-64 verify speedup {speedup:.2f}x below floor "
            f"{args.proof_speedup_floor}x vs the per-root path")
    record = {
        "idle": idle,
        "serving": serving,
        "digests_match": serving["ordered_hash"] == idle["ordered_hash"],
        "per_root_64_s": round(per_root_s, 4),
        "batch_64_s": round(batch_s, 4),
        "batch_speedup": round(speedup, 2),
        "proof_speedup_floor": args.proof_speedup_floor,
    }
    return record, failures


def catchup_gate(args) -> "tuple[dict, list]":
    """Chaos-hardened catchup gate: (1) the seeded GC-crossing
    crash/restart scenario (``f_crash_gc_catchup``: crash, >= 2
    checkpoint windows stabilize AND garbage-collect in the victim's
    absence, restart, full leecher round) must PASS every verdict —
    including ``catchup_recovery`` (each leeched batch audit-proof
    verified, victim participating again) and ``catchup_proof_read``
    (the caught-up node serves a ``verify_proved_read``-able reply from
    the window it just leeched); (2) the caught-up node's
    committed-ledger hash must be bit-identical to every survivor's;
    (3) the run must replay byte-identically (``trace_hash``) from its
    seed; (4) the byzantine-seeder scenario must REJECT corrupted
    CATCHUP_REPs by proof verification (asserted, not assumed) and
    still recover through honest seeders. Catchup throughput lands in
    the gate record."""
    from indy_plenum_tpu.chaos import run_scenario

    t0 = time.perf_counter()
    first = run_scenario("f_crash_gc_catchup", seed=args.seed, trace=True)
    gc_wall = time.perf_counter() - t0
    replay = run_scenario("f_crash_gc_catchup", seed=args.seed, trace=True)
    byz = run_scenario("byzantine_seeder_catchup", seed=args.seed)

    failures = []
    if not first.verdict_as_expected:
        failures.append(
            f"f_crash_gc_catchup verdicts: failed={first.failed} "
            f"expected={first.expected_failures}")
    hashes = first.catchup.get("ledger_hash_per_node", {})
    if len(set(hashes.values())) != 1:
        failures.append(
            "caught-up node's committed ledger diverges from the "
            f"survivors: {hashes}")
    if replay.trace_hash != first.trace_hash:
        failures.append(
            "catchup-bearing run does not replay byte-identically "
            f"(trace_hash {first.trace_hash[:12]} vs "
            f"{replay.trace_hash[:12]})")
    if not first.catchup.get("proof_read", {}).get("verified"):
        failures.append("caught-up node's proof-attached read failed "
                        "verify_proved_read")
    if not byz.verdict_as_expected:
        failures.append(
            f"byzantine_seeder_catchup verdicts: failed={byz.failed}")
    if byz.catchup.get("reps_rejected", 0) < 1:
        failures.append("byzantine seeder's corrupted CATCHUP_REPs were "
                        "never rejected (the corruption was trusted or "
                        "never exercised)")
    record = {
        "scenario": "f_crash_gc_catchup",
        "seed": args.seed,
        "verdicts_pass": first.verdict_as_expected,
        "txns_leeched": first.catchup.get("txns_leeched"),
        "proofs_verified": first.catchup.get("proofs_verified"),
        "retries": first.catchup.get("retries"),
        "ledger_hashes_match": len(set(hashes.values())) == 1,
        "proof_read": first.catchup.get("proof_read"),
        "trace_hash": first.trace_hash,
        "replay_identical": replay.trace_hash == first.trace_hash,
        "wall_s": round(gc_wall, 2),
        # recovery throughput: what the whole seeded arc (detect the
        # gap, agree a target, fetch, device-verify, rejoin) sustained
        "leeched_txns_per_wall_sec": round(
            (first.catchup.get("txns_leeched") or 0) / gc_wall, 1)
        if gc_wall else None,
        "byzantine_seeder": {
            "verdicts_pass": byz.verdict_as_expected,
            "reps_rejected": byz.catchup.get("reps_rejected"),
            "retries": byz.catchup.get("retries"),
        },
        "replay_command": first.replay_command,
    }
    return record, failures


def measure_laned(lanes: int, n_nodes: int, txns_per_lane: int,
                  tick: float, seed: int) -> dict:
    """One laned measurement (ordering lanes, ISSUE 14): K full
    ordering lanes (per-lane vote plane groups, one shared tick,
    adaptive governor) under the cross-lane checkpoint barrier with
    tiny windows, traced, routed client traffic, then a seal flush so
    every journey's window seals. Throughput is ordered txns per SIM
    second — the protocol-time rate the lanes add up to."""
    from indy_plenum_tpu.lanes import LanedPool
    from indy_plenum_tpu.observability.causal import journey_summary

    config = getConfig({
        "Max3PCBatchSize": 5,
        "Max3PCBatchWait": 0.05,
        "CHK_FREQ": 2,
        "LOG_SIZE": 6,
        "QuorumTickInterval": tick,
        "QuorumTickAdaptive": True,
    })
    pool = LanedPool(lanes=lanes, n_nodes=n_nodes, seed=seed,
                     config=config, device_quorum=True, trace=True)
    total = txns_per_lane * lanes
    sim_t0 = pool.timer.get_current_time()
    for i in range(total):
        pool.submit_request(i)
    deadline = time.monotonic() + 240
    while pool.ordered_total() < total and time.monotonic() < deadline:
        pool.run_for(0.5)
    assert pool.ordered_total() >= total, \
        f"laned run stalled at {pool.ordered_total()}/{total}"
    assert pool.honest_nodes_agree()
    sim_elapsed = pool.timer.get_current_time() - sim_t0
    pads = pool.seal_flush()
    js = journey_summary(pool.trace.events())
    lanes_js = js.get("lanes") or {}
    return {
        "lanes": lanes,
        "n_per_lane": n_nodes,
        "txns_ordered": total,
        "ordered_per_sim_second": round(total / sim_elapsed, 2),
        "sim_elapsed": round(sim_elapsed, 3),
        "router_distribution": list(pool.router.distribution),
        "ordered_hash_per_lane": pool.ordered_hashes(),
        "sealed_window": pool.barrier.sealed_window,
        "sealed_fingerprint": pool.sealed_fingerprint,
        "seal_pads": pads,
        "journey_hash": js["journey_hash"],
        "journeys_count": js["count"],
        "journeys_complete": js["complete"],
        "orphan_spans": js["orphan_spans"],
        "with_lane": lanes_js.get("with_lane", 0),
        "with_barrier_hop": lanes_js.get("with_barrier_hop", 0),
        "e2e_per_lane": lanes_js.get("e2e_per_lane") or {},
    }


def lanes_gate(args) -> "tuple[dict, list]":
    """Multi-lane ordering gate (ISSUE 14): on the SAME seed,

    1. the 4-lane arm's ordered/sim-second must be at least
       ``--lanes-speedup-floor`` (3.0) times the 1-lane arm's — the
       write path scales horizontally, barrier included;
    2. a 4-lane replay must be BYTE-IDENTICAL: per-lane
       ``ordered_hash``es, the sealed-window fingerprint chain tip, and
       the journey table fingerprint;
    3. zero orphan journeys, and EVERY journey names its lane and
       carries the cross-lane barrier hop (seal coverage is total after
       the seal flush).
    """
    one = measure_laned(1, args.lanes_nodes, args.lanes_txns,
                        args.tick, seed=args.seed)
    four = measure_laned(4, args.lanes_nodes, args.lanes_txns,
                         args.tick, seed=args.seed)
    replay = measure_laned(4, args.lanes_nodes, args.lanes_txns,
                           args.tick, seed=args.seed)
    failures = []
    speedup = (four["ordered_per_sim_second"]
               / one["ordered_per_sim_second"])
    if speedup < args.lanes_speedup_floor:
        failures.append(
            f"4-lane ordered/sim-sec speedup {speedup:.2f} below the "
            f"{args.lanes_speedup_floor}x floor "
            f"({four['ordered_per_sim_second']} vs "
            f"{one['ordered_per_sim_second']})")
    if replay["ordered_hash_per_lane"] != four["ordered_hash_per_lane"]:
        failures.append("per-lane ordered hashes diverge across "
                        "identical seeded 4-lane runs")
    if replay["sealed_fingerprint"] != four["sealed_fingerprint"]:
        failures.append("sealed-window fingerprint diverges across "
                        "identical seeded 4-lane runs")
    if replay["journey_hash"] != four["journey_hash"]:
        failures.append("laned journey tables diverge across identical "
                        "seeded 4-lane runs")
    for arm, label in ((one, "1-lane"), (four, "4-lane")):
        if arm["orphan_spans"] > 0 \
                or arm["journeys_complete"] != arm["journeys_count"]:
            failures.append(
                f"{label}: {arm['orphan_spans']} orphan journeys "
                f"({arm['journeys_complete']}/{arm['journeys_count']} "
                f"complete)")
        if arm["with_lane"] != arm["journeys_count"]:
            failures.append(
                f"{label}: only {arm['with_lane']} of "
                f"{arm['journeys_count']} journeys name their lane")
        if arm["with_barrier_hop"] != arm["journeys_count"]:
            failures.append(
                f"{label}: only {arm['with_barrier_hop']} of "
                f"{arm['journeys_count']} journeys carry the barrier "
                f"hop")
    record = {
        "one_lane": one,
        "four_lane": four,
        "replay_identical": (
            replay["ordered_hash_per_lane"]
            == four["ordered_hash_per_lane"]
            and replay["sealed_fingerprint"] == four["sealed_fingerprint"]
            and replay["journey_hash"] == four["journey_hash"]),
        "speedup_4_lanes": round(speedup, 3),
        "speedup_floor": args.lanes_speedup_floor,
    }
    return record, failures


def latency_gate(args, traced: "dict | None" = None,
                 base: "dict | None" = None,
                 laned: "dict | None" = None) -> "tuple[dict, list]":
    """End-to-end latency gate (causal tracing plane, ISSUE 12): on the
    SAME n=16/k=6 workload and seed,

    1. two traced runs must produce BYTE-IDENTICAL journey tables
       (``journey_hash``) — the causal plane is deterministic like
       everything else in this repo;
    2. 100% of ordered requests must yield COMPLETE journeys (no orphan
       spans: every ingress joins a finalisation, a batch, an ordering
       and an execution across the pool);
    3. the traced run's ordered digests must match the untraced run's
       bit-for-bit (tracing never perturbs consensus — shared with the
       tracing gate, re-asserted here because this gate can run alone
       via ``--only latency``);
    4. e2e p99 (client ingress -> executed, VIRTUAL protocol time) is
       recorded against ``--e2e-budget`` and fails the gate when over;
    5. (journeys phase 2, ISSUE 14) at 4 ordering lanes: zero orphan
       journeys and EVERY lane's e2e p99 within the same budget.

    ``traced``/``base`` reuse the tracing gate's runs and ``laned``
    the lanes gate's 4-lane arm (identical arguments) so the default
    full-script invocation pays only ONE extra traced run (the
    byte-identity replay)."""
    if laned is None:
        laned = measure_laned(4, args.lanes_nodes, args.lanes_txns,
                              args.tick, seed=args.seed)
    if traced is None:
        traced = measure(args.sharded_nodes, args.sharded_instances,
                         args.batches, args.batch_size, args.tick,
                         seed=args.seed, trace=True)
    replay = measure(args.sharded_nodes, args.sharded_instances,
                     args.batches, args.batch_size, args.tick,
                     seed=args.seed, trace=True)
    if base is None:
        base = measure(args.sharded_nodes, args.sharded_instances,
                       args.batches, args.batch_size, args.tick,
                       seed=args.seed)
    failures = []
    j, j2 = traced["journeys"], replay["journeys"]
    if j["journey_hash"] != j2["journey_hash"]:
        failures.append(
            "journey tables diverge across identical seeded runs "
            f"({j['journey_hash'][:12]} vs {j2['journey_hash'][:12]})")
    if j["orphan_spans"] > 0 or j["complete"] != j["count"]:
        failures.append(
            f"{j['orphan_spans']} ordered requests left orphan spans "
            f"({j['complete']}/{j['count']} journeys complete)")
    if j["count"] < traced["txns_ordered"]:
        failures.append(
            f"journey table covers {j['count']} of "
            f"{traced['txns_ordered']} ordered requests")
    if traced["ordered_hash"] != base["ordered_hash"]:
        failures.append("traced ordered digests diverge from the "
                        "untraced run (journey marks perturbed "
                        "consensus)")
    p99 = j["e2e"]["p99"]
    if p99 > args.e2e_budget:
        failures.append(f"e2e p99 {p99} sim-seconds over budget "
                        f"{args.e2e_budget}")
    # journeys phase 2 (ordering lanes): at 4 lanes, zero orphans and
    # per-lane e2e p99 inside the same budget
    if laned["orphan_spans"] > 0 \
            or laned["journeys_complete"] != laned["journeys_count"]:
        failures.append(
            f"4-lane run left {laned['orphan_spans']} orphan journeys "
            f"({laned['journeys_complete']}/{laned['journeys_count']} "
            f"complete)")
    lane_p99 = {lane: block["p99"]
                for lane, block in sorted(laned["e2e_per_lane"].items())}
    for lane, value in lane_p99.items():
        if value > args.e2e_budget:
            failures.append(
                f"lane {lane} e2e p99 {value} sim-seconds over budget "
                f"{args.e2e_budget}")
    record = {
        "traced": traced,
        "replay_journey_hash": j2["journey_hash"],
        "journeys_deterministic":
            j["journey_hash"] == j2["journey_hash"],
        "digests_match": traced["ordered_hash"] == base["ordered_hash"],
        "e2e": j["e2e"],
        "e2e_budget": args.e2e_budget,
        "attribution_share": j["attribution_share"],
        "laned_e2e_p99_per_lane": lane_p99,
        "laned_orphan_spans": laned["orphan_spans"],
    }
    return record, failures


def static_gate(args) -> "tuple[dict, list]":
    """Determinism & hot-path hygiene gate (static analysis plane): the
    pure-AST analyzer runs over ``indy_plenum_tpu/`` TWICE on the SAME
    rule catalog and fails if

    1. any UNSUPPRESSED finding remains — the shipped baseline is
       empty, so a new nondeterminism source / fingerprint-ordering
       hazard / unguarded trace arg / stray device sync / staging-
       buffer alias / config-knob orphan fails closed the moment it is
       committed, whether or not a dynamic gate's seeds exercise it;
    2. any pragma suppressing a finding lacks a justification (the
       ``pragma`` rule fires, which is itself unsuppressed);
    3. ``findings_hash`` is not byte-identical across the two runs —
       the analyzer obeys the replay contract it enforces.
    """
    from collections import Counter

    from indy_plenum_tpu.analysis import analyze_paths

    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "indy_plenum_tpu")
    first = analyze_paths([pkg])
    replay = analyze_paths([pkg])
    failures = []
    if first.unsuppressed:
        head = "; ".join(f.render() for f in first.unsuppressed[:5])
        failures.append(
            f"{len(first.unsuppressed)} unsuppressed static finding(s) "
            f"(run scripts/lint_determinism.py for the list): {head}")
    if replay.findings_hash != first.findings_hash:
        failures.append(
            "static findings_hash drifts across identical runs "
            f"({first.findings_hash[:12]} vs "
            f"{replay.findings_hash[:12]}) — the analyzer itself is "
            "nondeterministic")
    by_rule = Counter(f.rule for f in first.findings)
    record = {
        "files_analyzed": first.files_analyzed,
        "rules": first.rules,
        "findings_total": len(first.findings),
        "unsuppressed": len(first.unsuppressed),
        "suppressed": len(first.suppressed),
        "by_rule": {k: by_rule[k] for k in sorted(by_rule)},
        "findings_hash": first.findings_hash,
        "replay_identical": replay.findings_hash == first.findings_hash,
    }
    return record, failures


def state_gate(args) -> "tuple[dict, list]":
    """State-commit plane gate (state/sparse_merkle_state.py): the
    batched one-walk commit must be a pure optimization —

    1. identical per-window write sets driven through the sequential
       ``set()`` loop, batched host waves and batched ``mode='auto'``
       waves produce BIT-IDENTICAL per-window state roots (the replica-
       agreement invariant: placement and batching move nanoseconds,
       never a root);
    2. at delta=256 on a 100k-key SMT under the hot-key write law the
       batched walk performs <= 1/3 the hashes per commit of the
       sequential loop (the O(delta) claim, measured);
    3. the virtual-time soak holds: a diurnal profile driving a real-
       execution pool across a simulated multi-hour horizon shows a flat
       bounded-structure memory high-water, <``--state-drift-tolerance``
       ordered-throughput drift first-vs-last simulated hour, and two
       same-seed runs byte-identical.
    """
    from indy_plenum_tpu.simulation.state_commit_bench import (
        run_commit_arms,
        run_state_soak,
    )

    failures = []
    try:
        arms = run_commit_arms(n_keys=args.state_keys,
                               delta=args.state_delta,
                               windows=args.state_windows)
    except AssertionError as ex:
        return {"arms_error": str(ex)}, [f"state arms: {ex}"]
    if not arms["roots_identical"]:
        failures.append("state roots diverged across commit arms")
    reduction = arms.get("hash_reduction", 0.0)
    if reduction < args.state_hash_floor:
        failures.append(
            f"state batched hashes/commit reduction {reduction}x "
            f"< {args.state_hash_floor}x floor (delta={args.state_delta} "
            f"on {args.state_keys} keys)")
    soak = run_state_soak(hours=args.state_soak_hours)
    if not soak["deterministic"]:
        failures.append("state soak: same-seed runs not byte-identical")
    if not soak["agree"]:
        failures.append("state soak: honest nodes diverged")
    if not soak["flat_high_water"]:
        failures.append(
            "state soak: bounded-structure high-water grew "
            f"(first hour {soak['first_hour_high_water']} -> last hour "
            f"{soak['last_hour_high_water']})")
    if soak["throughput_drift"] >= args.state_drift_tolerance:
        failures.append(
            f"state soak: ordered-throughput drift "
            f"{soak['throughput_drift']:.1%} >= "
            f"{args.state_drift_tolerance:.0%} first-vs-last hour")
    record = {
        "hash_reduction": reduction,
        "hash_floor": args.state_hash_floor,
        "roots_identical": arms["roots_identical"],
        "final_root": arms["final_root"],
        "arms": arms["arms"],
        "populate_s": arms["populate_s"],
        "n_keys": arms["n_keys"],
        "delta": arms["delta"],
        "windows": arms["windows"],
        "soak": {k: soak[k] for k in (
            "hours", "arrivals", "ordered_total", "hourly_ordered",
            "throughput_drift", "flat_high_water",
            "first_hour_high_water", "last_hour_high_water",
            "cache_hit_rate", "hashes_total", "deterministic", "agree",
            "fingerprint", "wall_s")},
    }
    return record, failures


def geo_gate(args) -> "tuple[dict, list]":
    """Planet-scale read fabric gate: a 3-region seeded pool serves a
    region-spread read storm twice on the same seed — once through
    region-local edge proof caches, once with every read paying the WAN
    trip to the origin validator. Passes when (1) the edge arm serves
    >= ``--geo-hit-floor`` of reads region-locally at intra-band p99
    while the no-edge arm's non-home regions pay the WAN band; (2) the
    edge serve path performs ZERO pairing checks (clients amortize one
    full verify per trusted window); (3) every reply in BOTH arms
    passes offline client verification; (4) ordered/journey/shed
    fingerprints are bit-identical between arms (the fabric never
    touches the pool's RNG or timer); (5) two same-seed edge runs
    produce byte-identical records."""
    from indy_plenum_tpu.observability.causal import journey_summary
    from indy_plenum_tpu.proofs.edge_cache import (
        EdgeProofCache,
        GeoReadFabric,
    )

    def run(use_edges: bool) -> dict:
        config = getConfig({
            "CHK_FREQ": 5, "LOG_SIZE": 15,
            "Max3PCBatchSize": 1, "Max3PCBatchWait": 0.05,
            "RegionCount": 3,
        })
        pool = SimPool(4, seed=args.seed, config=config,
                       real_execution=True, bls=True, trace=True)
        for i in range(8):
            pool.submit_request(i, region=i % 3)
        deadline = time.monotonic() + 240
        while (min(len(nd.ordered_digests) for nd in pool.nodes) < 8
               or pool.nodes[0].proof_cache.current() is None) \
                and time.monotonic() < deadline:
            pool.run_for(0.5)
        assert pool.honest_nodes_agree()
        assert pool.nodes[0].proof_cache.current() is not None, \
            "no proof window stabilized for the edge tier"
        origin = pool.make_read_service("node0", mode="host")
        entry = origin.proof_cache.current()
        pool_keys = {n: pk
                     for n, (kp, pk, pop) in pool.bls_keys.items()}
        edges = {}
        if use_edges:
            for i in range(entry.tree_size):
                origin.submit(i)
            replies = origin.drain()
            edges = {r: EdgeProofCache(
                region=r, clock=pool.timer.get_current_time)
                for r in range(3)}
            for edge in edges.values():
                edge.replicate(entry.window, replies)
        fabric = GeoReadFabric(
            origin, pool.region_matrix, pool_keys, min_participants=3,
            n_regions=3, origin_region=0, edges=edges, seed=args.seed,
            clock=pool.timer.get_current_time)
        for wave in range(3):
            for client in range(60):
                fabric.submit(client,
                              (7 * client + wave) % entry.tree_size)
            served = fabric.drain()
            assert len(served) == 60, (wave, len(served))
            pool.run_for(1.0)
        counters = fabric.counters()
        js = journey_summary(pool.trace.events())
        # deterministic by construction: virtual clock + the fabric's
        # dedicated seeded RNG — no wall fields, so the whole record is
        # byte-comparable across same-seed runs
        return {
            "edges": bool(use_edges),
            "fabric": counters,
            "ordered_hash": pool.ordered_hash(),
            "journey_hash": js["journey_hash"],
            "shed_hash": origin.shed_hash(),
        }

    serving = run(use_edges=True)
    replay = run(use_edges=True)
    plain = run(use_edges=False)
    failures = []
    fb = serving["fabric"]
    if fb["edge_hit_rate"] < args.geo_hit_floor:
        failures.append(
            f"edge hit rate {fb['edge_hit_rate']} below floor "
            f"{args.geo_hit_floor} (reads leaking to the origin)")
    if fb["edge_serve_pairings"] != 0:
        failures.append(
            f"edge serve path performed {fb['edge_serve_pairings']} "
            "pairing checks (must be lookups — zero pairings)")
    if fb["verify_failures"] or plain["fabric"]["verify_failures"]:
        failures.append("replies failed offline client verification")
    intra_hi = 0.05  # the pool's intra-region band ceiling
    wan_floor = getConfig().RegionWanMinLatency
    for region, block in fb["regions"].items():
        if block["latency_p99"] > intra_hi:
            failures.append(
                f"edge arm region {region} read p99 "
                f"{block['latency_p99']} above the intra band "
                f"{intra_hi} (edge tier not region-local)")
    for region in ("1", "2"):
        p99 = plain["fabric"]["regions"][region]["latency_p99"]
        if p99 < wan_floor:
            failures.append(
                f"no-edge arm region {region} read p99 {p99} under the "
                f"WAN floor {wan_floor} (baseline not paying the WAN)")
    for key in ("ordered_hash", "journey_hash", "shed_hash"):
        if serving[key] != plain[key]:
            failures.append(
                f"{key} diverged between the edge and no-edge arms "
                "(the read fabric perturbed the write planes)")
    deterministic = (json.dumps(serving, sort_keys=True)
                     == json.dumps(replay, sort_keys=True))
    if not deterministic:
        failures.append("two same-seed edge runs were not "
                        "byte-identical")
    record = {
        "edge": serving,
        "no_edge": plain,
        "hit_floor": args.geo_hit_floor,
        "deterministic": deterministic,
        "wan_over_edge_p99": round(
            max(plain["fabric"]["regions"][r]["latency_p99"]
                for r in ("1", "2"))
            / max(b["latency_p99"] for b in fb["regions"].values()), 2),
    }
    return record, failures


def residency_gate(args) -> "tuple[dict, list]":
    """Multi-tick residency + rebalancing gate (ISSUE 19): on the SAME
    n=16/k=6 workload and seed,

    1. the resident arm (``--residency-depth`` ring slots) must order
       digests bit-identical to the per-tick arm — residency changes
       WHEN the host looks, never what the pool orders;
    2. its device dispatches per ordered batch must sit under
       ``--residency-dispatch-budget`` (1.0 — fewer than one fused
       step per ordered batch, cold start included) AND the ring must
       actually defer readbacks (a silently per-tick run is vacuous);
    3. ordered/sim-second must stay within ``--residency-tolerance``
       of the per-tick arm (generous: a short cold run quantizes sim
       time to whole ticks, so deferring the final readback by one
       tick legitimately moves the ratio);
    4. the deterministic rebalance law must un-skew a synthetic hot
       shard: skew 8:1 over 4 member blocks with threshold 2.0 plans a
       sub-block rotation whose predicted hottest block drops below
       the threshold;
    5. a forced mid-run rebalance on a 4-way member mesh (plane
       migration at a checkpoint-boundary barrier, host mirrors
       rotated) must leave the ordered digests bit-identical to the
       never-rebalanced same-seed arm.
    """
    from indy_plenum_tpu.tpu.quorum import make_fabric_mesh
    from indy_plenum_tpu.tpu.rebalance import RebalancePolicy

    failures = []
    per_tick = measure(args.sharded_nodes, args.sharded_instances,
                       args.residency_batches, args.batch_size,
                       args.tick, seed=args.seed)
    resident = measure(args.sharded_nodes, args.sharded_instances,
                       args.residency_batches, args.batch_size,
                       args.tick, seed=args.seed,
                       resident_depth=args.residency_depth)
    if resident["ordered_hash"] != per_tick["ordered_hash"]:
        failures.append("resident ordered digests diverge from the "
                        "per-tick run (residency changed semantics)")
    r_pb = resident["device_dispatches_per_ordered_batch"]
    if r_pb > args.residency_dispatch_budget:
        failures.append(
            f"resident dispatches/batch {r_pb} over budget "
            f"{args.residency_dispatch_budget}")
    res = resident.get("residency") or {}
    if not res.get("readbacks_deferred"):
        failures.append("resident arm deferred no readbacks — the ring "
                        "silently ran per-tick (gate vacuous)")
    tol = args.residency_tolerance
    p_tps = per_tick["ordered_per_sim_second"] or 0.0
    r_tps = resident["ordered_per_sim_second"] or 0.0
    if r_tps < p_tps * (1.0 - tol):
        failures.append(f"resident ordered/sim-sec {r_tps} regresses "
                        f"per-tick {p_tps} beyond {tol:.0%}")

    # the deterministic un-skew law on a synthetic hot shard: one block
    # 8x hotter than the rest must plan a sub-block rotation that
    # splits its heat below the threshold
    policy = RebalancePolicy(m_shards=4, shard_rows=2, threshold=2.0,
                             dwell=2)
    hot = [8.0, 1.0, 1.0, 1.0]
    rows = 0
    for _ in range(policy.dwell + 1):
        rows = policy.observe(hot)
        if rows:
            break
    pre_skew = policy.skew(policy.block_heat(hot))
    post_heat = _predicted_heat(policy.block_heat(hot), rows,
                                policy.shard_rows)
    post_skew = policy.skew(post_heat)
    if not rows:
        failures.append(f"skew {pre_skew:.2f} over threshold "
                        f"{policy.threshold} never planned a rotation")
    elif post_skew >= min(pre_skew, policy.threshold):
        failures.append(
            f"planned rotation ({rows} rows) does not un-skew the hot "
            f"shard: predicted skew {post_skew:.2f} (pre {pre_skew:.2f},"
            f" threshold {policy.threshold})")

    # forced plane migration mid-run on the 4-way member mesh: the
    # barrier drains the ring, the planes rotate, the host placement
    # map rewrites — and the ordering must not notice
    devices = jax.devices()
    if len(devices) < 4:
        failures.append("residency gate needs 4 host devices for the "
                        f"rebalance arm (have {len(devices)})")
        rebalanced = baseline = {"skipped": "needs 4 devices"}
    else:
        mesh = make_fabric_mesh(devices, (4,))
        window = {"CHK_FREQ": 5, "LOG_SIZE": 15}
        baseline = measure(8, 2, args.residency_batches,
                           args.batch_size, args.tick, seed=args.seed,
                           mesh=mesh, resident_depth=args.residency_depth,
                           overrides=window)
        rebalanced = measure(8, 2, args.residency_batches,
                             args.batch_size, args.tick, seed=args.seed,
                             mesh=mesh,
                             resident_depth=args.residency_depth,
                             overrides={**window,
                                        "RebalanceForceTick": 12})
        moved = rebalanced.get("residency") or {}
        if not moved.get("rebalances"):
            failures.append("forced rebalance never executed (no "
                            "checkpoint barrier reached, or the policy "
                            "never planned)")
        elif not moved.get("row_shift"):
            failures.append("rebalance executed but the placement map "
                            "never rotated")
        if rebalanced["ordered_hash"] != baseline["ordered_hash"]:
            failures.append("rebalanced ordered digests diverge from "
                            "the never-rebalanced arm (plane migration "
                            "changed semantics)")

    record = {
        "per_tick": per_tick,
        "resident": resident,
        "residency_depth": args.residency_depth,
        "residency_dispatch_budget": args.residency_dispatch_budget,
        "residency_tolerance": tol,
        "digests_match":
            resident["ordered_hash"] == per_tick["ordered_hash"],
        "dispatch_ratio": round(
            r_pb / per_tick["device_dispatches_per_ordered_batch"], 3)
        if per_tick["device_dispatches_per_ordered_batch"] else None,
        "unskew_law": {
            "planned_rows": rows,
            "pre_skew": round(pre_skew, 3),
            "predicted_post_skew": round(post_skew, 3),
            "threshold": policy.threshold,
        },
        "rebalance_baseline": baseline,
        "rebalance_forced": rebalanced,
    }
    return record, failures


def soak_gate(args) -> "tuple[dict, list]":
    """Virtual-day soak gate (simulation/soak.py, ISSUE 20): the
    24-simulated-hour diurnal arc on a real-execution pool with the
    chaos folded into ONE day — a GC-crossing crash + catchup at hour
    6, a view change at hour 12, and (on hosts with >= 4 XLA devices,
    where the pool runs tick-batched on a quorum fabric) one forced
    shard rebalance — judged entirely by the telemetry plane:

    1. resource high-water FLAT after hour 1 (tail windows vs the
       baseline that contains the whole chaos arc);
    2. hour-1 -> hour-24 ordered-throughput drift < ``--soak-drift-
       tolerance`` (default 1%: the deterministic arrival grid makes
       both hours' offered load byte-identical, so drift is the
       system's);
    3. ZERO unexplained anomalies (chaos-window anomalies are
       classified explained; bound violations never are) and zero
       bound violations;
    4. the whole artifact — ordered hash, state head, hourly tallies,
       telemetry hash chain — byte-identical across two same-seed runs;
    5. non-vacuity: a short arm with a deliberately registered leaking
       resource MUST trip the leak law (the detector is proven live,
       not just silent).
    """
    from indy_plenum_tpu.simulation.soak import run_day_soak

    failures = []
    soak = run_day_soak(hours=args.soak_hours, rate=args.soak_rate,
                        seed=args.soak_seed, repeats=2)
    if not soak["deterministic"]:
        failures.append("day soak: same-seed runs not byte-identical")
    if not soak["agree"]:
        failures.append("day soak: ledgers diverged across the chaos arc")
    if not soak["flat_high_water"]:
        grew = {n: (soak["first_high_water"][n],
                    soak["last_high_water"][n])
                for n in soak["first_high_water"]
                if soak["last_high_water"][n]
                > soak["first_high_water"][n] * 1.2}
        failures.append(
            f"day soak: resource high-water grew past hour 1: {grew}")
    if soak["throughput_drift"] >= args.soak_drift_tolerance:
        failures.append(
            f"day soak: ordered-throughput drift "
            f"{soak['throughput_drift']:.2%} >= "
            f"{args.soak_drift_tolerance:.0%} hour-1 vs hour-24")
    if soak["anomalies_unexplained"]:
        failures.append(
            f"day soak: {soak['anomalies_unexplained']} unexplained "
            f"telemetry anomalies: {soak['unexplained']}")
    if soak["bound_violations"]:
        failures.append(
            f"day soak: declared bounds violated: "
            f"{soak['bound_violations']}")
    chaos = soak["chaos"]
    if chaos["crash"] is not None and not chaos["crash"]["ok"]:
        failures.append(
            f"day soak: crash/catchup leg failed: {chaos['crash']}")
    if chaos["view_change"] is not None \
            and not chaos["view_change"]["ok"]:
        failures.append(
            f"day soak: view-change leg failed: {chaos['view_change']}")
    if chaos["rebalance"]["armed"] and not chaos["rebalance"]["ok"]:
        failures.append(
            f"day soak: forced-rebalance leg never planned: "
            f"{chaos['rebalance']}")

    # non-vacuity: the leak law must CATCH a planted leak — otherwise
    # "zero anomalies" above proves nothing. EVERY chaos leg is pushed
    # out of range (rebalance_tick=0 included: a forced rotation's
    # explained-anomaly window would swallow the planted leak's)
    leak = run_day_soak(hours=4.0, rate=args.soak_rate,
                        seed=args.soak_seed, crash_hour=99.0,
                        vc_hour=99.0, rebalance_tick=0, repeats=1,
                        synthetic_leak=True)
    caught = [a for a in leak["unexplained"]
              if a["law"] == "resource_leak"
              and a.get("resource") == "soak.synthetic_leak"]
    if not caught:
        failures.append(
            "day soak: the leak law never caught the planted "
            "synthetic leak (detector is vacuous) — anomalies: "
            f"{leak['unexplained']}")

    record = {
        "soak": {k: soak[k] for k in (
            "hours", "rate", "seed", "device_arm", "arrivals",
            "ordered_total", "hourly_ordered", "throughput_drift",
            "flat_high_water", "windows", "anomalies",
            "anomalies_unexplained", "unexplained", "bound_violations",
            "chaos", "agree", "telemetry_hash", "fingerprint",
            "deterministic", "wall_s")},
        "drift_tolerance": args.soak_drift_tolerance,
        "rebalance_leg": ("ran" if chaos["rebalance"]["armed"]
                          else "skipped (needs >= 4 XLA devices)"),
        "leak_arm": {
            "caught": bool(caught),
            "caught_at_window": caught[0]["window"] if caught else None,
            "anomalies": leak["anomalies"],
            "wall_s": leak["wall_s"],
        },
    }
    return record, failures


def _predicted_heat(heat, rows, shard_rows):
    """The policy's own placement model: rotating by ``rows`` device
    rows splits each block's load proportionally between the blocks
    its rows land on."""
    n_blocks = len(heat)
    b0, r = divmod(rows, shard_rows)
    return [(shard_rows - r) / shard_rows * heat[(k - b0) % n_blocks]
            + r / shard_rows * heat[(k - b0 - 1) % n_blocks]
            for k in range(n_blocks)]


# gate registry (--list-gates / --only): name -> (argparse dest of the
# skip flag, one-line description). The core dispatch-budget measurement
# always runs — it is the baseline every budget compares against.
GATES = {
    "static": ("no_static_gate",
               "determinism & hot-path static analysis (zero "
               "unsuppressed, 2-run findings_hash identity)"),
    "governor": ("no_governor_gates",
                 "bursty static-vs-adaptive tick comparison"),
    "sharded": ("no_sharded_gate", "1-device vs mesh-sharded identity"),
    "fabric": ("no_fabric_gate", "1-axis vs 2-axis quorum fabric"),
    "trace": ("no_trace_gate", "flight-recorder overhead + identity"),
    "readback": ("no_readback_gate", "device-eval vs host-eval readback"),
    "ingress": ("no_ingress_gate", "open-loop saturation/admission"),
    "overload": ("no_overload_gate",
                 "closed-loop retry storm: goodput floor, no metastable "
                 "collapse, byte-identical replay, catchup under "
                 "saturation with seeder throttling"),
    "proof": ("no_proof_gate", "state-proof plane (BLS, zero pairings)"),
    "catchup": ("no_catchup_gate", "chaos-hardened catchup recovery"),
    "lanes": ("no_lanes_gate",
              "multi-lane ordering: 1-vs-4-lane scaling floor, "
              "byte-identical replay, lane+barrier journey coverage"),
    "latency": ("no_latency_gate",
                "causal journeys: byte-identical tables, zero orphans, "
                "e2e p99 budget (pool-wide + per-lane at 4 lanes)"),
    "state": ("no_state_gate",
              "batched state commit: root bit-identity across "
              "sequential/host/auto arms, >=3x hashes/commit reduction "
              "at delta=256 on 100k keys, flat+deterministic "
              "virtual-time soak"),
    "geo": ("no_geo_gate",
            "planet-scale read fabric: >=90% edge-local reads at intra "
            "p99 vs same-seed WAN baseline, zero serve-path pairings, "
            "bit-identical write fingerprints, deterministic replay"),
    "residency": ("no_residency_gate",
                  "multi-tick device residency + rebalancing: per-tick "
                  "digest identity, <=1 dispatch/ordered batch, "
                  "synthetic un-skew law, forced plane migration with "
                  "unchanged digests"),
    "soak": ("no_soak_gate",
             "virtual-day soak: 24 simulated diurnal hours with one "
             "chaos arc (GC-crossing crash+catchup, view change, "
             "forced rebalance), flat resource high-water after hour "
             "1, <1% hour-1-vs-24 ordered drift, zero unexplained "
             "anomalies, byte-identical telemetry hash across two "
             "same-seed runs, leak-law non-vacuity"),
}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--instances", type=int, default=1)
    ap.add_argument("--batches", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=10)
    ap.add_argument("--tick", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--budget-per-batch", type=float, default=25.0,
                    help="max device dispatches per ordered batch")
    ap.add_argument("--budget-per-message", type=float, default=0.25,
                    help="max device dispatches per delivered message")
    ap.add_argument("--no-governor-gates", action="store_true",
                    help="skip the bursty static-vs-adaptive comparison")
    ap.add_argument("--no-sharded-gate", action="store_true",
                    help="skip the 1-device vs mesh-sharded comparison")
    ap.add_argument("--no-trace-gate", action="store_true",
                    help="skip the flight-recorder overhead comparison")
    ap.add_argument("--no-ingress-gate", action="store_true",
                    help="skip the open-loop saturation/admission gate")
    ap.add_argument("--no-overload-gate", action="store_true",
                    help="skip the overload robustness gate (flash-"
                         "crowd retry storm goodput/recovery floors, "
                         "byte-identical shed/retry/ordered replay, "
                         "catchup-under-saturation chaos verdicts)")
    ap.add_argument("--overload-goodput-floor", type=float, default=0.70,
                    help="min retry-storm ordered count as a fraction "
                         "of the open-loop arm's")
    ap.add_argument("--overload-recovery-tolerance", type=float,
                    default=0.30,
                    help="max fractional post-spike ordered-rate "
                         "shortfall vs pre-spike (metastable-collapse "
                         "detector) either overload arm may show")
    ap.add_argument("--no-readback-gate", action="store_true",
                    help="skip the device-eval vs host-eval ordering "
                         "fast path comparison")
    ap.add_argument("--no-fabric-gate", action="store_true",
                    help="skip the 1-axis vs 2-axis quorum-fabric "
                         "comparison")
    ap.add_argument("--no-proof-gate", action="store_true",
                    help="skip the state-proof plane gate (ordered-hash "
                         "identity, zero serve-path pairings, client "
                         "verify, batched-verify speedup)")
    ap.add_argument("--no-catchup-gate", action="store_true",
                    help="skip the chaos-hardened catchup gate "
                         "(GC-crossing crash/restart verdicts, ledger "
                         "bit-identity, byte-identical replay, byzantine "
                         "seeder rejection)")
    ap.add_argument("--no-lanes-gate", action="store_true",
                    help="skip the multi-lane ordering gate (1-vs-4-"
                         "lane scaling floor, byte-identical replay, "
                         "lane + barrier-hop journey coverage)")
    ap.add_argument("--lanes-speedup-floor", type=float, default=3.0,
                    help="min 4-lane vs 1-lane ordered/sim-second "
                         "ratio the lanes gate accepts")
    ap.add_argument("--lanes-nodes", type=int, default=4,
                    help="validators PER LANE for the lanes gate")
    ap.add_argument("--lanes-txns", type=int, default=40,
                    help="routed txns per lane for the lanes gate")
    ap.add_argument("--no-latency-gate", action="store_true",
                    help="skip the causal-journey latency gate "
                         "(byte-identical journey tables, zero orphan "
                         "spans, traced-vs-untraced ordered_hash, e2e "
                         "p99 budget)")
    ap.add_argument("--no-static-gate", action="store_true",
                    help="skip the determinism & hot-path static-"
                         "analysis gate (zero unsuppressed findings, "
                         "byte-stable findings_hash across two runs)")
    ap.add_argument("--no-state-gate", action="store_true",
                    help="skip the batched state-commit gate (root "
                         "bit-identity across arms, hashes/commit "
                         "reduction floor, virtual-time soak flatness)")
    ap.add_argument("--state-keys", type=int, default=100_000,
                    help="resident SMT keys for the state gate's "
                         "commit arms")
    ap.add_argument("--state-delta", type=int, default=256,
                    help="writes per window commit for the state gate")
    ap.add_argument("--state-windows", type=int, default=20,
                    help="window commits per arm for the state gate")
    ap.add_argument("--state-hash-floor", type=float, default=3.0,
                    help="min sequential/batched hashes-per-commit "
                         "ratio the state gate accepts")
    ap.add_argument("--state-soak-hours", type=float, default=2.0,
                    help="simulated hours for the state gate's "
                         "virtual-time soak arm")
    ap.add_argument("--state-drift-tolerance", type=float, default=0.05,
                    help="max first-vs-last simulated-hour ordered-"
                         "throughput drift the soak arm accepts")
    ap.add_argument("--no-geo-gate", action="store_true",
                    help="skip the planet-scale read fabric gate "
                         "(edge hit-rate floor at intra-band p99 vs "
                         "the same-seed WAN baseline, zero serve-path "
                         "pairings, bit-identical write fingerprints "
                         "between arms, byte-identical replay)")
    ap.add_argument("--no-residency-gate", action="store_true",
                    help="skip the multi-tick residency + rebalancing "
                         "gate (per-tick digest identity, dispatch "
                         "budget, un-skew law, forced plane migration)")
    ap.add_argument("--residency-depth", type=int, default=4,
                    help="ring depth for the resident arm")
    ap.add_argument("--residency-batches", type=int, default=6,
                    help="ordered batches per residency-gate arm (long "
                         "enough to amortize the cold-start consumes "
                         "the gate deliberately counts)")
    ap.add_argument("--residency-dispatch-budget", type=float,
                    default=1.0,
                    help="max device dispatches per ordered batch on "
                         "the resident arm (cold run, n=16/k=6)")
    ap.add_argument("--residency-tolerance", type=float, default=0.5,
                    help="allowed resident ordered/sim-second slack vs "
                         "the per-tick arm (generous: short cold runs "
                         "quantize sim time to whole ticks)")
    ap.add_argument("--geo-hit-floor", type=float, default=0.90,
                    help="min fraction of storm reads the edge arm "
                         "must serve from region-local edge caches")
    ap.add_argument("--no-soak-gate", action="store_true",
                    help="skip the virtual-day soak gate (24 simulated "
                         "diurnal hours with one chaos arc, judged by "
                         "the telemetry plane; two same-seed runs + a "
                         "leak-law non-vacuity arm)")
    ap.add_argument("--soak-hours", type=float, default=None,
                    help="virtual hours for the day soak (default: the "
                         "SoakHours config knob, 24)")
    ap.add_argument("--soak-rate", type=float, default=None,
                    help="base arrivals/sim-second for the soak's "
                         "diurnal grid (default: the SoakRate knob)")
    ap.add_argument("--soak-seed", type=int, default=17,
                    help="seed for the day soak's two same-seed runs")
    ap.add_argument("--soak-drift-tolerance", type=float, default=0.01,
                    help="max hour-1 vs hour-24 ordered-throughput "
                         "drift for the day soak")
    ap.add_argument("--only", default=None, metavar="GATE[,GATE]",
                    help="run ONLY the named gate(s) — e.g. '--only "
                         "latency' instead of stacking nine --no-*-gate "
                         "flags; see --list-gates for names. The core "
                         "dispatch-budget measurement always runs")
    ap.add_argument("--list-gates", action="store_true",
                    help="print the gate names --only accepts and exit")
    ap.add_argument("--e2e-budget", type=float, default=5.0,
                    help="max e2e p99 (client ingress -> executed, "
                         "VIRTUAL sim-seconds) the latency gate accepts")
    ap.add_argument("--proof-speedup-floor", type=float, default=2.0,
                    help="min batch-64 multi-sig verify speedup vs the "
                         "per-root path")
    ap.add_argument("--fabric-tolerance", type=float, default=0.10,
                    help="max fractional dispatches/ordered-batch and "
                         "bytes/readback drift the 2-axis fabric run "
                         "may show vs the 1-axis mesh run")
    ap.add_argument("--readback-budget", type=float, default=32768,
                    help="max device->host bytes per readback the "
                         "compact (device-eval) run may average")
    ap.add_argument("--readback-tolerance", type=float, default=0.05,
                    help="max fractional ordered/sim-second regression "
                         "device eval may show vs the host_eval fallback")
    ap.add_argument("--ingress-capacity", type=int, default=16,
                    help="bounded auth-queue capacity for the ingress "
                         "gate (small on purpose: overload must engage "
                         "within the short gate window)")
    ap.add_argument("--ingress-rate", type=float, default=700.0,
                    help="saturated arrivals/sim-second (must overload "
                         "the queue at the starting tick)")
    ap.add_argument("--ingress-unsat-rate", type=float, default=120.0,
                    help="unsaturated baseline arrivals/sim-second")
    ap.add_argument("--ingress-duration", type=float, default=1.0,
                    help="arrival window, sim-seconds")
    ap.add_argument("--ingress-tolerance", type=float, default=0.10,
                    help="max fractional ordered/sim-second collapse the "
                         "saturated run may show vs the unsaturated run")
    ap.add_argument("--trace-tolerance", type=float, default=0.05,
                    help="max fractional ordered/sim-second regression "
                         "the recorder-enabled run may show vs disabled")
    ap.add_argument("--mesh-devices", type=int, default=4,
                    help="host mesh width for the sharded gate (the "
                         "script provisions virtual CPU devices via "
                         "XLA_FLAGS at import; widths beyond that need "
                         "the flag preset in the environment)")
    ap.add_argument("--sharded-nodes", type=int, default=16,
                    help="pool size for the sharded gate")
    ap.add_argument("--sharded-instances", type=int, default=6,
                    help="RBFT instances for the sharded gate")
    ap.add_argument("--sharded-tolerance", type=float, default=0.10,
                    help="max fractional dispatches/ordered-batch drift "
                         "the mesh run may show vs the 1-device run")
    ap.add_argument("--occupancy-floor", type=float, default=0.01,
                    help="min steady-state flush occupancy for the "
                         "adaptive bursty run")
    ap.add_argument("--adaptive-tolerance", type=float, default=0.05,
                    help="max fractional regression the adaptive run may "
                         "show vs the static run (dispatches/batch and "
                         "ordered/sim-second)")
    ap.add_argument("--json", action="store_true",
                    help="emit the measurement as one JSON line")
    args = ap.parse_args()

    if args.list_gates:
        for name, (_dest, desc) in GATES.items():
            print(f"{name:10s} {desc}")
        return 0
    if args.only is not None:
        chosen = [g.strip() for g in args.only.split(",") if g.strip()]
        unknown = [g for g in chosen if g not in GATES]
        if unknown:
            raise SystemExit(
                f"--only: unknown gate(s) {', '.join(unknown)} "
                f"(see --list-gates)")
        for name, (dest, _desc) in GATES.items():
            setattr(args, dest, name not in chosen)

    result = measure(args.nodes, args.instances, args.batches,
                     args.batch_size, args.tick, seed=args.seed)
    per_batch = result["device_dispatches_per_ordered_batch"]
    per_msg = result["device_dispatches_per_delivered_message"]
    result["budget_per_batch"] = args.budget_per_batch
    result["budget_per_message"] = args.budget_per_message
    over = []
    if per_batch > args.budget_per_batch:
        over.append(f"dispatches/batch {per_batch} > {args.budget_per_batch}")
    if per_msg > args.budget_per_message:
        over.append(f"dispatches/message {per_msg} "
                    f"> {args.budget_per_message}")
    if not args.no_static_gate:
        record, failures = static_gate(args)
        result["static_gate"] = record
        over.extend(failures)
    if not args.no_governor_gates:
        record, failures = governor_gates(args)
        result["governor_gate"] = record
        over.extend(failures)
    sharded_single = None
    sharded_mesh = None
    if not args.no_sharded_gate:
        record, failures = sharded_gates(args)
        result["sharded_gate"] = record
        over.extend(failures)
        # same args as the tracing gate's untraced baseline — reuse it
        sharded_single = record.get("single_device")
        # ... and as the fabric gate's 1-axis arm
        sharded_mesh = record.get("mesh_sharded")
    if not args.no_fabric_gate:
        record, failures = fabric_gate(args, base=sharded_mesh)
        result["fabric_gate"] = record
        over.extend(failures)
    traced_run = None
    if not args.no_trace_gate:
        record, failures = tracing_gate(args, base=sharded_single)
        result["tracing_gate"] = record
        over.extend(failures)
        # same args as the latency gate's first traced arm — reuse it
        traced_run = record.get("traced")
    laned_run = None
    if not args.no_lanes_gate:
        record, failures = lanes_gate(args)
        result["lanes_gate"] = record
        over.extend(failures)
        # same args as the latency gate's 4-lane rider — reuse it
        laned_run = record.get("four_lane")
    if not args.no_latency_gate:
        record, failures = latency_gate(args, traced=traced_run,
                                        base=sharded_single,
                                        laned=laned_run)
        result["latency_gate"] = record
        over.extend(failures)
    if not args.no_readback_gate:
        record, failures = readback_gate(args, base=sharded_single)
        result["readback_gate"] = record
        over.extend(failures)
    if not args.no_ingress_gate:
        record, failures = ingress_gate(args)
        result["ingress_gate"] = record
        over.extend(failures)
    if not args.no_overload_gate:
        record, failures = overload_gate(args)
        result["overload_gate"] = record
        over.extend(failures)
    if not args.no_proof_gate:
        record, failures = proof_gate(args)
        result["proof_gate"] = record
        over.extend(failures)
    if not args.no_catchup_gate:
        record, failures = catchup_gate(args)
        result["catchup_gate"] = record
        over.extend(failures)
    if not args.no_state_gate:
        record, failures = state_gate(args)
        result["state_gate"] = record
        over.extend(failures)
    if not args.no_geo_gate:
        record, failures = geo_gate(args)
        result["geo_gate"] = record
        over.extend(failures)
    if not args.no_residency_gate:
        record, failures = residency_gate(args)
        result["residency_gate"] = record
        over.extend(failures)
    if not args.no_soak_gate:
        record, failures = soak_gate(args)
        result["soak_gate"] = record
        over.extend(failures)
    result["verdict"] = "FAIL: " + "; ".join(over) if over else "PASS"
    if args.json:
        print(json.dumps(result, separators=(",", ":")))
    else:
        for key, value in result.items():
            print(f"{key}: {value}")
    return 1 if over else 0


if __name__ == "__main__":
    sys.exit(main())
