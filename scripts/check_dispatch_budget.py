"""Dispatch-budget gate: fail if the tick barrier stops amortizing.

Runs a short deterministic ``SimPool`` round through the tick-batched
dispatch plane and computes ``device_dispatches_per_ordered_batch`` (and
dispatches per delivered message). Exit status 1 if either exceeds its
budget — callable from the bench loop, chaos runs, or CI, so a regression
that quietly reverts to per-message flushing turns red instead of slow.

Usage:
    python scripts/check_dispatch_budget.py                # defaults
    python scripts/check_dispatch_budget.py --nodes 16 --instances 6 \
        --budget-per-batch 40 --json
"""
import argparse
import json
import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from indy_plenum_tpu.common.metrics_collector import MetricsName  # noqa: E402
from indy_plenum_tpu.config import getConfig  # noqa: E402
from indy_plenum_tpu.simulation.pool import SimPool  # noqa: E402


def measure(n_nodes: int, instances: int, batches: int, batch_size: int,
            tick_interval: float, seed: int = 11) -> dict:
    """DELIBERATELY a cold run, unlike profile_rbft's warm-up-excluded
    measurement: the gate counts every dispatch from pool construction on
    (cold-start/compile steps included), because the budget protects the
    whole loop's dispatch discipline, not the steady-state ratio. Budgets
    are calibrated with ~10x headroom over the cold numbers."""
    config = getConfig({
        "Max3PCBatchSize": batch_size,
        "Max3PCBatchWait": 0.05,
        "QuorumTickInterval": tick_interval,
    })
    pool = SimPool(n_nodes=n_nodes, seed=seed, config=config,
                   device_quorum=True, shadow_check=False,
                   num_instances=instances)

    def min_ordered():
        return min(len(nd.ordered_digests) for nd in pool.nodes)

    target = batches * batch_size
    for i in range(target):
        pool.submit_request(i)
    deadline = time.monotonic() + 240
    while min_ordered() < target and time.monotonic() < deadline:
        pool.run_for(0.5)
    assert min_ordered() >= target, f"stalled at {min_ordered()}/{target}"
    assert pool.honest_nodes_agree()

    dispatches = pool.vote_group.flushes
    delivered = pool.network.sent
    occ = pool.metrics.stat(MetricsName.DEVICE_FLUSH_OCCUPANCY)
    per_tick = pool.metrics.stat(MetricsName.DEVICE_DISPATCHES_PER_TICK)
    return {
        "n_nodes": n_nodes,
        "instances": instances,
        "txns_ordered": min_ordered(),
        "ordered_batches": batches,
        "device_dispatches": dispatches,
        "delivered_messages": delivered,
        "device_dispatches_per_ordered_batch": round(
            dispatches / batches, 2),
        "device_dispatches_per_delivered_message": round(
            dispatches / delivered, 4) if delivered else 0.0,
        "flush_occupancy_avg": round(occ.avg, 4) if occ else None,
        "dispatches_per_tick_max": per_tick.max if per_tick else None,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--instances", type=int, default=1)
    ap.add_argument("--batches", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=10)
    ap.add_argument("--tick", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--budget-per-batch", type=float, default=25.0,
                    help="max device dispatches per ordered batch")
    ap.add_argument("--budget-per-message", type=float, default=0.25,
                    help="max device dispatches per delivered message")
    ap.add_argument("--json", action="store_true",
                    help="emit the measurement as one JSON line")
    args = ap.parse_args()

    result = measure(args.nodes, args.instances, args.batches,
                     args.batch_size, args.tick, seed=args.seed)
    per_batch = result["device_dispatches_per_ordered_batch"]
    per_msg = result["device_dispatches_per_delivered_message"]
    result["budget_per_batch"] = args.budget_per_batch
    result["budget_per_message"] = args.budget_per_message
    over = []
    if per_batch > args.budget_per_batch:
        over.append(f"dispatches/batch {per_batch} > {args.budget_per_batch}")
    if per_msg > args.budget_per_message:
        over.append(f"dispatches/message {per_msg} "
                    f"> {args.budget_per_message}")
    result["verdict"] = "FAIL: " + "; ".join(over) if over else "PASS"
    if args.json:
        print(json.dumps(result, separators=(",", ":")))
    else:
        for key, value in result.items():
            print(f"{key}: {value}")
    return 1 if over else 0


if __name__ == "__main__":
    sys.exit(main())
