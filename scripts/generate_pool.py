#!/usr/bin/env python3
"""Provision a local validator pool: keys + genesis files.

Usage: python scripts/generate_pool.py DIR [N_NODES] [BASE_PORT] [SEED_HEX]
(reference analog: scripts/generate_indy_pool_transactions)

Secrets land under DIR/keys/ — copy pool_info.json + genesis to every
host, but each keys/<node>.json ONLY to that node's host. SEED_HEX (64
hex chars) makes provisioning reproducible; omit it for fresh randomness.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from indy_plenum_tpu.tools import generate_pool_config  # noqa: E402


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__)
        return 2
    directory = sys.argv[1]
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    base_port = int(sys.argv[3]) if len(sys.argv) > 3 else 9700
    seed = bytes.fromhex(sys.argv[4]) if len(sys.argv) > 4 else None
    info = generate_pool_config(directory, n_nodes=n, base_port=base_port,
                                master_seed=seed)
    print(f"pool of {n} validators provisioned in {directory}")
    for name, rec in sorted(info["nodes"].items()):
        print(f"  {name}: {rec['node_ip']}:{rec['node_port']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
