/* BN254 pairing in C: the native backend for the BLS hot path.
 *
 * The reference stack (hyperledger indy-plenum) delegates BLS to a native
 * Rust library (indy-crypto / ursa, AMCL BN254); this module is the
 * analogous native backend here.  Same tower and the same projective /
 * sparse-line formulas as the pure-Python fast path
 * (indy_plenum_tpu/crypto/bls/bn254_fast.py — derivations documented
 * there), over 4x64-limb Montgomery arithmetic.  The pure-Python
 * bn254.py remains the correctness oracle; tests pin this module
 * against it on scalar muls, pairings and subgroup checks.
 *
 * Interface contract (coarse calls; ints cross as 32-byte big-endian):
 *   g1_mul(xy:bytes64|None, k:bytes32) -> bytes64|None
 *   g2_mul(xyxy:bytes128|None, k:bytes32) -> bytes128|None
 *   g1_sum([bytes64,...]) -> bytes64|None
 *   g2_sum([bytes128,...]) -> bytes128|None
 *   g2_in_subgroup(bytes128) -> bool
 *   multi_pairing([(bytes64|None, bytes128|None), ...]) -> bytes384 (Fp12)
 *   pairing_check([...]) -> bool
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

typedef unsigned __int128 u128;
typedef struct { uint64_t v[4]; } fp;       /* Montgomery form */
typedef struct { fp a, b; } fp2;            /* a + b*i, i^2 = -1 */
typedef struct { fp2 c0, c1, c2; } fp6;     /* Fp2[v]/(v^3 - xi) */
typedef struct { fp6 a, b; } fp12;          /* Fp6[w]/(w^2 - v) */

/* ---- constants (generated; see repo notes) --------------------------- */
static const fp FP_P   = {{0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                           0xb85045b68181585dULL, 0x30644e72e131a029ULL}};
static const fp FP_R1  = {{0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                           0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL}};
static const fp FP_R2  = {{0xf32cfc5b538afa89ULL, 0xb5e71911d44501fbULL,
                           0x47ab1eff0a417ff6ULL, 0x06d89f71cab8351fULL}};
static const uint64_t N0INV = 0x87d20782e4866389ULL;
/* BN parameter u and the ate loop count 6u+2 */
static const uint64_t BN_U = 0x44e992b44a6909f1ULL;
/* 6u+2 = 0x19d797039be763ba8 (65 bits) */
static const uint64_t ATE_LO = 0x9d797039be763ba8ULL;
static const int ATE_BITS = 65; /* including leading 1 bit */

/* ---- fp -------------------------------------------------------------- */

static inline int fp_is_zero(const fp *a) {
    return (a->v[0] | a->v[1] | a->v[2] | a->v[3]) == 0;
}
static inline int fp_eq(const fp *a, const fp *b) {
    return a->v[0] == b->v[0] && a->v[1] == b->v[1]
        && a->v[2] == b->v[2] && a->v[3] == b->v[3];
}
static inline int fp_gte_p(const fp *a) {
    for (int i = 3; i >= 0; i--) {
        if (a->v[i] > FP_P.v[i]) return 1;
        if (a->v[i] < FP_P.v[i]) return 0;
    }
    return 1;
}
static inline void fp_sub_p(fp *a) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a->v[i] - FP_P.v[i] - (uint64_t)borrow;
        a->v[i] = (uint64_t)d;
        borrow = (d >> 64) & 1;  /* 1 if borrowed */
    }
}
static inline void fp_add(fp *r, const fp *a, const fp *b) {
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 s = (u128)a->v[i] + b->v[i] + (uint64_t)carry;
        r->v[i] = (uint64_t)s;
        carry = s >> 64;
    }
    if (carry || fp_gte_p(r)) fp_sub_p(r);
}
static inline void fp_sub(fp *r, const fp *a, const fp *b) {
    u128 borrow = 0;
    fp t;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a->v[i] - b->v[i] - (uint64_t)borrow;
        t.v[i] = (uint64_t)d;
        borrow = (d >> 64) & 1;
    }
    if (borrow) { /* add P back */
        u128 carry = 0;
        for (int i = 0; i < 4; i++) {
            u128 s = (u128)t.v[i] + FP_P.v[i] + (uint64_t)carry;
            t.v[i] = (uint64_t)s;
            carry = s >> 64;
        }
    }
    *r = t;
}
static inline void fp_neg(fp *r, const fp *a) {
    if (fp_is_zero(a)) { *r = *a; return; }
    fp zero = {{0, 0, 0, 0}};
    fp_sub(r, &zero, a);
}

/* Montgomery multiplication, CIOS */
static void fp_mul(fp *r, const fp *a, const fp *b) {
    uint64_t t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 s = (u128)a->v[j] * b->v[i] + t[j] + (uint64_t)carry;
            t[j] = (uint64_t)s;
            carry = s >> 64;
        }
        u128 s = (u128)t[4] + (uint64_t)carry;
        t[4] = (uint64_t)s;
        t[5] = (uint64_t)(s >> 64);
        uint64_t m = t[0] * N0INV;
        carry = 0;
        u128 s0 = (u128)m * FP_P.v[0] + t[0];
        carry = s0 >> 64;
        for (int j = 1; j < 4; j++) {
            u128 sj = (u128)m * FP_P.v[j] + t[j] + (uint64_t)carry;
            t[j - 1] = (uint64_t)sj;
            carry = sj >> 64;
        }
        u128 s4 = (u128)t[4] + (uint64_t)carry;
        t[3] = (uint64_t)s4;
        t[4] = t[5] + (uint64_t)(s4 >> 64);
    }
    fp out = {{t[0], t[1], t[2], t[3]}};
    if (t[4] || fp_gte_p(&out)) fp_sub_p(&out);
    *r = out;
}
static inline void fp_sqr(fp *r, const fp *a) { fp_mul(r, a, a); }

static void fp_from_bytes_be(fp *r, const unsigned char *be32) {
    fp raw;
    for (int i = 0; i < 4; i++) {
        uint64_t w = 0;
        for (int j = 0; j < 8; j++)
            w = (w << 8) | be32[(3 - i) * 8 + j];
        raw.v[i] = w;
    }
    fp_mul(r, &raw, &FP_R2); /* to Montgomery */
}
static void fp_to_bytes_be(unsigned char *be32, const fp *a) {
    fp one = {{1, 0, 0, 0}}, std_;
    fp_mul(&std_, a, &one); /* from Montgomery */
    for (int i = 0; i < 4; i++) {
        uint64_t w = std_.v[i];
        for (int j = 7; j >= 0; j--) {
            be32[(3 - i) * 8 + j] = (unsigned char)(w & 0xFF);
            w >>= 8;
        }
    }
}
/* pow(a, P-2): inversion (exponent fixed) */
static void fp_inv(fp *r, const fp *a) {
    fp e = FP_P;
    /* exponent = P - 2 */
    u128 borrow = 2;
    for (int i = 0; i < 4 && borrow; i++) {
        u128 d = (u128)e.v[i] - (uint64_t)borrow;
        e.v[i] = (uint64_t)d;
        borrow = (d >> 64) & 1;
    }
    fp out = FP_R1, base = *a;
    for (int i = 0; i < 4; i++) {
        uint64_t bits = e.v[i];
        for (int j = 0; j < 64; j++) {
            if (bits & 1) fp_mul(&out, &out, &base);
            fp_sqr(&base, &base);
            bits >>= 1;
        }
    }
    *r = out;
}
static inline void fp_set_small(fp *r, uint64_t x) {
    fp raw = {{x, 0, 0, 0}};
    fp_mul(r, &raw, &FP_R2);
}

/* sqrt via pow(a, (P+1)/4): P = 3 mod 4.  Variable-time is fine — the
 * only caller is hash-to-curve over PUBLIC protocol data. */
static const unsigned char SQRT_EXP_BE[32] = {
    0x0c, 0x19, 0x13, 0x9c, 0xb8, 0x4c, 0x68, 0x0a,
    0x6e, 0x14, 0x11, 0x6d, 0xa0, 0x60, 0x56, 0x17,
    0x65, 0xe0, 0x5a, 0xa4, 0x5a, 0x1c, 0x72, 0xa3,
    0x4f, 0x08, 0x23, 0x05, 0xb6, 0x1f, 0x3f, 0x52};
static void fp_pow_be(fp *r, const fp *a, const unsigned char *e_be32) {
    fp out = FP_R1, base = *a;
    int started = 0;
    /* MSB-first square-and-multiply, skipping leading zero bits */
    for (int i = 0; i < 32; i++) {
        unsigned char byte = e_be32[i];
        for (int b = 7; b >= 0; b--) {
            if (started) fp_sqr(&out, &out);
            if ((byte >> b) & 1) {
                if (started) fp_mul(&out, &out, &base);
                else { out = base; started = 1; }
            }
        }
    }
    *r = out;
}

/* ---- fp2 ------------------------------------------------------------- */

static inline void f2_add(fp2 *r, const fp2 *x, const fp2 *y) {
    fp_add(&r->a, &x->a, &y->a); fp_add(&r->b, &x->b, &y->b);
}
static inline void f2_sub(fp2 *r, const fp2 *x, const fp2 *y) {
    fp_sub(&r->a, &x->a, &y->a); fp_sub(&r->b, &x->b, &y->b);
}
static inline void f2_neg(fp2 *r, const fp2 *x) {
    fp_neg(&r->a, &x->a); fp_neg(&r->b, &x->b);
}
static inline void f2_conj(fp2 *r, const fp2 *x) {
    r->a = x->a; fp_neg(&r->b, &x->b);
}
static inline int f2_is_zero(const fp2 *x) {
    return fp_is_zero(&x->a) && fp_is_zero(&x->b);
}
static inline int f2_eq(const fp2 *x, const fp2 *y) {
    return fp_eq(&x->a, &y->a) && fp_eq(&x->b, &y->b);
}
static void f2_mul(fp2 *r, const fp2 *x, const fp2 *y) {
    fp t0, t1, sa, sb, cross;
    fp_mul(&t0, &x->a, &y->a);
    fp_mul(&t1, &x->b, &y->b);
    fp_add(&sa, &x->a, &x->b);
    fp_add(&sb, &y->a, &y->b);
    fp_mul(&cross, &sa, &sb);
    fp2 out;
    fp_sub(&out.a, &t0, &t1);
    fp_sub(&cross, &cross, &t0);
    fp_sub(&out.b, &cross, &t1);
    *r = out;
}
static inline void f2_sqr(fp2 *r, const fp2 *x) { f2_mul(r, x, x); }
static void f2_muls(fp2 *r, const fp2 *x, uint64_t s) {
    fp fs; fp_set_small(&fs, s);
    fp_mul(&r->a, &x->a, &fs);
    fp_mul(&r->b, &x->b, &fs);
}
/* xi = 9 + i:  (9a - b) + (9b + a) i */
static void f2_mul_xi(fp2 *r, const fp2 *x) {
    fp nine; fp_set_small(&nine, 9);
    fp t9a, t9b;
    fp_mul(&t9a, &x->a, &nine);
    fp_mul(&t9b, &x->b, &nine);
    fp2 out;
    fp_sub(&out.a, &t9a, &x->b);
    fp_add(&out.b, &t9b, &x->a);
    *r = out;
}
static void f2_inv(fp2 *r, const fp2 *x) {
    fp a2, b2, n, ni;
    fp_sqr(&a2, &x->a);
    fp_sqr(&b2, &x->b);
    fp_add(&n, &a2, &b2);
    fp_inv(&ni, &n);
    fp_mul(&r->a, &x->a, &ni);
    fp nb; fp_neg(&nb, &x->b);
    fp_mul(&r->b, &nb, &ni);
}

/* ---- fp6 ------------------------------------------------------------- */

static inline void f6_add(fp6 *r, const fp6 *x, const fp6 *y) {
    f2_add(&r->c0, &x->c0, &y->c0);
    f2_add(&r->c1, &x->c1, &y->c1);
    f2_add(&r->c2, &x->c2, &y->c2);
}
static inline void f6_sub(fp6 *r, const fp6 *x, const fp6 *y) {
    f2_sub(&r->c0, &x->c0, &y->c0);
    f2_sub(&r->c1, &x->c1, &y->c1);
    f2_sub(&r->c2, &x->c2, &y->c2);
}
static inline void f6_neg(fp6 *r, const fp6 *x) {
    f2_neg(&r->c0, &x->c0); f2_neg(&r->c1, &x->c1); f2_neg(&r->c2, &x->c2);
}
/* Karatsuba-style 3-term mul (same structure as the Python tower) */
static void f6_mul(fp6 *r, const fp6 *x, const fp6 *y) {
    fp2 t0, t1, t2, s, u, w;
    f2_mul(&t0, &x->c0, &y->c0);
    f2_mul(&t1, &x->c1, &y->c1);
    f2_mul(&t2, &x->c2, &y->c2);
    fp6 out;
    /* c0 = t0 + xi*((x1+x2)(y1+y2) - t1 - t2) */
    f2_add(&s, &x->c1, &x->c2);
    f2_add(&u, &y->c1, &y->c2);
    f2_mul(&w, &s, &u);
    f2_sub(&w, &w, &t1);
    f2_sub(&w, &w, &t2);
    f2_mul_xi(&w, &w);
    f2_add(&out.c0, &t0, &w);
    /* c1 = (x0+x1)(y0+y1) - t0 - t1 + xi*t2 */
    f2_add(&s, &x->c0, &x->c1);
    f2_add(&u, &y->c0, &y->c1);
    f2_mul(&w, &s, &u);
    f2_sub(&w, &w, &t0);
    f2_sub(&w, &w, &t1);
    fp2 xt2; f2_mul_xi(&xt2, &t2);
    f2_add(&out.c1, &w, &xt2);
    /* c2 = (x0+x2)(y0+y2) - t0 - t2 + t1 */
    f2_add(&s, &x->c0, &x->c2);
    f2_add(&u, &y->c0, &y->c2);
    f2_mul(&w, &s, &u);
    f2_sub(&w, &w, &t0);
    f2_sub(&w, &w, &t2);
    f2_add(&out.c2, &w, &t1);
    *r = out;
}
static inline void f6_sqr(fp6 *r, const fp6 *x) { f6_mul(r, x, x); }
static void f6_mul_v(fp6 *r, const fp6 *x) {
    /* v*(c0 + c1 v + c2 v^2) = xi*c2 + c0 v + c1 v^2 */
    fp2 t; f2_mul_xi(&t, &x->c2);
    fp2 c0 = x->c0, c1 = x->c1;
    r->c0 = t; r->c1 = c0; r->c2 = c1;
}
static void f6_inv(fp6 *r, const fp6 *x) {
    fp2 c0, c1, c2, t, u;
    f2_sqr(&c0, &x->c0);
    f2_mul(&t, &x->c1, &x->c2); f2_mul_xi(&t, &t);
    f2_sub(&c0, &c0, &t);
    f2_sqr(&c1, &x->c2); f2_mul_xi(&c1, &c1);
    f2_mul(&t, &x->c0, &x->c1);
    f2_sub(&c1, &c1, &t);
    f2_sqr(&c2, &x->c1);
    f2_mul(&t, &x->c0, &x->c2);
    f2_sub(&c2, &c2, &t);
    f2_mul(&t, &x->c2, &c1);
    f2_mul(&u, &x->c1, &c2);
    f2_add(&t, &t, &u);
    f2_mul_xi(&t, &t);
    f2_mul(&u, &x->c0, &c0);
    f2_add(&t, &t, &u);
    fp2 ti; f2_inv(&ti, &t);
    f2_mul(&r->c0, &c0, &ti);
    f2_mul(&r->c1, &c1, &ti);
    f2_mul(&r->c2, &c2, &ti);
}

/* ---- fp12 ------------------------------------------------------------ */

static void f12_mul(fp12 *r, const fp12 *x, const fp12 *y) {
    fp6 t0, t1, s, u, w;
    f6_mul(&t0, &x->a, &y->a);
    f6_mul(&t1, &x->b, &y->b);
    fp12 out;
    f6_mul_v(&w, &t1);
    f6_add(&out.a, &t0, &w);
    f6_add(&s, &x->a, &x->b);
    f6_add(&u, &y->a, &y->b);
    f6_mul(&w, &s, &u);
    f6_sub(&w, &w, &t0);
    f6_sub(&out.b, &w, &t1);
    *r = out;
}
static void f12_sqr(fp12 *r, const fp12 *x) { f12_mul(r, x, x); }
static void f12_conj(fp12 *r, const fp12 *x) {
    r->a = x->a; f6_neg(&r->b, &x->b);
}
static void f12_inv(fp12 *r, const fp12 *x) {
    fp6 t, u, ti;
    f6_mul(&t, &x->a, &x->a);
    f6_mul(&u, &x->b, &x->b);
    f6_mul_v(&u, &u);
    f6_sub(&t, &t, &u);
    f6_inv(&ti, &t);
    f6_mul(&r->a, &x->a, &ti);
    fp6 nb; f6_neg(&nb, &x->b);
    f6_mul(&r->b, &nb, &ti);
}
static void f12_one(fp12 *r) {
    memset(r, 0, sizeof *r);
    r->a.c0.a = FP_R1;
}
static int f12_is_one(const fp12 *x) {
    fp12 one; f12_one(&one);
    return memcmp(x, &one, sizeof one) == 0;
}

/* Frobenius gamma constants, standard (non-Montgomery) hex; converted at
 * module init. gamma[j] = XI^((p-1)j/6), j = 1..5. */
static const char *G1C_HEX[6][2] = {
    {NULL, NULL},
    {"1284b71c2865a7dfe8b99fdd76e68b605c521e08292f2176d60b35dadcc9e470",
     "246996f3b4fae7e6a6327cfe12150b8e747992778eeec7e5ca5cf05f80f362ac"},
    {"2fb347984f7911f74c0bec3cf559b143b78cc310c2c3330c99e39557176f553d",
     "16c9e55061ebae204ba4cc8bd75a079432ae2a1d0b7c9dce1665d51c640fcba2"},
    {"063cf305489af5dcdc5ec698b6e2f9b9dbaae0eda9c95998dc54014671a0135a",
     "07c03cbcac41049a0704b5a7ec796f2b21807dc98fa25bd282d37f632623b0e3"},
    {"05b54f5e64eea80180f3c0b75a181e84d33365f7be94ec72848a1f55921ea762",
     "2c145edbe7fd8aee9f3a80b03b0b1c923685d2ea1bdec763c13b4711cd2b8126"},
    {"0183c1e74f798649e93a3661a4353ff4425c459b55aa1bd32ea2c810eab7692f",
     "12acf2ca76fd0675a27fb246c7729f7db080cb99678e2ac024c6b8ee6e0c2c4b"},
};
static const char *B_TWIST_HEX[2] = {
    "2b149d40ceb8aaae81be18991be06ac3b5b4c5e559dbefa33267e6dc24a138e5",
    "009713b03af0fed4cd2cafadeed8fdf4a74fa084e52d1852e4a2bd0685c315d2"};
static fp2 G1C[6];
static fp2 B_TWIST;

static void fp_from_hex(fp *r, const char *hex) {
    unsigned char be[32];
    for (int i = 0; i < 32; i++) {
        unsigned hi, lo;
        sscanf(hex + 2 * i, "%1x", &hi);
        sscanf(hex + 2 * i + 1, "%1x", &lo);
        be[i] = (unsigned char)((hi << 4) | lo);
    }
    fp_from_bytes_be(r, be);
}

static void f12_frobenius(fp12 *r, const fp12 *x) {
    fp2 t;
    fp12 out;
    f2_conj(&out.a.c0, &x->a.c0);
    f2_conj(&t, &x->a.c1); f2_mul(&out.a.c1, &t, &G1C[2]);
    f2_conj(&t, &x->a.c2); f2_mul(&out.a.c2, &t, &G1C[4]);
    f2_conj(&t, &x->b.c0); f2_mul(&out.b.c0, &t, &G1C[1]);
    f2_conj(&t, &x->b.c1); f2_mul(&out.b.c1, &t, &G1C[3]);
    f2_conj(&t, &x->b.c2); f2_mul(&out.b.c2, &t, &G1C[5]);
    *r = out;
}
/* pow by the 63-bit BN u (square-and-multiply, MSB first) */
static void f12_pow_u(fp12 *r, const fp12 *x) {
    fp12 out = *x;
    for (int i = 61; i >= 0; i--) {   /* BN_U is 63 bits: bit62 is MSB */
        f12_sqr(&out, &out);
        if ((BN_U >> i) & 1) f12_mul(&out, &out, x);
    }
    *r = out;
}

/* final exponentiation: easy part then the DSD vector chain (mirrors the
 * oracle bn254.py:_hard, itself pinned against a generic exponentiation) */
static void final_exp(fp12 *r, const fp12 *f) {
    fp12 f1, f2i, m, t;
    f12_conj(&f1, f);
    f12_inv(&f2i, f);
    f12_mul(&m, &f1, &f2i);             /* f^(p^6 - 1) */
    f12_frobenius(&t, &m);
    f12_frobenius(&t, &t);
    f12_mul(&m, &t, &m);                /* ^(p^2 + 1) */

    fp12 fu1, fu2, fu3, fp1, fp2_, fp3;
    f12_pow_u(&fu1, &m);
    f12_pow_u(&fu2, &fu1);
    f12_pow_u(&fu3, &fu2);
    f12_frobenius(&fp1, &m);
    f12_frobenius(&fp2_, &fp1);
    f12_frobenius(&fp3, &fp2_);
    fp12 y0, y1, y2, y3, y4, y5, y6, t0, t1, u;
    f12_mul(&y0, &fp1, &fp2_); f12_mul(&y0, &y0, &fp3);
    f12_conj(&y1, &m);
    f12_frobenius(&y2, &fu2); f12_frobenius(&y2, &y2);
    f12_frobenius(&y3, &fu1); f12_conj(&y3, &y3);
    f12_frobenius(&u, &fu2); f12_mul(&u, &fu1, &u); f12_conj(&y4, &u);
    f12_conj(&y5, &fu2);
    f12_frobenius(&u, &fu3); f12_mul(&u, &fu3, &u); f12_conj(&y6, &u);
    f12_sqr(&t0, &y6);
    f12_mul(&t0, &t0, &y4); f12_mul(&t0, &t0, &y5);
    f12_mul(&t1, &y3, &y5); f12_mul(&t1, &t1, &t0);
    f12_mul(&t0, &t0, &y2);
    f12_sqr(&t1, &t1); f12_mul(&t1, &t1, &t0);
    f12_sqr(&t1, &t1);
    f12_mul(&t0, &t1, &y1);
    f12_mul(&t1, &t1, &y0);
    f12_sqr(&t0, &t0);
    f12_mul(r, &t0, &t1);
}

/* ---- G1 jacobian ------------------------------------------------------ */

typedef struct { fp x, y, z; } g1j;

static void g1j_double(g1j *r, const g1j *p) {
    if (fp_is_zero(&p->y)) { memset(r, 0, sizeof *r); r->y = FP_R1; return; }
    fp y2, s, m, x3, y3, z3, t;
    fp_sqr(&y2, &p->y);
    fp_mul(&s, &p->x, &y2);
    fp_add(&s, &s, &s); fp_add(&s, &s, &s);        /* 4 X Y^2 */
    fp_sqr(&m, &p->x);
    fp_add(&t, &m, &m); fp_add(&m, &t, &m);        /* 3 X^2 */
    fp_sqr(&x3, &m);
    fp_add(&t, &s, &s);
    fp_sub(&x3, &x3, &t);                          /* M^2 - 2S */
    fp_sqr(&t, &y2);
    fp_add(&t, &t, &t); fp_add(&t, &t, &t); fp_add(&t, &t, &t); /* 8Y^4 */
    fp_sub(&y3, &s, &x3);
    fp_mul(&y3, &m, &y3);
    fp_sub(&y3, &y3, &t);
    fp_mul(&z3, &p->y, &p->z);
    fp_add(&z3, &z3, &z3);
    r->x = x3; r->y = y3; r->z = z3;
}
static void g1j_add_affine(g1j *r, const g1j *p, const fp *x2, const fp *y2) {
    if (fp_is_zero(&p->z)) { r->x = *x2; r->y = *y2; r->z = FP_R1; return; }
    fp z2, u2, s2, h, rr, h2, h3, xh2, t;
    fp_sqr(&z2, &p->z);
    fp_mul(&u2, x2, &z2);
    fp_mul(&s2, y2, &z2); fp_mul(&s2, &s2, &p->z);
    fp_sub(&h, &u2, &p->x);
    fp_sub(&rr, &s2, &p->y);
    if (fp_is_zero(&h)) {
        if (fp_is_zero(&rr)) { g1j_double(r, p); return; }
        memset(r, 0, sizeof *r); r->y = FP_R1; return;
    }
    fp_sqr(&h2, &h);
    fp_mul(&h3, &h, &h2);
    fp_mul(&xh2, &p->x, &h2);
    fp_sqr(&t, &rr);
    fp_sub(&t, &t, &h3);
    fp x3; fp_add(&x3, &xh2, &xh2);
    fp_sub(&x3, &t, &x3);
    fp y3; fp_sub(&y3, &xh2, &x3);
    fp_mul(&y3, &rr, &y3);
    fp_mul(&t, &p->y, &h3);
    fp_sub(&y3, &y3, &t);
    fp z3; fp_mul(&z3, &p->z, &h);
    r->x = x3; r->y = y3; r->z = z3;
}
static void g1j_to_affine(fp *x, fp *y, int *is_inf, const g1j *p) {
    if (fp_is_zero(&p->z)) { *is_inf = 1; return; }
    *is_inf = 0;
    fp zi, zi2;
    fp_inv(&zi, &p->z);
    fp_sqr(&zi2, &zi);
    fp_mul(x, &p->x, &zi2);
    fp_mul(y, &p->y, &zi2);
    fp_mul(y, y, &zi);
}

/* ---- G2 jacobian over fp2 --------------------------------------------- */

typedef struct { fp2 x, y, z; } g2j;

static void g2j_set_inf(g2j *r) {
    memset(r, 0, sizeof *r);
    r->y.a = FP_R1;
}
static void g2j_double(g2j *r, const g2j *p) {
    if (f2_is_zero(&p->y)) { g2j_set_inf(r); return; }
    fp2 y2, s, m, x3, y3, z3, t;
    f2_sqr(&y2, &p->y);
    f2_mul(&s, &p->x, &y2);
    f2_muls(&s, &s, 4);
    f2_sqr(&m, &p->x);
    f2_muls(&m, &m, 3);
    f2_sqr(&x3, &m);
    f2_add(&t, &s, &s);
    f2_sub(&x3, &x3, &t);
    f2_sqr(&t, &y2);
    f2_muls(&t, &t, 8);
    f2_sub(&y3, &s, &x3);
    f2_mul(&y3, &m, &y3);
    f2_sub(&y3, &y3, &t);
    f2_mul(&z3, &p->y, &p->z);
    f2_add(&z3, &z3, &z3);
    r->x = x3; r->y = y3; r->z = z3;
}
static void g2j_add_affine(g2j *r, const g2j *p, const fp2 *x2,
                           const fp2 *y2) {
    if (f2_is_zero(&p->z)) { r->x = *x2; r->y = *y2;
        memset(&r->z, 0, sizeof r->z); r->z.a = FP_R1; return; }
    fp2 z2, u2, s2, h, rr, h2, h3, xh2, t, x3, y3, z3;
    f2_sqr(&z2, &p->z);
    f2_mul(&u2, x2, &z2);
    f2_mul(&s2, y2, &z2); f2_mul(&s2, &s2, &p->z);
    f2_sub(&h, &u2, &p->x);
    f2_sub(&rr, &s2, &p->y);
    if (f2_is_zero(&h)) {
        if (f2_is_zero(&rr)) { g2j_double(r, p); return; }
        g2j_set_inf(r); return;
    }
    f2_sqr(&h2, &h);
    f2_mul(&h3, &h, &h2);
    f2_mul(&xh2, &p->x, &h2);
    f2_sqr(&t, &rr);
    f2_sub(&t, &t, &h3);
    f2_add(&x3, &xh2, &xh2);
    f2_sub(&x3, &t, &x3);
    f2_sub(&y3, &xh2, &x3);
    f2_mul(&y3, &rr, &y3);
    f2_mul(&t, &p->y, &h3);
    f2_sub(&y3, &y3, &t);
    f2_mul(&z3, &p->z, &h);
    r->x = x3; r->y = y3; r->z = z3;
}
static void g2j_to_affine(fp2 *x, fp2 *y, int *is_inf, const g2j *p) {
    if (f2_is_zero(&p->z)) { *is_inf = 1; return; }
    *is_inf = 0;
    fp2 zi, zi2;
    f2_inv(&zi, &p->z);
    f2_sqr(&zi2, &zi);
    f2_mul(x, &p->x, &zi2);
    f2_mul(y, &p->y, &zi2);
    f2_mul(y, y, &zi);
}

/* ---- Miller loop (projective twist; same derivation as bn254_fast) ---- */

typedef struct { fp2 x, y, z; } tw; /* fractional: x = X/Z, y = Y/Z */

/* sparse f * (c0 + c1 w + c3 w^3), c0 scaled by yp (fp), c1 by xp (fp) */
static void sparse6(fp6 *r, const fp6 *x, const fp2 *e0, const fp2 *e1) {
    /* (x0,x1,x2) * (e0,e1,0) */
    fp2 t, u;
    f2_mul(&t, &x->c2, e1); f2_mul_xi(&t, &t);
    f2_mul(&u, &x->c0, e0);
    f2_add(&r->c0, &u, &t);
    f2_mul(&t, &x->c0, e1);
    f2_mul(&u, &x->c1, e0);
    f2_add(&r->c1, &t, &u);
    f2_mul(&t, &x->c1, e1);
    f2_mul(&u, &x->c2, e0);
    f2_add(&r->c2, &t, &u);
}
static void f12_sparse013(fp12 *f, const fp2 *c0, const fp2 *c1,
                          const fp2 *c3) {
    fp6 t0, t1, s, cross, la_lb0;
    /* t0 = a * (c0,0,0) = scalar */
    f2_mul(&t0.c0, &f->a.c0, c0);
    f2_mul(&t0.c1, &f->a.c1, c0);
    f2_mul(&t0.c2, &f->a.c2, c0);
    sparse6(&t1, &f->b, c1, c3);
    f6_add(&s, &f->a, &f->b);
    fp2 e0; f2_add(&e0, c0, c1);
    sparse6(&cross, &s, &e0, c3);
    f6_mul_v(&la_lb0, &t1);
    fp12 out;
    f6_add(&out.a, &t0, &la_lb0);
    f6_sub(&cross, &cross, &t0);
    f6_sub(&out.b, &cross, &t1);
    *f = out;
}

static void dbl_step(tw *t, fp2 *c0, fp2 *c1, fp2 *c3,
                     const fp *xp, const fp *yp) {
    fp2 X2, X4, Y2, Z2, YZ, XY2Z, u, w;
    f2_sqr(&X2, &t->x);
    f2_sqr(&X4, &X2);
    f2_sqr(&Y2, &t->y);
    f2_sqr(&Z2, &t->z);
    f2_mul(&YZ, &t->y, &t->z);
    f2_mul(&XY2Z, &t->x, &Y2); f2_mul(&XY2Z, &XY2Z, &t->z);
    /* c0 = 2 Y Z^2 yp ; c1 = -3 X^2 Z xp ; c3 = X^3 - 2 b' Z^3 */
    f2_mul(&u, &t->y, &Z2);
    f2_add(&u, &u, &u);
    c0->a.v[0] = 0; /* will overwrite */
    fp2 scaled;
    fp_mul(&scaled.a, &u.a, yp); fp_mul(&scaled.b, &u.b, yp);
    *c0 = scaled;
    f2_mul(&u, &X2, &t->z);
    f2_muls(&u, &u, 3);
    f2_neg(&u, &u);
    fp_mul(&scaled.a, &u.a, xp); fp_mul(&scaled.b, &u.b, xp);
    *c1 = scaled;
    f2_mul(&u, &t->x, &X2);
    f2_mul(&w, &t->z, &Z2);
    f2_mul(&w, &B_TWIST, &w);
    f2_add(&w, &w, &w);
    f2_sub(c3, &u, &w);
    /* X3 = 2YZ(9X^4 - 8XY^2Z); Y3 = 36 X^3 Y^2 Z - 27 X^6 - 8 Y^4 Z^2;
       Z3 = 8 (YZ)^3 */
    fp2 nx, ny, nz;
    f2_muls(&u, &X4, 9);
    f2_muls(&w, &XY2Z, 8);
    f2_sub(&u, &u, &w);
    f2_mul(&nx, &YZ, &u);
    f2_add(&nx, &nx, &nx);
    fp2 x3cu; f2_mul(&x3cu, &t->x, &X2);           /* X^3 */
    f2_mul(&u, &x3cu, &Y2); f2_mul(&u, &u, &t->z); /* X^3 Y^2 Z */
    f2_muls(&u, &u, 36);
    f2_mul(&w, &X2, &X4);                          /* X^6 */
    f2_muls(&w, &w, 27);
    f2_sub(&u, &u, &w);
    f2_sqr(&w, &Y2); f2_mul(&w, &w, &Z2);          /* Y^4 Z^2 */
    f2_muls(&w, &w, 8);
    f2_sub(&ny, &u, &w);
    f2_mul(&u, &Y2, &Z2);
    f2_mul(&nz, &YZ, &u);
    f2_muls(&nz, &nz, 8);
    t->x = nx; t->y = ny; t->z = nz;
}
static int add_step(tw *t, fp2 *c0, fp2 *c1, fp2 *c3,
                    const fp2 *x2, const fp2 *y2,
                    const fp *xp, const fp *yp) {
    fp2 x2Z, A, B, A2, B2, B3, A2Z, u, w, scaled;
    f2_mul(&x2Z, x2, &t->z);
    f2_mul(&A, y2, &t->z);
    f2_sub(&A, &A, &t->y);
    f2_sub(&B, &x2Z, &t->x);
    if (f2_is_zero(&B)) return 0; /* degenerate: caller falls back */
    f2_sqr(&A2, &A);
    f2_sqr(&B2, &B);
    f2_mul(&B3, &B, &B2);
    f2_mul(&A2Z, &A2, &t->z);
    /* line: c0 = B yp ; c1 = -A xp ; c3 = A x2 - B y2 */
    fp_mul(&scaled.a, &B.a, yp); fp_mul(&scaled.b, &B.b, yp);
    *c0 = scaled;
    f2_neg(&u, &A);
    fp_mul(&scaled.a, &u.a, xp); fp_mul(&scaled.b, &u.b, xp);
    *c1 = scaled;
    f2_mul(&u, &A, x2);
    f2_mul(&w, &B, y2);
    f2_sub(c3, &u, &w);
    /* X3 = B (A^2 Z - (X + x2 Z) B^2);
       Y3 = A ((2 x2 Z + X) B^2 - A^2 Z) - y2 B^3 Z; Z3 = B^3 Z */
    fp2 nx, ny, nz;
    f2_add(&u, &t->x, &x2Z);
    f2_mul(&u, &u, &B2);
    f2_sub(&u, &A2Z, &u);
    f2_mul(&nx, &B, &u);
    f2_add(&u, &x2Z, &x2Z);
    f2_add(&u, &u, &t->x);
    f2_mul(&u, &u, &B2);
    f2_sub(&u, &u, &A2Z);
    f2_mul(&u, &A, &u);
    f2_mul(&w, &B3, &t->z);
    f2_mul(&w, y2, &w);
    f2_sub(&ny, &u, &w);
    f2_mul(&nz, &B3, &t->z);
    t->x = nx; t->y = ny; t->z = nz;
    return 1;
}

/* pi on the twist: (x, y) -> (conj(x) G1C2, conj(y) G1C3) */
static void frob_twist(fp2 *rx, fp2 *ry, const fp2 *x, const fp2 *y) {
    fp2 t;
    f2_conj(&t, x); f2_mul(rx, &t, &G1C[2]);
    f2_conj(&t, y); f2_mul(ry, &t, &G1C[3]);
}

static int miller(fp12 *f, const fp2 *qx, const fp2 *qy,
                  const fp *xp, const fp *yp) {
    tw T = {*qx, *qy, {{{0}}, {{0}}}};
    T.z.a = FP_R1; /* Z = 1 */
    memset(&T.z.b, 0, sizeof T.z.b);
    f12_one(f);
    fp2 c0, c1, c3;
    for (int i = ATE_BITS - 2; i >= 0; i--) {
        dbl_step(&T, &c0, &c1, &c3, xp, yp);
        f12_sqr(f, f);
        f12_sparse013(f, &c0, &c1, &c3);
        if ((ATE_LO >> i) & 1) {
            if (!add_step(&T, &c0, &c1, &c3, qx, qy, xp, yp)) return 0;
            f12_sparse013(f, &c0, &c1, &c3);
        }
    }
    fp2 q1x, q1y, q2x, q2y;
    frob_twist(&q1x, &q1y, qx, qy);
    frob_twist(&q2x, &q2y, &q1x, &q1y);
    f2_neg(&q2y, &q2y);
    if (!add_step(&T, &c0, &c1, &c3, &q1x, &q1y, xp, yp)) return 0;
    f12_sparse013(f, &c0, &c1, &c3);
    if (!add_step(&T, &c0, &c1, &c3, &q2x, &q2y, xp, yp)) return 0;
    f12_sparse013(f, &c0, &c1, &c3);
    return 1;
}

/* ---- Python glue ------------------------------------------------------ */

static int parse_fp_be(fp *r, const unsigned char *buf) {
    fp_from_bytes_be(r, buf);
    return 1;
}
static int parse_g1(fp *x, fp *y, int *is_inf, PyObject *obj) {
    if (obj == Py_None) { *is_inf = 1; return 1; }
    char *buf; Py_ssize_t len;
    if (PyBytes_AsStringAndSize(obj, &buf, &len) < 0) return 0;
    if (len != 64) { PyErr_SetString(PyExc_ValueError, "G1 needs 64 bytes");
        return 0; }
    *is_inf = 0;
    parse_fp_be(x, (unsigned char *)buf);
    parse_fp_be(y, (unsigned char *)buf + 32);
    return 1;
}
static int parse_g2(fp2 *x, fp2 *y, int *is_inf, PyObject *obj) {
    if (obj == Py_None) { *is_inf = 1; return 1; }
    char *buf; Py_ssize_t len;
    if (PyBytes_AsStringAndSize(obj, &buf, &len) < 0) return 0;
    if (len != 128) { PyErr_SetString(PyExc_ValueError, "G2 needs 128 bytes");
        return 0; }
    *is_inf = 0;
    parse_fp_be(&x->a, (unsigned char *)buf);
    parse_fp_be(&x->b, (unsigned char *)buf + 32);
    parse_fp_be(&y->a, (unsigned char *)buf + 64);
    parse_fp_be(&y->b, (unsigned char *)buf + 96);
    return 1;
}
static PyObject *g1_to_py(const g1j *p) {
    fp x, y; int inf;
    g1j_to_affine(&x, &y, &inf, p);
    if (inf) Py_RETURN_NONE;
    unsigned char out[64];
    fp_to_bytes_be(out, &x);
    fp_to_bytes_be(out + 32, &y);
    return PyBytes_FromStringAndSize((char *)out, 64);
}
static PyObject *g2_to_py(const g2j *p) {
    fp2 x, y; int inf;
    g2j_to_affine(&x, &y, &inf, p);
    if (inf) Py_RETURN_NONE;
    unsigned char out[128];
    fp_to_bytes_be(out, &x.a);
    fp_to_bytes_be(out + 32, &x.b);
    fp_to_bytes_be(out + 64, &y.a);
    fp_to_bytes_be(out + 96, &y.b);
    return PyBytes_FromStringAndSize((char *)out, 128);
}
static int parse_scalar_bits(unsigned char *be32, PyObject *obj) {
    char *buf; Py_ssize_t len;
    if (PyBytes_AsStringAndSize(obj, &buf, &len) < 0) return 0;
    if (len != 32) { PyErr_SetString(PyExc_ValueError,
                                     "scalar needs 32 bytes"); return 0; }
    memcpy(be32, buf, 32);
    return 1;
}

static PyObject *py_g1_mul(PyObject *self, PyObject *args) {
    PyObject *pt, *kobj;
    if (!PyArg_ParseTuple(args, "OO", &pt, &kobj)) return NULL;
    fp x, y; int inf;
    unsigned char k[32];
    if (!parse_g1(&x, &y, &inf, pt) || !parse_scalar_bits(k, kobj))
        return NULL;
    g1j acc; memset(&acc, 0, sizeof acc); acc.y = FP_R1;
    if (!inf) {
        int started = 0;  /* skip leading zero bits: short (e.g. 128-bit
                           * batch-verify) scalars cost half a full mul */
        for (int i = 0; i < 32; i++) {
            unsigned char byte = k[i];
            if (!started && byte == 0) continue;
            for (int b = 7; b >= 0; b--) {
                if (started) g1j_double(&acc, &acc);
                if ((byte >> b) & 1) {
                    g1j_add_affine(&acc, &acc, &x, &y);
                    started = 1;
                }
            }
        }
    }
    return g1_to_py(&acc);
}
static PyObject *py_g2_mul(PyObject *self, PyObject *args) {
    PyObject *pt, *kobj;
    if (!PyArg_ParseTuple(args, "OO", &pt, &kobj)) return NULL;
    fp2 x, y; int inf;
    unsigned char k[32];
    if (!parse_g2(&x, &y, &inf, pt) || !parse_scalar_bits(k, kobj))
        return NULL;
    g2j acc; g2j_set_inf(&acc);
    if (!inf) {
        int started = 0;  /* as in g1_mul: skip leading zero bits */
        for (int i = 0; i < 32; i++) {
            unsigned char byte = k[i];
            if (!started && byte == 0) continue;
            for (int b = 7; b >= 0; b--) {
                if (started) g2j_double(&acc, &acc);
                if ((byte >> b) & 1) {
                    g2j_add_affine(&acc, &acc, &x, &y);
                    started = 1;
                }
            }
        }
    }
    return g2_to_py(&acc);
}
static int be32_lt_p(const unsigned char *be32) {
    /* raw big-endian value < P? (canonical-encoding check; FP_P holds
     * the raw prime limbs — Montgomery form applies to elements only) */
    unsigned char p_be[32];
    for (int i = 0; i < 4; i++) {
        uint64_t w = FP_P.v[3 - i];
        for (int j = 0; j < 8; j++) {
            p_be[i * 8 + j] = (unsigned char)(w >> (8 * (7 - j)));
        }
    }
    return memcmp(be32, p_be, 32) < 0;
}

static int g1_on_curve_mont(const fp *x, const fp *y) {
    fp y2, x2, x3, three;
    fp_sqr(&y2, y);
    fp_sqr(&x2, x);
    fp_mul(&x3, &x2, x);
    fp_set_small(&three, 3);
    fp_add(&x3, &x3, &three);
    return fp_eq(&y2, &x3);
}

static PyObject *py_g1_sum_checked(PyObject *self, PyObject *args) {
    /* Sum raw 64-byte G1 encodings with canonical + on-curve validation
     * in C — the signature-share aggregation hot path, sparing the host
     * a bytes->int->python-check->bytes round-trip per share.  All-zero
     * bytes = the identity (contributes nothing); anything else invalid
     * raises ValueError. */
    PyObject *seq;
    if (!PyArg_ParseTuple(args, "O", &seq)) return NULL;
    PyObject *it = PyObject_GetIter(seq);
    if (!it) return NULL;
    g1j acc; memset(&acc, 0, sizeof acc); acc.y = FP_R1;
    static const unsigned char zeros[64] = {0};
    PyObject *item;
    while ((item = PyIter_Next(it)) != NULL) {
        char *buf; Py_ssize_t len;
        if (PyBytes_AsStringAndSize(item, &buf, &len) < 0) {
            Py_DECREF(item); Py_DECREF(it); return NULL;
        }
        if (len != 64) {
            Py_DECREF(item); Py_DECREF(it);
            PyErr_SetString(PyExc_ValueError, "G1 needs 64 bytes");
            return NULL;
        }
        if (memcmp(buf, zeros, 64) == 0) { Py_DECREF(item); continue; }
        if (!be32_lt_p((unsigned char *)buf)
                || !be32_lt_p((unsigned char *)buf + 32)) {
            Py_DECREF(item); Py_DECREF(it);
            PyErr_SetString(PyExc_ValueError,
                            "non-canonical G1 coordinate");
            return NULL;
        }
        fp x, y;
        fp_from_bytes_be(&x, (unsigned char *)buf);
        fp_from_bytes_be(&y, (unsigned char *)buf + 32);
        Py_DECREF(item);
        if (!g1_on_curve_mont(&x, &y)) {
            Py_DECREF(it);
            PyErr_SetString(PyExc_ValueError, "point not on G1");
            return NULL;
        }
        g1j_add_affine(&acc, &acc, &x, &y);
    }
    Py_DECREF(it);
    if (PyErr_Occurred()) return NULL;
    return g1_to_py(&acc);
}

static PyObject *py_g1_sum(PyObject *self, PyObject *args) {
    PyObject *seq;
    if (!PyArg_ParseTuple(args, "O", &seq)) return NULL;
    PyObject *it = PyObject_GetIter(seq);
    if (!it) return NULL;
    g1j acc; memset(&acc, 0, sizeof acc); acc.y = FP_R1;
    PyObject *item;
    while ((item = PyIter_Next(it)) != NULL) {
        fp x, y; int inf;
        int ok = parse_g1(&x, &y, &inf, item);
        Py_DECREF(item);
        if (!ok) { Py_DECREF(it); return NULL; }
        if (!inf) g1j_add_affine(&acc, &acc, &x, &y);
    }
    Py_DECREF(it);
    if (PyErr_Occurred()) return NULL;
    return g1_to_py(&acc);
}
static PyObject *py_g2_sum(PyObject *self, PyObject *args) {
    PyObject *seq;
    if (!PyArg_ParseTuple(args, "O", &seq)) return NULL;
    PyObject *it = PyObject_GetIter(seq);
    if (!it) return NULL;
    g2j acc; g2j_set_inf(&acc);
    PyObject *item;
    while ((item = PyIter_Next(it)) != NULL) {
        fp2 x, y; int inf;
        int ok = parse_g2(&x, &y, &inf, item);
        Py_DECREF(item);
        if (!ok) { Py_DECREF(it); return NULL; }
        if (!inf) g2j_add_affine(&acc, &acc, &x, &y);
    }
    Py_DECREF(it);
    if (PyErr_Occurred()) return NULL;
    return g2_to_py(&acc);
}
/* [R]Q ladder over the group order (unreduced by construction: R's bits) */
static const unsigned char R_BE[32] = {
    0x30, 0x64, 0x4e, 0x72, 0xe1, 0x31, 0xa0, 0x29,
    0xb8, 0x50, 0x45, 0xb6, 0x81, 0x81, 0x58, 0x5d,
    0x28, 0x33, 0xe8, 0x48, 0x79, 0xb9, 0x70, 0x91,
    0x43, 0xe1, 0xf5, 0x93, 0xf0, 0x00, 0x00, 0x01};
static PyObject *py_g2_in_subgroup(PyObject *self, PyObject *args) {
    PyObject *pt;
    if (!PyArg_ParseTuple(args, "O", &pt)) return NULL;
    fp2 x, y; int inf;
    if (!parse_g2(&x, &y, &inf, pt)) return NULL;
    if (inf) Py_RETURN_TRUE;
    g2j acc; g2j_set_inf(&acc);
    for (int i = 0; i < 32; i++) {
        unsigned char byte = R_BE[i];
        for (int b = 7; b >= 0; b--) {
            g2j_double(&acc, &acc);
            if ((byte >> b) & 1) g2j_add_affine(&acc, &acc, &x, &y);
        }
    }
    if (f2_is_zero(&acc.z)) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

static int accumulate_pairs(fp12 *f, PyObject *pairs) {
    f12_one(f);
    PyObject *it = PyObject_GetIter(pairs);
    if (!it) return 0;
    PyObject *item;
    while ((item = PyIter_Next(it)) != NULL) {
        PyObject *pobj, *qobj;
        if (!PyArg_ParseTuple(item, "OO", &pobj, &qobj)) {
            Py_DECREF(item); Py_DECREF(it); return 0;
        }
        fp px, py_; int pinf;
        fp2 qx, qy; int qinf;
        int ok = parse_g1(&px, &py_, &pinf, pobj)
              && parse_g2(&qx, &qy, &qinf, qobj);
        Py_DECREF(item);
        if (!ok) { Py_DECREF(it); return 0; }
        if (pinf || qinf) continue;
        fp12 ml;
        if (!miller(&ml, &qx, &qy, &px, &py_)) {
            Py_DECREF(it);
            PyErr_SetString(PyExc_ArithmeticError,
                            "degenerate point in miller loop");
            return 0;
        }
        f12_mul(f, f, &ml);
    }
    Py_DECREF(it);
    if (PyErr_Occurred()) return 0;
    return 1;
}
static PyObject *py_multi_pairing(PyObject *self, PyObject *args) {
    PyObject *pairs;
    if (!PyArg_ParseTuple(args, "O", &pairs)) return NULL;
    fp12 f, out;
    if (!accumulate_pairs(&f, pairs)) return NULL;
    final_exp(&out, &f);
    /* 12 x 32 bytes in the Python tuple coefficient order:
       a.c0.a, a.c0.b, a.c1.a, ... b.c2.b */
    unsigned char buf[384];
    const fp *coeffs[12] = {
        &out.a.c0.a, &out.a.c0.b, &out.a.c1.a, &out.a.c1.b,
        &out.a.c2.a, &out.a.c2.b, &out.b.c0.a, &out.b.c0.b,
        &out.b.c1.a, &out.b.c1.b, &out.b.c2.a, &out.b.c2.b};
    for (int i = 0; i < 12; i++)
        fp_to_bytes_be(buf + 32 * i, coeffs[i]);
    return PyBytes_FromStringAndSize((char *)buf, 384);
}
static PyObject *py_pairing_check(PyObject *self, PyObject *args) {
    PyObject *pairs;
    if (!PyArg_ParseTuple(args, "O", &pairs)) return NULL;
    fp12 f, out;
    if (!accumulate_pairs(&f, pairs)) return NULL;
    final_exp(&out, &f);
    if (f12_is_one(&out)) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

static PyObject *py_fp_sqrt(PyObject *self, PyObject *args) {
    /* sqrt in Fp (P = 3 mod 4): bytes32 -> bytes32 | None (non-residue).
     * Serves hash-to-curve's try-and-increment; the Python modular pow
     * it replaces was the single hottest host op per hashed message. */
    PyObject *xobj;
    if (!PyArg_ParseTuple(args, "O", &xobj)) return NULL;
    char *buf; Py_ssize_t len;
    if (PyBytes_AsStringAndSize(xobj, &buf, &len) < 0) return NULL;
    if (len != 32) { PyErr_SetString(PyExc_ValueError,
                                     "fp needs 32 bytes"); return NULL; }
    fp x; fp_from_bytes_be(&x, (unsigned char *)buf);
    fp y; fp_pow_be(&y, &x, SQRT_EXP_BE);
    fp y2; fp_sqr(&y2, &y);
    if (!fp_eq(&y2, &x)) Py_RETURN_NONE;
    unsigned char out[32]; fp_to_bytes_be(out, &y);
    return PyBytes_FromStringAndSize((char *)out, 32);
}

static PyMethodDef Methods[] = {
    {"fp_sqrt", py_fp_sqrt, METH_VARARGS,
     "sqrt in Fp (bytes32 -> bytes32 | None)"},
    {"g1_mul", py_g1_mul, METH_VARARGS, "G1 scalar mul (bytes64, bytes32)"},
    {"g2_mul", py_g2_mul, METH_VARARGS, "G2 scalar mul (bytes128, bytes32)"},
    {"g1_sum", py_g1_sum, METH_VARARGS, "sum of G1 points"},
    {"g1_sum_checked", py_g1_sum_checked, METH_VARARGS,
     "sum raw bytes64 G1 encodings with canonical+curve checks"},
    {"g2_sum", py_g2_sum, METH_VARARGS, "sum of G2 points"},
    {"g2_in_subgroup", py_g2_in_subgroup, METH_VARARGS,
     "unreduced [R]Q == O check"},
    {"multi_pairing", py_multi_pairing, METH_VARARGS,
     "prod e(Pi, Qi) -> 384-byte Fp12"},
    {"pairing_check", py_pairing_check, METH_VARARGS,
     "prod e(Pi, Qi) == 1"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "bn254c", "native BN254 pairing", -1, Methods};

PyMODINIT_FUNC PyInit_bn254c(void) {
    for (int j = 1; j < 6; j++) {
        fp_from_hex(&G1C[j].a, G1C_HEX[j][0]);
        fp_from_hex(&G1C[j].b, G1C_HEX[j][1]);
    }
    fp_from_hex(&B_TWIST.a, B_TWIST_HEX[0]);
    fp_from_hex(&B_TWIST.b, B_TWIST_HEX[1]);
    return PyModule_Create(&moduledef);
}
