/* Base58 (bitcoin alphabet) codec in C.
 *
 * Reference analog: the reference stack leans on the `base58` PyPI
 * package (plenum/common/messages/fields.py Base58Field et al.); here
 * every wire identifier, verkey, merkle root and BLS signature crosses
 * as base58, so the codec sits on the signature-aggregation and
 * proved-read hot paths.  Classic big-endian repeated mul-add over a
 * byte buffer: O(n_digits * n_bytes) single-byte ops — ~1us for a
 * 64-byte signature vs ~10us for the chunked pure-Python fallback
 * (indy_plenum_tpu/utils/base58.py, which remains the oracle).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static const char ALPHABET[59] = "123456789ABCDEFGHJKLMNPQRSTUVWXYZ"
                                 "abcdefghijkmnopqrstuvwxyz";
static signed char INDEX[256];

static PyObject *py_b58_decode(PyObject *self, PyObject *arg) {
    const char *text; Py_ssize_t n;
    if (PyBytes_Check(arg)) {
        text = PyBytes_AS_STRING(arg); n = PyBytes_GET_SIZE(arg);
    } else if (PyUnicode_Check(arg)) {
        text = PyUnicode_AsUTF8AndSize(arg, &n);
        if (!text) return NULL;
    } else {
        PyErr_SetString(PyExc_TypeError, "str or bytes required");
        return NULL;
    }
    Py_ssize_t zeros = 0;
    while (zeros < n && text[zeros] == '1') zeros++;
    /* upper bound on decoded size: n * log(58)/log(256) < n * 0.7325 + 1 */
    Py_ssize_t cap = (Py_ssize_t)(n * 733 / 1000) + 1;
    unsigned char *buf = (unsigned char *)PyMem_Malloc(cap ? cap : 1);
    if (!buf) return PyErr_NoMemory();
    Py_ssize_t used = 0; /* buf[cap-used .. cap-1] holds the value (BE) */
    for (Py_ssize_t i = 0; i < n; i++) {
        int d = INDEX[(unsigned char)text[i]];
        if (d < 0) {
            /* match the pure-Python fallback's message: the offending
             * CHARACTER with repr quoting (e.g. '0'), not the raw byte
             * value — PyObject_Repr gives Python's exact quoting rules
             * for non-printables too */
            PyObject *ch, *r;
            PyMem_Free(buf);
            ch = PyUnicode_FromOrdinal((int)(unsigned char)text[i]);
            if (!ch) return NULL;
            r = PyObject_Repr(ch);
            Py_DECREF(ch);
            if (!r) return NULL;
            PyErr_Format(PyExc_ValueError,
                         "invalid base58 character %U", r);
            Py_DECREF(r);
            return NULL;
        }
        unsigned int carry = (unsigned int)d;
        for (Py_ssize_t j = 0; j < used; j++) {
            unsigned int v = (unsigned int)buf[cap - 1 - j] * 58u + carry;
            buf[cap - 1 - j] = (unsigned char)v;
            carry = v >> 8;
        }
        while (carry) {
            buf[cap - 1 - used] = (unsigned char)carry;
            carry >>= 8;
            used++;
        }
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, zeros + used);
    if (!out) { PyMem_Free(buf); return NULL; }
    unsigned char *o = (unsigned char *)PyBytes_AS_STRING(out);
    memset(o, 0, zeros);
    memcpy(o + zeros, buf + cap - used, used);
    PyMem_Free(buf);
    return out;
}

static PyObject *py_b58_encode(PyObject *self, PyObject *arg) {
    const unsigned char *data; Py_ssize_t n;
    if (!PyBytes_Check(arg)) {
        PyErr_SetString(PyExc_TypeError, "bytes required");
        return NULL;
    }
    data = (const unsigned char *)PyBytes_AS_STRING(arg);
    n = PyBytes_GET_SIZE(arg);
    Py_ssize_t zeros = 0;
    while (zeros < n && data[zeros] == 0) zeros++;
    /* upper bound on encoded size: n * log(256)/log(58) < n * 1.3658 + 1 */
    Py_ssize_t cap = (Py_ssize_t)(n * 137 / 100) + 1;
    unsigned char *buf = (unsigned char *)PyMem_Malloc(cap ? cap : 1);
    if (!buf) return PyErr_NoMemory();
    Py_ssize_t used = 0; /* buf[cap-used .. cap-1] holds digits (BE) */
    for (Py_ssize_t i = zeros; i < n; i++) {
        unsigned int carry = data[i];
        for (Py_ssize_t j = 0; j < used; j++) {
            unsigned int v = ((unsigned int)buf[cap - 1 - j] << 8) + carry;
            buf[cap - 1 - j] = (unsigned char)(v % 58u);
            carry = v / 58u;
        }
        while (carry) {
            buf[cap - 1 - used] = (unsigned char)(carry % 58u);
            carry /= 58u;
            used++;
        }
    }
    PyObject *out = PyUnicode_New(zeros + used, 127);
    if (!out) { PyMem_Free(buf); return NULL; }
    Py_UCS1 *o = PyUnicode_1BYTE_DATA(out);
    memset(o, '1', zeros);
    for (Py_ssize_t j = 0; j < used; j++)
        o[zeros + j] = (Py_UCS1)ALPHABET[buf[cap - used + j]];
    PyMem_Free(buf);
    return out;
}

static PyMethodDef Methods[] = {
    {"b58_decode", py_b58_decode, METH_O, "base58 -> bytes"},
    {"b58_encode", py_b58_encode, METH_O, "bytes -> base58 str"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef Module = {
    PyModuleDef_HEAD_INIT, "b58c", NULL, -1, Methods,
};

PyMODINIT_FUNC PyInit_b58c(void) {
    memset(INDEX, -1, sizeof INDEX);
    for (int i = 0; i < 58; i++) INDEX[(unsigned char)ALPHABET[i]] = (signed char)i;
    return PyModule_Create(&Module);
}
