"""Mesh-sharded dispatch plane (PR 4): shard_map group step semantics.

Contract under test (README "Mesh-sharded dispatch plane"): pad M →
shard → per-shard stage → single grouped step → gathered events.
Sharding is a PLACEMENT choice — the grouped step on a mesh must order
bit-identical digests to the 1-device plane on the same seed, through
view changes, under the adaptive governor, and under chaos. The
governor's law runs per shard: one hot shard narrows the tick for the
whole pool.

The heavyweight acceptance shape (n=16/k=6 on a 4-way mesh) rides the
slow lane; the tier-1 tests pin the same invariants at sizes that fit
the suite budget. ``scripts/check_dispatch_budget.py``'s sharded gate
covers the n=16/k=6 dispatch-discipline comparison in CI.
"""
import os
import sys

import pytest

jax = pytest.importorskip("jax")
np = pytest.importorskip("numpy")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from indy_plenum_tpu.config import getConfig  # noqa: E402
from indy_plenum_tpu.simulation.pool import SimPool  # noqa: E402


def _mesh(devices, n):
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:n]), ("members",))


def _run_pool(n_nodes, k, seed, mesh, adaptive=True, view_change=True):
    """Order a workload (optionally through a view change) and return the
    surviving nodes' digest map."""
    cfg = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 5,
                     "QuorumTickInterval": 0.05,
                     "QuorumTickAdaptive": adaptive})
    pool = SimPool(n_nodes, seed=seed, config=cfg, device_quorum=True,
                   shadow_check=False, num_instances=k, mesh=mesh)
    primary = pool.nodes[0].data.primaries[0]
    for i in range(6):
        pool.submit_request(i)
    pool.run_for(8)
    if view_change:
        pool.network.disconnect(primary)
        pool.run_for(pool.config.ToleratePrimaryDisconnection + 10)
        for i in range(100, 104):
            pool.submit_request(i)
        pool.run_for(12)
    assert pool.honest_nodes_agree()
    digests = {n.name: tuple(n.ordered_digests) for n in pool.nodes
               if not view_change or n.name != primary}
    return digests, pool


# ---------------------------------------------------------------------
# tier-1: semantics identity + the mesh plumbing
# ---------------------------------------------------------------------

@pytest.mark.perf
def test_sharded_digest_identity_incl_view_change(eight_devices):
    """4-way mesh vs 1-device on the same seed, adaptive tick, through a
    view change: bit-identical ordered digests. (The n=16/k=6 acceptance
    shape runs in the slow lane and in check_dispatch_budget's sharded
    gate — this pins the same invariant inside the tier-1 budget.)"""
    mesh = _mesh(eight_devices, 4)
    sharded, spool = _run_pool(8, 2, seed=37, mesh=mesh)
    single, _ = _run_pool(8, 2, seed=37, mesh=None)
    assert sharded == single
    assert spool.vote_group.shards == 4
    # the whole member axis really ran split across the mesh
    states = spool.vote_group._states.prepare_votes
    assert len(states.sharding.device_set) == 4


def test_member_axis_pads_to_mesh_multiple(eight_devices):
    """M not divisible by the mesh is padded, not rejected: pad rows are
    zero planes with no member view, and occupancy accounting excludes
    them (capacity counts real rows only)."""
    from indy_plenum_tpu.tpu.vote_plane import FLUSH_LADDER, VotePlaneGroup

    mesh = _mesh(eight_devices, 4)
    validators = [f"n{i}" for i in range(4)]
    group = VotePlaneGroup(6, validators, log_size=8, n_checkpoints=2,
                           mesh=mesh)
    assert group.shards == 4
    assert group._m_pad == 8 and group._shard_rows == 2
    assert group._real_rows == [2, 2, 2, 0]
    group.view(0).record_preprepare(1)
    for sender in validators[1:]:
        group.view(0).record_prepare(sender, 1)
    group.view(5).record_prepare("n1", 2)
    group.flush()
    assert group.view(0).prepare_count(1) == 3
    assert group.view(5).prepare_count(2) == 1
    # capacity excludes the pad-only shard entirely
    assert group.flush_capacity_per_shard[3] == 0
    assert sum(group.flush_capacity_per_shard) \
        == 6 * FLUSH_LADDER[0] == group.flush_capacity_total
    assert sum(group.flush_votes_per_shard) == group.flush_votes_total == 5


def test_sharded_slide_and_reset_match_unsharded(eight_devices):
    """Window slide and view-change reset through the shard_map path
    leave the same events as the 1-device path."""
    from indy_plenum_tpu.tpu.vote_plane import VotePlaneGroup

    validators = [f"n{i}" for i in range(4)]

    def run(mesh):
        group = VotePlaneGroup(4, validators, log_size=8, n_checkpoints=2,
                               mesh=mesh)
        for m in range(4):
            group.view(m).record_preprepare(2)
            for sender in validators:
                group.view(m).record_prepare(sender, 2)
                group.view(m).record_commit(sender, 2)
        group.flush()
        group.view(1).slide_to(1)   # slot axis rolls for member 1 only
        group.view(2).reset()       # member 2 forgets everything
        group.flush()
        return [np.asarray(group._host_prepared)[m].tolist()
                for m in range(4)]

    assert run(_mesh(eight_devices, 4)) == run(None)


def test_monitor_snapshot_surfaces_shards(eight_devices):
    """Monitor.snapshot()'s device_dispatch block carries the mesh width
    and per-shard occupancy when the pool runs sharded."""
    from indy_plenum_tpu.simulation.node_pool import NodePool

    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 5,
                        "PropagateBatchWait": 0.05,
                        "QuorumTickInterval": 0.05,
                        "QuorumTickAdaptive": True})
    pool = NodePool(4, seed=83, config=config, device_quorum=True,
                    mesh=_mesh(eight_devices, 4))
    for _ in range(3):
        pool.submit_to("node0", pool.make_nym_request())
    pool.run_for(15)
    assert all(len(n.ordered_digests) == 3 for n in pool.nodes)
    device = pool.node("node0").monitor.snapshot()["device_dispatch"]
    assert device["shards"] == 4
    assert len(device["shard_occupancy"]) == 4
    assert any(occ for occ in device["shard_occupancy"])
    # the governor saw the per-shard series too
    assert pool.governor is not None
    assert pool.governor.shard_ewmas is not None
    assert len(pool.governor.shard_ewmas) == 4


# ---------------------------------------------------------------------
# per-shard governor law (unit-level, no devices needed)
# ---------------------------------------------------------------------

def test_governor_hot_shard_narrows_for_everyone():
    """One saturated shard must narrow the tick even while the pool-wide
    AVERAGE occupancy sits far below the hot threshold."""
    from indy_plenum_tpu.tpu.governor import DispatchGovernor

    gov = DispatchGovernor(0.05, 0.01, 0.2, occupancy_high=0.5)
    interval = gov.observe_shards([60, 0, 0, 0], [64, 64, 64, 64], 1)
    assert interval < 0.05  # narrowed
    assert gov.ewma == pytest.approx(60 / 64)  # the hottest shard rules
    # pool-wide average would have been 60/256 < high: the per-shard law
    # is what caught it
    assert (60 / 256) < 0.5


def test_governor_single_shard_is_bitwise_pr3_law():
    """observe() and observe_shards([v],[c],d) must replay identically —
    unsharded pools keep the exact PR 3 trajectory."""
    from indy_plenum_tpu.tpu.governor import DispatchGovernor

    a = DispatchGovernor(0.05, 0.01, 0.2)
    b = DispatchGovernor(0.05, 0.01, 0.2)
    series = [(10, 64, 1), (0, 0, 1), (60, 64, 2), (1, 64, 1), (0, 0, 1)]
    for votes, cap, dispatches in series:
        assert a.observe(votes, cap, dispatches) \
            == b.observe_shards([votes], [cap], dispatches)
    assert a.ewma == b.ewma
    assert list(a.trajectory) == list(b.trajectory)


def test_governor_idle_shards_still_widen():
    """All shards sparse ⇒ widen (the per-shard max must not break the
    widen half of the law)."""
    from indy_plenum_tpu.tpu.governor import DispatchGovernor

    gov = DispatchGovernor(0.05, 0.01, 0.2, occupancy_low=0.05)
    interval = gov.observe_shards([1, 0], [64, 64], 1)
    assert interval > 0.05


# ---------------------------------------------------------------------
# adaptive flush ladder (unit-level)
# ---------------------------------------------------------------------

def test_adaptive_ladder_learns_top_rung():
    from indy_plenum_tpu.tpu.vote_plane import (
        FLUSH_BATCH,
        FLUSH_LADDER,
        AdaptiveLadder,
        pow2_rung,
    )

    ladder = AdaptiveLadder(window=512, min_samples=64)
    # before the warm-up window the static ladder behaviour holds
    assert ladder.top == FLUSH_BATCH
    assert ladder.shape(5) == FLUSH_LADDER[0]
    assert ladder.shape(20) == FLUSH_BATCH
    for _ in range(64):
        ladder.record(20)
    # p99 of a constant-20 series rounds up to 32: the pool stops paying
    # (and compiling) the 128-wide rung
    assert ladder.top == 32
    assert ladder.shape(20) == 32
    assert ladder.shape(5) == FLUSH_LADDER[0]
    # overflow beyond the learned top still gets a containing rung
    assert ladder.shape(100) == FLUSH_BATCH
    # clamps: pow2 math stays inside the static bounds
    assert pow2_rung(0) == FLUSH_LADDER[0]
    assert pow2_rung(FLUSH_BATCH + 1) == FLUSH_BATCH


def test_adaptive_ladder_deterministic_and_tracks_p99():
    from indy_plenum_tpu.tpu.vote_plane import AdaptiveLadder

    def learn(series):
        ladder = AdaptiveLadder(window=512, min_samples=64)
        for sample in series:
            ladder.record(sample)
        return ladder.top

    series = [3] * 70 + [25] * 2
    assert learn(series) == learn(series)  # pure function of the series
    assert learn([3] * 70) == 16
    # the p99 follows a heavy tail present at the recompute point
    assert learn([3] * 50 + [60] * 14) == 64
    # recomputes happen on a stride (not per record — the flush loop
    # must not pay a window sort per dispatch): a tail landing between
    # strides folds in at the next boundary
    assert learn([3] * 64 + [60] * 31) == 16   # tail not yet folded
    assert learn([3] * 64 + [60] * 32) == 64   # stride boundary hit


def test_group_uses_learned_rung():
    """End-to-end through VotePlaneGroup: after the warm-up window a
    ~20-vote busiest member pads to the learned 32-wide rung, not 128."""
    from indy_plenum_tpu.tpu.vote_plane import VotePlaneGroup

    validators = [f"n{i}" for i in range(4)]
    group = VotePlaneGroup(2, validators, log_size=64, n_checkpoints=2,
                           adaptive_ladder=True)
    ladder = group._ladder
    assert ladder is not None
    for _ in range(64):
        ladder.record(20)
    for slot in range(20):
        group.view(0).record_prepare("n1", slot + 1)
    group.flush()
    # capacity for the last dispatch: members * learned rung
    assert group.flush_capacity_total == 2 * 32


# ---------------------------------------------------------------------
# slow lane: the acceptance shape + chaos on the mesh path
# ---------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.perf
def test_sharded_digest_identity_n16_k6(eight_devices):
    """The ISSUE 4 acceptance shape: n=16, k=6 (M=96 members) on a 4-way
    host mesh vs 1-device, adaptive governor, through a view change —
    bit-identical ordered digests."""
    mesh = _mesh(eight_devices, 4)
    sharded, spool = _run_pool(16, 6, seed=41, mesh=mesh)
    single, _ = _run_pool(16, 6, seed=41, mesh=None)
    assert sharded == single
    assert spool.vote_group.shards == 4
    assert spool.vote_group._m_pad == 96


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_f_crash_partition_on_mesh_matches_single_device(eight_devices):
    """f crash + partition through the MESH-SHARDED dispatch plane: all
    invariants hold and every node's ordered-digest hash equals the
    1-device run on the same seed (the chaos replay contract extends to
    placement)."""
    from indy_plenum_tpu.chaos import run_scenario

    mesh = _mesh(eight_devices, 4)
    sharded = run_scenario("f_crash_partition", seed=7,
                           device_quorum=True, quorum_tick_interval=0.05,
                           quorum_tick_adaptive=True, mesh=mesh)
    assert sharded.verdict_as_expected, sharded.failed
    assert not sharded.expected_failures
    assert sharded.metrics.get("device.dispatches_per_tick")
    single = run_scenario("f_crash_partition", seed=7,
                          device_quorum=True, quorum_tick_interval=0.05,
                          quorum_tick_adaptive=True)
    assert sharded.ordered_hash_per_node == single.ordered_hash_per_node
