"""Metrics collector + recorder/replayer (VERDICT round-2 item 9).

Reference: plenum/common/metrics_collector.py, plenum/recorder/. The
acceptance criterion: a recorded sim run replays into a FRESH node and
produces an identical ordered log (and identical committed state roots).
"""
from indy_plenum_tpu.common.metrics_collector import (
    KvMetricsCollector,
    MetricsCollector,
    MetricsName,
)
from indy_plenum_tpu.config import getConfig
from indy_plenum_tpu.recorder import Recorder, Replayer
from indy_plenum_tpu.recorder.recorder import ReplayNetwork
from indy_plenum_tpu.simulation.node_pool import NodePool


def test_metrics_collector_stats_and_measure_time():
    m = MetricsCollector()
    for v in (2.0, 4.0, 6.0):
        m.add_event("x", v)
    s = m.stat("x")
    assert (s.count, s.total, s.min, s.max, s.avg) == (3, 12.0, 2.0, 6.0, 4.0)
    with m.measure_time("t"):
        pass
    assert m.stat("t").count == 1
    assert "x" in m.summary() and "t" in m.summary()


def test_kv_metrics_collector_persists():
    from indy_plenum_tpu.storage.kv_store import KeyValueStorageInMemory

    store = KeyValueStorageInMemory()
    m = KvMetricsCollector(store, flush_every=2)
    m.add_event("a", 1.0)
    m.add_event("a", 3.0)  # second event triggers flush
    persisted = KvMetricsCollector(store).load_persisted()
    assert persisted["a"]["count"] == 2
    assert persisted["a"]["sum"] == 4.0
    # restart: a reopened collector SEEDS from the store and keeps counting
    reopened = KvMetricsCollector(store, flush_every=1)
    reopened.add_event("a", 5.0)
    assert reopened.stat("a").count == 3
    assert KvMetricsCollector(store).load_persisted()["a"]["count"] == 3


def test_node_and_device_plane_emit_metrics():
    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 5,
                        "PropagateBatchWait": 0.05,
                        "QuorumTickInterval": 0.05})
    pool = NodePool(4, seed=81, config=config, device_quorum=True)
    for _ in range(4):
        pool.submit_to("node0", pool.make_nym_request())
    pool.run_for(20)
    assert all(len(n.ordered_digests) == 4 for n in pool.nodes)

    node = pool.node("node0")
    summary = node.metrics.summary()
    assert summary[MetricsName.AUTH_BATCH_SIZE]["count"] >= 1
    assert summary[MetricsName.AUTH_BATCH_TIME]["sum"] > 0
    assert summary[MetricsName.ORDERED_BATCH_SIZE]["sum"] >= 4
    assert summary[MetricsName.COMMIT_TIME]["count"] >= 1
    # the pool-level device plane accounts its flushes + latencies
    dev = pool.vote_group.metrics.summary()
    assert dev[MetricsName.DEVICE_FLUSH]["count"] == pool.vote_group.flushes
    assert dev[MetricsName.DEVICE_FLUSH_TIME]["sum"] > 0


def test_recorded_run_replays_to_identical_ordered_log(tmp_path):
    """Record everything node2 saw during a live pool run; replay it into
    a brand-new node: identical ordered log, ledger and state roots."""
    from indy_plenum_tpu.common.constants import DOMAIN_LEDGER_ID
    from indy_plenum_tpu.server.node import Node
    from indy_plenum_tpu.simulation.mock_timer import MockTimer

    pool = NodePool(4, seed=82)
    recorder = Recorder()
    recorder.attach(pool.node("node2"))

    for i in range(6):
        pool.submit_to(f"node{i % 4}", pool.make_nym_request())
    pool.run_for(25)
    original = pool.node("node2")
    assert len(original.ordered_digests) == 6
    assert recorder.entries

    # persistence round-trip (the debugging workflow: dump, load, replay)
    path = str(tmp_path / "node2.rec")
    recorder.dump(path)
    loaded = Recorder.load(path)
    assert len(loaded.entries) == len(recorder.entries)

    fresh_timer = MockTimer(start_time=1_700_000_000.0)
    fresh = Node(
        "node2", list(pool.validators), fresh_timer, ReplayNetwork(),
        config=pool.config,
        domain_genesis=[dict(t) for t in pool._domain_genesis],
        seed_keys=dict(pool._seed_keys))
    fresh.start()
    Replayer(loaded).replay_into(fresh, fresh_timer)
    fresh_timer.advance(30)

    assert fresh.ordered_digests == original.ordered_digests
    for lid in (DOMAIN_LEDGER_ID,):
        assert (fresh.boot.db.get_ledger(lid).root_hash
                == original.boot.db.get_ledger(lid).root_hash)
        assert (fresh.boot.db.get_state(lid).committed_head_hash
                == original.boot.db.get_state(lid).committed_head_hash)


def test_metrics_last_and_bounded_histogram():
    """Stat.last tracks the CURRENT value of control variables (the
    governor's effective tick interval) and histograms stay bounded."""
    from indy_plenum_tpu.common.metrics_collector import (
        HISTOGRAM_MAX_BUCKETS,
        HISTOGRAM_OVERFLOW_KEY,
        NullMetricsCollector,
    )

    m = MetricsCollector()
    m.add_event("x", 2.0)
    m.add_event("x", 5.0)
    assert m.stat("x").last == 5.0
    assert m.summary()["x"]["last"] == 5.0

    for v in (0.05, 0.05, 0.1):
        m.add_to_histogram("h", v)
    assert m.histogram("h") == {0.05: 2, 0.1: 1}
    assert m.histogram("missing") is None
    # returned histogram is a copy, not the live dict
    m.histogram("h")["h4x"] = 99
    assert "h4x" not in m.histogram("h")

    for i in range(HISTOGRAM_MAX_BUCKETS + 100):
        m.add_to_histogram("b", i)
    hist = m.histogram("b")
    assert len(hist) == HISTOGRAM_MAX_BUCKETS + 1
    assert hist[HISTOGRAM_OVERFLOW_KEY] == 100

    null = NullMetricsCollector()
    null.add_to_histogram("h", 1)
    assert null.histogram("h") is None


def test_measure_time_exception_lands_in_error_series():
    """Satellite: a raising body must NOT pollute the hot-path series —
    its timing lands under <name>.error instead."""
    import pytest

    m = MetricsCollector()
    with pytest.raises(ValueError):
        with m.measure_time("op"):
            raise ValueError("boom")
    assert m.stat("op") is None
    err = m.stat("op.error")
    assert err is not None and err.count == 1
    with m.measure_time("op"):
        pass
    assert m.stat("op").count == 1  # success path unaffected
    assert m.stat("op.error").count == 1

    from indy_plenum_tpu.common.metrics_collector import (
        NullMetricsCollector,
    )

    null = NullMetricsCollector()
    with pytest.raises(ValueError):
        with null.measure_time("op"):
            raise ValueError("still propagates")
    assert null.stat("op.error") is None


def test_kv_collector_close_flushes_partial_window():
    """Satellite: without close(), up to flush_every - 1 events are lost
    on a clean shutdown; close() flushes them (Node.stop calls it)."""
    from indy_plenum_tpu.storage.kv_store import KeyValueStorageInMemory

    store = KeyValueStorageInMemory()
    m = KvMetricsCollector(store, flush_every=1000)
    for _ in range(7):
        m.add_event("a")
    assert KvMetricsCollector(store).load_persisted() == {}  # unflushed
    m.close()
    assert KvMetricsCollector(store).load_persisted()["a"]["count"] == 7
    # the base collector's close() is a no-op (teardown can call it
    # unconditionally)
    MetricsCollector().close()


def test_kv_collector_persists_and_reseeds_histograms():
    """Satellite: governor.tick_interval dwell history must survive a
    restart — histograms persist alongside stats (float buckets intact)."""
    from indy_plenum_tpu.storage.kv_store import KeyValueStorageInMemory

    store = KeyValueStorageInMemory()
    m = KvMetricsCollector(store, flush_every=1000)
    for bucket in (0.05, 0.05, 0.1, "other"):
        m.add_to_histogram(MetricsName.GOVERNOR_TICK_INTERVAL, bucket)
    m.add_event("a", 2.0)
    m.close()

    reopened = KvMetricsCollector(store)
    hist = reopened.histogram(MetricsName.GOVERNOR_TICK_INTERVAL)
    assert hist == {0.05: 2, 0.1: 1, "other": 1}
    # keyspaces stay separate: histogram rows never read back as stats
    assert not any(k.startswith("hist!")
                   for k in reopened.load_persisted())
    # and it keeps counting into the seeded history
    reopened.add_to_histogram(MetricsName.GOVERNOR_TICK_INTERVAL, 0.05)
    reopened.close()
    assert KvMetricsCollector(store).histogram(
        MetricsName.GOVERNOR_TICK_INTERVAL)[0.05] == 3
