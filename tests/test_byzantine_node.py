"""Tier-6 byzantine scenarios at the Node layer.

Reference: plenum's byzantine test suites (plenum/test/malicious_behaviors
+ view_change tests). These run the REAL Node composition (ingress,
propagation, execution) under actively malicious behaviour, not just
delayed/dropped messages.
"""
import hashlib

from indy_plenum_tpu.common.messages.node_messages import PrePrepare
from indy_plenum_tpu.config import getConfig
from indy_plenum_tpu.simulation.node_pool import NodePool


def test_equivocating_primary_cannot_split_the_pool():
    """The primary sends DIFFERENT batches to different replicas for the
    same (view, seqNo). No conflicting batch can gather a prepare quorum
    (prepare votes are digest-filtered), the pool detects the stall, view
    changes, and the honest log stays consistent."""
    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 2,
                        "PropagateBatchWait": 0.05,
                        "ToleratePrimaryDisconnection": 10_000.0,
                        "NewViewTimeout": 5.0})
    pool = NodePool(4, seed=201, config=config)
    primary = pool.node("node0")
    assert primary.data.primaries[0] == "node0"

    # byzantine send hook: every PRE-PREPARE going to node2/node3 gets a
    # FORGED digest (an equivocation: content differs per recipient)
    original_send = pool.network._make_send_handler("node0")

    def equivocating_send(msg, dst=None):
        if isinstance(msg, PrePrepare):
            targets = sorted(set(pool.validators) - {"node0"})
            for to in targets:
                out = msg
                if to in ("node2", "node3"):
                    forged = msg._fields
                    forged["digest"] = hashlib.sha256(
                        (msg.digest + to).encode()).hexdigest()
                    out = PrePrepare(**forged)
                pool.network._deliver_later(out, "node0", to)
            return
        original_send(msg, dst)

    primary.external_bus._send_handler = equivocating_send

    pool.submit_to("node1", pool.make_nym_request())
    pool.run_for(60)

    honest = [n for n in pool.nodes if n.name != "node0"]
    # the equivocation could not split the honest nodes' logs
    logs = [tuple(n.ordered_digests) for n in honest]
    shortest = min(len(l) for l in logs)
    assert all(l[:shortest] == logs[0][:shortest] for l in logs)
    # and the pool escaped the faulty primary via view change
    assert all(n.data.view_no >= 1 for n in honest), \
        [n.data.view_no for n in honest]
    assert all(n.data.primaries[0] != "node0" for n in honest)


def test_byzantine_node_cannot_finalise_unsigned_request():
    """f byzantine propagates for a never-authenticated request cannot
    reach the f+1 quorum: every honest vote requires a verified signature."""
    from indy_plenum_tpu.common.messages.node_messages import Propagate
    from indy_plenum_tpu.common.request import Request

    pool = NodePool(4, seed=202)
    forged = Request(identifier=pool.trustee.identifier, reqId=999,
                     operation={"type": "1", "dest": "EvilDid",
                                "verkey": "EvilKey"})
    forged.signature = "1" * 88  # structurally plausible, never valid

    # node3 (byzantine, f=1) broadcasts PROPAGATE for the forged request
    evil_bus = pool.node("node3").external_bus
    evil_bus.send(Propagate(request=forged.as_dict(), senderClient="evil"))
    pool.run_for(15)

    for node in pool.nodes:
        if node.name == "node3":
            continue
        state = node.propagator.requests.get(forged.digest)
        # recorded at most the byzantine vote; never finalised, never
        # ordered, never executed
        assert state is None or not state.finalised, node.name
        assert forged.digest not in node.ordered_digests
        assert node.get_nym_data("EvilDid") is None


def test_everything_on_integration():
    """The whole stack at once: real Nodes, BLS multi-signatures, grouped
    device vote plane as sole authority with tick batching, f+1 backup
    instances + monitor, pool-ledger membership — ordering, checkpointing
    and proved reads all working together."""
    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 4,
                        "PropagateBatchWait": 0.05,
                        "QuorumTickInterval": 0.05,
                        "CHK_FREQ": 5, "LOG_SIZE": 15,
                        "ThroughputWindowSize": 5, "ThroughputMinCnt": 4})
    pool = NodePool(4, seed=203, config=config, device_quorum=True,
                    bls=True, num_instances=0, with_pool_genesis=True)
    client = pool.make_client()
    digests = []
    for i in range(24):
        req = pool.make_nym_request()
        digests.append(client.submit_write(req))
    pool.run_for(60)
    pool.pump_client(client)

    for node in pool.nodes:
        assert len(node.ordered_digests) == 24, node.name
        assert node.data.stable_checkpoint >= 5, node.name
        assert node.replicas.backups, node.name  # RBFT instances live
    assert pool.vote_group.flushes > 0
    assert all(client.result(d) is not None for d in digests)

    # proved read through BLS on the device-quorum pool
    from indy_plenum_tpu.common.constants import GET_NYM, TARGET_NYM, TXN_TYPE
    from indy_plenum_tpu.common.request import Request

    target_did = None
    for d in digests:
        target_did = client.result(d)["txn"]["data"]["dest"]
        break
    read = Request(identifier="reader", reqId=5000,
                   operation={TXN_TYPE: GET_NYM, TARGET_NYM: target_did})
    rd = client.submit_read(read, to="node3")
    pool.pump_client(client)
    assert client.result(rd) is not None
