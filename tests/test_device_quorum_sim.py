"""Device quorum plane drives the consensus path (VERDICT round-1 item 2).

SimPool(device_quorum=True) wires a DeviceVotePlane into every node's
OrderingService: prepare/commit certificates are decided by the dense
device vote tensors (tpu.quorum.QuorumEvents), with shadow_check asserting
dict-derived quorum == device verdict on every query. These tests prove the
ordering decisions come from the device plane, across the full protocol:
ordering, checkpoints/watermark slides, and view change resets.
"""
import pytest

from indy_plenum_tpu.config import getConfig
from indy_plenum_tpu.simulation.pool import SimPool


def test_device_plane_orders_4_nodes():
    pool = SimPool(4, seed=21, device_quorum=True)
    for i in range(8):
        pool.submit_request(i)
    pool.run_for(10)
    assert pool.honest_nodes_agree()
    for node in pool.nodes:
        assert len(node.ordered_digests) == 8, node.name
        # decisions demonstrably came from the device: the plane flushed
        # vote batches and its verdicts were returned (shadow_check would
        # have raised on any divergence from the dict tallies)
        assert node.vote_plane is not None
        assert node.vote_plane.flushes > 0, node.name


def test_device_plane_matches_host_only_run():
    def digests(device):
        pool = SimPool(4, seed=22, device_quorum=device)
        for i in range(6):
            pool.submit_request(i)
        pool.run_for(8)
        return [tuple(n.ordered_digests) for n in pool.nodes]

    assert digests(True) == digests(False)


def test_device_plane_watermark_slide():
    cfg = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 1,
                     "CHK_FREQ": 5, "LOG_SIZE": 15})
    pool = SimPool(4, seed=23, config=cfg, device_quorum=True)
    for i in range(12):
        pool.submit_request(i)
    pool.run_for(20)
    assert pool.honest_nodes_agree()
    for node in pool.nodes:
        assert node.data.last_ordered_3pc[1] >= 12
        assert node.data.stable_checkpoint >= 10
        # the plane's window slid with the stable checkpoint
        assert node.vote_plane.h == node.data.low_watermark


def test_device_plane_survives_view_change():
    pool = SimPool(4, seed=24, device_quorum=True)
    primary_name = pool.nodes[0].data.primaries[0]
    for i in range(4):
        pool.submit_request(i)
    pool.run_for(5)
    assert all(len(n.ordered_digests) == 4 for n in pool.nodes)

    pool.network.disconnect(primary_name)
    pool.run_for(pool.config.ToleratePrimaryDisconnection + 8)

    survivors = [n for n in pool.nodes if n.name != primary_name]
    for node in survivors:
        assert node.data.view_no >= 1
        assert not node.data.waiting_for_new_view

    for i in range(100, 105):
        pool.submit_request(i)
    pool.run_for(10)
    logs = [tuple(n.ordered_digests) for n in survivors]
    assert len(set(logs)) == 1
    assert len(logs[0]) == 9


def test_tick_batched_sole_authority_orders_and_checkpoints():
    """Tick-batched mode (the bench/Node-event-loop configuration): no host
    shadow tallies, quorum queries read per-tick snapshots of the grouped
    vote plane, and the WHOLE pool's votes ride one vmapped flush per tick.
    Checkpoint stabilization must also progress (retried via service_tick
    when the snapshot was stale at message time)."""
    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 2,
                        "CHK_FREQ": 5, "LOG_SIZE": 15,
                        "QuorumTickInterval": 0.05})
    pool = SimPool(4, seed=31, config=config, device_quorum=True,
                   shadow_check=False)
    for i in range(24):
        pool.submit_request(i)
    pool.run_for(30)
    assert pool.honest_nodes_agree()
    for node in pool.nodes:
        assert len(node.ordered_digests) == 24, node.name
        assert node.data.stable_checkpoint >= 10, node.name
        assert node.vote_plane.h == node.data.low_watermark
    # amortization: far fewer group flushes than messages processed
    assert pool.vote_group.flushes < pool.network.sent / 4


def test_tick_batched_survives_view_change():
    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 2,
                        "QuorumTickInterval": 0.05})
    pool = SimPool(4, seed=32, config=config, device_quorum=True,
                   shadow_check=False)
    primary_name = pool.nodes[0].data.primaries[0]
    for i in range(4):
        pool.submit_request(i)
    pool.run_for(10)
    assert all(len(n.ordered_digests) == 4 for n in pool.nodes)

    pool.network.disconnect(primary_name)
    pool.run_for(pool.config.ToleratePrimaryDisconnection + 10)
    survivors = [n for n in pool.nodes if n.name != primary_name]
    for node in survivors:
        assert node.data.view_no >= 1, node.name
        assert not node.data.waiting_for_new_view, node.name

    for i in range(100, 105):
        pool.submit_request(i)
    pool.run_for(15)
    logs = [tuple(n.ordered_digests) for n in survivors]
    assert len(set(logs)) == 1
    assert len(logs[0]) == 9


def test_sim_pool_rbft_instances_on_device_plane():
    """SimPool's RBFT instance axis (the bench's full-RBFT config at
    miniature scale): f+1 instances per node, every backup's tallies on
    the shared (node x instance) device group, one flush wave per tick."""
    from indy_plenum_tpu.config import getConfig
    from indy_plenum_tpu.simulation.pool import SimPool

    cfg = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 5,
                     "QuorumTickInterval": 0.05})
    pool = SimPool(4, seed=5, config=cfg, device_quorum=True,
                   shadow_check=False, num_instances=0)  # auto f+1 = 2
    assert pool.num_instances == 2
    for n in pool.nodes:
        assert len(n.replicas.backups) == 1
        assert n.replicas.backups[0].vote_plane is not None
    for i in range(6):
        pool.submit_request(i)
    pool.run_for(25)
    assert all(len(n.ordered_digests) == 6 for n in pool.nodes)
    assert pool.honest_nodes_agree()
    # the backup instance (primary node1) ordered the same traffic
    for n in pool.nodes:
        assert n.replicas.backups[0].data.last_ordered_3pc[1] >= 1
    assert pool.vote_group.flushes > 0


def test_pipelined_flush_orders_with_one_tick_lag():
    """Round-5 pipelined flush: each tick DISPATCHES the step and absorbs
    the previous tick's events, so the device round-trip overlaps host
    work. Verdicts lag one tick; the lost-wakeup guard must keep the pool
    making progress to full ordering anyway."""
    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 2,
                        "CHK_FREQ": 5, "LOG_SIZE": 15,
                        "QuorumTickInterval": 0.05})
    pool = SimPool(4, seed=31, config=config, device_quorum=True,
                   shadow_check=False, pipelined_flush=True)
    assert pool.vote_group.pipelined
    for i in range(24):
        pool.submit_request(i)
    pool.run_for(30)
    assert pool.honest_nodes_agree()
    for node in pool.nodes:
        assert len(node.ordered_digests) == 24, node.name
        # checkpoint stabilization (window slide syncs the in-flight step)
        assert node.data.stable_checkpoint >= 10, node.name
        assert node.vote_plane.h == node.data.low_watermark


def test_pipelined_flush_survives_view_change():
    """View change resets a member's plane mid-pipeline: the in-flight
    step is absorbed BEFORE the zeroing, so old-view events can't land in
    the new view's snapshot, and the pool still re-converges."""
    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 2,
                        "QuorumTickInterval": 0.05})
    pool = SimPool(4, seed=32, config=config, device_quorum=True,
                   shadow_check=False, pipelined_flush=True)
    primary_name = pool.nodes[0].data.primaries[0]
    for i in range(4):
        pool.submit_request(i)
    pool.run_for(10)
    assert all(len(n.ordered_digests) == 4 for n in pool.nodes)
    pool.network.disconnect(primary_name)
    pool.run_for(pool.config.ToleratePrimaryDisconnection + 10)
    survivors = [n for n in pool.nodes if n.name != primary_name]
    for node in survivors:
        assert node.data.view_no >= 1, node.name
        assert not node.data.waiting_for_new_view, node.name
    for i in range(100, 105):
        pool.submit_request(i)
    pool.run_for(15)
    logs = [tuple(n.ordered_digests) for n in survivors]
    assert len(set(logs)) == 1
    assert len(logs[0]) == 9


def test_rbft_pipelined_with_accounting():
    """The round-5 bench configuration end-to-end at miniature scale:
    RBFT instance axis + pipelined flush + per-host CPU accounting."""
    cfg = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 5,
                     "QuorumTickInterval": 0.05})
    pool = SimPool(4, seed=5, config=cfg, device_quorum=True,
                   shadow_check=False, num_instances=0,
                   host_accounting=True, pipelined_flush=True)
    for i in range(6):
        pool.submit_request(i)
    pool.run_for(25)
    assert all(len(n.ordered_digests) == 6 for n in pool.nodes)
    assert pool.honest_nodes_agree()
    for n in pool.nodes:
        assert n.replicas.backups[0].data.last_ordered_3pc[1] >= 1
    # every node accrued SOME host time, and nobody is a wild outlier
    # (symmetric protocol work modulo the primary's batch builds)
    assert all(s > 0 for s in pool.host_seconds.values())


def test_pipelined_flush_without_tick_driver_degenerates_to_sync():
    """pipelined=True with QuorumTickInterval=0 (no tick driver): per-query
    refresh must absorb the in-flight step, or the final batch's commit
    votes sit on-device forever and the pool stalls at quiescence."""
    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 2,
                        "QuorumTickInterval": 0.0})
    pool = SimPool(4, seed=33, config=config, device_quorum=True,
                   shadow_check=False, pipelined_flush=True)
    for i in range(6):
        pool.submit_request(i)
    pool.run_for(20)
    assert all(len(n.ordered_digests) == 6 for n in pool.nodes)
    assert pool.honest_nodes_agree()
