"""Tier 3: in-process socket pools over the authenticated ZMQ transport.

VERDICT round-2 item 6: the node-to-node plane is authenticated — sender
attribution comes from the connection's Curve25519 key (ZAP User-Id), so a
message forged under another node's name is attributed to its REAL sender,
and an unknown key cannot complete the handshake at all.

Reference: stp_zmq/zstack.py + stp_zmq tests (test_zstack.py).
"""
import hashlib
import time

import pytest

from indy_plenum_tpu.common.looper import Looper
from indy_plenum_tpu.common.messages.node_messages import Checkpoint
from indy_plenum_tpu.network import ZStack, ZStackNetwork
from indy_plenum_tpu.server.node import Node


def seed_of(name: str) -> bytes:
    return hashlib.sha256(b"zstack-test-" + name.encode()).digest()


def make_msg(n: int = 1) -> Checkpoint:
    return Checkpoint(instId=0, viewNo=0, seqNoStart=1, seqNoEnd=n,
                      digest="d" * 16)


def pump(stacks, seconds: float) -> None:
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        if sum(s.service() for s in stacks) == 0:
            time.sleep(0.002)


def wire(names):
    stacks = {n: ZStack(n, seed_of(n)) for n in names}
    for a in stacks.values():
        for b in stacks.values():
            if a is not b:
                a.allow_peer(b.name, b.public_key)
                a.connect(b.name, b.ha, b.public_key)
    return stacks


def test_messages_flow_and_are_attributed_by_curve_key():
    stacks = wire(["A", "B"])
    got = []
    stacks["A"].on_message = lambda msg, frm: got.append((msg, frm))
    stacks["B"].send(make_msg(), ["A"])
    pump(list(stacks.values()), 1.5)
    assert got, "message did not arrive"
    msg, frm = got[0]
    # attribution is the AUTHENTICATED key owner — nothing B put in the
    # message content can change it
    assert frm == "B"
    assert isinstance(msg, Checkpoint)
    for s in stacks.values():
        s.close()


def test_trace_context_piggybacks_on_the_envelope():
    """Causal tracing plane over real sockets: a traced PREPARE carries
    the ~trc context on the wire; the sender stamps net.send, the
    receiver strips the context before schema validation and stamps a
    net.recv joinable by (viewNo, ppSeqNo) + flow id."""
    from indy_plenum_tpu.common.messages.node_messages import Prepare
    from indy_plenum_tpu.observability.trace import TraceRecorder

    stacks = wire(["A", "B"])
    try:
        stacks["A"].trace = TraceRecorder(time.perf_counter, node="A")
        stacks["B"].trace = TraceRecorder(time.perf_counter, node="B")
        got = []
        stacks["B"].on_message = lambda msg, frm: got.append((msg, frm))
        stacks["A"].send(
            Prepare(instId=0, viewNo=2, ppSeqNo=7, ppTime=time.time(),
                    digest="d" * 16, stateRootHash=None,
                    txnRootHash=None),
            ["B"])
        pump(list(stacks.values()), 1.5)
        assert got, "traced message did not arrive"
        msg, frm = got[0]
        assert frm == "A" and msg.viewNo == 2 and msg.ppSeqNo == 7
        sends = [e for e in stacks["A"].trace.events()
                 if e["name"] == "net.send"]
        recvs = [e for e in stacks["B"].trace.events()
                 if e["name"] == "net.recv"]
        assert len(sends) == 1 and len(recvs) == 1
        assert sends[0]["key"] == [2, 7] == recvs[0]["key"]
        # the flow id propagated THROUGH the wire, not via shared state
        assert recvs[0]["args"]["id"] == sends[0]["args"]["id"]
        # the sender's clock reading rode along (offset estimate)
        assert recvs[0]["args"]["sent"] == pytest.approx(
            sends[0]["ts"], abs=1e-6)
        # untraced messages stay byte-compatible: no context injected
        stacks["A"].trace = TraceRecorder(time.perf_counter, node="A")
        stacks["A"].send(make_msg(), ["B"])
        pump(list(stacks.values()), 1.5)
        assert len(got) == 2 and isinstance(got[1][0], Checkpoint)
    finally:
        for s in stacks.values():
            s.close()


def test_unknown_curve_key_cannot_deliver():
    stacks = wire(["A", "B"])
    attacker = ZStack("evil", seed_of("evil"))
    # attacker knows A's address and public key but is NOT in A's registry
    attacker.connect("A", stacks["A"].ha, stacks["A"].public_key)
    got = []
    stacks["A"].on_message = lambda msg, frm: got.append((msg, frm))
    attacker.send(make_msg(), ["A"])
    pump([*stacks.values(), attacker], 1.5)
    assert got == []
    assert stacks["A"].rejected_unknown_key > 0
    for s in [*stacks.values(), attacker]:
        s.close()


def test_peer_cannot_speak_under_another_name():
    """C is a legitimate pool member, but anything it sends is attributed
    to C by its curve key — it cannot inject votes as B."""
    stacks = wire(["A", "B", "C"])
    got = []
    stacks["A"].on_message = lambda msg, frm: got.append(frm)
    stacks["C"].send(make_msg(), ["A"])
    pump(list(stacks.values()), 1.5)
    assert got == ["C"]
    for s in stacks.values():
        s.close()


def test_batch_coalescing_roundtrip():
    stacks = wire(["A", "B"])
    got = []
    stacks["A"].on_message = lambda msg, frm: got.append(msg)
    for i in range(25):
        stacks["B"].send(make_msg(i + 1), ["A"])
    pump(list(stacks.values()), 1.5)
    assert len(got) == 25
    assert {m.seqNoEnd for m in got} == set(range(1, 26))
    for s in stacks.values():
        s.close()


def test_socket_pool_orders_requests_end_to_end():
    """A real 4-node pool over real sockets: full Node stacks, Looper
    runtime, client requests ordered and executed everywhere."""
    from indy_plenum_tpu.common.constants import TRUSTEE
    from indy_plenum_tpu.common.request import Request
    from indy_plenum_tpu.config import getConfig
    from indy_plenum_tpu.crypto.signers import DidSigner
    from indy_plenum_tpu.ledger.genesis import genesis_nym_txn

    names = [f"node{i}" for i in range(4)]
    config = getConfig({"Max3PCBatchWait": 0.05, "Max3PCBatchSize": 10,
                        "PropagateBatchWait": 0.02})
    trustee = DidSigner(b"\x09" * 32)
    genesis = [genesis_nym_txn(trustee.identifier, trustee.verkey,
                               role=TRUSTEE)]

    looper = Looper()
    stacks = wire(names)
    nodes = []
    for name in names:
        net = ZStackNetwork(stacks[name])
        node = Node(name, names, looper.timer, net, config=config,
                    domain_genesis=[dict(t) for t in genesis],
                    seed_keys={trustee.identifier: trustee.verkey})
        net.mark_connected(set(names) - {name})
        node.start()
        looper.add(stacks[name])
        nodes.append(node)

    reqs = []
    for i in range(6):
        from indy_plenum_tpu.common.constants import (
            NYM, TARGET_NYM, TXN_TYPE, VERKEY)

        target = DidSigner(hashlib.sha256(b"sock-target-%d" % i).digest())
        req = Request(identifier=trustee.identifier, reqId=i + 1,
                      operation={TXN_TYPE: NYM,
                                 TARGET_NYM: target.identifier,
                                 VERKEY: target.verkey})
        trustee.sign_request(req)
        reqs.append(req)

    # warm the device verify kernel OUTSIDE the liveness budget (first XLA
    # compile of the Ed25519 batch kernel can take tens of seconds)
    assert nodes[0].authnr.authenticate_batch([reqs[0]]).all()

    for i, req in enumerate(reqs):
        nodes[i % 4].submit_client_request(req, client_id="cli")

    ok = looper.run_until(
        lambda: all(len(n.ordered_digests) == 6 for n in nodes),
        timeout=30)
    assert ok, [len(n.ordered_digests) for n in nodes]
    logs = [tuple(n.ordered_digests) for n in nodes]
    assert len(set(logs)) == 1
    for node in nodes:
        for req in reqs:
            assert node.get_nym_data(req.operation["dest"]) is not None
    looper.shutdown()
    for node in nodes:
        node.stop()
    for s in stacks.values():
        s.close()


def test_malformed_batch_from_authenticated_peer_is_contained():
    """An authenticated pool member sending nested/malformed BATCH
    envelopes must not crash the receiver's service loop (DoS guard)."""
    from indy_plenum_tpu.common.messages.node_messages import Batch
    from indy_plenum_tpu.common.serializers.serialization import (
        serialize_msg)

    stacks = wire(["A", "B"])
    got = []
    stacks["A"].on_message = lambda msg, frm: got.append(msg)

    # deeply nested batches (recursion bomb) — raw bytes via the dealer
    payload = serialize_msg(make_msg().as_dict())
    for _ in range(1200):
        payload = serialize_msg(
            Batch(messages=[payload], signature=None).as_dict())
    sock = stacks["B"]._remotes["A"]
    sock.send(payload)
    # batch with a str element (schema admits str; dispatch must not crash)
    sock.send(serialize_msg(
        Batch(messages=["not-bytes"], signature=None).as_dict()))
    # a healthy message afterwards still flows — the stack survived
    stacks["B"].send(make_msg(42), ["A"])
    pump(list(stacks.values()), 1.5)
    assert [m.seqNoEnd for m in got] == [42]
    for s in stacks.values():
        s.close()


def test_primary_crash_detected_and_view_changed_over_sockets():
    """Socket liveness: the primary's process dies (stack closed); the
    libzmq monitors report the drop, the primary-disconnect detector
    votes, and the pool completes a view change over REAL sockets."""
    from indy_plenum_tpu.common.constants import TRUSTEE
    from indy_plenum_tpu.config import getConfig
    from indy_plenum_tpu.crypto.signers import DidSigner
    from indy_plenum_tpu.ledger.genesis import genesis_nym_txn

    names = [f"node{i}" for i in range(4)]
    config = getConfig({"Max3PCBatchWait": 0.05, "Max3PCBatchSize": 10,
                        "PropagateBatchWait": 0.02,
                        "ToleratePrimaryDisconnection": 1.0})
    trustee = DidSigner(b"\x09" * 32)
    genesis = [genesis_nym_txn(trustee.identifier, trustee.verkey,
                               role=TRUSTEE)]
    looper = Looper()
    stacks = wire(names)
    nodes = []
    for name in names:
        net = ZStackNetwork(stacks[name])
        node = Node(name, names, looper.timer, net, config=config,
                    domain_genesis=[dict(t) for t in genesis],
                    seed_keys={trustee.identifier: trustee.verkey})
        net.mark_connected(set(names) - {name})
        node.start()
        looper.add(stacks[name])
        nodes.append(node)
    # let the curve handshakes complete
    looper.run_for(1.0)

    assert nodes[1].data.primaries[0] == "node0"
    looper.remove(stacks["node0"])
    nodes[0].stop()
    stacks["node0"].close()  # the primary process dies

    survivors = nodes[1:]
    ok = looper.run_until(
        lambda: all(n.data.view_no >= 1 and not n.data.waiting_for_new_view
                    for n in survivors),
        timeout=30)
    assert ok, [(n.name, n.data.view_no) for n in survivors]
    assert all(n.data.primaries[0] != "node0" for n in survivors)
    for n in survivors:
        n.stop()
    looper.shutdown()
    for name in names[1:]:
        stacks[name].close()


def test_hwm_drop_is_counted():
    """Silent HWM drops are now observable: the stack counts messages
    lost to a full peer queue (and reports them to metrics when wired)."""
    import zmq

    from indy_plenum_tpu.common.metrics_collector import (
        MetricsCollector,
        MetricsName,
    )

    stacks = wire(["A", "B"])
    metrics = MetricsCollector()
    stacks["B"]._metrics = metrics
    real_sock = stacks["B"]._remotes["A"]

    class FullSocket:
        def send(self, *a, **k):
            raise zmq.Again()

    stacks["B"]._remotes["A"] = FullSocket()
    for i in range(3):
        stacks["B"].send(make_msg(i + 1), ["A"])
    stacks["B"]._flush()
    assert stacks["B"].dropped == 3
    stat = metrics.stat(MetricsName.ZSTACK_DROPPED)
    assert stat is not None and stat.total == 3
    stacks["B"]._remotes["A"] = real_sock
    for s in stacks.values():
        s.close()


def test_looper_drains_transports_before_timer_events():
    """The zstack transport barrier: within one pump pass, prodables
    (socket drains) run BEFORE due timer events, so a barrier quorum
    tick always evaluates a drained transport."""
    from indy_plenum_tpu.common.looper import Looper

    looper = Looper()
    order = []

    class FakeStack:
        def service(self):
            order.append("drain")
            return 0

    looper.add(FakeStack())
    looper.timer.schedule(0.0, lambda: order.append("tick"))
    looper._pump_once()
    assert order == ["drain", "tick"]


@pytest.mark.slow
def test_zstack_barrier_tick_with_governor_over_sockets():
    """Deployed-node dispatch plane: 4 full Nodes over REAL sockets, each
    flushing its own device vote plane on a governed barrier tick. The
    pool orders identically on every node, the tick amortizes (far fewer
    device dispatches than transport messages), and the governor runs —
    the live-transport analog of the sim pools' tick contract."""
    from indy_plenum_tpu.common.constants import TRUSTEE
    from indy_plenum_tpu.common.metrics_collector import (
        MetricsCollector,
        MetricsName,
    )
    from indy_plenum_tpu.common.request import Request
    from indy_plenum_tpu.config import getConfig
    from indy_plenum_tpu.crypto.signers import DidSigner
    from indy_plenum_tpu.ledger.genesis import genesis_nym_txn
    from indy_plenum_tpu.tpu.vote_plane import DeviceVotePlane

    names = [f"node{i}" for i in range(4)]
    config = getConfig({"Max3PCBatchWait": 0.05, "Max3PCBatchSize": 10,
                        "PropagateBatchWait": 0.02,
                        "QuorumTickInterval": 0.05,
                        "QuorumTickAdaptive": True})
    trustee = DidSigner(b"\x09" * 32)
    genesis = [genesis_nym_txn(trustee.identifier, trustee.verkey,
                               role=TRUSTEE)]

    looper = Looper()
    stacks = wire(names)
    nodes = []
    for name in names:
        net = ZStackNetwork(stacks[name])
        plane = DeviceVotePlane(
            names, log_size=config.LOG_SIZE,
            n_checkpoints=max(1, config.LOG_SIZE // config.CHK_FREQ))
        node = Node(name, names, looper.timer, net, config=config,
                    domain_genesis=[dict(t) for t in genesis],
                    seed_keys={trustee.identifier: trustee.verkey},
                    vote_plane=plane, metrics=MetricsCollector())
        net.mark_connected(set(names) - {name})
        node.start()
        looper.add(stacks[name])
        nodes.append(node)

    reqs = []
    for i in range(6):
        from indy_plenum_tpu.common.constants import (
            NYM, TARGET_NYM, TXN_TYPE, VERKEY)

        target = DidSigner(hashlib.sha256(b"gov-target-%d" % i).digest())
        req = Request(identifier=trustee.identifier, reqId=i + 1,
                      operation={TXN_TYPE: NYM,
                                 TARGET_NYM: target.identifier,
                                 VERKEY: target.verkey})
        trustee.sign_request(req)
        reqs.append(req)

    # compile device kernels outside the liveness budget
    assert nodes[0].authnr.authenticate_batch([reqs[0]]).all()
    nodes[0].vote_plane.sync()

    for i, req in enumerate(reqs):
        nodes[i % 4].submit_client_request(req, client_id="cli")

    ok = looper.run_until(
        lambda: all(len(n.ordered_digests) == 6 for n in nodes),
        timeout=60)
    assert ok, [len(n.ordered_digests) for n in nodes]
    assert len({tuple(n.ordered_digests) for n in nodes}) == 1

    for node in nodes:
        # the barrier tick actually drove the plane (and the governor)
        per_tick = node.metrics.stat(MetricsName.DEVICE_DISPATCHES_PER_TICK)
        assert per_tick is not None and per_tick.count > 0
        assert node._dispatch_governor is not None
        assert node._dispatch_governor.ticks > 0
        lo, hi = config.governor_bounds()
        assert lo <= node._dispatch_governor.interval <= hi
        assert node.metrics.histogram(MetricsName.GOVERNOR_TICK_INTERVAL)
        # amortization over the live transport: one tick's grouped step
        # covers many socket deliveries (transport Batch envelopes mean
        # `received` already undercounts protocol messages, so flushes
        # beating even that is a conservative bar)
        received = stacks[node.name].received
        assert received > 15
        assert node.vote_plane.flushes < 0.5 * received, (
            node.vote_plane.flushes, received)

    looper.shutdown()
    for node in nodes:
        node.stop()
    for s in stacks.values():
        s.close()
