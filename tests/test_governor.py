"""Dispatch governor: convergence, determinism, identity, observability.

The adaptive tick's contract (README "Performance"): the interval is a
pure function of the observed dispatch metrics — occupancy EWMA widens
sparse pools toward QuorumTickIntervalMax, chained/hot ticks narrow
toward QuorumTickIntervalMin — so a seeded run replays to the identical
trajectory, and batching cadence NEVER changes ordering outcomes.
"""
import pytest

from indy_plenum_tpu.config import getConfig
from indy_plenum_tpu.simulation.pool import SimPool
from indy_plenum_tpu.tpu.governor import DispatchGovernor


def make_governor(**kw):
    defaults = dict(interval=0.05, min_interval=0.0125, max_interval=0.2,
                    alpha=0.3, occupancy_low=0.02, occupancy_high=0.85,
                    widen=1.5, narrow=0.5)
    defaults.update(kw)
    return DispatchGovernor(**defaults)


def _adaptive_pool(seed=41, tick=0.05, overrides=None, **kwargs):
    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 2,
                        "QuorumTickInterval": tick,
                        "QuorumTickAdaptive": tick > 0,
                        **(overrides or {})})
    return SimPool(4, seed=seed, config=config, device_quorum=True,
                   shadow_check=False if tick > 0 else None, **kwargs)


# ---------------------------------------------------------------------
# control-law units
# ---------------------------------------------------------------------

def test_governor_bursty_idle_bursty_reaches_bounds():
    """The convergence contract: saturation pins the interval to the
    floor, a long idle stretch raises it to the ceiling, and a fresh
    burst brings it back down — never leaving the configured bounds."""
    g = make_governor()
    for _ in range(10):  # bursty: chained grouped steps, full scatters
        g.observe(votes=1536, capacity=1536, dispatches=3)
    assert g.interval == g.min_interval
    for _ in range(20):  # idle: occupancy EWMA decays below the floor
        g.observe(votes=0, capacity=0, dispatches=0)
    assert g.interval == g.max_interval
    for _ in range(10):  # bursty again
        g.observe(votes=1536, capacity=1536, dispatches=3)
    assert g.interval == g.min_interval
    assert min(g.trajectory) == g.min_interval
    assert max(g.trajectory) == g.max_interval
    assert g.ticks == 40 and len(g.trajectory) == 40


def test_governor_holds_inside_the_band():
    """One well-filled grouped step per tick is the plane's equilibrium:
    the governor must not oscillate around it."""
    g = make_governor()
    for _ in range(50):
        g.observe(votes=256, capacity=512, dispatches=1)  # occupancy 0.5
    assert g.interval == 0.05
    assert set(g.trajectory) == {0.05}


def test_governor_absorb_clamp_caps_effective_interval_only():
    """Ordering fast path: while a pipelined step's verdicts are in
    flight, the RETURNED interval is capped at the configured base so
    the absorb tick comes promptly — but the law's own interval state
    (and hence the occupancy trajectory it will follow once the wave
    completes) is untouched, and inflight=False calls stay bit-identical
    to the clamp-free law."""
    g = make_governor()
    for _ in range(20):  # idle: widen to the ceiling
        g.observe(votes=0, capacity=0, dispatches=0)
    assert g.interval == g.max_interval
    # a wave dispatches with verdicts in flight: effective cadence drops
    # to the base interval, law state holds at what occupancy says
    eff = g.observe(votes=32, capacity=512, dispatches=1, inflight=True)
    assert eff == g.absorb_interval == 0.05
    assert g.interval == g.max_interval  # law state undisturbed
    assert g.absorb_clamps == 1
    assert g.trajectory[-1] == eff  # trajectory records the real cadence
    # wave complete: the law cadence resumes instantly
    assert g.observe(votes=0, capacity=0, dispatches=0) == g.max_interval
    # law already at/below base: inflight must not touch the interval
    tight = make_governor()
    for _ in range(10):
        tight.observe(votes=1536, capacity=1536, dispatches=3)
    assert tight.interval == tight.min_interval
    assert tight.observe(votes=512, capacity=512, dispatches=1,
                         inflight=True) == tight.min_interval
    assert tight.absorb_clamps == 0
    # inflight=False twin: bit-identical to the pre-clamp law
    a, b = make_governor(), make_governor()
    seq = [(0, 0, 0)] * 6 + [(128, 512, 1)] * 4 + [(0, 0, 0)] * 3
    for votes, cap, disp in seq:
        a.observe(votes, cap, disp)
        b.observe(votes, cap, disp, inflight=False)
    assert a.trajectory == b.trajectory and b.absorb_clamps == 0


def test_governor_determinism_same_observation_sequence():
    seq = ([(0, 0, 0)] * 5 + [(512, 512, 2)] * 7 + [(3, 64, 1)] * 9
           + [(0, 0, 0)] * 4)
    a, b = make_governor(), make_governor()
    for votes, cap, disp in seq:
        a.observe(votes, cap, disp)
        b.observe(votes, cap, disp)
    assert a.trajectory == b.trajectory
    assert a.ewma == b.ewma
    assert a.trajectory_summary() == b.trajectory_summary()


def test_governor_parameter_validation():
    with pytest.raises(ValueError):
        DispatchGovernor(0.05, 0.0, 0.2)  # zero floor
    with pytest.raises(ValueError):
        DispatchGovernor(0.05, 0.2, 0.1)  # inverted bounds
    with pytest.raises(ValueError):
        make_governor(widen=0.9)  # widen must widen
    with pytest.raises(ValueError):
        make_governor(narrow=1.5)  # narrow must narrow
    # start interval is clamped into the bounds
    assert DispatchGovernor(5.0, 0.01, 0.2).interval == 0.2


def test_from_config_gating_and_default_bounds():
    assert DispatchGovernor.from_config(
        getConfig({"QuorumTickInterval": 0.05})) is None  # not adaptive
    assert DispatchGovernor.from_config(
        getConfig({"QuorumTickAdaptive": True})) is None  # not tick mode
    g = DispatchGovernor.from_config(getConfig(
        {"QuorumTickInterval": 0.05, "QuorumTickAdaptive": True}))
    assert g is not None
    assert (g.min_interval, g.max_interval) == (0.0125, 0.2)
    g = DispatchGovernor.from_config(getConfig(
        {"QuorumTickInterval": 0.05, "QuorumTickAdaptive": True,
         "QuorumTickIntervalMin": 0.02, "QuorumTickIntervalMax": 0.08}))
    assert (g.min_interval, g.max_interval) == (0.02, 0.08)


# ---------------------------------------------------------------------
# closed loop over a real pool
# ---------------------------------------------------------------------

def test_pool_trajectory_deterministic_and_widens_when_idle():
    """Same seed, same workload ⇒ bit-identical interval trajectory; the
    idle stretch after ordering completes must widen the tick to its
    configured ceiling (the convergence bound, measured in-pool)."""

    def run():
        pool = _adaptive_pool(seed=53)
        for i in range(6):
            pool.submit_request(i)
        pool.run_for(10)
        assert pool.honest_nodes_agree()
        assert all(len(n.ordered_digests) == 6 for n in pool.nodes)
        return (list(pool.governor.trajectory),
                [tuple(n.ordered_digests) for n in pool.nodes])

    traj1, digests1 = run()
    traj2, digests2 = run()
    assert traj1 == traj2
    assert digests1 == digests2
    assert traj1, "governor never observed a tick"
    assert max(traj1) == pool_max_bound()  # idle widened to the ceiling


def pool_max_bound() -> float:
    lo, hi = getConfig({"QuorumTickInterval": 0.05,
                        "QuorumTickAdaptive": True}).governor_bounds()
    return hi


def test_pool_narrows_under_saturation():
    """With the hot-occupancy threshold lowered into this small pool's
    range, a burst must drive the interval BELOW the base tick (the
    narrow half of the control law, exercised through the real loop)."""
    pool = _adaptive_pool(seed=59, overrides={
        "GovernorOccupancyHigh": 0.05, "GovernorOccupancyLow": 0.001})
    base = pool.config.QuorumTickInterval
    for i in range(12):
        pool.submit_request(i)
    pool.run_for(10)
    assert all(len(n.ordered_digests) == 12 for n in pool.nodes)
    assert min(pool.governor.trajectory) < base
    assert min(pool.governor.trajectory) >= pool.governor.min_interval


def test_adaptive_tick_matches_per_message_digests():
    """The governor changes COST, never OUTCOMES: adaptive-tick and
    per-message runs on the same seed order identical digests, including
    through a view change in the middle."""

    def run(tick):
        pool = _adaptive_pool(seed=47, tick=tick)
        primary = pool.nodes[0].data.primaries[0]
        for i in range(4):
            pool.submit_request(i)
        pool.run_for(8)
        pool.network.disconnect(primary)
        pool.run_for(pool.config.ToleratePrimaryDisconnection + 10)
        for i in range(100, 104):
            pool.submit_request(i)
        pool.run_for(12)
        return {n.name: tuple(n.ordered_digests) for n in pool.nodes
                if n.name != primary}

    assert run(0.05) == run(0.0)


def test_monitor_snapshot_surfaces_tick_interval():
    """Monitor.snapshot()'s device_dispatch block carries the CURRENT
    effective interval and the dwell histogram (NodePool shares one
    collector, so every node's monitor sees the pool governor)."""
    from indy_plenum_tpu.simulation.node_pool import NodePool

    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 5,
                        "PropagateBatchWait": 0.05,
                        "QuorumTickInterval": 0.05,
                        "QuorumTickAdaptive": True})
    pool = NodePool(4, seed=81, config=config, device_quorum=True)
    for _ in range(3):
        pool.submit_to("node0", pool.make_nym_request())
    pool.run_for(15)
    assert all(len(n.ordered_digests) == 3 for n in pool.nodes)
    assert pool.governor is not None and pool.governor.ticks > 0

    snap = pool.node("node0").monitor.snapshot()
    device = snap["device_dispatch"]
    tick = device["tick_interval"]
    lo, hi = pool.config.governor_bounds()
    assert lo <= tick["current"] <= hi
    assert lo <= tick["min"] <= tick["max"] <= hi
    assert tick["histogram"] and sum(
        tick["histogram"].values()) == pool.governor.ticks
    assert "occupancy_ewma" in device


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_adaptive_tick_deterministic_and_orders_like_per_message():
    """Chaos-grade determinism (the replay contract): the same seeded
    f_crash_partition run through the ADAPTIVE dispatch plane twice
    yields the identical interval trajectory and identical per-node
    ordered-digest hashes — and the same ordering as the per-message
    loop on that seed."""
    from indy_plenum_tpu.chaos import run_scenario

    def adaptive():
        return run_scenario("f_crash_partition", seed=7,
                            device_quorum=True,
                            quorum_tick_interval=0.05,
                            quorum_tick_adaptive=True)

    r1, r2 = adaptive(), adaptive()
    assert r1.verdict_as_expected, r1.failed
    assert not r1.expected_failures
    # the governor actually ran, and deterministically
    assert r1.metrics["governor.tick_interval"]["count"] > 0
    assert (r1.metrics["governor.tick_interval"]
            == r2.metrics["governor.tick_interval"])
    assert (r1.metrics["governor.occupancy_ewma"]
            == r2.metrics["governor.occupancy_ewma"])
    assert r1.ordered_hash_per_node == r2.ordered_hash_per_node

    base = run_scenario("f_crash_partition", seed=7, device_quorum=True)
    assert r1.ordered_hash_per_node == base.ordered_hash_per_node
