"""Multi-tick device residency + occupancy-driven rebalancing (PR 19).

Contract under test (README "Multi-tick device residency &
rebalancing"): with ``ResidentTickDepth`` N > 1 the grouped vote plane
accumulates up to N ticks of votes in device-side ring slots and
consumes them with ONE fused step (checkpoint slides folded in per
slot) — a placement/scheduling choice, so ordering must stay
bit-identical to the per-tick plane on the same seed, through view
changes, window slides and forced rebalances. The rebalance law
(tpu/rebalance.py) is a pure deterministic fold over the governor's
occupancy EWMAs; rotations execute only at the checkpoint-boundary
barrier where the ring is guaranteed drained.

The heavyweight chaos arm rides the slow lane; the n=16/k=6 dispatch
budget comparison lives in scripts/check_dispatch_budget.py's residency
gate.
"""
import os
import sys

import pytest

jax = pytest.importorskip("jax")
np = pytest.importorskip("numpy")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from indy_plenum_tpu.config import getConfig  # noqa: E402
from indy_plenum_tpu.simulation.pool import SimPool  # noqa: E402
from indy_plenum_tpu.tpu.rebalance import RebalancePolicy  # noqa: E402


def _mesh(devices, n):
    from jax.sharding import Mesh

    return Mesh(np.array(devices[:n]), ("members",))


def _run_pool(n_nodes, k, seed, mesh, overrides=None, view_change=True,
              trace=False):
    """Order a workload (optionally through a view change) and return the
    surviving nodes' digest map plus the pool."""
    knobs = {"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 5,
             "QuorumTickInterval": 0.05, "QuorumTickAdaptive": True}
    knobs.update(overrides or {})
    cfg = getConfig(knobs)
    pool = SimPool(n_nodes, seed=seed, config=cfg, device_quorum=True,
                   shadow_check=False, num_instances=k, mesh=mesh,
                   trace=trace)
    primary = pool.nodes[0].data.primaries[0]
    for i in range(6):
        pool.submit_request(i)
    pool.run_for(8)
    if view_change:
        pool.network.disconnect(primary)
        pool.run_for(pool.config.ToleratePrimaryDisconnection + 10)
        for i in range(100, 104):
            pool.submit_request(i)
        pool.run_for(12)
    assert pool.honest_nodes_agree()
    digests = {n.name: tuple(n.ordered_digests) for n in pool.nodes
               if not view_change or n.name != primary}
    return digests, pool


# ---------------------------------------------------------------------
# tier-1: residency is a scheduling choice — bit-identical ordering
# ---------------------------------------------------------------------

@pytest.mark.perf
def test_resident_digest_identity_incl_view_change(eight_devices):
    """Depth-4 residency vs per-tick on the same seed (n=8/k=2, 4-way
    mesh, adaptive tick) through a view change: bit-identical ordered
    digests, and the ring really deferred readbacks (non-vacuity)."""
    mesh = _mesh(eight_devices, 4)
    resident, rpool = _run_pool(
        8, 2, seed=37, mesh=mesh, overrides={"ResidentTickDepth": 4})
    per_tick, _ = _run_pool(8, 2, seed=37, mesh=mesh)
    assert resident == per_tick
    g = rpool.vote_group
    assert g.resident_depth == 4
    assert g.resident_ticks > 0, "ring never accumulated a tick"
    assert g.readbacks_deferred > 0, "ring never deferred a readback"


def test_resident_slide_fold_identity(eight_devices):
    """Checkpoint slides FOLD into the resident step: a window-sliding
    workload (CHK_FREQ 5) orders bit-identically at depth 4, the window
    really slid, and every plane's h tracks its member's low
    watermark."""
    overrides = {"Max3PCBatchSize": 1, "CHK_FREQ": 5, "LOG_SIZE": 15}

    def run(depth):
        cfg = getConfig({"Max3PCBatchWait": 0.1,
                         "QuorumTickInterval": 0.05,
                         "QuorumTickAdaptive": True,
                         "ResidentTickDepth": depth, **overrides})
        pool = SimPool(4, seed=11, config=cfg, device_quorum=True,
                       shadow_check=False)
        for i in range(12):
            pool.submit_request(i)
        pool.run_for(30)
        assert pool.honest_nodes_agree()
        return pool

    resident = run(4)
    per_tick = run(1)
    assert resident.ordered_hash() == per_tick.ordered_hash()
    for node in resident.nodes:
        assert node.data.stable_checkpoint >= 10
        assert node.vote_plane.h == node.data.low_watermark
    g = resident.vote_group
    assert g.readbacks_deferred > 0
    assert g.flushes < per_tick.vote_group.flushes, \
        (g.flushes, per_tick.vote_group.flushes)


def test_ring_drains_on_view_reset():
    """The residency barrier: a member reset must observe fully-settled
    state, so a non-empty ring drains synchronously — and ``lagging``
    covers resident-but-unread slots (the governor's absorb clamp
    input)."""
    from indy_plenum_tpu.tpu.vote_plane import VotePlaneGroup

    validators = [f"n{i}" for i in range(4)]
    group = VotePlaneGroup(4, validators, log_size=8, n_checkpoints=2,
                           resident_depth=4)
    # cold start: first flush consumes synchronously (callers need SOME
    # snapshot), leaving a live host snapshot behind
    group.view(0).record_preprepare(1)
    group.view(0).record_prepare("n1", 1)
    group.flush()
    assert not group._ring
    # second tick enqueues and DEFERS (ring_ticks 1 < depth 4)
    group.view(1).record_prepare("n0", 2)
    group.view(1).record_prepare("n2", 2)
    group.flush()
    assert group._ring, "tick should have enqueued a ring slot"
    assert group.readbacks_deferred == 1
    assert group.lagging  # resident slots count as in-flight work
    # view reset of ANY member drains the whole ring first
    group.reset_member(3)
    assert not group._ring
    assert not group._pending_slide.any()
    assert not group.lagging
    # the drained slot's votes are visible, the reset member's are gone
    assert group.view(1).prepare_count(2) == 2
    assert group.view(0).prepare_count(1) == 1


# ---------------------------------------------------------------------
# tier-1: forced rebalance is a placement choice — bit-identical too
# ---------------------------------------------------------------------

def _run_rebalance_arm(n_nodes, seed, mesh, force_tick, depth=4):
    """Fixed-tick sliding workload; rotation forced at ``force_tick``
    executes at the next checkpoint barrier."""
    cfg = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 1,
                     "QuorumTickInterval": 0.05,
                     "CHK_FREQ": 5, "LOG_SIZE": 15,
                     "ResidentTickDepth": depth,
                     "RebalanceForceTick": force_tick})
    pool = SimPool(n_nodes, seed=seed, config=cfg, device_quorum=True,
                   shadow_check=False, mesh=mesh, trace=True)
    for i in range(6):
        pool.submit_request(i)
    pool.run_for(5)
    for i in range(6, 12):
        pool.submit_request(i)
    pool.run_for(25)
    assert pool.honest_nodes_agree()
    return pool


@pytest.mark.parametrize("shape", [(4,), (2, 2)])
def test_forced_rebalance_digest_identity(eight_devices, shape):
    """A forced mid-run member-plane rotation (1-axis and 2-axis
    fabric): ordered_hash AND trace_hash(exclude_cats={'dispatch'})
    bit-identical to the never-rebalanced arm — only the dispatch
    timeline may differ."""
    from indy_plenum_tpu.tpu.quorum import make_fabric_mesh

    mesh = (_mesh(eight_devices, shape[0]) if len(shape) == 1
            else make_fabric_mesh(eight_devices, shape))
    forced = _run_rebalance_arm(8, seed=23, mesh=mesh, force_tick=12)
    baseline = _run_rebalance_arm(8, seed=23, mesh=mesh, force_tick=0)
    g = forced.vote_group
    assert g.rebalances >= 1, "forced rotation never executed"
    assert g.row_shift != 0
    assert baseline.vote_group.rebalances == 0
    assert forced.ordered_hash() == baseline.ordered_hash()
    assert (forced.trace.trace_hash(exclude_cats=("dispatch",))
            == baseline.trace.trace_hash(exclude_cats=("dispatch",)))
    # the migration landed in the trace's dispatch timeline
    names = [ev["name"] for ev in forced.trace.events()]
    assert "rebalance.planned" in names
    assert "rebalance.executed" in names


# ---------------------------------------------------------------------
# tier-1: the rebalance law (pure fold — unit-testable without jax run)
# ---------------------------------------------------------------------

def test_rebalance_skew_even_count_median():
    """Hottest/median with an even block count takes the mean of the
    middle two."""
    assert RebalancePolicy.skew([8.0, 1.0, 1.0, 1.0]) == 8.0
    assert RebalancePolicy.skew([4.0, 2.0]) == pytest.approx(4.0 / 3.0)
    assert RebalancePolicy.skew([1.0, 1.0, 1.0]) == 1.0


def test_rebalance_dwell_counting_and_reset():
    """The skew must hold above threshold for DWELL consecutive ticks;
    a single dip re-arms the counter."""
    hot = [8.0, 1.0, 1.0, 1.0]
    cool = [1.0, 1.0, 1.0, 1.0]
    p = RebalancePolicy(4, 2, threshold=2.0, dwell=3)
    assert p.observe(hot) == 0
    assert p.observe(hot) == 0
    assert p.observe(cool) == 0  # dip resets the dwell counter
    assert p.observe(hot) == 0
    assert p.observe(hot) == 0
    rows = p.observe(hot)  # third consecutive over-threshold tick
    assert rows > 0 and p.planned == 1
    assert p.last_skew == 8.0


def test_rebalance_cooldown_mutes_the_law():
    """After a plan the law mutes while post-rotation EWMAs re-learn —
    the stale transient must not immediately re-trigger."""
    hot = [8.0, 1.0, 1.0, 1.0]
    p = RebalancePolicy(4, 2, threshold=2.0, dwell=2, cooldown=5)
    assert [p.observe(hot) for _ in range(2)][-1] > 0
    assert all(p.observe(hot) == 0 for _ in range(5))  # muted
    # re-armed after the cooldown window
    out = [p.observe(hot) for _ in range(2)]
    assert out[-1] > 0 and p.planned == 2


def test_rebalance_plan_minimizes_predicted_hot_block():
    """Row-granular rotation: heat [8,1,1,1] on 2-row blocks splits the
    hot block across two neighbours — one row (predicted hottest 4.5)
    beats any whole-block shift (which is heat-invariant), and the
    smallest winning shift ties-break."""
    p = RebalancePolicy(4, 2)
    assert p.plan([8.0, 1.0, 1.0, 1.0]) == 1
    # perfectly flat heat: no rotation strictly improves — plan 0
    assert p.plan([3.0, 3.0, 3.0, 3.0]) == 0
    # whole-block shifts alone never help: with 1-row blocks every
    # rotation is whole-block, so the plan stays 0
    assert RebalancePolicy(4, 1).plan([8.0, 1.0, 1.0, 1.0]) == 0


def test_rebalance_policy_determinism():
    """Same observation series, same plans — the law is a pure fold."""
    rng = np.random.RandomState(5)
    series = [list(rng.uniform(0.0, 8.0, size=4)) for _ in range(64)]
    a = RebalancePolicy(4, 2, threshold=1.5, dwell=3)
    b = RebalancePolicy(4, 2, threshold=1.5, dwell=3)
    plans_a = [a.observe(s) for s in series]
    plans_b = [b.observe(s) for s in series]
    assert plans_a == plans_b
    assert a.last_skew == b.last_skew and a.planned == b.planned


def test_rebalance_from_config_gating():
    """Composition root: None unless member-sharded AND a trigger armed
    — the common path pays nothing."""
    class FakeGroup:
        _m_shards = 4
        _shard_rows = 2
        _v_shards = 1

    armed = getConfig({"RebalanceSkewThreshold": 2.0})
    assert RebalancePolicy.from_config(armed, None) is None
    unarmed = getConfig({})
    assert RebalancePolicy.from_config(unarmed, FakeGroup()) is None
    policy = RebalancePolicy.from_config(armed, FakeGroup())
    assert policy is not None and policy.threshold == 2.0
    assert policy.dwell == armed.RebalanceDwellTicks
    forced = getConfig({"RebalanceForceTick": 7})
    assert RebalancePolicy.from_config(forced, FakeGroup()) is not None


def test_rebalance_forced_rotation_unskews_hot_block():
    """The gate's un-skew law: rotating the planned rows really lowers
    the predicted hottest/median skew below threshold."""
    p = RebalancePolicy(4, 2, threshold=2.0, dwell=2)
    hot = [8.0, 1.0, 1.0, 1.0]
    rows = 0
    for _ in range(4):
        rows = rows or p.observe(hot)
    assert rows == 1
    b0, r = divmod(rows, 2)
    predicted = [
        (2 - r) / 2 * hot[(k - b0) % 4] + r / 2 * hot[(k - b0 - 1) % 4]
        for k in range(4)]
    assert RebalancePolicy.skew(predicted) < min(
        RebalancePolicy.skew(hot), p.threshold)


# ---------------------------------------------------------------------
# tier-1: zero-residency runs stay bit-identical (governor included)
# ---------------------------------------------------------------------

def test_zero_residency_bit_identical_governor_trajectory(eight_devices):
    """Depth 1 (the default) is byte-for-byte the pre-residency plane:
    same ordering, same governor EWMA trajectory, no ring counters."""
    mesh = _mesh(eight_devices, 4)
    explicit, ep = _run_pool(8, 2, seed=41, mesh=mesh, view_change=False,
                             overrides={"ResidentTickDepth": 1})
    default, dp = _run_pool(8, 2, seed=41, mesh=mesh, view_change=False)
    assert explicit == default
    assert ep.governor is not None
    assert ep.governor.trajectory_summary() \
        == dp.governor.trajectory_summary()
    assert ep.governor.shard_ewmas == dp.governor.shard_ewmas
    g = ep.vote_group
    assert g.resident_depth == 1
    assert g.resident_ticks == 0 and g.readbacks_deferred == 0


def test_monitor_snapshot_residency_block():
    """Monitor.snapshot()'s device_dispatch block carries the residency
    counters when a ring ran — and stays byte-compatible (no block)
    at depth 1."""
    from indy_plenum_tpu.simulation.node_pool import NodePool

    def run(depth):
        config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 5,
                            "PropagateBatchWait": 0.05,
                            "QuorumTickInterval": 0.05,
                            "QuorumTickAdaptive": True,
                            "ResidentTickDepth": depth})
        pool = NodePool(4, seed=81, config=config, device_quorum=True)
        for _ in range(3):
            pool.submit_to("node0", pool.make_nym_request())
        pool.run_for(15)
        assert all(len(n.ordered_digests) == 3 for n in pool.nodes)
        return pool.node("node0").monitor.snapshot()["device_dispatch"]

    resident = run(4)
    assert resident["residency"]["resident_depth"] == 4
    assert resident["residency"]["resident_ticks"] > 0
    assert resident["residency"]["readbacks_deferred"] >= 0
    assert "residency" not in run(1)


# ---------------------------------------------------------------------
# tier-1: observability + CLI surfaces (no jax run needed)
# ---------------------------------------------------------------------

def test_overlap_report_residency_and_rebalance_marks():
    """overlap_report folds the resident event shapes: enqueues carry
    the votes, the fused dispatch carries the consumed tick count,
    defers count, and rebalance marks surface with their args."""
    from indy_plenum_tpu.observability.trace import overlap_report

    events = [
        {"name": "flush.enqueue", "cat": "dispatch", "ts": 0.0,
         "args": {"votes": 4, "shape": 16}},
        {"name": "flush.defer", "cat": "dispatch", "ts": 0.01,
         "args": {"ring_ticks": 1}},
        {"name": "tick.flush", "cat": "dispatch", "ts": 0.05, "args": {}},
        {"name": "flush.enqueue", "cat": "dispatch", "ts": 0.1,
         "args": {"votes": 2, "shape": 16}},
        {"name": "rebalance.planned", "cat": "dispatch", "ts": 0.11,
         "args": {"rows": 1, "skew": 8.0}},
        {"name": "flush.dispatch", "cat": "dispatch", "ts": 0.12,
         "args": {"slots": 2, "ticks": 2, "resident": 4}},
        {"name": "rebalance.executed", "cat": "dispatch", "ts": 0.13,
         "args": {"rows": 1, "shift": 1}},
        {"name": "flush.readback", "cat": "dispatch", "ts": 0.14,
         "args": {"bytes": 100, "overlapped": True}},
        {"name": "tick.flush", "cat": "dispatch", "ts": 0.15, "args": {}},
    ]
    report = overlap_report(events)
    assert report["ticks"] == 2
    res = report["residency"]
    assert res["enqueues"] == 2
    assert res["resident_ticks_total"] == 2
    assert res["readbacks_deferred"] == 1
    reb = report["rebalances"]
    assert reb["executed"] == 1
    assert [m["name"] for m in reb["marks"]] \
        == ["rebalance.planned", "rebalance.executed"]
    # enqueued votes land on the tick rows (not double-counted by the
    # fused dispatch, which carries no votes key)
    assert [t["votes"] for t in report["per_tick"]] == [4, 2]
    assert [t["enqueues"] for t in report["per_tick"]] == [1, 1]
    # non-resident dumps keep the old shape: no residency block at all
    flat = [
        {"name": "flush.dispatch", "cat": "dispatch", "ts": 0.0,
         "args": {"votes": 6, "shape": 16}},
        {"name": "tick.flush", "cat": "dispatch", "ts": 0.1, "args": {}},
    ]
    out = overlap_report(flat)
    assert "residency" not in out and "rebalances" not in out


def test_chaos_runner_validates_resident_depth():
    """resident_depth > 1 needs the tick-batched device plane — the
    runner rejects unsupported combinations up front."""
    from indy_plenum_tpu.chaos.runner import run_scenario

    with pytest.raises(ValueError):
        run_scenario("f_crash_partition", seed=3, resident_depth=4)
    with pytest.raises(ValueError):
        run_scenario("f_crash_partition", seed=3, device_quorum=True,
                     quorum_tick_interval=0.1, host_eval=True,
                     resident_depth=4)


# ---------------------------------------------------------------------
# slow lane: chaos under residency
# ---------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_f_crash_partition_under_residency():
    """The acceptance chaos scenario through a depth-4 ring: every
    invariant PASSes and the replay command reproduces the depth."""
    from indy_plenum_tpu.chaos.runner import run_scenario

    report = run_scenario("f_crash_partition", seed=7,
                          device_quorum=True,
                          quorum_tick_interval=0.1,
                          quorum_tick_adaptive=True,
                          resident_depth=4)
    assert report.failed == [], report.invariants
    assert report.verdict_as_expected
    assert "--resident-depth 4" in report.replay_command
    assert report.dispatch_mode["resident"] == 4
