"""Batched O(delta) state-commit plane (state/sparse_merkle_state.py).

The contracts under test (README "State-commit plane"):

- ``apply_batch`` is a pure optimization: random write sets (including
  overwrite-within-batch and removes) produce roots BIT-IDENTICAL to
  the sequential ``set()``/``remove()`` loop, on every placement arm
  (host waves, forced device waves, ``mode='auto'``) — and with fewer
  tree hashes (each touched internal node hashed once per batch);
- ``generate_state_proof``/``verify_state_proof`` verify against
  batch-produced roots, including HISTORICAL roots after ``commit()``;
- ``verify_state_proof`` returns ``False`` on malformed untrusted input
  (undecodable msgpack, short roots, non-bytes path elements,
  wrong-length siblings) instead of raising;
- the write-buffer overlay (``begin_batch``/``flush_batch``) keeps
  reads-at-uncommitted coherent mid-batch, and the revert seams
  (``set_head_hash``/``revert_to_head``) DISCARD buffered writes;
- the LRU node cache and ``LedgerBacking``'s audit-path cache hold
  their caps (bounded on a long-lived node);
- end-to-end: a real-execution pool with the batch plane enabled orders
  the same requests to the same roots as one with it disabled, and the
  ``state.commit`` trace mark joins ``3pc.executed`` per (view, seq)
  into the ``state_commit`` phase.
"""
import random

from indy_plenum_tpu.common.constants import DOMAIN_LEDGER_ID
from indy_plenum_tpu.common.metrics_collector import (
    MetricsCollector,
    MetricsName,
)
from indy_plenum_tpu.config import getConfig
from indy_plenum_tpu.simulation.pool import SimPool
from indy_plenum_tpu.state.sparse_merkle_state import (
    DEFAULTS,
    DEPTH,
    EMPTY_ROOT,
    SparseMerkleState,
    verify_state_proof,
)


def _random_batches(seed, n_rounds=5, keyspace=160, max_writes=60):
    """Write sequences with hot-key collisions (overwrite-within-batch)
    and removes of live keys — the shapes the dedupe and the unchanged-
    subtree short-circuit must get right."""
    rng = random.Random(seed)
    live = set()
    rounds = []
    for _ in range(n_rounds):
        writes = []
        for _ in range(rng.randrange(1, max_writes)):
            if live and rng.random() < 0.25:
                k = rng.choice(sorted(live))
                writes.append((k, None))
                live.discard(k)
            else:
                k = b"k%d" % rng.randrange(keyspace)
                writes.append((k, b"v%d" % rng.randrange(1 << 20)))
                live.add(k)
        rounds.append(writes)
    return rounds


def test_apply_batch_root_identical_to_sequential_and_cheaper():
    for seed in (3, 17, 91):
        seq = SparseMerkleState()
        bat = SparseMerkleState(commit_mode="host")
        for writes in _random_batches(seed):
            for k, v in writes:
                if v is None:
                    seq.remove(k)
                else:
                    seq.set(k, v)
            bat.apply_batch(writes)
            assert bat.head_hash == seq.head_hash
        # the O(delta) claim at property scale: strictly fewer hashes
        assert bat.hashes_total < seq.hashes_total


def test_apply_batch_device_and_auto_arms_bit_identical():
    rng = random.Random(5)
    writes = [(b"key%d" % rng.randrange(300), b"val%d" % i)
              for i in range(150)]
    host = SparseMerkleState(commit_mode="host")
    dev = SparseMerkleState(commit_mode="device")
    auto = SparseMerkleState(commit_mode="auto")
    for st in (host, dev, auto):
        st.apply_batch(writes)
    assert host.head_hash == dev.head_hash == auto.head_hash
    # the logical hash meter is placement-independent (it may ride
    # traces/fingerprints; the wave_* placement meters may not)
    assert host.hashes_total == dev.hashes_total == auto.hashes_total
    assert dev.wave_device_hashes > 0 or dev.wave_host_hashes > 0


def test_apply_batch_edge_cases():
    st = SparseMerkleState()
    assert st.apply_batch([]) == EMPTY_ROOT
    # removes into an empty tree are a no-op, not a new root
    assert st.apply_batch([(b"ghost", None)]) == EMPTY_ROOT
    st.apply_batch([(b"a", b"1"), (b"b", b"2")])
    r = st.head_hash
    # rewriting identical values leaves the root (and the tree) alone
    assert st.apply_batch([(b"a", b"1"), (b"b", b"2")]) == r
    # last-write-wins within one batch
    st2 = SparseMerkleState()
    st2.apply_batch([(b"a", b"old"), (b"b", b"2"), (b"a", b"1")])
    assert st2.head_hash == r
    # removing everything returns to the empty root
    st.apply_batch([(b"a", None), (b"b", None)])
    assert st.head_hash == EMPTY_ROOT


def test_proofs_verify_against_batch_roots_and_historical_roots():
    st = SparseMerkleState(commit_mode="host")
    st.apply_batch([(b"k%d" % i, b"v%d" % i) for i in range(40)])
    st.commit()
    old_root = st.committed_head_hash
    st.apply_batch([(b"k%d" % i, b"NEW%d" % i) for i in range(0, 40, 2)]
                   + [(b"k7", None)])
    st.commit()
    new_root = st.committed_head_hash
    # current root: updated, removed (non-membership) and untouched keys
    assert verify_state_proof(new_root, b"k0", b"NEW0",
                              st.generate_state_proof(b"k0"))
    assert verify_state_proof(new_root, b"k7", None,
                              st.generate_state_proof(b"k7"))
    assert verify_state_proof(new_root, b"k9", b"v9",
                              st.generate_state_proof(b"k9"))
    # historical root after commit(): content-addressed nodes keep every
    # committed root readable and provable
    p_old = st.generate_state_proof(b"k7", root=old_root)
    assert st.get_for_root_hash(old_root, b"k7") == b"v7"
    assert verify_state_proof(old_root, b"k7", b"v7", p_old)
    assert not verify_state_proof(new_root, b"k7", b"v7", p_old)
    assert not verify_state_proof(old_root, b"k7", b"tampered", p_old)


def test_verify_state_proof_malformed_input_returns_false():
    import msgpack

    st = SparseMerkleState()
    # several neighbours so the proof carries non-default (packed)
    # siblings — otherwise the truncation mutations below are no-ops
    st.apply_batch([(b"fill%d" % i, b"f%d" % i) for i in range(8)]
                   + [(b"key", b"value")])
    st.commit()
    root = st.committed_head_hash
    proof = st.generate_state_proof(b"key")
    assert verify_state_proof(root, b"key", b"value", proof)
    # every malformed shape must verify False, never raise
    assert not verify_state_proof(b"short-root", b"key", b"value", proof)
    assert not verify_state_proof(root[:-1], b"key", b"value", proof)
    assert not verify_state_proof(root, "not-bytes", b"value", proof)
    assert not verify_state_proof(root, None, b"value", proof)
    assert not verify_state_proof(root, b"key", b"value", b"\x93garbage")
    assert not verify_state_proof(root, b"key", b"value", 42)
    assert not verify_state_proof(root, b"key", b"value",
                                  msgpack.packb([1, 2], use_bin_type=True))
    bitmap, packed = msgpack.unpackb(proof, raw=False)
    for bad in (
        msgpack.packb([bitmap[:-1], packed], use_bin_type=True),
        msgpack.packb([bitmap, packed[:-1]], use_bin_type=True),
        msgpack.packb([bitmap, packed + [b"x" * 31]], use_bin_type=True),
        msgpack.packb([bitmap, ["not-bytes"] * len(packed)],
                      use_bin_type=True),
        msgpack.packb([None, packed], use_bin_type=True),
    ):
        assert not verify_state_proof(root, b"key", b"value", bad)


def test_batch_overlay_reads_and_revert_discard():
    st = SparseMerkleState()
    st.set(b"a", b"committed")
    st.commit()
    assert st.begin_batch()
    st.set(b"a", b"staged")
    st.set(b"b", b"new")
    st.remove(b"a")
    # uncommitted reads see the pending overlay (dynamic validation
    # inside a 3PC batch observes earlier same-batch writes)...
    assert st.get(b"a") is None
    assert st.get(b"b") == b"new"
    # ...committed reads do not
    assert st.get(b"a", is_committed=True) == b"committed"
    root = st.head_hash  # flushes + closes the batch
    assert not st.in_batch
    ref = SparseMerkleState()
    ref.set(b"b", b"new")
    assert root == ref.head_hash
    # set_head_hash is the exception/revert path: buffered writes die
    st.begin_batch()
    st.set(b"z", b"doomed")
    st.set_head_hash(root)
    assert st.get(b"z") is None and not st.in_batch
    st.begin_batch()
    st.set(b"z", b"doomed-too")
    st.revert_to_head()
    assert st.get(b"z") is None and not st.in_batch
    # the knob: a disabled plane refuses to open a batch
    off = SparseMerkleState(commit_batch_enabled=False)
    assert not off.begin_batch()
    assert not off.in_batch


def test_commit_batch_min_small_batches_apply_sequentially():
    st = SparseMerkleState(commit_batch_min=10)
    ref = SparseMerkleState()
    writes = [(b"x%d" % i, b"y%d" % i) for i in range(4)]
    st.apply_batch(writes)
    for k, v in writes:
        ref.set(k, v)
    assert st.head_hash == ref.head_hash
    # below the min the sequential path runs: hash counts match exactly
    assert st.hashes_total == ref.hashes_total


def test_node_cache_bounded_lru():
    # cap must exceed one full root-to-leaf walk (DEPTH nodes) or a
    # sequential re-walk evicts its own path before revisiting it
    cap = DEPTH * 2
    st = SparseMerkleState(node_cache_size=cap)
    st.apply_batch([(b"n%d" % i, b"v%d" % i) for i in range(50)])
    st.commit()
    for i in range(50):
        assert st.get(b"n%d" % i) == b"v%d" % i
    assert st.node_cache_len <= cap
    assert st.cache_misses > 0
    # the last-read key's path is still resident: re-reading it hits
    h0 = st.cache_hits
    st.get(b"n49")
    assert st.cache_hits > h0
    # 0 disables caching entirely
    off = SparseMerkleState(node_cache_size=0)
    off.set(b"k", b"v")
    off.commit()
    off.get(b"k")
    assert off.node_cache_len == 0


def test_defaults_table_shape():
    assert len(DEFAULTS) == DEPTH + 1
    assert DEFAULTS[0] == EMPTY_ROOT


def test_ledger_backing_path_cache_lru_capped_and_cleared_on_refresh():
    from indy_plenum_tpu.ingress.read_service import LedgerBacking
    from indy_plenum_tpu.ledger.ledger import Ledger

    ledger = Ledger()
    for i in range(40):
        ledger.add({"type": "1", "v": i})
    backing = LedgerBacking(ledger, path_cache_max=8)
    # live-snapshot and pinned-historical keys both count against the cap
    for i in range(30):
        backing.path(i)
        backing.path(i % 15, tree_size=20 + (i % 10))
    assert len(backing._path_cache) <= 8
    # LRU: the hot key survives the sweep
    hot = backing.path(0)
    for i in range(1, 8):
        backing.path(i)
        backing.path(0)
    assert backing.path(0) is hot
    # refresh on growth clears the cache outright
    ledger.add({"type": "1", "v": 99})
    backing.refresh()
    assert len(backing._path_cache) == 0
    assert backing.path(3) == ledger.audit_path(4, ledger.size)


def _real_pool(seed, overrides=None, trace=False):
    cfg = {"CHK_FREQ": 5, "LOG_SIZE": 15,
           "Max3PCBatchSize": 10, "Max3PCBatchWait": 0.05}
    cfg.update(overrides or {})
    metrics = MetricsCollector()
    pool = SimPool(4, seed=seed, config=getConfig(cfg),
                   real_execution=True, trace=trace, metrics=metrics)
    for i in range(12):
        pool.submit_request(i)
    pool.run_for(15)
    assert pool.honest_nodes_agree()
    return pool


def test_pool_batched_commit_matches_disabled_and_meters():
    batched = _real_pool(29)
    sequential = _real_pool(29, {"StateCommitBatchEnabled": False})
    # end-to-end bit-identity: same seed, same requests, same roots —
    # whether state committed through one walk per batch or per write
    assert batched.ordered_hash() == sequential.ordered_hash()
    for nb, ns in zip(batched.nodes, sequential.nodes):
        sb = nb.boot.db.get_state(DOMAIN_LEDGER_ID)
        ss = ns.boot.db.get_state(DOMAIN_LEDGER_ID)
        assert sb.committed_head_hash == ss.committed_head_hash
        assert sb.batches_applied > 0
        assert ss.batches_applied == 0  # knob really disabled the plane
        # one walk per batch beats one walk per write
        assert sb.hashes_total < ss.hashes_total
    # the per-batch meters landed on the pool collector
    stat = batched.metrics.stat(MetricsName.STATE_COMMIT_HASHES)
    assert stat is not None and stat.count > 0
    assert batched.metrics.stat(
        MetricsName.STATE_COMMIT_BATCH_SIZE) is not None


def test_state_commit_trace_phase_joined():
    from indy_plenum_tpu.observability.trace import (
        STATE_PHASE,
        phase_durations,
    )

    pool = _real_pool(31, trace=True)
    events = pool.trace.events()
    marks = [e for e in events if e["name"] == "state.commit"]
    assert marks and all(e["cat"] == "state" for e in marks)
    assert all(e["args"]["hashes"] > 0 for e in marks)
    phases = phase_durations(events)
    samples = phases.get(STATE_PHASE[0])
    assert samples, "state_commit phase did not join"
    assert all(d >= 0.0 for d in samples)
