"""On-device ordering fast path (ISSUE 7): compact-vs-full eval.

Contract under test (README "Performance" / the readback gate in
``scripts/check_dispatch_budget.py``): with device-side quorum eval (the
default) a tick reads back only O(newly certified + frontier) bytes —
the ``host_eval`` fallback fetches the full (member x window) event
matrix — and the eval mode may change WHAT crosses the device->host
link, never the ordering. Seeded runs must produce bit-identical
``ordered_hash`` (and protocol-timeline ``trace_hash``) either way,
through view changes, on the 4-way mesh, and under chaos.
"""
import os
import sys

import pytest

jax = pytest.importorskip("jax")
np = pytest.importorskip("numpy")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from indy_plenum_tpu.config import getConfig  # noqa: E402
from indy_plenum_tpu.simulation.pool import SimPool  # noqa: E402
from indy_plenum_tpu.tpu.vote_plane import DeviceVotePlane  # noqa: E402

VALIDATORS = ["n0", "n1", "n2", "n3"]


def _certify(plane, pp_seq_no, prepares=3, commits=3):
    """Record a full 3PC vote wave for one slot (n=4, f=1: prepare cert
    needs n-f-1=2 matching PREPAREs, commit cert n-f=3 COMMITs)."""
    plane.record_preprepare(pp_seq_no)
    for sender in VALIDATORS[1:1 + prepares]:
        plane.record_prepare(sender, pp_seq_no)
    for sender in VALIDATORS[:commits]:
        plane.record_commit(sender, pp_seq_no)


# ---------------------------------------------------------------------
# tier-1: standalone-plane semantics
# ---------------------------------------------------------------------

def test_standalone_plane_compact_matches_host_eval():
    """Same vote sequence through both eval modes: identical quorum
    verdicts, and the device-eval plane feeds the deltas + frontier that
    the host_eval fallback would have recomputed by rescanning."""
    # a realistic window: the compact readback is FIXED-size (delta cap
    # slots + frontier), the matrix fallback scales with log_size
    dev = DeviceVotePlane(VALIDATORS, log_size=256, n_checkpoints=2)
    host = DeviceVotePlane(VALIDATORS, log_size=256, n_checkpoints=2,
                           host_eval=True)
    assert dev.delta_feed and not host.delta_feed
    for plane in (dev, host):
        _certify(plane, 1)
        _certify(plane, 2)
        plane.record_preprepare(4)  # no certs: stays out of every delta
        plane.sync()
    for pp in (1, 2):
        assert dev.has_prepare_quorum(pp) and host.has_prepare_quorum(pp)
        assert dev.has_commit_quorum(pp) and host.has_commit_quorum(pp)
        assert dev.prepare_count(pp) == host.prepare_count(pp) == 3
    assert not dev.has_commit_quorum(4) and not host.has_commit_quorum(4)
    # the fast path names exactly the slots that crossed their
    # thresholds (h-relative: pp_seq_no = h + slot + 1)
    deltas = dev.poll_deltas()
    assert deltas is not None
    assert deltas.prepared == [0, 1]
    assert deltas.committed == [0, 1]
    assert deltas.frontier == 2  # both certs are contiguous from h
    # consumed once; quiet polls are None (allocation-free)
    assert dev.poll_deltas() is None
    # the fallback never feeds deltas — services rescan snapshots
    assert host.poll_deltas() is None
    # the structural claim: the compact readback is a small fraction of
    # the event matrix the fallback fetches per refresh
    assert dev.readbacks == host.readbacks
    assert dev.readback_bytes_total < host.readback_bytes_total / 4


def test_frontier_advances_in_order_only():
    """The frontier is the leading CONTIGUOUS run of commit-certified
    slots: a gap pins it, filling the gap releases the whole run."""
    plane = DeviceVotePlane(VALIDATORS, log_size=16, n_checkpoints=2)
    _certify(plane, 2)
    _certify(plane, 3)
    plane.sync()
    deltas = plane.poll_deltas()
    assert deltas.committed == [1, 2]
    assert deltas.frontier == 0  # slot 0 (pp_seq 1) still missing
    _certify(plane, 1)
    plane.sync()
    deltas = plane.poll_deltas()
    assert deltas.committed == [0]
    assert deltas.frontier == 3  # the gap filled: the whole run releases


def test_delta_overflow_falls_back_to_full_events():
    """A step whose newly-certified count exceeds the fixed delta
    capacity reconciles from the full device-resident events — same
    verdicts, bigger (but still deterministic) readback."""
    over = DeviceVotePlane(VALIDATORS, log_size=32, n_checkpoints=2,
                           delta_cap=2)
    wide = DeviceVotePlane(VALIDATORS, log_size=32, n_checkpoints=2)
    # same delta cap, no overflow: the per-readback byte baseline
    calm = DeviceVotePlane(VALIDATORS, log_size=32, n_checkpoints=2,
                           delta_cap=2)
    _certify(calm, 1)
    calm.sync()
    for plane in (over, wide):
        for pp in range(1, 9):  # 8 slots certify inside ONE flush
            _certify(plane, pp)
        plane.sync()
    d_over, d_wide = over.poll_deltas(), wide.poll_deltas()
    assert d_over.prepared == d_wide.prepared == list(range(8))
    assert d_over.committed == d_wide.committed == list(range(8))
    assert d_over.frontier == d_wide.frontier == 8
    for pp in range(1, 9):
        assert over.has_commit_quorum(pp)
    # the overflow path actually paid for the full-events fetch: same
    # readback count and compact struct size as the calm run, more bytes
    assert over.readbacks == calm.readbacks
    assert over.readback_bytes_total > calm.readback_bytes_total


def test_slide_rebases_unpolled_deltas():
    """Checkpoint slide between certify and poll: unpolled delta slots
    re-base to the new h; slots below it drop (their consumers are done
    — the checkpoint stabilized past them)."""
    plane = DeviceVotePlane(VALIDATORS, log_size=16, n_checkpoints=4)
    _certify(plane, 1)
    _certify(plane, 6)
    plane.sync()
    plane.slide_to(4)
    deltas = plane.poll_deltas()
    assert deltas.committed == [1]  # pp_seq 6 is slot 1 under h=4
    assert deltas.prepared == [1]
    assert plane.has_commit_quorum(6)
    plane.reset()
    assert plane.poll_deltas() is None  # view change voids everything


# ---------------------------------------------------------------------
# tier-1: pool-level digest identity + the readback contract
# ---------------------------------------------------------------------

def _run_pool(host_eval, seed=41, view_change=False, mesh=None,
              n_nodes=4, k=1, trace=True):
    cfg = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 2,
                     "QuorumTickInterval": 0.05,
                     "QuorumTickAdaptive": True})
    pool = SimPool(n_nodes, seed=seed, config=cfg, device_quorum=True,
                   shadow_check=False, num_instances=k, mesh=mesh,
                   host_eval=host_eval, trace=trace)
    primary = pool.nodes[0].data.primaries[0]
    for i in range(8):
        pool.submit_request(i)
    pool.run_for(10)
    if view_change:
        pool.network.disconnect(primary)
        pool.run_for(pool.config.ToleratePrimaryDisconnection + 10)
        for i in range(100, 104):
            pool.submit_request(i)
        pool.run_for(12)
    assert pool.honest_nodes_agree()
    return pool


def test_pool_digest_identity_device_vs_host_eval():
    """Same seed, both eval modes: bit-identical ordered_hash AND
    protocol-timeline trace_hash (the dispatch category legitimately
    differs — flush.readback carries the byte counts being changed)."""
    dev = _run_pool(host_eval=False)
    host = _run_pool(host_eval=True)
    assert dev.vote_group.eval_mode == "device"
    assert host.vote_group.eval_mode == "host"
    assert dev.ordered_hash() == host.ordered_hash()
    assert dev.trace.trace_hash(exclude_cats=("dispatch",)) \
        == host.trace.trace_hash(exclude_cats=("dispatch",))
    # the acceptance contract: per-tick transfer is O(newly ordered +
    # frontier), not O(member x instance x window) — asserted via the
    # flush.readback trace attribute, not just the counters
    def readback_bytes(pool):
        return [ev["args"]["bytes"] for ev in pool.trace.events()
                if ev["name"] == "flush.readback" and ev.get("args")]

    dev_rb, host_rb = readback_bytes(dev), readback_bytes(host)
    assert sum(dev_rb) == dev.vote_group.readback_bytes_total
    assert sum(host_rb) == host.vote_group.readback_bytes_total
    # the full event matrix costs O(M * S) per fetch; every compact
    # readback must undercut a single matrix fetch by a wide margin
    matrix_bytes = min(b for b in host_rb if b)
    assert max(dev_rb) < matrix_bytes / 4
    assert sum(dev_rb) < sum(host_rb) / 4
    # the pipelined default actually overlapped: most absorbs consumed a
    # step dispatched by an earlier flush call
    assert dev.vote_group.readbacks_overlapped \
        >= dev.vote_group.readbacks // 2


@pytest.mark.perf
def test_pool_digest_identity_incl_view_change():
    """The eval mode survives a view change bit-for-bit (reset/slide
    paths clear the device-eval mirrors exactly like the device state)."""
    dev = _run_pool(host_eval=False, seed=37, view_change=True)
    host = _run_pool(host_eval=True, seed=37, view_change=True)
    assert dev.ordered_hash() == host.ordered_hash()
    assert dev.trace.trace_hash(exclude_cats=("dispatch",)) \
        == host.trace.trace_hash(exclude_cats=("dispatch",))


# ---------------------------------------------------------------------
# slow lane: the mesh path + chaos
# ---------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.perf
def test_mesh_digest_identity_device_vs_host_eval(eight_devices):
    """Compact readback through the 4-way shard_map'd group step: the
    sharded fast path orders identically to the sharded host_eval
    fallback AND to the 1-device fast path, through a view change."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(eight_devices[:4]), ("members",))
    dev = _run_pool(host_eval=False, seed=37, view_change=True,
                    mesh=mesh, n_nodes=8, k=2)
    host = _run_pool(host_eval=True, seed=37, view_change=True,
                     mesh=mesh, n_nodes=8, k=2)
    single = _run_pool(host_eval=False, seed=37, view_change=True,
                       mesh=None, n_nodes=8, k=2)
    assert dev.vote_group.shards == 4
    assert dev.ordered_hash() == host.ordered_hash() \
        == single.ordered_hash()
    assert dev.vote_group.readback_bytes_total \
        < host.vote_group.readback_bytes_total / 4


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_f_crash_partition_device_vs_host_eval():
    """f crash + partition through the fast path: all invariants hold
    and every node's ordered-digest hash equals the host_eval fallback
    run on the same seed (the chaos replay contract extends to the eval
    mode)."""
    from indy_plenum_tpu.chaos import run_scenario

    dev = run_scenario("f_crash_partition", seed=7, device_quorum=True,
                       quorum_tick_interval=0.05,
                       quorum_tick_adaptive=True)
    assert dev.verdict_as_expected, dev.failed
    assert not dev.expected_failures
    host = run_scenario("f_crash_partition", seed=7, device_quorum=True,
                        quorum_tick_interval=0.05,
                        quorum_tick_adaptive=True, host_eval=True)
    assert host.verdict_as_expected, host.failed
    assert dev.ordered_hash_per_node == host.ordered_hash_per_node
    assert dev.dispatch_mode["host_eval"] is False
    assert host.dispatch_mode["host_eval"] is True
