"""Tier-1/5: device quorum plane vs a numpy oracle; sharded == unsharded.

The sharded variant runs on the 8-device virtual CPU mesh (conftest), the
same code path the driver's dryrun_multichip exercises.
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from indy_plenum_tpu.tpu import quorum as q  # noqa: E402

N = 16
S = 32  # log slots
C = 4  # checkpoint slots
F = (N - 1) // 3


def np_oracle(entries):
    pp = np.zeros(S, bool)
    pv = np.zeros((N, S), bool)
    cv = np.zeros((N, S), bool)
    ck = np.zeros((N, C), bool)
    for k, s, sl in entries:
        if k == q.PREPREPARE:
            pp[sl] = True
        elif k == q.PREPARE:
            pv[s, sl] = True
        elif k == q.COMMIT:
            cv[s, sl] = True
        elif k == q.CHECKPOINT:
            ck[s, sl] = True
    prepared = pp & (pv.sum(0) >= N - F - 1)
    ordered = prepared & (cv.sum(0) >= N - F)
    stable = ck.sum(0) >= N - F
    return prepared, ordered, stable


def random_entries(rng, m):
    out = []
    for _ in range(m):
        k = rng.choice([q.PREPREPARE, q.PREPARE, q.COMMIT, q.CHECKPOINT])
        s = rng.randint(0, N)
        sl = rng.randint(0, S if k != q.CHECKPOINT else C)
        out.append((int(k), int(s), int(sl)))
    return out


def test_step_matches_oracle():
    rng = np.random.RandomState(0)
    entries = random_entries(rng, 400)
    state = q.init_state(N, S, C)
    msgs = q.pack_messages(entries, 512)
    state, ev = q.step(state, msgs, N)
    prepared, ordered, stable = np_oracle(entries)
    assert np.array_equal(np.asarray(ev.prepared), prepared)
    assert np.array_equal(np.asarray(ev.ordered), ordered)
    assert np.array_equal(np.asarray(ev.newly_ordered), ordered)
    assert np.array_equal(np.asarray(ev.stable_checkpoints), stable)


def test_incremental_newly_ordered():
    # Drive one slot to commit quorum across two steps; newly_ordered fires once.
    state = q.init_state(N, S, C)
    first = [(q.PREPREPARE, 0, 5)] + [(q.PREPARE, v, 5) for v in range(1, N)]
    state, ev = q.step(state, q.pack_messages(first, 64), N)
    assert bool(ev.prepared[5]) and not bool(ev.ordered[5])
    second = [(q.COMMIT, v, 5) for v in range(N - F)]
    state, ev = q.step(state, q.pack_messages(second, 64), N)
    assert bool(ev.newly_ordered[5])
    # a third step with more commits must NOT re-fire newly_ordered
    third = [(q.COMMIT, v, 5) for v in range(N)]
    state, ev = q.step(state, q.pack_messages(third, 64), N)
    assert bool(ev.ordered[5]) and not bool(ev.newly_ordered[5])


def test_sharded_step_matches_unsharded(eight_devices):
    mesh = Mesh(np.array(eight_devices), ("validators",))
    sharded = q.make_sharded_step(mesh, N)
    rng = np.random.RandomState(1)
    entries = random_entries(rng, 300)
    msgs = q.pack_messages(entries, 512)

    ref_state, ref_ev = q.step(q.init_state(N, S, C), msgs, N)
    state, ev = sharded(q.init_state(N, S, C), msgs)
    for a, b in zip(ev, ref_ev):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(state, ref_state):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_word_pack_roundtrip_and_group_equivalence():
    """The uint32 wire format (valid|kind|sender|slot) must decode to the
    same MsgBatch the four-array packer builds, and a word-packed step
    must produce identical events."""
    rng = np.random.RandomState(3)
    entries = random_entries(rng, 100)
    words = q.pack_words(entries, 128)
    unpacked = q.unpack_words(jnp.asarray(words))
    ref = q.pack_messages(entries, 128)
    assert np.array_equal(np.asarray(unpacked.kind), np.asarray(ref.kind))
    assert np.array_equal(np.asarray(unpacked.sender),
                          np.asarray(ref.sender))
    assert np.array_equal(np.asarray(unpacked.slot), np.asarray(ref.slot))
    assert np.array_equal(np.asarray(unpacked.valid), np.asarray(ref.valid))

    state = q.init_state(N, S, C)
    _, ev_ref = q.step(state, ref, N)
    _, ev_w = q.step(q.init_state(N, S, C), unpacked, N)
    for a, b in zip(ev_ref, ev_w):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_pack_vote_enforces_field_bounds():
    """An out-of-range kind/sender/slot would silently alias another
    bit-field in the packed word; pack_vote must refuse instead."""
    assert q.pack_vote(3, 8191, 65535) == 0xFFFFFFFF
    assert q.pack_vote(0, 0, 0) == 0x80000000
    for kind, sender, slot in ((4, 0, 0), (0, 8192, 0), (0, 0, 65536),
                               (-1, 0, 0), (0, -1, 0), (0, 0, -1)):
        with pytest.raises(ValueError):
            q.pack_vote(kind, sender, slot)
