"""Determinism & hot-path hygiene analyzer (indy_plenum_tpu.analysis).

Per-rule fixture snippets (positive + suppressed + allowlisted), the
pragma grammar self-lint, findings_hash byte-determinism, CLI
subprocess smoke, and the tier-1 whole-repo clean run that fails this
suite the moment a new unsuppressed finding lands anywhere in the
package.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from indy_plenum_tpu.analysis import (
    Analyzer,
    ModuleInfo,
    analyze_paths,
    analyze_source,
    make_rules,
)
from indy_plenum_tpu.analysis.rules_config import ConfigKnobRule

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "indy_plenum_tpu")
LINT = os.path.join(REPO, "scripts", "lint_determinism.py")


def rules_of(report, rule):
    return [f for f in report.findings if f.rule == rule]


def unsuppressed_of(report, rule):
    return [f for f in report.unsuppressed if f.rule == rule]


def src(text):
    return textwrap.dedent(text)


# --- nondet-source ------------------------------------------------------

class TestNondetSource:
    def test_wall_clock_flagged_through_alias(self):
        rep = analyze_source(src("""
            import time as _t

            def f():
                return _t.perf_counter()
        """))
        hits = unsuppressed_of(rep, "nondet-source")
        assert len(hits) == 1 and "time.perf_counter" in hits[0].message

    def test_from_import_and_datetime(self):
        rep = analyze_source(src("""
            from time import monotonic
            from datetime import datetime

            def f():
                return monotonic(), datetime.now()
        """))
        assert len(unsuppressed_of(rep, "nondet-source")) == 2

    def test_unseeded_rng_flagged_seeded_ok(self):
        rep = analyze_source(src("""
            import random
            import numpy as np

            def bad():
                return random.Random(), np.random.RandomState(), \\
                    random.randint(0, 4), np.random.rand(3)

            def good(seed):
                return random.Random(seed), np.random.RandomState(seed)
        """))
        assert len(unsuppressed_of(rep, "nondet-source")) == 4

    def test_pragma_suppresses_with_reason(self):
        rep = analyze_source(src("""
            import time

            def f():
                t0 = time.perf_counter()  # da: allow[nondet-source] -- wall meter
                return t0
        """))
        assert not unsuppressed_of(rep, "nondet-source")
        assert rules_of(rep, "nondet-source")[0].suppressed == "pragma"
        assert rules_of(rep, "nondet-source")[0].reason == "wall meter"

    def test_standalone_pragma_covers_next_line(self):
        rep = analyze_source(src("""
            import time

            def f():
                # da: allow[nondet-source] -- wall meter spanning a long call
                t0 = time.perf_counter()
                return t0
        """))
        assert not unsuppressed_of(rep, "nondet-source")

    def test_file_level_pragma(self):
        rep = analyze_source(src("""
            # da: allow-file[nondet-source] -- deployed-clock module
            import time

            def f():
                return time.time()

            def g():
                return time.monotonic()
        """))
        assert not unsuppressed_of(rep, "nondet-source")
        assert len(rules_of(rep, "nondet-source")) == 2

    def test_crypto_allowlist(self):
        rep = analyze_source(src("""
            import os

            def keygen():
                return os.urandom(32)
        """), path="indy_plenum_tpu/crypto/newkeys.py")
        assert not rules_of(rep, "nondet-source")

    def test_docstring_grammar_is_not_a_pragma(self):
        rep = analyze_source(src('''
            import time

            def f():
                """Examples: # da: allow[nondet-source] -- quoted"""
                return time.time()
        '''))
        assert len(unsuppressed_of(rep, "nondet-source")) == 1


# --- pragma self-lint ---------------------------------------------------

class TestPragmaRule:
    def test_missing_reason_is_a_finding(self):
        rep = analyze_source(src("""
            import time

            def f():
                return time.time()  # da: allow[nondet-source]
        """))
        msgs = [f.message for f in unsuppressed_of(rep, "pragma")]
        assert any("missing justification" in m for m in msgs)

    def test_unknown_rule_is_a_finding(self):
        rep = analyze_source(src("""
            x = 1  # da: allow[no-such-rule] -- because
        """))
        msgs = [f.message for f in unsuppressed_of(rep, "pragma")]
        assert any("unknown rule 'no-such-rule'" in m for m in msgs)


# --- hash-id-flow -------------------------------------------------------

class TestHashIdFlow:
    def test_hash_into_sink(self):
        rep = analyze_source(src("""
            import hashlib

            def fingerprint(items):
                h = hash(tuple(items))
                return hashlib.sha256(str(h).encode()).hexdigest()
        """))
        assert len(unsuppressed_of(rep, "hash-id-flow")) == 1

    def test_dunder_hash_exempt(self):
        rep = analyze_source(src("""
            class K:
                def __hash__(self):
                    return hash((self.a, self.b))
        """))
        assert not rules_of(rep, "hash-id-flow")

    def test_plain_hash_without_sink_ok(self):
        rep = analyze_source(src("""
            def bucket(key, n):
                return hash(key) % n
        """))
        assert not rules_of(rep, "hash-id-flow")


# --- unordered-fingerprint ----------------------------------------------

class TestUnorderedFingerprint:
    def test_set_iteration_in_hash_fn(self):
        rep = analyze_source(src("""
            import hashlib

            def ordered_hash(digests):
                acc = hashlib.sha256()
                for d in set(digests):
                    acc.update(d)
                return acc.hexdigest()
        """))
        assert len(unsuppressed_of(rep, "unordered-fingerprint")) == 1

    def test_sorted_wrapper_ok(self):
        rep = analyze_source(src("""
            import hashlib

            def ordered_hash(digests):
                acc = hashlib.sha256()
                for d in sorted(set(digests)):
                    acc.update(d)
                return acc.hexdigest()
        """))
        assert not rules_of(rep, "unordered-fingerprint")

    def test_dict_values_and_named_set(self):
        rep = analyze_source(src("""
            def trace_hash(by_node):
                seen = set()
                rows = [v for v in by_node.values()]
                rows += [s for s in seen]
                return my_hash(rows)
        """))
        assert len(unsuppressed_of(rep, "unordered-fingerprint")) == 2

    def test_non_fingerprint_function_exempt(self):
        rep = analyze_source(src("""
            def drain(pending):
                for p in set(pending):
                    p.fire()
        """))
        assert not rules_of(rep, "unordered-fingerprint")


# --- trace-guard --------------------------------------------------------

_HOT = "indy_plenum_tpu/tpu/fake_plane.py"


class TestTraceGuard:
    def test_unguarded_allocating_args_flagged(self):
        rep = analyze_source(src("""
            def flush(self):
                self.trace.record("flush.dispatch", cat="dispatch",
                                  args={"votes": self.votes})
        """), path=_HOT)
        assert len(unsuppressed_of(rep, "trace-guard")) == 1

    def test_guarded_if_and_guard_name(self):
        rep = analyze_source(src("""
            def flush(self):
                if self.trace.enabled:
                    self.trace.record("a", args={"v": 1 + 1})
                trace_on = self.trace.enabled
                if trace_on:
                    self.trace.record("b", args={"v": self.x * 2})
        """), path=_HOT)
        assert not rules_of(rep, "trace-guard")

    def test_ifexp_span_guard(self):
        rep = analyze_source(src("""
            def tick(self, _NO_SPAN):
                with self.trace.span("tick.eval",
                                     args={"n": len(self.nodes)}) \\
                        if self.trace.enabled else _NO_SPAN:
                    pass
        """), path=_HOT)
        assert not rules_of(rep, "trace-guard")

    def test_early_exit_guard(self):
        rep = analyze_source(src("""
            def mark(self, key):
                if not self.trace.enabled:
                    return
                self.trace.record("m", key=(key, self.view_no))
        """), path=_HOT)
        assert not rules_of(rep, "trace-guard")

    def test_constant_args_exempt(self):
        rep = analyze_source(src("""
            def tick(self):
                self.trace.record("tick.drain", cat="dispatch")
        """), path=_HOT)
        assert not rules_of(rep, "trace-guard")

    def test_out_of_scope_package_exempt(self):
        rep = analyze_source(src("""
            def report(self):
                self.trace.record("chaos.fault", args={"k": [1, 2]})
        """), path="indy_plenum_tpu/chaos/fake.py")
        assert not rules_of(rep, "trace-guard")


# --- device-sync --------------------------------------------------------

class TestDeviceSync:
    def test_sync_calls_flagged(self):
        rep = analyze_source(src("""
            import jax
            import jax.numpy as jnp
            import numpy as np

            def readback(dev):
                host = np.asarray(dev)
                full = jax.device_get(dev)
                dev.block_until_ready()
                return host, full
        """), path="indy_plenum_tpu/server/fake.py")
        assert len(unsuppressed_of(rep, "device-sync")) == 3

    def test_float_coercion_on_jnp_value(self):
        rep = analyze_source(src("""
            import jax.numpy as jnp

            def occupancy(votes, cap):
                frac = jnp.sum(votes) / cap
                return float(frac)
        """), path="indy_plenum_tpu/server/fake.py")
        assert len(unsuppressed_of(rep, "device-sync")) == 1

    def test_sanctioned_modules_exempt(self):
        code = src("""
            import jax
            import numpy as np

            def absorb(dev):
                return np.asarray(jax.device_get(dev))
        """)
        for path in ("indy_plenum_tpu/tpu/vote_plane.py",
                     "indy_plenum_tpu/tpu/quorum.py"):
            assert not rules_of(analyze_source(code, path=path),
                                "device-sync")

    def test_non_jax_module_exempt(self):
        rep = analyze_source(src("""
            import numpy as np

            def pack(rows):
                return np.asarray(rows)
        """), path="indy_plenum_tpu/ledger/fake.py")
        assert not rules_of(rep, "device-sync")


# --- buffer-donation ----------------------------------------------------

class TestBufferDonation:
    def test_persistent_buffer_flagged(self):
        rep = analyze_source(src("""
            import jax.numpy as jnp

            def stage(self):
                return jnp.asarray(self._scatter_buf)
        """), path="indy_plenum_tpu/tpu/fake_plane.py")
        assert len(unsuppressed_of(rep, "buffer-donation")) == 1

    def test_local_alias_of_buffer_flagged(self):
        rep = analyze_source(src("""
            import jax.numpy as jnp

            def stage(self):
                buf = self._bufs[64]
                buf[:] = 0
                return jnp.asarray(buf)
        """), path="indy_plenum_tpu/tpu/fake_plane.py")
        assert len(unsuppressed_of(rep, "buffer-donation")) == 1

    def test_fresh_value_and_forced_copy_ok(self):
        rep = analyze_source(src("""
            import jax.numpy as jnp
            import numpy as np

            def stage(self, words):
                fresh = np.zeros((4, 64), np.uint32)
                return jnp.asarray(fresh), jnp.array(self._buf), \\
                    jnp.asarray(words_row(words))
        """), path="indy_plenum_tpu/tpu/fake_plane.py")
        assert not rules_of(rep, "buffer-donation")


# --- config-knob --------------------------------------------------------

_CONFIG_FIXTURE = src("""
    from dataclasses import dataclass

    @dataclass
    class Config:
        KnobUsed: int = 1
        KnobOrphan: int = 2
        KnobPragmad: int = 3  # da: allow[config-knob] -- read by external scripts
""")


def _knob_report(consumer_src):
    analyzer = Analyzer(make_rules())
    mods = [
        ModuleInfo.from_source(_CONFIG_FIXTURE,
                               path="fakepkg/config.py"),
        ModuleInfo.from_source(consumer_src, path="fakepkg/user.py"),
    ]
    return analyzer.analyze_modules(mods)


class TestConfigKnob:
    def test_unknown_read_and_orphan_flagged(self):
        rep = _knob_report(src("""
            def f(config):
                return config.KnobUsed + config.KnobTypo
        """))
        msgs = [f.message for f in unsuppressed_of(rep, "config-knob")]
        assert any("'KnobTypo' has no default" in m for m in msgs)
        assert any("'KnobOrphan' is defined but never read" in m
                   for m in msgs)
        assert not any("KnobUsed" in m or "KnobPragmad" in m
                       for m in msgs)

    def test_getattr_read_counts(self):
        rep = _knob_report(src("""
            def f(config):
                return getattr(config, "KnobOrphan", None)
        """))
        msgs = [f.message for f in unsuppressed_of(rep, "config-knob")]
        assert not any("KnobOrphan" in m for m in msgs)

    def test_registry_renders_markdown(self):
        rule = ConfigKnobRule()
        analyzer = Analyzer([rule])
        analyzer.analyze_modules([
            ModuleInfo.from_source(_CONFIG_FIXTURE,
                                   path="fakepkg/config.py"),
            ModuleInfo.from_source(
                "def f(config):\n    return config.KnobUsed\n",
                path="fakepkg/user.py"),
        ])
        table = rule.render_registry()
        assert "| Knob | Default | Read by |" in table
        assert "| `KnobUsed` | `1` |" in table


# --- whole-repo gate + determinism --------------------------------------

class TestWholeRepo:
    def test_package_is_clean(self):
        """THE tier-1 backstop: any new unsuppressed finding anywhere in
        indy_plenum_tpu/ fails this test — fix it or pragma it with a
        justification."""
        report = analyze_paths([PKG])
        pretty = "\n".join(f.render() for f in report.unsuppressed)
        assert not report.unsuppressed, f"new static findings:\n{pretty}"

    def test_findings_hash_byte_identical_across_runs(self):
        r1 = analyze_paths([PKG])
        r2 = analyze_paths([PKG])
        assert r1.findings_hash == r2.findings_hash
        assert [f.to_dict() for f in r1.findings] \
            == [f.to_dict() for f in r2.findings]

    def test_every_pragma_has_a_reason(self):
        report = analyze_paths([PKG])
        for f in report.findings:
            if f.suppressed == "pragma":
                assert f.reason, f"reasonless pragma suppressing {f}"

    def test_shipped_baseline_is_empty(self):
        from indy_plenum_tpu.analysis import DEFAULT_BASELINE, \
            load_baseline
        assert load_baseline(DEFAULT_BASELINE) == set(), \
            "the shipped baseline must stay empty — fix or pragma " \
            "findings instead of baselining them"


class TestReviewRegressions:
    def test_rule_filter_keeps_full_catalog_for_pragma_lint(self):
        """--rule nondet-source must not flag pragmas naming OTHER
        shipped rules as unknown (the self-lint sees the catalog)."""
        proc = _run_cli("indy_plenum_tpu", "--rule", "nondet-source",
                        "--json")
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr
        data = json.loads(proc.stdout)
        assert data["unsuppressed"] == 0

    def test_single_file_run_anchors_at_package_root(self):
        """Per-file lint must name modules like a package walk would,
        so path-prefix allowlists (crypto/ keygen) still apply."""
        proc = _run_cli(os.path.join(PKG, "crypto", "signers.py"))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_baseline_cannot_suppress_pragma_findings(self, tmp_path):
        from indy_plenum_tpu.analysis import write_baseline

        mod = tmp_path / "mod.py"
        mod.write_text("import time\n\n"
                       "def f():\n"
                       "    return time.time()  # da: allow[nondet-source]\n")
        first = analyze_paths([str(mod)])
        write_baseline(str(tmp_path / "bl.json"),
                       [f.baseline_key() for f in first.unsuppressed])
        again = analyze_paths([str(mod)],
                              baseline_path=str(tmp_path / "bl.json"))
        assert any(f.rule == "pragma" for f in again.unsuppressed), \
            "reasonless-pragma findings must never be baselined away"

    def test_subdirectory_run_anchors_at_package_root(self):
        """`lint indy_plenum_tpu/tpu` must apply the same allowlists as
        the whole-package walk (vote_plane is sanctioned by PATH)."""
        proc = _run_cli(os.path.join(PKG, "tpu"))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_missing_path_fails_closed(self):
        proc = _run_cli("no/such/package")
        assert proc.returncode != 0
        assert "does not exist" in proc.stderr + proc.stdout

    def test_unrelated_enabled_flag_is_not_a_trace_guard(self):
        rep = analyze_source(src("""
            def flush(self):
                if self.metrics.enabled:
                    self.trace.record("a", args={"v": self.x + 1})
        """), path=_HOT)
        assert len(unsuppressed_of(rep, "trace-guard")) == 1

    def test_baseline_ordinals_distinguish_identical_findings(
            self, tmp_path):
        from indy_plenum_tpu.analysis import write_baseline

        mod = tmp_path / "mod.py"
        mod.write_text("import time\n\n"
                       "def f():\n"
                       "    a = time.time()\n"
                       "    b = time.time()\n"
                       "    return a, b\n")
        first = analyze_paths([str(mod)])
        keys = [f.baseline_key() for f in first.unsuppressed]
        assert len(keys) == 2 and len(set(keys)) == 2
        # baselining only the FIRST occurrence must leave the second
        # (and any future identical finding) unsuppressed
        bl = tmp_path / "bl.json"
        write_baseline(str(bl), keys[:1])
        again = analyze_paths([str(mod)], baseline_path=str(bl))
        assert len(again.unsuppressed) == 1

    def test_inverted_guard_is_not_a_guard(self):
        """`off = not trace.enabled; if off:` runs when tracing is
        DISABLED — the allocating record inside must be flagged."""
        rep = analyze_source(src("""
            def flush(self):
                off = not self.trace.enabled
                if off:
                    self.trace.record("a", args={"v": self.x + 1})
        """), path=_HOT)
        assert len(unsuppressed_of(rep, "trace-guard")) == 1

    def test_negated_if_guards_the_else_branch(self):
        rep = analyze_source(src("""
            def flush(self):
                if not self.trace.enabled:
                    pass
                else:
                    self.trace.record("a", args={"v": self.x + 1})
        """), path=_HOT)
        assert not rules_of(rep, "trace-guard")

    def test_bare_relative_tpu_import_in_scope(self):
        """A tpu/ sibling getting kernels via `from . import ...` is
        still device-sync scoped (the reviewer's staging.py case)."""
        rep = analyze_source(src("""
            import numpy as np
            from . import ed25519 as ted

            def readback(batch):
                return np.asarray(ted.verify_kernel_full(batch))
        """), path="indy_plenum_tpu/tpu/staging.py")
        assert len(unsuppressed_of(rep, "device-sync")) == 1

    def test_streaming_hashlib_update_is_a_sink(self):
        rep = analyze_source(src("""
            import hashlib

            def ordered_hash(items):
                h = hash(tuple(items))
                acc = hashlib.sha256()
                acc.update(str(h).encode())
                return acc.hexdigest()
        """))
        assert len(unsuppressed_of(rep, "hash-id-flow")) == 1

    def test_trailing_knob_pragma_does_not_leak_to_next_knob(self):
        rule = ConfigKnobRule()
        Analyzer([rule]).analyze_modules([ModuleInfo.from_source(src("""
            from dataclasses import dataclass

            @dataclass
            class Config:
                KnobA: int = 1  # da: allow[config-knob] -- read by scripts
                KnobB: int = 2
        """), path="fakepkg/config.py")])
        assert rule.knob_defs["KnobA"].pragma_reason == "read by scripts"
        assert rule.knob_defs["KnobB"].pragma_reason == ""

    def test_nested_functions_are_separate_scopes(self):
        rep = analyze_source(src("""
            import hashlib

            def outer(items):
                h = hash(items[0])

                def inner(xs):
                    g = hash(xs)
                    return hashlib.sha256(str(g).encode())
                return inner, h
        """))
        hits = unsuppressed_of(rep, "hash-id-flow")
        # exactly ONE finding, attributed to inner(); outer's unrelated
        # taint must not bleed in and the site must not double-report
        assert len(hits) == 1 and "inner()" in hits[0].message


class TestBaseline:
    def test_write_then_suppress_round_trip(self, tmp_path):
        from indy_plenum_tpu.analysis import write_baseline

        mod = tmp_path / "pkg" / "mod.py"
        mod.parent.mkdir()
        mod.write_text("import time\n\n"
                       "def f():\n    return time.time()\n")
        first = analyze_paths([str(mod.parent)])
        assert len(first.unsuppressed) == 1
        bl = tmp_path / "baseline.json"
        write_baseline(str(bl),
                       [f.baseline_key() for f in first.unsuppressed])
        second = analyze_paths([str(mod.parent)],
                               baseline_path=str(bl))
        assert not second.unsuppressed
        assert second.findings[0].suppressed == "baseline"


# --- CLI smoke ----------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, LINT, *args],
        capture_output=True, text=True, cwd=REPO, timeout=120)


class TestCli:
    def test_exit_1_on_finding_and_0_when_pragmad(self, tmp_path):
        bad = tmp_path / "mod.py"
        bad.write_text("import time\n\n"
                       "def f():\n    return time.time()\n")
        proc = _run_cli(str(bad))
        assert proc.returncode == 1
        assert "nondet-source" in proc.stdout
        bad.write_text(
            "import time\n\n"
            "def f():\n"
            "    # da: allow[nondet-source] -- fixture seam\n"
            "    return time.time()\n")
        proc = _run_cli(str(bad), "--json")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        data = json.loads(proc.stdout)
        assert data["unsuppressed"] == 0 and data["total"] == 1

    def test_list_rules(self):
        proc = _run_cli("--list-rules")
        assert proc.returncode == 0
        for name in ("nondet-source", "trace-guard", "device-sync",
                     "buffer-donation", "config-knob",
                     "unordered-fingerprint", "hash-id-flow", "pragma"):
            assert name in proc.stdout

    @pytest.mark.slow
    def test_whole_package_cli_and_knob_registry(self):
        proc = _run_cli("indy_plenum_tpu", "--json")
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr
        data = json.loads(proc.stdout)
        assert data["unsuppressed"] == 0
        knobs = _run_cli("indy_plenum_tpu", "--emit-knobs")
        assert knobs.returncode == 0
        assert "| Knob | Default | Read by |" in knobs.stdout
        assert "`QuorumTickInterval`" in knobs.stdout
