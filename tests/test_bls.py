"""BLS over BN254: pairing correctness, sign/aggregate/verify, and the
end-to-end state-proof read (VERDICT round-1 item 4).

The pairing library is pinned against algebraic identities (bilinearity,
the DSD hard-part vs a generic exponentiation); the protocol test drives a
real-execution sim pool with BlsBftReplica attached and verifies that a
CLIENT accepts a single node's proved read — the whole point of BLS here.
"""
import pytest

from indy_plenum_tpu.crypto.bls import bn254 as bn
from indy_plenum_tpu.crypto.bls.bls_crypto import (
    BlsCryptoSigner,
    BlsCryptoVerifier,
    BlsKeyPair,
)

V = BlsCryptoVerifier()


# --- tier 1: curve + pairing ----------------------------------------------


def test_non_canonical_encodings_rejected():
    """Advisor r2 (low): coordinates >= P must be rejected — otherwise one
    point has many wire forms (signature malleability breaking dedup and
    the b58-keyed subgroup cache identity)."""
    from indy_plenum_tpu.crypto.bls.bls_crypto import (
        g1_from_bytes,
        g1_to_bytes,
        g2_from_bytes,
        g2_to_bytes,
    )

    g1 = g1_to_bytes(bn.G1_GEN)
    assert g1_from_bytes(g1) == bn.G1_GEN
    aliased_x = (bn.G1_GEN[0] + bn.P).to_bytes(32, "big") + g1[32:]
    with pytest.raises(ValueError):
        g1_from_bytes(aliased_x)
    aliased_y = g1[:32] + (bn.G1_GEN[1] + bn.P).to_bytes(32, "big")
    with pytest.raises(ValueError):
        g1_from_bytes(aliased_y)

    g2 = g2_to_bytes(bn.G2_GEN)
    assert g2_from_bytes(g2) == bn.G2_GEN
    aliased = (bn.G2_GEN[0][0] + bn.P).to_bytes(32, "big") + g2[32:]
    with pytest.raises(ValueError):
        g2_from_bytes(aliased)


def test_generators_and_orders():
    assert bn.g1_is_on_curve(bn.G1_GEN)
    assert bn.g2_is_on_curve(bn.G2_GEN)
    assert bn.g1_mul(bn.G1_GEN, bn.R) is None
    assert bn.g2_mul(bn.G2_GEN, bn.R) is None


def test_pairing_bilinear_and_nondegenerate():
    e1 = bn.pairing(bn.G2_GEN, bn.G1_GEN)
    assert e1 != bn.FP12_ONE
    a, b = 6, 13
    lhs = bn.pairing(bn.g2_mul(bn.G2_GEN, b), bn.g1_mul(bn.G1_GEN, a))
    assert lhs == bn.f12_pow(e1, a * b)
    assert bn.pairing_check([(bn.G1_GEN, bn.G2_GEN),
                             (bn.g1_neg(bn.G1_GEN), bn.G2_GEN)])


def test_hard_part_matches_generic_pow():
    m = bn._easy(bn.miller_loop(bn.G2_GEN, bn.G1_GEN))
    e = (bn.P ** 4 - bn.P ** 2 + 1) // bn.R
    assert bn._hard(m) == bn.f12_pow(m, e)


# --- tier 1: BLS scheme ----------------------------------------------------


def test_sign_verify_and_reject():
    kp = BlsKeyPair(b"\x21" * 32)
    signer = BlsCryptoSigner(kp)
    sig = signer.sign(b"state-root-1")
    assert V.verify_sig(sig, b"state-root-1", kp.pk_b58)
    assert not V.verify_sig(sig, b"state-root-2", kp.pk_b58)
    other = BlsKeyPair(b"\x22" * 32)
    assert not V.verify_sig(sig, b"state-root-1", other.pk_b58)


def test_proof_of_possession():
    kp = BlsKeyPair(b"\x23" * 32)
    assert V.verify_pop(kp.pop(), kp.pk_b58)
    other = BlsKeyPair(b"\x24" * 32)
    assert not V.verify_pop(other.pop(), kp.pk_b58)


def test_aggregate_multi_sig():
    kps = [BlsKeyPair(bytes([0x30 + i]) * 32) for i in range(4)]
    msg = b"the committed state root"
    sigs = [BlsCryptoSigner(kp).sign(msg) for kp in kps]
    agg = V.aggregate_sigs(sigs)
    pks = [kp.pk_b58 for kp in kps]
    assert V.verify_multi_sig(agg, msg, pks)
    # missing participant -> fail; wrong message -> fail
    assert not V.verify_multi_sig(agg, msg, pks[:3])
    assert not V.verify_multi_sig(agg, b"other", pks)
    # aggregate with one bad signature -> fail
    bad = V.aggregate_sigs(sigs[:3] + [BlsCryptoSigner(kps[3]).sign(b"x")])
    assert not V.verify_multi_sig(bad, msg, pks)


# --- tier 5: protocol e2e --------------------------------------------------


def test_state_proof_read_from_single_node():
    from indy_plenum_tpu.client.state_proof import verify_proved_reply
    from indy_plenum_tpu.simulation.pool import SimPool

    pool = SimPool(4, seed=51, real_execution=True, bls=True)
    reqs = [pool.submit_request(i) for i in range(3)]
    pool.run_for(8)
    assert all(len(n.ordered_digests) == 3 for n in pool.nodes)

    # the client's trust anchor: the pool's BLS keys (from genesis)
    pool_keys = {name: pk for name, (kp, pk, pop) in pool.bls_keys.items()}
    n, f = 4, 1
    target = reqs[0].target_signer

    # ask ONE node; verify without talking to anyone else
    reply = pool.node("node2").read_nym_with_proof(target.identifier)
    assert reply.value is not None
    assert verify_proved_reply(reply, pool_keys, min_participants=n - f)

    # non-membership is provable too
    absent = pool.node("node1").read_nym_with_proof("NoSuchDid111111111111")
    assert absent.value is None
    assert verify_proved_reply(absent, pool_keys, min_participants=n - f)

    # a lying node cannot forge: tampered value fails the Merkle check
    forged = pool.node("node3").read_nym_with_proof(target.identifier)
    forged.value = b"forged"
    assert not verify_proved_reply(forged, pool_keys, min_participants=n - f)

    # a multi-sig from too few nodes is rejected by the client
    reply2 = pool.node("node0").read_nym_with_proof(target.identifier)
    if reply2.multi_sig is not None:
        reply2.multi_sig.participants = reply2.multi_sig.participants[:f]
        assert not verify_proved_reply(reply2, pool_keys,
                                       min_participants=n - f)


# --- fast path pinned against the oracle (bn254_fast vs bn254) -------------


def test_fast_scalar_muls_match_oracle():
    from indy_plenum_tpu.crypto.bls import bn254 as bn
    from indy_plenum_tpu.crypto.bls import bn254_fast as fast

    for k in (0, 1, 2, 3, 17, 255, 2**64 + 3, bn.R - 1, bn.R, bn.R + 7,
              0x1234567890abcdef1234567890abcdef):
        assert fast.g1_mul(bn.G1_GEN, k) == bn.g1_mul(bn.G1_GEN, k), k
        assert fast.g2_mul(bn.G2_GEN, k) == bn.g2_mul(bn.G2_GEN, k), k


def test_fast_pairing_matches_oracle_and_is_bilinear():
    from indy_plenum_tpu.crypto.bls import bn254 as bn
    from indy_plenum_tpu.crypto.bls import bn254_fast as fast

    for a, b in ((12345, 67890), (1, 1), (bn.R - 2, 3)):
        p = fast.g1_mul(bn.G1_GEN, a)
        q = fast.g2_mul(bn.G2_GEN, b)
        assert fast.pairing(q, p) == bn.pairing(q, p), (a, b)
    # e(aP, bQ) == e(abP, Q)
    p7 = fast.g1_mul(bn.G1_GEN, 7)
    q11 = fast.g2_mul(bn.G2_GEN, 11)
    assert fast.pairing(q11, p7) == fast.pairing(
        bn.G2_GEN, fast.g1_mul(bn.G1_GEN, 77))


def test_fast_pairing_check_and_sums():
    from indy_plenum_tpu.crypto.bls import bn254 as bn
    from indy_plenum_tpu.crypto.bls import bn254_fast as fast

    p = fast.g1_mul(bn.G1_GEN, 31337)
    q = fast.g2_mul(bn.G2_GEN, 424242)
    assert fast.pairing_check([(p, q), (bn.g1_neg(p), q)])
    assert not fast.pairing_check([(p, q), (p, q)])

    pts1 = [fast.g1_mul(bn.G1_GEN, k) for k in (5, 9, 31, bn.R - 1)]
    acc = None
    for x in pts1:
        acc = bn.g1_add(acc, x)
    assert fast.g1_sum(pts1) == acc
    pts2 = [fast.g2_mul(bn.G2_GEN, k) for k in (4, 8, 15)]
    acc2 = None
    for x in pts2:
        acc2 = bn.g2_add(acc2, x)
    assert fast.g2_sum(pts2) == acc2


def test_out_of_subgroup_g2_point_rejected():
    """The twist E'(Fp2) has order R*(2P-R): an on-curve point outside the
    R-subgroup must fail g2_in_subgroup (both oracle and fast path). A
    scalar ladder that reduces k mod R computes [R mod R]Q = O for EVERY
    point and silently accepts such keys (wrong-subgroup key attack)."""
    from indy_plenum_tpu.crypto.bls import bn254 as bn
    from indy_plenum_tpu.crypto.bls import bn254_fast as fast

    # Tonelli-Shanks square root in Fp2 (test-only helper)
    order = bn.P * bn.P - 1

    def is_qr(a):
        return a == (0, 0) or bn.f2_pow(a, order // 2) == (1, 0)

    def f2_sqrt(a):
        q, s = order, 0
        while q % 2 == 0:
            q //= 2
            s += 1
        z = None
        for zc in ((2, 1), (1, 1), (3, 1), (1, 2), (5, 3)):
            if not is_qr(zc):
                z = zc
                break
        assert z is not None
        m, c = s, bn.f2_pow(z, q)
        t, r = bn.f2_pow(a, q), bn.f2_pow(a, (q + 1) // 2)
        while t != (1, 0):
            i, t2 = 0, t
            while t2 != (1, 0):
                t2 = bn.f2_sqr(t2)
                i += 1
            b = bn.f2_pow(c, 1 << (m - i - 1))
            m, c = i, bn.f2_sqr(b)
            t, r = bn.f2_mul(t, c), bn.f2_mul(r, b)
        return r

    # find an on-curve point; with cofactor 2P-R >> 1, a random point is
    # essentially never in the R-subgroup
    found = None
    for xi in range(1, 200):
        x = (xi, 7)
        rhs = bn.f2_add(bn.f2_mul(bn.f2_sqr(x), x), bn.B2)
        if not is_qr(rhs):
            continue
        y = f2_sqrt(rhs)
        pt = (x, y)
        assert bn.g2_is_on_curve(pt)
        if bn.g2_mul(pt, 1) == pt:  # sanity
            found = pt
            break
    assert found is not None
    # confirmed out of subgroup by an unreduced [R] ladder
    assert not fast.g2_in_subgroup(found)
    assert not bn.g2_in_subgroup(found)
    # and the real generator still passes
    assert fast.g2_in_subgroup(bn.G2_GEN)
    assert bn.g2_in_subgroup(bn.G2_GEN)
    try:
        from indy_plenum_tpu.crypto.bls import bn254_native as nat
    except Exception:
        nat = None
    if nat is not None:  # the native ladder must agree
        assert not nat.g2_in_subgroup(found)
        assert nat.g2_in_subgroup(bn.G2_GEN)

    # end to end: such a key is rejected by the verifier
    from indy_plenum_tpu.crypto.bls.bls_crypto import (
        BlsCryptoVerifier, g2_to_bytes)
    from indy_plenum_tpu.utils.base58 import b58encode

    bad_pk = b58encode(g2_to_bytes(found))
    assert not BlsCryptoVerifier.verify_sig(
        b58encode(b"\x00" * 64), b"msg", bad_pk)


# --- native C backend pinned against the oracle ----------------------------


def _native():
    import pytest

    try:
        from indy_plenum_tpu.crypto.bls import bn254_native as nat
        return nat
    except Exception:
        pytest.skip("native BN254 backend unavailable (no compiler)")


def test_native_scalar_muls_match_oracle():
    from indy_plenum_tpu.crypto.bls import bn254 as bn

    nat = _native()
    for k in (0, 1, 2, 3, 17, 2**64 + 3, bn.R - 1, bn.R, bn.R + 7,
              0x1234567890abcdef1234567890abcdef):
        assert nat.g1_mul(bn.G1_GEN, k) == bn.g1_mul(bn.G1_GEN, k), k
        assert nat.g2_mul(bn.G2_GEN, k) == bn.g2_mul(bn.G2_GEN, k), k


def test_native_pairing_matches_oracle():
    from indy_plenum_tpu.crypto.bls import bn254 as bn

    nat = _native()
    for a, b in ((12345, 67890), (1, 1), (bn.R - 2, 3)):
        p = bn.g1_mul(bn.G1_GEN, a)
        q = bn.g2_mul(bn.G2_GEN, b)
        assert nat.pairing(q, p) == bn.pairing(q, p), (a, b)
    p = nat.g1_mul(bn.G1_GEN, 31337)
    q = nat.g2_mul(bn.G2_GEN, 424242)
    assert nat.pairing_check([(p, q), (bn.g1_neg(p), q)])
    assert not nat.pairing_check([(p, q), (p, q)])


def test_native_sums_and_subgroup_match_oracle():
    from indy_plenum_tpu.crypto.bls import bn254 as bn
    from indy_plenum_tpu.crypto.bls import bn254_fast as fast

    nat = _native()
    pts1 = [bn.g1_mul(bn.G1_GEN, k) for k in (5, 9, 31, bn.R - 1)]
    assert nat.g1_sum(pts1) == fast.g1_sum(pts1)
    pts2 = [bn.g2_mul(bn.G2_GEN, k) for k in (4, 8, 15)]
    assert nat.g2_sum(pts2) == fast.g2_sum(pts2)
    assert nat.g2_in_subgroup(bn.G2_GEN)
    assert nat.g2_in_subgroup(None)


def test_native_fp_sqrt_matches_python_pow():
    from indy_plenum_tpu.crypto.bls import bn254 as bn

    nat = _native()
    for x in (4, 9, 12345, bn.P - 1, 2):
        want = pow(x, (bn.P + 1) // 4, bn.P)
        want = want if want * want % bn.P == x % bn.P else None
        assert nat.fp_sqrt(x) == want, x
    # a known non-residue for P = 3 mod 4: -1
    assert nat.fp_sqrt(bn.P - 1) is None or \
        nat.fp_sqrt(bn.P - 1) ** 2 % bn.P == bn.P - 1


def test_batch_multi_sig_verify_exact_verdicts():
    """Round-5 batched plane: k multi-sigs verified with one shared final
    exponentiation; a forged item is pinpointed exactly via fallback."""
    import hashlib

    from indy_plenum_tpu.crypto.bls.bls_crypto import (
        BlsCryptoSigner,
        BlsCryptoVerifier,
        BlsKeyPair,
    )

    kps = [BlsKeyPair(hashlib.sha256(b"bt%d" % i).digest())
           for i in range(7)]
    pks = [kp.pk_b58 for kp in kps]
    items = []
    for j in range(5):
        msg = b"root-batch-%d" % j
        items.append(([BlsCryptoSigner(kp).sign(msg) for kp in kps],
                      msg, pks))
    out = BlsCryptoVerifier.aggregate_and_verify_batch(items)
    assert all(ok for _, ok in out)
    # each aggregate also passes the UNBATCHED verifier (oracle-pinned
    # path: verify_multi_sig rides the affine-pinned pairing check)
    for (agg, _ok), (_s, msg, pk) in zip(out, items):
        assert BlsCryptoVerifier.verify_multi_sig(agg, msg, pk)

    # tamper item 2: wrong message for its shares
    bad = list(items)
    bad[2] = (bad[2][0], b"forged", bad[2][2])
    out2 = BlsCryptoVerifier.aggregate_and_verify_batch(bad)
    assert [ok for _, ok in out2] == [True, True, False, True, True]

    # mixed participant sets (two apk groups) still verify in one call
    sub = pks[:5]
    msg = b"subset-batch"
    mixed = items[:2] + [([BlsCryptoSigner(kp).sign(msg)
                           for kp in kps[:5]], msg, sub)]
    out3 = BlsCryptoVerifier.aggregate_and_verify_batch(mixed)
    assert all(ok for _, ok in out3)

    # malformed signature share: that item fails, others unaffected
    broken = list(items)
    broken[0] = (["not-a-sig!"] + broken[0][0][1:], broken[0][1],
                 broken[0][2])
    out4 = BlsCryptoVerifier.aggregate_and_verify_batch(broken)
    assert [ok for _, ok in out4] == [False, True, True, True, True]


def test_b58_native_matches_python_fallback():
    import random

    from indy_plenum_tpu.utils import base58 as b58

    rnd = random.Random(7)
    for _ in range(200):
        data = bytes(rnd.randrange(256)
                     for _ in range(rnd.randrange(0, 70)))
        enc = b58.b58encode(data)
        # pure-Python oracle (the fallback implementation)
        num = int.from_bytes(data, "big")
        out = bytearray()
        z = len(data) - len(data.lstrip(b"\0"))
        while num:
            num, r = divmod(num, 58)
            out.append(b58.ALPHABET[r])
        out.extend(b58.ALPHABET[0:1] * z)
        out.reverse()
        assert enc == out.decode()
        assert b58.b58decode(enc) == data
    for bad in ("0", "l", "I", "O", "x0y"):
        try:
            b58.b58decode(bad)
            raise AssertionError(f"accepted invalid {bad!r}")
        except ValueError:
            pass


def test_deferred_bls_verification_ticks_and_proved_reads():
    """Round-5 wiring: tick mode defers per-ordered-batch BLS aggregate
    checks into ONE multi-pairing per tick (BlsBftReplica.flush via
    service_quorum_tick); proved reads still verify end-to-end."""
    from indy_plenum_tpu.client.state_proof import verify_proved_reply
    from indy_plenum_tpu.config import getConfig
    from indy_plenum_tpu.simulation.pool import SimPool

    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 2,
                        "QuorumTickInterval": 0.05})
    pool = SimPool(4, seed=52, config=config, real_execution=True,
                   bls=True, device_quorum=True, shadow_check=False,
                   pipelined_flush=True)
    # tick mode switched every master replica into deferred verification
    assert all(n.bls_replica.defer_verification for n in pool.nodes)
    reqs = [pool.submit_request(i) for i in range(6)]
    pool.run_for(20)
    assert all(len(n.ordered_digests) == 6 for n in pool.nodes)
    pool_keys = {name: pk for name, (kp, pk, pop) in pool.bls_keys.items()}
    reply = pool.node("node2").read_nym_with_proof(
        reqs[0].target_signer.identifier)
    assert reply.value is not None
    assert verify_proved_reply(reply, pool_keys, min_participants=3)


def test_deferred_flush_identifies_culprit():
    """A bad signature share in the deferred queue: the tick flush's
    batch check fails for that batch only; the culprit scan raises a
    suspicion and the good-subset multi-sig is still stored."""
    import hashlib

    from indy_plenum_tpu.bls.bls_key_register import BlsKeyRegister
    from indy_plenum_tpu.bls.bls_bft_replica import BlsBftReplica
    from indy_plenum_tpu.crypto.bls.bls_crypto import (
        BlsCryptoSigner,
        BlsKeyPair,
    )
    from indy_plenum_tpu.server.quorums import Quorums

    names = ["n0", "n1", "n2", "n3"]
    kps = {nm: BlsKeyPair(hashlib.sha256(nm.encode()).digest())
           for nm in names}
    register = BlsKeyRegister()
    for nm in names:
        register.add_key(nm, kps[nm].pk_b58)
    suspicions = []
    replica = BlsBftReplica(
        "n0", BlsCryptoSigner(kps["n0"]), register,
        suspicion_sink=suspicions.append)
    replica.defer_verification = True

    class PP:
        ledgerId = 1
        poolStateRootHash = None

    quorums = Quorums(4)
    for j, honest in enumerate((True, False, True)):
        pp = PP()
        pp.stateRootHash = "root%d" % j
        pp.txnRootHash = "troot%d" % j
        pp.ppTime = 1700000000 + j
        value = replica._value_for(pp)
        msg = value.serialize()
        for nm in names[1:]:
            signer = BlsCryptoSigner(kps[nm])
            sig = signer.sign(msg if honest or nm != "n2"
                              else b"forged")

            class C:
                viewNo, ppSeqNo, blsSig = 0, j + 1, sig
            replica.process_commit(C, nm)
        replica.process_order((0, j + 1), quorums, pp)
    assert replica.store.get("root0") is None  # nothing verified yet
    replica.flush()
    for j in range(3):
        ms = replica.store.get("root%d" % j)
        assert ms is not None, j
        if j == 1:
            assert "n2" not in ms.participants  # culprit excluded
    assert any(s.node == "n2" for s in suspicions)


def test_native_g1_sum_checked_matches_and_rejects():
    from indy_plenum_tpu.crypto.bls import bn254 as bn
    from indy_plenum_tpu.crypto.bls.bls_crypto import g1_to_bytes

    nat = _native()
    pts = [bn.g1_mul(bn.G1_GEN, k) for k in (5, 9, 31)]
    raws = [g1_to_bytes(p) for p in pts]
    want = g1_to_bytes(nat.g1_sum(pts))
    assert nat.g1_sum_checked_bytes(raws) == want
    # identity bytes contribute nothing
    assert nat.g1_sum_checked_bytes([b"\x00" * 64] + raws) == want
    # off-curve point rejected
    bad = bytearray(raws[0])
    bad[-1] ^= 1
    try:
        nat.g1_sum_checked_bytes(raws + [bytes(bad)])
        raise AssertionError("off-curve accepted")
    except ValueError:
        pass
    # non-canonical coordinate (>= P) rejected
    noncanon = (bn.P).to_bytes(32, "big") + raws[0][32:]
    try:
        nat.g1_sum_checked_bytes([noncanon])
        raise AssertionError("non-canonical accepted")
    except ValueError:
        pass
    # wrong length rejected
    try:
        nat.g1_sum_checked_bytes([b"\x01" * 63])
        raise AssertionError("short encoding accepted")
    except ValueError:
        pass
