"""BLS over BN254: pairing correctness, sign/aggregate/verify, and the
end-to-end state-proof read (VERDICT round-1 item 4).

The pairing library is pinned against algebraic identities (bilinearity,
the DSD hard-part vs a generic exponentiation); the protocol test drives a
real-execution sim pool with BlsBftReplica attached and verifies that a
CLIENT accepts a single node's proved read — the whole point of BLS here.
"""
import pytest

from indy_plenum_tpu.crypto.bls import bn254 as bn
from indy_plenum_tpu.crypto.bls.bls_crypto import (
    BlsCryptoSigner,
    BlsCryptoVerifier,
    BlsKeyPair,
)

V = BlsCryptoVerifier()


# --- tier 1: curve + pairing ----------------------------------------------


def test_non_canonical_encodings_rejected():
    """Advisor r2 (low): coordinates >= P must be rejected — otherwise one
    point has many wire forms (signature malleability breaking dedup and
    the b58-keyed subgroup cache identity)."""
    from indy_plenum_tpu.crypto.bls.bls_crypto import (
        g1_from_bytes,
        g1_to_bytes,
        g2_from_bytes,
        g2_to_bytes,
    )

    g1 = g1_to_bytes(bn.G1_GEN)
    assert g1_from_bytes(g1) == bn.G1_GEN
    aliased_x = (bn.G1_GEN[0] + bn.P).to_bytes(32, "big") + g1[32:]
    with pytest.raises(ValueError):
        g1_from_bytes(aliased_x)
    aliased_y = g1[:32] + (bn.G1_GEN[1] + bn.P).to_bytes(32, "big")
    with pytest.raises(ValueError):
        g1_from_bytes(aliased_y)

    g2 = g2_to_bytes(bn.G2_GEN)
    assert g2_from_bytes(g2) == bn.G2_GEN
    aliased = (bn.G2_GEN[0][0] + bn.P).to_bytes(32, "big") + g2[32:]
    with pytest.raises(ValueError):
        g2_from_bytes(aliased)


def test_generators_and_orders():
    assert bn.g1_is_on_curve(bn.G1_GEN)
    assert bn.g2_is_on_curve(bn.G2_GEN)
    assert bn.g1_mul(bn.G1_GEN, bn.R) is None
    assert bn.g2_mul(bn.G2_GEN, bn.R) is None


def test_pairing_bilinear_and_nondegenerate():
    e1 = bn.pairing(bn.G2_GEN, bn.G1_GEN)
    assert e1 != bn.FP12_ONE
    a, b = 6, 13
    lhs = bn.pairing(bn.g2_mul(bn.G2_GEN, b), bn.g1_mul(bn.G1_GEN, a))
    assert lhs == bn.f12_pow(e1, a * b)
    assert bn.pairing_check([(bn.G1_GEN, bn.G2_GEN),
                             (bn.g1_neg(bn.G1_GEN), bn.G2_GEN)])


def test_hard_part_matches_generic_pow():
    m = bn._easy(bn.miller_loop(bn.G2_GEN, bn.G1_GEN))
    e = (bn.P ** 4 - bn.P ** 2 + 1) // bn.R
    assert bn._hard(m) == bn.f12_pow(m, e)


# --- tier 1: BLS scheme ----------------------------------------------------


def test_sign_verify_and_reject():
    kp = BlsKeyPair(b"\x21" * 32)
    signer = BlsCryptoSigner(kp)
    sig = signer.sign(b"state-root-1")
    assert V.verify_sig(sig, b"state-root-1", kp.pk_b58)
    assert not V.verify_sig(sig, b"state-root-2", kp.pk_b58)
    other = BlsKeyPair(b"\x22" * 32)
    assert not V.verify_sig(sig, b"state-root-1", other.pk_b58)


def test_proof_of_possession():
    kp = BlsKeyPair(b"\x23" * 32)
    assert V.verify_pop(kp.pop(), kp.pk_b58)
    other = BlsKeyPair(b"\x24" * 32)
    assert not V.verify_pop(other.pop(), kp.pk_b58)


def test_aggregate_multi_sig():
    kps = [BlsKeyPair(bytes([0x30 + i]) * 32) for i in range(4)]
    msg = b"the committed state root"
    sigs = [BlsCryptoSigner(kp).sign(msg) for kp in kps]
    agg = V.aggregate_sigs(sigs)
    pks = [kp.pk_b58 for kp in kps]
    assert V.verify_multi_sig(agg, msg, pks)
    # missing participant -> fail; wrong message -> fail
    assert not V.verify_multi_sig(agg, msg, pks[:3])
    assert not V.verify_multi_sig(agg, b"other", pks)
    # aggregate with one bad signature -> fail
    bad = V.aggregate_sigs(sigs[:3] + [BlsCryptoSigner(kps[3]).sign(b"x")])
    assert not V.verify_multi_sig(bad, msg, pks)


# --- tier 5: protocol e2e --------------------------------------------------


def test_state_proof_read_from_single_node():
    from indy_plenum_tpu.client.state_proof import verify_proved_reply
    from indy_plenum_tpu.simulation.pool import SimPool

    pool = SimPool(4, seed=51, real_execution=True, bls=True)
    reqs = [pool.submit_request(i) for i in range(3)]
    pool.run_for(8)
    assert all(len(n.ordered_digests) == 3 for n in pool.nodes)

    # the client's trust anchor: the pool's BLS keys (from genesis)
    pool_keys = {name: pk for name, (kp, pk, pop) in pool.bls_keys.items()}
    n, f = 4, 1
    target = reqs[0].target_signer

    # ask ONE node; verify without talking to anyone else
    reply = pool.node("node2").read_nym_with_proof(target.identifier)
    assert reply.value is not None
    assert verify_proved_reply(reply, pool_keys, min_participants=n - f)

    # non-membership is provable too
    absent = pool.node("node1").read_nym_with_proof("NoSuchDid111111111111")
    assert absent.value is None
    assert verify_proved_reply(absent, pool_keys, min_participants=n - f)

    # a lying node cannot forge: tampered value fails the Merkle check
    forged = pool.node("node3").read_nym_with_proof(target.identifier)
    forged.value = b"forged"
    assert not verify_proved_reply(forged, pool_keys, min_participants=n - f)

    # a multi-sig from too few nodes is rejected by the client
    reply2 = pool.node("node0").read_nym_with_proof(target.identifier)
    if reply2.multi_sig is not None:
        reply2.multi_sig.participants = reply2.multi_sig.participants[:f]
        assert not verify_proved_reply(reply2, pool_keys,
                                       min_participants=n - f)
