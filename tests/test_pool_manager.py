"""Pool membership from the ledger (VERDICT round-2 item 8).

Reference: plenum/server/pool_manager.py (`TxnPoolManager`). Committed
NODE txns reconfigure the validator registry, quorums, BLS keys; a node
admitted by a NODE txn can then join, catch up, and participate.
"""
import hashlib

from indy_plenum_tpu.common.constants import (
    ALIAS,
    CLIENT_IP,
    CLIENT_PORT,
    NODE,
    NODE_IP,
    NODE_PORT,
    SERVICES,
    STEWARD,
    TARGET_NYM,
    TXN_TYPE,
    VALIDATOR,
    VERKEY,
    NYM,
    ROLE,
)
from indy_plenum_tpu.common.request import Request
from indy_plenum_tpu.crypto.signers import DidSigner
from indy_plenum_tpu.simulation.node_pool import NodePool


def _submit_and_order(pool, req, entry="node0", expect_total=None):
    pool.submit_to(entry, req)
    pool.run_for(15)
    if expect_total is not None:
        counts = [len(n.ordered_digests) for n in pool.nodes]
        assert counts == [expect_total] * len(pool.nodes), counts


def _node_request(steward: DidSigner, alias: str, req_id: int,
                  services=None) -> Request:
    data = {ALIAS: alias, NODE_IP: "127.0.0.1", NODE_PORT: 9800,
            CLIENT_IP: "127.0.0.1", CLIENT_PORT: 9801}
    if services is not None:
        data[SERVICES] = services
    req = Request(identifier=steward.identifier, reqId=req_id,
                  operation={TXN_TYPE: NODE,
                             TARGET_NYM: f"nym-{alias}", "data": data})
    steward.sign_request(req)
    return req


def test_membership_bootstraps_from_pool_genesis():
    pool = NodePool(4, seed=51, with_pool_genesis=True)
    for node in pool.nodes:
        assert node.pool_manager.validators == pool.validators
        assert node.data.validators == pool.validators
        assert node.data.quorums.n == 4
    # and consensus still works in membership-from-ledger mode
    req = pool.make_nym_request()
    _submit_and_order(pool, req, expect_total=1)


def test_node_txn_grows_pool_to_n5_quorums():
    """The verdict's acceptance: add a 5th node via NODE txn; every node
    reconfigures to n=5 quorums; the new node joins, catches up (through
    the NODE txn that admitted it), and the pool orders with 5 members."""
    pool = NodePool(4, seed=52, with_pool_genesis=True)
    _submit_and_order(pool, pool.make_nym_request(), expect_total=1)

    # trustee creates a NEW steward, who adds node4
    steward5 = DidSigner(hashlib.sha256(b"steward-5").digest())
    nym = Request(identifier=pool.trustee.identifier, reqId=900,
                  operation={TXN_TYPE: NYM, TARGET_NYM: steward5.identifier,
                             VERKEY: steward5.verkey, ROLE: STEWARD})
    pool.trustee.sign_request(nym)
    _submit_and_order(pool, nym, expect_total=2)

    changed = []
    for node in pool.nodes:
        node.on_membership_changed_hook = \
            lambda v, reg, n=node.name: changed.append((n, list(v)))
    node_txn = _node_request(steward5, "node4", 901)
    _submit_and_order(pool, node_txn, expect_total=3)

    expected = [f"node{i}" for i in range(5)]
    for node in pool.nodes:
        assert node.data.validators == expected, node.name
        assert node.data.quorums.n == 5
        assert node.data.quorums.commit.value == 4  # n - f with f=1
    assert len(changed) == 4  # every node's composition hook fired

    # the admitted node joins and catches up everything, including the
    # NODE txn that admitted it -> its own registry reaches n=5
    new = pool.add_node("node4")
    pool.run_for(30)
    assert new.pool_manager.validators == expected
    assert new.data.quorums.n == 5
    assert new.boot.db.get_ledger(1).size >= 2  # domain caught up

    # liveness at n=5: new writes order on ALL FIVE nodes (commit quorum
    # is 4 of 5, so consensus provably runs with the new membership)
    req = pool.make_nym_request()
    pool.submit_to("node1", req)
    pool.run_for(20)
    assert all(n.get_nym_data(req.operation["dest"]) is not None
               for n in pool.nodes), [n.name for n in pool.nodes]


def test_demotion_shrinks_active_set():
    pool = NodePool(4, seed=53, with_pool_genesis=True)
    steward3 = pool.stewards["node3"]
    demote = _node_request(steward3, "node3", 902, services=[])
    pool.submit_to("node0", demote)
    pool.run_for(15)
    for node in pool.nodes[:3]:
        assert node.data.validators == ["node0", "node1", "node2"]
        assert node.data.quorums.n == 3
    # promotion restores it, preserving the original round-robin order
    promote = _node_request(steward3, "node3", 903, services=[VALIDATOR])
    pool.submit_to("node0", promote)
    pool.run_for(15)
    for node in pool.nodes[:3]:
        assert node.data.validators == pool.validators
        assert node.data.quorums.n == 4


def test_non_steward_cannot_add_node():
    pool = NodePool(4, seed=54, with_pool_genesis=True)
    impostor = DidSigner(hashlib.sha256(b"impostor").digest())
    nym = Request(identifier=pool.trustee.identifier, reqId=904,
                  operation={TXN_TYPE: NYM, TARGET_NYM: impostor.identifier,
                             VERKEY: impostor.verkey})  # NO steward role
    pool.trustee.sign_request(nym)
    _submit_and_order(pool, nym)

    evil = _node_request(impostor, "evilnode", 905)
    pool.submit_to("node0", evil)
    pool.run_for(15)
    for node in pool.nodes:
        assert "evilnode" not in node.data.validators
        assert node.data.quorums.n == 4


def test_demoting_the_primary_triggers_view_change():
    """The master primary leaves the validator set via NODE txn: the pool
    must vote it out rather than keep accepting its PRE-PREPAREs."""
    pool = NodePool(4, seed=55, with_pool_genesis=True)
    assert pool.nodes[0].data.primaries[0] == "node0"
    steward0 = pool.stewards["node0"]
    demote = _node_request(steward0, "node0", 906, services=[])
    pool.submit_to("node1", demote)
    pool.run_for(30)
    survivors = [n for n in pool.nodes if n.name != "node0"]
    for node in survivors:
        assert node.data.validators == ["node1", "node2", "node3"]
        assert node.data.view_no >= 1, node.name
        assert node.data.primaries[0] != "node0"
    # and the reduced pool still orders
    req = pool.make_nym_request()
    pool.submit_to("node1", req)
    pool.run_for(20)
    assert all(n.get_nym_data(req.operation["dest"]) is not None
               for n in survivors)


def test_idle_pool_freshness_batches_keep_proofs_verifiable():
    """No writes for longer than the proof freshness window: the primary's
    empty freshness batches re-sign the roots, so proved reads still
    verify (reference: STATE_FRESHNESS_UPDATE_INTERVAL)."""
    from indy_plenum_tpu.common.constants import GET_NYM
    from indy_plenum_tpu.config import getConfig

    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 10,
                        "PropagateBatchWait": 0.05,
                        "StateFreshnessUpdateInterval": 60.0})
    pool = NodePool(4, seed=56, config=config, bls=True)
    client = pool.make_client()
    req = pool.make_nym_request()
    d = client.submit_write(req)
    pool.run_for(15)
    pool.pump_client(client)
    assert client.result(d) is not None

    # idle far beyond the client's freshness window (300s)
    pool.run_for(500)
    read = Request(identifier="reader", reqId=907,
                   operation={TXN_TYPE: GET_NYM,
                              TARGET_NYM: req.operation["dest"]})
    rd = client.submit_read(read, to="node2")
    pool.pump_client(client)
    assert client.result(rd) is not None, \
        "proved read went stale on an idle pool despite freshness batches"
