"""Tier-1 unit tests: GF(2^255-19) limb arithmetic vs Python big ints.

Mirrors the reference's pure-unit tier (SURVEY.md §4 tier 1); the oracle is
Python's arbitrary-precision integers.
"""
import random

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from indy_plenum_tpu.tpu import field25519 as fe  # noqa: E402

rng = random.Random(0xED25519)


def rand_int():
    return rng.randrange(0, fe.P)


ADVERSARIAL = [
    0,
    1,
    2,
    19,
    fe.P - 1,
    fe.P - 2,
    (1 << 255) - 1,
    (1 << 256) - 1,
    fe.P,
    fe.P + 1,
    2 * fe.P - 1,
    (1 << 263) + 12345,
    (1 << 264) - 1,
    511 * fe.P + 7,
]


def batch(ints):
    full = 1 << (fe.RADIX * fe.NLIMBS)
    return jnp.asarray(np.stack([fe.limbs_from_int(x % full) for x in ints]))


def loose_batch(ints):
    """Adversarial loose limbs: value encoded with limbs up to 2^17-1."""
    out = []
    for x in ints:
        limbs = np.zeros(fe.NLIMBS, dtype=np.int64)
        rem = x
        for i in range(fe.NLIMBS):
            limbs[i] = rem & fe.MASK
            rem >>= fe.RADIX
        # push random slack between adjacent limbs: limb_i += 2^16, limb_{i+1} -= 1
        for i in range(fe.NLIMBS - 1):
            if rng.random() < 0.5 and limbs[i + 1] > 0:
                limbs[i] += 1 << fe.RADIX
                limbs[i + 1] -= 1
        out.append(limbs)
    return jnp.asarray(np.stack(out))


def as_ints(limbs):
    arr = np.asarray(limbs)
    return [fe.int_from_limbs(arr[i]) for i in range(arr.shape[0])]


def test_roundtrip():
    xs = [rand_int() for _ in range(64)] + ADVERSARIAL
    got = as_ints(batch(xs))
    assert got == [x % fe.P for x in xs]


def test_add_sub_mul():
    xs = [rand_int() for _ in range(128)] + ADVERSARIAL
    ys = [rand_int() for _ in range(128)] + list(reversed(ADVERSARIAL))
    a, b = batch(xs), batch(ys)
    assert as_ints(fe.add(a, b)) == [(x + y) % fe.P for x, y in zip(xs, ys)]
    assert as_ints(fe.sub(a, b)) == [(x - y) % fe.P for x, y in zip(xs, ys)]
    assert as_ints(fe.mul(a, b)) == [(x * y) % fe.P for x, y in zip(xs, ys)]
    assert as_ints(fe.sqr(a)) == [(x * x) % fe.P for x in xs]
    assert as_ints(fe.neg(a)) == [(-x) % fe.P for x in xs]


def test_loose_inputs():
    xs = [rand_int() for _ in range(64)]
    ys = [rand_int() for _ in range(64)]
    a, b = loose_batch(xs), loose_batch(ys)
    assert as_ints(fe.mul(a, b)) == [(x * y) % fe.P for x, y in zip(xs, ys)]
    assert as_ints(fe.add(a, b)) == [(x + y) % fe.P for x, y in zip(xs, ys)]


def test_freeze_canonical():
    xs = [rand_int() for _ in range(32)] + ADVERSARIAL
    a = fe.freeze(loose_batch(xs))
    arr = np.asarray(a)
    assert arr.min() >= 0
    assert arr.max() < (1 << fe.RADIX)
    assert as_ints(a) == [x % fe.P for x in xs]
    # canonical: value below p when re-read without mod
    for i in range(arr.shape[0]):
        raw = sum(int(arr[i, j]) << (fe.RADIX * j) for j in range(fe.NLIMBS))
        assert raw < fe.P


def test_invert_and_sqrt_core():
    xs = [rand_int() for x in range(8) if True]
    xs = [x if x != 0 else 1 for x in xs]
    a = batch(xs)
    inv = fe.invert(a)
    assert as_ints(inv) == [pow(x, fe.P - 2, fe.P) for x in xs]
    p58 = fe.pow_p58(a)
    assert as_ints(p58) == [pow(x, (fe.P - 5) // 8, fe.P) for x in xs]


def test_eq_parity_encode():
    xs = [rand_int() for _ in range(16)]
    a = batch(xs)
    b = batch([x + fe.P for x in xs])  # same values mod p, different encoding
    assert bool(jnp.all(fe.eq(a, b)))
    assert [int(v) for v in fe.parity(a)] == [x % 2 for x in xs]
    enc = np.asarray(fe.encode_bytes(a))
    for i, x in enumerate(xs):
        assert enc[i].tobytes() == (x % fe.P).to_bytes(32, "little")
    dec = fe.decode_bytes(jnp.asarray(enc))
    assert as_ints(dec) == [x % fe.P for x in xs]
