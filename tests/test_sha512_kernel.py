"""Tier-1: device SHA-512 + mod-L + full Ed25519 verify vs host oracles."""
import hashlib
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from indy_plenum_tpu.crypto import ed25519 as ed  # noqa: E402
from indy_plenum_tpu.tpu import ed25519 as ted  # noqa: E402
from indy_plenum_tpu.tpu import sha512 as s5  # noqa: E402


def test_constants_derived_match_fips():
    assert s5._K64[0] == 0x428a2f98d728ae22
    assert s5._K64[79] == 0x6c44198c4a475817
    assert s5._H064[0] == 0x6a09e667f3bcc908
    assert s5._H064[7] == 0x5be0cd19137e2179


def test_sha512_blocks_matches_hashlib():
    rng = np.random.RandomState(3)
    msgs = [b"", b"abc", rng.bytes(111), rng.bytes(112), rng.bytes(128),
            rng.bytes(239), rng.bytes(240), rng.bytes(300)]
    blocks, counts = s5.pad_ed25519_messages([b""] * len(msgs), msgs, 4)
    out = np.asarray(s5.sha512_blocks(jnp.asarray(blocks),
                                      jnp.asarray(counts)))
    for i, m in enumerate(msgs):
        assert bytes(out[i]) == hashlib.sha512(m).digest(), len(m)


def test_reduce_mod_l_matches_python():
    rng = np.random.RandomState(5)
    hs = [rng.bytes(64) for _ in range(8)] + [b"\xff" * 64, b"\x00" * 64,
                                              b"\x01" + b"\x00" * 63]
    arr = jnp.asarray(np.stack([np.frombuffer(h, np.uint8) for h in hs]))
    red = np.asarray(s5.reduce_mod_l(arr))
    for i, h in enumerate(hs):
        want = (int.from_bytes(h, "little") % s5._L_INT)
        assert bytes(red[i]) == want.to_bytes(32, "little"), i


def test_full_device_verify_matches_host_hash_path():
    rng = np.random.RandomState(9)
    seeds = [rng.bytes(32) for _ in range(8)]
    pks = [ed.fast_public_key(s) for s in seeds]
    msgs = [rng.bytes(rng.randint(1, 200)) for _ in range(8)]
    sigs = [ed.fast_sign(seeds[i], msgs[i]) for i in range(8)]
    # corrupt two: flipped message + flipped sig byte
    msgs[3] = msgs[3][:-1] + bytes([msgs[3][-1] ^ 1])
    sigs[5] = bytes([sigs[5][0] ^ 1]) + sigs[5][1:]
    got = ted.batch_verify(pks, msgs, sigs)
    want = np.array([True, True, True, False, True, False, True, True])
    assert np.array_equal(got, want)
