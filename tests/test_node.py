"""Node composition root: ingress, propagation, replay protection, replies.

VERDICT round-2 item 3: a real Node owning ingress (device-batched
authentication per node), PROPAGATE with per-node f+1 digest finalisation
replacing the shared-pool fiction, replay protection, and NYM-state-backed
verkey resolution in CoreAuthNr.

Reference behaviours: plenum/server/node.py (processRequest ->
tryForwarding), plenum/server/propagator.py (f+1 finalisation),
plenum/persistence/req_id_to_txn.py (replay detection).
"""
import pytest

from indy_plenum_tpu.common.messages.node_messages import (
    Propagate,
    Reply,
    RequestAck,
    RequestNack,
)
from indy_plenum_tpu.config import getConfig
from indy_plenum_tpu.simulation.node_pool import NodePool
from indy_plenum_tpu.simulation.sim_network import delay_message_types


def all_ordered(pool, count):
    return all(len(n.ordered_digests) == count for n in pool.nodes)


def test_single_entry_node_request_orders_everywhere():
    """A client talks to ONE node; f+1 PROPAGATE finalisation carries the
    request to the whole pool and it orders + executes on every node."""
    pool = NodePool(4, seed=1)
    req = pool.make_nym_request()
    assert pool.submit_to("node2", req)  # NOT the primary
    pool.run_for(15)
    assert all_ordered(pool, 1)
    assert pool.honest_nodes_agree()
    # executed: the NYM is readable from committed state on every node
    for node in pool.nodes:
        data = node.get_nym_data(req.operation["dest"])
        assert data is not None and data["verkey"] == req.operation["verkey"]
    # the entry node produced REQACK + REPLY for the client
    entry = pool.node("node2")
    kinds = [type(m) for _, m in entry.client_outbox]
    assert RequestAck in kinds and Reply in kinds
    reply = entry.replies[req.digest]
    assert reply.result["txnMetadata"]["seqNo"] >= 1


def test_propagation_finalises_on_node_that_missed_propagates():
    """A node cut off from PROPAGATEs sees the PRE-PREPARE reference an
    unknown request, fetches peers' PROPAGATEs (digest-authenticated) and
    still orders — VERDICT item 3's 'missing request finalises' criterion."""
    pool = NodePool(4, seed=2)
    # node3 receives no PROPAGATE from anyone
    undelay = pool.network.add_delayer(
        delay_message_types(Propagate, to="node3"))
    req = pool.make_nym_request()
    pool.submit_to("node0", req)
    pool.run_for(20)
    undelay()
    # consensus proceeded without node3's propagate vote (quorum is f+1=2);
    # node3 fetched the request content and ordered the same log
    assert all_ordered(pool, 1)
    assert pool.honest_nodes_agree()
    assert pool.node("node3").get_nym_data(req.operation["dest"]) is not None


def test_replayed_request_is_rejected():
    pool = NodePool(4, seed=3)
    req = pool.make_nym_request()
    pool.submit_to("node1", req)
    pool.run_for(15)
    assert all_ordered(pool, 1)

    # same request again (same signature): synchronous NACK, nothing orders
    assert pool.submit_to("node1", req) is False
    nacks = [m for _, m in pool.node("node1").client_outbox
             if isinstance(m, RequestNack)]
    assert nacks and "already processed" in nacks[-1].reason
    # replay to a DIFFERENT node is also rejected (index is per-node but
    # fed identically by execution on every node)
    assert pool.submit_to("node2", req) is False
    pool.run_for(10)
    assert all_ordered(pool, 1)


def test_forged_signature_nacked_and_not_propagated():
    pool = NodePool(4, seed=4)
    req = pool.make_nym_request()
    req.operation["evil"] = True  # signature no longer covers payload
    pool.submit_to("node0", req)
    pool.run_for(10)
    assert all_ordered(pool, 0)
    outbox = pool.node("node0").client_outbox
    assert any(isinstance(m, RequestNack)
               and "signature" in m.reason for _, m in outbox)
    # the forged request never reached other nodes' propagators
    assert pool.node("node2").propagator.requests.get(req.digest) is None


def test_state_backed_verkey_resolution_end_to_end():
    """The north-star e2e: a NYM txn writes a NEW identity's verkey into
    domain state via consensus; that identity then signs a request which
    authenticates purely from state (no seed_keys entry exists for it)."""
    pool = NodePool(4, seed=5)
    nym_req = pool.make_nym_request()
    target = nym_req.target_signer
    pool.submit_to("node0", nym_req)
    pool.run_for(15)
    assert all_ordered(pool, 1)

    # the fresh identity is NOT in any node's seed keys
    for node in pool.nodes:
        assert target.identifier not in node.authnr._seed_keys

    follow_up = pool.make_nym_request(signer=target)
    pool.submit_to("node3", follow_up)
    pool.run_for(15)
    # NYM role rules: the new identity (no role) may create its own NYMs?
    # NymHandler requires TRUSTEE for role-bearing writes only; a plain NYM
    # write by a known identity is authenticated — the signature check is
    # what this test pins. It must have been ACKed (auth passed via state).
    entry = pool.node("node3")
    acks = [m for _, m in entry.client_outbox if isinstance(m, RequestAck)]
    assert acks, [m for _, m in entry.client_outbox]


def test_device_quorum_node_pool_tick_mode():
    """The full Node stack with the grouped device vote plane as sole
    authority and tick-batched flushes (the bench configuration, now with
    real ingress/propagation/execution)."""
    config = getConfig({"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 5,
                        "PropagateBatchWait": 0.05,
                        "QuorumTickInterval": 0.05})
    pool = NodePool(4, seed=6, config=config, device_quorum=True)
    reqs = [pool.make_nym_request() for _ in range(6)]
    for i, req in enumerate(reqs):
        pool.submit_to(f"node{i % 4}", req)
    pool.run_for(30)
    assert all_ordered(pool, 6)
    assert pool.honest_nodes_agree()
    assert pool.vote_group.flushes > 0


def test_reads_nacked_while_not_participating():
    """Fail-closed read surface: a node that is catching up (or whose
    catchup FAILED after a divergence conviction) must not answer reads
    from state it cannot vouch for — the client gets a NACK, not a value
    from a possibly-wrong committed head."""
    from indy_plenum_tpu.common.constants import (
        GET_NYM,
        TARGET_NYM,
        TXN_TYPE,
    )
    from indy_plenum_tpu.common.request import Request

    pool = NodePool(4, seed=7)
    req = pool.make_nym_request()
    pool.submit_to("node0", req)
    pool.run_for(15)
    assert all_ordered(pool, 1)

    node = pool.node("node2")
    read = Request(identifier=pool.trustee.identifier, reqId=999,
                   operation={TXN_TYPE: GET_NYM,
                              TARGET_NYM: req.operation["dest"]})
    # healthy: the read is served
    assert node.submit_client_request(read, client_id="c1") is True
    assert isinstance(node.client_outbox[-1][1], Reply)

    # catching up: the same read is refused
    node.data.is_participating = False
    read2 = Request(identifier=pool.trustee.identifier, reqId=1000,
                    operation={TXN_TYPE: GET_NYM,
                               TARGET_NYM: req.operation["dest"]})
    assert node.submit_client_request(read2, client_id="c1") is False
    nack = node.client_outbox[-1][1]
    assert isinstance(nack, RequestNack) and "catching up" in nack.reason
